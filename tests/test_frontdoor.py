"""Multi-process serving front door tests.

The robustness core of the front-door PR: supervised executor worker
processes behind a Unix-socket protocol — crash detection via
heartbeats + waitpid, session re-placement through the bounded backoff
ladder, the loud :class:`WorkerLost` contract for non-replayable
victims, load shedding under lost capacity, and the fleet-wide
zero-orphan shutdown report.

Each test spawns real worker processes (each imports jax), so the
fixtures keep fleets small and heartbeats fast.
"""

import os
import signal
import tempfile
import threading
import time

import pytest

from spark_rapids_jni_tpu import config, faultinj
from spark_rapids_jni_tpu.serve import (
    AdmissionShed,
    FrontDoor,
    ServeError,
    WorkerLost,
    fleet_metrics,
)


@pytest.fixture(autouse=True)
def _fast_ladder(tmp_path, monkeypatch):
    # deterministic per-test fleet dirs: every mkdtemp (the fleet dir,
    # its sockets, stores, worker dirs) lands under THIS test's tmp_path
    # instead of a shared /tmp — two tests (or a retried flake) can
    # never contend on leftover directories, and pytest reaps them
    monkeypatch.setattr(tempfile, "tempdir", str(tmp_path))
    config.set("serve_backoff_ms", 40.0)
    yield
    config.reset("serve_backoff_ms")
    faultinj.configure(None)
    # bounded straggler drain: frontdoor threads from THIS test must
    # wind down before the next test builds a fleet, or a slow reader
    # from a dead fleet aliases into the next test's thread checks
    _poll(lambda: not [t.name for t in threading.enumerate()
                       if t.name.startswith("frontdoor-")], timeout=5.0)


def _poll(pred, timeout=15.0, interval=0.02):
    """Bounded condition wait — the deflake primitive: every wait in
    this file polls a predicate with a deadline instead of sleeping a
    guessed duration, so a slow box waits longer, never flakes."""
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _no_stragglers():
    return _poll(lambda: not [t.name for t in threading.enumerate()
                              if t.name.startswith("frontdoor-")],
                 timeout=5.0)


class TestHappyPath:
    def test_echo_roundtrip_pinning_and_clean_shutdown(self):
        fd = FrontDoor(workers=2, heartbeat_ms=80.0)
        try:
            sessions = [fd.submit("echo", {"value": f"v{i}"},
                                  tenant=f"t{i % 2}") for i in range(6)]
            assert [s.result(timeout=60) for s in sessions] == \
                [f"v{i}" for i in range(6)]
            # sticky pinning: every session of a tenant on ONE worker
            for tenant in ("t0", "t1"):
                workers = {s.worker_id for i, s in enumerate(sessions)
                           if f"t{i % 2}" == tenant}
                assert len(workers) == 1, (tenant, workers)
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        assert report["orphan_spill_files"] == []
        assert all(e["clean"] for e in report["workers"].values())
        assert not os.path.exists(fd.fleet_dir)
        # idempotent: the second call returns the first report
        assert fd.shutdown() == report
        with pytest.raises(ServeError):
            fd.submit("echo", {"value": "late"}).result(timeout=1)
        assert _no_stragglers()

    def test_unknown_kind_fails_loudly(self):
        fd = FrontDoor(workers=1, heartbeat_ms=80.0)
        try:
            with pytest.raises(ServeError, match="unknown query kind"):
                fd.submit("no_such_kind", {}).result(timeout=60)
        finally:
            assert fd.shutdown()["clean"]


class TestWorkerLoss:
    def test_crash_replaces_replayable_session(self):
        """A worker that SIGKILLs itself mid-query is detected, its
        spill dir reaped, the session re-placed onto the respawned
        worker, and the merged fired_log carries the worker's trace."""
        faultinj.configure({"faults": [
            {"match": "serve_step", "fault": "worker_crash", "count": 1},
        ]})
        fd = FrontDoor(workers=1, heartbeat_ms=80.0)
        try:
            s = fd.submit("spill_walk", {"seed": 3}, tenant="t0",
                          replayable=True)
            digest = s.result(timeout=90)
            assert s.replacements >= 1
            assert s.status == "done"
            # determinism across the replacement: same seed, same digest
            s2 = fd.submit("spill_walk", {"seed": 3}, tenant="t0")
            assert s2.result(timeout=90) == digest
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        assert report["fleet"]["crashes"] == 1
        assert report["fleet"]["respawns"] == 1
        fired = faultinj.fired_log()
        assert any(e.get("fault") == "worker_crash"
                   and str(e.get("source", "")).startswith("worker-")
                   for e in fired)

    def test_crash_fails_nonreplayable_with_worker_lost(self):
        """A non-replayable session whose worker dies with the result
        undelivered fails loudly with WorkerLost carrying the dead
        worker's fired_log — never a silent re-run."""
        faultinj.configure({"faults": [
            {"match": "worker_result", "fault": "worker_crash",
             "count": 1},
        ]})
        fd = FrontDoor(workers=1, heartbeat_ms=80.0)
        try:
            s = fd.submit("sleep", {"seconds": 0.2}, tenant="t0",
                          replayable=False)
            with pytest.raises(WorkerLost) as exc:
                s.result(timeout=90)
            assert exc.value.worker_id == 0
            assert any(e.get("fault") == "worker_crash"
                       for e in exc.value.fired_log)
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        assert report["fleet"]["worker_lost"] == 1

    def test_stall_detected_and_session_replaced(self):
        """A wedged worker (stops answering heartbeats) is SIGKILLed by
        the monitor and its session re-placed — the supervisor's
        detector, not any in-process cleanup, ends the wedge."""
        faultinj.configure({"faults": [
            {"match": "serve_step", "fault": "worker_stall", "count": 1},
        ]})
        fd = FrontDoor(workers=1, heartbeat_ms=60.0)
        try:
            s = fd.submit("spill_walk", {"seed": 9}, tenant="t0")
            assert s.result(timeout=90)
            assert s.replacements >= 1
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        assert report["fleet"]["stalls"] == 1


class TestDegradation:
    def test_shed_lowest_priority_when_capacity_lost(self):
        """With one of two single-slot workers dead and its respawn
        circuit open, pending admissions beyond the surviving capacity
        are shed lowest-priority-first."""
        fd = FrontDoor(workers=2, max_concurrent=1, respawn_max=0,
                       shed_threshold=0.6, heartbeat_ms=60.0)
        try:
            assert _poll(lambda: sum(
                1 for w in fd._workers.values()
                if w.state == "healthy") == 2)
            busy = [fd.submit("sleep", {"seconds": 3.0}, tenant=f"b{i}")
                    for i in range(2)]
            assert _poll(lambda: all(
                s.worker_id is not None for s in busy), timeout=10.0)
            hi = fd.submit("echo", {"value": "hi"}, tenant="b0",
                           priority=5)
            lo = fd.submit("echo", {"value": "lo"}, tenant="b1",
                           priority=0)
            with fd._lock:
                pid = fd._workers[1].proc.pid
            os.kill(pid, signal.SIGKILL)
            with pytest.raises(AdmissionShed):
                lo.result(timeout=30)
            assert lo.status == "shed"
            assert hi.result(timeout=30) == "hi"
        finally:
            report = fd.shutdown()
        assert report["fleet"]["sheds"] >= 1
        assert report["fleet"]["circuit_open"] == 1

    def test_fleet_exhausted_fails_pending_with_worker_lost(self):
        """All workers dead with the breaker open: pending sessions
        fail with WorkerLost instead of hanging forever."""
        fd = FrontDoor(workers=1, max_concurrent=1, respawn_max=0,
                       heartbeat_ms=60.0)
        try:
            assert _poll(lambda: any(
                w.state == "healthy" for w in fd._workers.values()))
            hold = fd.submit("sleep", {"seconds": 5.0}, tenant="t0")
            assert _poll(lambda: hold.worker_id is not None, timeout=10.0)
            queued = fd.submit("echo", {"value": "q"}, tenant="t1")
            with fd._lock:
                pid = fd._workers[0].proc.pid
            os.kill(pid, signal.SIGKILL)
            with pytest.raises(WorkerLost):
                queued.result(timeout=30)
        finally:
            fd.shutdown()


class TestStorePlane:
    def test_crash_recovery_adopts_committed_shards(self):
        """The tentpole invariant: a worker SIGKILLed after committing
        its map output is re-placed onto a respawn that ADOPTS the
        committed shard (map_runs == 0) with a bit-identical digest;
        the same crash with the store disabled re-runs the map."""
        # query 1 commits, query 2's first step crashes the worker
        schedule = {"faults": [
            {"match": "serve_step", "fault": "worker_crash",
             "skip": 1, "count": 1}]}
        faultinj.configure(schedule)
        fd = FrontDoor(workers=1, heartbeat_ms=80.0)
        try:
            r1 = fd.submit("shuffle_digest",
                           {"seed": 3, "store_key": "sd-3"},
                           tenant="t0").result(timeout=120)
            assert r1["map_runs"] == 1 and r1["adopted"] == 0
            s2 = fd.submit("shuffle_digest",
                           {"seed": 3, "store_key": "sd-3"}, tenant="t0")
            r2 = s2.result(timeout=120)
            assert s2.replacements >= 1
            assert r2["digest"] == r1["digest"]  # bit-identical recovery
            assert r2["adopted"] >= 1 and r2["map_runs"] == 0
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        assert report["fleet"]["crashes"] == 1
        assert "store" in report
        assert not os.path.exists(fd.fleet_dir)

        # the comparison arm: store disabled, same crash — the map MUST
        # re-run (map_runs 1 > the store run's 0), same digest
        faultinj.configure(schedule)
        fd2 = FrontDoor(workers=1, heartbeat_ms=80.0, store=False)
        try:
            p1 = fd2.submit("shuffle_digest",
                            {"seed": 3, "store_key": "sd-3"},
                            tenant="t0").result(timeout=120)
            s2 = fd2.submit("shuffle_digest",
                            {"seed": 3, "store_key": "sd-3"}, tenant="t0")
            p2 = s2.result(timeout=120)
            assert s2.replacements >= 1
            assert p2["digest"] == r1["digest"]
            assert p2["map_runs"] == 1 and p2["adopted"] == 0
            assert p1["map_runs"] == 1
        finally:
            report2 = fd2.shutdown()
        assert report2["clean"], report2
        assert "store" not in report2

    def test_zombie_generation_is_fenced(self):
        """A dead generation's epoch is revoked at loss time: a zombie
        that outlives its SIGKILL verdict can write tmp entries but its
        commit is rejected at the rename — never adoptable."""
        import jax.numpy as jnp

        from spark_rapids_jni_tpu.shuffle.store import ShuffleStore

        faultinj.configure({"faults": [
            {"match": "serve_step", "fault": "worker_crash", "count": 1}]})
        fd = FrontDoor(workers=1, heartbeat_ms=80.0)
        try:
            s = fd.submit("spill_walk", {"seed": 5}, tenant="t0")
            assert s.result(timeout=90)
            assert s.replacements >= 1  # gen 1 died and was revoked
            zombie = ShuffleStore(fd.store_dir, epoch=1)
            assert zombie.fenced(1)
            assert not zombie.put("zq", "map", {"x": jnp.arange(4)})
            assert zombie.snapshot()["fenced_commits"] == 1
            # nothing committed, nothing adoptable, by any reader
            reader = ShuffleStore(fd.store_dir)
            assert not reader.has_committed("zq", "map")
            assert reader.adopt("zq", "map") is None
            # the respawned generation (gen 2) is NOT fenced
            assert not zombie.fenced(2)
        finally:
            report = fd.shutdown()
        assert report["clean"], report

    def test_retain_knob_keeps_store_past_shutdown(self):
        """shuffle_store_retain=True: shutdown reaps the fleet but
        leaves the committed store for the next fleet to adopt from."""
        import shutil

        from spark_rapids_jni_tpu.shuffle.store import ShuffleStore

        fd = FrontDoor(workers=1, heartbeat_ms=80.0)
        config.set("shuffle_store_retain", True)
        try:
            r = fd.submit("shuffle_digest",
                          {"seed": 7, "store_key": "keep-7"},
                          tenant="t0").result(timeout=120)
            assert r["map_runs"] == 1
        finally:
            report = fd.shutdown()
            config.reset("shuffle_store_retain")
        try:
            assert report["clean"], report
            assert os.path.isdir(fd.store_dir)
            assert ShuffleStore(fd.store_dir).has_committed("keep-7", "map")
            # everything else in the fleet dir was still reaped
            assert os.listdir(fd.fleet_dir) == ["shuffle-store"]
        finally:
            shutil.rmtree(fd.fleet_dir, ignore_errors=True)


class TestMultiHostTransport:
    def test_tcp_two_host_fleet_round_trip(self):
        """Two workers placed round-robin on two named hosts over TCP:
        the fleet behaves exactly like the single-box Unix default."""
        fd = FrontDoor(workers=2, heartbeat_ms=80.0, transport="tcp",
                       hosts="hostA,hostB")
        try:
            sessions = [fd.submit("echo", {"value": f"v{i}"},
                                  tenant=f"t{i % 2}") for i in range(4)]
            assert [s.result(timeout=60) for s in sessions] == \
                [f"v{i}" for i in range(4)]
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        assert report["transport"] == "tcp"
        assert report["hosts"] == ["hostA", "hostB"]
        hosts = {e["host"] for e in report["workers"].values()}
        assert hosts == {"hostA", "hostB"}  # both hosts got a slot
        assert report["fleet"]["self_fenced_workers"] == 0

    def test_multi_host_list_forces_tcp(self):
        """>1 host cannot ride a Unix socket; the front door promotes
        the transport instead of silently colocating everything."""
        fd = FrontDoor(workers=1, heartbeat_ms=80.0,
                       hosts=["h0", "h1"])
        try:
            assert fd._transport == "tcp"
            assert fd.submit("echo", {"value": "m"}).result(timeout=60) \
                == "m"
        finally:
            assert fd.shutdown()["clean"]

    def test_reconnect_reattaches_without_session_loss(self):
        """The connection-supervision contract: an injected link drop on
        the supervisor's send is NOT a worker loss.  The worker re-dials,
        the idempotent hello re-attaches the same incarnation, the
        in-flight session completes exactly once — zero replacements,
        zero crashes, one reconnect."""
        faultinj.configure({"faults": [
            {"match": "net_send_sup", "fault": "net_drop", "count": 1}]})
        # generous grace: on a starved box a slow re-dial must stay a
        # reconnect, not cross into the partition/self-fence path
        fd = FrontDoor(workers=1, heartbeat_ms=80.0,
                       partition_grace_ms=8000.0)
        try:
            s = fd.submit("sleep", {"seconds": 1.0}, tenant="t0",
                          replayable=True)
            assert s.result(timeout=90) == "slept"
            assert s.replacements == 0  # link loss != worker loss
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        assert report["fleet"]["reconnects"] >= 1
        assert report["fleet"]["crashes"] == 0
        assert report["fleet"]["respawns"] == 0
        assert report["fleet"]["partitions_detected"] == 0
        fired = faultinj.fired_log()
        assert any(e.get("fault") == "net_drop" for e in fired)

    def test_partitioned_worker_self_fences_and_is_quarantined(self):
        """Split-brain: a worker that cannot reach the supervisor past
        the partition grace revokes its OWN store epoch (self-fence),
        writes the sentinel, and exits; the supervisor counts it and
        re-places the session.  Post-revocation commits from that
        generation are rejected at the rename — zero zombie shards."""
        import jax.numpy as jnp

        from spark_rapids_jni_tpu.shuffle.store import ShuffleStore

        # skip=2 spares hello+first pong; count=4 = 1 live send + 3
        # ladder hellos, so the rule is fully consumed by the first
        # incarnation and the respawn inherits a quiet network
        faultinj.configure({"faults": [
            {"match": "net_send_wk", "fault": "net_drop",
             "skip": 2, "count": 4}]})
        fd = FrontDoor(workers=1, heartbeat_ms=80.0,
                       partition_grace_ms=700.0, reconnect_max=3)
        try:
            s = fd.submit("sleep", {"seconds": 2.0}, tenant="t0",
                          replayable=True)
            assert s.result(timeout=120) == "slept"
            assert s.replacements >= 1
            revoked = fd._store.revoked()
            assert 1 in revoked  # the fenced generation's epoch
            zombie = ShuffleStore(fd.store_dir, epoch=1)
            assert not zombie.put("zp", "map", {"x": jnp.arange(4)})
            reader = ShuffleStore(fd.store_dir)
            assert not reader.has_committed("zp", "map")
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        assert report["fleet"]["self_fenced_workers"] >= 1
        assert report["fleet"]["partitions_detected"] >= 1
        assert report["self_fenced"], report
        entry = report["self_fenced"][0]
        assert entry["worker_id"] == 0 and entry["epoch"] == 1
        assert entry["fenced_commits"] == 0  # nothing slipped through


class TestFailover:
    """Supervisor crash → a fresh FrontDoor adopts the same fleet dir
    off the write-ahead journal (serve/journal.py)."""

    @staticmethod
    def _adopt(fleet_dir, **kw):
        kw.setdefault("workers", 1)
        kw.setdefault("heartbeat_ms", 80.0)
        kw.setdefault("partition_grace_ms", 8000.0)
        kw.setdefault("reconnect_max", 60)
        return FrontDoor(adopt_dir=fleet_dir, **kw)

    def test_adoption_recovers_a_live_session(self):
        from spark_rapids_jni_tpu.serve import journal
        fd = FrontDoor(workers=1, heartbeat_ms=80.0,
                       partition_grace_ms=8000.0, reconnect_max=60)
        fleet = fd.fleet_dir
        sess = fd.submit("sleep", {"seconds": 3.0}, tenant="t")
        assert _poll(lambda: sess.worker_id is not None)
        fd._simulate_crash()
        assert fd.crashed
        nd = self._adopt(fleet)
        try:
            rec = nd.recovered()
            assert sess.sid in rec
            assert rec[sess.sid].result(timeout=60.0) == "slept"
            snap = nd.metrics.snapshot()
            assert snap["adopted_workers"] >= 1
            assert snap["recovered_sessions"] + \
                snap["replayed_sessions"] >= 1
            # the journal proves the adoption AND that the logical
            # query ran exactly once — follow the sid through any
            # re-keying to its single terminal record
            entries = journal.scan(journal.journal_path(fleet))
            assert any(e["rec"] == "adopt" for e in entries)
            sid, done = sess.sid, 0
            for e in entries:
                if e["rec"] in ("requeued", "replayed") \
                        and e.get("sid") == sid \
                        and e.get("new_sid") is not None:
                    sid = int(e["new_sid"])
                elif e["rec"] == "result" and e.get("sid") == sid \
                        and e.get("status") == "done":
                    done += 1
            assert done == 1
        finally:
            report = nd.shutdown()
            fd.shutdown()
        assert report["clean"]
        assert report["recovery"]["adopted_workers"] >= 1
        assert _no_stragglers()

    def test_double_restart_resurrects_nothing(self, tmp_path):
        from spark_rapids_jni_tpu.serve import journal
        fd = FrontDoor(workers=1, heartbeat_ms=80.0,
                       partition_grace_ms=8000.0, reconnect_max=60)
        fleet = fd.fleet_dir
        jpath = journal.journal_path(fleet)
        try:
            for i in range(2):
                assert fd.submit("echo", {"value": i},
                                 tenant="t").result(timeout=60.0) == i
            fd._simulate_crash()
            nd = self._adopt(fleet)
            fd = nd
            # the wave was terminal before the crash: adoption must
            # resurrect NOTHING
            assert nd.recovered() == {}
            state_a = journal.replay(jpath)
            nd._simulate_crash()
            fd = self._adopt(fleet)
            assert fd.recovered() == {}
            state_b = journal.replay(jpath)
            # double restart is idempotent: same folded session states
            assert {s: v["status"] for s, v in state_a.sessions.items()} \
                == {s: v["status"] for s, v in state_b.sessions.items()}
            # and the twice-adopted door still serves
            assert fd.submit("echo", {"value": "z"},
                             tenant="t").result(timeout=60.0) == "z"
        finally:
            report = fd.shutdown()
        assert report["clean"]
        assert _no_stragglers()

    def test_adoption_replays_past_a_self_fenced_worker(self):
        # the worker ORPHANS itself (supervisor silent past the grace)
        # before any new door adopts: the journal-alive pid is gone, so
        # adoption must fence its generation and REPLAY the session on
        # a fresh worker instead of re-dialing a corpse
        config.set("serve_orphan_grace_ms", 200.0)
        try:
            fd = FrontDoor(workers=1, heartbeat_ms=40.0)
            fleet = fd.fleet_dir
            sess = fd.submit("sleep", {"seconds": 30.0}, tenant="t")
            assert _poll(lambda: sess.worker_id is not None)
            with fd._lock:
                proc = list(fd._workers.values())[0].proc
            fd._simulate_crash()
            # rc=3: the orphan drained and self-fenced its generation
            assert _poll(lambda: proc.poll() is not None, timeout=30.0)
            assert proc.poll() == 3
            nd = self._adopt(fleet)
            try:
                rec = nd.recovered()
                assert sess.sid in rec
                assert rec[sess.sid].result(timeout=120.0) == "slept"
                snap = nd.metrics.snapshot()
                assert snap["adopted_workers"] == 0
                assert snap["replayed_sessions"] >= 1
            finally:
                report = nd.shutdown()
                fd.shutdown()
            assert report["clean"]
            # the fenced generation's sentinel surfaced in the report
            assert any("orphaned" in s.get("reason", "")
                       for s in report["self_fenced"]) or \
                report["recovery"]["adopted_workers"] == 0
        finally:
            config.reset("serve_orphan_grace_ms")
        assert _no_stragglers()

    def test_cancel_during_adoption_unwinds_cleanly(self):
        from spark_rapids_jni_tpu.serve import QueryCancelled
        fd = FrontDoor(workers=1, heartbeat_ms=80.0,
                       partition_grace_ms=8000.0, reconnect_max=60)
        fleet = fd.fleet_dir
        sess = fd.submit("sleep", {"seconds": 60.0}, tenant="t")
        assert _poll(lambda: sess.worker_id is not None)
        fd._simulate_crash()
        nd = self._adopt(fleet)
        try:
            rec = nd.recovered()
            assert sess.sid in rec
            ns = rec[sess.sid]
            ns.cancel()
            with pytest.raises(QueryCancelled):
                ns.result(timeout=60.0)
            assert ns.status == "cancelled"
        finally:
            report = nd.shutdown()
            fd.shutdown()
        # the unwound session left nothing behind: clean fleet, no
        # orphan spill files, fleet dir gone
        assert report["clean"]
        assert not os.path.exists(fleet)
        assert _no_stragglers()


class TestFleetMetrics:
    def test_zeros_safe_surface(self):
        snap = fleet_metrics()
        for field in ("workers_spawned", "crashes", "stalls", "sheds",
                      "respawns", "worker_lost", "circuit_open",
                      "replacements", "reconnects", "partitions_detected",
                      "self_fenced_workers", "recovered_sessions",
                      "adopted_workers", "replayed_sessions"):
            assert field in snap and snap[field] >= 0
        from spark_rapids_jni_tpu.mem.rmm_spark import RmmSpark
        assert RmmSpark.fleet_metrics() == fleet_metrics()
        from spark_rapids_jni_tpu.profiler import fleet_summary
        summary = fleet_summary()
        assert summary["workers_spawned"] >= 0
        assert "liveness" in summary

    def test_counters_track_a_fleet(self):
        fd = FrontDoor(workers=1, heartbeat_ms=80.0)
        try:
            fd.submit("echo", {"value": "x"}).result(timeout=60)
        finally:
            fd.shutdown()
        snap = fleet_metrics()
        assert snap["workers_spawned"] == 1
        assert snap["liveness"] == {0: "shutdown"}
