"""Parquet footer parse/filter/rewrite round-trips on real pyarrow files.

Exercises the reference contracts (ParquetFooter.java + NativeParquetJni):
row-group pruning by split midpoint, case-(in)sensitive column pruning
over flat/struct/list/map schemas, num_rows/num_columns accounting, and
the PAR1-framed re-serialization being a footer pyarrow can read back.
"""

import io
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_jni_tpu.io import ParquetFooter, read_footer_bytes


@pytest.fixture
def flat_file(tmp_path):
    path = str(tmp_path / "flat.parquet")
    t = pa.table(
        {
            "a": pa.array(range(1000), pa.int64()),
            "b": pa.array([f"s{i}" for i in range(1000)]),
            "C": pa.array([float(i) for i in range(1000)]),
        }
    )
    pq.write_table(t, path, row_group_size=100)
    return path


def reparse(footer_file_bytes):
    """Read our serialized footer back with pyarrow."""
    return pq.read_metadata(io.BytesIO(footer_file_bytes))


class TestRoundTrip:
    def test_identity(self, flat_file):
        with ParquetFooter.read_and_filter(flat_file) as f:
            assert f.num_rows == 1000
            assert f.num_columns == 3
            assert f.num_row_groups == 10
            md = reparse(f.serialize())
        assert md.num_rows == 1000
        assert md.num_columns == 3
        assert md.num_row_groups == 10
        assert [md.schema.column(i).name for i in range(3)] == ["a", "b", "C"]

    def test_column_pruning(self, flat_file):
        with ParquetFooter.read_and_filter(
            flat_file, schema={"b": None}
        ) as f:
            assert f.num_columns == 1
            md = reparse(f.serialize())
        assert md.num_columns == 1
        assert md.schema.column(0).name == "b"
        assert md.row_group(0).num_columns == 1
        assert md.row_group(0).column(0).path_in_schema == "b"

    def test_case_insensitive(self, flat_file):
        with ParquetFooter.read_and_filter(
            flat_file, schema={"c": None, "A": None}, ignore_case=True
        ) as f:
            assert f.num_columns == 2
        with ParquetFooter.read_and_filter(
            flat_file, schema={"c": None}, ignore_case=False
        ) as f:
            assert f.num_columns == 0

    def test_row_group_split_pruning(self, flat_file):
        size = os.path.getsize(flat_file)
        with ParquetFooter.read_and_filter(flat_file, 0, size) as f:
            assert f.num_row_groups == 10
        # first half / second half of the file byte range partition the
        # groups between them with none lost
        with ParquetFooter.read_and_filter(flat_file, 0, size // 2) as f1, \
                ParquetFooter.read_and_filter(
                    flat_file, size // 2, size - size // 2) as f2:
            assert f1.num_row_groups + f2.num_row_groups == 10
            assert f1.num_rows + f2.num_rows == 1000
            assert f1.num_row_groups > 0 and f2.num_row_groups > 0
        # an empty byte range keeps nothing
        with ParquetFooter.read_and_filter(flat_file, size, 10) as f:
            assert f.num_row_groups == 0
            assert f.num_rows == 0


class TestNested:
    def test_struct(self, tmp_path):
        path = str(tmp_path / "s.parquet")
        t = pa.table(
            {
                "s": pa.array([{"x": 1, "y": "a", "z": 2.0}] * 10),
                "plain": pa.array(range(10)),
            }
        )
        pq.write_table(t, path)
        with ParquetFooter.read_and_filter(
            path, schema={"s": {"y": None}}
        ) as f:
            md = reparse(f.serialize())
        assert md.num_columns == 1  # only s.y leaf remains
        assert md.row_group(0).column(0).path_in_schema == "s.y"

    def test_list(self, tmp_path):
        path = str(tmp_path / "l.parquet")
        t = pa.table(
            {
                "l": pa.array([[1, 2], [3]], pa.list_(pa.int32())),
                "q": pa.array([1, 2]),
            }
        )
        pq.write_table(t, path)
        with ParquetFooter.read_and_filter(path, schema={"l": [None]}) as f:
            md = reparse(f.serialize())
        assert md.num_columns == 1
        assert "l" in md.row_group(0).column(0).path_in_schema

    def test_map(self, tmp_path):
        path = str(tmp_path / "m.parquet")
        t = pa.table(
            {
                "m": pa.array([[("k", 1)], []],
                              pa.map_(pa.string(), pa.int64())),
                "q": pa.array([1, 2]),
            }
        )
        pq.write_table(t, path)
        with ParquetFooter.read_and_filter(
            path, schema={"m": (None, None)}
        ) as f:
            md = reparse(f.serialize())
        assert md.num_columns == 2  # key + value leaves
        paths = {md.row_group(0).column(i).path_in_schema for i in range(2)}
        assert all("m." in p for p in paths)


def test_read_footer_bytes_rejects_garbage(tmp_path):
    p = str(tmp_path / "x.bin")
    with open(p, "wb") as f:
        f.write(b"not a parquet file")
    with pytest.raises(ValueError):
        read_footer_bytes(p)


def test_bad_thrift_raises():
    with pytest.raises(ValueError):
        ParquetFooter.read_and_filter(b"\xff\xff\xff\xff\xff")


def test_empty_schema_prunes_everything(flat_file):
    """schema={} means keep zero columns, unlike schema=None (keep all)."""
    with ParquetFooter.read_and_filter(flat_file, schema={}) as f:
        assert f.num_columns == 0


class TestJniWireSchema:
    """The Java surface (ParquetFooter.java SchemaElement.toJson) sends a
    JSON-safe schema encoding; jni_bridge._wire_schema decodes it back to
    the internal leaf=None / list / (k,v)-tuple spec."""

    def test_wire_decoding(self):
        from spark_rapids_jni_tpu.jni_bridge import _wire_schema

        wire = {"a": None,
                "s": {"x": None, "lst": {"__list__": None}},
                "m": {"__map__": [None, {"y": None}]}}
        spec = _wire_schema(wire)
        assert spec["a"] is None
        assert spec["s"]["lst"] == [None]
        assert spec["m"] == (None, {"y": None})

    def test_read_and_filter_via_invoke(self, flat_file):
        import base64

        from spark_rapids_jni_tpu.jni_bridge import invoke

        raw = read_footer_bytes(flat_file)
        args = {"data": base64.b64encode(raw).decode(),
                "schema": {"a": None, "b": None}, "ignore_case": False}
        outs, meta = invoke("ParquetFooter.readAndFilter",
                            __import__("json").dumps(args), [])
        footer = outs[0]
        assert footer.num_columns == 2
        outs2, meta2 = invoke("ParquetFooter.serializeThriftFile", "{}",
                              [footer])
        data = base64.b64decode(__import__("json").loads(meta2)["data"])
        assert data[:4] == b"PAR1" and data[-4:] == b"PAR1"
        footer.close()


class TestParquetScan:
    """read_parquet split semantics must agree with the native footer
    engine, and the q6 pipeline from a real file must match the oracle."""

    def test_split_pruning_matches_native_engine(self, flat_file):
        from spark_rapids_jni_tpu.io.parquet import (
            read_parquet,
            select_row_groups,
        )

        raw = read_footer_bytes(flat_file)
        meta = pq.ParquetFile(flat_file).metadata
        size = os.path.getsize(flat_file)
        for off, ln in [(0, size), (0, size // 2), (size // 2, size),
                        (0, 1), (size // 3, size // 3)]:
            with ParquetFooter.read_and_filter(
                    raw, part_offset=off, part_length=ln) as ft:
                native_rows = ft.num_rows
            keep = select_row_groups(meta, off, ln)
            py_rows = sum(meta.row_group(i).num_rows for i in keep)
            assert py_rows == native_rows, (off, ln)
            batch = read_parquet(flat_file, part_offset=off, part_length=ln)
            assert batch.num_rows == native_rows

    def test_q6_from_parquet_matches_oracle(self, tmp_path):
        import numpy as np

        import jax

        path = str(tmp_path / "q6.parquet")
        rng = np.random.default_rng(8)
        n = 5000
        k = rng.integers(0, 50, n).astype(np.int32)
        v = rng.integers(-1000, 1000, n)
        price = rng.random(n) * 100
        pq.write_table(pa.table({"k": k, "v": v, "price": price}), path,
                       row_group_size=512)

        import __graft_entry__ as ge

        batch = read_parquet_cols(path)
        res, ng = jax.jit(ge._q6_step)(batch)
        got = {}
        ks = res["k"].to_pylist()[: int(ng)]
        ss = res["sum_v"].to_pylist()[: int(ng)]
        cs = res["cnt"].to_pylist()[: int(ng)]
        for i in range(int(ng)):
            got[ks[i]] = (ss[i], cs[i])

        mask = price < 50.0
        want = {}
        for kk in np.unique(k[mask]):
            sel = mask & (k == kk)
            want[int(kk)] = (int(v[sel].sum()), int(sel.sum()))
        assert got == want

    def test_column_pruning_case_insensitive(self, flat_file):
        batch = read_parquet_cols(flat_file, columns=["c"],
                                  ignore_case=True)
        assert batch.names == ("C",) or list(batch.names) == ["C"]


def read_parquet_cols(path, **kw):
    from spark_rapids_jni_tpu.io.parquet import read_parquet

    return read_parquet(path, **kw)


# ---------------------------------------------------------------------------
# predicate pruning: row groups whose stats cannot satisfy the filter
# ---------------------------------------------------------------------------


@pytest.fixture
def gapped_file(tmp_path):
    """Interleaved low/high ranges: groups 0,2 hold 0..99 and groups
    1,3 hold 1000..1099, so a high predicate keeps NON-consecutive
    groups — the span-mapping edge case."""
    import numpy as np

    path = str(tmp_path / "gapped.parquet")
    a = np.r_[np.arange(100), np.arange(100) + 1000,
              np.arange(100), np.arange(100) + 1000]
    pq.write_table(pa.table({"a": pa.array(a, pa.int64())}), path,
                   row_group_size=100)
    return path


class TestPredicatePruning:
    @pytest.fixture(autouse=True)
    def _reset_config(self):
        from spark_rapids_jni_tpu import config

        yield
        config.reset()

    def test_stats_prune_drops_cold_groups(self, flat_file):
        from spark_rapids_jni_tpu.io.parquet import prune_row_groups

        meta = pq.ParquetFile(flat_file).metadata
        keep, pruned = prune_row_groups(meta, range(10), ("a", "<", 250))
        assert keep == [0, 1, 2] and pruned == 7
        keep, pruned = prune_row_groups(meta, range(10), ("a", ">=", 950))
        assert keep == [9] and pruned == 9
        keep, pruned = prune_row_groups(meta, range(10), ("a", "==", 437))
        assert keep == [4] and pruned == 9

    def test_pruned_read_unions_to_exact_result(self, flat_file):
        import numpy as np

        full = read_parquet_cols(flat_file, columns=["a"])
        a_full = np.asarray(full["a"].data)
        for pred in (("a", "<", 250), ("a", ">=", 950), ("a", "==", 437),
                     ("a", "!=", 0), ("a", "<=", 99), ("a", ">", 998)):
            col, op, v = pred
            got = read_parquet_cols(flat_file, columns=["a"],
                                    predicate=pred)
            a_got = np.asarray(got["a"].data)
            import operator as _o

            fn = {"<": _o.lt, "<=": _o.le, "==": _o.eq, "!=": _o.ne,
                  ">=": _o.ge, ">": _o.gt}[op]
            # the filter applied downstream of the pruned scan must
            # equal the filter over the full scan — nothing lost
            assert sorted(a_got[fn(a_got, v)].tolist()) == \
                sorted(a_full[fn(a_full, v)].tolist()), pred

    def test_all_pruned_keeps_schema_group(self, flat_file):
        from spark_rapids_jni_tpu.io.parquet import prune_row_groups

        meta = pq.ParquetFile(flat_file).metadata
        keep, pruned = prune_row_groups(meta, range(10), ("a", "<", -5))
        assert keep == [0] and pruned == 9  # schema-bearing survivor

    def test_unpushable_predicates_keep_everything(self, flat_file):
        from spark_rapids_jni_tpu.io.parquet import prune_row_groups

        meta = pq.ParquetFile(flat_file).metadata
        # string literal: not a stats-comparable value
        assert prune_row_groups(meta, range(10),
                                ("a", "<", "zzz"))[1] == 0
        # type-mismatched column (string stats vs int literal):
        # conservative keep via the TypeError guard
        assert prune_row_groups(meta, range(10), ("b", "<", 5))[1] == 0
        # unknown column: nothing to consult
        assert prune_row_groups(meta, range(10),
                                ("nope", "<", 5))[1] == 0

    def test_knob_off_keeps_everything(self, flat_file):
        from spark_rapids_jni_tpu import config
        from spark_rapids_jni_tpu.io.parquet import prune_row_groups

        config.set("scan_pruning", False)
        meta = pq.ParquetFile(flat_file).metadata
        assert prune_row_groups(meta, range(10), ("a", "<", 250))[1] == 0

    def test_prune_spans_union_to_surviving_groups(self, gapped_file):
        from spark_rapids_jni_tpu.io.parquet_footer import (
            predicate_prune_spans)

        spans = predicate_prune_spans(gapped_file, ("a", ">=", 900))
        assert len(spans) == 2  # non-consecutive survivors -> two runs
        groups = rows = 0
        for off, length in spans:
            with ParquetFooter.read_and_filter(gapped_file, off,
                                               length) as f:
                groups += f.num_row_groups
                rows += f.num_rows
        assert groups == 2 and rows == 200  # exactly groups 1 and 3

    def test_prune_spans_single_run(self, flat_file):
        from spark_rapids_jni_tpu.io.parquet_footer import (
            predicate_prune_spans)

        spans = predicate_prune_spans(flat_file, ("a", "<", 250))
        assert len(spans) == 1
        off, length = spans[0]
        with ParquetFooter.read_and_filter(flat_file, off, length) as f:
            assert f.num_row_groups == 3 and f.num_rows == 300

    def test_from_parquet_never_replays_pruned_groups(
            self, flat_file, eight_devices):
        import numpy as np

        from spark_rapids_jni_tpu.parallel import data_mesh
        from spark_rapids_jni_tpu.shuffle import MorselSource

        mesh = data_mesh(8)
        src = MorselSource.from_parquet(flat_file, mesh, columns=["a"],
                                        morsel_rows=16,
                                        predicate=("a", "<", 250))
        assert src.row_groups_pruned == 7
        assert src.row_groups_scanned == 3
        full = MorselSource.from_parquet(flat_file, mesh, columns=["a"],
                                         morsel_rows=16)
        assert full.row_groups_pruned == 0
        assert len(src) < len(full)  # pruned groups built NO replays
        seen = []
        for replay in src:
            b, rv = replay()
            a = np.asarray(b["a"].data)
            seen.extend(a[np.asarray(rv)].tolist())
        # every row the filter may keep is present, no cold-group rows
        assert sorted(x for x in seen if x < 250) == list(range(250))
        assert all(x < 300 for x in seen)  # only groups 0..2 decoded
