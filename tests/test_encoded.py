"""Encoded columnar execution: dictionary/RLE columns with late
materialization (columnar/encoded.py).

The correctness contract is BIT-PARITY with the decoded path: every
relational operator fed encoded columns must produce output that decodes
to exactly what the plain-column plan produces — same values, same
validity, same group/match order.  Covers:

* encode/decode round trips are bit-exact (bit-distinct dictionary:
  ``-0.0``/``0.0`` stay separate entries, NaNs keep their payloads);
* the code-set filter (``predicate_mask``) matches the row-wise mask;
* joins on encoded keys across every how — the same-token canon fast
  path, the cross-dictionary gathered-words fallback, mixed
  encoded/plain sides, and ``reconcile_dictionaries``;
* group-by on encoded/RLE keys across all aggs and both engines, with
  encoded VALUE columns late-materializing at the point of need;
* the ShuffleService exchange moves CODES (fewer bytes than the decoded
  exchange; dictionary broadcast charged once) and reattaches
  dictionaries losslessly;
* SpillableHandle round-trips encoded batches through all three tiers,
  and the ``host_corrupt`` fault is detected at promotion / disk
  read-back and recovered through ``recompute=`` lineage.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu import config, faultinj
from spark_rapids_jni_tpu.columnar import types as T
from spark_rapids_jni_tpu.columnar.column import (
    Column, ColumnBatch, Decimal128Column, StringColumn)
from spark_rapids_jni_tpu.columnar.encoded import (
    DictionaryColumn,
    RunLengthColumn,
    align_encoded_key_columns,
    dictionary_from_arrays,
    encode_batch,
    encode_column,
    encode_rle,
    is_encoded,
    materialize_batch,
    predicate_mask,
    reconcile_dictionaries,
    resolve_encoded_execution,
)
from spark_rapids_jni_tpu.mem import SpillableHandle
from spark_rapids_jni_tpu.mem import spill as spill_mod
from spark_rapids_jni_tpu.relational import AggSpec, group_by, hash_join
from spark_rapids_jni_tpu.relational.filter import apply_mask


@pytest.fixture(autouse=True)
def _reset():
    yield
    config.reset()
    faultinj.configure({})


def col_i32(vals, valid=None):
    vals = np.asarray(vals, np.int32)
    v = np.ones(len(vals), bool) if valid is None else np.asarray(valid, bool)
    return Column(jnp.asarray(vals), jnp.asarray(v), T.INT32)


def col_f64(vals, valid=None):
    vals = np.asarray(vals, np.float64)
    v = np.ones(len(vals), bool) if valid is None else np.asarray(valid, bool)
    return Column(jnp.asarray(vals), jnp.asarray(v), T.FLOAT64)


def assert_bit_exact(name, got, want):
    """Decoded column == original column over VALID rows, bitwise."""
    gv, wv = np.asarray(got.validity), np.asarray(want.validity)
    assert np.array_equal(gv, wv), f"{name}: validity"
    if isinstance(want, StringColumn):
        assert got.to_pylist() == want.to_pylist(), f"{name}: strings"
        return
    gd = np.asarray(got.data)[wv]
    wd = np.asarray(want.data)[wv]
    # bitwise: -0.0 != 0.0, NaN payloads compared as raw bytes
    assert np.array_equal(gd.view(np.uint8), wd.view(np.uint8)), \
        f"{name}: data bits"


def assert_batches_equal(name, a, ca, b, cb, approx=()):
    """Live-prefix equality via to_pylist (decodes encoded outputs)."""
    na, nb = int(ca), int(cb)
    assert na == nb, f"{name}: count {na} != {nb}"
    assert a.names == b.names, f"{name}: {a.names} vs {b.names}"
    for coln in a.names:
        la = a[coln].to_pylist()[:na]
        lb = b[coln].to_pylist()[:na]
        if coln in approx:
            for x, y in zip(la, lb):
                if x is None or y is None:
                    assert x == y, f"{name}/{coln}: null mismatch"
                elif isinstance(x, float) and np.isnan(x):
                    assert np.isnan(y), f"{name}/{coln}: NaN"
                else:
                    assert y == pytest.approx(x, rel=1e-12), f"{name}/{coln}"
        else:
            # NaN != NaN under ==, so compare via repr-stable numpy
            for x, y in zip(la, lb):
                same = (x == y) or (
                    isinstance(x, float) and isinstance(y, float)
                    and np.isnan(x) and np.isnan(y))
                assert same, f"{name}/{coln}: {x!r} != {y!r}"


# ---------------------------------------------------------------------------
# encode / decode round trips
# ---------------------------------------------------------------------------

class TestRoundTrip:
    def test_int_with_nulls(self):
        rng = np.random.default_rng(1)
        c = col_i32(rng.integers(0, 20, 200), rng.random(200) > 0.15)
        enc = encode_column(c)
        assert is_encoded(enc) and enc.num_rows == 200
        # nulls borrow an existing identity: dictionary covers live only
        live = np.unique(np.asarray(c.data)[np.asarray(c.validity)])
        assert enc.num_entries <= len(live) + 1
        assert_bit_exact("int", enc.decode(), c)
        assert enc.to_pylist() == c.to_pylist()

    def test_float_bit_distinct_entries(self):
        vals = np.array([1.5, -0.0, 0.0, np.nan, -0.0, 1.5, np.nan])
        c = col_f64(vals)
        enc = encode_column(c)
        # -0.0 and 0.0 are DISTINCT entries (decode must be bit-exact)...
        assert enc.num_entries == 4
        dec = enc.decode()
        assert_bit_exact("float", dec, c)
        assert np.signbit(np.asarray(dec.data)[1]) and not np.signbit(
            np.asarray(dec.data)[2])
        # ...but ONE equality class: canon collapses -0.0 == 0.0
        canon = np.asarray(enc.canon)
        codes = np.asarray(enc.codes)
        assert canon[codes[1]] == canon[codes[2]]

    def test_string_with_nulls(self):
        vals = ["ab", None, "abcdef", "ab", "", None, "zz"]
        c = StringColumn.from_pylist(vals, max_len=128)
        enc = encode_column(c)
        assert enc.num_entries == 4  # ab, abcdef, "", zz
        assert enc.to_pylist() == vals
        assert enc.decode().to_pylist() == vals
        # the dictionary is width-planned (bucketed ladder), not inflated
        # to the row column's 128-byte pad width
        assert enc.dictionary.max_len < 128

    def test_decimal(self):
        vals = [10 ** 20, -(10 ** 19), None, 10 ** 20, 0]
        c = Decimal128Column.from_unscaled(vals, 38, 2)
        enc = encode_column(c)
        assert enc.num_entries == 3
        assert enc.to_pylist() == c.to_pylist()

    def test_empty(self):
        enc = encode_column(col_i32([]))
        assert enc.num_rows == 0
        assert enc.decode().to_pylist() == []

    def test_rle_round_trip(self):
        vals = np.repeat([3, 7, 7, 1, 9], [10, 5, 4, 20, 1])
        v = np.ones(40, bool)
        v[::7] = False
        c = Column(jnp.asarray(vals.astype(np.int64)), jnp.asarray(v),
                   T.INT64)
        r = encode_rle(c)
        # adjacent equal values merge: 7,7 is one run
        assert r.num_runs == 4
        assert_bit_exact("rle", r.decode(), c)
        assert r.to_pylist() == c.to_pylist()
        run = np.asarray(r.row_to_run())
        assert run[0] == 0 and run[9] == 0 and run[10] == 1
        assert run[-1] == r.num_runs - 1

    def test_rle_rejects_strings(self):
        with pytest.raises(TypeError):
            encode_rle(StringColumn.from_pylist(["a", "b"], max_len=4))

    def test_encode_batch_auto_and_explicit(self):
        rng = np.random.default_rng(2)
        n = 256
        batch = ColumnBatch({
            "s": StringColumn.from_pylist(
                [f"c{i % 5}" for i in range(n)], max_len=8),
            "low": col_i32(rng.integers(0, 4, n)),
            "high": col_i32(np.arange(n)),
        })
        auto = encode_batch(batch)
        assert isinstance(auto["s"], DictionaryColumn)
        assert isinstance(auto["low"], DictionaryColumn)
        assert not is_encoded(auto["high"])  # cardinality == rows: skip
        exp = encode_batch(batch, dictionary=["s"], rle=["low"])
        assert isinstance(exp["s"], DictionaryColumn)
        assert isinstance(exp["low"], RunLengthColumn)
        assert not is_encoded(exp["high"])
        assert_batches_equal("encode_batch", materialize_batch(auto), n,
                             batch, n)

    def test_knob_validation(self):
        config.set("encoded_execution", "on")
        assert resolve_encoded_execution() is True
        config.set("encoded_execution", "off")
        assert resolve_encoded_execution() is False
        config.set("encoded_execution", "bogus")
        with pytest.raises(ValueError, match="encoded_execution"):
            resolve_encoded_execution()


# ---------------------------------------------------------------------------
# code-set filter
# ---------------------------------------------------------------------------

class TestPredicateMask:
    def test_matches_rowwise_mask(self):
        rng = np.random.default_rng(3)
        n = 300
        c = col_i32(rng.integers(0, 30, n), rng.random(n) > 0.1)
        enc = encode_column(c)
        got = np.asarray(predicate_mask(enc, lambda d: d.data < 15))
        want = (np.asarray(c.data) < 15) & np.asarray(c.validity)
        assert np.array_equal(got, want)

    def test_filter_keeps_columns_encoded(self):
        vals = [f"g{i % 4}" for i in range(64)]
        batch = encode_batch(ColumnBatch({
            "k": StringColumn.from_pylist(vals, max_len=8),
            "v": col_i32(np.arange(64)),
        }), dictionary=["k"])
        mask = predicate_mask(batch["k"],
                              lambda d: d.lengths > 0)  # all live
        out = apply_mask(batch, mask)
        assert isinstance(out["k"], DictionaryColumn)
        assert out["k"].to_pylist() == vals


# ---------------------------------------------------------------------------
# joins on encoded keys
# ---------------------------------------------------------------------------

HOWS = ("inner", "left", "right", "full", "semi", "anti")


def _join_sides(nl=120, nr=40, seed=11):
    rng = np.random.default_rng(seed)
    cats = [f"cat-{i:03d}" for i in range(24)]
    lk = [cats[i] for i in rng.integers(0, 24, nl)]
    rk = [cats[i] for i in rng.integers(0, 32 if True else 24, nr) % 24] + []
    # some right keys miss the left domain entirely
    rk = [cats[i] if i < 24 else f"miss-{i}" for i in rng.integers(0, 32, nr)]
    left = ColumnBatch({
        "k": StringColumn.from_pylist(lk, max_len=12),
        "lpay": col_i32(rng.integers(0, 1000, nl),
                        rng.random(nl) > 0.1)})
    right = ColumnBatch({
        "k": StringColumn.from_pylist(rk, max_len=12),
        "rpay": col_i32(rng.integers(0, 1000, nr))})
    return left, right


class TestJoinParity:
    @pytest.mark.parametrize("how", HOWS)
    def test_cross_dictionary_fallback(self, how):
        """Independently-encoded sides (distinct tokens) take the
        gathered-words lowering and still match the decoded join."""
        left, right = _join_sides()
        eleft = encode_batch(left, dictionary=["k"])
        eright = encode_batch(right, dictionary=["k"])
        assert eleft["k"].dict_token != eright["k"].dict_token
        rd, cd = hash_join(left, right, ["k"], ["k"], how, capacity=6000)
        re_, ce = hash_join(eleft, eright, ["k"], ["k"], how, capacity=6000)
        assert_batches_equal(f"cross/{how}", rd, cd, re_, ce)

    @pytest.mark.parametrize("how", HOWS)
    def test_reconciled_canon_fast_path(self, how):
        left, right = _join_sides(seed=13)
        eleft = encode_batch(left, dictionary=["k"])
        eright = encode_batch(right, dictionary=["k"])
        lk, rk = reconcile_dictionaries(eleft["k"], eright["k"])
        assert lk.dict_token == rk.dict_token
        # the alignment actually substitutes the single canon word
        lout, rout = align_encoded_key_columns([lk], [rk])
        assert isinstance(lout[0], Column) and isinstance(rout[0], Column)
        eleft = ColumnBatch({"k": lk, "lpay": eleft["lpay"]})
        eright = ColumnBatch({"k": rk, "rpay": eright["rpay"]})
        rd, cd = hash_join(left, right, ["k"], ["k"], how, capacity=6000)
        re_, ce = hash_join(eleft, eright, ["k"], ["k"], how, capacity=6000)
        assert_batches_equal(f"canon/{how}", rd, cd, re_, ce)

    @pytest.mark.parametrize("how", ("inner", "left", "full"))
    def test_mixed_encoded_and_plain(self, how):
        """Encoded probe side against a PLAIN build side."""
        left, right = _join_sides(seed=17)
        eleft = encode_batch(left, dictionary=["k"])
        rd, cd = hash_join(left, right, ["k"], ["k"], how, capacity=6000)
        re_, ce = hash_join(eleft, right, ["k"], ["k"], how, capacity=6000)
        assert_batches_equal(f"mixed/{how}", rd, cd, re_, ce)

    def test_align_passthrough_on_token_mismatch(self):
        a = encode_column(col_i32([1, 2, 3]))
        b = encode_column(col_i32([2, 3, 4]))
        lout, rout = align_encoded_key_columns([a], [b])
        assert lout[0] is a and rout[0] is b

    def test_engine_parity_on_encoded_keys(self):
        left, right = _join_sides(seed=19)
        el = encode_batch(left, dictionary=["k"])
        er = encode_batch(right, dictionary=["k"])
        for how in ("inner", "full", "anti"):
            rs, cs = hash_join(el, er, ["k"], ["k"], how, capacity=6000,
                               engine="sort")
            rh, ch = hash_join(el, er, ["k"], ["k"], how, capacity=6000,
                               engine="hash")
            assert_batches_equal(f"engines/{how}", rs, cs, rh, ch)


# ---------------------------------------------------------------------------
# group-by on encoded keys / values
# ---------------------------------------------------------------------------

ALL_AGGS = [AggSpec("count", None, "cstar"), AggSpec("sum", "v", "s"),
            AggSpec("count", "v", "c"), AggSpec("min", "v", "mn"),
            AggSpec("max", "v", "mx"), AggSpec("mean", "v", "avg"),
            AggSpec("sum", "f", "fs"), AggSpec("mean", "f", "favg")]
FLOAT_APPROX = ("fs", "favg")


def _gb_batch(n=400, seed=23):
    rng = np.random.default_rng(seed)
    k = [f"grp-{i:02d}" for i in rng.integers(0, 25, n)]
    return ColumnBatch({
        "k": StringColumn.from_pylist(
            [None if rng.random() < 0.1 else s for s in k], max_len=8),
        "v": col_i32(rng.integers(-1000, 1000, n), rng.random(n) > 0.15),
        "f": col_f64(rng.choice([1.5, -0.0, 0.0, np.nan, 2.5], n))})


class TestGroupByParity:
    @pytest.mark.parametrize("engine", ("sort", "scatter"))
    def test_encoded_string_key_all_aggs(self, engine):
        batch = _gb_batch()
        enc = encode_batch(batch, dictionary=["k"])
        rd, nd = group_by(batch, ["k"], ALL_AGGS, engine=engine)
        re_, ne = group_by(enc, ["k"], ALL_AGGS, engine=engine)
        assert_batches_equal(f"gb/{engine}", rd, nd, re_, ne,
                             approx=FLOAT_APPROX)

    def test_row_valid(self):
        rng = np.random.default_rng(29)
        batch = _gb_batch(seed=29)
        enc = encode_batch(batch, dictionary=["k"])
        rv = jnp.asarray(rng.random(400) > 0.3)
        rd, nd = group_by(batch, ["k"], ALL_AGGS, row_valid=rv)
        re_, ne = group_by(enc, ["k"], ALL_AGGS, row_valid=rv)
        assert_batches_equal("gb/row_valid", rd, nd, re_, ne,
                             approx=FLOAT_APPROX)

    def test_rle_key(self):
        rng = np.random.default_rng(31)
        k = np.sort(rng.integers(0, 12, 300)).astype(np.int32)
        batch = ColumnBatch({"k": col_i32(k),
                             "v": col_i32(rng.integers(0, 100, 300))})
        enc = ColumnBatch({"k": encode_rle(batch["k"]), "v": batch["v"]})
        aggs = [AggSpec("count", None, "c"), AggSpec("sum", "v", "s")]
        rd, nd = group_by(batch, ["k"], aggs)
        re_, ne = group_by(enc, ["k"], aggs)
        assert_batches_equal("gb/rle", rd, nd, re_, ne)

    def test_encoded_value_column_materializes(self):
        """Dictionary-encoded agg VALUES late-materialize at the point of
        need — sums match the plain plan exactly."""
        rng = np.random.default_rng(37)
        n = 300
        batch = ColumnBatch({
            "k": col_i32(rng.integers(0, 10, n)),
            "v": col_i32(rng.integers(0, 5, n))})  # low-card: encodable
        enc = ColumnBatch({"k": batch["k"],
                           "v": encode_column(batch["v"])})
        aggs = [AggSpec("sum", "v", "s"), AggSpec("min", "v", "mn"),
                AggSpec("max", "v", "mx")]
        rd, nd = group_by(batch, ["k"], aggs)
        re_, ne = group_by(enc, ["k"], aggs)
        assert_batches_equal("gb/encval", rd, nd, re_, ne)

    def test_jit_single_trace_same_dictionary(self):
        """Batches over ONE dictionary (shared token) share a treedef —
        the jitted group-by traces once across them."""
        cats = StringColumn.from_pylist(
            [f"g{i}" for i in range(8)], max_len=4)
        rng = np.random.default_rng(41)
        ones = jnp.ones((64,), jnp.bool_)
        base = dictionary_from_arrays(
            rng.integers(0, 8, 64).astype(np.uint32), ones, cats)
        traces = {"n": 0}

        @jax.jit
        def jgb(b):
            traces["n"] += 1
            return group_by(b, ["k"], [AggSpec("count", None, "c")])

        for seed in (1, 2, 3):
            codes = np.random.default_rng(seed).integers(0, 8, 64)
            k = dataclasses.replace(
                base, codes=jnp.asarray(codes.astype(np.uint32)))
            jgb(ColumnBatch({"k": k}))
        assert traces["n"] == 1


# ---------------------------------------------------------------------------
# shuffle: codes move, dictionaries broadcast once
# ---------------------------------------------------------------------------

P8 = 8


class TestShuffleEncoded:
    def _batches(self, n):
        rng = np.random.default_rng(43)
        # wide strings make the decoded exchange pay real byte width
        vals = [f"warehouse-{i:02d}-{'x' * 12}" for i in
                rng.integers(0, 16, n)]
        plain = ColumnBatch({
            "k": StringColumn.from_pylist(vals, max_len=28),
            "v": Column(jnp.asarray(rng.integers(0, 1000, n)),
                        jnp.ones((n,), jnp.bool_), T.INT64)})
        return plain, encode_batch(plain, dictionary=["k"])

    def test_codes_move_fewer_bytes_lossless(self, eight_devices):
        from spark_rapids_jni_tpu.parallel import data_mesh, shard_batch
        from spark_rapids_jni_tpu.shuffle import (
            ShuffleRegistry, ShuffleService)

        mesh = data_mesh(P8)
        n = P8 * 64
        plain, enc = self._batches(n)
        pid = jax.device_put(
            jnp.asarray(np.arange(n, dtype=np.int32) % P8),
            jax.sharding.NamedSharding(mesh,
                                       jax.sharding.PartitionSpec("data")))
        svc = ShuffleService(mesh, registry=ShuffleRegistry())
        rp = svc.exchange(shard_batch(plain, mesh), pid=pid)
        re_ = svc.exchange(shard_batch(enc, mesh), pid=pid)
        assert rp.rows_moved == re_.rows_moved == n
        # the encoded exchange moves u32 codes + ONE dictionary broadcast
        assert re_.bytes_moved < rp.bytes_moved
        # lossless: delivered rows decode to the same multiset
        occ_p = np.asarray(jax.device_get(rp.occupancy))
        occ_e = np.asarray(jax.device_get(re_.occupancy))
        kp = [v for v, ok in zip(rp.batch["k"].to_pylist(), occ_p) if ok]
        ke = [v for v, ok in zip(re_.batch["k"].to_pylist(), occ_e) if ok]
        assert sorted(kp) == sorted(ke)
        assert isinstance(re_.batch["k"], DictionaryColumn)

    def test_keyed_routing_matches_decoded(self, eight_devices):
        """Routing BY an encoded key hashes the VALUES (codes are
        dictionary-local) — per-partition row sets match the plain path."""
        from spark_rapids_jni_tpu.parallel import data_mesh, shard_batch
        from spark_rapids_jni_tpu.shuffle import (
            ShuffleRegistry, ShuffleService)

        mesh = data_mesh(P8)
        n = P8 * 32
        plain, enc = self._batches(n)
        svc = ShuffleService(mesh, registry=ShuffleRegistry())
        rp = svc.exchange(shard_batch(plain, mesh), key_names=["k"])
        re_ = svc.exchange(shard_batch(enc, mesh), key_names=["k"])
        assert rp.rows_moved == re_.rows_moved == n

        def per_shard(res):
            occ = np.asarray(jax.device_get(res.occupancy))
            ks = res.batch["k"].to_pylist()
            rows = len(occ) // P8
            return [sorted(k for k, ok in zip(
                ks[d * rows:(d + 1) * rows], occ[d * rows:(d + 1) * rows])
                if ok) for d in range(P8)]

        assert per_shard(rp) == per_shard(re_)


# ---------------------------------------------------------------------------
# spill: encoded trees through the tiers; host_corrupt detection/recovery
# ---------------------------------------------------------------------------

@pytest.fixture
def framework(tmp_path):
    fw = spill_mod.install(spill_dir=str(tmp_path / "spill"))
    yield fw
    spill_mod.shutdown()


def _enc_tree(seed=5):
    rng = np.random.default_rng(seed)
    n = 256
    batch = ColumnBatch({
        "k": StringColumn.from_pylist(
            [f"s{i % 9}" for i in rng.integers(0, 9, n)], max_len=4),
        "r": col_i32(np.sort(rng.integers(0, 6, n))),
        "v": col_i32(rng.integers(0, 1000, n))})
    return encode_batch(batch, dictionary=["k"], rle=["r"])


class TestSpillEncoded:
    def test_three_tier_round_trip(self, framework):
        enc = _enc_tree()
        want = {c: enc[c].to_pylist() for c in enc.names}
        h = SpillableHandle(enc, name="enc")
        h.spill()
        assert h.tier == "host"
        h.spill_host()
        assert h.tier == "disk"
        got = h.get()
        assert h.tier == "device"
        # encodings survive the walk: still encoded, bit-identical
        assert isinstance(got["k"], DictionaryColumn)
        assert isinstance(got["r"], RunLengthColumn)
        assert got["k"].dict_token == enc["k"].dict_token
        for c in enc.names:
            assert got[c].to_pylist() == want[c]
        h.close()

    def test_host_corrupt_detected_loudly(self, framework):
        faultinj.configure({"faults": [
            {"match": "host_corrupt_probe", "fault": "host_corrupt",
             "count": 1}]})
        h = SpillableHandle(_enc_tree(), name="hc")
        h.spill()  # the injected flip damages the host copy
        assert h.tier == "host"
        with pytest.raises(faultinj.HostCorruptionError):
            h.get()
        assert framework.metrics.snapshot()["corrupt_reads"] == 1
        h.close()

    def test_host_corrupt_recovers_via_lineage(self, framework):
        enc = _enc_tree(seed=7)
        want = {c: enc[c].to_pylist() for c in enc.names}
        faultinj.configure({"faults": [
            {"match": "host_corrupt_probe", "fault": "host_corrupt",
             "count": 1}]})
        h = SpillableHandle(enc, name="hcr", recompute=lambda: _enc_tree(
            seed=7))
        h.spill()
        got = h.get()  # detect → discard → rebuild from lineage
        for c in enc.names:
            assert got[c].to_pylist() == want[c]
        assert framework.metrics.snapshot()["corrupt_reads"] == 1
        h.close()

    def test_host_corrupt_cascades_to_disk_readback(self, framework):
        """Damage in the host tier lands on disk with the DEMOTION-time
        CRC (re-hashing would launder it) — the disk read-back detects."""
        faultinj.configure({"faults": [
            {"match": "host_corrupt_probe", "fault": "host_corrupt",
             "count": 1}]})
        h = SpillableHandle(_enc_tree(seed=9), name="hcd")
        h.spill()
        h.spill_host()
        assert h.tier == "disk"
        with pytest.raises(faultinj.SpillCorruptionError):
            h.get()
        h.close()

    def test_checksum_off_skips_detection(self, framework):
        """Without spill_checksum there is no demotion-time CRC: the
        flip goes undetected (documented trade-off, not a promise)."""
        config.set("spill_checksum", False)
        faultinj.configure({"faults": [
            {"match": "host_corrupt_probe", "fault": "host_corrupt",
             "count": 1}]})
        h = SpillableHandle({"x": jnp.arange(64, dtype=jnp.int32)},
                            name="nock")
        h.spill()
        h.get()  # no meta recorded -> promotion cannot verify
        assert framework.metrics.snapshot()["corrupt_reads"] == 0
        h.close()
