"""Fleet result cache (r16, ``serve/result_cache.py``): snapshot ids,
the three-component key, tiered capacity, and the stale/corrupt
detection paths.

The contract under test everywhere: a cached answer is served ONLY
when signature, input snapshot id, and knob fingerprint all match —
and a served hit is bit-identical with zero compute (no admission
ticket, no worker transfer).  Detection of a stale or damaged entry
always resolves to a recompute, never a wrong answer.
"""

import os
import time
from types import SimpleNamespace

import pytest

from spark_rapids_jni_tpu import config, faultinj
from spark_rapids_jni_tpu.columnar import Column, ColumnBatch
from spark_rapids_jni_tpu.columnar import types as T
from spark_rapids_jni_tpu.plan import compile as plan_compile
from spark_rapids_jni_tpu.plan import ir
from spark_rapids_jni_tpu.serve import FrontDoor
from spark_rapids_jni_tpu.serve import data_plane as dp
from spark_rapids_jni_tpu.serve import result_cache as rc
from spark_rapids_jni_tpu.serve import runtime as rt


@pytest.fixture(autouse=True)
def _clean():
    config.set("serve_backoff_ms", 40.0)
    yield
    config.reset("serve_backoff_ms")
    faultinj.configure(None)


def _batch(vals):
    return ColumnBatch({"x": Column.from_pylist(list(vals), T.INT64)})


def _payload(n, seed=0):
    return bytes((seed + i) % 256 for i in range(n))


def _cache_triple(tag="t"):
    """A ready-to-use (signature, snapshot, knob_fp) key triple."""
    return (rc.query_signature("arrow_batch", {"rows": 64, "tag": tag}),
            rc.snapshot_for_obj({"tag": tag, "gen": 0}),
            rc.knob_fingerprint())


class TestSnapshotIds:
    def test_batch_content_hash_stable_and_mutation_sensitive(self):
        vals = list(range(32))
        s1 = rc.snapshot_for_batch(_batch(vals))
        s2 = rc.snapshot_for_batch(_batch(list(vals)))
        assert s1 == s2 and s1.startswith("mem:")
        mutated = list(vals)
        mutated[17] += 1  # one-row mutation must change the id
        assert rc.snapshot_for_batch(_batch(mutated)) != s1

    def test_path_snapshot_tracks_rewrites(self, tmp_path):
        p = tmp_path / "input.parquet"
        p.write_bytes(b"a" * 128)
        s1 = rc.snapshot_for_path(str(p))
        assert s1 == rc.snapshot_for_path(str(p))
        assert s1.startswith("file:")
        # same-size rewrite: mtime_ns moves, so the id must move
        p.write_bytes(b"b" * 128)
        os.utime(p, ns=(time.time_ns(), time.time_ns() + 1))
        assert rc.snapshot_for_path(str(p)) != s1
        with pytest.raises(OSError):
            rc.snapshot_for_path(str(tmp_path / "missing"))

    def test_obj_snapshot_canonical(self):
        a = rc.snapshot_for_obj({"rows": 64, "seed": 3})
        b = rc.snapshot_for_obj({"seed": 3, "rows": 64})
        assert a == b  # dict order is canonicalized
        assert rc.snapshot_for_obj({"rows": 64, "seed": 4}) != a


class TestResultKey:
    def test_no_snapshot_id_no_caching_never_a_guess(self):
        plan = ir.Scan("t")
        assert plan_compile.result_key(plan, {"t": object()}) is None
        src = SimpleNamespace(snapshot_id="mem:abc")
        key = plan_compile.result_key(plan, {"t": src})
        assert key is not None
        # every scan must be pinned: one unproven input poisons the key
        two = ir.Union((ir.Scan("t"), ir.Scan("u"))) \
            if hasattr(ir, "Union") else None
        if two is not None:
            assert plan_compile.result_key(
                two, {"t": src, "u": object()}) is None

    def test_key_moves_with_each_component(self):
        plan = ir.Scan("t")
        src = SimpleNamespace(snapshot_id="mem:abc")
        base = plan_compile.result_key(plan, {"t": src})
        moved = plan_compile.result_key(
            plan, {"t": SimpleNamespace(snapshot_id="mem:abd")})
        assert moved != base  # snapshot component
        config.set("shuffle_round_rows", 1 << 12)
        try:
            flipped = plan_compile.result_key(plan, {"t": src})
        finally:
            config.reset("shuffle_round_rows")
        assert flipped != base  # knob-fingerprint component
        other = plan_compile.result_key(ir.Scan("u"), {"u": src})
        assert other != base  # signature component

    def test_plan_cache_key_stays_content_blind(self):
        # the plan cache reuses compiled programs ACROSS contents: its
        # key must not move when only the snapshot does
        plan = ir.Scan("t")
        b = _batch(range(16))
        k1 = plan_compile.plan_cache_key(plan, {"t": b})
        k2 = plan_compile.plan_cache_key(ir.Scan("t"), {"t": b})
        assert k1 == k2


class TestCacheCore:
    def test_miss_insert_hit_roundtrip(self):
        cache = rc.ResultCache(max_bytes=1 << 20)
        sig, snap, fp = _cache_triple()
        assert cache.serve(sig, snap, fp) is None
        payload = _payload(4096, seed=9)
        assert cache.insert(sig, snap, fp, payload, schema_fp="fp0",
                            tenant="a", chunk_bytes=1024)
        view = cache.serve(sig, snap, fp)
        assert view is not None
        assert bytes(view.payload) == payload  # bit-identical bytes
        assert view.snapshot == snap
        assert view.crcs == list(
            dp.chunk_crcs(memoryview(payload), 1024))
        cache.record_hit(view.size)
        m = cache.metrics()
        assert (m["hits"], m["misses"], m["inserts"]) == (1, 1, 1)
        assert m["hit_bytes_served"] == len(payload)
        cache.clear()

    def test_any_component_mismatch_is_a_miss(self):
        cache = rc.ResultCache(max_bytes=1 << 20)
        sig, snap, fp = _cache_triple()
        cache.insert(sig, snap, fp, _payload(256), schema_fp="fp0")
        assert cache.serve(sig, snap + "!new", fp) is None
        assert cache.serve(rc.query_signature("arrow_batch",
                                              {"rows": 65}),
                           snap, fp) is None
        config.set("shuffle_round_rows", 1 << 12)
        try:
            assert cache.serve(sig, snap, rc.knob_fingerprint()) is None
        finally:
            config.reset("shuffle_round_rows")
        assert cache.serve(sig, None, fp) is None  # never a guess
        cache.clear()

    def test_disabled_knob_bypasses_both_directions(self):
        cache = rc.ResultCache(max_bytes=1 << 20)
        sig, snap, fp = _cache_triple()
        config.set("result_cache", False)
        try:
            assert not cache.insert(sig, snap, fp, _payload(64),
                                    schema_fp="fp0")
            assert cache.serve(sig, snap, fp) is None
            assert len(cache) == 0
        finally:
            config.reset("result_cache")

    def test_tenant_quota_evicts_own_lru_only(self):
        cache = rc.ResultCache(max_bytes=1 << 20, tenant_quota=2048)
        fp = rc.knob_fingerprint()
        keys = {}
        for i in range(3):  # 3 x 1KiB for tenant a: quota holds 2
            sig = rc.query_signature("arrow_batch", {"i": i})
            snap = rc.snapshot_for_obj({"i": i})
            keys[i] = (sig, snap)
            cache.insert(sig, snap, fp, _payload(1024, seed=i),
                         schema_fp="fp0", tenant="a")
        bsig = rc.query_signature("arrow_batch", {"i": 99})
        bsnap = rc.snapshot_for_obj({"i": 99})
        cache.insert(bsig, bsnap, fp, _payload(1024, seed=99),
                     schema_fp="fp0", tenant="b")
        # tenant a's OLDEST entry paid; a's newest and b's survive
        assert cache.serve(*keys[0], fp) is None
        assert cache.serve(*keys[2], fp) is not None
        assert cache.serve(bsig, bsnap, fp) is not None
        assert cache.metrics()["quota_evictions"] >= 1
        assert cache.tenant_bytes("a") <= 2048
        assert cache.tenant_bytes("b") == 1024
        cache.clear()

    def test_host_budget_demotes_before_dropping(self, tmp_path):
        # the disk tier exists only under an installed spill framework;
        # without one the budget can only DROP (graceful degradation)
        from spark_rapids_jni_tpu.mem import spill as spill_mod

        spill_mod.install(spill_dir=str(tmp_path / "spill"))
        try:
            cache = rc.ResultCache(max_bytes=8192, tenant_quota=0)
            fp = rc.knob_fingerprint()
            triples = []
            for i in range(3):  # 3 x 4KiB against an 8KiB host budget
                sig = rc.query_signature("arrow_batch", {"i": i})
                snap = rc.snapshot_for_obj({"i": i})
                triples.append((sig, snap))
                cache.insert(sig, snap, fp, _payload(4096, seed=i),
                             schema_fp="fp0", tenant="a")
            m = cache.metrics()
            assert m["demotions"] >= 1 and m["drops"] == 0
            assert cache.tiers().get("disk", 0) >= 1
            assert m["host_bytes"] <= 8192
            # a demoted entry still serves its exact bytes (checksummed
            # disk read-back through the spill framework)
            for i, (sig, snap) in enumerate(triples):
                view = cache.serve(sig, snap, fp)
                assert view is not None
                assert bytes(view.payload) == _payload(4096, seed=i)
            cache.clear()
        finally:
            spill_mod.shutdown()

    def test_no_framework_budget_drops_loudly_counted(self):
        # no spill framework installed: over-budget entries cannot
        # demote, so the cache drops its coldest and counts it
        cache = rc.ResultCache(max_bytes=8192, tenant_quota=0)
        fp = rc.knob_fingerprint()
        for i in range(3):
            cache.insert(rc.query_signature("arrow_batch", {"i": i}),
                         rc.snapshot_for_obj({"i": i}), fp,
                         _payload(4096, seed=i), schema_fp="fp0")
        m = cache.metrics()
        assert m["drops"] >= 1
        assert m["host_bytes"] <= 8192
        cache.clear()

    def test_invalidate_snapshot_drops_all_entries_for_it(self):
        cache = rc.ResultCache(max_bytes=1 << 20)
        fp = rc.knob_fingerprint()
        snap = rc.snapshot_for_obj({"shared": True})
        for i in range(2):
            cache.insert(rc.query_signature("arrow_batch", {"i": i}),
                         snap, fp, _payload(128), schema_fp="fp0")
        other = rc.snapshot_for_obj({"shared": False})
        cache.insert(rc.query_signature("arrow_batch", {"i": 9}),
                     other, fp, _payload(128), schema_fp="fp0")
        assert cache.invalidate_snapshot(snap) == 2
        assert len(cache) == 1
        assert cache.serve(rc.query_signature("arrow_batch", {"i": 9}),
                           other, fp) is not None
        cache.clear()


class TestFaultPaths:
    """The injected `cache_stale` / `cache_corrupt` kinds, converted to
    real damage at the `cache_serve` / `cache_insert` probes — serve
    verification must catch every shape."""

    def test_stale_at_serve_surfaces_rewound_snapshot(self):
        cache = rc.ResultCache(max_bytes=1 << 20)
        sig, snap, fp = _cache_triple()
        cache.insert(sig, snap, fp, _payload(512), schema_fp="fp0")
        faultinj.configure({"faults": [
            {"match": "cache_serve", "fault": "cache_stale", "count": 1},
        ]})
        view = cache.serve(sig, snap, fp)
        # the view's snapshot no longer equals the submit's expected
        # one — exactly what the front door's verify_snapshot rejects
        assert view is not None and view.snapshot != snap
        cache.record_stale(view.key)
        assert cache.metrics()["stale_rejected"] == 1
        # the entry itself is kept: a genuinely mutated input arrives
        # under a NEW id and simply never matches this key
        clean = cache.serve(sig, snap, fp)
        assert clean is not None and clean.snapshot == snap
        cache.clear()

    def test_stale_at_insert_rewinds_the_stored_id(self):
        cache = rc.ResultCache(max_bytes=1 << 20)
        sig, snap, fp = _cache_triple()
        faultinj.configure({"faults": [
            {"match": "cache_insert", "fault": "cache_stale", "count": 1},
        ]})
        cache.insert(sig, snap, fp, _payload(512), schema_fp="fp0")
        faultinj.configure(None)
        view = cache.serve(sig, snap, fp)
        assert view is not None and view.snapshot != snap
        cache.clear()

    def _assert_corrupt_detected(self, cache, sig, snap, fp, payload):
        view = cache.serve(sig, snap, fp)
        if view is None:
            # the stored tier itself refused the bytes (checksummed
            # read-back) and the entry was quarantined in serve()
            pass
        else:
            # host-tier damage: the bytes came back but can never
            # re-derive the insert-time chunk CRCs — the front door's
            # per-chunk verify catches it and quarantines
            got = list(dp.chunk_crcs(memoryview(view.payload),
                                     view.chunk_bytes))
            assert got != view.crcs
            assert bytes(view.payload) != payload
            cache.quarantine(view.key)
        assert cache.metrics()["corrupt_quarantined"] == 1
        assert cache.serve(sig, snap, fp) is None  # slot freed

    def test_corrupt_at_serve_quarantined(self):
        cache = rc.ResultCache(max_bytes=1 << 20)
        sig, snap, fp = _cache_triple()
        payload = _payload(2048, seed=5)
        cache.insert(sig, snap, fp, payload, schema_fp="fp0",
                     chunk_bytes=512)
        faultinj.configure({"faults": [
            {"match": "cache_serve", "fault": "cache_corrupt",
             "count": 1},
        ]})
        self._assert_corrupt_detected(cache, sig, snap, fp, payload)
        cache.clear()

    def test_corrupt_at_insert_detected_on_first_serve(self):
        cache = rc.ResultCache(max_bytes=1 << 20)
        sig, snap, fp = _cache_triple()
        payload = _payload(2048, seed=6)
        faultinj.configure({"faults": [
            {"match": "cache_insert", "fault": "cache_corrupt",
             "count": 1},
        ]})
        cache.insert(sig, snap, fp, payload, schema_fp="fp0",
                     chunk_bytes=512)
        faultinj.configure(None)
        self._assert_corrupt_detected(cache, sig, snap, fp, payload)
        cache.clear()


class TestFrontDoorE2E:
    def test_hit_bit_identical_with_zero_compute(self):
        fd = FrontDoor(workers=2, heartbeat_ms=80.0)
        try:
            snap = rc.snapshot_for_obj({"case": "e2e", "gen": 0})
            params = {"rows": 256, "seed": 5}
            warm = fd.submit("arrow_batch", params, tenant="a",
                             snapshot=snap)
            digest = dp.batch_digest(warm.result(timeout=90))
            assert not warm.served_from_cache
            before = fd.metrics.snapshot()
            tick0 = rt.admission_tickets_issued()
            # repeat — from ANOTHER tenant, pinned to the other worker:
            # the cache is supervisor-side and fleet-wide
            hit = fd.submit("arrow_batch", params, tenant="b",
                            snapshot=snap)
            assert dp.batch_digest(hit.result(timeout=90)) == digest
            assert hit.served_from_cache
            after = fd.metrics.snapshot()
            # zero compute: no admission ticket, no data-plane transfer
            assert rt.admission_tickets_issued() == tick0
            assert after["data_batches"] == before["data_batches"]
            assert after["cache_hits"] == before["cache_hits"] + 1
            assert after["hit_bytes_served"] > before["hit_bytes_served"]
            # a mutated input is a NEW snapshot id: never a hit
            moved = fd.submit("arrow_batch", params, tenant="b",
                              snapshot=rc.snapshot_for_obj(
                                  {"case": "e2e", "gen": 1}))
            assert dp.batch_digest(moved.result(timeout=90)) == digest
            assert not moved.served_from_cache
            # no snapshot id, no caching: repeats recompute every time
            for _ in range(2):
                bare = fd.submit("arrow_batch", params, tenant="a")
                bare.result(timeout=90)
                assert not bare.served_from_cache
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        m = report["result_cache"]
        assert m["hits"] == 1 and m["inserts"] >= 2
        assert m["hit_bytes_served"] > 0

    def test_stale_and_corrupt_entries_recompute_not_served(self):
        fd = FrontDoor(workers=1, heartbeat_ms=80.0)
        try:
            snap = rc.snapshot_for_obj({"case": "faulted", "gen": 0})
            params = {"rows": 128, "seed": 11}
            warm = fd.submit("arrow_batch", params, tenant="a",
                             snapshot=snap)
            digest = dp.batch_digest(warm.result(timeout=90))
            fired = set()
            # 1) the cached entry goes stale right at serve time: the
            # snapshot fence rejects it and the query recomputes
            faultinj.configure({"faults": [
                {"match": "cache_serve", "fault": "cache_stale",
                 "count": 1},
            ]})
            s = fd.submit("arrow_batch", params, tenant="a",
                          snapshot=snap)
            assert dp.batch_digest(s.result(timeout=90)) == digest
            assert not s.served_from_cache
            fired |= {e.get("fault") for e in faultinj.fired_log()}
            # 2) real payload damage while cached: chunk CRCs catch it,
            # the entry is quarantined, the query recomputes + reinserts
            faultinj.configure({"faults": [
                {"match": "cache_serve", "fault": "cache_corrupt",
                 "count": 1},
            ]})
            c = fd.submit("arrow_batch", params, tenant="a",
                          snapshot=snap)
            assert dp.batch_digest(c.result(timeout=90)) == digest
            assert not c.served_from_cache
            fired |= {e.get("fault") for e in faultinj.fired_log()}
            # 3) fault cleared: the reinserted entry serves a clean hit
            # (configure resets the fired trace, hence the captures)
            faultinj.configure(None)
            h = fd.submit("arrow_batch", params, tenant="a",
                          snapshot=snap)
            assert dp.batch_digest(h.result(timeout=90)) == digest
            assert h.served_from_cache
        finally:
            report = fd.shutdown()
        assert report["clean"], report
        m = report["result_cache"]
        assert m["stale_rejected"] >= 1
        assert m["corrupt_quarantined"] >= 1
        assert m["hits"] >= 1
        assert {"cache_stale", "cache_corrupt"} <= fired
