"""decimal -> string vs Java BigDecimal.toString oracle (python Decimal)."""

from decimal import Decimal, localcontext

import pytest

from spark_rapids_jni_tpu.columnar.column import Decimal128Column
from spark_rapids_jni_tpu.ops.decimal_to_string import decimal_to_string


def oracle(unscaled: int, scale: int) -> str:
    """Java BigDecimal(unscaled, scale).toString()."""
    with localcontext() as ctx:
        ctx.prec = 80
        d = Decimal(unscaled).scaleb(-scale)
    # python Decimal string rules match Java BigDecimal.toString (both
    # switch to scientific when adjusted exponent < -6 or scale < 0)
    return str(d)


def col(vals, scale, precision=38):
    return Decimal128Column.from_unscaled(vals, precision, scale)


class TestDecimalToString:
    @pytest.mark.parametrize("scale", [0, 1, 2, 6, 10, 37])
    def test_random_vs_oracle(self, rng, scale):
        vals = []
        for _ in range(40):
            bits = int(rng.integers(1, 120))
            v = int(rng.integers(0, 2**60)) << (bits // 2) | int(
                rng.integers(0, 2**30)
            )
            v &= (1 << bits) - 1
            if rng.random() < 0.5:
                v = -v
            vals.append(v)
        vals += [0, 1, -1, 10**scale if scale else 1]
        got = decimal_to_string(col(vals, scale)).to_pylist()
        for g, v in zip(got, vals):
            assert g == oracle(v, scale), (v, scale, g, oracle(v, scale))

    def test_goldens(self):
        assert decimal_to_string(col([123456], 2)).to_pylist() == ["1234.56"]
        assert decimal_to_string(col([-123456], 2)).to_pylist() == ["-1234.56"]
        assert decimal_to_string(col([5], 3)).to_pylist() == ["0.005"]
        assert decimal_to_string(col([0], 2)).to_pylist() == ["0.00"]
        assert decimal_to_string(col([7], 0)).to_pylist() == ["7"]
        # adjusted exponent < -6 -> scientific
        assert decimal_to_string(col([1], 8)).to_pylist() == ["1E-8"]
        assert decimal_to_string(col([12], 9)).to_pylist() == ["1.2E-8"]
        assert decimal_to_string(col([123], 10)).to_pylist() == ["1.23E-8"]
        # boundary: adjusted == -6 stays plain
        assert decimal_to_string(col([1], 6)).to_pylist() == ["0.000001"]
        assert decimal_to_string(col([1], 7)).to_pylist() == ["1E-7"]

    def test_nulls(self):
        assert decimal_to_string(col([123, None], 1)).to_pylist() == ["12.3", None]

    def test_full_precision(self):
        v = 12345678901234567890123456789012345678
        assert decimal_to_string(col([v], 10)).to_pylist() == [
            "1234567890123456789012345678.9012345678"
        ]
        assert decimal_to_string(col([-v], 0)).to_pylist() == [
            "-12345678901234567890123456789012345678"
        ]
