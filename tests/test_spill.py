"""Tiered spill framework tests (mem/spill.py).

Covers the subsystem end-to-end: tier walks with exact metric
accounting, the bounded host tier demoting to disk under CpuRetryOOM
pressure, task-aware LRU eviction priority, the spill()/get() race fix,
TaskContext auto-unregistration, injected spill-I/O faults degrading to
the higher tier, and the acceptance scenario — two concurrent tasks
oversubscribing the device arena and completing via automatic cross-task
device→host→disk spill and read-back with no manual ``make_spillable``
wiring (the reference proves the same story with
SpillableColumnarBatch + SpillFramework suites plugin-side).
"""

import os
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_jni_tpu import faultinj, profiler
from spark_rapids_jni_tpu.mem import (
    RmmSpark,
    Spillable,
    SpillableHandle,
    TaskContext,
    ThreadStateRegistry,
    batch_nbytes,
    run_with_retry,
)
from spark_rapids_jni_tpu.mem import spill as spill_mod

MB = 1 << 20
KB = 1 << 10


@pytest.fixture
def framework(tmp_path):
    fw = spill_mod.install(spill_dir=str(tmp_path / "spill"))
    yield fw
    spill_mod.shutdown()


@pytest.fixture
def adaptor():
    a = RmmSpark.set_event_handler(2 * MB, host_pool_bytes=512 * KB,
                                   poll_ms=10.0)
    yield a
    RmmSpark.clear_event_handler()


def _tree(n_words, seed=0):
    """A device tree of n_words int32 (4 * n_words bytes)."""
    return {"x": jnp.asarray(
        np.random.default_rng(seed).integers(0, 1 << 20, n_words,
                                             dtype=np.int32))}


def _spill_files(fw):
    return [f for f in os.listdir(fw.spill_dir)
            if os.path.isfile(os.path.join(fw.spill_dir, f))]


class TestTierWalk:
    def test_device_host_disk_roundtrip_exact_metrics(self, framework):
        h = SpillableHandle(_tree(256), name="walk")
        want = np.asarray(h.get()["x"])
        assert h.tier == "device"
        h.spill()
        assert h.tier == "host"
        h.spill_host()
        assert h.tier == "disk"
        assert len(_spill_files(framework)) == 1
        got = np.asarray(h.get()["x"])
        assert h.tier == "device"
        assert (got == want).all()
        assert _spill_files(framework) == []  # read-back deletes the file
        m = framework.metrics.snapshot()
        assert m["device_to_host_bytes"] == 1024
        assert m["host_to_disk_bytes"] == 1024
        assert m["disk_to_host_bytes"] == 1024
        assert m["host_to_device_bytes"] == 1024
        assert all(m[k] == 1 for k in (
            "device_to_host_count", "host_to_disk_count",
            "disk_to_host_count", "host_to_device_count"))
        assert m["eviction_ns"] > 0
        h.close()
        assert h.tier == "closed"
        assert len(framework.store) == 0

    def test_close_cleans_disk_files(self, framework):
        h = SpillableHandle(_tree(64), name="cleanup")
        h.spill()
        h.spill_host()
        assert len(_spill_files(framework)) == 1
        h.close()
        assert _spill_files(framework) == []
        with pytest.raises(ValueError):
            h.get()

    def test_spill_is_idempotent(self, framework):
        h = SpillableHandle(_tree(64))
        assert h.spill() == 0  # uncharged (no ctx): moved but freed 0
        assert h.tier == "host"
        assert h.spill() == 0  # already host: no-op
        assert framework.metrics.snapshot()["device_to_host_count"] == 1
        h.close()


class TestChargedTiers:
    def test_spill_releases_device_charge_get_recharges(self, framework,
                                                        adaptor):
        with TaskContext(1) as ctx:
            h = SpillableHandle(_tree(64 * KB // 4), ctx=ctx)
            nbytes = 64 * KB
            assert adaptor.total_allocated() == nbytes
            freed = h.spill()
            assert freed == nbytes
            assert adaptor.total_allocated() == 0
            # host tier is CHARGED against the unified host arena
            assert adaptor.host_total_allocated() == nbytes
            h.get()
            assert adaptor.total_allocated() == nbytes
            assert adaptor.host_total_allocated() == 0
            h.close()
            assert adaptor.total_allocated() == 0
        RmmSpark.task_done(1)

    def test_host_pressure_demotes_lru_to_disk(self, framework, adaptor):
        """Filling the 512K host arena pushes the COLDEST host batch to
        disk (the SpillableHostStore host→disk demotion)."""
        with TaskContext(1) as ctx:
            h1 = SpillableHandle(_tree(200 * KB // 4, seed=1), ctx=ctx,
                                 name="h1")
            h2 = SpillableHandle(_tree(200 * KB // 4, seed=2), ctx=ctx,
                                 name="h2")
            h3 = SpillableHandle(_tree(300 * KB // 4, seed=3), ctx=ctx,
                                 name="h3")
            h1.spill()   # host: 200K
            h2.spill()   # host: 400K
            h3.spill()   # 300K > 112K free -> h1 (LRU) demoted to disk
            assert h1.tier == "disk"
            assert h2.tier == "host"
            assert h3.tier == "host"
            m = framework.metrics.snapshot()
            assert m["host_to_disk_bytes"] == 200 * KB
            assert adaptor.host_total_allocated() == 500 * KB
            for h, words, seed in ((h1, 200 * KB // 4, 1),
                                   (h2, 200 * KB // 4, 2),
                                   (h3, 300 * KB // 4, 3)):
                assert (np.asarray(h.get()["x"])
                        == np.asarray(_tree(words, seed=seed)["x"])).all()
                h.close()
        RmmSpark.task_done(1)

    def test_batch_bigger_than_host_pool_goes_straight_to_disk(
            self, framework, adaptor):
        with TaskContext(1) as ctx:
            h = SpillableHandle(_tree(1 * MB // 4), ctx=ctx)  # 1M > 512K
            h.spill()
            assert h.tier == "disk"  # host tier can NEVER hold it
            assert adaptor.host_total_allocated() == 0
            m = framework.metrics.snapshot()
            assert m["device_to_host_bytes"] == 1 * MB
            assert m["host_to_disk_bytes"] == 1 * MB
            h.close()
        RmmSpark.task_done(1)


class TestStorePriority:
    def test_lru_order_and_task_awareness(self, framework):
        a = SpillableHandle(_tree(64), name="a")
        a.task_id = 1
        b = SpillableHandle(_tree(64), name="b")
        b.task_id = 2
        c = SpillableHandle(_tree(64), name="c")
        c.task_id = 2
        a.get()  # a is now the hottest AND owned by the requester
        freed = framework.spill_to_fit(requesting_task_id=1)
        assert freed == 0  # uncharged handles free no device bytes
        # nbytes=None (spill everything eligible): others AND own unpinned
        assert a.tier == "host" and b.tier == "host" and c.tier == "host"
        for h in (a, b, c):
            h.close()

    def test_eviction_order_other_tasks_lru_first(self, framework):
        order = []
        hs = []
        for name, task in (("own-cold", 1), ("other-new", 2),
                           ("other-old", 2)):
            h = SpillableHandle(_tree(16), name=name)
            h.task_id = task
            orig = h.spill
            h.spill = (lambda o=orig, n=name: (order.append(n), o())[1])
            hs.append(h)
        hs[0]._last_use = 1  # requester's own batch is the COLDEST
        hs[2]._last_use = 2
        hs[1]._last_use = 3
        framework.spill_to_fit(requesting_task_id=1)
        # other tasks' batches go first (LRU among them); the requester's
        # own — though colder than both — goes last
        assert order == ["other-old", "other-new", "own-cold"]
        for h in hs:
            h.close()

    def test_pinned_handles_are_skipped(self, framework):
        h = SpillableHandle(_tree(64), name="pinned")
        with h.pinned():
            framework.spill_to_fit()
            assert h.tier == "device"
        framework.spill_to_fit()
        assert h.tier == "host"
        h.close()

    def test_spill_to_fit_stops_at_nbytes(self, framework, adaptor):
        with TaskContext(1) as ctx:
            h1 = SpillableHandle(_tree(64 * KB // 4), ctx=ctx, name="old")
            h2 = SpillableHandle(_tree(64 * KB // 4), ctx=ctx, name="new")
            h2.get()  # h1 is LRU
            freed = framework.spill_to_fit(1)  # any positive amount
            assert freed == 64 * KB
            assert h1.tier != "device" and h2.tier == "device"
            h1.close()
            h2.close()
        RmmSpark.task_done(1)


class TestSpillGetRace:
    def test_spill_while_getting_keeps_data_intact(self, framework):
        """The satellite race fix: cross-thread spill() during the owner's
        get() must serialize (or skip), never corrupt."""
        h = SpillableHandle(_tree(4096, seed=9), name="race")
        want = np.asarray(h.get()["x"]).copy()
        stop = threading.Event()
        errors = []

        def evictor():
            while not stop.is_set():
                try:
                    h.spill()
                    h.spill_host()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

        t = threading.Thread(target=evictor, daemon=True)
        t.start()
        try:
            for _ in range(300):
                got = np.asarray(h.get()["x"])
                assert (got == want).all()
        finally:
            stop.set()
            t.join(timeout=10.0)
        assert not errors, errors
        h.close()

    def test_busy_handle_is_skipped_not_deadlocked(self, framework):
        """An evictor hitting a handle whose lock is held treats it like a
        pinned one (try-lock), so no lock-order deadlock is possible."""
        h = SpillableHandle(_tree(64), name="busy")
        held = threading.Event()
        release = threading.Event()

        def holder():  # RLock is reentrant: must be held by ANOTHER thread
            h._lock.acquire()
            held.set()
            release.wait(10.0)
            h._lock.release()

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert held.wait(10.0)
        try:
            assert h.spill() == 0
            assert h.tier == "device"
        finally:
            release.set()
            t.join(timeout=10.0)
        h.spill()
        assert h.tier == "host"
        h.close()


class TestTaskContextIntegration:
    def test_exit_auto_closes_and_unregisters(self, framework, adaptor):
        with TaskContext(5) as ctx:
            SpillableHandle(_tree(64 * KB // 4), ctx=ctx)
            h2 = SpillableHandle(_tree(64 * KB // 4), ctx=ctx)
            h2.spill()
            h2.spill_host()
            assert len(framework.store) == 2
            assert len(_spill_files(framework)) == 1
        # never close()d explicitly: the context exit did it all
        assert len(framework.store) == 0
        assert _spill_files(framework) == []
        assert adaptor.total_allocated() == 0
        assert adaptor.host_total_allocated() == 0
        RmmSpark.task_done(5)

    def test_columnbatch_spillable_helper(self, framework, adaptor):
        import __graft_entry__ as ge

        with TaskContext(6) as ctx:
            batch = ge._example_batch(256)
            assert batch.device_nbytes == batch_nbytes(batch)
            h = batch.spillable(ctx)
            assert adaptor.total_allocated() == batch.device_nbytes
            h.spill()
            assert adaptor.total_allocated() == 0
            assert h.get().num_rows == 256
        RmmSpark.task_done(6)


class TestBatchNbytesDedupe:
    def test_aliased_leaves_charge_once(self):
        a = jnp.arange(1024, dtype=jnp.int32)
        assert batch_nbytes({"x": a}) == 4096
        assert batch_nbytes({"x": a, "y": a}) == 4096  # same buffer
        b = jnp.arange(1024, dtype=jnp.int32) + 1
        assert batch_nbytes({"x": a, "y": b}) == 8192

    def test_numpy_leaves_dedupe_by_identity(self):
        a = np.arange(1024, dtype=np.int32)
        assert batch_nbytes([a, a]) == 4096
        assert batch_nbytes([a, a.copy()]) == 8192


class TestSpillIOFault:
    def test_disk_write_fault_keeps_host_tier(self, framework, adaptor):
        faultinj.configure({"faults": [
            {"match": "spill_io_write", "fault": "spill_io", "count": 1}]})
        try:
            with TaskContext(7) as ctx:
                h = SpillableHandle(_tree(64 * KB // 4, seed=4), ctx=ctx)
                want = np.asarray(h.get()["x"]).copy()
                h.spill()
                assert h.tier == "host"
                h.spill_host()  # injected SpillIOError
                # graceful degradation: still host-resident, still charged
                assert h.tier == "host"
                assert adaptor.host_total_allocated() == 64 * KB
                assert _spill_files(framework) == []  # no partial files
                m = framework.metrics.snapshot()
                assert m["disk_write_failures"] == 1
                assert m["host_to_disk_count"] == 0
                h.spill_host()  # injection exhausted: now it works
                assert h.tier == "disk"
                assert (np.asarray(h.get()["x"]) == want).all()
                h.close()
            RmmSpark.task_done(7)
        finally:
            faultinj.configure({})

    def test_spill_io_rule_validates(self):
        faultinj._Rule({"match": "spill_io_*", "fault": "spill_io"})
        with pytest.raises(ValueError):
            faultinj._Rule({"fault": "bogus"})  # graftlint: disable=GL006


class TestMetricsExport:
    def test_rmm_spark_and_profiler_surfaces(self, framework, adaptor):
        with TaskContext(9) as ctx:
            h = SpillableHandle(_tree(64 * KB // 4), ctx=ctx)
            h.spill()
            h.get()
            h.close()
        RmmSpark.task_done(9)
        g = RmmSpark.spill_metrics()
        assert g["device_to_host_bytes"] == 64 * KB
        assert profiler.spill_summary() == g
        t = RmmSpark.get_and_reset_task_spill_metrics(9)
        assert t["device_to_host_bytes"] == 64 * KB
        assert t["host_to_device_bytes"] == 64 * KB
        # consume-once, like get_and_reset_num_retry
        t2 = RmmSpark.get_and_reset_task_spill_metrics(9)
        assert sum(t2.values()) == 0

    def test_zeros_without_framework(self):
        assert sum(RmmSpark.spill_metrics().values()) == 0
        assert sum(profiler.spill_summary().values()) == 0


class TestLegacySpillableDelegates:
    def test_spillable_registers_with_store(self, framework, adaptor):
        with TaskContext(11) as ctx:
            s = Spillable(_tree(64), ctx)
            assert isinstance(s, SpillableHandle)
            assert len(framework.store) == 1
            # the central store can now evict a legacy Spillable
            framework.spill_to_fit(requesting_task_id=99)
            assert s.is_spilled
            s.close()
        RmmSpark.task_done(11)


class TestEndToEndOversubscription:
    """The acceptance scenario: device arena (2M) below the combined
    working set (2 x 1.2M), two concurrent dedicated tasks, NO manual
    make_spillable — task 2's RetryOOM automatically evicts task 1's idle
    batch device→host, the 512K host arena bounces it to disk, and task 1
    reads it back — all transitions metered exactly."""

    NWORDS = 307200  # 1,228,800 bytes of int32

    def test_two_tasks_complete_via_automatic_tiered_spill(
            self, framework, adaptor):
        nbytes = self.NWORDS * 4
        ev_a_ready = threading.Event()
        ev_b_done = threading.Event()
        results = {}
        failures = []

        def task_a():
            try:
                with TaskContext(1) as ctx:
                    h = SpillableHandle(_tree(self.NWORDS, seed=1), ctx=ctx,
                                        name="task1-batch")
                    want = np.asarray(h.get()["x"]).copy()
                    ev_a_ready.set()
                    # idle while task 2 runs; blocked_section tells the
                    # native deadlock scan this thread is parked host-side
                    with ThreadStateRegistry.blocked_section():
                        if not ev_b_done.wait(60.0):
                            raise TimeoutError("task 2 never finished")
                    assert h.tier == "disk", h.tier  # evicted down both tiers
                    got = run_with_retry(lambda: np.asarray(h.get()["x"]))
                    results["a"] = (got == want).all()
            except BaseException as e:  # noqa: BLE001
                failures.append(("a", e))

        def task_b():
            try:
                if not ev_a_ready.wait(60.0):
                    raise TimeoutError("task 1 never set up")
                with TaskContext(2) as ctx:
                    def step():
                        h = SpillableHandle(_tree(self.NWORDS, seed=2),
                                            ctx=ctx, name="task2-batch")
                        out = int(np.asarray(h.get()["x"]).sum())
                        h.close()
                        return out

                    # NO make_spillable: the framework default evicts
                    # task 1's idle batch cross-task
                    results["b"] = run_with_retry(step)
                ev_b_done.set()
            except BaseException as e:  # noqa: BLE001
                failures.append(("b", e))
                ev_b_done.set()

        ta = threading.Thread(target=task_a, daemon=True)
        tb = threading.Thread(target=task_b, daemon=True)
        ta.start()
        tb.start()
        ta.join(timeout=90.0)
        tb.join(timeout=90.0)
        assert not ta.is_alive() and not tb.is_alive(), "deadlock"
        assert not failures, failures
        assert results["a"], "task 1's batch corrupted by the round trip"
        want_b = int(np.asarray(_tree(self.NWORDS, seed=2)["x"]).sum())
        assert results["b"] == want_b

        # ---- exact metric accounting across every tier transition ----
        m = framework.metrics.snapshot()
        assert m["device_to_host_bytes"] == nbytes
        assert m["device_to_host_count"] == 1
        assert m["host_to_disk_bytes"] == nbytes  # 1.2M > 512K host arena
        assert m["host_to_disk_count"] == 1
        assert m["disk_to_host_bytes"] == nbytes
        assert m["disk_to_host_count"] == 1
        assert m["host_to_device_bytes"] == nbytes
        assert m["host_to_device_count"] == 1
        assert m["disk_write_failures"] == 0
        # the spilled batch belonged to TASK 1: per-task attribution
        t1 = RmmSpark.get_and_reset_task_spill_metrics(1)
        assert t1["device_to_host_bytes"] == nbytes
        # task 2 went through the native retry ladder to get there
        assert adaptor.get_and_reset_num_retry(2) >= 1
        # nothing left behind
        assert adaptor.total_allocated() == 0
        assert adaptor.host_total_allocated() == 0
        assert len(framework.store) == 0
        assert _spill_files(framework) == []
        RmmSpark.task_done(1)
        RmmSpark.task_done(2)
