"""Python-level smoke coverage of EVERY jni_bridge dispatcher op.

The ctypes suite (test_jni_bridge.py) proves the C ABI; this one drives
``invoke`` for each registered op with representative inputs so Java-wire
-> kernel signature drift cannot hide in untested entries (two such bugs
were found by review in ops this file now covers).
"""

import base64
import json

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_jni_tpu import jni_bridge as jb
from spark_rapids_jni_tpu.columnar import types as T
from spark_rapids_jni_tpu.columnar.column import (
    Column,
    Decimal128Column,
    StringColumn,
)


def invoke(name, args=None, objs=()):
    return jb.invoke(name, json.dumps(args or {}), list(objs))


def ints(vals, kind=T.INT64):
    return Column.from_pylist(vals, kind)


def strs(vals):
    return StringColumn.from_pylist(vals)


def dec(vals, precision=20, scale=2):
    import jax.numpy as jnp

    n = len(vals)
    limbs = np.zeros((n, 2), np.uint64)
    valid = np.zeros(n, bool)
    for i, v in enumerate(vals):
        if v is None:
            continue
        valid[i] = True
        u = int(v) & ((1 << 128) - 1)
        limbs[i, 0] = u & ((1 << 64) - 1)
        limbs[i, 1] = u >> 64
    return Decimal128Column(jnp.asarray(limbs), jnp.asarray(valid),
                            T.SparkType.decimal(precision, scale))


class TestCastOps:
    def test_to_integer(self):
        out, _ = invoke("CastStrings.toInteger",
                        {"ansi": False, "strip": True, "kind": "int16"},
                        [strs(["7", "x"])])
        assert out[0].to_pylist() == [7, None]

    def test_to_float(self):
        out, _ = invoke("CastStrings.toFloat",
                        {"ansi": False, "kind": "float64"},
                        [strs(["1.5", "inf"])])
        assert out[0].to_pylist()[0] == 1.5

    def test_to_decimal(self):
        out, _ = invoke("CastStrings.toDecimal",
                        {"ansi": False, "strip": True, "precision": 5,
                         "scale": 0}, [strs(["123"])])
        assert out[0].to_pylist() == [123]

    def test_from_float(self):
        out, _ = invoke("CastStrings.fromFloat", {},
                        [ints([1], T.FLOAT64)])
        assert out[0].to_pylist() == ["1.0"]

    def test_from_float_fmt(self):
        out, _ = invoke("CastStrings.fromFloatWithFormat", {"digits": 2},
                        [Column.from_pylist([1.239], T.FLOAT64)])
        assert out[0].to_pylist() == ["1.24"]

    def test_from_decimal(self):
        out, _ = invoke("CastStrings.fromDecimal", {}, [dec([12345])])
        assert out[0].to_pylist() == ["123.45"]

    def test_with_base_roundtrip(self):
        out, _ = invoke("CastStrings.toIntegersWithBase",
                        {"base": 16, "ansi": False, "kind": "uint64"},
                        [strs(["ff"])])
        out2, _ = invoke("CastStrings.fromIntegersWithBase", {"base": 10},
                         out)
        assert out2[0].to_pylist() == ["255"]


class TestHashBloom:
    def test_hashes(self):
        for op in ("Hash.murmurHash32", "Hash.xxhash64"):
            out, _ = invoke(op, {"seed": 42}, [ints([1, 2, None])])
            assert out[0].num_rows == 3

    def test_bloom_cycle(self):
        bf, _ = invoke("BloomFilter.create", {"num_hashes": 3, "bits": 4096})
        bf2, _ = invoke("BloomFilter.put", {}, [bf[0], ints([5, 6])])
        probed, _ = invoke("BloomFilter.probe", {}, [bf2[0], ints([5, 99])])
        vals = probed[0].to_pylist()
        assert vals[0] is True
        _, meta = invoke("BloomFilter.serialize", {}, [bf2[0]])
        blob = json.loads(meta)["data"]
        back, _ = invoke("BloomFilter.deserialize", {"data": blob})
        merged, _ = invoke("BloomFilter.merge", {}, [bf2[0], back[0]])
        probed2, _ = invoke("BloomFilter.probe", {}, [merged[0], ints([5])])
        assert probed2[0].to_pylist() == [True]


class TestDecimalOps:
    @pytest.mark.parametrize("op", ["add128", "subtract128", "multiply128",
                                    "divide128", "remainder128"])
    def test_binops(self, op):
        out, _ = invoke(f"DecimalUtils.{op}", {"scale": -2},
                        [dec([10000]), dec([300])])
        assert len(out) == 2  # (overflow, result)
        assert out[0].to_pylist() == [False]

    def test_integer_divide(self):
        out, _ = invoke("DecimalUtils.integerDivide128", {},
                        [dec([10000]), dec([300])])
        assert out[1].to_pylist()[0] == 33  # 100.00 div 3.00


class TestDatetimeTz:
    def test_rebase(self):
        col = Column.from_pylist([-141714], T.SparkType(T.Kind.DATE))
        out, _ = invoke("DateTimeRebase.rebaseGregorianToJulian", {}, [col])
        back, _ = invoke("DateTimeRebase.rebaseJulianToGregorian", {}, out)
        assert back[0].to_pylist() == [-141714]

    def test_timezones(self):
        ts = Column.from_pylist([1700000000_000000],
                                T.SparkType(T.Kind.TIMESTAMP))
        out, _ = invoke("GpuTimeZoneDB.fromUtcTimestampToTimestamp",
                        {"zone": "Asia/Shanghai"}, [ts])
        back, _ = invoke("GpuTimeZoneDB.fromTimestampToUtcTimestamp",
                        {"zone": "Asia/Shanghai"}, out)
        assert back[0].to_pylist() == [1700000000_000000]
        _, meta = invoke("GpuTimeZoneDB.isSupportedTimeZone",
                         {"zone": "Asia/Shanghai"})
        assert json.loads(meta)["supported"] is True


class TestJsonUriRegex:
    def test_get_json_object(self):
        out, _ = invoke("JSONUtils.getJsonObject",
                        {"path": [["named", "a", -1]]},
                        [strs(['{"a": 1}', '{"b": 2}'])])
        assert out[0].to_pylist() == ["1", None]

    def test_from_json(self):
        out, meta = invoke("MapUtils.extractRawMapFromJsonString", {},
                           [strs(['{"x": "y"}'])])
        assert len(out) == 2
        offs = json.loads(meta)["offsets"]
        assert offs[0] == 0

    def test_parse_uri_parts(self):
        col = strs(["https://u@host.com:1/p?a=1#f"])
        for part, want in [("PROTOCOL", "https"), ("HOST", "host.com"),
                           ("QUERY", "a=1"), ("PATH", "/p")]:
            out, _ = invoke("ParseURI.parseURI", {"part": part}, [col])
            assert out[0].to_pylist() == [want], part
        out, _ = invoke("ParseURI.parseURI", {"part": "QUERY", "key": "a"},
                        [col])
        assert out[0].to_pylist() == ["1"]
        out, _ = invoke("ParseURI.parseURI", {"part": "QUERY"},
                        [col, strs(["a"])])
        assert out[0].to_pylist() == ["1"]

    def test_regex_literal_range(self):
        out, _ = invoke("RegexRewriteUtils.literalRangePattern",
                        {"literal": "a", "len": 1, "start": 48, "end": 57},
                        [strs(["a1", "ab"])])
        assert out[0].to_pylist() == [True, False]


class TestRowsZorderHistogram:
    def test_rows_roundtrip(self):
        cols = [ints([1, 2, 3]), ints([4, 5, 6], T.INT32)]
        rows, _ = invoke("RowConversion.convertToRows", {}, cols)
        back, _ = invoke(
            "RowConversion.convertFromRows",
            {"schema": [{"kind": "int64"}, {"kind": "int32"}]}, rows[:1])
        assert back[0].to_pylist() == [1, 2, 3]
        assert back[1].to_pylist() == [4, 5, 6]

    def test_rows_schema_requires_decimal_info(self):
        rows, _ = invoke("RowConversion.convertToRows", {}, [ints([1])])
        with pytest.raises(ValueError):
            invoke("RowConversion.convertFromRows",
                   {"schema": [{"kind": "decimal"}]}, rows[:1])

    def test_zorder(self):
        out, _ = invoke("ZOrder.interleaveBits", {},
                        [ints([1, 2], T.INT32), ints([3, 4], T.INT32)])
        assert out[0].num_rows == 2
        out, _ = invoke("ZOrder.hilbertIndex", {"num_bits": 8},
                        [ints([1, 2], T.INT32), ints([3, 4], T.INT32)])
        assert out[0].num_rows == 2

    def test_histogram(self):
        vals, _ = invoke("Histogram.createHistogramIfValid", {},
                         [ints([1, 2, 3]), ints([1, 1, 2])])
        assert len(vals) == 2
        out, _ = invoke("Histogram.percentileFromHistogram",
                        {"percentages": [0.5]}, vals)
        assert out[0].num_rows == 1


class TestErrors:
    def test_unknown_op(self):
        with pytest.raises(NotImplementedError):
            invoke("Nope.nope")

    def test_classify(self):
        from spark_rapids_jni_tpu.mem.rmm_spark import (
            CpuRetryOOM,
            RetryOOM,
            SplitAndRetryOOM,
        )
        from spark_rapids_jni_tpu.ops.cast_string import CastException

        assert jb.classify_exception(CastException("x", 0)) == jb.ERR_CAST
        assert jb.classify_exception(RetryOOM()) == jb.ERR_RETRY_OOM
        assert jb.classify_exception(
            SplitAndRetryOOM()) == jb.ERR_SPLIT_OOM
        assert jb.classify_exception(CpuRetryOOM()) == jb.ERR_CPU_RETRY_OOM
        assert jb.classify_exception(ValueError()) == jb.ERR_GENERIC


def test_multiply128_interim_cast_toggle():
    """Both rounding modes reachable through the wire (reference
    DecimalUtils.java:70 interimCast)."""
    a = dec([10**37], precision=38, scale=2)
    b = dec([10**3], precision=38, scale=2)
    with_bug, _ = invoke("DecimalUtils.multiply128",
                         {"scale": 2, "interim_cast": True}, [a, b])
    without, _ = invoke("DecimalUtils.multiply128",
                        {"scale": 2, "interim_cast": False}, [a, b])
    assert with_bug[0].num_rows == 1 and without[0].num_rows == 1
