"""Histogram percentile vs the direct expanded-array definition."""

import numpy as np
import pytest

from spark_rapids_jni_tpu.columnar import types as T
from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.ops.histogram import (
    create_histogram_if_valid,
    percentile_from_histogram,
)


def oracle_percentile(pairs, pct):
    """pairs: [(value, freq)] with None values dropped; Spark percentile
    definition: sort, expand by frequency, interpolate at (N-1)*pct."""
    expanded = []
    for v, f in sorted((p for p in pairs if p[0] is not None)):
        expanded.extend([v] * f)
    if not expanded:
        return None
    pos = (len(expanded) - 1) * pct
    lo, hi = int(np.floor(pos)), int(np.ceil(pos))
    if lo == hi:
        return float(expanded[lo])
    return (hi - pos) * expanded[lo] + (pos - lo) * expanded[hi]


def build(hists, dtype=T.INT64):
    """hists: list of [(value|None, freq)] -> (values, freqs, offsets)."""
    values, freqs, offsets = [], [], [0]
    for h in hists:
        for v, f in h:
            values.append(v)
            freqs.append(f)
        offsets.append(len(values))
    v, f = create_histogram_if_valid(
        Column.from_pylist(values, dtype),
        Column.from_pylist(freqs, T.INT64),
    )
    return v, f, np.array(offsets, np.int32)


class TestPercentileFromHistogram:
    def test_basic_median(self):
        v, f, off = build([[(1, 2), (2, 1), (3, 1)]])
        out, valid = percentile_from_histogram(v, f, off, [0.5])
        # expanded: 1 1 2 3 -> median (pos 1.5) = 1.5
        assert bool(valid[0])
        assert float(out[0, 0]) == pytest.approx(1.5)

    def test_multiple_histograms_and_pcts(self, rng):
        hists = []
        for _ in range(20):
            k = int(rng.integers(0, 6))
            h = [
                (
                    None if rng.random() < 0.15 else int(rng.integers(-50, 50)),
                    int(rng.integers(1, 5)),
                )
                for _ in range(k)
            ]
            hists.append(h)
        pcts = [0.0, 0.1, 0.5, 0.9, 1.0]
        v, f, off = build(hists)
        out, valid = percentile_from_histogram(v, f, off, pcts)
        for h_i, h in enumerate(hists):
            for p_i, p in enumerate(pcts):
                exp = oracle_percentile(h, p)
                if exp is None:
                    assert not bool(valid[h_i])
                else:
                    assert bool(valid[h_i])
                    assert float(out[h_i, p_i]) == pytest.approx(exp), (h, p)

    def test_zero_freq_dropped(self):
        v, f, off = build([[(1, 0), (5, 2), (9, 2)]])
        out, valid = percentile_from_histogram(v, f, off, [0.0, 1.0])
        assert float(out[0, 0]) == 5.0 and float(out[0, 1]) == 9.0

    def test_negative_freq_raises(self):
        with pytest.raises(ValueError):
            create_histogram_if_valid(
                Column.from_pylist([1], T.INT64),
                Column.from_pylist([-1], T.INT64),
            )

    def test_double_values(self, rng):
        hists = [[(float(rng.normal()), int(rng.integers(1, 4))) for _ in range(5)]]
        v, f, off = build(hists, T.FLOAT64)
        out, valid = percentile_from_histogram(v, f, off, [0.25, 0.75])
        for p_i, p in enumerate([0.25, 0.75]):
            assert float(out[0, p_i]) == pytest.approx(oracle_percentile(hists[0], p))
