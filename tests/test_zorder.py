"""interleave_bits / hilbert_index vs python oracles + anchor values."""

import numpy as np

from spark_rapids_jni_tpu.columnar import types as T
from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.ops.zorder import hilbert_index, interleave_bits

# ---------------------------------------------------------------------------
# oracles
# ---------------------------------------------------------------------------


def oracle_interleave(rows, width):
    """rows: list of per-column int values (nulls already 0); width bytes."""
    C = len(rows)
    nbits = width * 8
    out_bits = []
    for k in range(nbits * C):
        col = k % C
        bit = k // C
        v = rows[col] & ((1 << nbits) - 1)
        out_bits.append((v >> (nbits - 1 - bit)) & 1)
    out = bytearray()
    for j in range(width * C):
        byte = 0
        for b in range(8):
            byte = (byte << 1) | out_bits[8 * j + b]
        out.append(byte)
    return bytes(out)


def oracle_hilbert(point, bits):
    """Skilling transpose -> index (davidmoten/hilbert-curve semantics)."""
    n = len(point)
    x = [p & ((1 << bits) - 1) for p in point]
    M = 1 << (bits - 1)
    q = M
    while q > 1:
        p = q - 1
        for i in range(n):
            if x[i] & q:
                x[0] ^= p
            else:
                t = (x[0] ^ x[i]) & p
                x[0] ^= t
                x[i] ^= t
        q >>= 1
    for i in range(1, n):
        x[i] ^= x[i - 1]
    t = 0
    q = M
    while q > 1:
        if x[n - 1] & q:
            t ^= q - 1
        q >>= 1
    x = [xi ^ t for xi in x]
    b = 0
    for i in range(bits):
        for j in range(n):
            b = (b << 1) | ((x[j] >> (bits - 1 - i)) & 1)
    return b


def ints(vals, dtype=T.INT32):
    return Column.from_pylist(vals, dtype)


class TestInterleaveBits:
    def test_single_int32(self):
        vals = [0, 1, -1, 0x12345678, None]
        raw = interleave_bits([ints(vals)])
        chars = np.asarray(raw.chars)
        for i, v in enumerate(vals):
            exp = oracle_interleave([v if v is not None else 0], 4)
            assert bytes(chars[i, :4]) == exp, (i, v)

    def test_two_int32(self, rng):
        a = rng.integers(-(2**31), 2**31, 16).tolist()
        b = rng.integers(-(2**31), 2**31, 16).tolist()
        raw = interleave_bits([ints(a), ints(b)])
        chars = np.asarray(raw.chars)
        for i in range(16):
            assert bytes(chars[i, :8]) == oracle_interleave([a[i], b[i]], 4)

    def test_known_two_col(self):
        # 0xFF000000 x 0x00000000 -> alternating 10101010 for the top 2 bytes
        raw = interleave_bits([ints([-16777216]), ints([0])])
        chars = np.asarray(raw.chars)[0, :8]
        assert bytes(chars) == bytes([0xAA, 0xAA, 0, 0, 0, 0, 0, 0])

    def test_three_int16(self, rng):
        a = rng.integers(-(2**15), 2**15, 8).tolist()
        b = rng.integers(-(2**15), 2**15, 8).tolist()
        c = rng.integers(-(2**15), 2**15, 8).tolist()
        raw = interleave_bits(
            [ints(a, T.INT16), ints(b, T.INT16), ints(c, T.INT16)]
        )
        chars = np.asarray(raw.chars)
        for i in range(8):
            assert bytes(chars[i, :6]) == oracle_interleave([a[i], b[i], c[i]], 2)

    def test_int64(self, rng):
        a = rng.integers(-(2**62), 2**62, 8).tolist()
        raw = interleave_bits([ints(a, T.INT64)])
        chars = np.asarray(raw.chars)
        for i in range(8):
            assert bytes(chars[i, :8]) == oracle_interleave([a[i]], 8)


class TestHilbertIndex:
    def test_first_order_2d(self):
        # 1-bit 2-D curve: (0,0)->0 (0,1)->1 (1,1)->2 (1,0)->3
        a = ints([0, 0, 1, 1])
        b = ints([0, 1, 1, 0])
        out = hilbert_index(1, [a, b]).to_pylist()
        assert out == [0, 1, 2, 3]

    def test_matches_oracle_2d(self, rng):
        a = rng.integers(0, 1024, 32).tolist()
        b = rng.integers(0, 1024, 32).tolist()
        out = hilbert_index(10, [ints(a), ints(b)]).to_pylist()
        for i in range(32):
            assert out[i] == oracle_hilbert([a[i], b[i]], 10), i

    def test_matches_oracle_3d_nulls(self, rng):
        a = [None, 4, 1, 0, 1023, 512]
        b = [1, 8, None, 0, 1023, 512]
        c = [2, 0, 4, 0, 1023, None]
        out = hilbert_index(10, [ints(a), ints(b), ints(c)]).to_pylist()
        z = lambda v: 0 if v is None else v
        for i in range(6):
            assert out[i] == oracle_hilbert([z(a[i]), z(b[i]), z(c[i])], 10), i

    def test_single_dim(self):
        vals = [1, 2, 3, 4, 5]
        out = hilbert_index(3, [ints(vals)]).to_pylist()
        for i, v in enumerate(vals):
            assert out[i] == oracle_hilbert([v], 3)
