"""End-to-end test of the C-ABI bridge behind the Java/JNI surface.

Loads jni/libsrj_bridge.so with ctypes (the same entry points the JNI glue
calls — jni/src/jni_glue.cpp) and drives columns across the host boundary
exactly the way the Java classes do: build -> invoke -> export.  Because
the test process is already Python, srj_init attaches to the hosted
interpreter instead of embedding a fresh one — same code path minus
Py_InitializeEx.
"""

import ctypes
import json
import os
import subprocess

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
JNI_DIR = os.path.join(ROOT, "jni")
LIB = os.path.join(JNI_DIR, "libsrj_bridge.so")


class SrjHostColumn(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_char * 16),
        ("n", ctypes.c_int64),
        ("data", ctypes.POINTER(ctypes.c_uint8)),
        ("data_len", ctypes.c_int64),
        ("validity", ctypes.POINTER(ctypes.c_uint8)),
        ("offsets", ctypes.POINTER(ctypes.c_int32)),
        ("precision", ctypes.c_int),
        ("scale", ctypes.c_int),
    ]


@pytest.fixture(scope="module")
def lib():
    if not os.path.exists(LIB):
        rc = subprocess.run(
            ["make", "-C", JNI_DIR, "libsrj_bridge.so"], capture_output=True
        )
        if rc.returncode != 0 or not os.path.exists(LIB):
            pytest.skip("cannot build libsrj_bridge.so")
    L = ctypes.CDLL(LIB)
    L.srj_init.restype = ctypes.c_int
    L.srj_init.argtypes = [ctypes.c_char_p]
    L.srj_column_from_host.restype = ctypes.c_int64
    L.srj_column_from_host.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    L.srj_string_column_from_host.restype = ctypes.c_int64
    L.srj_string_column_from_host.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_char_p, ctypes.c_int64]
    L.srj_column_to_host.restype = ctypes.c_int
    L.srj_column_to_host.argtypes = [ctypes.c_int64,
                                     ctypes.POINTER(SrjHostColumn)]
    L.srj_invoke.restype = ctypes.c_int
    L.srj_invoke.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    L.srj_invoke_json.restype = ctypes.c_char_p
    L.srj_last_error.restype = ctypes.c_char_p
    L.srj_last_error_code.restype = ctypes.c_int
    L.srj_num_rows.restype = ctypes.c_int64
    L.srj_num_rows.argtypes = [ctypes.c_int64]
    L.srj_release.argtypes = [ctypes.c_int64]
    assert L.srj_init(ROOT.encode()) == 0, "srj_init failed"
    return L


def make_string_col(lib, values):
    chars = b"".join((v or "").encode() for v in values)
    offs = [0]
    for v in values:
        offs.append(offs[-1] + len((v or "").encode()))
    validity = bytes(1 if v is not None else 0 for v in values)
    arr = (ctypes.c_int32 * len(offs))(*offs)
    h = lib.srj_string_column_from_host(
        chars, len(chars), arr, validity, len(values))
    assert h != 0, lib.srj_last_error().decode()
    return h


def invoke(lib, op, args, handles, max_out=4):
    in_arr = (ctypes.c_int64 * max(len(handles), 1))(*(handles or [0]))
    out_arr = (ctypes.c_int64 * max_out)()
    n = lib.srj_invoke(op.encode(), json.dumps(args).encode(), in_arr,
                       len(handles), out_arr, max_out)
    return n, list(out_arr[:max(n, 0)])


def export(lib, h):
    hc = SrjHostColumn()
    rc = lib.srj_column_to_host(h, ctypes.byref(hc))
    assert rc == 0, lib.srj_last_error().decode()
    n = hc.n
    data = bytes(ctypes.cast(
        hc.data, ctypes.POINTER(ctypes.c_uint8 * hc.data_len)).contents) \
        if hc.data_len else b""
    valid = bytes(ctypes.cast(
        hc.validity, ctypes.POINTER(ctypes.c_uint8 * n)).contents) \
        if n else b""
    offs = None
    if hc.offsets:
        offs = list(ctypes.cast(
            hc.offsets, ctypes.POINTER(ctypes.c_int32 * (n + 1))).contents)
    kind = hc.kind.decode()
    lib.srj_free_host_column(ctypes.byref(hc))
    return kind, n, data, valid, offs


def test_int_column_roundtrip(lib):
    vals = np.array([1, -2, 3_000_000_000, -4], dtype=np.int64)
    h = lib.srj_column_from_host(
        b"int64", 4, vals.ctypes.data, vals.nbytes, bytes([1, 1, 0, 1]),
        0, 0)
    assert h != 0, lib.srj_last_error().decode()
    assert lib.srj_num_rows(h) == 4
    kind, n, data, valid, offs = export(lib, h)
    assert kind == "int64" and n == 4 and offs is None
    assert list(np.frombuffer(data, np.int64)) == list(vals)
    assert list(valid) == [1, 1, 0, 1]
    lib.srj_release(h)


def test_cast_to_integer_via_invoke(lib):
    h = make_string_col(lib, ["123", " 45 ", "junk", None])
    n, outs = invoke(lib, "CastStrings.toInteger",
                     {"ansi": False, "strip": True, "kind": "int32"}, [h])
    assert n == 1, lib.srj_last_error().decode()
    kind, cnt, data, valid, _ = export(lib, outs[0])
    assert kind == "int32"
    assert list(np.frombuffer(data, np.int32)[:2]) == [123, 45]
    assert list(valid) == [1, 1, 0, 0]
    lib.srj_release(h)
    lib.srj_release(outs[0])


def test_murmur_hash_via_invoke(lib):
    vals = np.array([0, 100, -100], dtype=np.int64)
    h = lib.srj_column_from_host(b"int64", 3, vals.ctypes.data, vals.nbytes,
                                 None, 0, 0)
    n, outs = invoke(lib, "Hash.murmurHash32", {"seed": 42}, [h])
    assert n == 1
    _, _, data, _, _ = export(lib, outs[0])
    got = list(np.frombuffer(data, np.int32))
    # golden values from reference HashTest.java int64 murmur vectors
    from spark_rapids_jni_tpu.columnar import types as T
    from spark_rapids_jni_tpu.columnar.column import Column
    from spark_rapids_jni_tpu.ops.hashing import murmur_hash3_32
    import jax.numpy as jnp

    ref = murmur_hash3_32([Column(
        jnp.asarray(vals), jnp.ones(3, jnp.bool_), T.INT64)])
    assert got == list(np.asarray(ref.data))
    lib.srj_release(h)
    lib.srj_release(outs[0])


def test_cast_exception_error_code(lib):
    h = make_string_col(lib, ["12", "oops"])
    n, _ = invoke(lib, "CastStrings.toInteger",
                  {"ansi": True, "strip": True, "kind": "int32"}, [h])
    assert n == -1
    assert lib.srj_last_error_code() == 2  # SRJ_ERR_CAST
    assert "oops" in lib.srj_last_error().decode()
    lib.srj_release(h)


def test_bloom_filter_lifecycle(lib):
    vals = np.array([10, 20, 30], dtype=np.int64)
    h = lib.srj_column_from_host(b"int64", 3, vals.ctypes.data, vals.nbytes,
                                 None, 0, 0)
    n, bf = invoke(lib, "BloomFilter.create",
                   {"num_hashes": 3, "bits": 1 << 12}, [])
    assert n == 1
    n, bf2 = invoke(lib, "BloomFilter.put", {}, [bf[0], h])
    assert n == 1
    probe_vals = np.array([10, 99], dtype=np.int64)
    hp = lib.srj_column_from_host(b"int64", 2, probe_vals.ctypes.data,
                                  probe_vals.nbytes, None, 0, 0)
    n, res = invoke(lib, "BloomFilter.probe", {}, [bf2[0], hp])
    assert n == 1
    _, _, data, _, _ = export(lib, res[0])
    hits = list(np.frombuffer(data, np.bool_))
    assert hits[0] is np.True_ or hits[0]
    # serialize round-trips through base64 metadata
    n, _ = invoke(lib, "BloomFilter.serialize", {}, [bf2[0]])
    assert n == 0
    meta = json.loads(lib.srj_invoke_json().decode())
    assert len(meta["data"]) > 0
    for hh in [h, hp, bf[0], bf2[0], res[0]]:
        lib.srj_release(hh)


def test_unknown_op_is_error(lib):
    n, _ = invoke(lib, "No.suchOp", {}, [])
    assert n == -1
    assert "unknown bridge op" in lib.srj_last_error().decode()


def test_get_json_object_wire_path(lib):
    docs = ['{"a": {"b": [10, 20]}}', '{"a": {"b": [7]}}', '{"x": 1}']
    h = make_string_col(lib, docs)
    # wire triples as JSONUtils.java PathInstructionJni emits them
    n, outs = invoke(lib, "JSONUtils.getJsonObject",
                     {"path": [["named", "a", -1], ["named", "b", -1],
                               ["index", "", 1]]}, [h])
    assert n == 1, lib.srj_last_error().decode()
    kind, cnt, data, valid, offs = export(lib, outs[0])
    vals = [data[offs[i]:offs[i + 1]].decode() if valid[i] else None
            for i in range(cnt)]
    assert vals == ["20", None, None]
    lib.srj_release(h)
    lib.srj_release(outs[0])
