"""Out-of-core ShuffleService tests: skew planning, lossless multi-round
drain, spillable buffers under a capped arena, strict/counted OOB ids,
transport fault injection, and the spillable join build table."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu import config, faultinj, profiler
from spark_rapids_jni_tpu.columnar import types as T
from spark_rapids_jni_tpu.columnar.column import Column, ColumnBatch
from spark_rapids_jni_tpu.parallel import data_mesh, shard_batch
from spark_rapids_jni_tpu.shuffle import (
    ShuffleError,
    ShuffleRegistry,
    ShuffleService,
    get_registry,
    plan_rounds,
)

P8 = 8


def _int_batch(vals):
    a = np.asarray(vals, np.int64)
    return ColumnBatch({
        "v": Column(jnp.asarray(a), jnp.ones((len(a),), jnp.bool_), T.INT64)
    })


def _row_sharded(arr, mesh):
    return jax.device_put(
        jnp.asarray(arr),
        jax.sharding.NamedSharding(mesh,
                                   jax.sharding.PartitionSpec("data")))


def _delivered(res):
    occ = np.asarray(jax.device_get(res.occupancy))
    out = np.asarray(jax.device_get(res.batch["v"].data))
    return out, occ


@pytest.fixture
def small_buckets():
    """Capacity bucket small enough that modest tests go multi-round."""
    old = config.get("shuffle_capacity_bucket")
    config.set("shuffle_capacity_bucket", 16)
    yield
    config.set("shuffle_capacity_bucket", old)


# ---------------------------------------------------------------------------
# planner units
# ---------------------------------------------------------------------------

class TestPlanRounds:
    def test_single_round_when_it_fits(self):
        plan = plan_rounds([[10, 5], [3, 2]], round_rows=64, bucket=16,
                           max_rounds=8)
        assert plan.rounds == 1
        assert plan.capacity == 16  # bucket-rounded max, not round_rows
        assert plan.max_bucket == 10 and plan.total_rows == 20
        assert plan.lossless

    def test_multi_round_drains_the_max_bucket(self):
        c = np.zeros((4, 4), np.int64)
        c[2, 1] = 1000
        plan = plan_rounds(c, round_rows=100, bucket=16, max_rounds=64)
        assert plan.capacity == 112  # 100 rounded up to the bucket
        assert plan.rounds == 9  # ceil(1000 / 112)
        assert plan.rounds * plan.capacity >= 1000 and plan.lossless

    def test_max_rounds_caps_by_raising_capacity(self):
        c = [[1000]]
        plan = plan_rounds(c, round_rows=10, bucket=1, max_rounds=4)
        assert plan.rounds <= 4
        assert plan.lossless  # never by dropping rows

    def test_zero_counts(self):
        plan = plan_rounds(np.zeros((8, 8), np.int64))
        assert plan.rounds == 1 and plan.total_rows == 0
        assert plan.skew_ratio == 0.0

    def test_skew_ratio_reads_all_to_one_as_p(self):
        c = np.zeros((P8, P8), np.int64)
        c[:, 0] = 64  # every sender's full batch goes to destination 0
        plan = plan_rounds(c, round_rows=1 << 16)
        assert plan.skew_ratio == pytest.approx(float(P8))

    def test_invalid_args_raise(self):
        with pytest.raises(ValueError):
            plan_rounds([[1]], round_rows=0)
        with pytest.raises(ValueError):
            plan_rounds([[1]], bucket=-1)


# ---------------------------------------------------------------------------
# adversarial skew through the service (lossless or loud)
# ---------------------------------------------------------------------------

class TestServiceAdversarialSkew:
    def test_all_rows_to_one_destination(self, eight_devices, small_buckets):
        mesh = data_mesh(P8)
        n = P8 * 64
        vals = np.arange(n, dtype=np.int64)
        batch = shard_batch(_int_batch(vals), mesh)
        pid = _row_sharded(np.zeros(n, np.int32), mesh)

        reg = ShuffleRegistry()
        res = ShuffleService(mesh, registry=reg).exchange(
            batch, pid=pid, round_rows=16)
        assert res.rounds >= 2  # skew forced a multi-round drain
        assert res.rows_moved == n
        assert res.skew_ratio == pytest.approx(float(P8))
        out, occ = _delivered(res)
        assert sorted(out[occ].tolist()) == vals.tolist()
        # every live row sits on device 0's shard
        shard_rows = out.shape[0] // P8
        assert not occ[shard_rows:].any()
        assert reg.metrics.snapshot()["dropped_rows"] == 0

    def test_zipf_pids_with_empty_partitions(self, eight_devices,
                                             small_buckets):
        mesh = data_mesh(P8)
        n = P8 * 128
        rng = np.random.default_rng(3)
        vals = rng.integers(0, 1 << 40, n).astype(np.int64)
        # zipf mass on low partitions, folded into [0, 5): partitions
        # 5..7 receive NOTHING — empty destinations must stay lossless
        pid_np = (np.minimum(rng.zipf(1.5, n), 1 << 20) % 5).astype(np.int32)
        batch = shard_batch(_int_batch(vals), mesh)
        pid = _row_sharded(pid_np, mesh)

        res = ShuffleService(mesh, registry=ShuffleRegistry()).exchange(
            batch, pid=pid, round_rows=32)
        assert res.rows_moved == n
        out, occ = _delivered(res)
        assert sorted(out[occ].tolist()) == sorted(vals.tolist())
        shard_rows = out.shape[0] // P8
        for d in range(P8):
            sl = slice(d * shard_rows, (d + 1) * shard_rows)
            want = sorted(vals[pid_np == d].tolist())
            assert sorted(out[sl][occ[sl]].tolist()) == want
        assert not occ[5 * shard_rows:].any()  # empty destinations

    def test_oob_pids_counted_when_not_strict(self, eight_devices):
        mesh = data_mesh(P8)
        n = P8 * 16
        vals = np.arange(n, dtype=np.int64)
        pid_np = (vals % P8).astype(np.int32)
        pid_np[::8] = 99
        pid_np[1::8] = -3
        n_oob = int(((pid_np < 0) | (pid_np > P8)).sum())
        batch = shard_batch(_int_batch(vals), mesh)
        pid = _row_sharded(pid_np, mesh)

        reg = ShuffleRegistry()
        res = ShuffleService(mesh, registry=reg).exchange(
            batch, pid=pid, strict=False)
        assert res.oob_rows == n_oob
        assert res.rows_moved == n - n_oob
        out, occ = _delivered(res)
        in_range = (pid_np >= 0) & (pid_np < P8)
        assert sorted(out[occ].tolist()) == sorted(vals[in_range].tolist())
        snap = reg.metrics.snapshot()
        assert snap["oob_rows"] == n_oob and snap["dropped_rows"] == 0

    def test_oob_pids_raise_when_strict(self, eight_devices):
        mesh = data_mesh(P8)
        n = P8 * 8
        batch = shard_batch(_int_batch(np.arange(n)), mesh)
        pid = _row_sharded(np.full(n, 99, np.int32), mesh)
        with pytest.raises(ShuffleError, match="out-of-range"):
            ShuffleService(mesh, registry=ShuffleRegistry()).exchange(
                batch, pid=pid, strict=True)


# ---------------------------------------------------------------------------
# the legacy data plane under the same adversarial shapes
# ---------------------------------------------------------------------------

class TestLegacyPlaneAdversarial:
    def test_plan_capacity_sizes_all_to_one_losslessly(self, eight_devices):
        from spark_rapids_jni_tpu.parallel import exchange
        from spark_rapids_jni_tpu.parallel.shuffle import plan_capacity

        mesh = data_mesh(P8)
        spec = jax.sharding.PartitionSpec("data")
        n = P8 * 24
        vals = np.arange(n, dtype=np.int64)
        batch = shard_batch(_int_batch(vals), mesh)
        pid = _row_sharded(np.zeros(n, np.int32), mesh)

        @jax.jit
        @jax.shard_map(mesh=mesh, in_specs=(spec,),
                       out_specs=spec, check_vma=False)
        def plan(p):
            return plan_capacity(p, "data", P8)[None]

        cap = int(np.asarray(jax.device_get(plan(pid)))[0])
        assert cap == 24  # every sender's whole shard targets one bucket

        @jax.jit
        @jax.shard_map(mesh=mesh, in_specs=(spec, spec),
                       out_specs=(spec, spec, spec), check_vma=False)
        def run(b, p):
            out, occ, dropped = exchange(b, p, "data", P8, capacity=cap)
            return out, occ, dropped[None]

        out, occ, dropped = run(batch, pid)
        assert int(np.asarray(jax.device_get(dropped)).sum()) == 0
        occ = np.asarray(jax.device_get(occ))
        got = np.asarray(jax.device_get(out["v"].data))
        assert sorted(got[occ].tolist()) == vals.tolist()

    def test_exchange_hierarchical_counts_oob_in_dropped(self,
                                                         eight_devices):
        from spark_rapids_jni_tpu.parallel import exchange_hierarchical
        from spark_rapids_jni_tpu.parallel.distributed import (
            hierarchical_mesh,
        )

        mesh = hierarchical_mesh(2, 4)
        spec = jax.sharding.PartitionSpec(("dcn", "ici"))
        n = P8 * 8
        vals = np.arange(n, dtype=np.int64)
        pid_np = (vals % P8).astype(np.int32)
        pid_np[::16] = 99
        pid_np[1::16] = -2
        n_oob = int(((pid_np < 0) | (pid_np > P8)).sum())
        batch = jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, jax.sharding.NamedSharding(mesh, spec)),
            _int_batch(vals))
        pid = jax.device_put(
            jnp.asarray(pid_np), jax.sharding.NamedSharding(mesh, spec))

        @jax.jit
        @jax.shard_map(mesh=mesh, in_specs=(spec, spec),
                       out_specs=(spec, spec, spec), check_vma=False)
        def run(b, p):
            out, occ, dropped = exchange_hierarchical(
                b, p, "dcn", "ici", 2, 4)
            return out, occ, dropped[None]

        out, occ, dropped = run(batch, pid)
        # OOB ids surface as COUNTED drops, not as silent padding
        assert int(np.asarray(jax.device_get(dropped)).sum()) == n_oob
        occ = np.asarray(jax.device_get(occ))
        got = np.asarray(jax.device_get(out["v"].data))
        in_range = (pid_np >= 0) & (pid_np < P8)
        assert sorted(got[occ].tolist()) == sorted(vals[in_range].tolist())


# ---------------------------------------------------------------------------
# out-of-core acceptance: eager buffers exceed the arena, shuffle completes
# ---------------------------------------------------------------------------

class TestOutOfCore:
    def test_skewed_exchange_spills_and_stays_lossless(self, eight_devices,
                                                       tmp_path):
        from spark_rapids_jni_tpu.mem import RmmSpark, TaskContext
        from spark_rapids_jni_tpu.mem import spill as spill_mod

        old_bucket = config.get("shuffle_capacity_bucket")
        config.set("shuffle_capacity_bucket", 256)
        get_registry().reset()
        mesh = data_mesh(P8)
        n = P8 * 4096
        rng = np.random.default_rng(7)
        vals = rng.integers(0, 1 << 40, n).astype(np.int64)
        batch = shard_batch(_int_batch(vals), mesh)
        pid = _row_sharded(np.zeros(n, np.int32), mesh)

        spill_mod.install(spill_dir=str(tmp_path))
        RmmSpark.set_event_handler(1 << 20, poll_ms=10.0)  # 1 MB arena
        try:
            with TaskContext(77) as ctx:
                res = ShuffleService(mesh).exchange(
                    batch, pid=pid, ctx=ctx, round_rows=512)
                out, occ = _delivered(res)
            RmmSpark.task_done(77)
        finally:
            RmmSpark.clear_event_handler()
            spill_mod.shutdown()
            config.set("shuffle_capacity_bucket", old_bucket)

        # lossless: the received multiset equals the sent multiset
        assert res.rows_moved == n
        assert sorted(out[occ].tolist()) == sorted(vals.tolist())
        summary = profiler.shuffle_summary()
        assert summary["rounds"] >= 2
        assert summary["spilled_bytes"] > 0  # the arena forced eviction
        assert summary["dropped_rows"] == 0
        assert RmmSpark.shuffle_metrics() == summary


# ---------------------------------------------------------------------------
# streaming morsel-driven exchange (bit-identical to the materialized path)
# ---------------------------------------------------------------------------

class TestStreamingExchange:
    """``exchange_stream`` must deliver rows BIT-IDENTICALLY to
    ``exchange`` over the same rows — same content, same per-shard
    order — while draining earlier rounds before the stream ends and
    tracing its drain program exactly once."""

    def _kv_batch(self, keys, vals):
        k = np.asarray(keys, np.int64)
        v = np.asarray(vals, np.int64)
        ones = jnp.ones((len(k),), jnp.bool_)
        return ColumnBatch({
            "k": Column(jnp.asarray(k), ones, T.INT64),
            "v": Column(jnp.asarray(v), ones, T.INT64)})

    @staticmethod
    def _rows(res):
        occ = np.asarray(jax.device_get(res.occupancy))
        k = np.asarray(jax.device_get(res.batch["k"].data))
        v = np.asarray(jax.device_get(res.batch["v"].data))
        return k, v, occ

    def _assert_bit_identical(self, mat, stream):
        """Delivered (occupancy-masked) rows equal per destination
        shard, in order.  Shapes may differ only when both paths fit in
        one round (the materialized capacity shrinks to its bucket) —
        the masked sequences still line up row for row."""
        mk, mv, mo = self._rows(mat)
        sk, sv, so = self._rows(stream)
        ra, rb = mk.shape[0] // P8, sk.shape[0] // P8
        for d in range(P8):
            a = slice(d * ra, (d + 1) * ra)
            b = slice(d * rb, (d + 1) * rb)
            assert np.array_equal(mk[a][mo[a]], sk[b][so[b]])
            assert np.array_equal(mv[a][mo[a]], sv[b][so[b]])

    def _run_both(self, keys, vals, round_rows, morsel_rows,
                  extra_morsels=None):
        from spark_rapids_jni_tpu.shuffle import MorselSource

        mesh = data_mesh(P8)
        batch = shard_batch(self._kv_batch(keys, vals), mesh)
        svc = ShuffleService(mesh, registry=ShuffleRegistry())
        mat = svc.exchange(batch, key_names=["k"], round_rows=round_rows)
        src = MorselSource.from_batch(batch, mesh, morsel_rows=morsel_rows)
        morsels = list(src)
        if extra_morsels:
            for at, m in extra_morsels:
                morsels.insert(at, m)
        res = svc.exchange_stream(morsels, key_names=["k"],
                                  round_rows=round_rows)
        self._assert_bit_identical(mat, res)
        return mat, res

    def test_uniform_multiround_overlaps_decode(self, eight_devices,
                                                small_buckets):
        n = P8 * 512
        rng = np.random.default_rng(5)
        keys = rng.integers(0, 1 << 20, n)
        mat, res = self._run_both(keys, np.arange(n), round_rows=16,
                                  morsel_rows=64)
        assert res.streamed and res.morsels == 8
        assert res.rows_moved == n and mat.rows_moved == n
        assert res.rounds >= 2
        # >= 2 rounds were IN FLIGHT: drained while later morsels were
        # still decoding, not after end-of-stream
        assert res.rounds_overlapped >= 2
        assert res.rounds == mat.rounds and res.capacity == mat.capacity

    def test_all_to_one_skew(self, eight_devices, small_buckets):
        # one constant key: every row hashes to a single destination,
        # the worst skew the planner can see
        n = P8 * 256
        mat, res = self._run_both(np.full(n, 7), np.arange(n),
                                  round_rows=64, morsel_rows=64)
        assert res.rows_moved == n
        assert res.rounds >= 2
        assert res.skew_ratio == pytest.approx(mat.skew_ratio)

    def test_zipf_keys_empty_partitions_and_empty_morsel(
            self, eight_devices, small_buckets):
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = data_mesh(P8)
        n = P8 * 128
        M = 32
        rng = np.random.default_rng(11)
        # zipf mass folded onto 5 distinct keys: several destinations
        # receive nothing at all
        keys = (np.minimum(rng.zipf(1.5, n), 1 << 20) % 5).astype(np.int64)
        sh = NamedSharding(mesh, PartitionSpec("data"))
        zeros = jax.device_put(jnp.zeros((P8 * M,), jnp.int64), sh)
        ones = jax.device_put(jnp.ones((P8 * M,), jnp.bool_), sh)
        empty = (ColumnBatch({"k": Column(zeros, ones, T.INT64),
                              "v": Column(zeros, ones, T.INT64)}),
                 jax.device_put(jnp.zeros((P8 * M,), jnp.bool_), sh))
        _, res = self._run_both(
            keys, np.arange(n), round_rows=32, morsel_rows=M,
            # an all-invalid morsel mid-stream contributes zero rows
            # everywhere and must not disturb accounting or order
            extra_morsels=[(2, lambda: empty)])
        assert res.rows_moved == n
        assert res.morsels == 5  # the empty one still counts as mapped

    def test_drain_program_traces_once(self, eight_devices, small_buckets):
        from spark_rapids_jni_tpu.shuffle.service import \
            _STREAM_DRAIN_TRACES

        n = P8 * 256
        rng = np.random.default_rng(13)
        self._run_both(rng.integers(0, 99, n), np.arange(n),
                       round_rows=16, morsel_rows=64)
        before = _STREAM_DRAIN_TRACES[0]
        # a second stream at the same capacity (fresh data, many
        # morsels, several rounds) must reuse every compiled program
        self._run_both(rng.integers(0, 99, n), np.arange(n) * 3,
                       round_rows=16, morsel_rows=64)
        assert _STREAM_DRAIN_TRACES[0] == before

    def test_out_of_core_stream_spills_and_stays_lossless(
            self, eight_devices, tmp_path):
        from spark_rapids_jni_tpu.mem import RmmSpark, TaskContext
        from spark_rapids_jni_tpu.mem import spill as spill_mod
        from spark_rapids_jni_tpu.shuffle import MorselSource

        old_bucket = config.get("shuffle_capacity_bucket")
        config.set("shuffle_capacity_bucket", 256)
        get_registry().reset()
        mesh = data_mesh(P8)
        n = P8 * 4096
        rng = np.random.default_rng(17)
        batch = shard_batch(
            self._kv_batch(np.full(n, 3), rng.integers(0, 1 << 40, n)),
            mesh)
        spill_mod.install(spill_dir=str(tmp_path))
        RmmSpark.set_event_handler(1 << 20, poll_ms=10.0)  # 1 MB arena
        try:
            with TaskContext(78) as ctx:
                src = MorselSource.from_batch(batch, mesh,
                                              morsel_rows=1024)
                res = ShuffleService(mesh).exchange_stream(
                    src, key_names=["k"], ctx=ctx, round_rows=512)
                k, _, occ = self._rows(res)
            RmmSpark.task_done(78)
        finally:
            RmmSpark.clear_event_handler()
            spill_mod.shutdown()
            config.set("shuffle_capacity_bucket", old_bucket)

        assert res.rows_moved == n
        assert (k[occ] == 3).all() and int(occ.sum()) == n
        summary = profiler.shuffle_summary()
        assert summary["rounds"] >= 2
        assert summary["spilled_bytes"] > 0  # the arena forced demotion
        assert summary["dropped_rows"] == 0


# ---------------------------------------------------------------------------
# transport fault injection (kind "shuffle_io")
# ---------------------------------------------------------------------------

class TestShuffleIOFaults:
    def _exchange(self, reg):
        mesh = data_mesh(P8)
        n = P8 * 8
        vals = np.arange(n, dtype=np.int64)
        batch = shard_batch(_int_batch(vals), mesh)
        pid = _row_sharded((vals % P8).astype(np.int32), mesh)
        res = ShuffleService(mesh, registry=reg).exchange(batch, pid=pid)
        return vals, res

    def test_round_is_redriven_after_injected_fault(self, eight_devices):
        reg = ShuffleRegistry()
        faultinj.configure({"faults": [{"match": "shuffle_io_round",
                                        "count": 1,
                                        "fault": "shuffle_io"}]})
        try:
            vals, res = self._exchange(reg)
        finally:
            faultinj.configure({})
        assert res.rows_moved == len(vals)
        out, occ = _delivered(res)
        assert sorted(out[occ].tolist()) == vals.tolist()
        assert reg.metrics.snapshot()["io_failures"] == 1

    def test_persistent_fault_raises_after_bounded_retries(self,
                                                           eight_devices):
        from spark_rapids_jni_tpu.shuffle.service import _IO_RETRIES

        reg = ShuffleRegistry()
        faultinj.configure({"faults": [{"match": "shuffle_io_round",
                                        "fault": "shuffle_io"}]})
        try:
            with pytest.raises(faultinj.ShuffleIOError):
                self._exchange(reg)
        finally:
            faultinj.configure({})
        assert reg.metrics.snapshot()["io_failures"] == _IO_RETRIES + 1


# ---------------------------------------------------------------------------
# service-backed distributed operators
# ---------------------------------------------------------------------------

class TestServiceBackedOperators:
    def test_group_by_routes_through_the_service(self, eight_devices):
        from spark_rapids_jni_tpu.parallel import distributed_group_by
        from spark_rapids_jni_tpu.parallel.distributed import collect_groups
        from spark_rapids_jni_tpu.relational import AggSpec

        mesh = data_mesh(P8)
        n = P8 * 32
        rng = np.random.default_rng(9)
        k = rng.integers(0, 6, n).astype(np.int64)
        v = rng.integers(-100, 100, n).astype(np.int64)
        batch = shard_batch(ColumnBatch({
            "k": Column(jnp.asarray(k), jnp.ones((n,), jnp.bool_), T.INT64),
            "v": Column(jnp.asarray(v), jnp.ones((n,), jnp.bool_), T.INT64),
        }), mesh)
        before = get_registry().metrics.snapshot()["shuffles"]
        res, ng, dropped = distributed_group_by(
            batch, ["k"], [AggSpec("sum", "v", "s")], mesh)
        assert int(np.asarray(jax.device_get(dropped)).sum()) == 0
        assert get_registry().metrics.snapshot()["shuffles"] == before + 1
        got = collect_groups(res, ng)
        want = {key: int(v[k == key].sum()) for key in np.unique(k)}
        assert dict(zip(got["k"], got["s"])) == want


# ---------------------------------------------------------------------------
# spillable join build tables (drop on eviction, rebuild on read-back)
# ---------------------------------------------------------------------------

class TestSpillableBuildTable:
    def _sides(self):
        rng = np.random.default_rng(1)
        def mk(keys, vals):
            a = np.asarray(keys, np.int64)
            b = np.asarray(vals, np.int64)
            return ColumnBatch({
                "k": Column(jnp.asarray(a), jnp.ones((len(a),), jnp.bool_),
                            T.INT64),
                "v": Column(jnp.asarray(b), jnp.ones((len(b),), jnp.bool_),
                            T.INT64),
            })
        left = mk(rng.integers(0, 40, 160), np.arange(160))
        right = mk(rng.integers(0, 40, 64), np.arange(64) + 1000)
        return left, right

    @staticmethod
    def _rows(batch, count):
        m = int(count)
        return sorted(zip(
            np.asarray(batch["k"].data)[:m].tolist(),
            np.asarray(batch["v"].data)[:m].tolist(),
            np.asarray(batch["v_r"].data)[:m].tolist()))

    def test_eviction_drops_and_get_rebuilds(self, tmp_path):
        from spark_rapids_jni_tpu.mem import spill as spill_mod
        from spark_rapids_jni_tpu.relational import (
            hash_join,
            spillable_build_table,
        )

        left, right = self._sides()
        ref, nref = hash_join(left, right, ["k"], ["k"], "inner",
                              capacity=1024)
        fw = spill_mod.install(spill_dir=str(tmp_path))
        try:
            bt = spillable_build_table(right, ["k"])
            got, ngot = hash_join(left, right, ["k"], ["k"], "inner",
                                  capacity=1024, prebuilt=bt)
            assert self._rows(got, ngot) == self._rows(ref, nref)
            assert bt.tier == "device" and bt.rebuilds == 0

            fw.spill_to_fit()  # arena pressure: the build table is dropped
            assert bt.tier == "dropped"

            got2, n2 = hash_join(left, right, ["k"], ["k"], "inner",
                                 capacity=1024, prebuilt=bt)
            assert self._rows(got2, n2) == self._rows(ref, nref)
            assert bt.rebuilds == 1
            bt.close()
            assert bt.tier == "closed"
        finally:
            spill_mod.shutdown()

    def test_prebuilt_full_join_matches(self):
        from spark_rapids_jni_tpu.relational import (
            hash_join,
            spillable_build_table,
        )

        left, right = self._sides()
        ref, nref = hash_join(left, right, ["k"], ["k"], "full",
                              capacity=1024)
        bt = spillable_build_table(right, ["k"])
        got, ngot = hash_join(left, right, ["k"], ["k"], "full",
                              capacity=1024, prebuilt=bt)
        bt.close()
        assert int(nref) == int(ngot)

    def test_guard_rails(self):
        from spark_rapids_jni_tpu.relational import (
            hash_join,
            spillable_build_table,
        )

        left, right = self._sides()
        empty = ColumnBatch({
            "k": Column(jnp.zeros((0,), jnp.int64),
                        jnp.zeros((0,), jnp.bool_), T.INT64)})
        with pytest.raises(ValueError, match="empty build side"):
            spillable_build_table(empty, ["k"])
        bt = spillable_build_table(right, ["k"])
        with pytest.raises(ValueError, match="right"):
            hash_join(left, right, ["k"], ["k"], "right", prebuilt=bt)
        bt.close()
