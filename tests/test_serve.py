"""Multi-tenant serving runtime tests.

The robustness core of the serving PR: admission control over the
unified arena, cross-tenant deadlock breaking (the classic all-blocked
scan AND the stall breaker for cycles starving behind a running
tenant), kill-safe cancellation at every lifecycle point, bounded
timeout re-admission, and the double-buffered shuffle drain lane.
"""

import threading
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import config, faultinj
from spark_rapids_jni_tpu.mem import RetryOOM, RmmSpark, SplitAndRetryOOM
from spark_rapids_jni_tpu.serve import (
    QueryCancelled,
    QueryTimeout,
    ServeRuntime,
)

MB = 1 << 20


@pytest.fixture
def arena():
    adaptor = RmmSpark.set_event_handler(10 * MB, poll_ms=20.0)
    yield adaptor
    RmmSpark.clear_event_handler()


@pytest.fixture
def runtime(arena):
    # fast stall breaker so cross-tenant cycle tests stay sub-second
    config.set("serve_stall_break_ms", 200.0)
    rt = ServeRuntime()
    yield rt
    rt.shutdown()
    config.reset("serve_stall_break_ms")


def _poll(pred, timeout=5.0, interval=0.005):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _deadlocking_tenant(hold, want, state, lock, barrier):
    """Charge ``hold``, rendezvous, then fight over ``want`` more.

    Exactly one tenant — the deadlock victim — rolls back (releases its
    hold and returns "victim"); any other escalated tenant follows the
    standard retry contract (block until ready, retry) and survives.
    """

    def q(ctx, sess):
        held = ctx.charge(hold)
        barrier.wait(timeout=10)
        for _ in range(50):
            try:
                n = ctx.charge(want)
                ctx.release(n)
                ctx.release(held)
                return "survivor"
            except (RetryOOM, SplitAndRetryOOM):
                with lock:
                    first = state["victim"] is None
                    if first:
                        state["victim"] = sess.tenant
                if first:
                    ctx.release(held)
                    return "victim"
                try:
                    RmmSpark.block_thread_until_ready()
                except (RetryOOM, SplitAndRetryOOM):
                    pass
        raise AssertionError("no progress after 50 retries")

    return q


class TestLifecycle:
    def test_happy_path(self, arena, runtime):
        s = runtime.submit(lambda ctx: "ok", est_bytes=1 * MB,
                           tenant="alpha")
        assert s.result(timeout=10) == "ok"
        assert s.status == "done"
        assert s.attempts == 1
        assert s.tenant == "alpha"
        assert s.granted_bytes == 1 * MB  # fit without splitting
        assert arena.total_allocated() == 0

    def test_reservation_splits_under_pressure(self, arena, runtime):
        gate = threading.Event()

        def holder(ctx):
            n = ctx.charge(6 * MB)
            gate.wait(15)
            ctx.release(n)
            return "held"

        h = runtime.submit(holder)
        assert _poll(lambda: arena.total_allocated() >= 6 * MB)
        # 8 MB cannot fit beside the 6 MB resident tenant: the admission
        # probe walks the ladder (park -> stall-break -> split) and is
        # granted the halved footprint that does fit
        s = runtime.submit(lambda ctx: "fit", est_bytes=8 * MB)
        assert s.result(timeout=20) == "fit"
        assert s.granted_bytes == 4 * MB
        gate.set()
        assert h.result(timeout=10) == "held"
        assert arena.total_allocated() == 0


class TestCrossTenantDeadlock:
    def test_two_tenant_bufn_cycle_broken_by_watchdog(self, arena, runtime):
        """Satellite #3: A<->B both hold 5 MB of the 10 MB arena and both
        demand 4 MB more — a cycle no tenant can resolve.  The watchdog
        hands the victim RetryOOM/SplitAndRetryOOM; it rolls back, the
        survivor completes, and both arenas drain."""
        state = {"victim": None}
        lock = threading.Lock()
        barrier = threading.Barrier(2)
        q = _deadlocking_tenant(5 * MB, 4 * MB, state, lock, barrier)
        a = runtime.submit(q, tenant="A")
        b = runtime.submit(q, tenant="B")
        outcomes = sorted([a.result(timeout=15), b.result(timeout=15)])
        assert outcomes == ["survivor", "victim"]
        assert state["victim"] in ("A", "B")
        assert a.status == "done" and b.status == "done"
        assert runtime.shutdown()
        assert arena.total_allocated() == 0
        assert arena.host_total_allocated() == 0

    def test_cycle_behind_running_tenant_needs_stall_breaker(
            self, arena, runtime):
        """The classic scan only fires when EVERY task thread is
        blocked: with tenant C happily running, an A<->B cycle starves
        until the stall breaker rolls the victim back."""
        stop = threading.Event()

        def busy(ctx):
            while not stop.is_set():
                n = ctx.charge(1024)
                ctx.release(n)
                time.sleep(0.005)
            return "busy-done"

        state = {"victim": None}
        lock = threading.Lock()
        barrier = threading.Barrier(2)
        q = _deadlocking_tenant(4 * MB, 4 * MB, state, lock, barrier)
        c = runtime.submit(busy, tenant="C")
        assert _poll(lambda: c.status == "running")
        a = runtime.submit(q, tenant="A")
        b = runtime.submit(q, tenant="B")
        outcomes = sorted([a.result(timeout=15), b.result(timeout=15)])
        assert outcomes == ["survivor", "victim"]
        assert state["victim"] is not None
        stop.set()
        assert c.result(timeout=10) == "busy-done"
        assert runtime.shutdown()
        assert arena.total_allocated() == 0


class TestKillSafety:
    def test_cancel_unparks_tenant_blocked_in_arena(self, arena, runtime):
        """A tenant parked in native BLOCKED (its demand can never fit,
        and a running peer keeps the global scan idle) must unwind
        promptly on cancel — the task_done kill path wakes it with
        REMOVE_THROW."""
        stop = threading.Event()

        def busy(ctx):
            while not stop.is_set():
                n = ctx.charge(1024)
                ctx.release(n)
                time.sleep(0.005)
            return "busy-done"

        c = runtime.submit(busy)
        assert _poll(lambda: c.status == "running")

        def hog(ctx):
            ctx.charge(100 * MB)  # can never fit: parks forever
            return "unreachable"

        h = runtime.submit(hog)
        assert _poll(lambda: h.status == "running")
        time.sleep(0.1)  # let the charge park in the native arena
        t0 = time.monotonic()
        runtime.cancel(h)
        with pytest.raises(QueryCancelled):
            h.result(timeout=5)
        assert time.monotonic() - t0 < 2.0  # woken, not watchdog-timed-out
        assert h.status == "cancelled"
        stop.set()
        assert c.result(timeout=10) == "busy-done"
        assert runtime.shutdown()
        assert arena.total_allocated() == 0

    def test_cancel_while_queued_for_admission(self, arena):
        rt = ServeRuntime(max_concurrent=1)
        try:
            gate = threading.Event()
            a = rt.submit(lambda ctx: (gate.wait(15), "held")[1])
            assert _poll(lambda: a.status == "running")
            b = rt.submit(lambda ctx: "never")
            assert _poll(lambda: b.status == "queued", timeout=1.0)
            rt.cancel(b)
            with pytest.raises(QueryCancelled):
                b.result(timeout=5)
            assert b.status == "cancelled"
            gate.set()
            assert a.result(timeout=10) == "held"
        finally:
            assert rt.shutdown()

    def test_admission_queue_timeout(self, arena):
        rt = ServeRuntime(max_concurrent=1)
        config.set("serve_admit_timeout_s", 0.3)
        try:
            gate = threading.Event()
            a = rt.submit(lambda ctx: (gate.wait(15), "held")[1])
            assert _poll(lambda: a.status == "running")
            b = rt.submit(lambda ctx: "never")
            with pytest.raises(QueryTimeout):
                b.result(timeout=5)
            assert b.status == "timeout"
            gate.set()
            assert a.result(timeout=10) == "held"
        finally:
            config.reset("serve_admit_timeout_s")
            assert rt.shutdown()

    def test_plan_cache_pin_released_on_kill(self, arena, runtime):
        from spark_rapids_jni_tpu.plan.cache import get_plan_cache

        cache = get_plan_cache()
        key = "serve-test-pinned-plan"

        def q(ctx, sess):
            sess.pin_plan(key)
            while True:
                sess._check_cancelled()
                time.sleep(0.01)

        s = runtime.submit(q)
        assert _poll(lambda: cache.pinned(key))
        runtime.cancel(s)
        with pytest.raises(QueryCancelled):
            s.result(timeout=5)
        assert not cache.pinned(key)  # the kill-safe unwind dropped it

    def test_injected_task_cancel_is_a_kill(self, arena, runtime):
        faultinj.configure({"faults": [{"match": "serve_step", "count": 1,
                                        "fault": "task_cancel"}]})
        try:
            s = runtime.submit(lambda ctx: "nope")
            with pytest.raises(faultinj.TaskCancelled):
                s.result(timeout=10)
            assert s.status == "cancelled"
            assert arena.total_allocated() == 0
        finally:
            faultinj.configure({})


class TestTimeoutReadmission:
    def test_timeout_kills_then_readmits_with_backoff(self, arena, runtime):
        def q(ctx, sess):
            # attempts 1 and 2 out-sleep the deadline; attempt 3 returns
            end = time.monotonic() + (10.0 if sess.attempts <= 2 else 0.0)
            while time.monotonic() < end:
                sess._check_cancelled()
                time.sleep(0.02)
            return "eventually"

        s = runtime.submit(q, timeout_s=0.25)
        assert s.result(timeout=20) == "eventually"
        assert s.status == "done"
        assert s.attempts == 3  # initial + serve_max_readmissions
        assert arena.total_allocated() == 0

    def test_timeout_budget_exhausts_to_query_timeout(self, arena, runtime):
        def q(ctx, sess):
            end = time.monotonic() + 10.0
            while time.monotonic() < end:
                sess._check_cancelled()
                time.sleep(0.02)
            return "never"

        s = runtime.submit(q, timeout_s=0.2)
        with pytest.raises(QueryTimeout):
            s.result(timeout=20)
        assert s.status == "timeout"
        assert s.attempts == 3
        assert arena.total_allocated() == 0


class TestDrainLaneOverlap:
    def test_exchange_rounds_pipeline_through_lane(self, eight_devices,
                                                   arena):
        """With the runtime's drain lane installed, a multi-round
        exchange drains round k on the lane thread while the tenant's
        worker runs round k+1 — and stays bit-identical to the solo
        (lane-less) exchange."""
        import jax
        import jax.numpy as jnp

        from spark_rapids_jni_tpu.columnar import types as T
        from spark_rapids_jni_tpu.columnar.column import Column, ColumnBatch
        from spark_rapids_jni_tpu.parallel import data_mesh, shard_batch
        from spark_rapids_jni_tpu.shuffle import ShuffleRegistry, ShuffleService

        P = 8
        n = P * 64
        mesh = data_mesh(P)
        vals = np.arange(n, dtype=np.int64)
        batch = shard_batch(ColumnBatch({
            "v": Column(jnp.asarray(vals), jnp.ones((n,), jnp.bool_),
                        T.INT64)}), mesh)
        # all rows to one destination: the worst skew, forcing rounds >= 2
        pid = jax.device_put(
            jnp.zeros((n,), jnp.int32),
            jax.sharding.NamedSharding(mesh,
                                       jax.sharding.PartitionSpec("data")))

        def delivered(res):
            return (np.asarray(jax.device_get(res.batch["v"].data)),
                    np.asarray(jax.device_get(res.occupancy)))

        old_bucket = config.get("shuffle_capacity_bucket")
        config.set("shuffle_capacity_bucket", 16)
        try:
            solo = ShuffleService(mesh, registry=ShuffleRegistry()).exchange(
                batch, pid=pid, round_rows=16)
            solo_v, solo_occ = delivered(solo)
            assert solo.rounds >= 2
            assert solo.rounds_overlapped == 0  # no lane installed yet

            rt = ServeRuntime()
            try:
                def q(ctx):
                    res = ShuffleService(
                        mesh, registry=ShuffleRegistry()).exchange(
                            batch, pid=pid, round_rows=16, ctx=ctx)
                    return delivered(res) + (res.rounds,
                                             res.rounds_overlapped)

                s = rt.submit(q, tenant="shuffler")
                v, occ, rounds, overlapped = s.result(timeout=120)
                assert rounds == solo.rounds
                assert overlapped >= 1  # the double-buffered drain ran
                # bit-identical to the solo run
                assert np.array_equal(v, solo_v)
                assert np.array_equal(occ, solo_occ)
            finally:
                assert rt.shutdown()
            assert arena.total_allocated() == 0
        finally:
            config.set("shuffle_capacity_bucket", old_bucket)


class TestPriorityAdmission:
    def test_higher_priority_overtakes_queue(self, arena):
        """Two tenants queued behind a full runtime are granted in
        (priority, arrival) order, not FIFO: the later, higher-priority
        submission runs first."""
        rt = ServeRuntime(max_concurrent=1)
        try:
            gate = threading.Event()
            order = []
            hold = rt.submit(lambda ctx: (gate.wait(15), "held")[1])
            assert _poll(lambda: hold.status == "running")
            lo = rt.submit(lambda ctx: order.append("lo"), priority=0)
            assert _poll(lambda: rt._slots.waiting() == 1, timeout=2.0)
            hi = rt.submit(lambda ctx: order.append("hi"), priority=5)
            assert _poll(lambda: rt._slots.waiting() == 2, timeout=2.0)
            gate.set()
            hi.result(timeout=10)
            lo.result(timeout=10)
            assert order == ["hi", "lo"]
        finally:
            assert rt.shutdown()

    def test_eviction_rank_prefers_low_priority(self, arena):
        """While a session runs, its spill-store eviction rank is
        dominated by its SLA class: a higher-priority tenant's handles
        outrank (evict later than) a lower-priority one's."""
        from spark_rapids_jni_tpu.mem import spill as spill_mod

        fw = spill_mod.install()
        rt = ServeRuntime()
        try:
            ranks = {}

            def q(tag):
                def body(ctx, sess):
                    ranks[tag] = fw.store.task_priority(sess.task_id)
                    return tag
                return body

            rt.submit(q("lo"), priority=0).result(timeout=10)
            rt.submit(q("hi"), priority=3).result(timeout=10)
            # class dominates: 3e6 minus any admission sequence beats 0e6
            assert ranks["hi"] > ranks["lo"]
            assert ranks["hi"] >= 3e6 - 1e6 / 2
        finally:
            assert rt.shutdown()
            spill_mod.shutdown()


class TestShutdownIdempotence:
    def test_second_call_returns_first_result(self, arena):
        rt = ServeRuntime()
        assert rt.submit(lambda ctx: "x").result(timeout=10) == "x"
        first = rt.shutdown()
        second = rt.shutdown()
        assert first is True and second is True

    def test_racing_shutdowns_agree(self, arena):
        rt = ServeRuntime()
        rt.submit(lambda ctx: "x").result(timeout=10)
        results = []
        threads = [threading.Thread(
            target=lambda: results.append(rt.shutdown()))
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        assert results == [True] * 4

    def test_submit_after_shutdown_raises(self, arena):
        from spark_rapids_jni_tpu.serve import ServeError

        rt = ServeRuntime()
        rt.shutdown()
        with pytest.raises(ServeError):
            rt.submit(lambda ctx: "late").result(timeout=1)


class TestReadmissionBackoff:
    def test_backoff_actually_waits(self, arena):
        """The re-admission ladder really sleeps serve_backoff_ms
        (doubling): with 200ms base and two readmissions the second
        attempt cannot start before ~200ms after the first kill."""
        config.set("serve_backoff_ms", 200.0)
        rt = ServeRuntime()
        try:
            stamps = []

            def q(ctx, sess):
                stamps.append(time.monotonic())
                end = time.monotonic() + (
                    10.0 if sess.attempts == 1 else 0.0)
                while time.monotonic() < end:
                    sess._check_cancelled()
                    time.sleep(0.01)
                return "done"

            s = rt.submit(q, timeout_s=0.15)
            assert s.result(timeout=20) == "done"
            assert len(stamps) == 2
            # attempt 2 started >= backoff after attempt 1 STARTED
            # (timeout fired ~0.15s in, then the 0.2s ladder wait)
            assert stamps[1] - stamps[0] >= 0.15 + 0.2 - 0.02
        finally:
            assert rt.shutdown()
            config.reset("serve_backoff_ms")

    def test_cancel_during_backoff_unwinds_immediately(self, arena):
        """A cancel landing while the session sleeps in the backoff
        ladder must not wait the ladder out: with a 5s base the session
        unwinds in well under a second."""
        config.set("serve_backoff_ms", 5000.0)
        rt = ServeRuntime()
        try:
            killed = threading.Event()

            def q(ctx, sess):
                killed.set()
                end = time.monotonic() + 10.0
                while time.monotonic() < end:
                    sess._check_cancelled()
                    time.sleep(0.01)
                return "never"

            s = rt.submit(q, timeout_s=0.1)
            assert killed.wait(10)
            # let the timeout fire and the backoff sleep begin
            assert _poll(lambda: s.attempts >= 1 and killed.is_set())
            time.sleep(0.3)
            t0 = time.monotonic()
            rt.cancel(s)
            with pytest.raises((QueryCancelled, QueryTimeout)):
                s.result(timeout=10)
            assert time.monotonic() - t0 < 2.0  # not the 5s ladder
        finally:
            assert rt.shutdown()
            config.reset("serve_backoff_ms")
