"""literal_range_pattern vs reference RegexRewriteUtilsTest vectors + oracle."""

import re

from spark_rapids_jni_tpu.columnar.column import StringColumn
from spark_rapids_jni_tpu.ops.regex_rewrite import literal_range_pattern


def oracle(s, literal, d, start, end):
    """Direct python recheck over characters."""
    if s is None:
        return None
    chars = list(s)
    m = len(literal)
    lit = list(literal)
    for i in range(len(chars) - m - d + 1):
        if chars[i : i + m] == lit and all(
            start <= ord(c) <= end for c in chars[i + m : i + m + d]
        ):
            return True
    return False


class TestLiteralRangePattern:
    def test_reference_vectors_ascii(self):
        vals = ["abc123", "aabc123", "aabc12", "abc1232", "aabc1232"]
        col = StringColumn.from_pylist(vals)
        got = literal_range_pattern(col, "abc", 3, 48, 57).to_pylist()
        assert got == [True, True, False, True, True]

    def test_reference_vectors_chinese(self):
        vals = ["数据砖块", "火花-急流英伟达", "英伟达Nvidia", "火花-急流"]
        col = StringColumn.from_pylist(vals)
        got = literal_range_pattern(col, "英", 2, 19968, 40869).to_pylist()
        assert got == [False, True, True, False]

    def test_nulls_and_empty(self):
        col = StringColumn.from_pylist(["abc12", None, ""])
        got = literal_range_pattern(col, "abc", 2, 48, 57).to_pylist()
        assert got == [True, None, False]

    def test_random_oracle(self, rng):
        alphabet = "ab1x"
        vals = [
            "".join(rng.choice(list(alphabet), size=rng.integers(0, 12)))
            for _ in range(100)
        ]
        col = StringColumn.from_pylist(vals, max_len=16)
        got = literal_range_pattern(col, "ab", 2, 48, 57).to_pylist()
        for g, s in zip(got, vals):
            assert g == oracle(s, "ab", 2, 48, 57), s
