"""float/double -> string vs Java-format oracle built on shortest-repr.

Shortest round-trip digit sequences are unique (both Ryu and Python/numpy's
repr produce them), so the oracle derives Java's output from python repr
digits re-formatted under Java's plain/scientific rules.
"""

import math
from decimal import Decimal

import numpy as np
import pytest

from spark_rapids_jni_tpu.columnar import types as T
from spark_rapids_jni_tpu.columnar.column import Column
from spark_rapids_jni_tpu.ops.float_to_string import float_to_string


def java_format(digits: str, E: int, neg: bool) -> str:
    sign = "-" if neg else ""
    if -3 <= E < 7:
        if E >= 0:
            ip = digits[: E + 1].ljust(E + 1, "0")
            frac = digits[E + 1 :] or "0"
            return f"{sign}{ip}.{frac}"
        return f"{sign}0." + "0" * (-E - 1) + digits
    frac = digits[1:] or "0"
    return f"{sign}{digits[0]}.{frac}E{E}"


def shortest_digits(s: str):
    d = Decimal(s)
    _, digits, exp = d.as_tuple()
    ds = "".join(map(str, digits))
    while len(ds) > 1 and ds.endswith("0"):
        ds = ds[:-1]
        exp += 1
    return ds, exp + len(ds) - 1


def oracle_double(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    if v == 0:
        return "-0.0" if math.copysign(1, v) < 0 else "0.0"
    ds, E = shortest_digits(repr(abs(v)))
    return java_format(ds, E, v < 0)


def oracle_float(v: np.float32) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "Infinity" if f > 0 else "-Infinity"
    if f == 0:
        return "-0.0" if math.copysign(1, f) < 0 else "0.0"
    s = np.format_float_scientific(abs(v), unique=True, trim="-")
    ds, E = shortest_digits(s.replace("e", "E"))
    return java_format(ds, E, f < 0)


class TestDoubleToString:
    def test_goldens(self):
        vals = [
            0.0, -0.0, 1.0, -1.0, 3.14, 0.001, 0.0001, 1e7, 9999999.0,
            1e-323, 1.7976931348623157e308, 123.456, 1 / 3,
            float("nan"), float("inf"), float("-inf"), 2.0, 1e16,
        ]
        col = Column.from_pylist(vals, T.FLOAT64)
        got = float_to_string(col).to_pylist()
        for g, v in zip(got, vals):
            assert g == oracle_double(v), (v, g, oracle_double(v))

    def test_random_bits(self, rng):
        bits = rng.integers(0, 2**64, 500, dtype=np.uint64)
        vals = bits.view(np.float64)
        col = Column(
            __import__("jax.numpy", fromlist=["asarray"]).asarray(vals),
            __import__("jax.numpy", fromlist=["ones"]).ones(500, bool),
            T.FLOAT64,
        )
        got = float_to_string(col).to_pylist()
        for g, v in zip(got, vals.tolist()):
            assert g == oracle_double(v), (v, g)

    def test_round_trip(self, rng):
        vals = (rng.normal(size=100) * 10.0 ** rng.integers(-300, 300, 100)).tolist()
        col = Column.from_pylist(vals, T.FLOAT64)
        got = float_to_string(col).to_pylist()
        for g, v in zip(got, vals):
            s = g.replace("E", "e")
            assert float(s) == v, (v, g)

    def test_nulls(self):
        col = Column.from_pylist([1.5, None], T.FLOAT64)
        assert float_to_string(col).to_pylist() == ["1.5", None]


class TestFloatToString:
    def test_goldens(self):
        vals = [0.0, 1.0, -1.5, 3.14, 0.001, 1e7, 1e-4, 1e38, 1e-45,
                float("nan"), float("inf")]
        f32 = [np.float32(v) for v in vals]
        col = Column.from_pylist([float(v) for v in f32], T.FLOAT32)
        got = float_to_string(col).to_pylist()
        for g, v in zip(got, f32):
            assert g == oracle_float(v), (float(v), g, oracle_float(v))

    def test_random_bits(self, rng):
        bits = rng.integers(0, 2**32, 500, dtype=np.uint32)
        vals = bits.view(np.float32)
        import jax.numpy as jnp

        col = Column(jnp.asarray(vals), jnp.ones(500, bool), T.FLOAT32)
        got = float_to_string(col).to_pylist()
        for g, v in zip(got, vals):
            assert g == oracle_float(v), (float(v), g)
