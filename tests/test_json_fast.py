"""Bit-parallel get_json_object fast path (ops/json_fast.py).

Contract under test (module docstring): rows the fast engine keeps must
match the scan machine byte-for-byte; everything it cannot prove it
handles must raise the per-row fallback flag (never a wrong answer).
Float formatting is compared fast-vs-serial, not vs the host oracle: both
engines share string_to_float, whose digit-limited parse can be one ulp
off the ideal (a pre-existing, engine-independent property).

Compile budget: every distinct (path, shape) pair compiles the fast
engine (and, in the hybrid, the scan machine), so the corpus is shared
across cases and the path list is kept short.
"""

import numpy as np
import pytest

from json_oracle import get_json_object as oracle
from spark_rapids_jni_tpu import config
from spark_rapids_jni_tpu.columnar.column import StringColumn
from spark_rapids_jni_tpu.ops.get_json_object import get_json_object, parse_path
from spark_rapids_jni_tpu.ops.json_fast import fast_path

CLEAN_DOCS = [
    '{"owner":"amy","store":{"fruit":[{"weight":8,"type":"apple"},'
    '{"weight":9,"type":"pear"}],"basket":[1,2,3]}}',
    '{"a": 1}',
    '{"a": -0}',
    '{"a": true, "b": false, "c": null}',
    '{"a": [10, 20, 30]}',
    '{"a": {"b": {"c": "deep"}}}',
    '[1, 2, 3]',
    '"just a string"',
    '42',
    '  {"a" : "spaced"}  trailing junk',
    '{"a": "x", "a": "y"}',
    '{"miss": 1}',
    '{"a": []}',
    '{"a": [1]}',
    '{"": 5, "a": ""}',
    '{"b":[[1,2],[3,4]]}',
    'null',
    '',
    '   ',
]

MALFORMED_DOCS = [
    '{"a": 01}',
    '{"a": 1,}',
    '{"a" 1}',
    '{"a": [1:2]}',
    '{"a": "x" "b": "y"}',
    '{"a": tru}',
    '{"a": nullx}',
    '{"a": 1.}',
    '{"a": .5}',
    '{"a": 1e}',
    '{"a": --1}',
    '{"a": 1.2.3}',
    '{"a": 1e2e3}',
    '{]',
]

DIRTY_DOCS = [  # valid but outside the fast-path accept list
    '{"a": "esc\\nape"}',
    "{'single': 1}",
    '{"a\\u0062c": 1}',
]

PATHS = ["$.a", "$.owner", "$.a[1]", "$[0]", "$", "$.a.b", "$.b[1][0]"]


def _pt(path):
    return tuple(parse_path(path))


def _run_fast(docs, path, pad=8):
    col = StringColumn.from_pylist(docs, pad_to_multiple=pad)
    out_c, out_l, ok, fb = map(
        np.asarray,
        fast_path(col.chars, col.lengths, col.validity, _pt(path),
                  col.max_len + 8))
    res = []
    for i in range(len(docs)):
        if fb[i]:
            res.append("<FB>")
        elif not ok[i]:
            res.append(None)
        else:
            res.append(bytes(out_c[i, :out_l[i]]).decode("utf-8", "replace"))
    return res


class TestFastEngineOracleParity:
    """Rows the fast engine keeps must equal the oracle; rows it rejects
    must raise fallback (checked per class of input)."""

    @pytest.mark.parametrize("path", PATHS)
    def test_clean_and_malformed(self, path):
        docs = CLEAN_DOCS + MALFORMED_DOCS
        got = _run_fast(docs, path)
        n_handled = 0
        for d, g in zip(docs, got):
            if g == "<FB>":
                continue
            n_handled += 1
            assert g == oracle(d, path), (path, d)
        # the clean corpus must be predominantly fast-handled — the
        # engine exists to keep clean analytics batches off the scan
        assert n_handled >= len(CLEAN_DOCS) // 2, (path, n_handled)

    def test_dirty_docs_always_fall_back(self):
        got = _run_fast(DIRTY_DOCS, "$.a")
        assert got == ["<FB>"] * len(DIRTY_DOCS)

    def test_null_semantics_asymmetry(self):
        # a null VALUE matched by a named step is NULL; a null ELEMENT
        # matched by an index step prints "null" (reference case 4 vs 9)
        docs = ['{"a": null}', '[null, 2]']
        assert _run_fast(docs, "$.a")[0] is None
        assert _run_fast(docs, "$[0]")[1] == "null"

    def test_deep_nesting_falls_back(self):
        doc = "[" * 20 + "1" + "]" * 20
        assert _run_fast([doc], "$[0]")[0] == "<FB>"

    def test_float_container_falls_back_int_container_kept(self):
        docs = ['{"a": {"x": 1.5}}', '{"a": {"x": 15}}', '{"a": [-0]}']
        got = _run_fast(docs, "$.a")
        assert got[0] == "<FB>"          # float inside a container copy
        assert got[1] == '{"x":15}'      # int container compacts fast
        assert got[2] == "<FB>"          # "-0" inside a container copy

    def test_float_scalar_matches_serial(self):
        docs = ['{"a": 1.5}', '{"a": 1.5e2}', '{"a": 0.25}', '{"a": 1e309}',
                '{"a": 2}', '{"a": -0.0}']
        fast = _run_fast(docs, "$.a")
        assert "<FB>" not in fast
        col = StringColumn.from_pylist(docs, pad_to_multiple=8)
        config.set("json_fast_path", False)
        try:
            serial = get_json_object(col, "$.a").to_pylist()
        finally:
            config.reset("json_fast_path")
        assert fast == serial


class TestHybridRouting:
    def test_mixed_batch_falls_back_whole_batch_correctly(self):
        # one dirty row forces the scan machine; results must equal the
        # scan machine everywhere (cond's serial branch)
        docs = CLEAN_DOCS + DIRTY_DOCS
        col = StringColumn.from_pylist(docs, pad_to_multiple=8)
        config.set("json_fast_path", True)
        try:
            hybrid = get_json_object(col, "$.a").to_pylist()
        finally:
            config.reset("json_fast_path")
        config.set("json_fast_path", False)
        try:
            serial = get_json_object(col, "$.a").to_pylist()
        finally:
            config.reset("json_fast_path")
        assert hybrid == serial

    def test_clean_batch_stays_fast_and_matches_serial(self):
        col = StringColumn.from_pylist(CLEAN_DOCS, pad_to_multiple=8)
        config.set("json_fast_path", True)
        try:
            hybrid = get_json_object(col, "$.a").to_pylist()
        finally:
            config.reset("json_fast_path")
        config.set("json_fast_path", False)
        try:
            serial = get_json_object(col, "$.a").to_pylist()
        finally:
            config.reset("json_fast_path")
        assert hybrid == serial

    def test_null_rows_do_not_force_fallback(self):
        docs = ['{"a": 1}', None, '{"a": 2}']
        col = StringColumn.from_pylist(docs, pad_to_multiple=8)
        out_c, out_l, ok, fb = map(
            np.asarray,
            fast_path(col.chars, col.lengths, col.validity, _pt("$.a"),
                      col.max_len + 8))
        assert not fb.any()
        assert list(ok) == [True, False, True]


class TestCompactFallback:
    """r5 per-row fallback compaction (VERDICT r4 weak #2): flagged rows
    are gathered into fixed-capacity chunks and ONLY those chunks run the
    scan machine; results must equal the pure-serial engine everywhere,
    for any dirty-row placement and any chunk count."""

    def _serial(self, col, path):
        config.set("json_fast_path", False)
        try:
            return get_json_object(col, path).to_pylist()
        finally:
            config.reset("json_fast_path")

    def _compact(self, col, path, div):
        config.set("json_fast_path", True)
        config.set("json_fallback_div", div)
        try:
            return get_json_object(col, path).to_pylist()
        finally:
            config.reset("json_fallback_div")
            config.reset("json_fast_path")

    def test_scattered_dirty_rows_match_serial(self):
        # dirty rows at the first, middle, and last position: the scatter
        # must land each scan result on its own row
        docs = list(CLEAN_DOCS)
        docs[0] = DIRTY_DOCS[0]
        docs[len(docs) // 2] = DIRTY_DOCS[1]
        docs[-1] = DIRTY_DOCS[2]
        col = StringColumn.from_pylist(docs, pad_to_multiple=8)
        assert self._compact(col, "$.a", 8) == self._serial(col, "$.a")

    def test_all_dirty_overflows_across_chunks(self):
        # nfb = n >> cap: the while_loop must run ceil(n/cap) iterations
        # and still cover every row (no cliff at capacity overflow)
        docs = DIRTY_DOCS * 6                      # 18 rows, all flagged
        col = StringColumn.from_pylist(docs, pad_to_multiple=8)
        assert self._compact(col, "$.a", 8) == self._serial(col, "$.a")

    def test_capacity_one_chunk_per_row(self):
        # div >= n -> cap=1: one loop iteration per dirty row
        docs = [CLEAN_DOCS[1], DIRTY_DOCS[0], CLEAN_DOCS[4], DIRTY_DOCS[1]]
        col = StringColumn.from_pylist(docs, pad_to_multiple=8)
        assert self._compact(col, "$.a", 64) == self._serial(col, "$.a")

    def test_div0_whole_batch_engine_unchanged(self):
        docs = CLEAN_DOCS[:6] + [DIRTY_DOCS[0]]
        col = StringColumn.from_pylist(docs, pad_to_multiple=8)
        assert self._compact(col, "$.a", 0) == self._serial(col, "$.a")

    def test_null_rows_with_dirty_neighbors(self):
        docs = ['{"a": 1}', None, DIRTY_DOCS[0], None, '{"a": 2}']
        col = StringColumn.from_pylist(docs, pad_to_multiple=8)
        assert self._compact(col, "$.a", 2) == self._serial(col, "$.a")


class TestFastEngineFuzz:
    def test_random_corpus_parity(self):
        """Random nested docs (ints/strings/literals only — float parity
        is engine-vs-engine, covered above) against the oracle."""
        import json
        import random

        rng = random.Random(7)
        names = ["a", "b", "cc", "owner", "x"]

        def rand_value(depth):
            r = rng.random()
            if depth >= 3 or r < 0.4:
                return rng.choice([
                    lambda: rng.randint(-10**6, 10**12),
                    lambda: rng.choice([True, False, None]),
                    lambda: "".join(rng.choice("abc XY-@#.")
                                    for _ in range(rng.randint(0, 10))),
                ])()
            if r < 0.75:
                return {rng.choice(names): rand_value(depth + 1)
                        for _ in range(rng.randint(0, 3))}
            return [rand_value(depth + 1) for _ in range(rng.randint(0, 3))]

        docs = []
        for _ in range(200):
            s = json.dumps(rand_value(0))
            if rng.random() < 0.5:
                s = s.replace(",", " , ").replace(":", " : ")
            docs.append(s)
        # mutate some into likely-malformed variants
        for i in range(0, 200, 9):
            d = docs[i]
            if len(d) > 3:
                j = rng.randrange(len(d))
                docs[i] = d[:j] + rng.choice("{},:0\"x") + d[j + 1:]

        for path in ("$.a", "$.owner[0]", "$.b.x"):
            got = _run_fast(docs, path)
            for d, g in zip(docs, got):
                if g == "<FB>":
                    continue
                assert g == oracle(d, path), (path, d)
