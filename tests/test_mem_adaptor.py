"""Memory-runtime state-machine tests.

Ports the distinctive scenarios of the reference's ``RmmSparkTest.java``
(scriptable task threads driven through BLOCKED/BUFN/split states, with
state polling) and a seeded Monte-Carlo oversubscription fuzz
(``RmmSparkMonteCarlo.java``, ``ci/fuzz-test.sh``: tasks allocating up to
2/3 over budget must all complete without deadlock/livelock).
"""

import queue
import random
import threading
import time

import pytest

from spark_rapids_jni_tpu.mem import (
    InjectedException,
    OOMError,
    RetryOOM,
    RmmSpark,
    SparkResourceAdaptor,
    SplitAndRetryOOM,
    ThreadState,
)

MB = 1 << 20


@pytest.fixture
def adaptor():
    a = SparkResourceAdaptor(10 * MB, poll_ms=20.0)
    yield a
    a.close()


def poll_for_state(adaptor, tid, want, timeout=5.0):
    """RmmSparkTest.pollForState equivalent."""
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        s = adaptor.get_state_of(tid)
        if s == want:
            return s
        time.sleep(0.005)
    return adaptor.get_state_of(tid)


class TaskThread(threading.Thread):
    """Scriptable worker: feed it closures, read results (RmmSparkTest's
    TaskThread op-queue pattern)."""

    def __init__(self, adaptor, task_id, dedicated=True, shuffle=False):
        super().__init__(daemon=True)
        self.adaptor = adaptor
        self.task_id = task_id
        self.dedicated = dedicated
        self.shuffle = shuffle
        self.ops = queue.Queue()
        self.results = queue.Queue()
        self.tid = None
        self._ready = threading.Event()
        self.start()
        self._ready.wait(5.0)

    def run(self):
        self.tid = threading.get_ident()
        if self.dedicated:
            self.adaptor.start_dedicated_task_thread(self.task_id)
        else:
            self.adaptor.pool_thread_working_on_tasks(
                self.shuffle, [self.task_id])
        self._ready.set()
        while True:
            fn = self.ops.get()
            if fn is None:
                return
            try:
                self.results.put(("ok", fn()))
            except BaseException as e:  # noqa: BLE001 - test harness
                self.results.put(("exc", e))

    def do(self, fn):
        self.ops.put(fn)

    def expect(self, timeout=10.0):
        kind, val = self.results.get(timeout=timeout)
        return kind, val

    def finish(self):
        self.ops.put(None)
        self.join(timeout=5.0)


class TestBasics:
    def test_alloc_dealloc_metrics(self, adaptor):
        t = TaskThread(adaptor, 1)
        t.do(lambda: adaptor.allocate(4 * MB, tid=t.tid))
        assert t.expect()[0] == "ok"
        assert adaptor.total_allocated() == 4 * MB
        t.do(lambda: adaptor.deallocate(4 * MB, tid=t.tid))
        assert t.expect()[0] == "ok"
        assert adaptor.total_allocated() == 0
        assert adaptor.get_max_memory_allocated(1) == 4 * MB
        t.finish()

    def test_unregistered_thread_raises(self, adaptor):
        with pytest.raises(RuntimeError):
            adaptor.allocate(MB)  # calling thread never registered

    def test_state_polling(self, adaptor):
        t = TaskThread(adaptor, 1)
        assert poll_for_state(adaptor, t.tid, ThreadState.RUNNING) \
            == ThreadState.RUNNING
        t.finish()


class TestBlocking:
    def test_second_task_blocks_until_free(self, adaptor):
        a = TaskThread(adaptor, 1)
        b = TaskThread(adaptor, 2)
        a.do(lambda: adaptor.allocate(8 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        # b wants 4MB; only 2MB free -> BLOCKED
        b.do(lambda: adaptor.allocate(4 * MB, tid=b.tid))
        assert poll_for_state(adaptor, b.tid, ThreadState.BLOCKED) \
            == ThreadState.BLOCKED
        # freeing unblocks b
        a.do(lambda: adaptor.deallocate(8 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        assert b.expect()[0] == "ok"
        assert adaptor.get_and_reset_block_time_ns(2) > 0
        for t in (a, b):
            t.finish()

    def test_deadlock_breaks_lowest_priority(self, adaptor):
        """Both tasks blocked -> the youngest task (highest id = lowest
        priority) gets RetryOOM (BUFN escalation, reference :1622-1631)."""
        a = TaskThread(adaptor, 1)
        b = TaskThread(adaptor, 2)
        a.do(lambda: adaptor.allocate(5 * MB, tid=a.tid))
        b.do(lambda: adaptor.allocate(5 * MB, tid=b.tid))
        assert a.expect()[0] == "ok"
        assert b.expect()[0] == "ok"
        # both now ask for more than remains -> deadlock
        a.do(lambda: adaptor.allocate(2 * MB, tid=a.tid))
        b.do(lambda: adaptor.allocate(2 * MB, tid=b.tid))
        # task 2 is younger -> lower priority -> it must get RetryOOM
        kind, exc = b.expect()
        assert kind == "exc" and isinstance(exc, RetryOOM)
        # b rolls back per the contract: free, then block until ready
        b.do(lambda: adaptor.deallocate(5 * MB, tid=b.tid))
        assert b.expect()[0] == "ok"
        assert a.expect()[0] == "ok"  # a's alloc proceeds
        assert adaptor.get_and_reset_num_retry(2) >= 1
        for t in (a, b):
            t.finish()

    def test_split_and_retry_when_all_bufn(self, adaptor):
        """If every task is BUFN the highest-priority one gets
        SplitAndRetryOOM (reference :1647-1669)."""
        a = TaskThread(adaptor, 1)
        b = TaskThread(adaptor, 2)
        a.do(lambda: adaptor.allocate(5 * MB, tid=a.tid))
        b.do(lambda: adaptor.allocate(5 * MB, tid=b.tid))
        assert a.expect()[0] == "ok" and b.expect()[0] == "ok"
        a.do(lambda: adaptor.allocate(2 * MB, tid=a.tid))
        b.do(lambda: adaptor.allocate(2 * MB, tid=b.tid))
        kind, exc = b.expect()
        assert kind == "exc" and isinstance(exc, RetryOOM)
        # b has nothing spillable and parks in BUFN; a is now the only
        # non-BUFN thread, so the next escalation hands IT a RetryOOM too
        b.do(lambda: adaptor.block_thread_until_ready(tid=b.tid))
        kind, exc = a.expect()
        assert kind == "exc" and isinstance(exc, RetryOOM)
        # a also parks without freeing: now EVERY task is BUFN, so the
        # highest-priority (oldest) task is told to split
        a.do(lambda: adaptor.block_thread_until_ready(tid=a.tid))
        kind, exc = a.expect()
        assert kind == "exc" and isinstance(exc, SplitAndRetryOOM)
        assert adaptor.get_and_reset_num_split_retry(1) >= 1
        # a halves its request; 0 free -> must free something first
        a.do(lambda: adaptor.deallocate(5 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        a.do(lambda: adaptor.allocate(1 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        assert b.expect()[0] == "ok"  # b's BUFN was rescued by the free
        for t in (a, b):
            t.finish()

    def test_shuffle_thread_outranks_tasks(self, adaptor):
        """A blocked shuffle thread wakes before older task threads."""
        a = TaskThread(adaptor, 1)
        s = TaskThread(adaptor, 2, dedicated=False, shuffle=True)
        a.do(lambda: adaptor.allocate(9 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        s.do(lambda: adaptor.allocate(2 * MB, tid=s.tid))
        assert poll_for_state(adaptor, s.tid, ThreadState.BLOCKED) \
            == ThreadState.BLOCKED
        a.do(lambda: adaptor.deallocate(9 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        assert s.expect()[0] == "ok"
        for t in (a, s):
            t.finish()


class TestInjection:
    def test_force_retry_oom_count_skip(self, adaptor):
        t = TaskThread(adaptor, 1)
        adaptor.force_retry_oom(t.tid, num_ooms=2, skip_count=1)
        t.do(lambda: adaptor.allocate(MB, tid=t.tid))  # skipped
        assert t.expect()[0] == "ok"
        for _ in range(2):
            t.do(lambda: adaptor.allocate(MB, tid=t.tid))
            kind, exc = t.expect()
            assert kind == "exc" and isinstance(exc, RetryOOM)
            t.do(lambda: adaptor.block_thread_until_ready(tid=t.tid))
            assert t.expect()[0] == "ok"
        t.do(lambda: adaptor.allocate(MB, tid=t.tid))  # injection exhausted
        assert t.expect()[0] == "ok"
        assert adaptor.get_and_reset_num_retry(1) == 2
        t.finish()

    def test_force_split_and_exception(self, adaptor):
        t = TaskThread(adaptor, 1)
        adaptor.force_split_and_retry_oom(t.tid, num_ooms=1)
        t.do(lambda: adaptor.allocate(MB, tid=t.tid))
        kind, exc = t.expect()
        assert kind == "exc" and isinstance(exc, SplitAndRetryOOM)
        adaptor.force_exception(t.tid, num_times=1)
        t.do(lambda: adaptor.allocate(MB, tid=t.tid))
        kind, exc = t.expect()
        assert kind == "exc" and isinstance(exc, InjectedException)
        t.finish()


class TestRetryCap:
    def test_oversized_request_hard_ooms(self, adaptor):
        """A single task asking for more than the pool must end in a hard
        OOM (after the 500-retry livelock bound), not hang."""
        t = TaskThread(adaptor, 1)
        t.do(lambda: adaptor.allocate(11 * MB, tid=t.tid))
        kind, exc = t.expect(timeout=30.0)
        assert kind == "exc" and isinstance(exc, (OOMError, RetryOOM,
                                                  SplitAndRetryOOM))
        t.finish()


class TestMonteCarlo:
    """Seeded oversubscription fuzz (RmmSparkMonteCarlo.java semantics:
    taskMax ~2048MiB vs pool 3072MiB, scaled down)."""

    @pytest.mark.parametrize(
        "seed",
        [int(s) for s in
         __import__("os").environ.get("MEM_FUZZ_SEEDS", "11,42").split(",")])
    def test_oversubscribed_tasks_all_complete(self, seed):
        pool = 3 * MB
        task_max = 2 * MB
        n_tasks = 6
        adaptor = SparkResourceAdaptor(pool, poll_ms=10.0)
        failures = []
        retries = [0]

        def task_fn(task_id):
            rng = random.Random(seed * 1000 + task_id)
            adaptor.start_dedicated_task_thread(task_id)
            held = []  # (nbytes)
            try:
                ops = 0
                budget = task_max
                while ops < 40:
                    want = rng.randrange(1, max(2, budget // 4))
                    try:
                        adaptor.allocate(want)
                        held.append(want)
                        ops += 1
                        if rng.random() < 0.4 and held:
                            adaptor.deallocate(
                                held.pop(rng.randrange(len(held))))
                        if sum(held) > task_max - want:
                            while held:
                                adaptor.deallocate(held.pop())
                    except SplitAndRetryOOM:
                        retries[0] += 1
                        while held:
                            adaptor.deallocate(held.pop())
                        budget = max(budget // 2, 4)
                    except RetryOOM:
                        retries[0] += 1
                        while held:
                            adaptor.deallocate(held.pop())
                        try:
                            adaptor.block_thread_until_ready()
                        except SplitAndRetryOOM:
                            # the scheduler may escalate the blocked thread
                            # to SPLIT_THROW (reference
                            # SparkResourceAdaptorJni.cpp:1084-1088) — the
                            # plugin contract is to halve and retry
                            budget = max(budget // 2, 4)
                        except RetryOOM:
                            pass
                while held:
                    adaptor.deallocate(held.pop())
            except BaseException as e:  # noqa: BLE001
                failures.append((task_id, e))
            finally:
                adaptor.task_done(task_id)

        threads = [threading.Thread(target=task_fn, args=(i + 1,),
                                    daemon=True) for i in range(n_tasks)]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 120.0  # generous: CI boxes are noisy
        for th in threads:
            th.join(timeout=max(0.1, deadline - time.monotonic()))
        alive = [th for th in threads if th.is_alive()]
        states = [adaptor.get_state_of(tid=th.ident) for th in threads]
        adaptor.close()
        assert not alive, (
            f"deadlocked/livelocked threads: {len(alive)}, states={states}, "
            f"retries={retries[0]}")
        assert not failures, failures
        assert adaptor._h is None


class TestSpillMonteCarlo:
    """Spill-framework oversubscription fuzz: N threads x spillable
    batches against a device arena far below the combined working set,
    with the bounded host tier bouncing overflow to disk.  Asserts no
    deadlock, no lost bytes (both arenas drain to zero), and that every
    disk-tier file is cleaned up on close()."""

    @pytest.mark.parametrize(
        "seed",
        [int(s) for s in
         __import__("os").environ.get("SPILL_FUZZ_SEEDS", "7,23").split(",")])
    def test_spill_fuzz_no_deadlock_no_lost_bytes(self, seed, tmp_path):
        import numpy as np

        from spark_rapids_jni_tpu.mem import TaskContext, run_with_retry
        from spark_rapids_jni_tpu.mem import spill as spill_mod

        fw = spill_mod.install(spill_dir=str(tmp_path / "fuzz"))
        adaptor = RmmSpark.set_event_handler(
            2 * MB, host_pool_bytes=256 << 10, poll_ms=10.0)
        failures = []
        n_threads = 4

        def task_fn(task_id):
            rng = random.Random(seed * 100 + task_id)
            try:
                with TaskContext(task_id) as ctx:
                    handles = []
                    for _ in range(12):
                        rows = {"n": rng.randrange(1 << 10, 96 << 10)}

                        def step():
                            tree = {"x": np.arange(rows["n"],
                                                   dtype=np.int32)}
                            return spill_mod.SpillableHandle(tree, ctx=ctx)

                        def split():
                            rows["n"] = max(rows["n"] // 2, 16)

                        # NO make_spillable: the framework default carries
                        # every thread through the shared-arena pressure
                        handles.append(run_with_retry(step, split=split,
                                                      max_retries=20))
                        if rng.random() < 0.35:
                            victim = rng.choice(handles)

                            def read_step():
                                t = victim.get()
                                return int(t["x"][-1]), t["x"].shape[0]

                            last, n = run_with_retry(read_step, split=split,
                                                     max_retries=20)
                            assert last == n - 1  # read-back uncorrupted
                        if rng.random() < 0.3:
                            handles.pop(rng.randrange(len(handles))).close()
                    for h in handles:
                        h.close()
            except BaseException as e:  # noqa: BLE001
                failures.append((task_id, e))
            finally:
                RmmSpark.task_done(task_id)

        try:
            threads = [threading.Thread(target=task_fn, args=(i + 1,),
                                        daemon=True)
                       for i in range(n_threads)]
            for th in threads:
                th.start()
            deadline = time.monotonic() + 120.0
            for th in threads:
                th.join(timeout=max(0.1, deadline - time.monotonic()))
            alive = [th for th in threads if th.is_alive()]
            assert not alive, (
                f"deadlocked spill-fuzz threads: {len(alive)}, "
                f"states={[adaptor.get_state_of(tid=th.ident) for th in threads]}")
            assert not failures, failures
            # no lost bytes: every charge in every tier was released
            assert adaptor.total_allocated() == 0
            assert adaptor.host_total_allocated() == 0
            assert len(fw.store) == 0
            leftover = [f for f in
                        __import__("os").listdir(fw.spill_dir)]
            assert leftover == [], f"disk tier not cleaned: {leftover}"
            # the arena WAS oversubscribed: the tiers actually moved
            assert fw.metrics.snapshot()["device_to_host_count"] > 0
        finally:
            spill_mod.shutdown()
            RmmSpark.clear_event_handler()


class TestCpuArena:
    def test_cpu_flavored_oom(self):
        RmmSpark.set_event_handler(8 * MB)
        RmmSpark.set_cpu_event_handler(1 * MB)
        try:
            RmmSpark.current_thread_is_dedicated_to_task(1)
            RmmSpark.cpu_allocate(512 << 10)
            RmmSpark.cpu_deallocate(512 << 10)
            from spark_rapids_jni_tpu.mem import CpuRetryOOM

            RmmSpark._c().force_retry_oom(None)
            with pytest.raises(CpuRetryOOM):
                RmmSpark.cpu_allocate(1)
        finally:
            RmmSpark.clear_event_handler()


class TestTransitionLog:
    def test_csv_state_log_written(self, tmp_path):
        """The spdlog-CSV analogue (reference :897-933): the race-hunting
        transition log records alloc state changes."""
        log = str(tmp_path / "transitions.csv")
        a = SparkResourceAdaptor(MB, log_path=log, poll_ms=50.0)
        try:
            t = TaskThread(a, 1)
            t.do(lambda: a.allocate(1024, tid=t.tid))
            assert t.expect()[0] == "ok"
            t.do(lambda: a.deallocate(1024, tid=t.tid))
            assert t.expect()[0] == "ok"
            t.finish()
        finally:
            a.close()
        with open(log) as f:
            lines = f.read().splitlines()
        assert lines[0].startswith("time_ns,op,")
        assert any("alloc_ok" in ln for ln in lines)


class TestExecutor:
    def test_task_context_charges_and_releases(self):
        import jax.numpy as jnp

        from spark_rapids_jni_tpu.mem.executor import TaskContext, batch_nbytes

        RmmSpark.set_event_handler(64 * MB)
        try:
            tree = {"a": jnp.zeros((1024,), jnp.int32)}
            n = batch_nbytes(tree)
            assert n == 4096
            with TaskContext(1) as ctx:
                ctx.charge(tree)
                assert RmmSpark._a().total_allocated() == n
            assert RmmSpark._a().total_allocated() == 0
        finally:
            RmmSpark.clear_event_handler()

    def test_run_with_retry_ladder(self):
        from spark_rapids_jni_tpu.mem.executor import TaskContext, run_with_retry

        RmmSpark.set_event_handler(64 * MB)
        try:
            with TaskContext(1):
                a = RmmSpark._a()
                a.force_retry_oom(None, num_ooms=1)
                a.force_split_and_retry_oom(None, num_ooms=1, skip_count=1)
                spilled = []
                halved = []

                def step():
                    RmmSpark.allocate(1024)
                    RmmSpark.deallocate(1024)
                    return "done"

                out = run_with_retry(step, make_spillable=lambda: spilled.append(1),
                                     split=lambda: halved.append(1))
                assert out == "done"
                assert spilled and halved
        finally:
            RmmSpark.clear_event_handler()


class TestPipelineUnderInjectedOOM:
    """End-to-end SURVEY §3.1 contract: the q6 pipeline driven through
    TaskContext + run_with_retry completes with correct results under
    injected RetryOOM and SplitAndRetryOOM (the reference proves this with
    RmmSparkTest's injection scenarios around real kernels)."""

    @staticmethod
    def _groups(res, ng):
        n = int(ng)
        return dict(zip(res["k"].to_pylist()[:n],
                        res["sum_v"].to_pylist()[:n]))

    @staticmethod
    def _numpy_oracle(n_rows):
        """Independent q6 oracle over the same seeded generator."""
        import numpy as np

        rng = np.random.default_rng(7)
        k = rng.integers(0, 100, n_rows).astype(np.int32)
        v = rng.integers(-1000, 1000, n_rows).astype(np.int64)
        price = rng.random(n_rows) * 100.0
        mask = price < 50.0
        out = {}
        for kk in np.unique(k[mask]):
            out[int(kk)] = int(v[mask & (k == kk)].sum())
        return out

    def test_q6_completes_under_injection(self):
        import jax

        import __graft_entry__ as ge
        from spark_rapids_jni_tpu.mem import RmmSpark, TaskContext, run_with_retry
        from spark_rapids_jni_tpu.mem.executor import batch_nbytes

        RmmSpark.set_event_handler(64 << 20)
        try:
            state = {"rows": 2048, "splits": 0, "spills": 0}

            with TaskContext(7) as ctx:
                # inject: one RetryOOM then (after one success) a split
                RmmSpark.force_retry_oom(None, 1, 0)

                def step():
                    b = ge._example_batch(state["rows"])
                    n = ctx.charge(batch_nbytes(b))
                    try:
                        res, ng = jax.jit(ge._q6_step)(b)
                        jax.block_until_ready((res, ng))
                        return res, ng
                    finally:
                        ctx.release(n)

                def make_spillable():
                    state["spills"] += 1

                def split():
                    state["splits"] += 1
                    state["rows"] //= 2

                res, ng = run_with_retry(step, make_spillable, split)
                assert state["spills"] == 1  # the injected retry fired
                # the retried (2048-row) result must match the
                # independent numpy oracle
                assert self._groups(res, ng) == self._numpy_oracle(2048)

                RmmSpark.force_split_and_retry_oom(None, 1, 0)
                res, ng = run_with_retry(step, make_spillable, split)
                assert state["splits"] == 1 and state["rows"] == 1024

            RmmSpark.task_done(7)
            # split halved the input; validate against the 1024-row oracle
            assert self._groups(res, ng) == self._numpy_oracle(1024)
            assert RmmSpark._a().get_and_reset_num_retry(7) >= 1
        finally:
            RmmSpark.clear_event_handler()


class TestSpillable:
    def test_spill_releases_and_reupload_recharges(self):
        import numpy as np

        import jax

        import __graft_entry__ as ge
        from spark_rapids_jni_tpu.mem import RmmSpark, Spillable, TaskContext
        from spark_rapids_jni_tpu.mem.executor import batch_nbytes

        RmmSpark.set_event_handler(64 << 20)
        try:
            with TaskContext(3) as ctx:
                batch = ge._example_batch(512)
                nbytes = batch_nbytes(batch)
                s = Spillable(batch, ctx)
                assert RmmSpark._a().total_allocated() == nbytes
                before = np.asarray(jax.device_get(batch["v"].data)).copy()

                s.spill()
                assert s.is_spilled
                assert RmmSpark._a().total_allocated() == 0

                got = s.get()  # re-upload + re-charge
                assert not s.is_spilled
                assert RmmSpark._a().total_allocated() == nbytes
                after = np.asarray(jax.device_get(got["v"].data))
                assert (before == after).all()
                s.close()
                assert RmmSpark._a().total_allocated() == 0
            RmmSpark.task_done(3)
        finally:
            RmmSpark.clear_event_handler()

    def test_retry_ladder_with_real_spill(self):
        import jax

        import __graft_entry__ as ge
        from spark_rapids_jni_tpu.mem import (
            RmmSpark,
            Spillable,
            TaskContext,
            run_with_retry,
        )

        RmmSpark.set_event_handler(64 << 20)
        try:
            with TaskContext(4) as ctx:
                s = Spillable(ge._example_batch(512), ctx)
                RmmSpark.force_retry_oom(None, 1, 0)

                def step():
                    RmmSpark.allocate(1 << 10)  # trips the injection once
                    try:
                        res, ng = jax.jit(ge._q6_step)(s.get())
                        jax.block_until_ready((res, ng))
                        return res, ng
                    finally:
                        RmmSpark.deallocate(1 << 10)

                res, ng = run_with_retry(step, make_spillable=s.spill)
                assert int(ng) > 0
                assert not s.is_spilled  # get() re-uploaded for the retry
                s.close()
            RmmSpark.task_done(4)
        finally:
            RmmSpark.clear_event_handler()


class TestRealDeviceOomTranslation:
    """VERDICT r2 item 3: a REAL XLA RESOURCE_EXHAUSTED at the execute
    boundary must drive the same spill -> block -> retry ladder as
    logical arena pressure (reference interposes the allocator,
    SparkResourceAdaptorJni.cpp:1731-1798; we translate where the error
    surfaces)."""

    @staticmethod
    def _fake_xla_oom():
        # matched by TYPE NAME + marker, exactly like the real
        # jaxlib.xla_extension.XlaRuntimeError we cannot construct here
        class XlaRuntimeError(RuntimeError):
            pass

        return XlaRuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 16777216 bytes")

    def test_is_device_oom_matcher(self):
        from spark_rapids_jni_tpu.mem import is_device_oom

        assert is_device_oom(self._fake_xla_oom())
        assert not is_device_oom(RuntimeError("RESOURCE_EXHAUSTED"))
        assert not is_device_oom(MemoryError("Out of memory"))

        class XlaRuntimeError(RuntimeError):
            pass

        assert not is_device_oom(XlaRuntimeError("INVALID_ARGUMENT: shape"))

    def test_without_adaptor_raw_error_propagates(self):
        import pytest

        from spark_rapids_jni_tpu.mem import run_with_retry

        err = self._fake_xla_oom()

        def step():
            raise err

        with pytest.raises(type(err)):
            run_with_retry(step)

    def test_real_oom_drives_spill_block_retry(self):
        import jax
        import jax.numpy as jnp

        from spark_rapids_jni_tpu.mem import (
            RmmSpark,
            Spillable,
            TaskContext,
            run_with_retry,
        )

        RmmSpark.set_event_handler(1 << 20)
        try:
            with TaskContext(21) as ctx:
                s = Spillable({"x": jnp.arange(1024, dtype=jnp.int32)}, ctx)
                calls = {"step": 0, "splits": 0}

                def step():
                    calls["step"] += 1
                    batch = s.get()
                    if calls["step"] == 1:
                        raise self._fake_xla_oom()  # "HBM" refuses
                    return int(jax.device_get(batch["x"][-1]))

                res = run_with_retry(step, make_spillable=s.spill,
                                     split=lambda: calls.__setitem__(
                                         "splits", calls["splits"] + 1))
                assert res == 1023
                assert calls["step"] >= 2  # the step really re-ran
                s.close()
            RmmSpark.task_done(21)
            # the ladder went through the native protocol, not a bare
            # python re-raise: the retry metric moved
            assert RmmSpark._a().get_and_reset_num_retry(21) >= 1
        finally:
            RmmSpark.clear_event_handler()

    def test_sync_pool_with_device_cpu_is_none(self):
        from spark_rapids_jni_tpu.mem import RmmSpark

        RmmSpark.set_event_handler(1 << 20)
        try:
            # CPU backends expose no memory_stats: sync is a no-op
            assert RmmSpark.sync_pool_with_device() is None
        finally:
            RmmSpark.clear_event_handler()

    def test_resize_pool_frees_budget(self):
        import pytest

        from spark_rapids_jni_tpu.mem import RetryOOM, RmmSpark, TaskContext

        RmmSpark.set_event_handler(1 << 10)
        try:
            with TaskContext(22) as ctx:
                ctx.charge(1 << 10)  # arena full
                RmmSpark._a().resize_pool(1 << 12)  # device says: more room
                ctx.charge(1 << 11)  # now fits
                with pytest.raises((RetryOOM, MemoryError)):
                    ctx.charge(1 << 12)  # beyond even the resized pool
            RmmSpark.task_done(22)
        finally:
            RmmSpark.clear_event_handler()


class TestUnifiedArenaDeadlock:
    """VERDICT r2 item 6: both arenas share ONE native state machine, so
    the deadlock scan sees a thread blocked on HOST memory while holding
    DEVICE budget (reference mixed CPU+GPU blocking,
    SparkResourceAdaptorJni.cpp:808-842)."""

    def test_cross_arena_deadlock_is_broken(self):
        import threading

        from spark_rapids_jni_tpu.mem import CpuRetryOOM, CpuSplitAndRetryOOM, RmmSpark

        MB = 1 << 20
        RmmSpark.set_event_handler(MB, host_pool_bytes=MB)
        try:
            barrier = threading.Barrier(2)
            results = {}

            def t1_fn():  # task 1: holds HOST, blocks on DEVICE
                RmmSpark.current_thread_is_dedicated_to_task(1)
                RmmSpark.cpu_allocate(900 << 10)
                barrier.wait()
                RmmSpark.allocate(900 << 10)  # parks until t2 rolls back
                RmmSpark.deallocate(900 << 10)
                RmmSpark.cpu_deallocate(900 << 10)
                results[1] = "ok"
                RmmSpark.remove_current_thread_association()

            def t2_fn():  # task 2 (lower priority): holds DEVICE,
                # blocks on HOST -> must be the BUFN victim
                RmmSpark.current_thread_is_dedicated_to_task(2)
                RmmSpark.allocate(900 << 10)
                barrier.wait()
                try:
                    RmmSpark.cpu_allocate(900 << 10)
                    results[2] = "no-escalation"
                except CpuRetryOOM:
                    results["escalated"] = True
                    RmmSpark.deallocate(900 << 10)  # roll back device
                    try:
                        RmmSpark.cpu_block_thread_until_ready()
                    except (CpuRetryOOM, CpuSplitAndRetryOOM):
                        # the scheduler may tell the sole remaining
                        # runner to split and push through — either way
                        # this thread may now retry
                        pass
                    RmmSpark.cpu_allocate(900 << 10)  # retry succeeds
                    RmmSpark.cpu_deallocate(900 << 10)
                    results[2] = "recovered"
                RmmSpark.remove_current_thread_association()

            t1 = threading.Thread(target=t1_fn, daemon=True)
            t2 = threading.Thread(target=t2_fn, daemon=True)
            t1.start()
            t2.start()
            t1.join(timeout=30)
            t2.join(timeout=30)
            assert not t1.is_alive() and not t2.is_alive(), (
                "cross-arena deadlock was NOT broken", results)
            assert results.get("escalated"), (
                "host-blocked thread holding device budget was not "
                "BUFN-escalated", results)
            assert results.get(1) == "ok" and results.get(2) == "recovered"
            RmmSpark.task_done(1)
            RmmSpark.task_done(2)
            # the victim's escalation shows up in the retry metric
            assert RmmSpark._a().get_and_reset_num_retry(2) >= 1
        finally:
            RmmSpark.clear_event_handler()

    def test_unified_host_pool_flavors(self):
        import pytest

        from spark_rapids_jni_tpu.mem import CpuRetryOOM, RmmSpark, TaskContext

        RmmSpark.set_event_handler(1 << 20, host_pool_bytes=1 << 16)
        try:
            with TaskContext(3):
                RmmSpark.cpu_allocate(1 << 15)
                assert RmmSpark._a().host_total_allocated() == 1 << 15
                with pytest.raises(CpuRetryOOM):
                    # single thread over the host pool: immediate
                    # escalation, Cpu flavor
                    RmmSpark.cpu_allocate(1 << 16)
                RmmSpark.cpu_deallocate(1 << 15)
            RmmSpark.task_done(3)
        finally:
            RmmSpark.clear_event_handler()


class TestRetryLadderInnerOOM:
    """run_with_retry: a RetryOOM raised from block_thread_until_ready()
    itself (a peer freed memory and the adaptor converts the park into an
    immediate retry) must loop back through make_spillable, not abort the
    ladder (the pre-hardening bug: the inner raise propagated out)."""

    def test_inner_retryoom_reruns_make_spillable(self, monkeypatch):
        from spark_rapids_jni_tpu.mem import run_with_retry

        spills = []
        attempts = []

        def step():
            attempts.append(1)
            if len(attempts) < 3:
                raise RetryOOM("pressure")
            return "done"

        def make_spillable():
            spills.append(1)
            return 0  # nothing freed: the ladder must park

        blocks = []

        def fake_block(*a, **k):
            blocks.append(1)
            if len(blocks) == 1:
                # the adaptor's park can itself surface RetryOOM; the
                # ladder must treat it as "try to free again", not a crash
                raise RetryOOM("woken for retry")

        monkeypatch.setattr(RmmSpark, "block_thread_until_ready",
                            staticmethod(fake_block))
        assert run_with_retry(step, make_spillable=make_spillable) == "done"
        # first step OOM -> spill (0) -> park raises -> spill again (0)
        # -> park ok -> second step OOM -> spill -> park ok -> third step
        assert len(spills) >= 3
        assert len(blocks) >= 2

    def test_inner_split_still_honored(self, monkeypatch):
        from spark_rapids_jni_tpu.mem import run_with_retry

        attempts = []
        splits = []

        def step():
            attempts.append(1)
            if len(attempts) == 1:
                raise RetryOOM("pressure")
            return len(attempts)

        def fake_block(*a, **k):
            raise SplitAndRetryOOM("split instead")

        monkeypatch.setattr(RmmSpark, "block_thread_until_ready",
                            staticmethod(fake_block))
        assert run_with_retry(step, make_spillable=lambda: 0,
                              split=lambda: splits.append(1)) == 2
        assert splits == [1]

    def test_inner_retryoom_bounded(self, monkeypatch):
        from spark_rapids_jni_tpu.mem import run_with_retry

        def step():
            raise RetryOOM("always")

        def fake_block(*a, **k):
            raise RetryOOM("always woken")

        monkeypatch.setattr(RmmSpark, "block_thread_until_ready",
                            staticmethod(fake_block))
        with pytest.raises(RetryOOM):
            run_with_retry(step, make_spillable=lambda: 0, max_retries=3)


class TestTaskDoneReleasesParkedThreads:
    """Serving kill-safety regression: ``task_done()`` for a task whose
    thread is parked inside the arena (BLOCKED on an allocate, or BUFN
    after a rollback) must WAKE that thread and fail its pending call
    promptly.  The pre-fix adaptor erased the ThreadInfo out from under
    the live condition-variable waiter (UB) or left the thread parked
    forever, which also wedged the watchdog join in ``close()``."""

    def test_task_done_wakes_blocked_thread(self, adaptor):
        from spark_rapids_jni_tpu.mem.rmm_spark import UnknownThreadError

        runner = TaskThread(adaptor, 1)  # stays RUNNING: the global
        runner.do(lambda: adaptor.allocate(1 * MB, tid=runner.tid))
        assert runner.expect()[0] == "ok"  # deadlock scan cannot rescue
        victim = TaskThread(adaptor, 2)
        victim.do(lambda: adaptor.allocate(20 * MB, tid=victim.tid))
        assert poll_for_state(adaptor, victim.tid, ThreadState.BLOCKED) \
            == ThreadState.BLOCKED
        adaptor.task_done(2)  # the external kill path
        kind, exc = victim.expect(timeout=5.0)
        assert kind == "exc" and isinstance(exc, UnknownThreadError)
        # the entry was fully released, not leaked in REMOVE_THROW
        assert adaptor.get_state_of(victim.tid) == ThreadState.UNKNOWN
        victim.finish()
        runner.do(lambda: adaptor.deallocate(1 * MB, tid=runner.tid))
        assert runner.expect()[0] == "ok"
        runner.finish()
        assert adaptor.total_allocated() == 0

    def test_task_done_wakes_bufn_parked_thread(self, adaptor):
        from spark_rapids_jni_tpu.mem.rmm_spark import UnknownThreadError

        a = TaskThread(adaptor, 1)
        b = TaskThread(adaptor, 2)
        a.do(lambda: adaptor.allocate(8 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        b.do(lambda: adaptor.allocate(4 * MB, tid=b.tid))
        assert poll_for_state(adaptor, b.tid, ThreadState.BLOCKED) \
            == ThreadState.BLOCKED
        # a over-asks too -> full deadlock -> the scan hands b (lowest
        # priority) a RetryOOM, then a (the only BLOCKED left) as well
        a.do(lambda: adaptor.allocate(4 * MB, tid=a.tid))
        kind, exc = b.expect()
        assert kind == "exc" and isinstance(exc, RetryOOM)
        kind, exc = a.expect()
        assert kind == "exc" and isinstance(exc, RetryOOM)
        # a recovers with a small alloc and keeps RUNNING, so the global
        # deadlock scan stays idle and nothing can rescue b
        a.do(lambda: adaptor.allocate(1 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        # b has nothing to spill and parks in BUFN
        b.do(lambda: adaptor.block_thread_until_ready(tid=b.tid))
        assert poll_for_state(adaptor, b.tid, ThreadState.BUFN) \
            == ThreadState.BUFN
        adaptor.task_done(2)  # kill while BUFN-parked
        kind, exc = b.expect(timeout=5.0)
        assert kind == "exc" and isinstance(exc, UnknownThreadError)
        assert adaptor.get_state_of(b.tid) == ThreadState.UNKNOWN
        b.finish()
        a.do(lambda: adaptor.deallocate(9 * MB, tid=a.tid))
        assert a.expect()[0] == "ok"
        a.finish()
        assert adaptor.total_allocated() == 0


class TestBreakStalledCycles:
    """Cross-tenant stall breaker: the classic scan only fires when EVERY
    task thread is blocked, so a blocked subset starves behind an
    unrelated running tenant.  ``break_stalled_cycles`` rolls back the
    lowest-priority thread blocked past the stall bound."""

    def test_subset_stall_is_broken(self, adaptor):
        runner = TaskThread(adaptor, 1)  # unrelated tenant, keeps running
        runner.do(lambda: adaptor.allocate(1 * MB, tid=runner.tid))
        assert runner.expect()[0] == "ok"
        stuck = TaskThread(adaptor, 2)
        stuck.do(lambda: adaptor.allocate(20 * MB, tid=stuck.tid))
        assert poll_for_state(adaptor, stuck.tid, ThreadState.BLOCKED) \
            == ThreadState.BLOCKED
        # too young to be considered stalled yet
        assert not adaptor.break_stalled_cycles(stall_ms=60_000)
        time.sleep(0.06)
        assert adaptor.break_stalled_cycles(stall_ms=50)
        kind, exc = stuck.expect(timeout=5.0)
        assert kind == "exc" and isinstance(exc, RetryOOM)
        assert adaptor.get_and_reset_num_retry(2) >= 1
        stuck.finish()
        runner.do(lambda: adaptor.deallocate(1 * MB, tid=runner.tid))
        assert runner.expect()[0] == "ok"
        runner.finish()
