"""Scaling sanity: if measured time doesn't scale with N, measurement is broken."""
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)


def bench(name, N, f, *args, reps=10):
    jf = jax.jit(f)
    jax.block_until_ready(jf(jnp.uint32(999), *args))
    t0 = time.perf_counter()
    for r in range(reps):
        out = jf(jnp.uint32(r), *args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:34s} {dt*1e3:9.3f} ms   {N/dt/1e6:9.1f} Mrows/s", flush=True)


rng = np.random.default_rng(0)
for logn in (21, 24):
    N = 1 << logn
    key = jnp.asarray(rng.integers(0, 2**32, N, dtype=np.uint32))
    iota = jnp.arange(N, dtype=jnp.int32)
    i64 = jnp.asarray(rng.integers(-(2**40), 2**40, N, dtype=np.int64))
    ridx = jnp.asarray(rng.integers(0, N, N, dtype=np.int32))
    gid = jnp.asarray(rng.integers(0, 100, N, dtype=np.int32))

    bench(f"sort_pair_N=2^{logn}", N,
          lambda s, k, i: jax.lax.sort((k ^ s, i), num_keys=1)[0][::4096].sum(),
          key, iota)
    bench(f"gather_rand_N=2^{logn}", N,
          lambda s, i, v: (v ^ jnp.int64(s))[i][::4096].sum(), ridx, i64)
    bench(f"segsum_bigseg_N=2^{logn}", N,
          lambda s, g, v: jax.ops.segment_sum(v ^ jnp.int64(s), g,
                                              num_segments=N)[::4096].sum(),
          gid, i64)
    bench(f"segsum_128_N=2^{logn}", N,
          lambda s, g, v: jax.ops.segment_sum(v ^ jnp.int64(s), g,
                                              num_segments=128).sum(),
          gid, i64)
    bench(f"scatter_min_tbl_N=2^{logn}", N,
          lambda s, g, v: jnp.full((N,), jnp.int32(2**31 - 1), jnp.int32)
          .at[(v ^ jnp.int64(s)).astype(jnp.uint32) & jnp.uint32(N - 1)]
          .min(jnp.arange(N, dtype=jnp.int32))[::4096].sum(),
          gid, i64)
