import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

t0 = time.time()


def log(m):
    print(f"[{time.time()-t0:7.1f}s] {m}", file=sys.stderr, flush=True)


log(f"devices {jax.devices()}")
N = 1 << 18
rng = np.random.default_rng(0)
k = jnp.asarray(rng.integers(0, 100, N, dtype=np.uint32))
iota = jnp.arange(N, dtype=jnp.int32)
v64 = jnp.asarray(rng.integers(-(2**40), 2**40, N, dtype=np.int64))
f64 = jnp.asarray(rng.random(N))
b = jnp.asarray(rng.random(N) < 0.5)

for name, ops, nk in [
    ("u32key+iota", (k, iota), 1),
    ("u32key+i64pay", (k, iota, v64), 1),
    ("u32key+bool", (k, iota, b), 1),
    ("u32key+f64", (k, iota, f64), 1),
    ("full_mix", (k, iota, v64, b, f64, b), 1),
]:
    try:
        f = jax.jit(lambda *a: jax.lax.sort(a, num_keys=nk, is_stable=True)[1][::4096].sum())
        r = np.asarray(jax.device_get(f(*ops)))
        log(f"{name}: OK {r.ravel()[0]}")
    except Exception as e:
        log(f"{name}: FAIL {type(e).__name__}: {str(e)[:200]}")
