"""Multi-chip parallelism: hash-partitioned shuffle over a device mesh.

The reference repo contributes only format-parity pieces to Spark's
distributed story (murmur3 partition hashing ``murmur_hash.cu:187``,
Spark-serializable bloom filters, JCUDF rows); the exchange itself lives in
the spark-rapids plugin (UCX shuffle manager) and NCCL (SURVEY.md §2.6).
For the TPU framework the exchange is in-tree and first-class:

* **Partitioning** (:mod:`partition`): Spark's exact partition assignment —
  ``pmod(murmur3_32(keys, seed=42), P)`` — so every row lands on the same
  partition a CPU/GPU Spark cluster would pick (bit-identical shuffles).
* **Exchange** (:mod:`shuffle`): a static-shape all-to-all inside
  ``shard_map``: rows are bucketed by partition id into per-destination
  slots, exchanged with one ``lax.all_to_all`` riding the ICI mesh axis, and
  re-compacted on the receiver.  No host round-trip, no dynamic shapes.
* **Distributed operators** (:mod:`distributed`): shuffle + local relational
  ops composed under one ``jit``: distributed group-by (partial/final) and
  the mesh helpers used by the driver's multi-chip dry run.

Scaling note: one process drives the whole slice (SPMD); the mesh axis here
is the Spark-shuffle "partition" axis.  Cross-pod (DCN) scale-out uses the
same code over a larger mesh — XLA lowers the collective onto ICI within a
slice and DCN across.
"""

from .partition import regroup_order, spark_partition_id
from .shuffle import exchange, exchange_hierarchical
from .distributed import (
    broadcast_build_handle,
    data_mesh,
    distributed_group_by,
    distributed_group_by_2d,
    distributed_group_by_domain,
    distributed_broadcast_join,
    distributed_hash_join,
    distributed_hash_join_2d,
    distributed_sort,
    distributed_sort_2d,
    hierarchical_mesh,
    shard_batch,
)

__all__ = [
    "broadcast_build_handle",
    "regroup_order",
    "spark_partition_id",
    "exchange",
    "exchange_hierarchical",
    "data_mesh",
    "hierarchical_mesh",
    "distributed_group_by",
    "distributed_group_by_2d",
    "distributed_group_by_domain",
    "distributed_broadcast_join",
    "distributed_hash_join",
    "distributed_hash_join_2d",
    "distributed_sort",
    "distributed_sort_2d",
    "shard_batch",
]
