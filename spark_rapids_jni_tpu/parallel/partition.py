"""Spark-exact shuffle partition assignment.

Spark's ``HashPartitioning`` computes ``Pmod(Murmur3Hash(keys, 42), P)``;
the reference repo's murmur3 kernel exists precisely to keep this assignment
bit-identical between CPU and accelerator (reference ``murmur_hash.cu:187``,
``Hash.java``).  We reuse :func:`ops.hashing.murmur_hash3_32` and apply
Spark's ``pmod`` (non-negative remainder) on the int32 hash.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..ops.hashing import murmur_hash3_32


def spark_partition_id(
    key_columns: Sequence,
    num_partitions: int,
    row_valid=None,
) -> jnp.ndarray:
    """int32[n] partition ids in [0, P); padding rows get P (routed nowhere).

    ``row_valid`` marks occupied rows (compaction/filter padding is sent to
    the out-of-range pseudo-partition so the exchange drops it).
    """
    h = murmur_hash3_32(key_columns, seed=42).data  # int32, Spark seed
    p = jnp.int32(num_partitions)
    # Spark's pmod(h, p): jnp % already yields a non-negative remainder for
    # p > 0 (sign of divisor), which equals pmod exactly
    pid = h % p
    if row_valid is not None:
        pid = jnp.where(row_valid, pid, p)
    return pid
