"""Spark-exact shuffle partition assignment.

Spark's ``HashPartitioning`` computes ``Pmod(Murmur3Hash(keys, 42), P)``;
the reference repo's murmur3 kernel exists precisely to keep this assignment
bit-identical between CPU and accelerator (reference ``murmur_hash.cu:187``,
``Hash.java``).  We reuse :func:`ops.hashing.murmur_hash3_32` and apply
Spark's ``pmod`` (non-negative remainder) on the int32 hash.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..ops.hashing import murmur_hash3_32


def spark_partition_id(
    key_columns: Sequence,
    num_partitions: int,
    row_valid=None,
) -> jnp.ndarray:
    """int32[n] partition ids in [0, P); padding rows get P (routed nowhere).

    ``row_valid`` marks occupied rows (compaction/filter padding is sent to
    the out-of-range pseudo-partition so the exchange drops it).
    """
    h = murmur_hash3_32(key_columns, seed=42).data  # int32, Spark seed
    p = jnp.int32(num_partitions)
    # Spark's pmod(h, p): jnp % already yields a non-negative remainder for
    # p > 0 (sign of divisor), which equals pmod exactly
    pid = h % p
    if row_valid is not None:
        pid = jnp.where(row_valid, pid, p)
    return pid


# auto-engine bounds: the counting sort materializes an [n, num_slots]
# int32 one-hot + same-size cumsum transient; past these the memory/
# bandwidth cost outgrows the O(n) sort it replaces, so 'auto' falls
# back to lax.sort.  The cell cap bounds the transients to ~268MB
# (2 x 4B x 2^25 cells) regardless of row count — a 2M-row 8-partition
# exchange (18M cells) stays on the fast path, the reviewer's 2M x 64
# case (128M cells, ~1GB) does not.
_COUNTING_MAX_SLOTS = 64
_COUNTING_MAX_CELLS = 1 << 25


def regroup_order(pid, num_slots: int, engine: str = "auto",
                  secondary=None):
    """Stable permutation that orders rows by partition id — the local
    leg every shuffle pays before its all-to-all.

    Bit-identical to ``jnp.argsort(pid, stable=True)`` for ``pid`` values
    in ``[0, num_slots)`` (callers clip; ``num_slots`` includes any
    pseudo-partition used for dead rows).  Engine is a hardware fact,
    same pattern as the relational domain-aggregation engines (r4):

    * ``'sort'`` — one stable ``lax.sort``: the TPU path (a 2-operand
      sort measured ~6 ms per 2M rows on v5e, BASELINE.md r2).
    * ``'scatter'`` — counting sort: per-partition ranks from one
      ``[n, num_slots]`` one-hot cumsum, plus ONE int32 scatter to
      invert the destination map.  The CPU path: ``lax.sort`` is
      XLA-CPU's worst primitive (r4 q6 engine table), while linear
      passes and scatters are its best.  Measured r5 (prof_q95, 64K
      rows, 1-core CPU): exchange leg 17.7 ms -> counting sort ~2 ms.
    * ``'auto'`` — scatter on CPU when the one-hot stays small (few
      slots AND bounded n*num_slots cells), sort otherwise.

    ``secondary`` (optional): extra uint32 sort operands ordered AFTER
    ``pid`` — an exchange whose regroup also orders rows by their
    aggregation key words, so a downstream sort-engine ``group_by`` can
    run ``assume_grouped=True`` instead of re-sorting rows it just
    received in key order (Spark's exchange-before-HashAggregate shape,
    fused into ONE row-sized sort).  Secondary operands force the sort
    engine: a counting sort has no within-slot key order.
    """
    import jax

    n = pid.shape[0]
    pid = pid.astype(jnp.int32)
    if secondary is not None:
        engine = "sort"
    if engine == "auto":
        engine = ("scatter" if jax.default_backend() == "cpu"
                  and num_slots <= _COUNTING_MAX_SLOTS
                  and n * num_slots <= _COUNTING_MAX_CELLS else "sort")
    if engine == "sort":
        if secondary is not None:
            ops = (pid,) + tuple(secondary) + (
                jnp.arange(n, dtype=jnp.int32),)
            return jax.lax.sort(ops, num_keys=len(ops) - 1,
                                is_stable=True)[-1]
        return jnp.argsort(pid, stable=True).astype(jnp.int32)
    if engine != "scatter":
        raise ValueError(f"unknown regroup engine {engine!r}")
    slots = jnp.arange(num_slots, dtype=jnp.int32)
    oh = (pid[:, None] == slots[None, :]).astype(jnp.int32)
    within = jnp.cumsum(oh, axis=0) - oh          # rank inside partition
    counts = within[-1] + oh[-1] if n > 0 else jnp.zeros(
        (num_slots,), jnp.int32)
    offsets = jnp.cumsum(counts) - counts         # exclusive
    dest = jnp.take_along_axis(
        within + offsets[None, :],
        jnp.clip(pid, 0, num_slots - 1)[:, None], axis=1)[:, 0]
    # dest is a bijection [n] -> [n]; invert it with one scatter to get
    # the gather permutation argsort would have produced
    return jnp.zeros((n,), jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32))
