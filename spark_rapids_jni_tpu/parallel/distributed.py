"""Distributed relational operators: shuffle + local op under one ``jit``.

The composition mirrors a Spark stage boundary: map-side partition → exchange
→ reduce-side operator, except the whole thing is one SPMD program — XLA
sees the collective and the surrounding compute together and overlaps them.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..columnar.column import ColumnBatch
from ..relational.aggregate import AggSpec, group_by
from .partition import spark_partition_id
from .shuffle import exchange


def data_mesh(num_devices: Optional[int] = None, axis_name: str = "data") -> Mesh:
    """1-D mesh over the first ``num_devices`` devices (default: all)."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_batch(batch: ColumnBatch, mesh: Mesh, axis_name: str = "data") -> ColumnBatch:
    """Place a batch row-sharded over the mesh (rows % devices == 0)."""
    sharding = NamedSharding(mesh, PartitionSpec(axis_name))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)


def distributed_group_by(
    batch: ColumnBatch,
    key_names: Sequence[str],
    aggs: Sequence[AggSpec],
    mesh: Mesh,
    axis_name: str = "data",
    row_valid=None,
    capacity: Optional[int] = None,
):
    """Shuffle rows by key hash, then group each partition locally.

    Spark semantics hold globally because the shuffle is *complete*: all rows
    of one key meet on one device (the Spark-exact partition id), so local
    group results are disjoint across devices — no merge pass needed.

    Returns ``(result, num_groups, dropped)``: ``result`` is row-sharded with
    each device's groups in front of its shard, ``num_groups`` int32[P] are
    per-device group counts, ``dropped`` int32[P] counts rows lost to slot
    overflow (0 unless ``capacity`` was undersized for the key skew).
    """
    step = _group_by_step(
        mesh, axis_name, tuple(key_names), tuple(aggs), capacity,
        row_valid is None,
    )
    return step(batch) if row_valid is None else step(batch, row_valid)


@lru_cache(maxsize=None)
def _group_by_step(mesh, axis_name, key_names, aggs, capacity, all_valid):
    """Jitted shuffle+group step, cached so repeated batches don't retrace."""
    P = mesh.shape[axis_name]
    spec = PartitionSpec(axis_name)
    n_in = 1 if all_valid else 2

    # check_vma off: kernel fori_loops seed carries from replicated constants
    # (hash seeds, zero accumulators), which the varying-axis checker rejects
    # inside shard_map even though the program is correct SPMD.
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec,) * n_in, out_specs=(spec, spec, spec),
        check_vma=False,
    )
    def step(b: ColumnBatch, *rv):
        rv = jnp.ones((b.num_rows,), jnp.bool_) if all_valid else rv[0]
        pid = spark_partition_id([b[k] for k in key_names], P, rv)
        shuffled, occ, dropped = exchange(b, pid, axis_name, P, capacity)
        res, ng = group_by(shuffled, key_names, aggs, row_valid=occ)
        return res, ng[None], dropped[None]

    return jax.jit(step)


def collect_groups(result: ColumnBatch, num_groups) -> dict:
    """Host-side: concatenate each device-shard's live group rows.

    Slices the live rows out of each shard (device-side gathers on index
    arrays) before any host conversion, so cost scales with actual group
    count, not the padded P*rows_per_dev result shape.
    """
    from ..relational.gather import gather_column

    ng = np.asarray(jax.device_get(num_groups))
    P = ng.shape[0]
    rows_per_dev = result.num_rows // P
    idx = np.concatenate(
        [d * rows_per_dev + np.arange(int(ng[d])) for d in range(P)]
    ).astype(np.int32)
    idx_dev = jnp.asarray(idx)
    return {
        name: gather_column(col, idx_dev).to_pylist()
        for name, col in zip(result.names, result.columns)
    }
