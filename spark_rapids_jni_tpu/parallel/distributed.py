"""Distributed relational operators: shuffle + local op under one ``jit``.

The composition mirrors a Spark stage boundary: map-side partition → exchange
→ reduce-side operator, except the whole thing is one SPMD program — XLA
sees the collective and the surrounding compute together and overlaps them.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..columnar.column import ColumnBatch
from ..columnar.encoded import DictionaryColumn, PACKED_COLUMNS, RunLengthColumn
from ..relational.aggregate import AggSpec, group_by
from .partition import spark_partition_id
from .shuffle import exchange, plan_capacity


def data_mesh(num_devices: Optional[int] = None, axis_name: str = "data") -> Mesh:
    """1-D mesh over the first ``num_devices`` devices (default: all)."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_batch(batch: ColumnBatch, mesh: Mesh, axis_name: str = "data") -> ColumnBatch:
    """Place a batch row-sharded over the mesh (rows % devices == 0).

    Encoded columns shard by their ROW-length leaves: dictionary + canon
    are [d]-sized lookup tables every device reads, so they replicate;
    RLE's [r]-sized run leaves have no row decomposition at all, so runs
    decode here (sharding is an output boundary for a local encoding).
    """
    sharding = NamedSharding(mesh, PartitionSpec(axis_name))
    replicated = NamedSharding(mesh, PartitionSpec())
    cols = {}
    for name, col in zip(batch.names, batch.columns):
        if isinstance(col, (RunLengthColumn,) + PACKED_COLUMNS):
            # run/lane leaves have no per-row decomposition (lane i mixes
            # rows across shard boundaries), so local encodings decode at
            # the sharding boundary, same as RLE
            col = col.decode()
        if isinstance(col, DictionaryColumn) and col.dictionary is not None:
            cols[name] = dataclasses.replace(
                col,
                codes=jax.device_put(col.codes, sharding),
                validity=jax.device_put(col.validity, sharding),
                canon=jax.device_put(col.canon, replicated),
                dictionary=jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, replicated),
                    col.dictionary))
        else:
            cols[name] = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), col)
    return ColumnBatch(cols)


def distributed_group_by(
    batch: ColumnBatch,
    key_names: Sequence[str],
    aggs: Sequence[AggSpec],
    mesh: Mesh,
    axis_name: str = "data",
    row_valid=None,
    capacity: Optional[int] = None,
    ctx=None,
):
    """Shuffle rows by key hash, then group each partition locally.

    Spark semantics hold globally because the shuffle is *complete*: all rows
    of one key meet on one device (the Spark-exact partition id), so local
    group results are disjoint across devices — no merge pass needed.

    Returns ``(result, num_groups, dropped)``: ``result`` is row-sharded with
    each device's groups in front of its shard, ``num_groups`` int32[P] are
    per-device group counts, ``dropped`` int32[P] counts rows lost to slot
    overflow (always zero on the default path — with ``capacity`` unset the
    exchange runs through the lossless multi-round
    :class:`~spark_rapids_jni_tpu.shuffle.ShuffleService`, whose buffers
    spill under pressure instead of dropping; pass an explicit ``capacity``
    to force the legacy single-round fused exchange).
    """
    P = mesh.shape[axis_name]
    if capacity is None:
        from ..shuffle import ShuffleService

        res = ShuffleService(mesh, axis_name).exchange(
            batch, key_names=key_names, row_valid=row_valid, ctx=ctx)
        local = _local_group_by_step(mesh, axis_name, tuple(key_names),
                                     tuple(aggs))
        result, ng = local(res.batch, res.occupancy)
        return result, ng, jnp.zeros((P,), jnp.int32)
    step = _group_by_step(
        mesh, axis_name, tuple(key_names), tuple(aggs), capacity,
        row_valid is None,
    )
    return step(batch) if row_valid is None else step(batch, row_valid)


def plan_exchange_capacity(batch, key_names, mesh, axis_name="data",
                           row_valid=None, bucket: Optional[int] = None):
    """Host-side planning: the exact global max bucket size, rounded up to
    ``bucket`` (default: the shuffle_capacity_bucket config knob) so
    repeated batches reuse one compiled exchange."""
    if bucket is None:
        from .. import config

        bucket = config.get("shuffle_capacity_bucket")
    plan = _plan_step(mesh, axis_name, tuple(key_names), row_valid is None)
    cmax = int(np.asarray(jax.device_get(
        plan(batch) if row_valid is None else plan(batch, row_valid)))[0])
    return max(bucket, -(-cmax // bucket) * bucket)


@lru_cache(maxsize=None)
def _plan_step(mesh, axis_name, key_names, all_valid):
    P = mesh.shape[axis_name]
    spec = PartitionSpec(axis_name)
    n_in = 1 if all_valid else 2

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec,) * n_in, out_specs=spec, check_vma=False,
    )
    def plan(b, *rv):
        rv = jnp.ones((b.num_rows,), jnp.bool_) if all_valid else rv[0]
        pid = spark_partition_id([b[k] for k in key_names], P, rv)
        return plan_capacity(pid, axis_name, P)[None]

    return jax.jit(plan)


@lru_cache(maxsize=None)
def _group_by_step(mesh, axis_name, key_names, aggs, capacity, all_valid):
    """Jitted shuffle+group step, cached so repeated batches don't retrace."""
    P = mesh.shape[axis_name]
    spec = PartitionSpec(axis_name)
    n_in = 1 if all_valid else 2

    # check_vma off: kernel fori_loops seed carries from replicated constants
    # (hash seeds, zero accumulators), which the varying-axis checker rejects
    # inside shard_map even though the program is correct SPMD.
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec,) * n_in, out_specs=(spec, spec, spec),
        check_vma=False,
    )
    def step(b: ColumnBatch, *rv):
        rv = jnp.ones((b.num_rows,), jnp.bool_) if all_valid else rv[0]
        pid = spark_partition_id([b[k] for k in key_names], P, rv)
        shuffled, occ, dropped = exchange(b, pid, axis_name, P, capacity)
        res, ng = group_by(shuffled, key_names, aggs, row_valid=occ)
        return res, ng[None], dropped[None]

    return jax.jit(step)


@lru_cache(maxsize=None)
def _local_group_by_step(mesh, axis_name, key_names, aggs):
    """Reduce-side-only step for ShuffleService exchanges: the rows are
    already on their destination device (occupancy marks slot padding)."""
    spec = PartitionSpec(axis_name)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec), out_specs=(spec, spec), check_vma=False,
    )
    def step(b: ColumnBatch, occ):
        res, ng = group_by(b, key_names, aggs, row_valid=occ)
        return res, ng[None]

    return jax.jit(step)


@lru_cache(maxsize=None)
def _local_join_step(mesh, axis_name, left_on, right_on, how, out_capacity):
    """Reduce-side-only join for ShuffleService exchanges (both sides
    already routed to their key's device)."""
    from ..relational.join import hash_join

    spec = PartitionSpec(axis_name)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec,) * 4, out_specs=(spec, spec), check_vma=False,
    )
    def step(lb: ColumnBatch, locc, rb: ColumnBatch, rocc):
        out, count = hash_join(lb, rb, list(left_on), list(right_on), how,
                               capacity=out_capacity,
                               left_valid=locc, right_valid=rocc)
        return out, count[None]

    return jax.jit(step)


def collect_groups(result: ColumnBatch, num_groups) -> dict:
    """Host-side: concatenate each device-shard's live group rows.

    Slices the live rows out of each shard (device-side gathers on index
    arrays) before any host conversion, so cost scales with actual group
    count, not the padded P*rows_per_dev result shape.
    """
    from ..relational.gather import gather_column

    ng = np.asarray(jax.device_get(num_groups))
    P = ng.shape[0]
    rows_per_dev = result.num_rows // P
    idx = np.concatenate(
        [d * rows_per_dev + np.arange(int(ng[d])) for d in range(P)]
    ).astype(np.int32)
    idx_dev = jnp.asarray(idx)
    return {
        name: gather_column(col, idx_dev).to_pylist()
        for name, col in zip(result.names, result.columns)
    }


def distributed_hash_join(
    left: ColumnBatch,
    right: ColumnBatch,
    left_on: Sequence[str],
    right_on: Sequence[str],
    how: str,
    mesh: Mesh,
    axis_name: str = "data",
    capacity: Optional[int] = None,
    out_capacity: Optional[int] = None,
    ctx=None,
):
    """Shuffle both sides by key hash, then join each partition locally.

    Spark semantics hold globally because matching keys land on the same
    device (identical murmur3 partition ids on both sides).  Returns
    ``(result, counts int32[P], dropped int32[P, 2])`` — result rows are
    device-local with each shard's matches in front.  With ``capacity``
    unset both sides route through the lossless
    :class:`~spark_rapids_jni_tpu.shuffle.ShuffleService` (dropped is
    zeros by invariant); an explicit ``capacity`` forces the legacy fused
    single-round exchange.
    """
    P = mesh.shape[axis_name]
    if capacity is None:
        from ..shuffle import ShuffleService

        svc = ShuffleService(mesh, axis_name)
        lres = svc.exchange(left, key_names=left_on, ctx=ctx)
        rres = svc.exchange(right, key_names=right_on, ctx=ctx)
        step = _local_join_step(mesh, axis_name, tuple(left_on),
                                tuple(right_on), how, out_capacity)
        out, count = step(lres.batch, lres.occupancy,
                          rres.batch, rres.occupancy)
        return out, count, jnp.zeros((P, 2), jnp.int32)
    step = _join_step(mesh, axis_name, tuple(left_on), tuple(right_on), how,
                      capacity, out_capacity)
    return step(left, right)


@lru_cache(maxsize=None)
def _join_step(mesh, axis_name, left_on, right_on, how, capacity,
               out_capacity):
    from ..relational.join import hash_join

    P = mesh.shape[axis_name]
    spec = PartitionSpec(axis_name)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec), out_specs=(spec, spec, spec), check_vma=False,
    )
    def step(lb: ColumnBatch, rb: ColumnBatch):
        lv = jnp.ones((lb.num_rows,), jnp.bool_)
        rv = jnp.ones((rb.num_rows,), jnp.bool_)
        lpid = spark_partition_id([lb[k] for k in left_on], P, lv)
        rpid = spark_partition_id([rb[k] for k in right_on], P, rv)
        ls, locc, ldrop = exchange(lb, lpid, axis_name, P, capacity)
        rs, rocc, rdrop = exchange(rb, rpid, axis_name, P, capacity)
        # dead slots neither match nor emit: hash_join's left_valid zeroes
        # probe counts and right_valid nulls build keys
        out, count = hash_join(ls, rs, list(left_on), list(right_on), how,
                               capacity=out_capacity,
                               left_valid=locc, right_valid=rocc)
        return out, count[None], jnp.stack([ldrop, rdrop])[None]

    return jax.jit(step)




def broadcast_build_handle(right: ColumnBatch, ctx=None,
                           name: Optional[str] = None):
    """Register a broadcast-join build batch with the spill store under
    the owning query's ``ctx`` (TaskContext).

    Shuffled builds were already spillable
    (``relational.join.spillable_build_table``); this closes the gap the
    broadcast path left — a parked tenant's replicated build batch was
    unevictable device residency.  Pass the handle to
    :func:`distributed_broadcast_join` as ``build=``; it is fetched
    through the retry ladder per call, so between calls (the tenant
    parked) the central store may demote it device→host→disk and the
    next call promotes it back.
    """
    return right.spillable(ctx=ctx, name=name or "broadcast-build")


def distributed_broadcast_join(
    left: ColumnBatch,
    right: Optional[ColumnBatch],
    left_on: Sequence[str],
    right_on: Sequence[str],
    how: str,
    mesh: Mesh,
    axis_name: str = "data",
    dense_domain: Optional[int] = None,
    out_capacity: Optional[int] = None,
    build=None,
    ctx=None,
):
    """Broadcast-hash join: the build side is replicated to every device
    and the sharded probe side never moves — ZERO exchange, vs the
    two-sided shuffle :func:`distributed_hash_join` pays.  This is the
    plan Spark picks for every small dimension join
    (BroadcastHashJoinExec; the reference accelerates exactly those
    plans), and on a TPU mesh it removes the all-to-all entirely — the
    only collective cost is XLA replicating the (small) build batch.

    With ``dense_domain`` set and a single join key, each device's local
    join takes the dense rowid-table path
    (:func:`~spark_rapids_jni_tpu.relational.join.join_dense_or_hash`);
    otherwise the general sort-probe engine runs locally.

    Join types: inner / left / semi / anti — the ones whose output is a
    function of each (probe row, whole build side) pair, so per-shard
    results compose globally.  ``right``/``full`` emit unmatched BUILD
    rows, and a replicated build row unmatched on one shard may match on
    another — every device would append its own copy, inflating the
    global result — so those types raise here; use
    :func:`distributed_hash_join` for them.

    Returns ``(result, counts int32[P])`` — result rows are
    device-local with each shard's matches compacted in front (same
    layout contract as :func:`distributed_hash_join`, minus the
    ``dropped`` output: nothing is exchanged, so nothing can drop).

    The build side registers with the spill store under the owning
    query's TaskContext: pass ``build=`` (a handle from
    :func:`broadcast_build_handle`, reusable across calls — the parked-
    tenant eviction story) or ``ctx=`` (a per-call handle is created,
    fetched through the retry ladder, and closed after the step).  With
    neither, ``right`` is used directly (the pre-registration
    behavior).
    """
    if how in ("right", "full"):
        raise ValueError(
            f"broadcast join cannot run {how!r}: unmatched build rows "
            "are per-shard facts on a replicated build side (each device "
            "would emit its own copy) — use distributed_hash_join")
    if len(left_on) != len(right_on):
        raise ValueError("left_on/right_on length mismatch")
    owned = None
    if build is None and ctx is not None:
        if right is None:
            raise ValueError("ctx= registration needs the right batch")
        owned = build = broadcast_build_handle(right, ctx=ctx)
    try:
        if build is not None:
            from ..mem.executor import run_with_retry

            # pin across the fetch AND the step: the central store must
            # not demote the build tree while the collective that
            # replicates it is in flight
            with build.pinned():
                right = run_with_retry(build.get)
                step = _bcast_join_step(
                    mesh, axis_name, tuple(left_on), tuple(right_on), how,
                    None if dense_domain is None else int(dense_domain),
                    out_capacity)
                return step(left, right)
        if right is None:
            raise ValueError("need either right= or build=")
        step = _bcast_join_step(
            mesh, axis_name, tuple(left_on), tuple(right_on), how,
            None if dense_domain is None else int(dense_domain),
            out_capacity)
        return step(left, right)
    finally:
        if owned is not None:
            owned.close()


@lru_cache(maxsize=None)
def _bcast_join_step(mesh, axis_name, left_on, right_on, how, dense_domain,
                     out_capacity):
    from ..relational.join import hash_join, join_dense_or_hash

    spec = PartitionSpec(axis_name)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, PartitionSpec()),  # build side replicated
        out_specs=(spec, spec), check_vma=False,
    )
    def step(lb: ColumnBatch, rb: ColumnBatch):
        if (dense_domain is not None and len(left_on) == 1
                and len(right_on) == 1):
            out, count = join_dense_or_hash(
                lb, rb, left_on[0], right_on[0], dense_domain, how,
                capacity=out_capacity)
        else:
            out, count = hash_join(lb, rb, list(left_on), list(right_on),
                                   how, capacity=out_capacity)
        return out, count[None]

    return jax.jit(step)


def _sample_splitters(batch: ColumnBatch, key_names, P: int):
    """Host-side sample-sort splitter plan shared by the 1-D and 2-D
    sorts: strided sample of the radix key words, P-1 picks."""
    from ..relational import keys as K

    kcols = [batch[k] for k in key_names]
    karr = K.batch_radix_keys(kcols, equality=False, nulls_first=True)
    n = karr[0].shape[0]
    sample_n = min(n, max(P * 64, 1024))
    stride = max(n // sample_n, 1)
    words = np.stack(
        [np.asarray(jax.device_get(a[::stride])) for a in karr], axis=1)
    order = np.lexsort(words[:, ::-1].T)
    m = words.shape[0]
    picks = order[np.linspace(0, m - 1, P + 1).astype(np.int64)[1:-1]]
    return jnp.asarray(words[picks])  # [P-1, nw]


def _local_sort_with_occ(shuffled: ColumnBatch, occ, key_names):
    """Local sort with dead shuffle slots last (shared epilogue)."""
    from ..columnar import types as T
    from ..columnar.column import Column
    from ..relational.sort import SortKey, sort_by

    aug = shuffled.with_column(
        "__occ", Column(occ.astype(jnp.int32), jnp.ones_like(occ), T.INT32))
    out = sort_by(aug, [SortKey("__occ", ascending=False)]
                  + [SortKey(k) for k in key_names])
    occ_sorted = out["__occ"].data == 1
    return out.select([n for n in out.names if n != "__occ"]), occ_sorted


def distributed_sort(
    batch: ColumnBatch,
    key_names: Sequence[str],
    mesh: Mesh,
    axis_name: str = "data",
    capacity: Optional[int] = None,
    ctx=None,
):
    """Global sort: range-partition by sampled splitters, then sort locally.

    Returns ``(result, occupancy bool rows, dropped)`` — device d holds the
    d-th global key range in sorted order (with slot padding interleaved).
    Splitters are sampled on the host from the first key column's radix
    words, the classic sample-sort plan pass.

    With ``capacity`` unset the range exchange routes through the
    lossless multi-round :class:`~spark_rapids_jni_tpu.shuffle.ShuffleService`
    (spillable buffers, skew-aware rounds, exact accounting — ``dropped``
    is zero by construction, and ``ctx`` charges the round buffers to the
    task's arena); pass an explicit ``capacity`` to force the legacy
    single-round fused exchange.
    """
    P = mesh.shape[axis_name]
    splitters = _sample_splitters(batch, key_names, P)

    if capacity is None:
        from ..shuffle import ShuffleService

        # _range_pid is elementwise over rows against the replicated
        # splitters, so it runs straight on the row-sharded globals
        pid = _range_pid(batch, key_names, splitters, P)
        res = ShuffleService(mesh, axis_name).exchange(
            batch, pid=pid, ctx=ctx)
        local = _local_sort_step(mesh, axis_name, tuple(key_names))
        out, occ_sorted = local(res.batch, res.occupancy)
        return out, occ_sorted, jnp.zeros((P,), jnp.int32)
    step = _sort_step(mesh, axis_name, tuple(key_names), splitters.shape,
                      capacity)
    return step(batch, splitters)


def _range_pid(b, key_names, splitters, P):
    from ..relational import keys as K

    karr = K.batch_radix_keys([b[k] for k in key_names], equality=False,
                              nulls_first=True)
    R = karr[0].shape[0]
    pid = jnp.zeros((R,), jnp.int32)
    for s in range(P - 1):
        gt = jnp.zeros((R,), jnp.bool_)
        lt = jnp.zeros((R,), jnp.bool_)
        for w, a in enumerate(karr):
            sw = splitters[s, w]
            gt = gt | (~lt & (a > sw))
            lt = lt | (~gt & (a < sw))
        pid = pid + gt.astype(jnp.int32)
    return pid


@lru_cache(maxsize=None)
def _local_sort_step(mesh, axis_name, key_names):
    """Reduce-side local sort over service-exchanged rows (dead shuffle
    slots sort last via the shared occupancy epilogue)."""
    spec = PartitionSpec(axis_name)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec),
             out_specs=(spec, spec), check_vma=False)
    def step(shuffled: ColumnBatch, occ):
        return _local_sort_with_occ(shuffled, occ, key_names)

    return jax.jit(step)


@lru_cache(maxsize=None)
def _sort_step(mesh, axis_name, key_names, splitter_shape, capacity):
    P = mesh.shape[axis_name]
    spec = PartitionSpec(axis_name)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, PartitionSpec()),
             out_specs=(spec, spec, spec), check_vma=False)
    def step(b, splitters):
        pid = _range_pid(b, key_names, splitters, P)
        shuffled, occ, dropped = exchange(b, pid, axis_name, P, capacity)
        out, occ_sorted = _local_sort_with_occ(shuffled, occ, key_names)
        return out, occ_sorted, dropped[None]

    return jax.jit(step)


# ---------------------------------------------------------------------------
# hierarchical (multi-host) mesh: DCN x ICI
# ---------------------------------------------------------------------------

def hierarchical_mesh(n_hosts: int, chips_per_host: int,
                      dcn_axis: str = "dcn", ici_axis: str = "ici") -> Mesh:
    """(hosts, chips) mesh: the outer axis maps across hosts (DCN), the
    inner across each host's chips (ICI).  On real multi-host TPU the
    device order from ``jax.devices()`` is already host-major, so the
    reshape lines the axes up with the physical links."""
    devs = jax.devices()[: n_hosts * chips_per_host]
    if len(devs) < n_hosts * chips_per_host:
        raise RuntimeError(
            f"need {n_hosts * chips_per_host} devices, have {len(devs)}")
    return Mesh(np.array(devs).reshape(n_hosts, chips_per_host),
                (dcn_axis, ici_axis))


def _hier_count_matrix(pid, P: int):
    """Host-side ``[P senders, P destinations]`` count matrix from a
    row-sharded pid array (rows are sender-major over the flattened
    mesh, so the sender index is just the row block)."""
    a = np.asarray(jax.device_get(pid)).reshape(P, -1)
    counts = np.zeros((P, P), np.int64)
    for s in range(P):
        row = a[s]
        counts[s] = np.bincount(row[(row >= 0) & (row < P)],
                                minlength=P)[:P]
    return counts


def _plan_2d_capacities(pid, H: int, D: int, capacity_dcn, capacity_ici):
    """Resolve per-hop capacities: keep explicit values, plan the rest
    from the observed count matrix (plan_hierarchical — per-hop buckets
    instead of the flat ``rows_per_device`` / ``H * C_dcn`` worst case)."""
    from ..shuffle import plan_hierarchical

    if capacity_dcn is not None and capacity_ici is not None:
        return capacity_dcn, capacity_ici
    hplan = plan_hierarchical(_hier_count_matrix(pid, H * D), H, D)
    if capacity_dcn is None:
        capacity_dcn = hplan.capacity_dcn
        if capacity_ici is None:
            capacity_ici = hplan.capacity_ici
    if capacity_ici is None:
        # explicit hop-one override without a hop-two one keeps the
        # legacy always-lossless coupling
        capacity_ici = H * capacity_dcn
    return capacity_dcn, capacity_ici


def distributed_group_by_2d(
    batch: ColumnBatch,
    key_names: Sequence[str],
    aggs: Sequence[AggSpec],
    mesh: Mesh,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    capacity_dcn: Optional[int] = None,
    capacity_ici: Optional[int] = None,
):
    """Group-by over a multi-host mesh via the two-hop hierarchical shuffle
    (rows cross DCN once, ICI once; see shuffle.exchange_hierarchical).

    Unset capacities are PLANNED: one elementwise pid pass feeds
    :func:`~spark_rapids_jni_tpu.shuffle.plan_hierarchical`, which sizes
    each hop's slot grid to its observed max bucket (bucket-rounded,
    overridable via ``shuffle_capacity_dcn`` / ``shuffle_capacity_ici``)
    instead of the flat worst case — multi-host meshes stop paying
    ``rows_per_device`` DCN slots and ``n_hosts * C_dcn`` ICI slots for
    uniformly hashed keys.  Pass explicit capacities to pin the grids.
    """
    H, D = mesh.shape[dcn_axis], mesh.shape[ici_axis]
    if capacity_dcn is None or capacity_ici is None:
        pid = spark_partition_id([batch[k] for k in key_names], H * D)
        capacity_dcn, capacity_ici = _plan_2d_capacities(
            pid, H, D, capacity_dcn, capacity_ici)
    step = _group_by_2d_step(mesh, dcn_axis, ici_axis, tuple(key_names),
                             tuple(aggs), capacity_dcn, capacity_ici)
    return step(batch)


@lru_cache(maxsize=None)
def _group_by_2d_step(mesh, dcn_axis, ici_axis, key_names, aggs,
                      capacity_dcn, capacity_ici):
    from .shuffle import exchange_hierarchical

    H, D = mesh.shape[dcn_axis], mesh.shape[ici_axis]
    P = H * D
    spec = PartitionSpec((dcn_axis, ici_axis))

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec,), out_specs=(spec, spec, spec), check_vma=False,
    )
    def step(b: ColumnBatch):
        rv = jnp.ones((b.num_rows,), jnp.bool_)
        pid = spark_partition_id([b[k] for k in key_names], P, rv)
        shuffled, occ, dropped = exchange_hierarchical(
            b, pid, dcn_axis, ici_axis, H, D, capacity_dcn, capacity_ici)
        res, ng = group_by(shuffled, key_names, aggs, row_valid=occ)
        return res, ng[None], dropped[None]

    return jax.jit(step)


def distributed_hash_join_2d(
    left: ColumnBatch,
    right: ColumnBatch,
    left_on: Sequence[str],
    right_on: Sequence[str],
    how: str,
    mesh: Mesh,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    capacity_dcn: Optional[int] = None,
    out_capacity: Optional[int] = None,
):
    """Hash join over a multi-host mesh via the two-hop shuffle (both
    sides routed by the same Spark-exact partition ids, so matching keys
    still meet on one chip).  With ``capacity_dcn`` unset both sides'
    count matrices feed the hierarchical planner and each hop's grid is
    sized to the larger side's observed bucket (see
    :func:`distributed_group_by_2d`)."""
    H, D = mesh.shape[dcn_axis], mesh.shape[ici_axis]
    P = H * D
    if capacity_dcn is None:
        lpid = spark_partition_id([left[k] for k in left_on], P)
        rpid = spark_partition_id([right[k] for k in right_on], P)
        lc_dcn, lc_ici = _plan_2d_capacities(lpid, H, D, None, None)
        rc_dcn, rc_ici = _plan_2d_capacities(rpid, H, D, None, None)
        capacity_dcn = max(lc_dcn, rc_dcn)
        capacity_ici = max(lc_ici, rc_ici)
    else:
        capacity_ici = H * capacity_dcn
    step = _join_2d_step(mesh, dcn_axis, ici_axis, tuple(left_on),
                         tuple(right_on), how, capacity_dcn, capacity_ici,
                         out_capacity)
    return step(left, right)


@lru_cache(maxsize=None)
def _join_2d_step(mesh, dcn_axis, ici_axis, left_on, right_on, how,
                  capacity_dcn, capacity_ici, out_capacity):
    from ..relational.join import hash_join
    from .shuffle import exchange_hierarchical

    H, D = mesh.shape[dcn_axis], mesh.shape[ici_axis]
    P = H * D
    spec = PartitionSpec((dcn_axis, ici_axis))

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec), out_specs=(spec, spec, spec), check_vma=False,
    )
    def step(lb: ColumnBatch, rb: ColumnBatch):
        lv = jnp.ones((lb.num_rows,), jnp.bool_)
        rv = jnp.ones((rb.num_rows,), jnp.bool_)
        lpid = spark_partition_id([lb[k] for k in left_on], P, lv)
        rpid = spark_partition_id([rb[k] for k in right_on], P, rv)
        ls, locc, ldrop = exchange_hierarchical(
            lb, lpid, dcn_axis, ici_axis, H, D, capacity_dcn,
            capacity_ici)
        rs, rocc, rdrop = exchange_hierarchical(
            rb, rpid, dcn_axis, ici_axis, H, D, capacity_dcn,
            capacity_ici)
        out, count = hash_join(ls, rs, list(left_on), list(right_on), how,
                               capacity=out_capacity,
                               left_valid=locc, right_valid=rocc)
        return out, count[None], jnp.stack([ldrop, rdrop])[None]

    return jax.jit(step)


def distributed_sort_2d(
    batch: ColumnBatch,
    key_names: Sequence[str],
    mesh: Mesh,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    capacity_dcn: Optional[int] = None,
):
    """Global sample-sort over a multi-host mesh: same splitter plan as
    :func:`distributed_sort` with P = hosts * chips range partitions,
    routed through the two-hop exchange.  Device (h, d) holds global
    range ``h * chips + d`` in sorted order.  With ``capacity_dcn``
    unset the range pids feed the hierarchical planner so each hop's
    grid tracks its observed bucket (a well-split sort is near-uniform,
    so this beats the flat ``rows // P`` worst case on multi-host
    meshes)."""
    H, D = mesh.shape[dcn_axis], mesh.shape[ici_axis]
    P = H * D
    splitters = _sample_splitters(batch, key_names, P)

    if capacity_dcn is None:
        # elementwise over rows against replicated splitters: runs
        # straight on the row-sharded globals, same as distributed_sort
        pid = _range_pid(batch, key_names, splitters, P)
        capacity_dcn, capacity_ici = _plan_2d_capacities(
            pid, H, D, None, None)
    else:
        capacity_ici = H * capacity_dcn
    step = _sort_2d_step(mesh, dcn_axis, ici_axis, tuple(key_names),
                         splitters.shape, capacity_dcn, capacity_ici)
    return step(batch, splitters)


@lru_cache(maxsize=None)
def _sort_2d_step(mesh, dcn_axis, ici_axis, key_names, splitter_shape,
                  capacity_dcn, capacity_ici):
    from .shuffle import exchange_hierarchical

    H, D = mesh.shape[dcn_axis], mesh.shape[ici_axis]
    P = H * D
    spec = PartitionSpec((dcn_axis, ici_axis))

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, PartitionSpec()),
             out_specs=(spec, spec, spec), check_vma=False)
    def step(b, splitters):
        pid = _range_pid(b, key_names, splitters, P)
        shuffled, occ, dropped = exchange_hierarchical(
            b, pid, dcn_axis, ici_axis, H, D, capacity_dcn,
            capacity_ici)
        out, occ_sorted = _local_sort_with_occ(shuffled, occ, key_names)
        return out, occ_sorted, dropped[None]

    return jax.jit(step)


def distributed_group_by_onehot(
    batch: ColumnBatch,
    key_name: str,
    aggs: Sequence[AggSpec],
    domain: int,
    mesh: Mesh,
    axis_name: str = "data",
    capacity: Optional[int] = None,
):
    """Distributed MXU-path aggregation: shuffle by key hash, then the
    one-hot matmul aggregate locally (relational.aggregate.group_by_onehot).

    Returns ``(result, num_groups int32[P], dropped int32[P],
    overflow bool[P])`` — overflow means some non-null key fell outside
    ``[0, domain)`` on that device and the caller must fall back to the
    sort-scan path.
    """
    if capacity is None:
        capacity = plan_exchange_capacity(batch, [key_name], mesh, axis_name)
    step = _group_by_onehot_step(mesh, axis_name, key_name, tuple(aggs),
                                 int(domain), capacity)
    return step(batch)


def distributed_group_by_domain(
    batch: ColumnBatch,
    key_name: str,
    aggs: Sequence[AggSpec],
    domain: int,
    mesh: Mesh,
    axis_name: str = "data",
    row_valid=None,
    engine: str = "auto",
    float_mode: str = "f64",
):
    """Map-side combine: NO row shuffle at all for small-domain keys.

    Each device reduces its local rows into additive ``[K+1]``-bucket
    partials (:func:`relational.aggregate._domain_partials` — the MXU
    one-hot contraction on TPU, segment sums on CPU), then ONE ``psum``
    over the mesh merges them and every device finalizes the identical
    replicated result.  The collective payload is O(domain x aggs)
    scalars instead of the row set — for the q6 shape (2M rows/device,
    domain 100) that is ~5 KB over ICI versus ~40 MB of all-to-all row
    exchange, and there is no capacity planning, no skew sensitivity,
    and no dropped-row accounting.  This is Spark's map-side combine
    (partial aggregation before the exchange) taken to its limit: the
    exchange degenerates into an all-reduce.

    Supports sum/count/mean over int/float/decimal128 (the additive
    ops); min/max stay on :func:`distributed_group_by`.  Returns
    ``(result, num_groups, overflow)`` — all REPLICATED across the mesh
    (every device holds the full group table; ``overflow`` True means
    some key fell outside ``[0, domain)`` somewhere and the caller must
    fall back to the shuffling path).
    """
    step = _group_by_domain_step(
        mesh, axis_name, key_name, tuple(aggs), int(domain),
        row_valid is None, engine, float_mode)
    return step(batch) if row_valid is None else step(batch, row_valid)


@lru_cache(maxsize=None)
def _group_by_domain_step(mesh, axis_name, key_name, aggs, domain,
                          all_valid, engine, float_mode):
    from ..relational.aggregate import _domain_partials, _finalize_domain

    spec = PartitionSpec(axis_name)
    rep = PartitionSpec()
    n_in = 1 if all_valid else 2

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec,) * n_in, out_specs=(rep, rep, rep),
        check_vma=False,
    )
    def step(b: ColumnBatch, *rv):
        rv = jnp.ones((b.num_rows,), jnp.bool_) if all_valid else rv[0]
        parts, ovf = _domain_partials(
            b, key_name, list(aggs), domain, row_valid=rv, engine=engine,
            float_mode=float_mode)
        parts = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, axis_name), parts)
        ovf = jax.lax.psum(ovf.astype(jnp.int32), axis_name) > 0
        res, ng = _finalize_domain(b, key_name, domain, list(aggs), parts)
        return res, ng, ovf

    return jax.jit(step)


@lru_cache(maxsize=None)
def _group_by_onehot_step(mesh, axis_name, key_name, aggs, domain, capacity):
    from ..relational.aggregate import group_by_onehot

    P = mesh.shape[axis_name]
    spec = PartitionSpec(axis_name)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec,), out_specs=(spec, spec, spec, spec),
        check_vma=False,
    )
    def step(b: ColumnBatch):
        rv = jnp.ones((b.num_rows,), jnp.bool_)
        pid = spark_partition_id([b[key_name]], P, rv)
        shuffled, occ, dropped = exchange(b, pid, axis_name, P, capacity)
        res, ng, overflow = group_by_onehot(
            shuffled, key_name, list(aggs), domain, row_valid=occ)
        return res, ng[None], dropped[None], overflow[None]

    return jax.jit(step)
