"""Static-shape all-to-all row exchange (the shuffle data plane).

Runs *inside* ``shard_map``: every device holds a local batch of R rows and
a partition id per row; after :func:`exchange` every device holds the rows
whose partition id names it.  The XLA-friendly formulation:

1. stable-sort local rows by destination (padding keys sort last),
2. gather rows into a ``[P, C]`` slot grid (destination-major; C slots per
   destination, unfilled slots are null rows),
3. one ``lax.all_to_all`` over the mesh axis transposes the grid globally —
   device d receives slot-row p = what device p bucketed for d,
4. the receiver keeps the ``[P*C]`` layout plus an occupancy mask; callers
   pass that mask to group_by/compact downstream.

C (``capacity``) is the static per-(sender,destination) slot count — the TPU
analogue of the reference's fixed 2GB batch discipline
(``row_conversion.cu:93-98``): shapes are decided before the data is seen.
Rows beyond C for one destination are dropped and counted in ``dropped``
(callers size C for their skew; C = R is always lossless).

Out-of-range partition ids (``pid < 0`` or ``pid > P``) are routed to the
null pseudo-partition P and counted in ``dropped`` — they used to be
clamped silently, which DELIVERED negative ids to partition 0 and lost
``pid > P`` rows without a trace.  The :mod:`~spark_rapids_jni_tpu.shuffle`
service raises on them under the ``shuffle_strict_pids`` flag and counts
them in its metrics otherwise.

For lossless exchanges of arbitrary skew without quadratic slot memory,
use :class:`spark_rapids_jni_tpu.shuffle.ShuffleService` — it runs this
exchange in multiple planned rounds with spillable buffers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..columnar.column import ColumnBatch
from ..relational.gather import gather_batch


def route_out_of_range(pid, num_partitions: int):
    """Route ids outside ``[0, P]`` to the null partition P; return
    ``(pid int32, n_oob int32)``.  A negative id must never be delivered
    (the old clip sent it to partition 0) and an id past P must be
    counted, not silently absorbed into the padding slot."""
    pid = pid.astype(jnp.int32)
    P = jnp.int32(num_partitions)
    oob = (pid < 0) | (pid > P)
    return jnp.where(oob, P, pid), oob.sum(dtype=jnp.int32)


def exchange(
    batch: ColumnBatch,
    pid,
    axis_name: str,
    num_partitions: int,
    capacity: int | None = None,
):
    """All-to-all rows by partition id. Must run inside ``shard_map``.

    ``pid`` is int32[R] in [0, P]; P routes nowhere (padding).  Returns
    ``(out_batch [P*C rows], occupancy bool[P*C], dropped int32)``.
    ``dropped`` counts rows lost to slot overflow PLUS out-of-range ids
    (< 0 or > P), which are routed to the null partition, never delivered.
    """
    R = batch.num_rows
    P = num_partitions
    C = R if capacity is None else capacity

    pid, n_oob = route_out_of_range(pid, P)
    # platform-aware stable regroup (counting sort on CPU, lax.sort on
    # accelerators) — the r5 prof_q95 breakdown showed this local leg
    # dominating the exchange cost on XLA-CPU
    from .partition import regroup_order

    perm = regroup_order(pid, P + 1)
    pid_sorted = jnp.take(pid, perm)
    counts = jax.ops.segment_sum(
        jnp.ones((R,), jnp.int32), pid_sorted, num_segments=P + 1,
        indices_are_sorted=True,
    )[:P]
    offsets = jnp.cumsum(counts) - counts  # exclusive

    # destination-major slot grid: slot (p, c) <- sorted row offsets[p] + c
    p_ids = jnp.repeat(jnp.arange(P, dtype=jnp.int32), C)
    c_ids = jnp.tile(jnp.arange(C, dtype=jnp.int32), P)
    slot_occ = c_ids < jnp.take(counts, p_ids)
    src = jnp.take(offsets, p_ids) + c_ids
    send_idx = jnp.take(perm, jnp.clip(src, 0, max(R - 1, 0)))
    send = gather_batch(batch, send_idx, valid=slot_occ)
    dropped = jnp.maximum(counts - C, 0).sum(dtype=jnp.int32) + n_oob

    def a2a(x):
        grid = x.reshape((P, C) + x.shape[1:])
        out = jax.lax.all_to_all(grid, axis_name, split_axis=0, concat_axis=0)
        return out.reshape((P * C,) + x.shape[1:])

    out_batch = jax.tree_util.tree_map(a2a, send)
    occupancy = a2a(slot_occ)
    return out_batch, occupancy, dropped


def plan_capacity(pid, axis_name: str, num_partitions: int):
    """Per-device max (sender,destination) bucket size, maxed over the mesh.

    The lossless-shuffle planning pass: run this (inside ``shard_map``)
    first, fetch the scalar, and size :func:`exchange`'s static ``capacity``
    with it — shapes stay static, no rows can drop.  The host round-trip is
    the TPU analogue of the reference's size-then-write two-pass kernels.
    """
    R = pid.shape[0]
    P = num_partitions
    pid, _ = route_out_of_range(pid, P)
    counts = jax.ops.segment_sum(
        jnp.ones((R,), jnp.int32), pid, num_segments=P + 1
    )[:P]
    local_max = counts.max()
    return jax.lax.pmax(local_max, axis_name)


def exchange_hierarchical(
    batch: ColumnBatch,
    pid,
    dcn_axis: str,
    ici_axis: str,
    n_hosts: int,
    n_chips: int,
    capacity_dcn: int | None = None,
    capacity_ici: int | None = None,
):
    """Two-hop all-to-all over a (dcn, ici) mesh: rows cross the slow DCN
    link exactly once (to the destination host, same chip index), then the
    fast ICI once (to the destination chip).  This is the multi-host form
    of the reference's single-exchange shuffle — the global partition id
    ``p = host * n_chips + chip`` is still the Spark-exact murmur3 pmod id,
    so results are bit-identical to the flat exchange.

    Must run inside ``shard_map`` over both axes.  Returns
    ``(out_batch, occupancy, dropped)`` like :func:`exchange`; ``dropped``
    sums both hops.
    """
    from ..columnar import types as T
    from ..columnar.column import Column

    if "__pid__" in batch.names:
        raise ValueError("'__pid__' is reserved by exchange_hierarchical")
    P = n_hosts * n_chips
    pid, n_oob = route_out_of_range(pid, P)
    carried = batch.with_column("__pid__", Column(pid, pid < P, T.INT32))

    host_dst = jnp.where(pid < P, pid // n_chips, n_hosts)
    out_a, occ_a, drop_a = exchange(
        carried, host_dst, dcn_axis, n_hosts, capacity_dcn)

    # the routing column has done its job after hop one — don't pay ICI
    # bandwidth shuffling it again
    pid_a = out_a["__pid__"].data
    chip_dst = jnp.where(occ_a, pid_a % n_chips, n_chips)
    out_b, occ_b, drop_b = exchange(
        out_a.select(list(batch.names)), chip_dst, ici_axis, n_chips,
        capacity_ici)
    # OOB ids were routed to the null partition before hop one, so they
    # surface as padding (never as hop drops) — count them explicitly
    return out_b, occ_b, drop_a + drop_b + n_oob
