"""Always-attachable profiler with the reference's lifecycle + writer API.

Reference: the CUPTI-based profiler (``Profiler.java:37-124``: init/start/
stop/shutdown with a ``DataWriter`` sink; ``profiler_serializer.cpp`` emits
size-prefixed flatbuffer records; ``spark_rapids_profile_converter`` turns
captures into JSON offline).  The TPU equivalent wraps the XLA profiler
(xplane/trace collection via ``jax.profiler``):

* :class:`Profiler` — ``init(writer)`` / ``start()`` / ``stop()`` /
  ``shutdown()``.  Each start/stop cycle collects a trace and streams it to
  the writer as size-prefixed framed chunks, so a Spark executor can route
  profiles to distributed storage exactly like the reference's
  ``DataWriter`` path.
* :func:`convert_profile` — the offline converter: reads a captured
  stream back into per-event records (kernel/op name, start, duration),
  decoding the Chrome-trace JSON the XLA profiler produces.

Frame format: ``b"SPTPUPRF" u32(version) [u32(len) bytes]*`` — the same
size-prefixed-records idea as ``profiler.fbs`` (``ProfileHeader`` magic +
``ActivityRecords``), carrying trace files instead of CUPTI activities.
"""

from __future__ import annotations

import glob
import gzip
import io
import json
import os
import shutil
import struct
import tempfile
import threading
from typing import Callable, List, Optional

MAGIC = b"SPTPUPRF"
VERSION = 1


class ProfilerError(RuntimeError):
    pass


class Profiler:
    """Process-wide profiler facade (mirrors Profiler.java's static API)."""

    _lock = threading.Lock()
    _writer: Optional[Callable[[bytes], None]] = None
    _dir: Optional[str] = None
    _running = False
    _initialized = False
    _wrote_header = False

    @classmethod
    def init(cls, data_writer: Callable[[bytes], None]):
        """Install the sink; profiling stays off until :meth:`start`."""
        with cls._lock:
            if cls._initialized:
                raise ProfilerError("profiler already initialized")
            cls._writer = data_writer
            cls._dir = tempfile.mkdtemp(prefix="sptpu_prof_")
            cls._initialized = True
            cls._wrote_header = False

    @classmethod
    def start(cls):
        """Begin collecting (cuProfilerStart equivalent)."""
        import jax

        with cls._lock:
            if not cls._initialized:
                raise ProfilerError("profiler not initialized")
            if cls._running:
                return
            jax.profiler.start_trace(cls._dir)
            cls._running = True

    @classmethod
    def stop(cls):
        """Stop collecting and flush the capture to the writer."""
        import jax

        with cls._lock:
            if not cls._initialized or not cls._running:
                return
            jax.profiler.stop_trace()
            cls._running = False
            cls._flush_locked()

    @classmethod
    def shutdown(cls):
        """Stop if needed, flush, and release the sink."""
        with cls._lock:
            if not cls._initialized:
                return
            if cls._running:
                import jax

                jax.profiler.stop_trace()
                cls._running = False
                cls._flush_locked()
            shutil.rmtree(cls._dir, ignore_errors=True)
            cls._writer = None
            cls._dir = None
            cls._initialized = False

    # -- internals -------------------------------------------------------
    @classmethod
    def _flush_locked(cls):
        buf = io.BytesIO()
        if not cls._wrote_header:
            buf.write(MAGIC)
            buf.write(struct.pack("<I", VERSION))
            cls._wrote_header = True
        for path in sorted(
            glob.glob(os.path.join(cls._dir, "**", "*"), recursive=True)
        ):
            if not os.path.isfile(path):
                continue
            name = os.path.relpath(path, cls._dir).encode()
            with open(path, "rb") as f:
                payload = f.read()
            rec = struct.pack("<I", len(name)) + name + payload
            buf.write(struct.pack("<I", len(rec)))
            buf.write(rec)
            os.remove(path)
        data = buf.getvalue()
        if data:
            cls._writer(data)


class FileWriter:
    """A DataWriter that appends frames to one capture file."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")

    def __call__(self, data: bytes):
        self._f.write(data)
        self._f.flush()

    def close(self):
        self._f.close()


def _iter_frames(data: bytes):
    off = 0
    if data[:8] == MAGIC:
        off = 12
    while off + 4 <= len(data):
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        rec = data[off: off + ln]
        off += ln
        (nlen,) = struct.unpack_from("<I", rec, 0)
        name = rec[4: 4 + nlen].decode()
        payload = rec[4 + nlen:]
        yield name, payload


def convert_profile(capture_path: str) -> List[dict]:
    """Offline converter: capture stream -> flat event records.

    Equivalent role to ``spark_rapids_profile_converter`` (flatbuffer ->
    JSON); decodes the Chrome-trace JSON (``*.trace.json.gz``) inside the
    capture into ``{"name", "ts_us", "dur_us", "tid", "pid"}`` records.
    """
    with open(capture_path, "rb") as f:
        data = f.read()
    if data[:8] != MAGIC:
        raise ProfilerError(f"{capture_path}: not a SPTPUPRF capture")
    events: List[dict] = []
    for name, payload in _iter_frames(data):
        if name.endswith(".trace.json.gz"):
            doc = json.loads(gzip.decompress(payload))
            for ev in doc.get("traceEvents", []):
                if ev.get("ph") == "X" and "name" in ev:
                    events.append(
                        {
                            "name": ev["name"],
                            "ts_us": ev.get("ts", 0),
                            "dur_us": ev.get("dur", 0),
                            "pid": ev.get("pid"),
                            "tid": ev.get("tid"),
                        }
                    )
    return events


def list_capture_files(capture_path: str) -> List[str]:
    """Names of the raw trace artifacts inside a capture (xplane etc.)."""
    with open(capture_path, "rb") as f:
        data = f.read()
    return [name for name, _ in _iter_frames(data)]
