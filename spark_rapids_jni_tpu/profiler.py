"""Always-attachable profiler with the reference's lifecycle + writer API.

Reference: the CUPTI-based profiler (``Profiler.java:37-124``: init/start/
stop/shutdown with a ``DataWriter`` sink; ``profiler_serializer.cpp`` emits
size-prefixed flatbuffer records; ``spark_rapids_profile_converter`` turns
captures into JSON offline).  The TPU equivalent wraps the XLA profiler
(xplane/trace collection via ``jax.profiler``):

* :class:`Profiler` — ``init(writer)`` / ``start()`` / ``stop()`` /
  ``shutdown()``.  Each start/stop cycle collects a trace and streams it to
  the writer as size-prefixed framed chunks, so a Spark executor can route
  profiles to distributed storage exactly like the reference's
  ``DataWriter`` path.
* :func:`convert_profile` — the offline converter: reads a captured
  stream back into per-event records (kernel/op name, start, duration),
  decoding the Chrome-trace JSON the XLA profiler produces.

Frame format: ``b"SPTPUPRF" u32(version) [u32(len) bytes]*`` — the same
size-prefixed-records idea as ``profiler.fbs`` (``ProfileHeader`` magic +
``ActivityRecords``), carrying trace files instead of CUPTI activities.
When a fault-injection schedule fired during the window, one synthetic
``faultinj.fired.json`` frame carries :func:`faultinj.fired_log` so the
capture explains its own anomalies.
"""

from __future__ import annotations

import glob
import gzip
import io
import json
import os
import shutil
import struct
import tempfile
import threading
from typing import Callable, List, Optional

MAGIC = b"SPTPUPRF"
VERSION = 1


class ProfilerError(RuntimeError):
    pass


class Profiler:
    """Process-wide profiler facade (mirrors Profiler.java's static API)."""

    _lock = threading.Lock()
    _writer: Optional[Callable[[bytes], None]] = None
    _dir: Optional[str] = None
    _running = False
    _initialized = False
    _wrote_header = False

    @classmethod
    def init(cls, data_writer: Callable[[bytes], None]):
        """Install the sink; profiling stays off until :meth:`start`."""
        with cls._lock:
            if cls._initialized:
                raise ProfilerError("profiler already initialized")
            cls._writer = data_writer
            cls._dir = tempfile.mkdtemp(prefix="sptpu_prof_")
            cls._initialized = True
            cls._wrote_header = False

    @classmethod
    def start(cls):
        """Begin collecting (cuProfilerStart equivalent)."""
        import jax

        with cls._lock:
            if not cls._initialized:
                raise ProfilerError("profiler not initialized")
            if cls._running:
                return
            jax.profiler.start_trace(cls._dir)
            cls._running = True

    @classmethod
    def stop(cls):
        """Stop collecting and flush the capture to the writer."""
        import jax

        with cls._lock:
            if not cls._initialized or not cls._running:
                return
            jax.profiler.stop_trace()
            cls._running = False
            cls._flush_locked()

    @classmethod
    def shutdown(cls):
        """Stop if needed, flush, and release the sink."""
        with cls._lock:
            if not cls._initialized:
                return
            if cls._running:
                import jax

                jax.profiler.stop_trace()
                cls._running = False
                cls._flush_locked()
            shutil.rmtree(cls._dir, ignore_errors=True)
            cls._writer = None
            cls._dir = None
            cls._initialized = False

    # -- internals -------------------------------------------------------
    @classmethod
    def _flush_locked(cls):
        buf = io.BytesIO()
        if not cls._wrote_header:
            buf.write(MAGIC)
            buf.write(struct.pack("<I", VERSION))
            cls._wrote_header = True
        for path in sorted(
            glob.glob(os.path.join(cls._dir, "**", "*"), recursive=True)
        ):
            if not os.path.isfile(path):
                continue
            name = os.path.relpath(path, cls._dir).encode()
            with open(path, "rb") as f:
                payload = f.read()
            rec = struct.pack("<I", len(name)) + name + payload
            buf.write(struct.pack("<I", len(rec)))
            buf.write(rec)
            os.remove(path)
        # fault-injection trace rides the capture: when a schedule fired
        # inside this collection window the (name, fault, occurrence)
        # log lands as a synthetic frame, so a profile of a chaos run is
        # self-describing about which faults shaped its timeline
        from . import faultinj

        fired = faultinj.fired_log()
        if fired:
            name = b"faultinj.fired.json"
            payload = json.dumps(fired).encode()
            rec = struct.pack("<I", len(name)) + name + payload
            buf.write(struct.pack("<I", len(rec)))
            buf.write(rec)
        data = buf.getvalue()
        if data:
            cls._writer(data)


def spill_summary() -> dict:
    """Spill-framework counters for profile reports: bytes/count per tier
    transition (device→host, host→disk, read-backs), eviction latency,
    and disk-write failures — the reference surfaces the same counters as
    task-level spill metrics next to its profiler captures.  All zeros
    when no spill framework is installed, so report code can emit the
    section unconditionally."""
    from .mem import spill

    fw = spill.get_framework()
    if fw is None:
        return dict.fromkeys(spill.SpillMetrics.FIELDS, 0)
    return fw.metrics.snapshot()


def shuffle_summary() -> dict:
    """ShuffleService counters for profile reports: shuffles/rounds run,
    rows and bytes moved, bytes spilled under pressure, out-of-range and
    dropped row counts, transport retry count, zone-map block skipping
    (``blocks_skipped``/``blocks_scanned`` from predicate-pruned morsel
    streams), and the worst skew ratio seen — the per-shuffle analogue
    of :func:`spill_summary`.  Always zeros-safe: the registry exists as
    soon as the shuffle package imports."""
    from .shuffle import get_registry

    return get_registry().metrics.snapshot()


def plan_cache_summary() -> dict:
    """Plan-cache counters for profile reports: compiled-program hits,
    misses, LRU evictions, and current size/capacity — the retrace
    story next to :func:`spill_summary`/:func:`shuffle_summary` (a hit
    means a repeated plan shape re-executed with zero retraces).
    Always zeros-safe: the cache exists as soon as the plan package
    imports."""
    from .plan.cache import plan_cache_metrics

    return plan_cache_metrics()


def fleet_summary() -> dict:
    """Front-door fleet counters for profile reports: workers spawned
    and respawned, crashes/stalls detected, session re-placements,
    ``WorkerLost`` failures, load-shed admissions, circuit-breaker
    opens, and the per-worker liveness map — the process-supervision
    story next to :func:`spill_summary`.  Always zeros-safe: a process
    that never constructed a :class:`~spark_rapids_jni_tpu.serve.
    frontdoor.FrontDoor` reports all-zero counters and no workers."""
    from .serve.frontdoor import fleet_metrics

    return fleet_metrics()


def trace_range(name: str):
    """Named range in the captured trace — the NVTX-range analogue
    (reference compiles nvtx3 ranges into kernels for nsys, SURVEY §5);
    here ``with trace_range("stage"):`` annotates the XLA trace so the
    converter's events carry pipeline-stage names."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class FileWriter:
    """A DataWriter that appends frames to one capture file."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")

    def __call__(self, data: bytes):
        self._f.write(data)
        self._f.flush()

    def close(self):
        self._f.close()


def _iter_frames(data: bytes):
    off = 0
    if data[:8] == MAGIC:
        off = 12
    while off + 4 <= len(data):
        (ln,) = struct.unpack_from("<I", data, off)
        off += 4
        rec = data[off: off + ln]
        off += ln
        (nlen,) = struct.unpack_from("<I", rec, 0)
        name = rec[4: 4 + nlen].decode()
        payload = rec[4 + nlen:]
        yield name, payload


# ---------------------------------------------------------------------------
# xplane.pb decoding (minimal protobuf wire reader; no tensorflow needed)
# ---------------------------------------------------------------------------
# Field numbers from tsl/profiler/protobuf/xplane.proto:
#   XSpace   { repeated XPlane planes = 1; }
#   XPlane   { int64 id=1; string name=2; repeated XLine lines=3;
#              map<int64, XEventMetadata> event_metadata=4; }
#   XLine    { int64 id=1; string name=2; int64 timestamp_ns=3;
#              repeated XEvent events=4; string display_name=11; }
#   XEvent   { int64 metadata_id=1; int64 offset_ps=2;
#              int64 duration_ps=3; }
#   XEventMetadata { int64 id=1; string name=2; }
# The device planes ("/device:TPU:0 ...") carry per-kernel events — the
# role of the reference's CUPTI activity records
# (profiler_serializer.cpp:222-280).


def _pb_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes."""
    off = 0
    n = len(buf)
    while off < n:
        key = 0
        shift = 0
        while True:
            b = buf[off]
            off += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wt = key >> 3, key & 7
        if wt == 0:  # varint
            v = 0
            shift = 0
            while True:
                b = buf[off]
                off += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wt, v
        elif wt == 2:  # length-delimited
            ln = 0
            shift = 0
            while True:
                b = buf[off]
                off += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wt, buf[off: off + ln]
            off += ln
        elif wt == 5:  # fixed32
            yield field, wt, buf[off: off + 4]
            off += 4
        elif wt == 1:  # fixed64
            yield field, wt, buf[off: off + 8]
            off += 8
        else:
            raise ProfilerError(f"unsupported protobuf wire type {wt}")


def _decode_xspace(payload: bytes) -> List[dict]:
    """XSpace bytes -> flat event records (plane/line/kernel name/us)."""
    events: List[dict] = []
    for f, wt, plane_buf in _pb_fields(payload):
        if f != 1 or wt != 2:
            continue
        plane_name = ""
        meta_names = {}
        lines = []
        for pf, pwt, pv in _pb_fields(plane_buf):
            if pf == 2 and pwt == 2:
                plane_name = pv.decode("utf-8", "replace")
            elif pf == 3 and pwt == 2:
                lines.append(pv)
            elif pf == 4 and pwt == 2:
                # map entry { int64 key=1; XEventMetadata value=2; }
                mid, mname = 0, ""
                for mf, mwt, mv in _pb_fields(pv):
                    if mf == 1 and mwt == 0:
                        mid = mv
                    elif mf == 2 and mwt == 2:
                        for ef, ewt, ev in _pb_fields(mv):
                            if ef == 2 and ewt == 2:
                                mname = ev.decode("utf-8", "replace")
                meta_names[mid] = mname
        for line_buf in lines:
            line_name = ""
            ts_ns = 0
            evs = []
            for lf, lwt, lv in _pb_fields(line_buf):
                if lf == 2 and lwt == 2:
                    line_name = lv.decode("utf-8", "replace")
                elif lf == 3 and lwt == 0:
                    ts_ns = lv
                elif lf == 4 and lwt == 2:
                    evs.append(lv)
            for ev_buf in evs:
                mid = off_ps = dur_ps = 0
                for ef, ewt, ev in _pb_fields(ev_buf):
                    if ef == 1 and ewt == 0:
                        mid = ev
                    elif ef == 2 and ewt == 0:
                        off_ps = ev
                    elif ef == 3 and ewt == 0:
                        dur_ps = ev
                events.append({
                    "name": meta_names.get(mid, f"event:{mid}"),
                    "ts_us": ts_ns / 1e3 + off_ps / 1e6,
                    "dur_us": dur_ps / 1e6,
                    "plane": plane_name,
                    "line": line_name,
                })
    return events


def convert_profile(capture_path: str) -> List[dict]:
    """Offline converter: capture stream -> flat event records.

    Equivalent role to ``spark_rapids_profile_converter`` (flatbuffer ->
    JSON).  Decodes BOTH artifact formats the XLA profiler produces:

    * ``*.trace.json.gz`` Chrome-trace -> {"name", "ts_us", "dur_us",
      "tid", "pid"} records;
    * ``*.xplane.pb`` XSpace protos -> {"name", "ts_us", "dur_us",
      "plane", "line"} records, where device planes carry the per-kernel
      activity (the reference's CUPTI record role);
    * the synthetic ``faultinj.fired.json`` frame -> one
      ``faultinj:<kind>@<boundary>`` record per injection that fired in
      the window, carrying the injector's (seq, occurrence) clock.
    """
    with open(capture_path, "rb") as f:
        data = f.read()
    if data[:8] != MAGIC:
        raise ProfilerError(f"{capture_path}: not a SPTPUPRF capture")
    events: List[dict] = []
    for name, payload in _iter_frames(data):
        if name.endswith(".trace.json.gz"):
            doc = json.loads(gzip.decompress(payload))
            for ev in doc.get("traceEvents", []):
                if ev.get("ph") == "X" and "name" in ev:
                    events.append(
                        {
                            "name": ev["name"],
                            "ts_us": ev.get("ts", 0),
                            "dur_us": ev.get("dur", 0),
                            "pid": ev.get("pid"),
                            "tid": ev.get("tid"),
                        }
                    )
        elif name.endswith(".xplane.pb"):
            events.extend(_decode_xspace(payload))
        elif name == "faultinj.fired.json":
            for e in json.loads(payload):
                events.append({
                    "name": (f"faultinj:{e.get('fault')}"
                             f"@{e.get('name')}"),
                    "ts_us": 0.0,
                    "dur_us": 0.0,
                    "fault": e.get("fault"),
                    "boundary": e.get("name"),
                    "occurrence": e.get("occurrence"),
                    "seq": e.get("seq"),
                })
    return events


def list_capture_files(capture_path: str) -> List[str]:
    """Names of the raw trace artifacts inside a capture (xplane etc.)."""
    with open(capture_path, "rb") as f:
        data = f.read()
    return [name for name, _ in _iter_frames(data)]
