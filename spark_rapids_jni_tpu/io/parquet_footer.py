"""Parquet footer parse / filter / rewrite (host facade).

Mirrors the reference's Java surface (``ParquetFooter.java:140-241``:
``readAndFilter`` with a depth-first flattened schema request using tags
{0=value, 1=struct, 2=list, 3=map}, then ``getNumRows`` /
``getNumColumns`` / ``serializeThriftFile``) over the native engine in
``native/parquet_footer.cpp`` (role of ``NativeParquetJni.cpp:109-670``).

The schema request here is a friendlier nested dict::

    {"a": None,                  # leaf column
     "b": {"x": None},           # struct, keeping only field x
     "l": [None],                # list of leaves (one-element list spec)
     "m": (None, {"y": None})}   # map: (key spec, value spec)

which flattens to the same depth-first (names, num_children, tags) wire
triple the Java side builds.
"""

from __future__ import annotations

import ctypes
import os
import struct as _struct
import subprocess
import threading
from typing import Optional, Sequence, Union

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtpu_parquet_footer.so")

TAG_VALUE, TAG_STRUCT, TAG_LIST, TAG_MAP = 0, 1, 2, 3

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        src_path = os.path.join(_NATIVE_DIR, "parquet_footer.cpp")
        stale = (not os.path.exists(_LIB_PATH)
                 or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src_path))
        if stale:
            # one-time native build: the lock exists precisely to
            # serialize make — a concurrent build would corrupt the .so
            proc = subprocess.run(  # graftlint: disable=GL019
                ["make", "-C", _NATIVE_DIR, "-B"],
                capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    "building libtpu_parquet_footer.so failed:\n"
                    + proc.stderr[-2000:])
        lib = ctypes.CDLL(_LIB_PATH)
        lib.pqf_read_and_filter.restype = ctypes.c_void_p
        lib.pqf_read_and_filter.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int]
        lib.pqf_error.restype = ctypes.c_char_p
        lib.pqf_error.argtypes = [ctypes.c_void_p]
        lib.pqf_free.argtypes = [ctypes.c_void_p]
        for fn in ("pqf_num_rows", "pqf_num_columns", "pqf_num_row_groups"):
            g = getattr(lib, fn)
            g.restype = ctypes.c_long
            g.argtypes = [ctypes.c_void_p]
        lib.pqf_serialize.restype = ctypes.c_long
        lib.pqf_serialize.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_long]
        _lib = lib
        return lib


def _flatten_schema(spec) -> tuple:
    """Nested request -> depth-first (names, num_children, tags)."""
    names, counts, tags = [], [], []

    def spec_tag(v):
        if v is None:
            return TAG_VALUE
        if isinstance(v, dict):
            return TAG_STRUCT
        if isinstance(v, (list,)):
            return TAG_LIST
        if isinstance(v, tuple):
            return TAG_MAP
        raise TypeError(f"bad schema spec entry {v!r}")

    def emit(name, v):
        tag = spec_tag(v)
        names.append(name)
        tags.append(tag)
        at = len(counts)
        counts.append(0)
        if tag == TAG_STRUCT:
            counts[at] = len(v)
            for k, sub in v.items():
                emit(k, sub)
        elif tag == TAG_LIST:
            if len(v) != 1:
                raise ValueError("list spec must have exactly one element")
            counts[at] = 1
            emit("element", v[0])
        elif tag == TAG_MAP:
            if len(v) != 2:
                raise ValueError("map spec must be (key, value)")
            counts[at] = 2
            emit("key", v[0])
            emit("value", v[1])

    if not isinstance(spec, dict):
        raise TypeError("top-level schema spec must be a dict of columns")
    for k, v in spec.items():
        emit(k, v)
    return names, counts, tags, len(spec)


def read_footer_bytes(path: str) -> bytes:
    """Extract the raw thrift footer bytes from a .parquet file."""
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size < 12:
            raise ValueError("not a parquet file (too small)")
        f.seek(size - 8)
        tail = f.read(8)
        if tail[4:] != b"PAR1":
            raise ValueError("not a parquet file (bad magic)")
        (flen,) = _struct.unpack("<I", tail[:4])
        f.seek(size - 8 - flen)
        return f.read(flen)


def predicate_prune_spans(path: str, predicate,
                          ignore_case: bool = False) -> list:
    """Byte windows covering the predicate-surviving row groups.

    The native facade prunes by ONE ``[part_offset, part_offset +
    part_length)`` split window (midpoint rule), so an arbitrary
    stats-pruned subset is expressed as its maximal runs of consecutive
    surviving groups: each returned ``(part_offset, part_length)``
    window contains exactly one run's midpoints and no pruned group's
    midpoint (row groups are laid out sequentially, so neighbouring
    groups' midpoints fall outside the run's byte span).  Feed each
    window to :meth:`ParquetFooter.read_and_filter`; their footers
    union to exactly the stats-surviving groups.

    Stats logic is shared with the pyarrow scan path
    (:func:`~spark_rapids_jni_tpu.io.parquet.prune_row_groups`), so the
    Python rule and the native facade cannot drift apart.
    """
    import pyarrow.parquet as pq

    from .parquet import _row_group_span, prune_row_groups

    meta = pq.ParquetFile(path).metadata
    keep, _ = prune_row_groups(meta, range(meta.num_row_groups),
                               predicate, ignore_case)
    spans = []
    run = []
    for i in keep:
        if run and i != run[-1] + 1:
            spans.append(run)
            run = []
        run.append(i)
    if run:
        spans.append(run)
    out = []
    for run in spans:
        start, _ = _row_group_span(meta.row_group(run[0]))
        _, end = _row_group_span(meta.row_group(run[-1]))
        out.append((start, end - start))
    return out


class ParquetFooter:
    """A parsed, filtered footer (reference ParquetFooter.java surface)."""

    def __init__(self, handle, lib):
        self._h = handle
        self._lib = lib

    @staticmethod
    def read_and_filter(
        footer: Union[bytes, str],
        part_offset: int = 0,
        part_length: int = 1 << 62,
        schema: Optional[dict] = None,
        ignore_case: bool = False,
    ) -> "ParquetFooter":
        """Parse + prune. ``footer`` is raw thrift bytes or a .parquet path.

        Row groups whose midpoint falls outside
        ``[part_offset, part_offset+part_length)`` are dropped; columns not
        named by ``schema`` (nested dict; None keeps everything) are pruned
        from both the schema tree and every row group's chunks.
        """
        if isinstance(footer, str):
            footer = read_footer_bytes(footer)
        lib = _load_lib()
        if schema is None:
            names, counts, tags, n_top = [], [], [], 0
        else:
            names, counts, tags, n_top = _flatten_schema(schema)
        n = len(names)
        c_names = (ctypes.c_char_p * max(n, 1))(
            *[nm.encode() for nm in names] or [b""])
        c_counts = (ctypes.c_int * max(n, 1))(*(counts or [0]))
        c_tags = (ctypes.c_int * max(n, 1))(*(tags or [0]))
        h = lib.pqf_read_and_filter(
            footer, len(footer), part_offset, part_length, c_names, c_counts,
            c_tags, n, n_top, int(ignore_case), int(schema is not None))
        err = lib.pqf_error(h)
        if err:
            msg = err.decode()
            lib.pqf_free(h)
            raise ValueError(f"parquet footer: {msg}")
        return ParquetFooter(h, lib)

    def close(self):
        if self._h:
            self._lib.pqf_free(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def num_rows(self) -> int:
        return self._lib.pqf_num_rows(self._h)

    @property
    def num_columns(self) -> int:
        return self._lib.pqf_num_columns(self._h)

    @property
    def num_row_groups(self) -> int:
        return self._lib.pqf_num_row_groups(self._h)

    def serialize(self) -> bytes:
        """PAR1-framed footer file (serializeThriftFile equivalent)."""
        size = self._lib.pqf_serialize(self._h, None, 0)
        buf = ctypes.create_string_buffer(size)
        wrote = self._lib.pqf_serialize(self._h, buf, size)
        if wrote != size:
            raise RuntimeError("footer serialization size mismatch")
        return buf.raw
