"""I/O & metadata components (reference SURVEY.md §2.3)."""

from .parquet import read_parquet, select_row_groups  # noqa: F401
from .parquet_footer import ParquetFooter, read_footer_bytes  # noqa: F401
