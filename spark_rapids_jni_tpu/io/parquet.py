"""Parquet scan: split-pruned read into a device ColumnBatch.

SURVEY.md §7 Phase 1's "Parquet host decode -> ColumnBatch upload".  The
reference keeps decode in libcudf and only prunes footers natively
(``NativeParquetJni.cpp``); here decode is pyarrow (host) and the pruning
rules are the reference's:

* a row group survives a split when its **midpoint** falls inside
  ``[part_offset, part_offset + part_length)`` — the same rule as
  ``NativeParquetJni.cpp:556-637`` (every row group belongs to exactly
  one split, splits need no coordination);
* column pruning by (case-(in)sensitively matched) top-level names.

Tests cross-check the selection against the native footer engine
(``parquet_footer.ParquetFooter.read_and_filter``) so the Python rule and
the C++ rule cannot drift apart.
"""

from __future__ import annotations

from typing import Optional, Sequence

import pyarrow.parquet as pq

from ..columnar.arrow import from_arrow
from ..columnar.column import ColumnBatch


def _row_group_span(rg) -> tuple:
    """(start, end) byte range of a row group's column chunk data."""
    start = None
    end = 0
    for ci in range(rg.num_columns):
        col = rg.column(ci)
        off = col.data_page_offset
        if col.dictionary_page_offset is not None:
            off = min(off, col.dictionary_page_offset)
        start = off if start is None else min(start, off)
        end = max(end, off + col.total_compressed_size)
    return (start or 0, end)


def select_row_groups(meta, part_offset: int, part_length: int) -> list:
    """Indices of row groups whose midpoint is inside the split."""
    lo, hi = part_offset, part_offset + part_length
    keep = []
    for i in range(meta.num_row_groups):
        start, end = _row_group_span(meta.row_group(i))
        mid = start + (end - start) // 2
        if lo <= mid < hi:
            keep.append(i)
    return keep


def _match_columns(schema_names, columns, ignore_case: bool) -> list:
    if columns is None:
        return list(schema_names)
    if not ignore_case:
        wanted = set(columns)
        return [n for n in schema_names if n in wanted]
    wanted_l = {c.lower() for c in columns}
    return [n for n in schema_names if n.lower() in wanted_l]


def read_parquet(
    path: str,
    columns: Optional[Sequence[str]] = None,
    part_offset: int = 0,
    part_length: int = 1 << 62,
    ignore_case: bool = False,
) -> ColumnBatch:
    """Read (a split of) a parquet file into a device ColumnBatch.

    With the ``encoded_execution`` knob resolved on, string columns read
    with ``read_dictionary``: their dictionary pages skip pyarrow's
    decode and hand through as
    :class:`~spark_rapids_jni_tpu.columnar.DictionaryColumn` (codes +
    values), so the char-matrix padding cost is paid once per distinct
    value instead of once per row.
    """
    from ..columnar.encoded import resolve_encoded_execution

    f = pq.ParquetFile(path)
    keep = select_row_groups(f.metadata, part_offset, part_length)
    schema = f.schema_arrow
    names = _match_columns(schema.names, columns, ignore_case)
    if resolve_encoded_execution():
        import pyarrow as pa

        dict_names = [n for n in names
                      if pa.types.is_string(schema.field(n).type)
                      or pa.types.is_large_string(schema.field(n).type)]
        if dict_names:
            # reopen with the dictionary set: pq decides per column chunk
            # (a chunk that fell back to plain encoding still decodes)
            f = pq.ParquetFile(path, read_dictionary=dict_names)
    if not keep:
        table = f.schema_arrow.empty_table().select(names)
    else:
        table = f.read_row_groups(keep, columns=names)
    return from_arrow(table)


def row_group_readers(
    path: str,
    columns: Optional[Sequence[str]] = None,
    part_offset: int = 0,
    part_length: int = 1 << 62,
    ignore_case: bool = False,
) -> list:
    """Replayable per-row-group readers for the streaming scan.

    Returns ``[(read, rows), ...]`` — one entry per split-surviving row
    group, in file order.  ``read()`` decodes JUST that row group into a
    ColumnBatch and may be called again at any time with a bit-identical
    result: it is the streaming pipeline's lineage hook (a lost or
    corrupt morsel-derived buffer re-decodes from source instead of
    keeping a second copy resident).  ``rows`` comes from the footer, so
    the morsel schedule is planned without touching any data pages.
    """
    f = pq.ParquetFile(path)
    keep = select_row_groups(f.metadata, part_offset, part_length)
    names = _match_columns(f.schema_arrow.names, columns, ignore_case)

    def make(i):
        def read() -> ColumnBatch:
            # a fresh ParquetFile per call: replay must not depend on a
            # shared reader's stream position or lifetime
            g = pq.ParquetFile(path)
            return from_arrow(g.read_row_groups([i], columns=names))
        return read

    return [(make(i), f.metadata.row_group(i).num_rows) for i in keep]
