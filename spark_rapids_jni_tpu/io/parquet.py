"""Parquet scan: split-pruned read into a device ColumnBatch.

SURVEY.md §7 Phase 1's "Parquet host decode -> ColumnBatch upload".  The
reference keeps decode in libcudf and only prunes footers natively
(``NativeParquetJni.cpp``); here decode is pyarrow (host) and the pruning
rules are the reference's:

* a row group survives a split when its **midpoint** falls inside
  ``[part_offset, part_offset + part_length)`` — the same rule as
  ``NativeParquetJni.cpp:556-637`` (every row group belongs to exactly
  one split, splits need no coordination);
* column pruning by (case-(in)sensitively matched) top-level names.

Tests cross-check the selection against the native footer engine
(``parquet_footer.ParquetFooter.read_and_filter``) so the Python rule and
the C++ rule cannot drift apart.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pyarrow.parquet as pq

from ..columnar.arrow import from_arrow
from ..columnar.column import ColumnBatch


def _row_group_span(rg) -> tuple:
    """(start, end) byte range of a row group's column chunk data."""
    start = None
    end = 0
    for ci in range(rg.num_columns):
        col = rg.column(ci)
        off = col.data_page_offset
        if col.dictionary_page_offset is not None:
            off = min(off, col.dictionary_page_offset)
        start = off if start is None else min(start, off)
        end = max(end, off + col.total_compressed_size)
    return (start or 0, end)


def select_row_groups(meta, part_offset: int, part_length: int) -> list:
    """Indices of row groups whose midpoint is inside the split."""
    lo, hi = part_offset, part_offset + part_length
    keep = []
    for i in range(meta.num_row_groups):
        start, end = _row_group_span(meta.row_group(i))
        mid = start + (end - start) // 2
        if lo <= mid < hi:
            keep.append(i)
    return keep


_PRUNE_OPS = ("<", "<=", "==", "!=", ">=", ">")


def _stats_may_match(stats, op: str, value) -> bool:
    """Conservative row-group stats check: False only when the chunk's
    min/max PROVE every row fails ``row <op> value``.  Missing stats,
    unset min/max, nulls, or cross-type comparisons all keep the group
    — pruning never guesses."""
    if stats is None or not stats.has_min_max:
        return True
    if stats.null_count is None or stats.null_count > 0:
        # a null row's decoded fill value is not described by min/max;
        # only all-valid chunks are provably cold
        return True
    lo, hi = stats.min, stats.max
    try:
        if op == "<":
            return bool(lo < value)
        if op == "<=":
            return bool(lo <= value)
        if op == ">":
            return bool(hi > value)
        if op == ">=":
            return bool(hi >= value)
        if op == "==":
            return bool(lo <= value) and bool(hi >= value)
        if op == "!=":
            return not (bool(lo == value) and bool(hi == value))
    except TypeError:
        return True
    return True


def _find_chunk(rg, column: str, ignore_case: bool):
    """Physical chunk index of top-level ``column`` in a row group."""
    want = column.lower() if ignore_case else column
    for ci in range(rg.num_columns):
        name = rg.column(ci).path_in_schema
        if (name.lower() if ignore_case else name) == want:
            return ci
    return None


def prune_row_groups(meta, keep, predicate,
                     ignore_case: bool = False) -> tuple:
    """Drop row groups whose column stats cannot satisfy ``predicate``
    (``(column, op, value)``), gated by the ``scan_pruning`` knob.

    Returns ``(kept_indices, pruned_count)``.  When every group is
    provably cold one schema-bearing group survives anyway (the morsel
    stream needs a first morsel; an empty filtered result still needs
    its schema) — its rows fail the predicate downstream.
    """
    from .. import config

    keep = list(keep)
    if predicate is None or not bool(config.get("scan_pruning")):
        return keep, 0
    column, op, value = predicate
    if (op not in _PRUNE_OPS or isinstance(value, bool)
            or not isinstance(value, (int, float, np.integer,
                                      np.floating))):
        return keep, 0
    kept = []
    for i in keep:
        rg = meta.row_group(i)
        ci = _find_chunk(rg, column, ignore_case)
        if ci is None or _stats_may_match(rg.column(ci).statistics,
                                          op, value):
            kept.append(i)
    if not kept and keep:
        kept = keep[:1]
    return kept, len(keep) - len(kept)


def _match_columns(schema_names, columns, ignore_case: bool) -> list:
    if columns is None:
        return list(schema_names)
    if not ignore_case:
        wanted = set(columns)
        return [n for n in schema_names if n in wanted]
    wanted_l = {c.lower() for c in columns}
    return [n for n in schema_names if n.lower() in wanted_l]


def read_parquet(
    path: str,
    columns: Optional[Sequence[str]] = None,
    part_offset: int = 0,
    part_length: int = 1 << 62,
    ignore_case: bool = False,
    predicate=None,
) -> ColumnBatch:
    """Read (a split of) a parquet file into a device ColumnBatch.

    With the ``encoded_execution`` knob resolved on, string columns read
    with ``read_dictionary``: their dictionary pages skip pyarrow's
    decode and hand through as
    :class:`~spark_rapids_jni_tpu.columnar.DictionaryColumn` (codes +
    values), so the char-matrix padding cost is paid once per distinct
    value instead of once per row.

    ``predicate`` (``(column, op, value)``) additionally drops row
    groups whose footer stats cannot satisfy it (``scan_pruning``
    knob): the split keeps only rows the filter may keep, so the caller
    must apply the same filter downstream regardless.
    """
    from ..columnar.encoded import resolve_encoded_execution

    f = pq.ParquetFile(path)
    keep = select_row_groups(f.metadata, part_offset, part_length)
    keep, _ = prune_row_groups(f.metadata, keep, predicate, ignore_case)
    schema = f.schema_arrow
    names = _match_columns(schema.names, columns, ignore_case)
    if resolve_encoded_execution():
        import pyarrow as pa

        dict_names = [n for n in names
                      if pa.types.is_string(schema.field(n).type)
                      or pa.types.is_large_string(schema.field(n).type)]
        if dict_names:
            # reopen with the dictionary set: pq decides per column chunk
            # (a chunk that fell back to plain encoding still decodes)
            f = pq.ParquetFile(path, read_dictionary=dict_names)
    if not keep:
        table = f.schema_arrow.empty_table().select(names)
    else:
        table = f.read_row_groups(keep, columns=names)
    return from_arrow(table)


def row_group_readers(
    path: str,
    columns: Optional[Sequence[str]] = None,
    part_offset: int = 0,
    part_length: int = 1 << 62,
    ignore_case: bool = False,
    predicate=None,
    counters: Optional[dict] = None,
) -> list:
    """Replayable per-row-group readers for the streaming scan.

    Returns ``[(read, rows), ...]`` — one entry per split-surviving row
    group, in file order.  ``read()`` decodes JUST that row group into a
    ColumnBatch and may be called again at any time with a bit-identical
    result: it is the streaming pipeline's lineage hook (a lost or
    corrupt morsel-derived buffer re-decodes from source instead of
    keeping a second copy resident).  ``rows`` comes from the footer, so
    the morsel schedule is planned without touching any data pages.

    ``predicate`` prunes stats-cold row groups before any reader is
    built (see :func:`prune_row_groups`); when ``counters`` is a dict it
    receives the ``{"pruned", "scanned"}`` group counts.
    """
    f = pq.ParquetFile(path)
    keep = select_row_groups(f.metadata, part_offset, part_length)
    keep, pruned = prune_row_groups(f.metadata, keep, predicate,
                                    ignore_case)
    if counters is not None:
        counters["pruned"] = pruned
        counters["scanned"] = len(keep)
    names = _match_columns(f.schema_arrow.names, columns, ignore_case)

    def make(i):
        def read() -> ColumnBatch:
            # a fresh ParquetFile per call: replay must not depend on a
            # shared reader's stream position or lifetime
            g = pq.ParquetFile(path)
            return from_arrow(g.read_row_groups([i], columns=names))
        return read

    return [(make(i), f.metadata.row_group(i).num_rows) for i in keep]
