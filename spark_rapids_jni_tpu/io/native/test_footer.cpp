/* Native-level round-trip test of the parquet footer engine (role of the
 * reference's footer coverage in its Java suite; sanitizer target for
 * ci/sanitize.sh).  Takes a real footer file produced by pyarrow
 * (ci/sanitize.sh generates it), reads+filters+re-serializes, and checks
 * the frame invariants.
 */
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
void* pqf_read_and_filter(const uint8_t* buf, long len, long part_offset,
                          long part_length, const char** names,
                          const int* num_children, const int* tags,
                          int n_entries, int parent_num_children,
                          int ignore_case, int do_prune);
const char* pqf_error(void* h);
void pqf_free(void* h);
long pqf_num_rows(void* h);
long pqf_num_columns(void* h);
long pqf_num_row_groups(void* h);
long pqf_serialize(void* h, uint8_t* outbuf, long cap);
}

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,   \
                   __LINE__, #cond);                                  \
      std::abort();                                                   \
    }                                                                 \
  } while (0)

static std::vector<uint8_t> read_file(const char* path) {
  FILE* f = std::fopen(path, "rb");
  CHECK(f != nullptr);
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(static_cast<size_t>(n));
  CHECK(std::fread(buf.data(), 1, buf.size(), f) == buf.size());
  std::fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  CHECK(argc > 1);
  auto raw = read_file(argv[1]); /* bare thrift footer bytes */

  /* identity pass */
  void* h = pqf_read_and_filter(raw.data(), (long)raw.size(), 0, 1L << 62,
                                nullptr, nullptr, nullptr, 0, 0, 0, 0);
  CHECK(pqf_error(h) == nullptr || pqf_error(h)[0] == '\0');
  long rows = pqf_num_rows(h);
  long cols = pqf_num_columns(h);
  CHECK(rows > 0 && cols >= 2);
  long need = pqf_serialize(h, nullptr, 0);
  CHECK(need > 8);
  std::vector<uint8_t> out(static_cast<size_t>(need));
  CHECK(pqf_serialize(h, out.data(), need) == need);
  CHECK(std::memcmp(out.data(), "PAR1", 4) == 0);
  CHECK(std::memcmp(out.data() + out.size() - 4, "PAR1", 4) == 0);
  pqf_free(h);

  /* column pruning: keep just column "a" (tag 0 = value leaf) */
  const char* names[] = {"a"};
  int counts[] = {0};
  int tags[] = {0};
  void* h2 = pqf_read_and_filter(raw.data(), (long)raw.size(), 0, 1L << 62,
                                 names, counts, tags, 1, 1, 0, 1);
  CHECK(pqf_error(h2) == nullptr || pqf_error(h2)[0] == '\0');
  CHECK(pqf_num_columns(h2) == 1);
  CHECK(pqf_num_rows(h2) == rows);
  pqf_free(h2);

  /* split pruning: zero-length split keeps no row groups */
  void* h3 = pqf_read_and_filter(raw.data(), (long)raw.size(), 0, 0, nullptr,
                                 nullptr, nullptr, 0, 0, 0, 0);
  CHECK(pqf_num_row_groups(h3) == 0);
  pqf_free(h3);

  /* garbage must error, not crash (sanitizer checks the parse paths) */
  std::vector<uint8_t> junk(raw.begin(), raw.begin() + raw.size() / 3);
  void* h4 = pqf_read_and_filter(junk.data(), (long)junk.size(), 0, 1L << 62,
                                 nullptr, nullptr, nullptr, 0, 0, 0, 0);
  /* either a clean error or a parsed prefix — must not crash */
  pqf_free(h4);

  std::puts("footer native tests OK");
  return 0;
}
