// Parquet footer parse / filter / rewrite for the TPU framework.
//
// Role-equivalent to the reference's NativeParquetJni.cpp (parse the thrift
// footer from host memory, prune row groups to a split's byte range by
// midpoint, prune columns against a case-(in)sensitive schema tree, then
// re-serialize a valid PAR1-framed footer) — but built differently: instead
// of typed thrift structs generated from parquet.thrift, the footer is
// parsed into a GENERIC thrift-compact value tree.  Unknown/new fields pass
// through untouched, and the pruner edits only the handful of semantically
// known paths (FileMetaData.schema / num_rows / row_groups, RowGroup.columns
// / num_rows / total_byte_size).
//
// Exported as a plain C ABI for ctypes (no JNI, no external deps).

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// thrift compact protocol: generic value tree
// ---------------------------------------------------------------------------

enum TType : uint8_t {
  T_STOP = 0,
  T_TRUE = 1,
  T_FALSE = 2,
  T_BYTE = 3,
  T_I16 = 4,
  T_I32 = 5,
  T_I64 = 6,
  T_DOUBLE = 7,
  T_BINARY = 8,
  T_LIST = 9,
  T_SET = 10,
  T_MAP = 11,
  T_STRUCT = 12,
};

struct TValue;
using TFields = std::vector<std::pair<int16_t, TValue>>;

struct TValue {
  uint8_t type = T_STOP;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string bin;
  uint8_t elem_type = T_STOP;           // for LIST/SET
  std::vector<TValue> elems;            // for LIST/SET
  uint8_t key_type = T_STOP, val_type = T_STOP;  // for MAP
  std::vector<std::pair<TValue, TValue>> kvs;    // for MAP
  std::shared_ptr<TFields> fields;      // for STRUCT (ordered, by field id)

  TValue* field(int16_t id) {
    if (!fields) return nullptr;
    for (auto& [fid, v] : *fields)
      if (fid == id) return &v;
    return nullptr;
  }
  const TValue* field(int16_t id) const {
    return const_cast<TValue*>(this)->field(id);
  }
  int64_t i64_or(int16_t id, int64_t dflt) const {
    auto* f = field(id);
    return f ? f->i : dflt;
  }
  void set_i64(int16_t id, int64_t v, uint8_t ty = T_I64) {
    if (auto* f = field(id)) {
      f->i = v;
      return;
    }
    TValue nv;
    nv.type = ty;
    nv.i = v;
    // keep fields sorted by id so the compact delta encoding stays small
    auto it = fields->begin();
    while (it != fields->end() && it->first < id) ++it;
    fields->insert(it, {id, nv});
  }
};

class Reader {
 public:
  Reader(const uint8_t* p, size_t n) : p_(p), n_(n) {}

  TValue read_struct() {
    TValue out;
    out.type = T_STRUCT;
    out.fields = std::make_shared<TFields>();
    int16_t last_id = 0;
    for (;;) {
      uint8_t head = u8();
      if (head == T_STOP) break;
      uint8_t delta = head >> 4;
      uint8_t type = head & 0x0F;
      int16_t id = delta ? int16_t(last_id + delta) : int16_t(zigzag(varint()));
      last_id = id;
      out.fields->push_back({id, read_value(type)});
    }
    return out;
  }

 private:
  TValue read_value(uint8_t type, bool in_container = false) {
    TValue v;
    v.type = type;
    switch (type) {
      case T_TRUE:
      case T_FALSE:
        if (in_container) {
          // container bools are one byte (1=true, 2=false); field bools
          // live in the field-header type nibble and consume nothing
          v.b = u8() == 1;
          v.type = v.b ? T_TRUE : T_FALSE;
        } else {
          v.b = (type == T_TRUE);
        }
        break;
      case T_BYTE:
        v.i = int8_t(u8());
        break;
      case T_I16:
      case T_I32:
      case T_I64:
        v.i = zigzag(varint());
        break;
      case T_DOUBLE: {
        uint64_t bits = 0;
        for (int k = 0; k < 8; k++) bits |= uint64_t(u8()) << (8 * k);
        std::memcpy(&v.d, &bits, 8);
        break;
      }
      case T_BINARY: {
        uint64_t len = varint();
        need(len);
        v.bin.assign(reinterpret_cast<const char*>(p_ + pos_), len);
        pos_ += len;
        break;
      }
      case T_LIST:
      case T_SET: {
        uint8_t head = u8();
        uint64_t size = head >> 4;
        v.elem_type = head & 0x0F;
        if (size == 15) size = varint();
        // every element consumes >= 1 byte except nothing does 0, so a
        // size beyond the remaining bytes is a corrupt/hostile footer
        if (size > remaining())
          throw std::runtime_error("container size exceeds footer");
        v.elems.reserve(size);
        for (uint64_t k = 0; k < size; k++)
          v.elems.push_back(read_value(v.elem_type, /*in_container=*/true));
        break;
      }
      case T_MAP: {
        uint64_t size = varint();
        if (size > remaining())
          throw std::runtime_error("map size exceeds footer");
        if (size > 0) {
          uint8_t kv = u8();
          v.key_type = kv >> 4;
          v.val_type = kv & 0x0F;
          for (uint64_t k = 0; k < size; k++) {
            TValue key = read_value(v.key_type, /*in_container=*/true);
            TValue val = read_value(v.val_type, /*in_container=*/true);
            v.kvs.push_back({std::move(key), std::move(val)});
          }
        }
        break;
      }
      case T_STRUCT:
        return read_struct();
      default:
        throw std::runtime_error("unknown thrift compact type " +
                                 std::to_string(type));
    }
    return v;
  }

  uint64_t remaining() const { return n_ - pos_; }

  void need(uint64_t n) {
    if (pos_ + n > n_) throw std::runtime_error("footer truncated");
  }
  uint8_t u8() {
    need(1);
    return p_[pos_++];
  }
  uint64_t varint() {
    uint64_t out = 0;
    int shift = 0;
    for (;;) {
      uint8_t b = u8();
      out |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) return out;
      shift += 7;
      if (shift > 63) throw std::runtime_error("varint overflow");
    }
  }
  static int64_t zigzag(uint64_t v) {
    return int64_t(v >> 1) ^ -int64_t(v & 1);
  }

  const uint8_t* p_;
  size_t n_;
  size_t pos_ = 0;
};

class Writer {
 public:
  void write_struct(const TValue& v) {
    int16_t last_id = 0;
    for (auto& [id, f] : *v.fields) {
      uint8_t type = f.type;
      if (type == T_TRUE || type == T_FALSE)
        type = f.b ? T_TRUE : T_FALSE;
      int delta = id - last_id;
      if (delta > 0 && delta <= 15) {
        u8(uint8_t(delta << 4) | type);
      } else {
        u8(type);
        varint(unzigzag(id));
      }
      write_value(f, /*in_field=*/true);
      last_id = id;
    }
    u8(T_STOP);
  }

  std::string out;

 private:
  void write_value(const TValue& v, bool in_field) {
    switch (v.type) {
      case T_TRUE:
      case T_FALSE:
        if (!in_field) u8(v.b ? 1 : 2);  // container bools: 1=true, 2=false
        break;  // field bools are encoded in the type nibble
      case T_BYTE:
        u8(uint8_t(v.i));
        break;
      case T_I16:
      case T_I32:
      case T_I64:
        varint(unzigzag(v.i));
        break;
      case T_DOUBLE: {
        uint64_t bits;
        double d = v.d;
        std::memcpy(&bits, &d, 8);
        for (int k = 0; k < 8; k++) u8(uint8_t(bits >> (8 * k)));
        break;
      }
      case T_BINARY:
        varint(v.bin.size());
        out.append(v.bin);
        break;
      case T_LIST:
      case T_SET: {
        size_t size = v.elems.size();
        if (size < 15) {
          u8(uint8_t(size << 4) | v.elem_type);
        } else {
          u8(uint8_t(0xF0) | v.elem_type);
          varint(size);
        }
        for (auto& e : v.elems) write_value(e, false);
        break;
      }
      case T_MAP: {
        varint(v.kvs.size());
        if (!v.kvs.empty()) {
          u8(uint8_t(v.key_type << 4) | v.val_type);
          for (auto& [k, val] : v.kvs) {
            write_value(k, false);
            write_value(val, false);
          }
        }
        break;
      }
      case T_STRUCT:
        write_struct(v);
        break;
      default:
        throw std::runtime_error("cannot serialize type " +
                                 std::to_string(v.type));
    }
  }

  void u8(uint8_t b) { out.push_back(char(b)); }
  void varint(uint64_t v) {
    while (v >= 0x80) {
      u8(uint8_t(v) | 0x80);
      v >>= 7;
    }
    u8(uint8_t(v));
  }
  static uint64_t unzigzag(int64_t v) {
    return (uint64_t(v) << 1) ^ uint64_t(v >> 63);
  }
};

// ---------------------------------------------------------------------------
// parquet footer model on top of the generic tree
// ---------------------------------------------------------------------------

// FileMetaData field ids (parquet.thrift)
constexpr int16_t FMD_SCHEMA = 2;
constexpr int16_t FMD_NUM_ROWS = 3;
constexpr int16_t FMD_ROW_GROUPS = 4;
constexpr int16_t FMD_COLUMN_ORDERS = 7;
// SchemaElement
constexpr int16_t SE_TYPE = 1;
constexpr int16_t SE_REPETITION = 3;
constexpr int16_t SE_NAME = 4;
constexpr int16_t SE_NUM_CHILDREN = 5;
constexpr int16_t SE_CONVERTED_TYPE = 6;
// RowGroup
constexpr int16_t RG_COLUMNS = 1;
constexpr int16_t RG_TOTAL_BYTE_SIZE = 2;
constexpr int16_t RG_NUM_ROWS = 3;
constexpr int16_t RG_FILE_OFFSET = 5;
constexpr int16_t RG_TOTAL_COMPRESSED = 6;
// ColumnChunk / ColumnMetaData
constexpr int16_t CC_META = 3;
constexpr int16_t CMD_TOTAL_COMPRESSED = 7;
constexpr int16_t CMD_DATA_PAGE_OFFSET = 9;
constexpr int16_t CMD_DICT_PAGE_OFFSET = 11;
// ConvertedType values
constexpr int64_t CT_MAP = 1;
constexpr int64_t CT_MAP_KEY_VALUE = 2;
constexpr int64_t REP_REPEATED = 2;

enum Tag : int { TAG_VALUE = 0, TAG_STRUCT = 1, TAG_LIST = 2, TAG_MAP = 3 };

std::string ascii_lower(const std::string& s) {
  std::string out = s;
  for (auto& c : out)
    c = char(std::tolower(static_cast<unsigned char>(c)));
  return out;
}

struct PruneNode {
  int tag = TAG_STRUCT;
  std::map<std::string, PruneNode> children;
};

// rebuild the depth-first flattened (names, num_children, tags) request into
// a tree (the same wire format ParquetFooter.java ships)
size_t build_prune_tree(PruneNode& node, const std::vector<std::string>& names,
                        const std::vector<int>& num_children,
                        const std::vector<int>& tags, size_t at, int n_kids,
                        bool ignore_case) {
  for (int k = 0; k < n_kids; k++) {
    std::string nm = ignore_case ? ascii_lower(names.at(at)) : names.at(at);
    PruneNode child;
    child.tag = tags.at(at);
    int kids = num_children.at(at);
    at++;
    at = build_prune_tree(child, names, num_children, tags, at, kids,
                          ignore_case);
    node.children.emplace(std::move(nm), std::move(child));
  }
  return at;
}

struct SchemaWalk {
  const std::vector<TValue>* schema;
  bool ignore_case;
  size_t si = 0;        // current schema element
  size_t chunk = 0;     // current leaf/chunk index
  std::vector<int> keep_schema;        // schema indexes kept
  std::vector<int> new_num_children;   // parallel to keep_schema
  std::vector<int> keep_chunks;        // chunk indexes kept

  const TValue& cur() const { return schema->at(si); }
  bool is_leaf() const { return cur().field(SE_TYPE) != nullptr; }
  int n_children() const { return int(cur().i64_or(SE_NUM_CHILDREN, 0)); }
  std::string name() const {
    auto* f = cur().field(SE_NAME);
    std::string nm = f ? f->bin : "";
    return ignore_case ? ascii_lower(nm) : nm;
  }
  int64_t repetition() const { return cur().i64_or(SE_REPETITION, -1); }

  void skip() {
    int to_skip = 1;
    while (to_skip > 0 && si < schema->size()) {
      if (is_leaf()) chunk++;
      to_skip += n_children();
      to_skip--;
      si++;
    }
  }

  void walk(const PruneNode& node) {
    switch (node.tag) {
      case TAG_STRUCT:
        walk_struct(node);
        break;
      case TAG_VALUE:
        walk_value();
        break;
      case TAG_LIST:
        walk_list(node);
        break;
      case TAG_MAP:
        walk_map(node);
        break;
      default:
        throw std::runtime_error("bad prune tag");
    }
  }

  void walk_value() {
    if (!is_leaf()) throw std::runtime_error("expected a leaf column");
    if (n_children() != 0)
      throw std::runtime_error("leaf with children in schema");
    keep_schema.push_back(int(si));
    new_num_children.push_back(0);
    si++;
    keep_chunks.push_back(int(chunk));
    chunk++;
  }

  void walk_struct(const PruneNode& node) {
    if (is_leaf())
      throw std::runtime_error("expected a struct, found a leaf");
    int kids = n_children();
    keep_schema.push_back(int(si));
    size_t my_count_at = new_num_children.size();
    new_num_children.push_back(0);
    si++;
    for (int k = 0; k < kids && si < schema->size(); k++) {
      auto found = node.children.find(name());
      if (found != node.children.end()) {
        new_num_children[my_count_at]++;
        walk(found->second);
      } else {
        skip();
      }
    }
  }

  void walk_list(const PruneNode& node) {
    // parquet LIST layouts (see format docs LogicalTypes.md):
    //   repeated leaf               -> element is the leaf itself
    //   repeated group, >1 fields   -> the group IS the element
    //   group(LIST) > repeated group(1 field, not legacy names) > element
    //   group(LIST) > repeated element          (older 2-level form)
    auto found = node.children.find("element");
    if (found == node.children.end())
      throw std::runtime_error("LIST request without an 'element' child");
    const TValue& list_item = cur();
    std::string list_name = list_item.field(SE_NAME)
                                ? list_item.field(SE_NAME)->bin
                                : "";
    bool group = !is_leaf();
    if (!group) {
      if (repetition() != REP_REPEATED)
        throw std::runtime_error("expected repeated list item");
      walk_value();
      return;
    }
    if (n_children() > 1) {
      if (repetition() != REP_REPEATED)
        throw std::runtime_error("expected repeated list item");
      walk(found->second);
      return;
    }
    if (n_children() != 1)
      throw std::runtime_error("non-standard outer list group");

    keep_schema.push_back(int(si));
    new_num_children.push_back(1);
    si++;

    if (repetition() != REP_REPEATED)
      throw std::runtime_error("non-repeating list child");
    bool rep_group = !is_leaf();
    int rep_kids = n_children();
    std::string rep_name =
        cur().field(SE_NAME) ? cur().field(SE_NAME)->bin : "";
    if (rep_group && rep_kids == 1 && rep_name != "array" &&
        rep_name != list_name + "_tuple") {
      keep_schema.push_back(int(si));
      new_num_children.push_back(1);
      si++;
      walk(found->second);
    } else {
      walk(found->second);
    }
  }

  void walk_map(const PruneNode& node) {
    auto key_it = node.children.find("key");
    auto val_it = node.children.find("value");
    if (key_it == node.children.end() || val_it == node.children.end())
      throw std::runtime_error("MAP request needs 'key' and 'value'");
    if (is_leaf()) throw std::runtime_error("expected a map group");
    int64_t ct = cur().i64_or(SE_CONVERTED_TYPE, -1);
    if (ct != CT_MAP && ct != CT_MAP_KEY_VALUE)
      throw std::runtime_error("expected a MAP converted type");
    if (n_children() != 1)
      throw std::runtime_error("non-standard outer map group");
    keep_schema.push_back(int(si));
    new_num_children.push_back(1);
    si++;

    if (repetition() != REP_REPEATED)
      throw std::runtime_error("non-repeating map child");
    int rep_kids = n_children();
    if (rep_kids != 1 && rep_kids != 2)
      throw std::runtime_error("map key_value with wrong child count");
    keep_schema.push_back(int(si));
    new_num_children.push_back(rep_kids);
    si++;
    walk(key_it->second);
    if (rep_kids == 2) walk(val_it->second);
  }
};

int64_t chunk_offset(const TValue& column_chunk) {
  const TValue* md = column_chunk.field(CC_META);
  if (!md) return 0;
  int64_t off = md->i64_or(CMD_DATA_PAGE_OFFSET, 0);
  const TValue* dict = md->field(CMD_DICT_PAGE_OFFSET);
  if (dict && off > dict->i) off = dict->i;
  return off;
}

// row-group selection by midpoint, with the PARQUET-2078 bad-file_offset
// fallbacks the java parquet-mr reader applies
std::vector<size_t> select_groups(const std::vector<TValue>& groups,
                                  int64_t part_offset, int64_t part_length) {
  std::vector<size_t> keep;
  bool first_has_md = false;
  if (!groups.empty()) {
    const TValue* cols = groups[0].field(RG_COLUMNS);
    if (cols && !cols->elems.empty())
      first_has_md = cols->elems[0].field(CC_META) != nullptr;
  }
  int64_t pre_start = 0, pre_size = 0;
  for (size_t g = 0; g < groups.size(); g++) {
    const TValue& rg = groups[g];
    const TValue* cols = rg.field(RG_COLUMNS);
    if (!cols || cols->elems.empty()) continue;
    int64_t start;
    if (first_has_md) {
      start = chunk_offset(cols->elems[0]);
    } else {
      start = rg.i64_or(RG_FILE_OFFSET, 0);
      bool invalid = (pre_start == 0 && start != 4) ||
                     (start < pre_start + pre_size);
      if (invalid) start = (pre_start == 0) ? 4 : pre_start + pre_size;
      pre_start = start;
      pre_size = rg.i64_or(RG_TOTAL_COMPRESSED, 0);
    }
    int64_t total = rg.i64_or(RG_TOTAL_COMPRESSED, -1);
    if (total < 0) {
      total = 0;
      for (auto& cc : cols->elems) {
        const TValue* md = cc.field(CC_META);
        if (md) total += md->i64_or(CMD_TOTAL_COMPRESSED, 0);
      }
    }
    int64_t mid = start + total / 2;
    if (mid >= part_offset && mid < part_offset + part_length)
      keep.push_back(g);
  }
  return keep;
}

struct Footer {
  TValue meta;  // FileMetaData struct
  int64_t num_columns = 0;
  std::string error;
};

}  // namespace

extern "C" {

void* pqf_read_and_filter(const uint8_t* buf, long len, long part_offset,
                          long part_length, const char** names,
                          const int* num_children, const int* tags,
                          int n_entries, int parent_num_children,
                          int ignore_case, int do_prune) {
  auto* out = new Footer();
  try {
    Reader r(buf, size_t(len));
    out->meta = r.read_struct();

    TValue* schema = out->meta.field(FMD_SCHEMA);
    TValue* groups = out->meta.field(FMD_ROW_GROUPS);
    if (!schema || schema->elems.empty())
      throw std::runtime_error("footer has no schema");

    // --- row-group pruning by split midpoint -------------------------
    std::vector<TValue> kept_groups;
    if (groups) {
      for (size_t g : select_groups(groups->elems, part_offset, part_length))
        kept_groups.push_back(groups->elems[g]);
      groups->elems = std::move(kept_groups);
    }

    // --- column pruning against the requested schema tree ------------
    if (do_prune) {
      PruneNode root;
      std::vector<std::string> nm(names, names + n_entries);
      std::vector<int> nc(num_children, num_children + n_entries);
      std::vector<int> tg(tags, tags + n_entries);
      build_prune_tree(root, nm, nc, tg, 0, parent_num_children,
                       ignore_case != 0);

      SchemaWalk walk{&schema->elems, ignore_case != 0};
      walk.walk_struct(root);  // the schema root is a struct

      std::vector<TValue> new_schema;
      for (size_t k = 0; k < walk.keep_schema.size(); k++) {
        TValue el = schema->elems[size_t(walk.keep_schema[k])];
        if (el.field(SE_NUM_CHILDREN))
          el.field(SE_NUM_CHILDREN)->i = walk.new_num_children[k];
        else if (walk.new_num_children[k] > 0)
          el.set_i64(SE_NUM_CHILDREN, walk.new_num_children[k], T_I32);
        new_schema.push_back(std::move(el));
      }
      schema->elems = std::move(new_schema);

      if (groups) {
        for (auto& rg : groups->elems) {
          TValue* cols = rg.field(RG_COLUMNS);
          if (!cols) continue;
          std::vector<TValue> kept;
          for (int ci : walk.keep_chunks)
            kept.push_back(cols->elems.at(size_t(ci)));
          cols->elems = std::move(kept);
        }
      }
      // column_orders carries one entry per LEAF column: prune in step
      if (TValue* co = out->meta.field(FMD_COLUMN_ORDERS)) {
        std::vector<TValue> kept;
        for (int ci : walk.keep_chunks)
          if (size_t(ci) < co->elems.size())
            kept.push_back(co->elems[size_t(ci)]);
        co->elems = std::move(kept);
      }
    }

    // --- num_rows reflects the kept row groups -----------------------
    int64_t rows = 0;
    if (groups)
      for (auto& rg : groups->elems) rows += rg.i64_or(RG_NUM_ROWS, 0);
    out->meta.set_i64(FMD_NUM_ROWS, rows, T_I64);

    // top-level column count (root's children after pruning)
    out->num_columns = out->meta.field(FMD_SCHEMA)
                           ->elems[0]
                           .i64_or(SE_NUM_CHILDREN, 0);
    return out;
  } catch (std::exception& e) {
    out->error = e.what();
    return out;
  }
}

const char* pqf_error(void* h) {
  auto* f = static_cast<Footer*>(h);
  return f->error.empty() ? nullptr : f->error.c_str();
}

void pqf_free(void* h) { delete static_cast<Footer*>(h); }

long pqf_num_rows(void* h) {
  auto* f = static_cast<Footer*>(h);
  auto* v = f->meta.field(FMD_NUM_ROWS);
  return v ? long(v->i) : 0;
}

long pqf_num_columns(void* h) {
  return long(static_cast<Footer*>(h)->num_columns);
}

long pqf_num_row_groups(void* h) {
  auto* f = static_cast<Footer*>(h);
  auto* g = f->meta.field(FMD_ROW_GROUPS);
  return g ? long(g->elems.size()) : 0;
}

// Serialized "footer file": PAR1 + thrift + u32 length + PAR1 (the same
// framing the reference's serializeThriftFile emits for the cudf reader).
long pqf_serialize(void* h, uint8_t* outbuf, long cap) {
  auto* f = static_cast<Footer*>(h);
  Writer w;
  w.write_struct(f->meta);
  uint32_t tlen = uint32_t(w.out.size());
  long total = 4 + long(tlen) + 4 + 4;
  if (outbuf == nullptr) return total;
  if (cap < total) return -1;
  std::memcpy(outbuf, "PAR1", 4);
  std::memcpy(outbuf + 4, w.out.data(), tlen);
  std::memcpy(outbuf + 4 + tlen, &tlen, 4);
  std::memcpy(outbuf + 8 + tlen, "PAR1", 4);
  return total;
}

}  // extern "C"
