"""Fault injection at the execute boundary.

Reference: the CUPTI injector (``faultinj/faultinj.cu:84-137`` + its
README): a library the driver loads into any process, configured by a JSON
file named in an env var, matching CUDA calls by function name or ``*``
with a probability and count, injecting one of three fault flavors, with
hot-reloadable config.  The TPU equivalent intercepts at OUR execute
boundary — instrumented jitted callables — since there is no CUPTI:

* config: JSON at ``SPARK_RAPIDS_TPU_FAULT_CONFIG`` (or passed directly)::

      {"seed": 42, "dynamic": true,
       "faults": [{"match": "q6*",  "probability": 0.01,
                   "fault": "exception"},
                  {"match": "*",    "count": 2, "fault": "oom"}]}

  ``match`` is an fnmatch pattern on the instrumented name; ``count``
  limits firings (omit for unlimited); ``probability`` defaults to 1.
* faults: ``"exception"`` raises :class:`InjectedFault` (the retryable
  CudfException analogue), ``"oom"`` raises
  :class:`~spark_rapids_jni_tpu.mem.RetryOOM` (driving the rollback
  ladder), ``"fatal"`` raises :class:`FatalInjectedFault` (the
  device-trap analogue — callers must treat the executor as poisoned),
  ``"spill_io"`` raises :class:`SpillIOError` at the spill framework's
  disk boundary (names ``spill_io_write``/``spill_io_read``) — the
  framework degrades by keeping the batch in the higher tier,
  ``"shuffle_io"`` raises :class:`ShuffleIOError` at the ShuffleService's
  per-round boundary (name ``shuffle_io_round``) — the service re-drives
  the round from its intact spillable buffers and counts the failure.
* ``dynamic: true`` re-reads the file when its mtime changes, matching
  the injector's ``dynamicReconfig`` thread without needing one.

Usage::

    from spark_rapids_jni_tpu import faultinj
    faultinj.configure(path_or_dict)          # or env var + configure()
    step = faultinj.instrument(jax.jit(fn), "q6_step")
    step(batch)   # may raise per config
"""

from __future__ import annotations

import fnmatch
import functools
import json
import os
import random
import threading
from typing import Optional, Union

ENV_CONFIG = "SPARK_RAPIDS_TPU_FAULT_CONFIG"


class InjectedFault(RuntimeError):
    """Retryable injected failure (the injected-CudfException analogue)."""


class FatalInjectedFault(RuntimeError):
    """Fatal injected failure (the device trap/assert analogue)."""


class SpillIOError(OSError):
    """Injected spill-path disk failure (kind ``"spill_io"``).

    Subclasses :class:`OSError` so the spill framework's degradation
    path — keep the batch in the higher tier, count the failure — handles
    injected and real disk faults identically."""


class ShuffleIOError(OSError):
    """Injected shuffle transport failure (kind ``"shuffle_io"``).

    Raised at the ShuffleService's per-round probe; the service re-drives
    the round from its spillable buffers (nothing was consumed) and
    counts the failure in ``ShuffleMetrics.io_failures``."""


def _raise_exception(name: str):
    raise InjectedFault(f"injected exception at {name}")


def _raise_oom(name: str):
    from .mem import RetryOOM

    raise RetryOOM(f"injected OOM at {name}")


def _raise_fatal(name: str):
    raise FatalInjectedFault(f"injected fatal fault at {name}")


def _raise_spill_io(name: str):
    raise SpillIOError(f"injected spill I/O fault at {name}")


def _raise_shuffle_io(name: str):
    raise ShuffleIOError(f"injected shuffle I/O fault at {name}")


# The registry of injectable fault flavors: kind -> raiser.  graftlint's
# GL006 keeps this in sync with every use site statically — a kind used
# in a config dict but missing here would otherwise only fail when its
# rule first fires, and a kind registered here but never injected by any
# test is an untested fault-handling path.
FAULT_KINDS = {
    "exception": _raise_exception,
    "oom": _raise_oom,
    "fatal": _raise_fatal,
    "spill_io": _raise_spill_io,
    "shuffle_io": _raise_shuffle_io,
}


class _Rule:
    def __init__(self, spec: dict):
        self.match = spec.get("match", "*")
        self.probability = float(spec.get("probability", 1.0))
        self.count = spec.get("count")  # None = unlimited
        self.fault = spec.get("fault", "exception")
        if self.fault not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.fault!r}; known: "
                             f"{sorted(FAULT_KINDS)}")
        self.remaining = None if self.count is None else int(self.count)

    def applies(self, name: str) -> bool:
        return fnmatch.fnmatchcase(name, self.match)


class _Injector:
    def __init__(self):
        self._lock = threading.Lock()
        self._rules: list = []
        self._rng = random.Random(0)
        self._path: Optional[str] = None
        self._mtime: float = 0.0
        self._dynamic = False

    def configure(self, config: Union[None, str, dict] = None):
        """Load config from a dict, a path, or the env var."""
        if config is None:
            config = os.environ.get(ENV_CONFIG)
            if config is None:
                with self._lock:
                    self._rules = []
                    self._path = None
                return
        if isinstance(config, str):
            path = config
            with open(path) as f:
                doc = json.load(f)
            with self._lock:
                self._path = path
                self._mtime = os.path.getmtime(path)
        else:
            doc = config
            with self._lock:
                self._path = None
        rules = [_Rule(r) for r in doc.get("faults", [])]
        with self._lock:
            self._rules = rules
            self._rng = random.Random(doc.get("seed", 0))
            self._dynamic = bool(doc.get("dynamic", False))

    def _maybe_reload(self):
        if not self._dynamic or self._path is None:
            return
        try:
            mtime = os.path.getmtime(self._path)
        except OSError:
            return
        if mtime != self._mtime:
            self.configure(self._path)

    def check(self, name: str):
        """Called at each instrumented execution; raises if a rule fires."""
        self._maybe_reload()
        with self._lock:
            for rule in self._rules:
                if not rule.applies(name):
                    continue
                if rule.remaining is not None and rule.remaining <= 0:
                    continue
                if self._rng.random() >= rule.probability:
                    continue
                if rule.remaining is not None:
                    rule.remaining -= 1
                kind = rule.fault
                break
            else:
                return
        FAULT_KINDS[kind](name)


_injector = _Injector()
configure = _injector.configure


def instrument(fn, name: Optional[str] = None):
    """Wrap an executable so the injector screens every invocation."""
    label = name or getattr(fn, "__name__", "anonymous")

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        _injector.check(label)
        return fn(*args, **kwargs)

    wrapped.__faultinj_name__ = label
    return wrapped
