"""Fault injection at the execute boundary.

Reference: the CUPTI injector (``faultinj/faultinj.cu:84-137`` + its
README): a library the driver loads into any process, configured by a JSON
file named in an env var, matching CUDA calls by function name or ``*``
with a probability and count, injecting one of three fault flavors, with
hot-reloadable config.  The TPU equivalent intercepts at OUR execute
boundary — instrumented jitted callables — since there is no CUPTI:

* config: JSON at ``SPARK_RAPIDS_TPU_FAULT_CONFIG`` (or passed directly)::

      {"seed": 42, "dynamic": true,
       "faults": [{"match": "q6*",  "probability": 0.01,
                   "fault": "exception"},
                  {"match": "*",    "count": 2, "skip": 1,
                   "fault": "oom"}]}

  ``match`` is an fnmatch pattern on the instrumented name; ``count``
  limits firings (omit for unlimited); ``probability`` defaults to 1;
  ``skip`` passes over the first N matching occurrences before the rule
  becomes eligible — with ``probability`` 1 this pins the firing to an
  exact occurrence, which is what makes chaos schedules deterministic
  and replayable (tools/chaos.py sweeps ``skip`` to hit every boundary
  crossing of a scenario).
* faults: ``"exception"`` raises :class:`InjectedFault` (the retryable
  CudfException analogue), ``"oom"`` raises
  :class:`~spark_rapids_jni_tpu.mem.RetryOOM` (driving the rollback
  ladder), ``"fatal"`` raises :class:`FatalInjectedFault` (the
  device-trap analogue — callers must treat the executor as poisoned),
  ``"spill_io"`` raises :class:`SpillIOError` at the spill framework's
  disk boundary (names ``spill_io_write``/``spill_io_read``) — the
  framework degrades by keeping the batch in the higher tier,
  ``"shuffle_io"`` raises :class:`ShuffleIOError` at the ShuffleService's
  per-round boundary (name ``shuffle_io_round``) — the service re-drives
  the round from its intact spillable buffers and counts the failure,
  ``"spill_corrupt"`` raises :class:`SpillCorruptionError` at the spill
  framework's post-write probe (name ``spill_corrupt_file``) — the
  framework responds by FLIPPING BYTES in the file it just wrote, so the
  checksum verification and lineage-recompute paths are proven against
  real on-disk damage, not just a raised exception,
  ``"host_corrupt"`` raises :class:`HostCorruptionError` at the spill
  framework's post-demotion probe (name ``host_corrupt_probe``) — the
  framework flips bytes in the numpy HOST copy it just made, proving the
  host tier's demotion-time CRC32s catch DRAM-resident damage on
  promotion (and, via the handed-down disk metadata, after a host→disk
  cascade),
  ``"task_cancel"`` raises :class:`TaskCancelled` — the tenant-kill
  analogue for the serving runtime: landing it at any instrumented
  boundary (via the occurrence clock) simulates a client killing its
  query mid-BUFN / mid-round / mid-spill, and the session must unwind
  kill-safe exactly as for an external ``ServeRuntime.cancel()``,
  ``"worker_crash"`` / ``"worker_stall"`` are PROCESS-level kinds for
  the multi-process front door (``serve/frontdoor.py``): inside an
  executor worker they kill -9 the interpreter mid-query or wedge it so
  it stops answering heartbeats (hooks installed by
  :func:`set_worker_fault_hooks`); in a process with no hooks installed
  they raise :class:`WorkerCrash` / :class:`WorkerStalled` so a stray
  rule match in a test harness is loud instead of fatal,
  ``"store_commit"`` raises :class:`StoreCommitError` at the shuffle
  store's pre-rename probe (name ``store_commit``) — the store responds
  by TEARING the in-flight write (the manifest is dropped, the tmp dir
  stays) so the commit never becomes visible, proving readers ignore
  tmp-only entries from a mid-commit kill,
  ``"store_corrupt"`` raises :class:`StoreCorruptionError` at the
  store's post-commit probe (name ``store_corrupt_file``) — the store
  converts it into real byte flips in a just-committed chunk file, so
  adoption-time CRC verification, quarantine, and the lineage fallback
  are proven against real on-disk damage,
  ``"net_drop"`` / ``"net_stall"`` / ``"net_torn"`` are NETWORK-level
  kinds for the fleet transport (``serve/wire.py``): raised at a
  transport's ``net_send_<role>``/``net_recv_<role>`` probes (role
  ``sup`` or ``wk``, so chaos can target either side of the link), the
  transport converts each into its real wire damage — a closed socket,
  a stall past the frame deadline then a close, or a half-written frame
  the peer's CRC/desync machinery must reject — and the reconnect
  ladder with resume-token reattach is the recovery path on every one,
  ``"cache_stale"`` raises :class:`CacheStaleError` at the result
  cache's ``cache_serve``/``cache_insert`` probes
  (serve/result_cache.py) — the cache rewinds the snapshot id on the
  served descriptor (or the stored entry), and the serve path's snapshot
  check must reject the entry and recompute live rather than ever serve
  a mutated input's stale result,
  ``"cache_corrupt"`` raises :class:`CacheCorruptError` at the same
  probes — the cache flips REAL bytes in the stored segment after its
  insert-time chunk CRCs were stamped, and serve-time CRC verification
  must quarantine the entry and recompute live, never decode damage,
  ``"scale_up_fail"`` raises :class:`ScaleUpFailError` at the elastic
  fleet's ``launcher_spawn`` probe (serve/launcher.py) — a worker
  launch that dies at the launcher boundary, which the supervisor must
  absorb through the respawn ladder instead of stranding queued work,
  ``"drain_stuck"`` raises :class:`DrainStuckError` at the worker's
  ``worker_drain`` probe (serve/worker.py) — a retiring worker that
  acknowledges the drain order but never finishes it, forcing the
  supervisor's drain deadline to escalate to a hard kill while the
  retired generation still ends fenced with zero zombie commits,
  ``"supervisor_crash"`` raises :class:`SupervisorCrash` at the session
  journal's ``journal_append``/``journal_replay`` probes
  (serve/journal.py) — the front door converts it into REAL supervisor
  death (``_simulate_crash``: listener and every worker link die
  abruptly, no cleanup, no fencing, no journal finalize) and the only
  recovery is a NEW FrontDoor adopting the fleet dir by journal replay,
  ``"journal_torn"`` raises :class:`JournalTornError` at the journal's
  ``journal_append`` probe — the journal converts it into REAL damage
  (the just-appended record's tail bytes are truncated on disk,
  modelling a write torn by the crash that accompanies it) and then
  surfaces the crash; replay must truncate the torn tail cleanly and
  the lost transition replays through the adoption ladder.
* ``dynamic: true`` re-reads the file when its mtime changes, matching
  the injector's ``dynamicReconfig`` thread without needing one.

Observability (all reset by :func:`configure` / :func:`reset_stats`):
:func:`check_counts` counts every screening per instrumented name (the
deterministic occurrence clock that ``skip`` indexes into),
:func:`fire_counts` counts actual injections per name, and
:func:`fired_log` returns the ordered trace of every injection —
``{"seq", "name", "fault", "match", "occurrence"}`` — which is enough to
reproduce a failing chaos schedule exactly (``skip = occurrence - 1``).

:func:`scope` applies a config for a ``with`` block and restores the
previous rules on exit; the block's stats survive the exit so a failing
trial can still be reported from its log.

Cross-process support (the front door's supervisor/worker split):
:func:`current_config` returns the live schedule as a config dict so a
supervisor can re-export it to spawned workers (each worker gets its own
occurrence clock); ``SPARK_RAPIDS_TPU_FAULT_MIRROR`` names a file every
firing is appended to (one JSON line, ``O_APPEND``, written BEFORE the
raiser runs) so a worker's injection trace survives even its own
SIGKILL; :func:`record_external` merges such a trace back into this
process's :func:`fired_log`, keeping the chaos campaign's
vacuous-trial and kind-coverage checks honest across the fleet.

Usage::

    from spark_rapids_jni_tpu import faultinj
    faultinj.configure(path_or_dict)          # or env var + configure()
    step = faultinj.instrument(jax.jit(fn), "q6_step")
    step(batch)   # may raise per config
    with faultinj.scope({"faults": [...]}):   # scoped schedule
        step(batch)
"""

from __future__ import annotations

import contextlib
import fnmatch
import functools
import json
import os
import random
import threading
from typing import Dict, List, Optional, Union

ENV_CONFIG = "SPARK_RAPIDS_TPU_FAULT_CONFIG"
ENV_MIRROR = "SPARK_RAPIDS_TPU_FAULT_MIRROR"


class InjectedFault(RuntimeError):
    """Retryable injected failure (the injected-CudfException analogue)."""


class FatalInjectedFault(RuntimeError):
    """Fatal injected failure (the device trap/assert analogue)."""


class SpillIOError(OSError):
    """Injected spill-path disk failure (kind ``"spill_io"``).

    Subclasses :class:`OSError` so the spill framework's degradation
    path — keep the batch in the higher tier, count the failure — handles
    injected and real disk faults identically."""


class ShuffleIOError(OSError):
    """Injected shuffle transport failure (kind ``"shuffle_io"``).

    Raised at the ShuffleService's per-round probe; the service re-drives
    the round from its spillable buffers (nothing was consumed) and
    counts the failure in ``ShuffleMetrics.io_failures``."""


class SpillCorruptionError(OSError):
    """Spilled data came back wrong or not at all (kind ``"spill_corrupt"``).

    Raised two ways: by the injector at the spill framework's post-write
    probe (where the framework converts it into real byte flips in the
    just-written file), and by the framework itself when a read-back
    fails checksum/length verification and the handle has no
    ``recompute=`` lineage to rebuild from.  Subclasses :class:`OSError`
    so callers treating disk loss generically catch both."""


class HostCorruptionError(SpillCorruptionError):
    """The HOST-tier copy of a spilled batch was damaged (kind
    ``"host_corrupt"``).

    Raised by the injector at the spill framework's post-demotion probe
    (name ``host_corrupt_probe``), where the framework converts it into
    real byte flips in the numpy copy it just made — the DRAM-error /
    stray-write analogue of ``"spill_corrupt"``'s disk damage.  The
    host tier records per-buffer CRC32s at demotion time and verifies
    them on promotion (and hands them to the disk tier unchanged, so
    damage that cascades host->disk is still caught at read-back).
    Subclasses :class:`SpillCorruptionError` so the framework's existing
    verify/lineage-rebuild path handles both damage sites."""


def _raise_exception(name: str):
    raise InjectedFault(f"injected exception at {name}")


def _raise_oom(name: str):
    from .mem import RetryOOM

    raise RetryOOM(f"injected OOM at {name}")


def _raise_fatal(name: str):
    raise FatalInjectedFault(f"injected fatal fault at {name}")


def _raise_spill_io(name: str):
    raise SpillIOError(f"injected spill I/O fault at {name}")


def _raise_shuffle_io(name: str):
    raise ShuffleIOError(f"injected shuffle I/O fault at {name}")


def _raise_spill_corrupt(name: str):
    raise SpillCorruptionError(f"injected spill corruption at {name}")


def _raise_host_corrupt(name: str):
    raise HostCorruptionError(f"injected host-tier corruption at {name}")


class TaskCancelled(RuntimeError):
    """Injected tenant kill (kind ``"task_cancel"``).

    Raised at any instrumented boundary — the occurrence clock lands it
    mid-BUFN, mid-shuffle-round, or mid-spill deterministically.  The
    serving runtime (``serve/runtime.py``) treats it exactly like an
    external ``ServeRuntime.cancel()`` arriving at that boundary: the
    session unwinds kill-safe (arena drained, spill files deleted,
    plan-cache pins released) and reports itself cancelled, so chaos
    trials can resubmit the tenant and compare against the fault-free
    baseline."""


def _raise_task_cancel(name: str):
    raise TaskCancelled(f"injected task cancel at {name}")


class WorkerCrash(RuntimeError):
    """An executor worker process was killed -9 (kind ``"worker_crash"``).

    Inside a worker the registered hook never returns — it SIGKILLs the
    interpreter, so there is no unwind, no atexit, no spill cleanup: the
    front door's reaper is the only recovery path, which is exactly what
    the chaos trials are proving.  In a process with no hook installed
    (pytest, the supervisor itself) this exception is raised instead."""


class WorkerStalled(RuntimeError):
    """An executor worker wedged mid-query (kind ``"worker_stall"``).

    Inside a worker the registered hook blocks the calling thread forever
    and flips a flag that stops the heartbeat loop answering pings — the
    supervisor must detect the missed heartbeats and SIGKILL the worker.
    With no hook installed this exception is raised instead."""


# Process-level fault hooks: only an executor worker installs these (see
# serve/worker.py); everywhere else the worker kinds degrade to loud
# exceptions via the default raisers below.
_worker_hooks: Dict[str, Optional[object]] = {"crash": None, "stall": None}


def set_worker_fault_hooks(crash=None, stall=None):
    """Install process-level handlers for the worker fault kinds.

    ``crash``/``stall`` are called with the instrumented name and are
    expected NOT to return (SIGKILL / block forever); if one does return,
    the corresponding exception is raised as a fallback."""
    _worker_hooks["crash"] = crash
    _worker_hooks["stall"] = stall


def _raise_worker_crash(name: str):
    hook = _worker_hooks["crash"]
    if hook is not None:
        hook(name)
    raise WorkerCrash(f"injected worker crash at {name} (no hook installed)")


def _raise_worker_stall(name: str):
    hook = _worker_hooks["stall"]
    if hook is not None:
        hook(name)
    raise WorkerStalled(f"injected worker stall at {name} (no hook installed)")


class StoreCommitError(OSError):
    """The shuffle store's commit rename failed (kind ``"store_commit"``).

    Raised at the store's pre-rename probe (name ``store_commit``),
    the instant after the tmp entry is fully written and fsynced but
    before the atomic rename makes it visible.  The store catches it,
    tears the write (the manifest is removed so the tmp entry can never
    be mistaken for committed), counts a ``commit_failures``, and
    reports the put as failed — callers keep their in-memory copy and
    the query is unaffected.  A ``worker_crash`` rule matched at the
    same probe name is the SIGKILL variant: the tmp-only entry survives
    on disk for the reaper/adoption paths to prove they ignore it."""


class StoreCorruptionError(OSError):
    """A committed shuffle-store entry was damaged (kind
    ``"store_corrupt"``).

    Raised two ways: by the injector at the store's post-commit probe
    (name ``store_corrupt_file``), where the store converts it into real
    byte flips in a chunk file it just committed; and by the store
    itself when adoption-time verification finds a manifest missing,
    unreadable, or a leaf failing its CRC32/length check.  The adoption
    path responds by quarantining the entry (renamed out of the
    committed namespace, counted) and falling back to the next-best
    attempt or the lineage re-run — graceful degradation, never a wrong
    answer."""


def _raise_store_commit(name: str):
    raise StoreCommitError(f"injected store commit fault at {name}")


def _raise_store_corrupt(name: str):
    raise StoreCorruptionError(f"injected store corruption at {name}")


class NetDropError(ConnectionError):
    """The link dropped (kind ``"net_drop"``).

    Raised at a transport's ``net_send_<role>``/``net_recv_<role>``
    probe (serve/wire.py); the transport converts it into a real closed
    socket — the peer sees EOF, this side sees ``WireError`` — and the
    reconnect supervision (worker-side ladder, supervisor-side
    resume-token reattach) is the only recovery path."""


class NetStallError(OSError):
    """The link stalled (kind ``"net_stall"``).

    The transport sleeps past its frame deadline (so heartbeat and
    deadline detectors genuinely fire, nothing is mocked), then drops
    the connection exactly like ``net_drop``."""


class NetTornError(ConnectionError):
    """A frame tore on the wire (kind ``"net_torn"``).

    On send the transport writes the header plus HALF the payload and
    closes — the peer's mid-frame/CRC desync machinery must detect the
    damage rather than parse garbage; on recv the already-read frame is
    discarded and the link closed (``WireDesync``)."""


def _raise_net_drop(name: str):
    raise NetDropError(f"injected link drop at {name}")


def _raise_net_stall(name: str):
    raise NetStallError(f"injected link stall at {name}")


def _raise_net_torn(name: str):
    raise NetTornError(f"injected torn frame at {name}")


class ShmTornError(OSError):
    """A data-plane payload tore after its CRC stamp (kind ``"shm_torn"``).

    Raised at the worker's ``data_write_wk`` probe (serve/worker.py);
    the worker converts it into REAL damage — bytes flipped inside the
    already-CRC-stamped shared-memory segment (or in-flight chunk on the
    frames/json planes) — so the supervisor's per-chunk CRC verification
    must catch the corruption and re-place the session, never decode
    garbage into a batch."""


class ShmStaleError(OSError):
    """A prior generation's segment name resurfaced (kind ``"shm_stale"``).

    Raised at the worker's ``data_descriptor_wk`` probe; the worker
    stamps the outgoing descriptor with the PREVIOUS fence epoch's
    segment name, modelling a crashed incarnation's segment being
    re-announced.  The supervisor's epoch check (descriptor epoch must
    equal the worker's current generation) must reject it."""


def _raise_shm_torn(name: str):
    raise ShmTornError(f"injected torn shared-memory payload at {name}")


def _raise_shm_stale(name: str):
    raise ShmStaleError(f"injected stale segment descriptor at {name}")


class CacheStaleError(OSError):
    """A result-cache descriptor carries a rewound snapshot id (kind
    ``"cache_stale"``).

    Raised at the front door's ``cache_serve``/``cache_insert`` probes
    (serve/result_cache.py); the cache converts it into a descriptor (or
    stored entry) whose snapshot id has been REWOUND to a prior
    generation, modelling an input that mutated after the entry was
    sealed.  The serve path's snapshot check (descriptor snapshot must
    equal the requested snapshot id) must reject it and fall through to
    a live recompute — a stale snapshot is never served."""


class CacheCorruptError(OSError):
    """A cached result segment was damaged after sealing (kind
    ``"cache_corrupt"``).

    Raised at the front door's ``cache_serve``/``cache_insert`` probes;
    the cache converts it into REAL byte flips in the stored segment
    bytes — after the insert-time chunk CRCs were stamped — so the serve
    path's per-chunk CRC verification must catch the damage, quarantine
    the entry, and recompute live rather than decode garbage."""


def _raise_cache_stale(name: str):
    raise CacheStaleError(f"injected stale result-cache snapshot at {name}")


def _raise_cache_corrupt(name: str):
    raise CacheCorruptError(f"injected result-cache corruption at {name}")


class ScaleUpFailError(OSError):
    """A worker launch failed at the launcher boundary (kind
    ``"scale_up_fail"``).

    Raised at the launcher's ``launcher_spawn`` probe
    (serve/launcher.py) — the supervisor must treat a failed launch like
    any other capacity loss: count it, keep the slot on the respawn
    ladder with backoff, and never leave queued sessions stranded on a
    worker that was never born.  Subclasses :class:`OSError` because a
    real agent/ssh launch fails with exactly that surface."""


class DrainStuckError(OSError):
    """A retiring worker wedged inside its drain ladder (kind
    ``"drain_stuck"``).

    Raised at the worker's ``worker_drain`` probe (serve/worker.py) —
    the worker acknowledges the drain order but never completes it, so
    the supervisor's drain deadline must escalate to a hard kill and the
    retired generation must still end fenced with zero zombie
    commits."""


def _raise_scale_up_fail(name: str):
    raise ScaleUpFailError(f"injected worker launch failure at {name}")


def _raise_drain_stuck(name: str):
    raise DrainStuckError(f"injected stuck drain at {name}")


class SupervisorCrash(RuntimeError):
    """The supervisor died abruptly (kind ``"supervisor_crash"``).

    Raised at the session journal's ``journal_append`` /
    ``journal_replay`` probes (serve/journal.py).  The front door's
    journal helper converts it into real supervisor death —
    ``FrontDoor._simulate_crash()`` closes the listener and every
    worker link with NO cleanup (no fencing, no reaping, no journal
    finalize, sessions left hanging) — exactly the state a SIGKILLed
    supervisor process leaves behind, minus the interpreter exit the
    in-process chaos harness cannot survive.  Recovery is a fresh
    FrontDoor adopting the same fleet dir: journal replay, dead-gen
    fencing, resume-token re-dials from the orphaned workers."""


class JournalTornError(OSError):
    """The just-appended journal record tore (kind ``"journal_torn"``).

    Raised at the journal's ``journal_append`` probe; the journal
    converts it into REAL on-disk damage — the tail of the record it
    just wrote is truncated mid-bytes, before any fsync — and then
    re-raises, because a torn tail only ever exists when the writer
    died mid-write (O_APPEND + fsync ordering).  The front door treats
    it exactly like :class:`SupervisorCrash`; replay must truncate the
    torn record cleanly and resume from the last intact one."""


def _raise_supervisor_crash(name: str):
    raise SupervisorCrash(f"injected supervisor crash at {name}")


def _raise_journal_torn(name: str):
    raise JournalTornError(f"injected torn journal record at {name}")


class ZoneMapCorruptionError(OSError):
    """A zone-map sidecar lies about its blocks (kind ``"zone_map_corrupt"``).

    Raised at the ``zone_map_check`` probe (shuffle/morsel.py); the skip
    path converts it into REAL damage — the sidecar's min/max stats are
    flipped AFTER the CRC stamp, modelling a corrupted or stale sidecar
    whose statistics no longer describe the blocks they claim to cover —
    and the mandatory ``ZoneMap.verify()`` CRC check must catch the
    mismatch and raise this same class LOUDLY at skip time.  Skipping on
    a lying sidecar would silently drop rows the filter should have
    kept, so corruption here may never degrade to wrong answers: the
    only recovery is re-encoding from source (a fresh sidecar is the
    lineage)."""


def _raise_zone_map_corrupt(name: str):
    raise ZoneMapCorruptionError(f"injected zone-map corruption at {name}")


# The registry of injectable fault flavors: kind -> raiser.  graftlint's
# GL006 keeps this in sync with every use site statically — a kind used
# in a config dict but missing here would otherwise only fail when its
# rule first fires, and a kind registered here but never injected by any
# test is an untested fault-handling path.  tools/chaos.py additionally
# proves every kind DYNAMICALLY: the premerge chaos campaign fails unless
# each entry here fired at least once across the spill/shuffle/q95
# scenarios with a bit-identical recovery.
FAULT_KINDS = {
    "exception": _raise_exception,
    "oom": _raise_oom,
    "fatal": _raise_fatal,
    "spill_io": _raise_spill_io,
    "shuffle_io": _raise_shuffle_io,
    "spill_corrupt": _raise_spill_corrupt,
    "host_corrupt": _raise_host_corrupt,
    "task_cancel": _raise_task_cancel,
    "worker_crash": _raise_worker_crash,
    "worker_stall": _raise_worker_stall,
    "store_commit": _raise_store_commit,
    "store_corrupt": _raise_store_corrupt,
    "net_drop": _raise_net_drop,
    "net_stall": _raise_net_stall,
    "net_torn": _raise_net_torn,
    "shm_torn": _raise_shm_torn,
    "shm_stale": _raise_shm_stale,
    "cache_stale": _raise_cache_stale,
    "cache_corrupt": _raise_cache_corrupt,
    "scale_up_fail": _raise_scale_up_fail,
    "drain_stuck": _raise_drain_stuck,
    "zone_map_corrupt": _raise_zone_map_corrupt,
    "supervisor_crash": _raise_supervisor_crash,
    "journal_torn": _raise_journal_torn,
}


class _Rule:
    def __init__(self, spec: dict):
        # the original spec survives so current_config() can re-export
        # the schedule verbatim to a spawned worker process
        self.spec = dict(spec)
        self.match = spec.get("match", "*")
        self.probability = float(spec.get("probability", 1.0))
        self.count = spec.get("count")  # None = unlimited
        self.skip = int(spec.get("skip", 0))
        if self.skip < 0:
            raise ValueError(f"skip must be >= 0, got {self.skip}")
        self.fault = spec.get("fault", "exception")
        if self.fault not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.fault!r}; known: "
                             f"{sorted(FAULT_KINDS)}")
        self.remaining = None if self.count is None else int(self.count)
        self.skip_remaining = self.skip

    def applies(self, name: str) -> bool:
        return fnmatch.fnmatchcase(name, self.match)


class _Injector:
    def __init__(self):
        self._lock = threading.Lock()
        self._rules: list = []
        self._rng = random.Random(0)
        self._path: Optional[str] = None
        self._mtime: float = 0.0
        self._dynamic = False
        self._seed = 0
        # crash-durable per-fire mirror (see module docstring): the fd is
        # opened lazily O_APPEND so a line is on disk before the raiser
        # runs — even a SIGKILL from _raise_worker_crash can't lose it
        self._mirror_path: Optional[str] = os.environ.get(ENV_MIRROR)
        self._mirror_fd: Optional[int] = None
        # deterministic observability: per-name screening/firing counters
        # and the ordered injection trace (see fired_log())
        self._checks: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._log: List[dict] = []
        self._seq = 0

    def _reset_stats_locked(self):
        self._checks = {}
        self._fired = {}
        self._log = []
        self._seq = 0

    def configure(self, config: Union[None, str, dict] = None):
        """Load config from a dict, a path, or the env var.

        Every (re)configuration resets the fire counters and the trace —
        a schedule's observability starts at its installation.  All state
        is swapped under one lock acquisition so a concurrent ``check()``
        sees either the old or the new schedule, never a mix (the
        ``_maybe_reload`` race of record: ``_dynamic``/``_path`` used to
        be readable mid-write)."""
        if config is None:
            config = os.environ.get(ENV_CONFIG)
            if config is None:
                with self._lock:
                    self._rules = []
                    self._path = None
                    self._dynamic = False
                    self._seed = 0
                    self._reset_stats_locked()
                return
        if isinstance(config, str):
            path: Optional[str] = config
            with open(path) as f:
                doc = json.load(f)
            mtime = os.path.getmtime(path)
        else:
            doc, path, mtime = config, None, 0.0
        rules = [_Rule(r) for r in doc.get("faults", [])]
        with self._lock:
            self._rules = rules
            self._seed = int(doc.get("seed", 0))
            self._rng = random.Random(self._seed)
            self._dynamic = bool(doc.get("dynamic", False))
            self._path = path
            self._mtime = mtime
            self._reset_stats_locked()

    def _maybe_reload(self):
        with self._lock:
            dynamic, path, known_mtime = self._dynamic, self._path, \
                self._mtime
        if not dynamic or path is None:
            return
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return
        if mtime != known_mtime:
            self.configure(path)

    def check(self, name: str):
        """Called at each instrumented execution; raises if a rule fires."""
        self._maybe_reload()
        with self._lock:
            self._checks[name] = self._checks.get(name, 0) + 1
            for rule in self._rules:
                if not rule.applies(name):
                    continue
                if rule.remaining is not None and rule.remaining <= 0:
                    continue
                if rule.skip_remaining > 0:
                    # deterministic pass-over: this matching occurrence is
                    # consumed whether or not probability would have fired
                    rule.skip_remaining -= 1
                    continue
                if self._rng.random() >= rule.probability:
                    continue
                if rule.remaining is not None:
                    rule.remaining -= 1
                self._seq += 1
                self._fired[name] = self._fired.get(name, 0) + 1
                entry = {
                    "seq": self._seq, "name": name, "fault": rule.fault,
                    "match": rule.match,
                    # occurrence is 1-based: replay with skip=occurrence-1
                    "occurrence": self._checks[name],
                }
                self._log.append(entry)
                self._mirror_locked(entry)
                kind = rule.fault
                break
            else:
                return
        FAULT_KINDS[kind](name)

    def _mirror_locked(self, entry: dict):
        """Append one fired entry to the mirror file, durably, pre-raise."""
        if not self._mirror_path:
            return
        try:
            if self._mirror_fd is None:
                self._mirror_fd = os.open(
                    self._mirror_path,
                    os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
            os.write(self._mirror_fd,
                     (json.dumps(entry) + "\n").encode("utf-8"))
        except OSError:
            # observability must never take the workload down with it
            self._mirror_fd = None

    def record_external(self, entries: List[dict],
                        source: Optional[str] = None):
        """Merge another process's fired entries into this injector's log.

        The front door calls this with a dead or drained worker's mirror
        file (or last pong's trace) so a chaos trial's ``fired_log()``
        covers the whole fleet.  Entries are re-sequenced locally;
        ``source`` tags where they came from."""
        with self._lock:
            for e in entries:
                self._seq += 1
                rec = {
                    "seq": self._seq,
                    "name": e.get("name", "?"),
                    "fault": e.get("fault", "?"),
                    "match": e.get("match", "*"),
                    "occurrence": e.get("occurrence", 0),
                }
                if source is not None:
                    rec["source"] = source
                elif "source" in e:
                    rec["source"] = e["source"]
                self._fired[rec["name"]] = self._fired.get(rec["name"], 0) + 1
                self._log.append(rec)

    def current_config(self) -> dict:
        """The live schedule as a config dict (original rule specs).

        What a supervisor exports to a spawned worker; the worker's
        injector starts a fresh occurrence clock over the same rules."""
        with self._lock:
            return {"seed": self._seed,
                    "faults": [dict(r.spec) for r in self._rules]}

    # -- observability ---------------------------------------------------
    def check_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._checks)

    def fire_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._fired)

    def fired_log(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._log]

    def reset_stats(self):
        with self._lock:
            self._reset_stats_locked()

    @contextlib.contextmanager
    def scope(self, config: Union[str, dict]):
        """Apply ``config`` for the block, restoring the previous schedule
        (rules, rng, dynamic-reload state) on exit.  Entry resets the
        stats (via :meth:`configure`); exit leaves them in place so the
        block's :func:`fired_log` stays readable after a failing trial."""
        with self._lock:
            saved = (self._rules, self._rng, self._dynamic, self._path,
                     self._mtime, self._seed)
        self.configure(config)
        try:
            yield self
        finally:
            with self._lock:
                (self._rules, self._rng, self._dynamic, self._path,
                 self._mtime, self._seed) = saved


_injector = _Injector()
configure = _injector.configure
scope = _injector.scope
check_counts = _injector.check_counts
fire_counts = _injector.fire_counts
fired_log = _injector.fired_log
reset_stats = _injector.reset_stats
record_external = _injector.record_external
current_config = _injector.current_config


def instrument(fn, name: Optional[str] = None):
    """Wrap an executable so the injector screens every invocation."""
    label = name or getattr(fn, "__name__", "anonymous")

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        _injector.check(label)
        return fn(*args, **kwargs)

    wrapped.__faultinj_name__ = label
    return wrapped
