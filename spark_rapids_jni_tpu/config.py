"""Unified config/flag registry.

The reference spreads its knobs over four layers (SURVEY.md §5: maven/
cmake build properties, Java system properties, env vars for injected
libs, and per-call arguments).  Here one registry holds every documented
runtime knob with an env-var override (``SPARK_RAPIDS_TPU_<KEY>``),
while per-call arguments keep winning at call sites — the same precedence
story, minus the scatter.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_ENV_PREFIX = "SPARK_RAPIDS_TPU_"


@dataclass(frozen=True)
class _Entry:
    default: Any
    parse: Callable[[str], Any]
    doc: str


_REGISTRY: Dict[str, _Entry] = {}
_overrides: Dict[str, Any] = {}
_lock = threading.Lock()


def _register(key: str, default, parse, doc: str):
    _REGISTRY[key] = _Entry(default, parse, doc)


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


# ---- documented knobs ------------------------------------------------------
_register("watchdog_poll_ms", 100.0, float,
          "Deadlock watchdog period for the resource adaptor "
          "(reference: ai.rapids.cudf.spark.rmmWatchdogPollingPeriod).")
_register("mem_pool_bytes", 0, int,
          "Default logical HBM arena size for RmmSpark.set_event_handler "
          "(0 = caller must pass one explicitly).")
_register("json_max_out", 0, int,
          "get_json_object output width cap (0 = provable 6*L+20 bound).")
_register("json_fast_path", True, _parse_bool,
          "Route wildcard-free get_json_object paths through the "
          "bit-parallel fast engine (ops/json_fast.py): O(path + log L) "
          "data-parallel passes instead of max_len sequential scan "
          "steps; rows it cannot prove it handles fall back to the scan "
          "machine per batch.")
_register("json_fallback_div", 16, int,
          "Per-row fallback compaction capacity for the JSON hybrid: "
          "flagged rows are gathered into fixed chunks of ceil(n/div) "
          "rows and only those chunks run the serial scan machine "
          "(lax.while_loop; clean batches run zero iterations). div=1 "
          "degenerates to whole-batch chunks; 0 disables compaction "
          "(any flagged row routes the whole batch, pre-r5 behavior). "
          "Default 16 from the r5 CPU sweep at 4K docs: 1.82x/2.47x the "
          "all-clean rate at 1%/10% dirty rows (div=8: 2.53x/2.64x; "
          "div=32: 1.64x/3.68x) — the chunk then costs about one fast "
          "pass, balancing low-rate latency against high-rate chunk "
          "count.")
_register("json_scan_unroll", 2, int,
          "Chars processed per while-loop iteration in the JSON scan "
          "(lax.scan unroll): the scan carry round-trips HBM once per "
          "iteration, so higher = fewer latency-bound steps, more code. "
          "Compile time scales ~linearly with the unroll (round 4: 23s/"
          "91s/~550s for 1/4/8 on a 1-core CPU) and the hybrid compiles "
          "the scan as the fallback branch of every wildcard-free query, "
          "so the default is a compile-friendly 2 now that the "
          "bit-parallel fast path carries clean batches.")
_register("spill_dir", "", str,
          "Directory for the spill framework's disk tier (mem/spill.py). "
          "Empty (default) = a fresh mkdtemp owned — and removed — by "
          "the SpillFramework; set it to put spill files on a chosen "
          "volume (reference: spark.local.dir for RapidsDiskStore).")
_register("shuffle_capacity_bucket", 256, int,
          "Rounding bucket for auto-planned exchange capacities (bigger = "
          "fewer recompiles, more slot padding).")
_register("shuffle_round_rows", 1 << 16, int,
          "Per-(sender,destination) slot rows one ShuffleService round may "
          "carry (shuffle/planner.py).  Buckets bigger than this drain "
          "over multiple all_to_all rounds instead of inflating the slot "
          "grid — the TPU analogue of the reference's fixed-size shuffle "
          "batch discipline.")
_register("shuffle_strict_pids", False, _parse_bool,
          "Raise ShuffleError on out-of-range partition ids (< 0 or > P) "
          "instead of routing them to the null partition and counting "
          "them in ShuffleMetrics.oob_rows.")
_register("shuffle_max_rounds", 64, int,
          "Cap on ShuffleService rounds per exchange; a plan that would "
          "exceed it RAISES per-round capacity (never drops rows) so the "
          "host-side round loop stays bounded under extreme skew.")
_register("spill_checksum", True, _parse_bool,
          "Record a CRC32 + byte length for every leaf the spill "
          "framework writes to disk and verify both on read-back "
          "(mem/spill.py).  A mismatch means the spilled copy is damaged: "
          "the handle rebuilds via its recompute= lineage when it has "
          "one, else raises SpillCorruptionError LOUDLY instead of "
          "silently computing on garbage.  Off = trust the filesystem.")
_register("shuffle_max_recoveries", 8, int,
          "Per-exchange budget for lineage recoveries in the "
          "ShuffleService (shuffle/service.py): each lost/corrupt "
          "PartitionBuffer rebuilt by re-running its map shards or "
          "re-driving its round counts against this bound "
          "(ShuffleMetrics.recovered_partitions); exceeding it raises "
          "ShuffleError so a flapping disk cannot loop a shuffle "
          "forever.")
_register("scan_morsel_rows", 4096, int,
          "Per-device rows in one scan morsel (shuffle/morsel.py): the "
          "streaming scan→shuffle pipeline decodes, maps and routes one "
          "morsel at a time so earlier exchange rounds drain while later "
          "morsels are still decoding.  Smaller = finer overlap and a "
          "lower device-resident peak; bigger = fewer map dispatches.")
_register("shuffle_stream", False, _parse_bool,
          "Lower Exchange(Scan) plans bound to a MorselSource through "
          "ShuffleService.exchange_stream (plan/compile.py) instead of "
          "materializing the whole scan before round 1 drains.  The "
          "streaming path is bit-identical on delivered rows; off = "
          "always materialize.")
_register("shuffle_scatter_engine", "auto", str,
          "Morsel->round-chunk scatter engine for the streaming shuffle "
          "map step (shuffle/service.py _scatter_step): 'lax' (XLA "
          "searchsorted + per-column scatters with the row->slot map "
          "rematerialized between programs), 'pallas' (ONE fused kernel "
          "computing pid, per-partition cumulative offsets, and every "
          "column's chunk scatter with the map resident in VMEM — "
          "interpret mode off-accelerator, bit-identical chunks), or "
          "'auto' (lax everywhere until a hardware round measures the "
          "kernel faster — PALLAS_MEMO.md's delete-or-measure rule).")
_register("shuffle_capacity_dcn", 0, int,
          "Override for the per-(sender, destination-host) slot capacity "
          "of hop one (DCN) in hierarchical exchanges "
          "(shuffle/planner.py plan_hierarchical); 0 = plan it from the "
          "observed count matrix instead of the flat worst-case grid.")
_register("shuffle_capacity_ici", 0, int,
          "Override for the per-(sender, destination-chip) slot capacity "
          "of hop two (ICI) in hierarchical exchanges "
          "(shuffle/planner.py plan_hierarchical); 0 = plan it from the "
          "observed count matrix.")
_register("chaos_trials", 4, int,
          "Seeded multi-fault trials per scenario in the chaos campaign "
          "(tools/chaos.py) on top of the exhaustive one-fault-per-trial "
          "sweep; each trial samples 2-3 recoverable fault rules with "
          "deterministic skip/count offsets from the campaign seed.")
# (the legacy `bench_rows` knob was dropped: nothing read it after the
# bench went per-platform — graftlint GL005 now fails on dead knobs)
_register("bench_rows_tpu", 1 << 24, int,
          "Full-size row count for the q6 bench on an accelerator; "
          "amortizes the ~63ms per-execution tunnel round-trip.")
_register("bench_rows_cpu", 1 << 20, int,
          "Full-size row count for the q6 bench on the CPU fallback "
          "(round 2's 2M-row CPU fallback blew the driver window; the "
          "round-4 scatter engine runs 1M rows in ~35ms, so the refine "
          "step fits the budget comfortably).")
_register("q6_group_path", "onehot", str,
          "Aggregation path for the q6 flagship bench: 'onehot' "
          "(group_by_onehot over the bench's static key domain, engine "
          "picked by q6_onehot_engine) or 'sort' (the general "
          "engine-selectable group_by — despite the legacy value name it "
          "honors the groupby_engine knob, so on CPU it runs the "
          "slot-table scatter engine, not a hard-wired sort).")
_register("q6_onehot_engine", "auto", str,
          "Engine for the q6 domain-key aggregation: 'auto' (scatter on "
          "CPU, xla on accelerators — measured both ways round 4), 'xla' "
          "(materialized one-hot contraction), 'pallas' (fused VMEM "
          "one-hot kernel), or 'scatter' (DOMAIN segment sums — keys "
          "index segments directly, no key normalization or slot table, "
          "unlike the general groupby_engine='scatter'; fast on CPU, 2 "
          "orders slow on TPU v5e).")
_register("group_sort_payload", "gather", str,
          "How sort-scan group_by moves agg values into sorted order: "
          "'gather' (sort only [keys..., row-id], then one take() per agg "
          "column — fewest sort operands) or 'ride' (agg words ride the "
          "sort as payload operands — no post-sort gathers).  The "
          "emulated-64-bit multi-operand sort measured ~1s/iter at 256K "
          "rows on v5e (round 3), so 'gather' is the default; 'ride' is "
          "kept for A/B.")
_register("groupby_engine", "auto", str,
          "General group_by engine (relational/aggregate.py): 'sort' "
          "(one stable multi-operand lax.sort + segmented scans — the "
          "accelerator engine), 'scatter' (open-addressing slot table + "
          "segment_* reductions, no row-sized sort — the CPU engine; "
          "falls back to sort via lax.cond when the slot table "
          "overflows), or 'auto' (scatter on CPU, sort on accelerators "
          "— XLA-CPU's lax.sort is its slowest primitive and its "
          "scatters the fastest; on TPU v5e the inversion holds, "
          "scatters at 16-150ms per 2M rows).")
_register("join_engine", "auto", str,
          "hash_join probe engine (relational/join.py): 'sort' "
          "(sorted build side + fused binary-search equal_range probe), "
          "'hash' (open-addressing slot table build + linear-probe "
          "walk; bit-identical output, no build-side lax.sort), or "
          "'auto' (hash on CPU, sort on accelerators — same hardware "
          "facts as groupby_engine).")
_register("encoded_execution", "auto", str,
          "Dictionary/RLE encoded columnar execution "
          "(columnar/encoded.py): 'on' encodes eligible columns at the "
          "host boundary (Parquet dictionary pages pass through as "
          "DictionaryColumn, bench inputs encode) and operators run on "
          "u32 codes with late materialization; 'off' decodes "
          "everything up front (the pre-PR-6 behavior); 'auto' = on for "
          "CPU, off for accelerators (the encoded paths lean on "
          "gathers, which serialize on the TPU VPU).  Bit-parity with "
          "the decoded path is the correctness contract either way — "
          "relational operators accept encoded and plain columns "
          "mixed, so the knob only gates where encoding is "
          "INTRODUCED.")
_register("packed_predicates", True, _parse_bool,
          "Evaluate comparison filters (<, <=, ==, !=, >=, >) directly "
          "on BitPackedColumn/FrameOfReferenceColumn residuals "
          "(columnar/encoded.py packed_filter_mask): the literal is "
          "transformed once per frame (subtract the reference, clamp to "
          "the pack-width domain, out-of-domain literals fold to "
          "constant masks) and u32 lanes compare without ever calling "
          "decode().  Bit-identical to decode-then-compare; off = "
          "always decode first (the exact-parity fallback).")
_register("zone_maps", True, _parse_bool,
          "Record a CRC32'd per-block min/max sidecar (ZoneMap) on "
          "packed columns at encode time and let MorselSource skip "
          "whole morsels a filter's zone-map check proves cold "
          "(shuffle/morsel.py), counted as ShuffleMetrics "
          "blocks_skipped/blocks_scanned.  A sidecar whose CRC or "
          "stats disagree raises ZoneMapCorruptionError LOUDLY at skip "
          "time — wrong rows are never silently returned.  Off = no "
          "sidecars, every block scanned.")
_register("scan_pruning", True, _parse_bool,
          "Push scan-level predicates into the Parquet footer "
          "(io/parquet.py / io/parquet_footer.py): row groups whose "
          "column min/max statistics cannot satisfy the predicate are "
          "dropped before any data page is read, and "
          "MorselSource.from_parquet never builds replays for them.  "
          "Groups with missing stats or nulls are conservatively kept; "
          "off = read every split-surviving row group.")
_register("plan_cache_size", 64, int,
          "Max compiled programs the plan cache (plan/cache.py) holds; "
          "LRU past it.  Keys are (canonical IR shape, input schema, "
          "config fingerprint), so a hit replays an already-traced "
          "program with zero retraces.")
_register("broadcast_threshold_rows", 1 << 16, int,
          "Adaptive-join build-side row cutoff (plan/adaptive.py): a "
          "strategy='auto' join whose observed build side is at or "
          "under this goes broadcast (spill-registered prebuilt build "
          "table), over it shuffled — Spark's "
          "autoBroadcastJoinThreshold, in rows.")
_register("adaptive_execution", True, _parse_bool,
          "Plan-time adaptive decisions (plan/adaptive.py): broadcast "
          "vs shuffled joins from observed build sizes, group-by engine "
          "from skewed counts passes, per-exchange round capacity from "
          "ShuffleMetrics.  Off = the static defaults everywhere "
          "(shuffled joins, knob-resolved engines).")
_register("q6_float_mode", "f32x3", str,
          "Float-sum mode for the q6 onehot path: 'f32x3' (exact Dekker "
          "split, MXU-native, order-nondeterministic rounding) or 'f64' "
          "(emulated f64 contraction, sort-path-compatible rounding).")
_register("serve_max_concurrent", 4, int,
          "Admission slots of the serving runtime (serve/runtime.py): "
          "how many tenant queries may hold a TaskContext at once; the "
          "rest wait in the admission queue (their wait is visible to "
          "the deadlock scan via ThreadStateRegistry).")
_register("serve_admit_timeout_s", 30.0, float,
          "Max seconds a submitted query may wait in the admission "
          "queue before failing with QueryTimeout (per admission "
          "attempt; re-admissions get a fresh window).")
_register("serve_stall_break_ms", 2000.0, float,
          "Serving-mode watchdog escalation: threads continuously "
          "blocked past this are treated as a cross-tenant deadlock "
          "cycle even while OTHER tenants keep running (the global scan "
          "only fires when every task thread is blocked), and the "
          "lowest-priority one is rolled back (RetryOOM).  0 disables; "
          "armed by ServeRuntime on construction.")
_register("serve_max_readmissions", 2, int,
          "How many times a query killed by its own timeout is backed "
          "off and re-admitted before QueryTimeout surfaces to the "
          "caller (bounded re-admission; external cancels never "
          "re-admit).")
_register("serve_backoff_ms", 50.0, float,
          "Base backoff between a query's timeout-kill and its "
          "re-admission, doubled per attempt (serve/runtime.py); the "
          "front door reuses it as the base delay of its session "
          "re-placement and worker-respawn ladders.")
_register("serve_workers", 2, int,
          "Executor worker processes the multi-process front door "
          "(serve/frontdoor.py) spawns; each hosts its own ServeRuntime, "
          "arena, spill store, and plan cache, with tenant sessions "
          "pinned to one worker over the local-socket protocol — one "
          "wedged interpreter can't take the fleet down.")
_register("serve_heartbeat_ms", 100.0, float,
          "Front-door heartbeat period: the supervisor pings every "
          "worker this often; a worker silent for ~3.5 periods (or "
          "whose native stall-breaker epoch keeps climbing with no "
          "completions) is declared wedged and SIGKILLed.")
_register("serve_respawn_max", 3, int,
          "Circuit breaker on worker respawns: how many times one "
          "worker slot may be respawned (with exponential backoff) "
          "before the front door stops replacing it and serves "
          "degraded on the surviving workers.")
_register("serve_shed_threshold", 0.5, float,
          "Degradation threshold: when the healthy fraction of "
          "configured workers drops below this, the front door sheds "
          "lowest-priority pending admissions beyond the surviving "
          "capacity (AdmissionShed) instead of queueing unboundedly.")
_register("serve_transport", "unix", str,
          "Fleet transport the front door serves workers over: 'unix' "
          "(one Unix-domain socket under the private fleet dir — the "
          "single-box default) or 'tcp' (workers dial the supervisor's "
          "127.0.0.1 listener; the multi-host placement path).  Both "
          "ride the same framed protocol with CRC32 trailers and "
          "frame deadlines (serve/wire.py).")
_register("serve_hosts", "", str,
          "Comma-separated logical host names for worker placement "
          "(e.g. 'hostA,hostB'): worker slots are distributed "
          "round-robin across hosts and the shutdown report records "
          "each worker's host.  More than one host forces the tcp "
          "transport (a Unix socket cannot span boxes).  Empty = one "
          "implicit local host.")
_register("serve_partition_grace_ms", 1500.0, float,
          "Split-brain budget: a worker that cannot reach the "
          "supervisor for this long SELF-FENCES — it revokes its own "
          "store epoch (shuffle/store.py revoke()) so a "
          "partitioned-but-alive worker can never zombie-commit, then "
          "drains and exits.  The supervisor mirrors the same window "
          "before declaring a silent connection a partition and "
          "re-placing the worker's sessions.")
_register("serve_reconnect_max", 4, int,
          "Bounded reconnect ladder: how many times a worker retries "
          "dialing the supervisor (exponential backoff, capped by "
          "serve_partition_grace_ms) after losing its CONNECTION "
          "before treating the link as a partition.  A successful "
          "re-dial re-attaches the same incarnation via its resume "
          "token — live sessions survive, nothing is re-run.")
_register("shuffle_store_dir", "", str,
          "Root of the persistent shuffle plane (shuffle/store.py): "
          "committed map outputs and drained round chunks land here "
          "(crash-safe tmp+fsync+rename commits, CRC-per-chunk "
          "manifests) so a replacement worker ADOPTS a dead worker's "
          "finished shards instead of lineage re-running them.  Empty "
          "disables the durable tier everywhere except the front door, "
          "which defaults its fleet to a store under its own fleet "
          "dir.")
_register("shuffle_store_retain", False, _parse_bool,
          "Whether FrontDoor.shutdown() leaves the shuffle store's "
          "committed entries on disk (for a later fleet to adopt) "
          "instead of reaping them with the fleet dir.  The zero-orphan "
          "shutdown report excludes the store subtree either way — "
          "retained entries are intentional, not leaks.")
_register("shuffle_store_max_attempts", 2, int,
          "Committed attempts the store keeps per (key, shard): after "
          "a successful commit, older attempts beyond this are pruned "
          "(adoption always reads the highest committed attempt, so "
          "extras only buy corruption fallback depth).  0 or negative "
          "keeps everything.")
_register("serve_data_plane", "auto", str,
          "How result BATCHES cross the supervisor<->worker boundary "
          "(serve/data_plane.py).  Control messages always stay on the "
          "framed JSON wire; this knob only routes columnar payloads: "
          "'shm' ships Arrow IPC bytes in a memfd segment passed by fd "
          "(SCM_RIGHTS, Unix transport only), 'frames' chunks the same "
          "IPC bytes into binary data frames on the existing socket "
          "(works over TCP), 'json' inlines a base64 payload in the "
          "result message (debug fallback; raises DataPlaneOverflow "
          "above the 16MB control-frame cap), and 'auto' picks shm on "
          "the unix transport and frames on tcp.")
_register("serve_segment_bytes", 1 << 20, int,
          "Chunk granularity of the zero-copy data plane: payloads are "
          "CRC32-stamped per chunk of this many bytes (torn-segment "
          "detection resolution) and the frames plane caps each binary "
          "data frame at this size so control messages interleave "
          "instead of queueing behind a monolithic payload frame.")
_register("shuffle_compress", "auto", str,
          "Pack columnar leaves before the all_to_all collective "
          "(shuffle/service.py): 'pack' bit-packs bool/dictionary-code "
          "leaves and frame-of-reference-packs int leaves into u32 lane "
          "words per round chunk (unpacked at the sanctioned reassembly "
          "seam), 'auto' packs only the cheap always-wins leaves "
          "(codes + bools), 'off' ships plain words.  Saved bytes are "
          "visible per-exchange as ShuffleMetrics.compressed_bytes_saved.")
_register("spill_codec", "off", str,
          "Codec for the spill framework's disk tier and the persistent "
          "shuffle store (mem/spill.py, shuffle/store.py): 'pack' "
          "frame-of-reference bit-packs eligible int leaves, 'block' runs "
          "a byte-wise RLE block codec over any leaf, 'off' writes raw "
          "npy.  CRCs are recorded over the STORED (compressed) bytes; "
          "a damaged frame fails loudly into the same quarantine + "
          "lineage-rebuild path as raw-leaf corruption.")
_register("result_cache", True, _parse_bool,
          "Fleet-wide result cache at the FrontDoor supervisor "
          "(serve/result_cache.py): submits that carry an input "
          "snapshot id are keyed (query signature, snapshot id, "
          "config-knob fingerprint) and repeat hits are served from the "
          "sealed Arrow IPC segment with zero compute and zero "
          "admission — bypassed entirely when off.  Submits WITHOUT a "
          "snapshot id are never cached regardless of this knob (no "
          "snapshot id, no caching, never a guess).")
_register("result_cache_bytes", 64 << 20, int,
          "Host-resident byte budget of the result cache.  Over budget, "
          "least-recently-served entries demote host->disk through the "
          "spill framework's checksummed paths before anything is "
          "dropped; 0 or negative disables the host bound (entries "
          "still honor per-tenant quotas).")
_register("result_cache_tenant_quota", 16 << 20, int,
          "Per-tenant byte quota of the result cache (host + disk "
          "tiers): inserts are charged to the submitting tenant, and a "
          "tenant over quota drops its own least-recently-served "
          "entries first — one dashboard's storm can never evict the "
          "whole fleet's cache.  0 or negative means unlimited.")
_register("serve_launcher", "local", str,
          "How worker processes come to exist (serve/launcher.py): "
          "'local' forks the worker argv on this box (today's spawn, "
          "verbatim); any other value is an agent/ssh-style command "
          "template (shlex-split, worker argv spliced at '{argv}' or "
          "appended) run per launch — the argv, resume token, and fence "
          "epoch are identical either way, so fencing and reattach work "
          "unmodified for remote workers.")
_register("serve_placement", "load", str,
          "Dispatch/placement policy of the front door (serve/"
          "elastic.py): 'load' scores workers by effective depth "
          "(placed sessions + pong queue depth), arena pressure, and "
          "stall suspicion, and spreads new incarnations across hosts "
          "fewest-live-slots-first; 'round_robin' keeps the legacy "
          "rotation — the comparison arm for bench.py --elastic.")
_register("serve_autoscale", False, _parse_bool,
          "Queue-driven autoscaling of the worker fleet (serve/"
          "elastic.py): admission-queue depth above the high-water mark "
          "for a full hold dwell spawns a worker; a slack queue with an "
          "idle worker retires one through the drain -> self-fence -> "
          "reap ladder.  Off = fixed capacity, today's behavior.")
_register("serve_autoscale_high_water", 4, int,
          "Admission-queue depth ABOVE which the autoscaler counts "
          "pressure; depth must stay above it for serve_autoscale_"
          "hold_ms before a worker is added.")
_register("serve_autoscale_low_water", 0, int,
          "Admission-queue depth AT OR BELOW which the autoscaler "
          "considers retiring an idle worker (drain ladder, never a "
          "kill).")
_register("serve_autoscale_min", 0, int,
          "Floor of the autoscaled fleet; 0 means the configured "
          "serve_workers is the floor (the fleet never shrinks below "
          "its starting size).")
_register("serve_autoscale_max", 8, int,
          "Ceiling of the autoscaled fleet: scale-ups stop here no "
          "matter the queue depth.")
_register("serve_autoscale_hold_ms", 250.0, float,
          "Debounce dwell for scale decisions: queue depth must hold "
          "above the high-water mark this long before a spawn, and "
          "consecutive scale actions are spaced by at least this much "
          "(up) / the idle dwell (down).")
_register("serve_autoscale_idle_ms", 1000.0, float,
          "How long a worker must sit with zero placed sessions and a "
          "zero pong queue depth before it is a retirement candidate.")
_register("serve_autoscale_drain_ms", 5000.0, float,
          "Drain deadline for a retiring worker: past it the drain is "
          "declared stuck and the supervisor escalates to the ordinary "
          "loss protocol (kill, fence, reap, re-place) — the "
          "drain_stuck fault kind proves this ladder.")
_register("serve_tenant_quota_bytes", 0, int,
          "Per-tenant admission byte quota at the front door: every "
          "submit is charged its est_bytes at admission, and a tenant "
          "over quota is rejected loudly with QuotaExceeded (counted "
          "in the shutdown report).  0 or negative means unlimited.")
_register("serve_tenant_quota_s", 0.0, float,
          "Per-tenant wall-clock quota at the front door: completed "
          "sessions charge their submit-to-finish seconds, and a "
          "tenant over quota has further submits rejected with "
          "QuotaExceeded.  0 or negative means unlimited.")
_register("serve_plan_warm", 4, int,
          "Warm plan-cache sharing on worker spawn: the supervisor "
          "records the last completed (kind, params) per TENANT CLASS "
          "(the tenant id up to its trailing -suffix) and ships up to "
          "this many entries to every new worker, which pre-traces "
          "them off the critical path so a fresh generation doesn't "
          "pay first-query compile for warm tenant classes.  0 "
          "disables the warm hand-off.")
_register("serve_journal", True, _parse_bool,
          "Write-ahead session journal of the front door (serve/"
          "journal.py): every session lifecycle transition and fleet "
          "fact is appended O_APPEND+fsync with a per-record CRC32 "
          "trailer to <fleet_dir>/journal.wal BEFORE the in-memory "
          "state mutates, so a supervisor crash loses no committed "
          "fact.  Off = PR-19 behavior (supervisor death loses the "
          "fleet).")
_register("serve_adopt", True, _parse_bool,
          "Restart adoption: a FrontDoor constructed with adopt_dir= "
          "pointed at a dead supervisor's fleet dir replays the "
          "journal, fences the dead generations (stamp/revoke), "
          "re-dials surviving workers over the resume-token hello, and "
          "re-places journal-known queued/replayable sessions.  Off = "
          "adopt_dir is refused loudly.")
_register("serve_orphan_grace_ms", 0.0, float,
          "Orphaned-worker self-fence grace: a worker that has heard "
          "NOTHING from its supervisor (no pings, no frames) for this "
          "long — even over a socket that still looks up — assumes the "
          "supervisor died without closing the link, and runs the "
          "self-fence ladder (revoke own epoch, sentinel, drain, exit "
          "rc=3) so a never-restarted supervisor leaks no processes "
          "and no unfenced generations.  0 disables (the reconnect "
          "ladder + serve_partition_grace_ms still cover dead-socket "
          "orphans).")


def get(key: str):
    """Resolve ``key``: programmatic override > env var > default."""
    entry = _REGISTRY.get(key)
    if entry is None:
        raise KeyError(f"unknown config key {key!r}; known: "
                       f"{sorted(_REGISTRY)}")
    with _lock:
        if key in _overrides:
            return _overrides[key]
    env = os.environ.get(_ENV_PREFIX + key.upper())
    if env is not None:
        return entry.parse(env)
    return entry.default


def set(key: str, value) -> None:  # noqa: A001 - mirrors a settings API
    if key not in _REGISTRY:
        raise KeyError(f"unknown config key {key!r}")
    with _lock:
        _overrides[key] = value


def reset(key: Optional[str] = None) -> None:
    with _lock:
        if key is None:
            _overrides.clear()
        else:
            _overrides.pop(key, None)


def describe() -> Dict[str, str]:
    """key -> one-line doc (for --help style listings)."""
    return {k: e.doc for k, e in sorted(_REGISTRY.items())}
