"""Pallas TPU kernels — only the ones that earn their place.

PALLAS_MEMO.md's decision rule admits a hand-written kernel in exactly
three situations; the single survivor here is the fused one-hot group-by
contraction (rule 1: XLA materializes a multi-GB ``[n, K]`` one-hot in
HBM just to contract it once; the kernel rebuilds each row-tile's
one-hot in VMEM and feeds the MXU directly).

Four hash kernels (murmur3/xxhash64 x int64/string) lived here through
round 4 "for parity/API only".  They were measured on real v5e (r3
session, corrected no-dedupe protocol) at 10-130x SLOWER than the jnp
formulations XLA fuses itself — murmur3_int64 6.8 vs 71.3 Mrows/s,
xxhash64_int64 6.1 vs 65.4, murmur3_string 0.16 vs 21.3, xxhash64_string
0.16 vs 10.4 — and were never the default path.  Deleted in r5 (VERDICT
r4 item 3): every kernel in this file must be measured-faster-than-XLA
on some shape or gone.  The winning jnp path lives in :mod:`hashing`
(reference parity: ``murmur_hash.cu:187``, ``xxhash64.cu:330``).

``interpret=None`` auto-falls back to the Pallas interpreter off-TPU, so
the kernel runs in CPU CI (an improvement over the reference, whose
kernels need a physical GPU — SURVEY.md §4).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 128


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() not in ("tpu", "axon")


# ---------------------------------------------------------------------------
# fused one-hot group-by contraction (the q6 aggregation hot loop)
# ---------------------------------------------------------------------------

# rows per grid step.  At 1024 rows the ~11KB int-payload DMA per step was
# grid-overhead dominated (16K steps at 16M rows); at 8192 the step's
# scoped VMEM — one-hot tile as int8 (1MB) AND f32 (4MB), the lanes iota
# (4MB), payload windows, all double-buffered — hit 21.24M against the
# 16M scoped-vmem limit on real v5e (Mosaic stack OOM, session r3b).
# 4096 halves the scaling terms (~10.6M) while keeping steps 4x fewer
# than the 1024 tiling.
GB_ROWS = 4096


def _onehot_tile(bucket_ref, kblock):
    """The tile's one-hot, built on the fly from [rows, 1] bucket ids —
    it lives only in VMEM/registers.  (The XLA formulation in
    :func:`relational.aggregate.group_by_onehot` materializes ``[n, K]``
    one-hots in HBM at every contraction dtype — multi-GB at bench row
    counts; here HBM traffic is just the payload columns.)"""
    b = bucket_ref[:]  # [rows, 1] int32; -1 = dead row (matches no lane)
    lanes = (jax.lax.broadcasted_iota(jnp.int32, (b.shape[0], LANES), 1)
             + kblock * LANES)
    return b == lanes


# Grid order: the K block is the OUTER dim and rows the INNER dim, so each
# output block is visited on consecutive grid steps — Pallas TPU keeps an
# output window resident in VMEM only across consecutive steps, and a
# revisited block would otherwise read back undefined HBM contents.
# Accumulation: int32 / f32; partials bound by |payload| <= 128 per row
# ⇒ callers chunk at 2^23 rows.

def _onehot_gb_kernel(bucket_ref, pi_ref, pf_ref, oi_ref, of_ref):
    i = pl.program_id(1)  # row tile (inner)

    @pl.when(i == 0)
    def _():
        oi_ref[:] = jnp.zeros_like(oi_ref)
        of_ref[:] = jnp.zeros_like(of_ref)

    oh = _onehot_tile(bucket_ref, pl.program_id(0))
    oi_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.int8), pi_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    of_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.float32), pf_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _onehot_gb_kernel_int(bucket_ref, pi_ref, oi_ref):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        oi_ref[:] = jnp.zeros_like(oi_ref)

    oh = _onehot_tile(bucket_ref, pl.program_id(0))
    oi_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.int8), pi_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32)


@partial(jax.jit, static_argnames=("domain", "interpret"))
def _onehot_gb_call(bucket, pi, pf, domain, interpret):
    n = bucket.shape[0]
    npad = -(-max(n, 1) // GB_ROWS) * GB_ROWS
    if npad != n:
        bucket = jnp.pad(bucket, (0, npad - n), constant_values=-1)
        pi = jnp.pad(pi, ((0, npad - n), (0, 0)))
        pf = jnp.pad(pf, ((0, npad - n), (0, 0)))
    KP = -(-domain // LANES) * LANES
    mi, mf = pi.shape[1], pf.shape[1]
    grid = (KP // LANES, npad // GB_ROWS)
    row_spec = lambda mcols: pl.BlockSpec(  # noqa: E731
        (GB_ROWS, mcols), lambda j, i: (i, jnp.int32(0)))
    out_spec = lambda mcols: pl.BlockSpec(  # noqa: E731
        (LANES, mcols), lambda j, i: (j, jnp.int32(0)))
    if mf == 0:  # int-only aggregations skip the float pass entirely
        oi = pl.pallas_call(
            _onehot_gb_kernel_int,
            out_shape=jax.ShapeDtypeStruct((KP, mi), jnp.int32),
            grid=grid,
            in_specs=[row_spec(1), row_spec(mi)],
            out_specs=out_spec(mi),
            interpret=interpret,
        )(bucket[:, None], pi)
        return oi[:domain], jnp.zeros((domain, 0), jnp.float32)
    oi, of = pl.pallas_call(
        _onehot_gb_kernel,
        out_shape=(jax.ShapeDtypeStruct((KP, mi), jnp.int32),
                   jax.ShapeDtypeStruct((KP, mf), jnp.float32)),
        grid=grid,
        in_specs=[row_spec(1), row_spec(mi), row_spec(mf)],
        out_specs=(out_spec(mi), out_spec(mf)),
        interpret=interpret,
    )(bucket[:, None], pi, pf)
    return oi[:domain], of[:domain]


def onehot_groupby_parts(bucket, int_payload, float_payload, domain,
                         interpret=None):
    """Fused group-by contraction: per-bucket column sums without an HBM
    one-hot.

    ``bucket``: int32[n], values in [0, domain) (use -1 for dead rows).
    ``int_payload``: int8[n, mi], |x| <= 128 per element (byte limbs,
    validity flags, count ones).  ``float_payload``: f32[n, mf] (Dekker
    limbs of f64 values).  Returns (int64[domain, mi], float64[domain,
    mf]) — int sums exact; float sums accumulate in f32 per 2^23-row
    chunk, then f64 across chunks.
    """
    interp = _auto_interpret(interpret)
    n = bucket.shape[0]
    CH = 1 << 23  # int32 partials hold n * 128 < 2^31 per chunk
    oi64 = jnp.zeros((domain, int_payload.shape[1]), jnp.int64)
    of64 = jnp.zeros((domain, float_payload.shape[1]), jnp.float64)
    for lo in range(0, max(n, 1), CH):
        oi, of = _onehot_gb_call(
            bucket[lo:lo + CH], int_payload[lo:lo + CH],
            float_payload[lo:lo + CH], domain, interp)
        oi64 = oi64 + oi.astype(jnp.int64)
        of64 = of64 + of.astype(jnp.float64)
    return oi64, of64
