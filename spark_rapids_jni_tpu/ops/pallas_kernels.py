"""Pallas TPU kernels for the hot hash path.

The jnp formulations in :mod:`hashing` leave fusion to XLA; these kernels
pin the whole per-row pipeline (seed -> mix per 4-byte block -> finalize ->
validity select) into one VMEM pass per tile, the shape SURVEY.md §2
prescribes for kernel work ("Pallas/XLA kernels, not Python stand-ins").
Tiles are ``(BLOCK_ROWS, 128)`` uint32 lanes — native VPU width; 64-bit
inputs arrive pre-split into lo/hi words so no 64-bit lanes are needed
(TPU has none).

Every entry point takes ``interpret=None`` and auto-falls back to the
Pallas interpreter off-TPU, so the same kernels run in CPU CI (an
improvement over the reference, whose kernels need a physical GPU —
SURVEY.md §4).

Parity: tests assert bit-identity against :mod:`hashing`'s golden-tested
murmur3/xxhash64 (reference ``murmur_hash.cu:187``, ``xxhash64.cu:330``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..columnar import types as T
from ..columnar.column import Column

LANES = 128
BLOCK_ROWS = 256  # 256x128 uint32 tile = 128KB/operand in VMEM


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() not in ("tpu", "axon")


def _rotl(x, r: int):
    return (x << r) | (x >> (32 - r))


# plain ints here: module-level jnp scalars would be captured constants,
# which pallas_call rejects; literals created inside the traced kernel fold
_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_C3 = 0xE6546B64


def _mix(h, k1):
    k1 = k1 * jnp.uint32(_C1)
    k1 = _rotl(k1, 15)
    k1 = k1 * jnp.uint32(_C2)
    h = h ^ k1
    h = _rotl(h, 13)
    return h * jnp.uint32(5) + jnp.uint32(_C3)


def _fmix(h):
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _murmur3_i64_kernel(lo_ref, hi_ref, valid_ref, seed_ref, out_ref):
    seed = seed_ref[0]
    h = jnp.full(lo_ref.shape, seed, jnp.uint32)
    h = _mix(h, lo_ref[:])
    h = _mix(h, hi_ref[:])
    h = h ^ jnp.uint32(8)
    h = _fmix(h)
    out_ref[:] = jnp.where(valid_ref[:] != 0, h,
                           jnp.full(lo_ref.shape, seed, jnp.uint32))


def _pad_tiles(a, n):
    rows = -(-n // LANES)
    rows = -(-rows // BLOCK_ROWS) * BLOCK_ROWS
    flat = jnp.zeros((rows * LANES,), a.dtype).at[:n].set(a)
    return flat.reshape(rows, LANES), rows


@partial(jax.jit, static_argnames=("interpret",))
def _murmur3_i64_call(lo, hi, valid, seed, interpret):
    n = lo.shape[0]
    lo2, rows = _pad_tiles(lo, n)
    hi2, _ = _pad_tiles(hi, n)
    va2, _ = _pad_tiles(valid.astype(jnp.uint32), n)
    grid = rows // BLOCK_ROWS
    out = pl.pallas_call(
        _murmur3_i64_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0))),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0))),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0))),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0))),
        interpret=interpret,
    )(lo2, hi2, va2, seed)
    return out.reshape(-1)[:n]


def murmur3_int64(col: Column, seed: int = 42,
                  interpret: Optional[bool] = None) -> Column:
    """Spark murmur3_32 of one int64 column (Pallas tile kernel)."""
    u = col.data.astype(jnp.int64)
    pair = jax.lax.bitcast_convert_type(u, jnp.uint32)
    lo, hi = pair[..., 0], pair[..., 1]
    h = _murmur3_i64_call(lo, hi, col.validity,
                          jnp.asarray([seed & 0xFFFFFFFF], jnp.uint32),
                          _auto_interpret(interpret))
    out = jax.lax.bitcast_convert_type(h, jnp.int32)
    return Column(out, jnp.ones_like(col.validity), T.INT32)


# ---------------------------------------------------------------------------
# xxhash64 (uint64 emulated as lo/hi uint32 pairs inside the kernel)
# ---------------------------------------------------------------------------

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P5 = 0x27D4EB2F165667C5


def _c64(v):
    return (jnp.uint32(v & 0xFFFFFFFF), jnp.uint32((v >> 32) & 0xFFFFFFFF))


def _add64(a, b):
    lo = a[0] + b[0]
    carry = (lo < a[0]).astype(jnp.uint32)
    return lo, a[1] + b[1] + carry


def _xor64(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def _mul64(a, b):
    """Full 64-bit product of two (lo, hi) uint32 pairs (mod 2^64)."""
    a0, a1 = a
    b0, b1 = b
    # 16-bit limb products to stay exact in uint32 arithmetic
    a0l, a0h = a0 & jnp.uint32(0xFFFF), a0 >> 16
    b0l, b0h = b0 & jnp.uint32(0xFFFF), b0 >> 16
    ll = a0l * b0l
    lh = a0l * b0h
    hl = a0h * b0l
    hh = a0h * b0h
    mid = (ll >> 16) + (lh & jnp.uint32(0xFFFF)) + (hl & jnp.uint32(0xFFFF))
    lo = (ll & jnp.uint32(0xFFFF)) | (mid << 16)
    carry = (mid >> 16) + (lh >> 16) + (hl >> 16) + hh
    hi = carry + a0 * b1 + a1 * b0
    return lo, hi


def _rotl64p(a, r: int):
    lo, hi = a
    if r == 32:
        return hi, lo
    if r < 32:
        return ((lo << r) | (hi >> (32 - r)), (hi << r) | (lo >> (32 - r)))
    r -= 32
    lo, hi = hi, lo
    return ((lo << r) | (hi >> (32 - r)), (hi << r) | (lo >> (32 - r)))


def _shr64(a, r: int):
    lo, hi = a
    if r >= 32:
        return hi >> (r - 32), jnp.zeros_like(hi)
    return (lo >> r) | (hi << (32 - r)), hi >> r


def _xxh_kernel(lo_ref, hi_ref, valid_ref, seed_ref, out_lo_ref, out_hi_ref):
    shape = lo_ref.shape
    seed = (jnp.full(shape, seed_ref[0], jnp.uint32),
            jnp.full(shape, seed_ref[1], jnp.uint32))
    p1 = _c64(_P1)
    p2 = _c64(_P2)
    p3 = _c64(_P3)
    p5 = _c64(_P5)

    def bc(c):
        return (jnp.broadcast_to(c[0], shape), jnp.broadcast_to(c[1], shape))

    h = _add64(_add64(seed, bc(p5)), bc(_c64(8)))
    k = (lo_ref[:], hi_ref[:])
    k = _mul64(k, bc(p2))
    k = _rotl64p(k, 31)
    k = _mul64(k, bc(p1))
    h = _xor64(h, k)
    h = _rotl64p(h, 27)
    h = _mul64(h, bc(p1))
    h = _add64(h, bc(_c64(0x85EBCA77C2B2AE63)))
    # finalize
    h = _xor64(h, _shr64(h, 33))
    h = _mul64(h, bc(p2))
    h = _xor64(h, _shr64(h, 29))
    h = _mul64(h, bc(p3))
    h = _xor64(h, _shr64(h, 32))
    live = valid_ref[:] != 0
    out_lo_ref[:] = jnp.where(live, h[0], seed[0])
    out_hi_ref[:] = jnp.where(live, h[1], seed[1])


@partial(jax.jit, static_argnames=("interpret",))
def _xxh_i64_call(lo, hi, valid, seed_pair, interpret):
    n = lo.shape[0]
    lo2, rows = _pad_tiles(lo, n)
    hi2, _ = _pad_tiles(hi, n)
    va2, _ = _pad_tiles(valid.astype(jnp.uint32), n)
    grid = rows // BLOCK_ROWS
    out_lo, out_hi = pl.pallas_call(
        _xxh_kernel,
        out_shape=(jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
                   jax.ShapeDtypeStruct((rows, LANES), jnp.uint32)),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0))),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0))),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0))),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0))),
                   pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, jnp.int32(0)))),
        interpret=interpret,
    )(lo2, hi2, va2, seed_pair)
    return out_lo.reshape(-1)[:n], out_hi.reshape(-1)[:n]


def xxhash64_int64(col: Column, seed: int = 42,
                   interpret: Optional[bool] = None) -> Column:
    """Spark xxhash64 of one int64 column (Pallas tile kernel).

    The whole 64-bit pipeline (multiplies included) runs on 32-bit lanes —
    ``_mul64`` builds the product from 16-bit limb partials, the same
    discipline the decimal128 kernels use.
    """
    u = col.data.astype(jnp.int64)
    pair = jax.lax.bitcast_convert_type(u, jnp.uint32)
    lo, hi = pair[..., 0], pair[..., 1]
    seed64 = seed & 0xFFFFFFFFFFFFFFFF
    seed_pair = jnp.asarray([seed64 & 0xFFFFFFFF, seed64 >> 32], jnp.uint32)
    out_lo, out_hi = _xxh_i64_call(lo, hi, col.validity, seed_pair,
                                   _auto_interpret(interpret))
    from .hashing import _u64_to_i64

    u64 = out_lo.astype(jnp.uint64) | (out_hi.astype(jnp.uint64)
                                       << jnp.uint64(32))
    return Column(_u64_to_i64(u64), jnp.ones_like(col.validity), T.INT64)


# ---------------------------------------------------------------------------
# murmur3 over byte strings (shuffle partition ids on string keys)
# ---------------------------------------------------------------------------

def _murmur3_str_kernel(words_ref, len_ref, valid_ref, seed_ref, out_ref):
    """One pass over the word axis handles blocks AND the tail uniformly.

    Layout is word-major: ``words_ref[j, :]`` is the j-th 4-byte word of
    128 rows (one sublane read per step — no cross-lane gathers).  The
    Spark tail (<=3 sign-extended bytes) always lives in word
    ``nblocks``, so each step applies the block mix where ``j < nblocks``
    and the ordered tail mixes where ``j == nblocks``.
    """
    W = words_ref.shape[0]
    lengths = len_ref[0, :].astype(jnp.int32)
    nblocks = lengths // 4
    seed = seed_ref[0]
    h0 = jnp.full(lengths.shape, seed, jnp.uint32)

    def body(j, h):
        w = words_ref[j, :]
        h = jnp.where(j < nblocks, _mix_mm3(h, w), h)
        is_tail = j == nblocks
        rem = lengths - 4 * j
        for t in range(3):
            b = (w >> jnp.uint32(8 * t)) & jnp.uint32(0xFF)
            # Java byte -> int sign-extends
            k1 = jnp.where(b >= jnp.uint32(0x80),
                           b | jnp.uint32(0xFFFFFF00), b)
            h = jnp.where(is_tail & (t < rem), _mix_mm3(h, k1), h)
        return h

    h = jax.lax.fori_loop(0, W, body, h0)
    h = h ^ lengths.astype(jnp.uint32)
    h = _fmix(h)
    out_ref[0, :] = jnp.where(valid_ref[0, :] != 0, h, h0)


# murmur3 block mix shared with the int64 kernel (different name to avoid
# shadowing hashing._mm3_mix's (h, k1) jnp-scalar signature)
def _mix_mm3(h, k1):
    return _mix(h, k1)


def murmur3_string(col, seed: int = 42,
                   interpret: Optional[bool] = None) -> Column:
    """Spark murmur3_32 of one string column (Pallas word-major kernel).

    Bit-identical to :func:`hashing.murmur3_bytes` (reference
    ``murmur_hash.cuh`` tail handling); null rows return the seed, like a
    null column contributing nothing to the row hash.
    """
    chars, lengths, valid = col.chars, col.lengths, col.validity
    n, L = chars.shape
    Lp = -(-max(L, 4) // 4) * 4
    if Lp != L:
        chars = jnp.pad(chars, ((0, 0), (0, Lp - L)))
    W = Lp // 4
    words = jax.lax.bitcast_convert_type(
        chars.reshape(n, W, 4), jnp.uint32)        # little-endian combine
    words_t = words.T                              # [W, n]

    npad = -(-max(n, 1) // LANES) * LANES
    if npad != n:
        words_t = jnp.pad(words_t, ((0, 0), (0, npad - n)))
        lengths = jnp.pad(lengths, (0, npad - n))
        valid = jnp.pad(valid, (0, npad - n))
    grid = npad // LANES

    out = pl.pallas_call(
        _murmur3_str_kernel,
        out_shape=jax.ShapeDtypeStruct((1, npad), jnp.uint32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((W, LANES), lambda i: (jnp.int32(0), i)),
            pl.BlockSpec((1, LANES), lambda i: (jnp.int32(0), i)),
            pl.BlockSpec((1, LANES), lambda i: (jnp.int32(0), i)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, LANES), lambda i: (jnp.int32(0), i)),
        interpret=_auto_interpret(interpret),
    )(
        words_t,
        lengths.astype(jnp.int32)[None, :],
        valid.astype(jnp.uint32)[None, :],
        jnp.asarray([seed & 0xFFFFFFFF], jnp.uint32),
    )
    h = out[0, :n]
    return Column(jax.lax.bitcast_convert_type(h, jnp.int32),
                  jnp.ones((n,), jnp.bool_), T.INT32)


# ---------------------------------------------------------------------------
# xxhash64 over byte strings (word-major layout like murmur3_string)
# ---------------------------------------------------------------------------

_P4 = 0x85EBCA77C2B2AE63


def _where64(m, a, b):
    return jnp.where(m, a[0], b[0]), jnp.where(m, a[1], b[1])


def _xxh_str_kernel(words_ref, len_ref, valid_ref, seed_ref,
                    out_lo_ref, out_hi_ref):
    """Full xxhash64 byte-stream pipeline in three uniform passes over the
    word axis: 32-byte stripes, then 8-byte chunks, then the 4-byte word +
    trailing bytes.  All per-row offsets (stripe count, chunk count, tail
    word) are data, never indices — every sublane read is uniform across
    lanes, so no cross-lane gathers (same discipline as
    _murmur3_str_kernel; reference xxhash64.cu processes a row per thread
    and has no such constraint).
    """
    W = words_ref.shape[0]
    lengths = len_ref[0, :].astype(jnp.int32)
    shape = lengths.shape
    seed = (jnp.full(shape, seed_ref[0], jnp.uint32),
            jnp.full(shape, seed_ref[1], jnp.uint32))

    def bc(c):
        return (jnp.broadcast_to(c[0], shape), jnp.broadcast_to(c[1], shape))

    p1, p2, p3 = bc(_c64(_P1)), bc(_c64(_P2)), bc(_c64(_P3))
    p4, p5 = bc(_c64(_P4)), bc(_c64(_P5))

    nstripes = lengths // 32
    n8 = (lengths % 32) // 8
    has4 = (lengths % 8) >= 4

    def u64_at(w_lo, w_hi):
        return (w_lo, w_hi)

    # --- pass 1: 32-byte stripes ------------------------------------
    def acc(v, k, m):
        nv = _mul64(_rotl64p(_add64(v, _mul64(k, p2)), 31), p1)
        return _where64(m, nv, v)

    def stripe_body(s, vs):
        v1, v2, v3, v4 = vs
        m = s < nstripes
        v1 = acc(v1, u64_at(words_ref[8 * s + 0, :],
                            words_ref[8 * s + 1, :]), m)
        v2 = acc(v2, u64_at(words_ref[8 * s + 2, :],
                            words_ref[8 * s + 3, :]), m)
        v3 = acc(v3, u64_at(words_ref[8 * s + 4, :],
                            words_ref[8 * s + 5, :]), m)
        v4 = acc(v4, u64_at(words_ref[8 * s + 6, :],
                            words_ref[8 * s + 7, :]), m)
        return v1, v2, v3, v4

    v1 = _add64(seed, bc(_c64((_P1 + _P2) & 0xFFFFFFFFFFFFFFFF)))
    v2 = _add64(seed, p2)
    v3 = seed
    v4 = _add64(seed, bc(_c64((-_P1) & 0xFFFFFFFFFFFFFFFF)))
    if W >= 8:
        v1, v2, v3, v4 = jax.lax.fori_loop(
            0, W // 8, stripe_body, (v1, v2, v3, v4))

    h_long = _add64(
        _add64(_rotl64p(v1, 1), _rotl64p(v2, 7)),
        _add64(_rotl64p(v3, 12), _rotl64p(v4, 18)))

    def merge(h, v):
        vv = _mul64(_rotl64p(_mul64(v, p2), 31), p1)
        return _add64(_mul64(_xor64(h, vv), p1), p4)

    for v in (v1, v2, v3, v4):
        h_long = merge(h_long, v)
    h = _where64(lengths >= 32, h_long, _add64(seed, p5))
    len64 = (jax.lax.bitcast_convert_type(lengths, jnp.uint32),
             jnp.zeros(shape, jnp.uint32))
    h = _add64(h, len64)

    # --- pass 2: 8-byte chunks after the stripes ---------------------
    def mix8(h, k):
        kk = _mul64(_rotl64p(_mul64(k, p2), 31), p1)
        return _add64(_mul64(_rotl64p(_xor64(h, kk), 27), p1), p4)

    npairs = W // 2

    def chunk8_body(p, h):
        c = p - 4 * nstripes
        m = (c >= 0) & (c < n8)
        k = u64_at(words_ref[2 * p, :], words_ref[2 * p + 1, :])
        return _where64(m, mix8(h, k), h)

    if npairs > 0:
        h = jax.lax.fori_loop(0, npairs, chunk8_body, h)

    # --- pass 3: the optional 4-byte word + trailing bytes -----------
    w4 = 8 * nstripes + 2 * n8
    wb = w4 + has4.astype(jnp.int32)

    def mix4(h, w):
        k = _mul64((w, jnp.zeros(shape, jnp.uint32)), p1)
        return _add64(_mul64(_rotl64p(_xor64(h, k), 23), p2), p3)

    def mix1(h, byte_u32):
        k = _mul64((byte_u32, jnp.zeros(shape, jnp.uint32)), p5)
        return _mul64(_rotl64p(_xor64(h, k), 11), p1)

    def tail_body(w, h):
        word = words_ref[w, :]
        h = _where64((w == w4) & has4, mix4(h, word), h)
        at_tail = w == wb
        nbytes = lengths - 4 * wb
        for t in range(3):
            b = (word >> jnp.uint32(8 * t)) & jnp.uint32(0xFF)
            h = _where64(at_tail & (t < nbytes), mix1(h, b), h)
        return h

    h = jax.lax.fori_loop(0, W, tail_body, h)

    # finalize
    h = _xor64(h, _shr64(h, 33))
    h = _mul64(h, p2)
    h = _xor64(h, _shr64(h, 29))
    h = _mul64(h, p3)
    h = _xor64(h, _shr64(h, 32))
    live = valid_ref[0, :] != 0
    out_lo_ref[0, :] = jnp.where(live, h[0], seed[0])
    out_hi_ref[0, :] = jnp.where(live, h[1], seed[1])


def xxhash64_string(col, seed: int = 42,
                    interpret: Optional[bool] = None) -> Column:
    """Spark xxhash64 of one string column (Pallas word-major kernel);
    bit-identical to :func:`hashing.xxhash64_bytes`.  Null rows return
    the seed, like a null column contributing nothing to the row hash."""
    chars, lengths, valid = col.chars, col.lengths, col.validity
    n, L = chars.shape
    # pad the word axis to a multiple of 8 (one full stripe) so every
    # sublane index 8s+k .. 2p+1 .. stays in range
    Lp = -(-max(L, 32) // 32) * 32
    if Lp != L:
        chars = jnp.pad(chars, ((0, 0), (0, Lp - L)))
    W = Lp // 4
    words = jax.lax.bitcast_convert_type(
        chars.reshape(n, W, 4), jnp.uint32)
    words_t = words.T

    npad = -(-max(n, 1) // LANES) * LANES
    if npad != n:
        words_t = jnp.pad(words_t, ((0, 0), (0, npad - n)))
        lengths = jnp.pad(lengths, (0, npad - n))
        valid = jnp.pad(valid, (0, npad - n))
    grid = npad // LANES

    seed64 = seed & 0xFFFFFFFFFFFFFFFF
    out_lo, out_hi = pl.pallas_call(
        _xxh_str_kernel,
        out_shape=(jax.ShapeDtypeStruct((1, npad), jnp.uint32),
                   jax.ShapeDtypeStruct((1, npad), jnp.uint32)),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((W, LANES), lambda i: (jnp.int32(0), i)),
            pl.BlockSpec((1, LANES), lambda i: (jnp.int32(0), i)),
            pl.BlockSpec((1, LANES), lambda i: (jnp.int32(0), i)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(pl.BlockSpec((1, LANES), lambda i: (jnp.int32(0), i)),
                   pl.BlockSpec((1, LANES), lambda i: (jnp.int32(0), i))),
        interpret=_auto_interpret(interpret),
    )(
        words_t,
        lengths.astype(jnp.int32)[None, :],
        valid.astype(jnp.uint32)[None, :],
        jnp.asarray([seed64 & 0xFFFFFFFF, seed64 >> 32], jnp.uint32),
    )
    from .hashing import _u64_to_i64

    u64 = (out_lo[0, :n].astype(jnp.uint64)
           | (out_hi[0, :n].astype(jnp.uint64) << jnp.uint64(32)))
    return Column(_u64_to_i64(u64), jnp.ones((n,), jnp.bool_), T.INT64)


# ---------------------------------------------------------------------------
# fused one-hot group-by contraction (the q6 aggregation hot loop)
# ---------------------------------------------------------------------------

# rows per grid step.  At 1024 rows the ~11KB int-payload DMA per step was
# grid-overhead dominated (16K steps at 16M rows); at 8192 the step's
# scoped VMEM — one-hot tile as int8 (1MB) AND f32 (4MB), the lanes iota
# (4MB), payload windows, all double-buffered — hit 21.24M against the
# 16M scoped-vmem limit on real v5e (Mosaic stack OOM, session r3b).
# 4096 halves the scaling terms (~10.6M) while keeping steps 4x fewer
# than the 1024 tiling.
GB_ROWS = 4096


def _onehot_tile(bucket_ref, kblock):
    """The tile's one-hot, built on the fly from [rows, 1] bucket ids —
    it lives only in VMEM/registers.  (The XLA formulation in
    :func:`relational.aggregate.group_by_onehot` materializes ``[n, K]``
    one-hots in HBM at every contraction dtype — multi-GB at bench row
    counts; here HBM traffic is just the payload columns.)"""
    b = bucket_ref[:]  # [rows, 1] int32; -1 = dead row (matches no lane)
    lanes = (jax.lax.broadcasted_iota(jnp.int32, (b.shape[0], LANES), 1)
             + kblock * LANES)
    return b == lanes


# Grid order: the K block is the OUTER dim and rows the INNER dim, so each
# output block is visited on consecutive grid steps — Pallas TPU keeps an
# output window resident in VMEM only across consecutive steps, and a
# revisited block would otherwise read back undefined HBM contents.
# Accumulation: int32 / f32; partials bound by |payload| <= 128 per row
# ⇒ callers chunk at 2^23 rows.

def _onehot_gb_kernel(bucket_ref, pi_ref, pf_ref, oi_ref, of_ref):
    i = pl.program_id(1)  # row tile (inner)

    @pl.when(i == 0)
    def _():
        oi_ref[:] = jnp.zeros_like(oi_ref)
        of_ref[:] = jnp.zeros_like(of_ref)

    oh = _onehot_tile(bucket_ref, pl.program_id(0))
    oi_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.int8), pi_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    of_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.float32), pf_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _onehot_gb_kernel_int(bucket_ref, pi_ref, oi_ref):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        oi_ref[:] = jnp.zeros_like(oi_ref)

    oh = _onehot_tile(bucket_ref, pl.program_id(0))
    oi_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.int8), pi_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32)


@partial(jax.jit, static_argnames=("domain", "interpret"))
def _onehot_gb_call(bucket, pi, pf, domain, interpret):
    n = bucket.shape[0]
    npad = -(-max(n, 1) // GB_ROWS) * GB_ROWS
    if npad != n:
        bucket = jnp.pad(bucket, (0, npad - n), constant_values=-1)
        pi = jnp.pad(pi, ((0, npad - n), (0, 0)))
        pf = jnp.pad(pf, ((0, npad - n), (0, 0)))
    KP = -(-domain // LANES) * LANES
    mi, mf = pi.shape[1], pf.shape[1]
    grid = (KP // LANES, npad // GB_ROWS)
    row_spec = lambda mcols: pl.BlockSpec(  # noqa: E731
        (GB_ROWS, mcols), lambda j, i: (i, jnp.int32(0)))
    out_spec = lambda mcols: pl.BlockSpec(  # noqa: E731
        (LANES, mcols), lambda j, i: (j, jnp.int32(0)))
    if mf == 0:  # int-only aggregations skip the float pass entirely
        oi = pl.pallas_call(
            _onehot_gb_kernel_int,
            out_shape=jax.ShapeDtypeStruct((KP, mi), jnp.int32),
            grid=grid,
            in_specs=[row_spec(1), row_spec(mi)],
            out_specs=out_spec(mi),
            interpret=interpret,
        )(bucket[:, None], pi)
        return oi[:domain], jnp.zeros((domain, 0), jnp.float32)
    oi, of = pl.pallas_call(
        _onehot_gb_kernel,
        out_shape=(jax.ShapeDtypeStruct((KP, mi), jnp.int32),
                   jax.ShapeDtypeStruct((KP, mf), jnp.float32)),
        grid=grid,
        in_specs=[row_spec(1), row_spec(mi), row_spec(mf)],
        out_specs=(out_spec(mi), out_spec(mf)),
        interpret=interpret,
    )(bucket[:, None], pi, pf)
    return oi[:domain], of[:domain]


def onehot_groupby_parts(bucket, int_payload, float_payload, domain,
                         interpret=None):
    """Fused group-by contraction: per-bucket column sums without an HBM
    one-hot.

    ``bucket``: int32[n], values in [0, domain) (use -1 for dead rows).
    ``int_payload``: int8[n, mi], |x| <= 128 per element (byte limbs,
    validity flags, count ones).  ``float_payload``: f32[n, mf] (Dekker
    limbs of f64 values).  Returns (int64[domain, mi], float64[domain,
    mf]) — int sums exact; float sums accumulate in f32 per 2^23-row
    chunk, then f64 across chunks.
    """
    interp = _auto_interpret(interpret)
    n = bucket.shape[0]
    CH = 1 << 23  # int32 partials hold n * 128 < 2^31 per chunk
    oi64 = jnp.zeros((domain, int_payload.shape[1]), jnp.int64)
    of64 = jnp.zeros((domain, float_payload.shape[1]), jnp.float64)
    for lo in range(0, max(n, 1), CH):
        oi, of = _onehot_gb_call(
            bucket[lo:lo + CH], int_payload[lo:lo + CH],
            float_payload[lo:lo + CH], domain, interp)
        oi64 = oi64 + oi.astype(jnp.int64)
        of64 = of64 + of.astype(jnp.float64)
    return oi64, of64
