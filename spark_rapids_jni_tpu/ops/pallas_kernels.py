"""Pallas TPU kernels — only the ones that earn their place.

PALLAS_MEMO.md's decision rule admits a hand-written kernel in exactly
three situations; four kernels live here today:

- the fused one-hot group-by contraction (rule 1: XLA materializes a
  multi-GB ``[n, K]`` one-hot in HBM just to contract it once; the
  kernel rebuilds each row-tile's one-hot in VMEM and feeds the MXU
  directly) — the only one that is a *default* on TPU;
- the fused slot-table build and probe (rule 3: the lax formulation in
  :mod:`relational.hashtable` is a ``while_loop`` whose whole-table
  carry round-trips HBM every CAS round; the kernels keep the table
  resident in VMEM across rounds, emitting bit-identical
  ``(owner, slot, overflow)`` / ``(found, slot)``), and
- the fused radix partition scatter for the shuffle map step (rule 2:
  XLA lowers the per-row routed write into per-element dynamic-update
  scatters; the kernel walks a morsel tile once and routes rows to
  partition chunks in a single pass).

The last three are an opt-in engine tier (``groupby_engine`` /
``join_engine`` / ``shuffle_scatter_engine`` = ``"pallas"``): under the
delete-or-measure rule they stay off the ``auto`` path until a hardware
round measures them faster than XLA on some shape.  The bench rows
``slot_build_pallas`` / ``slot_probe_pallas`` / ``partition_scatter_pallas``
and ``bench.py --multidevice`` are the standing A/B vehicle; CPU CI runs
them in interpret mode for parity only (PALLAS_MEMO.md r14 ledger).

Four hash kernels (murmur3/xxhash64 x int64/string) lived here through
round 4 "for parity/API only".  They were measured on real v5e (r3
session, corrected no-dedupe protocol) at 10-130x SLOWER than the jnp
formulations XLA fuses itself — murmur3_int64 6.8 vs 71.3 Mrows/s,
xxhash64_int64 6.1 vs 65.4, murmur3_string 0.16 vs 21.3, xxhash64_string
0.16 vs 10.4 — and were never the default path.  Deleted in r5 (VERDICT
r4 item 3): every kernel in this file must be measured-faster-than-XLA
on some shape or gone.  The winning jnp path lives in :mod:`hashing`
(reference parity: ``murmur_hash.cu:187``, ``xxhash64.cu:330``).

``interpret=None`` auto-falls back to the Pallas interpreter off-TPU, so
the kernel runs in CPU CI (an improvement over the reference, whose
kernels need a physical GPU — SURVEY.md §4).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 128


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() not in ("tpu", "axon")


# ---------------------------------------------------------------------------
# fused one-hot group-by contraction (the q6 aggregation hot loop)
# ---------------------------------------------------------------------------

# rows per grid step.  At 1024 rows the ~11KB int-payload DMA per step was
# grid-overhead dominated (16K steps at 16M rows); at 8192 the step's
# scoped VMEM — one-hot tile as int8 (1MB) AND f32 (4MB), the lanes iota
# (4MB), payload windows, all double-buffered — hit 21.24M against the
# 16M scoped-vmem limit on real v5e (Mosaic stack OOM, session r3b).
# 4096 halves the scaling terms (~10.6M) while keeping steps 4x fewer
# than the 1024 tiling.
GB_ROWS = 4096


def _onehot_tile(bucket_ref, kblock):
    """The tile's one-hot, built on the fly from [rows, 1] bucket ids —
    it lives only in VMEM/registers.  (The XLA formulation in
    :func:`relational.aggregate.group_by_onehot` materializes ``[n, K]``
    one-hots in HBM at every contraction dtype — multi-GB at bench row
    counts; here HBM traffic is just the payload columns.)"""
    b = bucket_ref[:]  # [rows, 1] int32; -1 = dead row (matches no lane)
    lanes = (jax.lax.broadcasted_iota(jnp.int32, (b.shape[0], LANES), 1)
             + kblock * LANES)
    return b == lanes


# Grid order: the K block is the OUTER dim and rows the INNER dim, so each
# output block is visited on consecutive grid steps — Pallas TPU keeps an
# output window resident in VMEM only across consecutive steps, and a
# revisited block would otherwise read back undefined HBM contents.
# Accumulation: int32 / f32; partials bound by |payload| <= 128 per row
# ⇒ callers chunk at 2^23 rows.

def _onehot_gb_kernel(bucket_ref, pi_ref, pf_ref, oi_ref, of_ref):
    i = pl.program_id(1)  # row tile (inner)

    @pl.when(i == 0)
    def _():
        oi_ref[:] = jnp.zeros_like(oi_ref)
        of_ref[:] = jnp.zeros_like(of_ref)

    oh = _onehot_tile(bucket_ref, pl.program_id(0))
    oi_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.int8), pi_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    of_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.float32), pf_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _onehot_gb_kernel_int(bucket_ref, pi_ref, oi_ref):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        oi_ref[:] = jnp.zeros_like(oi_ref)

    oh = _onehot_tile(bucket_ref, pl.program_id(0))
    oi_ref[:] += jax.lax.dot_general(
        oh.astype(jnp.int8), pi_ref[:],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32)


@partial(jax.jit, static_argnames=("domain", "interpret"))
def _onehot_gb_call(bucket, pi, pf, domain, interpret):
    n = bucket.shape[0]
    npad = -(-max(n, 1) // GB_ROWS) * GB_ROWS
    if npad != n:
        bucket = jnp.pad(bucket, (0, npad - n), constant_values=-1)
        pi = jnp.pad(pi, ((0, npad - n), (0, 0)))
        pf = jnp.pad(pf, ((0, npad - n), (0, 0)))
    KP = -(-domain // LANES) * LANES
    mi, mf = pi.shape[1], pf.shape[1]
    grid = (KP // LANES, npad // GB_ROWS)
    row_spec = lambda mcols: pl.BlockSpec(  # noqa: E731
        (GB_ROWS, mcols), lambda j, i: (i, jnp.int32(0)))
    out_spec = lambda mcols: pl.BlockSpec(  # noqa: E731
        (LANES, mcols), lambda j, i: (j, jnp.int32(0)))
    if mf == 0:  # int-only aggregations skip the float pass entirely
        oi = pl.pallas_call(
            _onehot_gb_kernel_int,
            out_shape=jax.ShapeDtypeStruct((KP, mi), jnp.int32),
            grid=grid,
            in_specs=[row_spec(1), row_spec(mi)],
            out_specs=out_spec(mi),
            interpret=interpret,
        )(bucket[:, None], pi)
        return oi[:domain], jnp.zeros((domain, 0), jnp.float32)
    oi, of = pl.pallas_call(
        _onehot_gb_kernel,
        out_shape=(jax.ShapeDtypeStruct((KP, mi), jnp.int32),
                   jax.ShapeDtypeStruct((KP, mf), jnp.float32)),
        grid=grid,
        in_specs=[row_spec(1), row_spec(mi), row_spec(mf)],
        out_specs=(out_spec(mi), out_spec(mf)),
        interpret=interpret,
    )(bucket[:, None], pi, pf)
    return oi[:domain], of[:domain]


def onehot_groupby_parts(bucket, int_payload, float_payload, domain,
                         interpret=None):
    """Fused group-by contraction: per-bucket column sums without an HBM
    one-hot.

    ``bucket``: int32[n], values in [0, domain) (use -1 for dead rows).
    ``int_payload``: int8[n, mi], |x| <= 128 per element (byte limbs,
    validity flags, count ones).  ``float_payload``: f32[n, mf] (Dekker
    limbs of f64 values).  Returns (int64[domain, mi], float64[domain,
    mf]) — int sums exact; float sums accumulate in f32 per 2^23-row
    chunk, then f64 across chunks.
    """
    interp = _auto_interpret(interpret)
    n = bucket.shape[0]
    CH = 1 << 23  # int32 partials hold n * 128 < 2^31 per chunk
    oi64 = jnp.zeros((domain, int_payload.shape[1]), jnp.int64)
    of64 = jnp.zeros((domain, float_payload.shape[1]), jnp.float64)
    for lo in range(0, max(n, 1), CH):
        oi, of = _onehot_gb_call(
            bucket[lo:lo + CH], int_payload[lo:lo + CH],
            float_payload[lo:lo + CH], domain, interp)
        oi64 = oi64 + oi.astype(jnp.int64)
        of64 = of64 + of.astype(jnp.float64)
    return oi64, of64


# ---------------------------------------------------------------------------
# fused slot-table build / probe (scatter group-by + hash-probe join engines)
# ---------------------------------------------------------------------------

# The lax formulation in relational/hashtable.py pays O(probe-chain)
# FULL passes over n-sized HBM arrays per round: one scatter-min claim,
# one owner gather, one gather+compare per key word, every round.  These
# kernels keep the whole slot table (owner ids, per-round proposals, and
# the owner's key words) resident in VMEM and stream the rows once per
# round as tiles, so HBM traffic per round drops from O(n * words) to
# the row tiles themselves.  Contract and bit-identity: same
# FNV-1a+lowbias32 candidate chain (cand0 is computed with
# hashtable.fold_hash and round r probes (cand0 + r) & (S-1)), same
# empty-slots-only minimum-row-id election, same retire rule — the
# (owner, slot, overflow) / (found, slot) products are bit-identical to
# build_slot_table / probe_slot_table, which is what lets the engines
# above dispatch on a knob with zero semantic change.

# rows per grid tile.  Per-step row state is SLOT_ROWS * (4+4+1+1+4W)
# bytes (cand0, rowid, live, active, W key words); at 512 rows and W<=4
# that is ~13KB, noise next to the resident tables.
SLOT_ROWS = 512

# resident-table budget: owner (4S) + proposals (4S) + owner key words
# (4*S*W) must sit in VMEM across the whole grid, so the pallas path
# bows out past ~4MB of table (S*(8+4W) bytes) and the caller's lax
# formulation runs instead — at the default 4096-slot group-by table
# with 2 key words that is 64KB, two orders under the ceiling.
_SLOT_TABLE_MAX_BYTES = 4 << 20


def _slot_build_kernel(n, S, W, cand0_ref, w_ref, live_ref,
                       owner_ref, prop_ref, slotw_ref, slot_ref, act_ref):
    """One grid step of the synchronous build rounds.

    Grid is (max_rounds, 3 phases, row tiles); the claim/elect/retire
    round of hashtable.build_slot_table is schedule-DEPENDENT (a later
    round's smaller row id may not steal, so tiles cannot insert
    sequentially), hence the three *global* phases per round: phase 0
    scatter-mins every tile's claims into ``prop``; phase 1 merges
    ``prop`` into empty ``owner`` slots once (tile 0) and each winning
    row publishes its key words to ``slotw``; phase 2 matches every
    still-active row against its candidate slot's published words and
    retires the hits.  ``owner``/``prop``/``slotw`` use constant index
    maps (table resident across the grid); ``slot``/``act`` are per-tile
    carried state revisited every round.
    """
    r = pl.program_id(0)
    ph = pl.program_id(1)
    t = pl.program_id(2)
    sent = jnp.int32(n)
    mask = jnp.int32(S - 1)
    cand = (cand0_ref[:] + r) & mask
    first = (r == 0) & (ph == 0)

    @pl.when(first & (t == 0))
    def _():
        owner_ref[:] = jnp.full((S,), sent, jnp.int32)
        slotw_ref[:] = jnp.zeros((S, W), jnp.uint32)

    @pl.when(first)
    def _():
        slot_ref[:] = jnp.full((SLOT_ROWS,), S, jnp.int32)
        act_ref[:] = live_ref[:]

    rid = (jax.lax.broadcasted_iota(jnp.int32, (SLOT_ROWS,), 0)
           + t * SLOT_ROWS)

    @pl.when(ph == 0)
    def _():
        @pl.when(t == 0)
        def _():
            prop_ref[:] = jnp.full((S,), sent, jnp.int32)

        claim = jnp.where(act_ref[:], rid, sent)
        prop_ref[:] = prop_ref[:].at[cand].min(claim)

    @pl.when(ph == 1)
    def _():
        @pl.when(t == 0)
        def _():
            ow = owner_ref[:]
            owner_ref[:] = jnp.where(ow == sent, prop_ref[:], ow)

        # a row that just won its candidate slot publishes its key words
        # so phase 2 compares against the OWNER's words without gathering
        # from other tiles' rows (the lax formulation's full-array gather)
        won = act_ref[:] & (jnp.take(owner_ref[:], cand) == rid)
        idx = jnp.where(won, cand, S)
        slotw_ref[:] = slotw_ref[:].at[idx].set(w_ref[:], mode="drop")

    @pl.when(ph == 2)
    def _():
        act = act_ref[:]
        ow = jnp.take(slotw_ref[:], cand, axis=0)
        w = w_ref[:]
        match = act
        for j in range(W):
            match = match & (ow[:, j] == w[:, j])
        slot_ref[:] = jnp.where(match, cand, slot_ref[:])
        act_ref[:] = act & ~match


@partial(jax.jit, static_argnames=("num_slots", "max_rounds", "interpret"))
def _slot_build_call(cand0, wstack, live, num_slots, max_rounds, interpret):
    n, W = wstack.shape
    S = num_slots
    npad = -(-max(n, 1) // SLOT_ROWS) * SLOT_ROWS
    if npad != n:
        cand0 = jnp.pad(cand0, (0, npad - n))
        wstack = jnp.pad(wstack, ((0, npad - n), (0, 0)))
        live = jnp.pad(live, (0, npad - n))
    row1 = pl.BlockSpec((SLOT_ROWS,), lambda r, p, t: (t,))
    roww = pl.BlockSpec((SLOT_ROWS, W), lambda r, p, t: (t, 0))
    tab1 = pl.BlockSpec((S,), lambda r, p, t: (0,))
    tabw = pl.BlockSpec((S, W), lambda r, p, t: (0, 0))
    owner, _prop, _slotw, slot, active = pl.pallas_call(
        partial(_slot_build_kernel, n, S, W),
        out_shape=(jax.ShapeDtypeStruct((S,), jnp.int32),
                   jax.ShapeDtypeStruct((S,), jnp.int32),
                   jax.ShapeDtypeStruct((S, W), jnp.uint32),
                   jax.ShapeDtypeStruct((npad,), jnp.int32),
                   jax.ShapeDtypeStruct((npad,), jnp.bool_)),
        grid=(max_rounds, 3, npad // SLOT_ROWS),
        in_specs=[row1, roww, row1],
        out_specs=(tab1, tab1, tabw, row1, row1),
        interpret=interpret,
    )(cand0, wstack, live)
    return owner, slot, active


def slot_table_build(words, live, num_slots: int, max_rounds=None,
                     interpret=None):
    """Pallas twin of :func:`relational.hashtable.build_slot_table` —
    same ``(owner, slot, overflow)`` contract, bit-identical.

    Falls back to the lax formulation when the resident tables exceed
    the VMEM budget or the round bound is degenerate, so callers can
    dispatch unconditionally on the engine knob.
    """
    from ..relational import hashtable as H

    n = words[0].shape[0]
    S = int(num_slots)
    if S & (S - 1):
        raise ValueError(f"num_slots must be a power of two, got {S}")
    mr = S if max_rounds is None else int(max_rounds)
    if mr <= 0 or S * (8 + 4 * len(words)) > _SLOT_TABLE_MAX_BYTES:
        return H.build_slot_table(words, live, S, max_rounds=mr)
    cand0 = (H.fold_hash(words) & jnp.uint32(S - 1)).astype(jnp.int32)
    wstack = jnp.stack([w.astype(jnp.uint32) for w in words], axis=1)
    owner, slot, active = _slot_build_call(
        cand0, wstack, live.astype(jnp.bool_), S, mr,
        _auto_interpret(interpret))
    return owner, slot[:n], jnp.any(active)


def _slot_probe_kernel(n, S, W, rounds_ref, owner_ref, slotw_ref,
                       cand0_ref, pw_ref, live_ref, found_ref, slot_ref):
    """Read-only chain walk, one probe tile per grid step.

    Unlike the build, probing has no cross-row interaction (the table is
    frozen), so each tile walks its own chains to completion with the
    owner table and the owners' key words resident — the whole
    O(chain) loop happens in VMEM with zero per-round HBM passes.
    """
    sent = jnp.int32(n)
    mask = jnp.int32(S - 1)
    owner = owner_ref[:]
    slotw = slotw_ref[:]
    pw = pw_ref[:]
    rounds = rounds_ref[0]

    def cond(state):
        rnd, _cand, _slot, _found, act = state
        return (rnd < rounds) & jnp.any(act)

    def body(state):
        rnd, cand, slot, found, act = state
        o = jnp.take(owner, cand)
        empty = o == sent
        ow = jnp.take(slotw, cand, axis=0)
        match = ~empty
        for j in range(W):
            match = match & (ow[:, j] == pw[:, j])
        hit = act & match
        slot = jnp.where(hit, cand, slot)
        found = found | hit
        # an empty slot ends the chain: the key cannot live past it
        act = act & ~match & ~empty
        return rnd + 1, (cand + 1) & mask, slot, found, act

    state = (jnp.int32(0), cand0_ref[:],
             jnp.full((SLOT_ROWS,), S, jnp.int32),
             jnp.zeros((SLOT_ROWS,), jnp.bool_), live_ref[:])
    _, _, slot, found, _ = jax.lax.while_loop(cond, body, state)
    found_ref[:] = found
    slot_ref[:] = slot


@partial(jax.jit, static_argnames=("n_build", "interpret"))
def _slot_probe_call(owner, slotw, cand0, pwstack, live, rounds, n_build,
                     interpret):
    m, W = pwstack.shape
    S = owner.shape[0]
    mpad = -(-max(m, 1) // SLOT_ROWS) * SLOT_ROWS
    if mpad != m:
        cand0 = jnp.pad(cand0, (0, mpad - m))
        pwstack = jnp.pad(pwstack, ((0, mpad - m), (0, 0)))
        live = jnp.pad(live, (0, mpad - m))
    row1 = pl.BlockSpec((SLOT_ROWS,), lambda t: (t,))
    roww = pl.BlockSpec((SLOT_ROWS, W), lambda t: (t, 0))
    const1 = pl.BlockSpec((1,), lambda t: (0,))
    tab1 = pl.BlockSpec((S,), lambda t: (0,))
    tabw = pl.BlockSpec((S, W), lambda t: (0, 0))
    found, slot = pl.pallas_call(
        partial(_slot_probe_kernel, n_build, S, W),
        out_shape=(jax.ShapeDtypeStruct((mpad,), jnp.bool_),
                   jax.ShapeDtypeStruct((mpad,), jnp.int32)),
        grid=(mpad // SLOT_ROWS,),
        in_specs=[const1, tab1, tabw, row1, roww, row1],
        out_specs=(row1, row1),
        interpret=interpret,
    )(rounds, owner, slotw, cand0, pwstack, live)
    return found, slot


def slot_table_probe(owner, build_words, probe_words, live, max_rounds=None,
                     interpret=None):
    """Pallas twin of :func:`relational.hashtable.probe_slot_table` —
    same ``(found, slot)`` contract, bit-identical for any ``max_rounds``
    the lax walk would be given (the bound only gates termination).

    The owners' key words are gathered once up front (exactly the values
    the lax walk re-gathers every round) so the in-kernel chain walk
    needs no access to the full build-side arrays.
    """
    from ..relational import hashtable as H

    S = owner.shape[0]
    n = build_words[0].shape[0]
    m = probe_words[0].shape[0]
    if S * (4 + 4 * len(build_words)) > _SLOT_TABLE_MAX_BYTES:
        return H.probe_slot_table(owner, build_words, probe_words, live,
                                  max_rounds=max_rounds)
    mr = S if max_rounds is None else max_rounds
    oc = jnp.clip(owner, 0, max(n - 1, 0))
    slotw = jnp.stack(
        [jnp.take(w.astype(jnp.uint32), oc) for w in build_words], axis=1)
    cand0 = (H.fold_hash(probe_words) & jnp.uint32(S - 1)).astype(jnp.int32)
    pwstack = jnp.stack([w.astype(jnp.uint32) for w in probe_words], axis=1)
    rounds = jnp.asarray(mr, jnp.int32).reshape((1,))
    found, slot = _slot_probe_call(
        owner, slotw, cand0, pwstack, live.astype(jnp.bool_), rounds, n,
        _auto_interpret(interpret))
    return found[:m], slot[:m]


# ---------------------------------------------------------------------------
# fused radix partition scatter (the shuffle map step's morsel -> chunk hop)
# ---------------------------------------------------------------------------

def _part_scatter_kernel(P, C, M, cnts_ref, base_ref, r_ref, occ_in_ref,
                         *refs):
    """pid + per-partition cumulative offsets + round-chunk scatter, one
    pass.  ``refs`` is ``chunk_in.. morsel.. occ_out chunk_out..`` — the
    XLA formulation runs these as separate cumsum / searchsorted /
    per-column scatter programs with the row->slot map rematerialized in
    HBM between them; here the map lives in registers and every column
    scatters from the same resident morsel."""
    nleaf = (len(refs) - 1) // 3
    chunk_in = refs[:nleaf]
    morsel = refs[nleaf:2 * nleaf]
    occ_out = refs[2 * nleaf]
    chunk_out = refs[2 * nleaf + 1:]
    cnts = cnts_ref[:]
    ends = jnp.cumsum(cnts)
    offs = ends - cnts
    i = jax.lax.broadcasted_iota(jnp.int32, (M,), 0)
    # searchsorted(ends, i, side="right") == how many ends are <= i
    d = jnp.sum((i[:, None] >= ends[None, :]).astype(jnp.int32), axis=1)
    d_c = jnp.minimum(d, P - 1)
    k = jnp.take(base_ref[:], d_c) + (i - jnp.take(offs, d_c))
    r = r_ref[0]
    in_round = (d < P) & (k >= r * C) & (k < (r + 1) * C)
    t = jnp.where(in_round, d_c * C + (k - r * C), P * C)
    occ_out[:] = occ_in_ref[:].at[t].set(True, mode="drop")
    for ci, mo, co in zip(chunk_in, morsel, chunk_out):
        co[:] = ci[:].at[t].set(mo[:], mode="drop")


def partition_scatter(chunk_leaves, occ, morsel_leaves, cnts, base, rnd,
                      partitions: int, capacity: int, interpret=None):
    """Fused twin of the shuffle map step's scatter
    (:mod:`shuffle.service` ``_scatter_step``): bit-identical
    ``(chunk_leaves, occ)`` for the same ``(cnts, base, rnd)`` routing
    inputs, with the row->slot map never leaving the kernel."""
    P = int(partitions)
    C = int(capacity)
    M = int(morsel_leaves[0].shape[0])
    chunk_leaves = tuple(chunk_leaves)
    morsel_leaves = tuple(morsel_leaves)
    full = lambda a: pl.BlockSpec(a.shape, lambda: (0,) * a.ndim)  # noqa: E731
    rarr = jnp.asarray(rnd, jnp.int32).reshape((1,))
    ins = (cnts, base, rarr, occ) + chunk_leaves + morsel_leaves
    outs = pl.pallas_call(
        partial(_part_scatter_kernel, P, C, M),
        out_shape=tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in (occ,) + chunk_leaves),
        in_specs=[full(a) for a in ins],
        out_specs=tuple(full(a) for a in (occ,) + chunk_leaves),
        interpret=_auto_interpret(interpret),
    )(*ins)
    return tuple(outs[1:]), outs[0]
