"""Float/double -> string matching Java ``Double.toString`` semantics.

The reference ports Ryu (shortest round-trip decimal) to CUDA
(``ftos_converter.cuh``: ``floating_decimal_64/32``, d2s tables) and
formats per Java rules (``cast_float_to_string.cu:110``): plain decimal
for 1e-3 <= |v| < 1e7, otherwise ``d.dddE±x``; always at least one
fractional digit; NaN -> "NaN", infinities -> "[-]Infinity", zeros ->
"[-]0.0".

This is an independent vectorized implementation of the published Ryu
algorithm (Ulf Adams, "Ryū: fast float-to-string conversion", PLDI 2018):

* the 125-bit power-of-five tables are *computed* at import time from
  python bigints (not copied), one ``uint64`` pair per entry;
* the 64x128-bit ``mulShift`` runs on 32-bit limb products in uint64
  lanes (TPU-friendly: every op is a vector op; 64-bit ints are XLA
  uint32-pair emulation);
* Ryu's variable-length digit-removal loops become one fixed-trip masked
  loop (<= 20 iterations — the max removable digits for binary64), the
  standard TPU rewrite for data-dependent while loops.

String assembly builds a ``uint8[n, 26]`` char matrix from the digit
array with positional ``where`` cascades — no scatters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column, StringColumn

# ---------------------------------------------------------------------------
# tables (computed, 125-bit double / 59-61-bit float splits)
# ---------------------------------------------------------------------------

_DOUBLE_POW5_INV_BITCOUNT = 125
_DOUBLE_POW5_BITCOUNT = 125
_FLOAT_POW5_INV_BITCOUNT = 59
_FLOAT_POW5_BITCOUNT = 61


def _pow5bits(e: int) -> int:
    return ((e * 1217359) >> 19) + 1


def _build_double_tables():
    inv = np.zeros((342, 2), np.uint64)
    for q in range(342):
        pow5 = 5**q
        inv_val = (1 << (_pow5bits(q) - 1 + _DOUBLE_POW5_INV_BITCOUNT)) // pow5 + 1
        inv[q, 0] = inv_val & 0xFFFFFFFFFFFFFFFF
        inv[q, 1] = inv_val >> 64
    split = np.zeros((326, 2), np.uint64)
    for i in range(326):
        s = _pow5bits(i) - _DOUBLE_POW5_BITCOUNT
        val = 5**i >> s if s > 0 else 5**i << -s  # normalize to 125 bits
        split[i, 0] = val & 0xFFFFFFFFFFFFFFFF
        split[i, 1] = val >> 64
    return inv, split


def _build_float_tables():
    inv = np.zeros((31,), np.uint64)
    for q in range(31):
        inv[q] = (1 << (_pow5bits(q) - 1 + _FLOAT_POW5_INV_BITCOUNT)) // 5**q + 1
    split = np.zeros((48,), np.uint64)
    for i in range(48):
        s = _pow5bits(i) - _FLOAT_POW5_BITCOUNT
        split[i] = 5**i >> s if s > 0 else 5**i << -s
    return inv, split


_D_INV, _D_SPLIT = _build_double_tables()
_F_INV, _F_SPLIT = _build_float_tables()

_U64 = jnp.uint64


def _log10pow2(e):
    return (e * 78913) >> 18  # floor(e * log10(2)), e in [0, 1650]


def _log10pow5(e):
    return (e * 732923) >> 20  # floor(e * log10(5))


def _pow5bits_arr(e):
    return ((e * 1217359) >> 19) + 1


def _umul64_128(a, b):
    """uint64 * uint64 -> (hi, lo) via 32-bit limb products."""
    a_lo = a & _U64(0xFFFFFFFF)
    a_hi = a >> _U64(32)
    b_lo = b & _U64(0xFFFFFFFF)
    b_hi = b >> _U64(32)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> _U64(32)) + (lh & _U64(0xFFFFFFFF)) + (hl & _U64(0xFFFFFFFF))
    lo = (ll & _U64(0xFFFFFFFF)) | (mid << _U64(32))
    hi = hh + (lh >> _U64(32)) + (hl >> _U64(32)) + (mid >> _U64(32))
    return hi, lo


def _shr128(hi, lo, s):
    """(hi:lo) >> s for per-row s in [1, 127] with result < 2**64."""
    s = s.astype(jnp.uint64)
    lt64 = s < _U64(64)
    s_lo = jnp.where(lt64, s, _U64(0))
    s_hi = jnp.where(lt64, _U64(0), s - _U64(64))
    lo_part = (lo >> s_lo) | jnp.where(
        (s_lo > 0), hi << (_U64(64) - s_lo), _U64(0)
    )
    return jnp.where(lt64, lo_part, hi >> s_hi)


def _mul_shift_64(m, mul_lo, mul_hi, j):
    """(m * (mul_hi:mul_lo)) >> j, j in (64, 191), result < 2**64."""
    hi1, lo1 = _umul64_128(m, mul_lo)
    hi2, lo2 = _umul64_128(m, mul_hi)
    # sum = (hi2:lo2) << 64 + (hi1:lo1); only bits >= 64 matter after >> j
    mid = hi1 + lo2
    carry = (mid < hi1).astype(jnp.uint64)
    top = hi2 + carry
    return _shr128(top, mid, j - 64)


def _pow5_factor_ge(value, p, max_iter):
    """value divisible by 5**p (p <= max_iter)?  Fixed-trip factor count."""
    count = jnp.zeros_like(value, dtype=jnp.int32)
    v = value
    for _ in range(max_iter):
        div = v % _U64(5) == 0
        v = jnp.where(div, v // _U64(5), v)
        count = count + div.astype(jnp.int32)
    return count >= p


def _d2d(bits):
    """Core Ryu shortest-decimal for binary64 (vectorized).

    bits: uint64[n] (finite, nonzero).  Returns (digits u64, exp10 i32).
    """
    m = bits & _U64((1 << 52) - 1)
    e = ((bits >> _U64(52)) & _U64(0x7FF)).astype(jnp.int32)

    is_sub = e == 0
    e2 = jnp.where(is_sub, 1, e) - 1075 - 2
    m2 = jnp.where(is_sub, m, m | _U64(1 << 52))

    even = (m2 & _U64(1)) == 0
    accept = even
    mv = m2 * _U64(4)
    mm_shift = ((m != 0) | (e <= 1)).astype(jnp.uint64)

    pos = e2 >= 0
    # ---- e2 >= 0 branch ------------------------------------------------
    q_p = jnp.maximum(_log10pow2(jnp.maximum(e2, 0)) - (e2 > 3), 0)
    k_p = _DOUBLE_POW5_INV_BITCOUNT + _pow5bits_arr(q_p) - 1
    i_p = -e2 + q_p + k_p
    inv = jnp.asarray(_D_INV)
    mul_lo_p = jnp.take(inv[:, 0], jnp.clip(q_p, 0, 341))
    mul_hi_p = jnp.take(inv[:, 1], jnp.clip(q_p, 0, 341))
    # ---- e2 < 0 branch -------------------------------------------------
    ne2 = jnp.maximum(-e2, 0)
    q_n = jnp.maximum(_log10pow5(ne2) - (ne2 > 1), 0)
    i_n = ne2 - q_n
    k_n = _pow5bits_arr(i_n) - _DOUBLE_POW5_BITCOUNT
    j_n = q_n - k_n
    spl = jnp.asarray(_D_SPLIT)
    mul_lo_n = jnp.take(spl[:, 0], jnp.clip(i_n, 0, 325))
    mul_hi_n = jnp.take(spl[:, 1], jnp.clip(i_n, 0, 325))

    e10 = jnp.where(pos, q_p, q_n + e2)
    mul_lo = jnp.where(pos, mul_lo_p, mul_lo_n)
    mul_hi = jnp.where(pos, mul_hi_p, mul_hi_n)
    j = jnp.where(pos, i_p, j_n)

    vr = _mul_shift_64(mv, mul_lo, mul_hi, j)
    vp = _mul_shift_64(mv + _U64(2), mul_lo, mul_hi, j)
    vm = _mul_shift_64(mv - _U64(1) - mm_shift, mul_lo, mul_hi, j)

    # trailing-zero tracking
    q = jnp.where(pos, q_p, q_n)
    vr_tz = jnp.zeros_like(even)
    vm_tz = jnp.zeros_like(even)
    # e2 >= 0, q <= 21 cases
    c_p = pos & (q_p <= 21)
    mv_mod5 = (mv % _U64(5)) == 0
    vr_tz = jnp.where(c_p & mv_mod5, _pow5_factor_ge(mv, q_p, 22), vr_tz)
    vm_tz = jnp.where(
        c_p & ~mv_mod5 & accept,
        _pow5_factor_ge(mv - _U64(1) - mm_shift, q_p, 22),
        vm_tz,
    )
    vp = jnp.where(
        c_p & ~mv_mod5 & ~accept,
        vp - _pow5_factor_ge(mv + _U64(2), q_p, 22).astype(jnp.uint64),
        vp,
    )
    # e2 < 0, q <= 1: vr trailing; vm trailing iff mm_shift == 1
    c_n1 = ~pos & (q_n <= 1)
    vr_tz = jnp.where(c_n1, True, vr_tz)
    vm_tz = jnp.where(c_n1 & accept, mm_shift == _U64(1), vm_tz)
    vp = jnp.where(c_n1 & ~accept, vp - _U64(1), vp)
    # e2 < 0, q < 63: vr_tz = multipleOfPowerOf2(mv, q)
    c_n2 = ~pos & (q_n > 1) & (q_n < 63)
    mask_q = (_U64(1) << q.astype(jnp.uint64)) - _U64(1)
    vr_tz = jnp.where(c_n2, (mv & mask_q) == _U64(0), vr_tz)

    # ---- digit removal (fixed-trip masked loop) ------------------------
    removed = jnp.zeros(bits.shape, jnp.int32)
    last_removed = jnp.zeros(bits.shape, jnp.uint64)

    def body(_, st):
        vr, vp, vm, vr_tz, vm_tz, removed, last_removed = st
        cond_main = (vp // _U64(10)) > (vm // _U64(10))
        vm_mod = vm % _U64(10)
        cond_extra = ~cond_main & vm_tz & (vm_mod == 0)
        active = cond_main | cond_extra
        vm_tz_new = vm_tz & (vm_mod == 0)
        vr_tz_new = vr_tz & (last_removed == 0)
        lr_new = vr % _U64(10)
        vr_n = vr // _U64(10)
        vp_n = vp // _U64(10)
        vm_n = vm // _U64(10)
        return (
            jnp.where(active, vr_n, vr),
            jnp.where(active, vp_n, vp),
            jnp.where(active, vm_n, vm),
            jnp.where(active, vr_tz_new, vr_tz),
            jnp.where(active, vm_tz_new, vm_tz),
            removed + active.astype(jnp.int32),
            jnp.where(active, lr_new, last_removed),
        )

    vr, vp, vm, vr_tz, vm_tz, removed, last_removed = jax.lax.fori_loop(
        0, 20, body, (vr, vp, vm, vr_tz, vm_tz, removed, last_removed)
    )

    last_removed = jnp.where(
        vr_tz & (last_removed == 5) & (vr % _U64(2) == 0),
        _U64(4),
        last_removed,
    )
    round_up = ((vr == vm) & (~accept | ~vm_tz)) | (last_removed >= 5)
    output = vr + round_up.astype(jnp.uint64)
    return output, e10 + removed


def _f2d(bits32):
    """Core Ryu for binary32 (vectorized, 64-bit arithmetic suffices)."""
    bits = bits32.astype(jnp.uint32)
    m = (bits & jnp.uint32((1 << 23) - 1)).astype(jnp.uint64)
    e = ((bits >> 23) & jnp.uint32(0xFF)).astype(jnp.int32)

    is_sub = e == 0
    e2 = jnp.where(is_sub, 1, e) - 150 - 2
    m2 = jnp.where(is_sub, m, m | _U64(1 << 23))

    even = (m2 & _U64(1)) == 0
    accept = even
    mv = m2 * _U64(4)
    mm_shift = ((m != 0) | (e <= 1)).astype(jnp.uint64)

    def mul_shift_32(mx, factor, shift):
        # (mx * factor) >> shift; mx < 2**26, factor < 2**64, shift > 32
        f_lo = factor & _U64(0xFFFFFFFF)
        f_hi = factor >> _U64(32)
        lo = mx * f_lo
        hi = mx * f_hi
        sum_ = (lo >> _U64(32)) + hi
        return sum_ >> (shift.astype(jnp.uint64) - _U64(32))

    pos = e2 >= 0
    q_p = _log10pow2(jnp.maximum(e2, 0))
    k_p = _FLOAT_POW5_INV_BITCOUNT + _pow5bits_arr(q_p) - 1
    i_p = -e2 + q_p + k_p
    inv = jnp.asarray(_F_INV)
    fac_p = jnp.take(inv, jnp.clip(q_p, 0, 30))

    ne2 = jnp.maximum(-e2, 0)
    q_n = _log10pow5(ne2)
    i_n = ne2 - q_n
    k_n = _pow5bits_arr(i_n) - _FLOAT_POW5_BITCOUNT
    j_n = q_n - k_n
    spl = jnp.asarray(_F_SPLIT)
    fac_n = jnp.take(spl, jnp.clip(i_n, 0, 47))

    e10 = jnp.where(pos, q_p, q_n + e2)
    factor = jnp.where(pos, fac_p, fac_n)
    j = jnp.where(pos, i_p, j_n)

    vr = mul_shift_32(mv, factor, j)
    vp = mul_shift_32(mv + _U64(2), factor, j)
    vm = mul_shift_32(mv - _U64(1) - mm_shift, factor, j)

    q = jnp.where(pos, q_p, q_n)
    vr_tz = jnp.zeros_like(even)
    vm_tz = jnp.zeros_like(even)

    # f2s pre-step: when the loop below may remove no digit, the rounding
    # digit comes from one extra decimal of precision (f2s.c q != 0 case)
    c_pre = (q != 0) & (((vp - _U64(1)) // _U64(10)) <= vm // _U64(10))
    # pos: mulPow5InvDivPow2(mv, q-1, -e2 + (q-1) + l), l from q-1
    qm1 = jnp.maximum(q_p - 1, 0)
    l_p = _FLOAT_POW5_INV_BITCOUNT + _pow5bits_arr(qm1) - 1
    fac_pre_p = jnp.take(inv, jnp.clip(qm1, 0, 30))
    j_pre_p = -e2 + qm1 + l_p
    lr_p = mul_shift_32(mv, fac_pre_p, jnp.maximum(j_pre_p, 33)) % _U64(10)
    # neg: mulPow5divPow2(mv, i+1, q - 1 - (pow5bits(i+1) - BITCOUNT))
    i1 = i_n + 1
    fac_pre_n = jnp.take(spl, jnp.clip(i1, 0, 47))
    j_pre_n = q_n - 1 - (_pow5bits_arr(i1) - _FLOAT_POW5_BITCOUNT)
    lr_n = mul_shift_32(mv, fac_pre_n, jnp.maximum(j_pre_n, 33)) % _U64(10)
    last_removed = jnp.where(
        c_pre, jnp.where(pos, lr_p, lr_n), _U64(0)
    )

    c_p = pos & (q_p <= 9)
    mv_mod5 = (mv % _U64(5)) == 0
    vr_tz = jnp.where(c_p & mv_mod5, _pow5_factor_ge(mv, q_p, 11), vr_tz)
    vm_tz = jnp.where(
        c_p & ~mv_mod5 & accept,
        _pow5_factor_ge(mv - _U64(1) - mm_shift, q_p, 11),
        vm_tz,
    )
    vp = jnp.where(
        c_p & ~mv_mod5 & ~accept,
        vp - _pow5_factor_ge(mv + _U64(2), q_p, 11).astype(jnp.uint64),
        vp,
    )
    c_n1 = ~pos & (q_n <= 1)
    vr_tz = jnp.where(c_n1, True, vr_tz)
    vm_tz = jnp.where(c_n1 & accept, mm_shift == _U64(1), vm_tz)
    vp = jnp.where(c_n1 & ~accept, vp - _U64(1), vp)
    c_n2 = ~pos & (q_n > 1) & (q_n < 31)
    mask_q = (_U64(1) << jnp.maximum(q - 1, 0).astype(jnp.uint64)) - _U64(1)
    vr_tz = jnp.where(c_n2, (mv & mask_q) == _U64(0), vr_tz)

    removed = jnp.zeros(bits.shape, jnp.int32)

    def body(_, st):
        vr, vp, vm, vr_tz, vm_tz, removed, last_removed = st
        cond_main = (vp // _U64(10)) > (vm // _U64(10))
        vm_mod = vm % _U64(10)
        cond_extra = ~cond_main & vm_tz & (vm_mod == 0)
        active = cond_main | cond_extra
        vm_tz_new = vm_tz & (vm_mod == 0)
        vr_tz_new = vr_tz & (last_removed == 0)
        lr_new = vr % _U64(10)
        return (
            jnp.where(active, vr // _U64(10), vr),
            jnp.where(active, vp // _U64(10), vp),
            jnp.where(active, vm // _U64(10), vm),
            jnp.where(active, vr_tz_new, vr_tz),
            jnp.where(active, vm_tz_new, vm_tz),
            removed + active.astype(jnp.int32),
            jnp.where(active, lr_new, last_removed),
        )

    vr, vp, vm, vr_tz, vm_tz, removed, last_removed = jax.lax.fori_loop(
        0, 11, body, (vr, vp, vm, vr_tz, vm_tz, removed, last_removed)
    )

    last_removed = jnp.where(
        vr_tz & (last_removed == 5) & (vr % _U64(2) == 0), _U64(4), last_removed
    )
    round_up = ((vr == vm) & (~accept | ~vm_tz)) | (last_removed >= 5)
    output = vr + round_up.astype(jnp.uint64)
    return output, e10 + removed


# ---------------------------------------------------------------------------
# Java-style formatting
# ---------------------------------------------------------------------------

_MAX_CHARS = 26


def _digit_count(v):
    count = jnp.ones(v.shape, jnp.int32)
    x = v
    for _ in range(19):
        x = x // _U64(10)
        count = count + (x > 0).astype(jnp.int32)
    return count


# fixed output width of double_to_json_string: _format's 26-char layout
# ("-2.2250738585072014E-308") + 2 pad columns for the quoted specials.
# json_fast's lax.cond skip branch must match this shape exactly.
DOUBLE_JSON_W = 28


def _format(digits, exp10, negative, is_nan, is_inf, is_zero):
    """Assemble Java toString chars: digits u64[n], exp10 = power of the
    LAST digit; value = digits * 10^exp10."""
    n = digits.shape[0]
    olength = _digit_count(digits)
    # E = exponent of the leading digit
    E = exp10 + olength - 1
    plain = (E >= -3) & (E < 7)

    # digit characters MSB-first: dig[k] = k-th most significant digit
    digs = []
    x = digits
    for _ in range(17):
        digs.append((x % _U64(10)).astype(jnp.uint8))
        x = x // _U64(10)
    dig_rev = jnp.stack(digs, axis=1)  # [n, 17] LSB-first
    kk = jnp.arange(17)[None, :]
    msb_idx = olength[:, None] - 1 - kk  # index into dig_rev for MSB-first
    dig = jnp.take_along_axis(dig_rev, jnp.clip(msb_idx, 0, 16), axis=1)
    dig = jnp.where(kk < olength[:, None], dig, 0).astype(jnp.int32)

    j = jnp.arange(_MAX_CHARS)[None, :]
    sign_len = negative.astype(jnp.int32)[:, None]
    out = jnp.full((n, _MAX_CHARS), ord(" "), jnp.int32)

    def put(out, pos_mask, ch):
        return jnp.where(pos_mask, ch, out)

    out = put(out, (j == 0) & negative[:, None], ord("-"))
    p = j - sign_len  # position net of sign

    # ---------- plain, E >= 0: digits[0..E] '.' frac ----------
    ip_len = E[:, None] + 1  # integer digits
    has_frac = olength[:, None] > ip_len
    frac_len = jnp.maximum(olength[:, None] - ip_len, 1)
    m_int = plain[:, None] & (E >= 0)[:, None] & (p >= 0) & (p < ip_len)
    out = put(out, m_int, ord("0") + jnp.take_along_axis(
        dig, jnp.clip(p, 0, 16), axis=1))
    m_dot = plain[:, None] & (E >= 0)[:, None] & (p == ip_len)
    out = put(out, m_dot, ord("."))
    fpos = p - ip_len - 1
    m_frac = plain[:, None] & (E >= 0)[:, None] & (fpos >= 0) & (fpos < frac_len)
    fdig = jnp.where(
        has_frac,
        jnp.take_along_axis(dig, jnp.clip(ip_len + fpos, 0, 16), axis=1),
        0,
    )
    out = put(out, m_frac, ord("0") + fdig)
    len_plain_pos = sign_len + ip_len + 1 + frac_len

    # ---------- plain, E < 0: "0." zeros digits ----------
    zeros = (-E[:, None]) - 1
    m0 = plain[:, None] & (E < 0)[:, None]
    out = put(out, m0 & (p == 0), ord("0"))
    out = put(out, m0 & (p == 1), ord("."))
    out = put(out, m0 & (p >= 2) & (p < 2 + zeros), ord("0"))
    dpos = p - 2 - zeros
    m_d = m0 & (dpos >= 0) & (dpos < olength[:, None])
    out = put(out, m_d, ord("0") + jnp.take_along_axis(
        dig, jnp.clip(dpos, 0, 16), axis=1))
    len_plain_neg = sign_len + 2 + zeros + olength[:, None]

    # ---------- scientific: d '.' frac 'E' [-] expdigits ----------
    msci = (~plain)[:, None]
    out = put(out, msci & (p == 0), ord("0") + dig[:, 0:1])
    out = put(out, msci & (p == 1), ord("."))
    sfrac_len = jnp.maximum(olength[:, None] - 1, 1)
    spos = p - 2
    sdig = jnp.where(
        olength[:, None] > 1,
        jnp.take_along_axis(dig, jnp.clip(1 + spos, 0, 16), axis=1),
        0,
    )
    out = put(out, msci & (spos >= 0) & (spos < sfrac_len), ord("0") + sdig)
    epos0 = 2 + sfrac_len
    out = put(out, msci & (p == epos0), ord("E"))
    eneg = (E < 0)[:, None]
    out = put(out, msci & eneg & (p == epos0 + 1), ord("-"))
    absE = jnp.abs(E)[:, None]
    e_len = 1 + (absE >= 10) + (absE >= 100)
    e_start = epos0 + 1 + eneg.astype(jnp.int32)
    ep = p - e_start
    e_digs = jnp.concatenate(
        [absE // 100 % 10, absE // 10 % 10, absE % 10], axis=1
    )  # [n,3] MSB-first (padded)
    e_idx = 3 - e_len + ep
    m_e = msci & (ep >= 0) & (ep < e_len)
    out = put(out, m_e, ord("0") + jnp.take_along_axis(
        e_digs, jnp.clip(e_idx, 0, 2), axis=1))
    len_sci = sign_len + 2 + sfrac_len + 1 + eneg.astype(jnp.int32) + e_len

    length = jnp.where(
        plain[:, None] & (E >= 0)[:, None],
        len_plain_pos,
        jnp.where(plain[:, None], len_plain_neg, len_sci),
    )[:, 0]

    # ---------- specials ----------
    chars = out.astype(jnp.uint8)
    length = length.astype(jnp.int32)

    def literal(s):
        buf = np.zeros((_MAX_CHARS,), np.uint8)
        raw = s.encode()
        buf[: len(raw)] = np.frombuffer(raw, np.uint8)
        return jnp.asarray(buf)[None, :], len(raw)

    nan_c, nan_l = literal("NaN")
    inf_c, inf_l = literal("Infinity")
    ninf_c, ninf_l = literal("-Infinity")
    z_c, z_l = literal("0.0")
    nz_c, nz_l = literal("-0.0")

    for mask, c, l in (
        (is_zero & ~negative, z_c, z_l),
        (is_zero & negative, nz_c, nz_l),
        (is_inf & ~negative, inf_c, inf_l),
        (is_inf & negative, ninf_c, ninf_l),
        (is_nan, nan_c, nan_l),
    ):
        chars = jnp.where(mask[:, None], c, chars)
        length = jnp.where(mask, l, length)

    idx = jnp.arange(_MAX_CHARS)[None, :]
    chars = jnp.where(idx < length[:, None], chars, jnp.uint8(0))
    return chars, length


def float_to_string(col: Column) -> StringColumn:
    """Java Float/Double.toString per row (reference
    ``cast_float_to_string.cu:110``)."""
    kind = col.dtype.kind
    if kind is T.Kind.FLOAT64:
        pair = jax.lax.bitcast_convert_type(col.data, jnp.uint32)
        bits = pair[..., 0].astype(jnp.uint64) | (
            pair[..., 1].astype(jnp.uint64) << 32
        )
        negative = (bits >> _U64(63)) != 0
        exp_field = (bits >> _U64(52)) & _U64(0x7FF)
        mant = bits & _U64((1 << 52) - 1)
        is_nan = (exp_field == 0x7FF) & (mant != 0)
        is_inf = (exp_field == 0x7FF) & (mant == 0)
        is_zero = (exp_field == 0) & (mant == 0)
        digits, exp10 = _d2d(bits & _U64((1 << 63) - 1))
    elif kind is T.Kind.FLOAT32:
        bits = jax.lax.bitcast_convert_type(col.data, jnp.uint32)
        negative = (bits >> 31) != 0
        exp_field = (bits >> 23) & jnp.uint32(0xFF)
        mant = bits & jnp.uint32((1 << 23) - 1)
        is_nan = (exp_field == 0xFF) & (mant != 0)
        is_inf = (exp_field == 0xFF) & (mant == 0)
        is_zero = (exp_field == 0) & (mant == 0)
        digits, exp10 = _f2d(bits & jnp.uint32((1 << 31) - 1))
    else:
        raise TypeError(f"float_to_string expects FLOAT32/64, got {col.dtype!r}")

    chars, length = _format(digits, exp10, negative, is_nan, is_inf, is_zero)
    return StringColumn(chars, length * col.validity, col.validity)


def double_to_json_string(data):
    """Java Double.toString with the JSON tweaks of the reference's
    ``ftos_converter.cuh:1154-1200``: ±Infinity and NaN come back QUOTED
    (bare Infinity is not valid JSON), ±0.0 as "0.0"/"-0.0".

    Takes a raw float64 array; returns (chars uint8[n, 28], lengths int32).
    Used by get_json_object's number normalization.
    """
    pair = jax.lax.bitcast_convert_type(data, jnp.uint32)
    bits = pair[..., 0].astype(jnp.uint64) | (pair[..., 1].astype(jnp.uint64) << 32)
    negative = (bits >> _U64(63)) != 0
    exp_field = (bits >> _U64(52)) & _U64(0x7FF)
    mant = bits & _U64((1 << 52) - 1)
    is_nan = (exp_field == 0x7FF) & (mant != 0)
    is_inf = (exp_field == 0x7FF) & (mant == 0)
    is_zero = (exp_field == 0) & (mant == 0)
    digits, exp10 = _d2d(bits & _U64((1 << 63) - 1))
    chars, length = _format(digits, exp10, negative, is_nan, is_inf, is_zero)

    # quote the non-JSON specials
    n = chars.shape[0]
    chars = jnp.pad(chars, ((0, 0), (0, 2)))

    def qlit(s):
        raw = ('"' + s + '"').encode()
        buf = np.zeros((chars.shape[1],), np.uint8)
        buf[: len(raw)] = np.frombuffer(raw, np.uint8)
        return jnp.asarray(buf)[None, :], len(raw)

    for mask, (c, l) in (
        (is_inf & ~negative, qlit("Infinity")),
        (is_inf & negative, qlit("-Infinity")),
        (is_nan, qlit("NaN")),
    ):
        chars = jnp.where(mask[:, None], c, chars)
        length = jnp.where(mask, l, length)
    return chars, length.astype(jnp.int32)
