"""Regex fast-path: ``literal[start-end]{len,}`` containment check.

Reference: ``regex_rewrite_utils.cu:65-121`` (``literal_range_pattern``).
The plugin rewrites regexes of this shape into a direct scan instead of a
regex engine: does any position hold ``literal`` followed by at least
``len`` characters whose code points lie in ``[start, end]``?

Vectorized over (row, byte position): the literal match is ``m`` shifted
byte comparisons; the character-range run walks ``len`` steps of
per-position UTF-8 char-length gathers (characters, not bytes — matching
the reference's ``utf8_to_codepoint`` semantics).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column, StringColumn


def _decode_utf8(chars):
    """Per byte position: (codepoint, char byte length, is_char_start).

    Values at continuation-byte positions are garbage; ``is_start`` masks
    them.  Truncated sequences at the padded tail decode from zero pad
    bytes (harmless: the in-range check fails or length mask cuts them).
    """
    n, L = chars.shape
    b = [chars]
    for k in range(1, 4):
        b.append(
            jnp.pad(chars, ((0, 0), (0, k)))[:, k : L + k]
        )
    b0, b1, b2, b3 = (x.astype(jnp.int32) for x in b)
    is1 = b0 < 0x80
    is2 = (b0 >= 0xC0) & (b0 < 0xE0)
    is3 = (b0 >= 0xE0) & (b0 < 0xF0)
    is4 = b0 >= 0xF0
    cp = jnp.where(
        is1,
        b0,
        jnp.where(
            is2,
            ((b0 & 0x1F) << 6) | (b1 & 0x3F),
            jnp.where(
                is3,
                ((b0 & 0x0F) << 12) | ((b1 & 0x3F) << 6) | (b2 & 0x3F),
                ((b0 & 0x07) << 18) | ((b1 & 0x3F) << 12)
                | ((b2 & 0x3F) << 6) | (b3 & 0x3F),
            ),
        ),
    )
    clen = jnp.where(is1, 1, jnp.where(is2, 2, jnp.where(is3, 3, 4)))
    is_start = is1 | is2 | is3 | is4
    return cp, clen, is_start


def literal_range_pattern(
    col: StringColumn, literal: str, range_len: int, start: int, end: int
) -> Column:
    """bool per row; nulls stay null (reference regex_rewrite_utils.cu:121)."""
    lit = literal.encode("utf-8")
    m = len(lit)
    chars, lengths = col.chars, col.lengths
    n, L = chars.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_str = pos < lengths[:, None]

    cp, clen, is_start = _decode_utf8(chars)
    ok_char = is_start & (cp >= start) & (cp <= end) & in_str

    # literal byte match at each starting byte position
    lit_match = jnp.ones((n, L), jnp.bool_)
    for j, byte in enumerate(lit):
        shifted = jnp.pad(chars, ((0, 0), (0, j)))[:, j : L + j] if j else chars
        lit_match = lit_match & (shifted == byte)
    lit_match = lit_match & is_start & ((pos + m) <= lengths[:, None])

    # range run of `range_len` characters starting right after the literal
    run_ok = jnp.ones((n, L), jnp.bool_)
    cursor = jnp.broadcast_to(pos + m, (n, L))
    for _ in range(range_len):
        cur_clip = jnp.clip(cursor, 0, L - 1)
        ok_here = jnp.take_along_axis(ok_char, cur_clip, axis=1) & (cursor < L)
        run_ok = run_ok & ok_here
        step = jnp.take_along_axis(clen, cur_clip, axis=1)
        cursor = cursor + step

    found = (lit_match & run_ok).any(axis=1)
    return Column(found & col.validity, col.validity, T.BOOLEAN)
