"""Spark-exact string -> numeric casts.

Behavioral contract extracted from the reference kernels
(``cast_string.cu:159-246`` string->int, ``cast_string_to_float.cu:58-658``
string->float).  Both are faithful to Spark quirks, including:

* whitespace = C0 control codes (<= 0x1F) plus space (``is_whitespace``);
* string->int truncates at a decimal point in non-ANSI mode but still
  validates the characters after it ("20.5" -> 20, "7.8.3" -> null), and a
  bare "." parses as 0;
* string->float keeps at most 19 significant digits (further digits become
  trailing zeros of the exponent), loses values whose first 19 counted
  digits are all zeros ("0.0000000000000000000123" -> 0.0), accepts one
  trailing f/F/d/D after a nonzero number but NOT after a zero ("1f" -> 1.0
  but "0f" -> null), treats "nan" with junk as an ANSI error but "inf" with
  junk as a plain null, and rejects "-nan";
* the final float value is assembled in float64 arithmetic (digits * 10^exp)
  exactly like the reference, so last-ulp behavior matches the GPU path
  rather than a correctly-rounded strtod.

Ints run a ``fori_loop`` char scan (state machine vectorized across rows);
floats are fully positional (masks + cumulative ops over the padded char
axis) — both shapes keep every row on the VPU with no per-row Python.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column, StringColumn
from ._util import char_at as _char_at
from ._util import is_digit as _is_digit
from ._util import is_ws as _is_ws
from ._util import strip_and_sign


class CastException(RuntimeError):
    """ANSI-mode cast failure; carries the first offending row.

    Mirrors the reference ``CastException`` (cast_string.hpp:28-58), which
    reports the first invalid string and its row index.
    """

    def __init__(self, string_with_error: str, row_with_error: int):
        super().__init__(
            f"Error casting data on row {row_with_error}: {string_with_error}"
        )
        self.string_with_error = string_with_error
        self.row_with_error = row_with_error


_INT_BOUNDS = {
    T.Kind.INT8: (-(2**7), 2**7 - 1),
    T.Kind.INT16: (-(2**15), 2**15 - 1),
    T.Kind.INT32: (-(2**31), 2**31 - 1),
    T.Kind.INT64: (-(2**63), 2**63 - 1),
}


def string_to_integer(
    col: StringColumn,
    dtype: T.SparkType,
    ansi_mode: bool = False,
    strip: bool = True,
) -> Column:
    """Spark-exact string -> int8/16/32/64 (reference cast_string.cu:159).

    Scans characters left to right with the reference's exact state
    machine: optional stripped whitespace, one optional sign, digits with
    incremental overflow checks (accumulating negatively for '-', so MIN
    values parse), '.'-truncation in non-ANSI mode, trailing whitespace
    (strip only), everything else invalid.
    """
    kind = dtype.kind
    if kind not in _INT_BOUNDS:
        raise TypeError(f"not an integer type: {dtype!r}")
    tmin, tmax = _INT_BOUNDS[kind]

    chars, lengths = col.chars, col.lengths
    n, L = chars.shape
    idx = jnp.arange(L)[None, :]
    in_range = idx < lengths[:, None]

    start, has_sign, negative = strip_and_sign(chars, lengths, strip)

    valid0 = col.validity & (lengths > 0) & (start < lengths)

    min64 = jnp.int64(tmin)
    max64 = jnp.int64(tmax)
    min_div10 = jnp.int64(int(tmin / 10))  # C truncation toward zero
    max_div10 = jnp.int64(tmax // 10)

    def body(j, state):
        val, valid, truncating, trailing_ws, seen = state
        c = chars[:, j]
        active = valid0 & valid & (j >= start) & (j < lengths)
        is_d = _is_digit(c)
        ws = _is_ws(c)

        # ordered rules from the reference scan loop
        kill_after_ws = trailing_ws & ~ws
        to_truncate = ~truncating & (c == ord(".")) & (not ansi_mode) & ~kill_after_ws
        plain = ~kill_after_ws & ~to_truncate
        allowed_ws = ws & (j != start) & strip
        to_trailing = plain & ~is_d & allowed_ws
        invalid_char = plain & ~is_d & ~allowed_ws

        digit = (c - ord("0")).astype(jnp.int64)
        first = ~seen
        # accumulate toward -inf for negatives so MIN parses (reference
        # process_value: adding=sign>0)
        mul_ovf = ~first & jnp.where(negative, val < min_div10, val > max_div10)
        val10 = jnp.where(first, val, val * 10)
        add_ovf = jnp.where(negative, val10 < min64 + digit, val10 > max64 - digit)
        ovf = mul_ovf | add_ovf
        newval = jnp.where(negative, val10 - digit, val10 + digit)

        do_digit = active & plain & is_d & ~truncating & ~trailing_ws
        val = jnp.where(do_digit & ~ovf, newval, val)
        seen = seen | do_digit
        valid = valid & ~(active & (kill_after_ws | invalid_char | (do_digit & ovf)))
        truncating = truncating | (active & to_truncate)
        trailing_ws = trailing_ws | (active & to_trailing)
        return val, valid, truncating, trailing_ws, seen

    init = (
        jnp.zeros((n,), jnp.int64),
        jnp.ones((n,), jnp.bool_),
        jnp.zeros((n,), jnp.bool_),
        jnp.zeros((n,), jnp.bool_),
        jnp.zeros((n,), jnp.bool_),
    )
    val, scan_valid, _, _, _ = jax.lax.fori_loop(0, L, body, init)
    valid = valid0 & scan_valid

    out = Column(val.astype(dtype.jnp_dtype), valid, dtype)
    if ansi_mode:
        _raise_on_invalid(col, valid)
    return out


def _raise_on_invalid(col: StringColumn, valid):
    """ANSI mode: surface the first failed row as a CastException.

    Fails only for rows that were non-null on input (a null input row stays
    null, it is not an error — reference CastStringJni ANSI handling).
    """
    bad = np.asarray(jax.device_get(col.validity & ~valid))
    if bad.any():
        row = int(np.argmax(bad))
        s = col.to_pylist()[row]
        raise CastException(s if s is not None else "<null>", row)


# ---------------------------------------------------------------------------
# string -> float
# ---------------------------------------------------------------------------

# correctly-rounded signed powers of ten: 1e-340 .. 1e309 (inf past the top,
# 0.0 past the bottom), indexed by e + _POW10_OFF
_POW10_OFF = 340
# numpy, not jnp: module scope must not mint device arrays (GL001) — the
# tables convert per use site, where they trace as compile-time constants
_POW10_F64 = np.asarray(
    [float(f"1e{k}") for k in range(-_POW10_OFF, 310)], dtype=np.float64
)


def _pow10f(e):
    """10.0**e in float64 (the reference computes exp10() in double)."""
    return jnp.asarray(_POW10_F64)[jnp.clip(e + _POW10_OFF, 0, _POW10_OFF + 309)]


_POW10_U64 = np.asarray([10**k for k in range(0, 19)], dtype=np.uint64)


def _all_ws_from(chars, lengths, pos):
    """True where every char in [pos, len) is whitespace."""
    idx = jnp.arange(chars.shape[1])[None, :]
    region = (idx >= pos[:, None]) & (idx < lengths[:, None])
    return ~(region & ~_is_ws(chars)).any(axis=1)


def string_to_float(
    col: StringColumn, dtype: T.SparkType, ansi_mode: bool = False
) -> Column:
    """Spark-exact string -> float32/float64 (reference cast_string_to_float.cu).

    Fully positional: leading/trailing regions, the digit+dot run, the
    19-significant-digit budget, and the optional exponent are all derived
    with masks and cumulative sums over the padded char axis — no scan.
    """
    if dtype.kind not in (T.Kind.FLOAT32, T.Kind.FLOAT64):
        raise TypeError(f"not a float type: {dtype!r}")

    chars, lengths = col.chars, col.lengths
    n, L = chars.shape
    idx = jnp.arange(L)[None, :]
    in_range = idx < lengths[:, None]
    lower = chars | jnp.uint8(0x20)  # ASCII lowercase for letter comparisons

    s, has_sign, negative = strip_and_sign(chars, lengths, strip=True)
    sign = jnp.where(negative, jnp.float64(-1.0), jnp.float64(1.0))

    base_valid = col.validity & (lengths > 0)

    def lc_at(pos):
        c = _char_at(chars, pos)
        return c | jnp.uint8(0x20)

    def match(pos, word):
        m = jnp.ones((n,), jnp.bool_)
        for k, ch in enumerate(word):
            m = m & (lc_at(pos + k) == ord(ch))
        return m

    # ---- nan ----------------------------------------------------------
    is_nan_word = match(s, "nan") & (s + 3 <= lengths)
    nan_clean = _all_ws_from(chars, lengths, s + 3)
    nan_ok = is_nan_word & nan_clean & ~negative
    nan_bad = is_nan_word & ~(nan_clean & ~negative)  # ANSI error (ref :239-266)

    # ---- inf / infinity ----------------------------------------------
    is_inf3 = match(s, "inf") & (s + 3 <= lengths) & ~is_nan_word
    is_inf8 = is_inf3 & match(s + 3, "inity") & (s + 8 <= lengths)
    inf_end = jnp.where(is_inf8, s + 8, s + 3)
    inf_clean = _all_ws_from(chars, lengths, inf_end)
    inf_ok = is_inf3 & inf_clean
    inf_bad = is_inf3 & ~inf_clean  # plain null, NOT an ANSI error (ref :286-327)

    word_path = is_nan_word | is_inf3

    # ---- digit run [s, q) --------------------------------------------
    digit = _is_digit(chars)
    dot = chars == ord(".")
    ok = (digit | dot) & in_range
    # run_ok[j] == all positions in [s, j] are ok  (positions < s are free)
    run_ok = jnp.cumprod(
        jnp.where(idx < s[:, None], True, ok).astype(jnp.int32), axis=1
    ).astype(bool)
    run = run_ok & (idx >= s[:, None])
    run_len = run.sum(axis=1).astype(jnp.int32)
    q = s + run_len

    ndots = (dot & run).sum(axis=1)
    multi_dot = ndots > 1
    has_dot = ndots == 1
    dot_in_run = dot & run
    dot_pos = jnp.where(
        has_dot, jnp.argmax(dot_in_run, axis=1).astype(jnp.int32), q
    )

    digit_in_run = digit & run
    any_digit = digit_in_run.any(axis=1)

    # counted digits: post-dot digits always count; pre-dot digits count
    # from the first nonzero on (leading-zero strip, ref :345-361)
    nz_pre = digit_in_run & (chars != ord("0")) & (idx < dot_pos[:, None])
    any_nz_pre = nz_pre.any(axis=1)
    first_nz_pre = jnp.where(
        any_nz_pre, jnp.argmax(nz_pre, axis=1).astype(jnp.int32), q
    )
    counted = digit_in_run & (
        (idx > dot_pos[:, None]) | (idx >= first_nz_pre[:, None])
    )
    total_counted = counted.sum(axis=1).astype(jnp.int32)
    real = jnp.minimum(total_counted, 19)
    truncated = total_counted - real

    # value of the first 19 counted digits (uint64), by per-digit rank
    rank = jnp.cumsum(counted.astype(jnp.int32), axis=1)  # 1-based at digits
    contrib_mask = counted & (rank <= 19)
    exp_k = jnp.clip(real[:, None] - rank, 0, 18)
    digitval = (chars - ord("0")).astype(jnp.uint64)
    digits = jnp.where(
        contrib_mask, digitval * jnp.asarray(_POW10_U64)[exp_k], jnp.uint64(0)
    ).sum(axis=1)

    decimal_pos_counted = (counted & (idx < dot_pos[:, None])).sum(axis=1).astype(
        jnp.int32
    )
    exp_base = truncated - jnp.where(
        has_dot, total_counted - decimal_pos_counted, 0
    )

    # ---- manual exponent ---------------------------------------------
    has_e = (lc_at(q) == ord("e")) & (q < lengths)
    esc = _char_at(chars, q + 1)
    has_esign = has_e & ((esc == ord("+")) | (esc == ord("-")))
    eneg = has_esign & (esc == ord("-"))
    ed_start = q + 1 + has_esign.astype(jnp.int32)
    # leading digit run after the exponent marker, capped at 4 digits read
    ed_ok = jnp.cumprod(
        jnp.where(idx < ed_start[:, None], True, digit & in_range).astype(jnp.int32),
        axis=1,
    ).astype(bool)
    ed_run_len = (ed_ok & (idx >= ed_start[:, None])).sum(axis=1).astype(jnp.int32)
    ed_count = jnp.minimum(ed_run_len, 4)
    e_digit_mask = (idx >= ed_start[:, None]) & (idx < (ed_start + ed_count)[:, None])
    e_rank = jnp.cumsum(e_digit_mask.astype(jnp.int32), axis=1)
    e_val = jnp.where(
        e_digit_mask,
        (chars - ord("0")).astype(jnp.int32)
        * jnp.asarray([10**k for k in range(4)], jnp.int32)[
            jnp.clip(ed_count[:, None] - e_rank, 0, 3)
        ],
        0,
    ).sum(axis=1)
    manual_exp = jnp.where(has_e, jnp.where(eneg, -e_val, e_val), 0)
    exp_bad = has_e & (ed_count == 0)  # "1e" / "1e+" -> ANSI error (ref :533-537)
    after_exp = jnp.where(has_e, ed_start + ed_count, q)

    # ---- zero-value quirk path ---------------------------------------
    is_zero = digits == jnp.uint64(0)
    zero_clean = _all_ws_from(chars, lengths, after_exp)  # no f/d allowed
    # ---- nonzero trailing: one optional f/F/d/D then whitespace ------
    tc = lc_at(after_exp)
    has_fd = ((tc == ord("f")) | (tc == ord("d"))) & (after_exp < lengths)
    after_fd = after_exp + has_fd.astype(jnp.int32)
    tail_clean = _all_ws_from(chars, lengths, after_fd)

    seen_valid_digit = any_digit  # a digit anywhere in the run
    num_invalid = (
        multi_dot
        | ~seen_valid_digit
        | exp_bad
        | (is_zero & ~zero_clean)
        | (~is_zero & ~tail_clean)
    )
    num_ok = ~word_path & ~num_invalid

    # ---- final value (float64 arithmetic, reference :154-197) --------
    digitsf = sign * digits.astype(jnp.float64)
    exp_ten = exp_base + manual_exp
    # subnormal pre-scaling (reference :181-189)
    sub_shift = -307 - exp_ten
    num_digits10 = jnp.where(
        is_zero,
        1,
        (jnp.floor(jnp.log10(jnp.maximum(digits.astype(jnp.float64), 1.0))) + 1).astype(
            jnp.int32
        ),
    )
    sub_digitsf = digitsf / _pow10f(num_digits10 - 1 + sub_shift)
    sub_exp = exp_ten + num_digits10 - 1
    sub_val = sub_digitsf * _pow10f(sub_exp + sub_shift)
    plain_pow = _pow10f(jnp.abs(exp_ten))
    plain_val = jnp.where(exp_ten < 0, digitsf / plain_pow, digitsf * plain_pow)
    number = jnp.where(
        exp_ten > 308,
        sign * jnp.float64(jnp.inf),
        jnp.where(sub_shift > 0, sub_val, plain_val),
    )
    number = jnp.where(is_zero, sign * jnp.float64(0.0), number)

    value = jnp.where(
        nan_ok,
        jnp.float64(jnp.nan),
        jnp.where(inf_ok, sign * jnp.float64(jnp.inf), number),
    )
    valid = base_valid & (nan_ok | inf_ok | num_ok)
    # ANSI "except" flag: digit-path errors (including empty/all-whitespace
    # strings, which fail the seen-valid-digit check, ref :400-405) and
    # nan-with-junk raise; a bad inf is a plain null without an exception
    # (reference check_for_inf sets only _valid) — replicated quirk.
    except_flag = col.validity & (nan_bad | (~word_path & num_invalid))
    _ = inf_bad  # inf junk: plain null (documented above)

    out = Column(value.astype(dtype.jnp_dtype), valid, dtype)
    if ansi_mode:
        bad = np.asarray(jax.device_get(except_flag))
        if bad.any():
            row = int(np.argmax(bad))
            s_err = col.to_pylist()[row]
            raise CastException(s_err if s_err is not None else "<null>", row)
    return out


# ---------------------------------------------------------------------------
# string -> decimal
# ---------------------------------------------------------------------------


def string_to_decimal(
    col: StringColumn,
    precision: int,
    scale: int,
    ansi_mode: bool = False,
    strip: bool = True,
) -> Column:
    """Spark-exact string -> decimal (reference cast_string.cu:247-582).

    ``scale`` follows the cudf/JNI convention of the reference API: negative
    scale means fraction digits (``string_to_decimal(precision=3, scale=-1)``
    of "9.23" gives unscaled 92).  The returned column's SparkType carries
    the Spark-style scale (``-scale``).

    Semantics replicated from the two-phase reference kernel:

    * phase A validates (optional stripped whitespace, sign, digits, one
      '.', exponent with sign) and finds the virtual decimal location =
      (digit count before '.'|'e'|ws) + exponent.  Quirks preserved: a bare
      trailing "e" or "e+" is VALID with exponent 0, "1e5 " is invalid
      (nothing may follow exponent digits), "." parses as 0.
    * phase B walks digits accumulating into the storage type, rounding
      half-up (away from zero) at the first digit beyond ``precision`` or
      beyond ``decimal_location - scale``, tracking whether rounding added
      a digit (999 -> 1000), then zero-pads up to the decimal location and
      down to the scale, failing on overflow or when more integer digits
      are required than ``precision + scale`` allows.

    Only precision <= 18 (decimal32/64 storage) is supported until the
    decimal128 limb arithmetic lands.
    """
    if precision > 18:
        raise NotImplementedError(
            "string_to_decimal with precision > 18 needs decimal128 limb math"
        )
    if precision <= 9:
        tmin, tmax = -(2**31), 2**31 - 1
    else:
        tmin, tmax = -(2**63), 2**63 - 1

    chars, lengths = col.chars, col.lengths
    n, L = chars.shape
    idx = jnp.arange(L)[None, :]
    in_range = idx < lengths[:, None]

    first_digit, has_sign, _neg = strip_and_sign(chars, lengths, strip)
    positive = ~_neg
    base_valid = col.validity & (lengths > 0) & (first_digit < lengths)

    # state machine over [first_digit, len): states as in the reference
    ST_DIGITS, ST_EXP_OR_SIGN, ST_EXP_SIGN, ST_EXP, ST_TRAIL_WS, ST_INVALID = range(6)
    min64 = jnp.int64(tmin)
    max64 = jnp.int64(tmax)
    min_div10 = jnp.int64(int(tmin / 10))
    max_div10 = jnp.int64(tmax // 10)

    def phase_a(j, st):
        state, dec_loc, exp_val, exp_pos, last_digit, seen_exp_digit = st
        c = chars[:, j]
        active = base_valid & (j >= first_digit) & (j < lengths)
        rel = j - first_digit  # chr_idx in the reference
        is_d = _is_digit(c)
        ws = _is_ws(c)
        allowed_ws = ws & (rel != 0) & strip

        in_digits = state == ST_DIGITS
        to_decimal = in_digits & (c == ord(".")) & (dec_loc < 0)
        to_exp_or_sign = in_digits & ((c == ord("e")) | (c == ord("E")))
        to_trail_from_digits = in_digits & ~is_d & ~to_decimal & ~to_exp_or_sign & allowed_ws
        digits_invalid = in_digits & ~is_d & ~to_decimal & ~to_exp_or_sign & ~allowed_ws

        in_eos = state == ST_EXP_OR_SIGN
        eos_sign = in_eos & ((c == ord("+")) | (c == ord("-")))
        eos_trail = in_eos & ~eos_sign & allowed_ws
        eos_digit = in_eos & ~eos_sign & ~eos_trail & is_d
        eos_invalid = in_eos & ~eos_sign & ~eos_trail & ~is_d

        in_exp = (state == ST_EXP) | (state == ST_EXP_SIGN)
        exp_digit = in_exp & is_d
        exp_invalid = in_exp & ~is_d

        trail_invalid = (state == ST_TRAIL_WS) & ~ws

        new_state = jnp.where(
            to_decimal | (in_digits & is_d),
            ST_DIGITS,
            jnp.where(
                to_exp_or_sign,
                ST_EXP_OR_SIGN,
                jnp.where(
                    eos_sign,
                    ST_EXP_SIGN,
                    jnp.where(
                        eos_digit | exp_digit,
                        ST_EXP,
                        jnp.where(
                            to_trail_from_digits | eos_trail, ST_TRAIL_WS, state
                        ),
                    ),
                ),
            ),
        )
        invalid_now = (
            digits_invalid | eos_invalid | exp_invalid | trail_invalid
        )
        new_state = jnp.where(invalid_now, ST_INVALID, new_state)
        # decimal location: index (relative) of the '.'
        dec_loc = jnp.where(active & to_decimal, rel, dec_loc)
        # leaving DIGITS (state was digits, new is exp-or-sign or trailing):
        # record the end of the digit run (reference :353-356)
        leaving = in_digits & (to_exp_or_sign | to_trail_from_digits)
        last_digit = jnp.where(active & leaving, j, last_digit)
        exp_pos = jnp.where(active & eos_sign & (c == ord("-")), False, exp_pos)

        # exponent accumulation with the same overflow rules as digits
        d = (c - ord("0")).astype(jnp.int64)
        is_exp_dig = active & (eos_digit | exp_digit)
        first = ~seen_exp_digit
        mul_ovf = ~first & jnp.where(exp_pos, exp_val > max_div10, exp_val < min_div10)
        e10 = jnp.where(first, exp_val, exp_val * 10)
        add_ovf = jnp.where(exp_pos, e10 > max64 - d, e10 < min64 + d)
        newexp = jnp.where(exp_pos, e10 + d, e10 - d)
        new_state = jnp.where(is_exp_dig & (mul_ovf | add_ovf), ST_INVALID, new_state)
        exp_val = jnp.where(is_exp_dig & ~(mul_ovf | add_ovf), newexp, exp_val)
        seen_exp_digit = seen_exp_digit | is_exp_dig

        state = jnp.where(active, new_state, state)
        return state, dec_loc, exp_val, exp_pos, last_digit, seen_exp_digit

    init_a = (
        jnp.full((n,), ST_DIGITS, jnp.int32),
        jnp.full((n,), -1, jnp.int32),       # decimal '.' relative index
        jnp.zeros((n,), jnp.int64),          # exponent value
        jnp.ones((n,), jnp.bool_),           # exponent positive
        jnp.full((n,), -1, jnp.int32),       # absolute end of digit run
        jnp.zeros((n,), jnp.bool_),
    )
    state, dot_rel, exp_val, _, last_digit_abs, _ = jax.lax.fori_loop(
        0, L, phase_a, init_a
    )
    a_valid = base_valid & (state != ST_INVALID)
    last_digit_abs = jnp.where(last_digit_abs < 0, lengths, last_digit_abs)
    dec_loc = jnp.where(
        dot_rel >= 0, dot_rel.astype(jnp.int64), (last_digit_abs - first_digit).astype(jnp.int64)
    )
    dec_loc = dec_loc + exp_val

    # ---- significant digits before the decimal location (reference :425-441)
    digit = _is_digit(chars)
    after_first = (idx >= first_digit[:, None]) & in_range
    # stop at e/E
    is_e = (chars == ord("e")) | (chars == ord("E"))
    before_e = jnp.cumsum((is_e & after_first).astype(jnp.int32), axis=1) == 0
    scan_region = after_first & before_e
    digits_found = jnp.cumsum((digit & scan_region).astype(jnp.int64), axis=1)
    # digit qualifies if its ordinal <= dec_loc
    qualifying = digit & scan_region & (digits_found <= dec_loc[:, None])
    # significant = from first nonzero qualifying digit on
    nz_qual = qualifying & (chars != ord("0"))
    any_nzq = nz_qual.any(axis=1)
    first_nzq = jnp.where(any_nzq, jnp.argmax(nz_qual, axis=1), L).astype(jnp.int32)
    sig_before_in_string = (qualifying & (idx >= first_nzq[:, None])).sum(axis=1).astype(jnp.int64)

    # ---- phase B: build the value with rounding ----------------------
    last_digit_cnt = dec_loc - scale  # digits to keep (reference :452)
    pow10_i64 = jnp.asarray([10**k for k in range(19)], jnp.int64)

    def count_digits(v):
        a = jnp.abs(v)
        return jnp.searchsorted(pow10_i64, a, side="right").astype(jnp.int32)

    def phase_b(j, st):
        val, total, precise, found_sig, rounding, done, bvalid, dloc = st
        c = chars[:, j]
        active = (
            a_valid
            & bvalid
            & ~done
            & (j >= first_digit)
            & (j < lengths)
            & (last_digit_cnt >= 0)
        )
        is_dot = c == ord(".")
        is_d = _is_digit(c)
        brk = active & ~is_dot & ~is_d
        done = done | brk
        process = active & is_d & ~brk

        d = (c - ord("0")).astype(jnp.int64)
        need_round = (precise + 1 > precision) | (total + 1 > last_digit_cnt)

        # rounding path (reference :474-512)
        inc_ovf = jnp.where(positive, val > max64 - 1, val < min64 + 1)
        rounded = jnp.where(positive, val + 1, val - 1)
        adds_digit = (val != 0) & (count_digits(rounded) > count_digits(val))
        do_round = process & need_round & (d >= 5)
        round_fail = do_round & inc_ovf
        val = jnp.where(do_round & ~inc_ovf, rounded, val)
        grow = do_round & ~inc_ovf & adds_digit
        total = total + grow.astype(jnp.int64)
        precise = precise + grow.astype(jnp.int64)
        dloc = dloc + grow.astype(jnp.int64)
        rounding = rounding + grow.astype(jnp.int64)
        done = done | (process & need_round)
        bvalid = bvalid & ~round_fail

        # normal digit accumulation
        acc = process & ~need_round
        total = total + acc.astype(jnp.int64)
        newly_sig = found_sig | (total > dloc) | (d != 0)
        first = j == first_digit
        mul_ovf = ~first & jnp.where(positive, val > max_div10, val < min_div10)
        v10 = jnp.where(first, val, val * 10)
        add_ovf = jnp.where(positive, v10 > max64 - d, v10 < min64 + d)
        ovf = acc & (mul_ovf | add_ovf)
        val = jnp.where(acc & ~ovf, jnp.where(positive, v10 + d, v10 - d), val)
        precise = precise + (acc & newly_sig).astype(jnp.int64)
        found_sig = jnp.where(acc, newly_sig, found_sig)
        bvalid = bvalid & ~ovf
        done = done | ovf
        return val, total, precise, found_sig, rounding, done, bvalid, dloc

    init_b = (
        jnp.zeros((n,), jnp.int64),
        jnp.zeros((n,), jnp.int64),
        jnp.zeros((n,), jnp.int64),
        jnp.zeros((n,), jnp.bool_),
        jnp.zeros((n,), jnp.int64),
        jnp.zeros((n,), jnp.bool_),
        jnp.ones((n,), jnp.bool_),
        dec_loc,
    )
    val, total, precise, _, rounding, _, b_valid, dec_loc2 = jax.lax.fori_loop(
        0, L, phase_b, init_b
    )

    # ---- padding & precision checks (reference :531-573) --------------
    sig_preceding_zeros = jnp.maximum(0, -dec_loc2)
    zeros_to_decimal = jnp.maximum(
        0,
        jnp.where(scale > 0, dec_loc2 - total - scale, dec_loc2 - total),
    )
    sig_before = sig_before_in_string + zeros_to_decimal + rounding
    fits = (precision + scale) >= sig_before

    # pad up to the decimal location: val *= 10 zeros_to_decimal times
    def pad_loop(k, st):
        val, precise, ok = st
        do = (k < zeros_to_decimal) & ok
        ovf = jnp.where(positive, val > max_div10, val < min_div10)
        val = jnp.where(do & ~ovf, val * 10, val)
        precise = precise + (do & ~ovf).astype(jnp.int64)
        ok = ok & ~(do & ovf)
        return val, precise, ok

    max_pad = int(precision + abs(scale) + 2)
    val, precise, pad_ok = jax.lax.fori_loop(
        0, max_pad, pad_loop, (val, precise, jnp.ones((n,), jnp.bool_))
    )

    digits_after = precise - sig_before + sig_preceding_zeros
    needed_after = jnp.minimum(precision - sig_before, -scale)

    def pad2_loop(k, st):
        val, ok = st
        do = ((digits_after + k) < needed_after) & ok
        ovf = jnp.where(positive, val > max_div10, val < min_div10)
        val = jnp.where(do & ~ovf, val * 10, val)
        ok = ok & ~(do & ovf)
        return val, ok

    val, pad2_ok = jax.lax.fori_loop(0, max_pad, pad2_loop, (val, jnp.ones((n,), jnp.bool_)))

    valid = a_valid & b_valid & fits & pad_ok & pad2_ok
    dtype = T.SparkType.decimal(precision, -scale)
    out = Column(val.astype(dtype.jnp_dtype), valid, dtype)
    if ansi_mode:
        _raise_on_invalid(col, valid)
    return out


# ---------------------------------------------------------------------------
# string <-> integer with base (Spark ``conv()``; reference
# CastStringJni.cpp:159-259 toIntegersWithBase / fromIntegersWithBase)
# ---------------------------------------------------------------------------

# the reference validity regexes use \s — cudf's [ \t\n\r\f\v]
_CONV_WS = (0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B)


def string_to_integer_with_base(
    col: StringColumn,
    dtype: T.SparkType,
    base: int = 10,
    ansi_mode: bool = False,
) -> Column:
    """Parse ``^\\s*(-?[digits]+).*`` per row; Spark ``conv()`` semantics.

    Mirrors reference ``CastStringJni.cpp:159-228``: rows are matched
    against the prefix regex; non-matching rows yield **0** (not null);
    all-whitespace/empty rows and input nulls yield null; a leading ``-``
    negates with wraparound in the unsigned bit pattern (``-510`` as
    UINT64 -> 18446744073709551106).  Junk after the digit run is ignored.
    The result column stores the unsigned bit pattern (our type system is
    signed; the JNI surface's UINT64 is the same 64 bits).  ``ansi_mode``
    is accepted for signature parity — the reference native code never
    reads it.
    """
    del ansi_mode
    if base not in (10, 16):
        raise ValueError(f"Bases supported 10, 16; Actual: {base}")
    chars, lengths = col.chars, col.lengths
    n, L = chars.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_str = pos < lengths[:, None]

    ws = jnp.zeros_like(chars, dtype=jnp.bool_)
    for w in _CONV_WS:
        ws = ws | (chars == w)
    ws = ws & in_str
    # run of leading whitespace
    nws = jnp.cumprod(ws.astype(jnp.int32), axis=1).sum(axis=1)

    start = jnp.minimum(nws, jnp.maximum(lengths, 1) - 1)
    first = jnp.take_along_axis(chars, start[:, None], axis=1)[:, 0]
    has_minus = (first == ord("-")) & (nws < lengths)
    dstart = nws + has_minus.astype(jnp.int32)

    lower = chars | 0x20
    is_dig = (chars >= ord("0")) & (chars <= ord("9"))
    dval = (chars - ord("0")).astype(jnp.uint64)
    if base == 16:
        is_hex = (lower >= ord("a")) & (lower <= ord("f"))
        dval = jnp.where(is_hex, (lower - ord("a") + 10).astype(jnp.uint64), dval)
        is_dig = is_dig | is_hex

    after = pos >= dstart[:, None]
    run = jnp.cumprod(
        jnp.where(after, is_dig & in_str, True).astype(jnp.int32), axis=1
    ).astype(jnp.bool_)
    digit_mask = run & after & in_str
    matched = digit_mask.any(axis=1)

    b = jnp.uint64(base)

    def body(j, v):
        return jnp.where(digit_mask[:, j], v * b + dval[:, j], v)

    val = jax.lax.fori_loop(0, L, body, jnp.zeros((n,), jnp.uint64))
    val = jnp.where(has_minus & matched, jnp.uint64(0) - val, val)
    val = jnp.where(matched, val, jnp.uint64(0))

    all_ws = nws >= lengths  # includes empty strings
    valid = col.validity & ~all_ws
    bits = jax.lax.bitcast_convert_type(val, jnp.int64).astype(
        dtype.jnp_dtype
    )
    return Column(bits, valid, dtype)


# numpy, not jnp: module scope must not mint device arrays (GL001)
_HEX_DIGITS = np.asarray(
    [ord(c) for c in "0123456789ABCDEF"], dtype=np.uint8
)
_POW10_CONV = np.asarray(
    [np.uint64(10) ** k for k in range(20)], dtype=np.uint64
)


def integer_to_string_with_base(col: Column, base: int = 10) -> StringColumn:
    """Format the unsigned bit pattern in base 10 or 16 (reference
    ``CastStringJni.cpp:229-259``).

    Base 16 emits minimal uppercase hex digits (cudf ``integers_to_hex``
    followed by the reference's leading-zero strip); base 10 emits the
    unsigned decimal of the stored bits (``strings::from_integers`` over
    the UINT64 column the paired cast produces).  Nulls propagate.
    """
    if base not in (10, 16):
        raise ValueError(f"Bases supported 10, 16; Actual: {base}")
    width_bytes = np.dtype(col.dtype.jnp_dtype).itemsize
    u = jax.lax.bitcast_convert_type(
        col.data.astype(jnp.int64), jnp.uint64
    )
    if width_bytes < 8:
        u = u & jnp.uint64((1 << (8 * width_bytes)) - 1)
    n = col.num_rows

    if base == 16:
        max_out = 2 * width_bytes
        nibble = jnp.arange(max_out, dtype=jnp.uint64)
        shifted = (u[:, None] >> (jnp.uint64(4) * nibble[None, :])) & jnp.uint64(0xF)
        ndig = jnp.maximum(
            (shifted != 0).astype(jnp.int32)
            * (jnp.arange(max_out, dtype=jnp.int32)[None, :] + 1),
            0,
        ).max(axis=1)
        ndig = jnp.maximum(ndig, 1)
        outpos = jnp.arange(max_out, dtype=jnp.int32)[None, :]
        src = ndig[:, None] - 1 - outpos  # nibble index, msd first
        digit = jnp.take_along_axis(
            shifted, jnp.clip(src, 0, max_out - 1).astype(jnp.int32), axis=1
        )
        out = jnp.where(
            outpos < ndig[:, None],
            jnp.asarray(_HEX_DIGITS)[digit.astype(jnp.int32)],
            jnp.uint8(0),
        )
        return StringColumn(out, ndig, col.validity)

    max_out = 20  # 2^64-1 has 20 decimal digits
    j = jnp.arange(max_out, dtype=jnp.int32)
    digs = (u[:, None] // jnp.asarray(_POW10_CONV)[None, :]) % jnp.uint64(10)
    ndig = jnp.maximum((digs != 0).astype(jnp.int32) * (j[None, :] + 1), 0).max(axis=1)
    ndig = jnp.maximum(ndig, 1)
    outpos = j[None, :]
    src = ndig[:, None] - 1 - outpos
    digit = jnp.take_along_axis(
        digs, jnp.clip(src, 0, max_out - 1).astype(jnp.int32), axis=1
    )
    out = jnp.where(
        outpos < ndig[:, None],
        (digit + jnp.uint64(ord("0"))).astype(jnp.uint8),
        jnp.uint8(0),
    )
    return StringColumn(out, ndig, col.validity)
