"""Proleptic-Gregorian ⇄ hybrid-Julian calendar rebase for days/micros.

Matches Spark's ``localRebaseGregorianToJulianDays`` /
``rebaseGregorianToJulianMicros`` (UTC) family as implemented by the
reference ``datetime_rebase.cu``:

* A date >= 1582-10-15 (Gregorian adoption) is identical in both calendars.
* Dates in the adoption gap (1582-10-05 .. 1582-10-14, which never existed
  in the hybrid calendar) collapse to 1582-10-15 → day -141427.
* Older dates: reinterpret the local y/m/d in the other calendar and
  recompute days-since-epoch.  Civil-date math follows Howard Hinnant's
  ``days_from_civil``/``civil_from_days`` algorithms (as the reference does,
  datetime_rebase.cu:40-52,110-126), which are pure integer arithmetic and
  vectorize directly; jnp's floor division replaces the reference's manual
  negative-value fixups.

Micros variants split into (days, time-of-day) with floor/pmod semantics
(``get_time_components``, datetime_rebase.cu:198-222) and reuse the day
rebase on the date part; time-of-day passes through unchanged.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column

_GREGORIAN_START_DAYS = -141427  # 1582-10-15
_JULIAN_END_DAYS = -141438  # 1582-10-04 in proleptic Gregorian days
_CUTOVER_MICROS = -12219292800000000  # 1582-10-15T00:00:00Z
_MICROS_PER_DAY = 86400 * 1000000


def _civil_from_days(z):
    """Gregorian days-since-epoch -> (y, m, d)."""
    z = z + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    return y + (m <= 2), m, d


def _days_from_civil(y, m, d):
    """(y, m, d) Gregorian -> days-since-epoch."""
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _days_from_julian(y, m, d):
    """(y, m, d) Julian calendar -> days-since-epoch (reference
    days_from_julian, datetime_rebase.cu:40)."""
    y = y - (m <= 2)
    era = y // 4
    yoe = y - era * 4
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + doy
    return era * 1461 + doe - 719470


def _julian_from_days(z):
    """days-since-epoch -> (y, m, d) in the Julian calendar (reference
    julian_from_days, datetime_rebase.cu:110)."""
    z = z + 719470
    era = z // 1461
    doe = z - era * 1461
    yoe = (doe - doe // 1460) // 365
    y = yoe + era * 4
    doy = doe - 365 * yoe
    mp = (5 * doy + 2) // 153
    m = mp + jnp.where(mp < 10, 3, -9)
    d = doy - (153 * mp + 2) // 5 + 1
    return y + (m <= 2), m, d


def _rebase_days_g2j(days):
    y, m, d = _civil_from_days(days)
    julian = _days_from_julian(y, m, d)
    out = jnp.where(days > _JULIAN_END_DAYS, _GREGORIAN_START_DAYS, julian)
    return jnp.where(days >= _GREGORIAN_START_DAYS, days, out).astype(days.dtype)


def _rebase_days_j2g(days):
    y, m, d = _julian_from_days(days)
    greg = _days_from_civil(y, m, d)
    return jnp.where(days >= _GREGORIAN_START_DAYS, days, greg).astype(days.dtype)


def _rebase_micros(micros, day_fn):
    days = micros // _MICROS_PER_DAY
    tod = micros - days * _MICROS_PER_DAY  # [0, day) — floor/pmod semantics
    out = day_fn(days) * _MICROS_PER_DAY + tod
    return jnp.where(micros >= _CUTOVER_MICROS, micros, out)


def rebase_gregorian_to_julian(col: Column) -> Column:
    """DATE/TIMESTAMP rebase (reference rebase_gregorian_to_julian,
    datetime_rebase.cu:346)."""
    if col.dtype.kind is T.Kind.DATE:
        return Column(_rebase_days_g2j(col.data), col.validity, col.dtype)
    if col.dtype.kind is T.Kind.TIMESTAMP:
        return Column(
            _rebase_micros(col.data, _rebase_days_g2j), col.validity, col.dtype
        )
    raise TypeError(f"rebase expects DATE or TIMESTAMP, got {col.dtype!r}")


def rebase_julian_to_gregorian(col: Column) -> Column:
    """Inverse rebase (reference rebase_julian_to_gregorian,
    datetime_rebase.cu:361)."""
    if col.dtype.kind is T.Kind.DATE:
        return Column(_rebase_days_j2g(col.data), col.validity, col.dtype)
    if col.dtype.kind is T.Kind.TIMESTAMP:
        return Column(
            _rebase_micros(col.data, _rebase_days_j2g), col.validity, col.dtype
        )
    raise TypeError(f"rebase expects DATE or TIMESTAMP, got {col.dtype!r}")
