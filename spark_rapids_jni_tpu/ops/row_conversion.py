"""JCUDF row ⇄ columnar transpose.

The row format (documented in reference ``RowConversion.java:57-116``, and
produced by ``row_conversion.cu``):

* columns laid out in order, each aligned to its own byte width (padding in
  front); little-endian values.
* a string column occupies an 8-byte ``(offset int32, length int32)`` slot
  in the fixed-width area (``row_conversion.cu:1337``); its bytes live in a
  variable region after the validity bytes, packed in column order.
* validity bytes right after the last fixed slot (no alignment gap): one
  byte per 8 columns, bit ``c % 8`` of byte ``c // 8`` (set = non-null).
* each row padded to an 8-byte boundary.

TPU formulation: the row image is a ``uint8[n, row_width]`` matrix.
``convert_to_rows`` writes column slices (static offsets — pure elementwise
byte math); the string region is assembled *gather-wise*: for each string
column the destination is a per-row offset, so instead of scattering we
compute, for every output byte position, which source byte lands there
(``take_along_axis`` per string column + masked select).  The reference's
2GB batch splitting is a host/driver concern and not replicated here —
one call produces one batch.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column, ColumnBatch, Decimal128Column, StringColumn

_WIDTH = {
    T.Kind.BOOLEAN: 1,
    T.Kind.INT8: 1,
    T.Kind.INT16: 2,
    T.Kind.INT32: 4,
    T.Kind.DATE: 4,
    T.Kind.FLOAT32: 4,
    T.Kind.INT64: 8,
    T.Kind.TIMESTAMP: 8,
    T.Kind.FLOAT64: 8,
}


def _col_width(col) -> int:
    if isinstance(col, StringColumn):
        return 8  # (offset, length) pair
    if isinstance(col, Decimal128Column):
        if col.dtype.decimal_storage_bits == 128:
            return 16
        return col.dtype.decimal_storage_bits // 8
    return _WIDTH[col.dtype.kind]


def _align(x: int, a: int) -> int:
    return -(-x // a) * a


def layout_from_widths(widths: Sequence[int]) -> Tuple[List[int], int, int, int]:
    """(per-column offsets, validity offset, fixed end, #validity bytes) —
    the single source of the JCUDF alignment rule."""
    off = 0
    offsets = []
    for w in widths:
        off = _align(off, min(w, 8))
        offsets.append(off)
        off += w
    validity_off = off
    nv = -(-len(widths) // 8)
    return offsets, validity_off, validity_off + nv, nv


def row_layout(cols: Sequence) -> Tuple[List[int], int, int, int]:
    return layout_from_widths([_col_width(c) for c in cols])


def _le_bytes(u, width: int):
    """uint value array [n] -> uint8[n, width] little-endian."""
    lanes = [((u >> jnp.uint64(8 * i)) & jnp.uint64(0xFF)).astype(jnp.uint8)
             for i in range(width)]
    return jnp.stack(lanes, axis=1)


def _fixed_as_u64(col):
    if isinstance(col, Decimal128Column):  # storage_bits < 128: low limb
        return col.limbs[:, 0]
    kind = col.dtype.kind
    d = col.data
    if kind is T.Kind.FLOAT32:
        d = jax.lax.bitcast_convert_type(d, jnp.uint32)
    elif kind is T.Kind.FLOAT64:
        pair = jax.lax.bitcast_convert_type(d, jnp.uint32)
        return pair[..., 0].astype(jnp.uint64) | (
            pair[..., 1].astype(jnp.uint64) << 32
        )
    elif kind is T.Kind.BOOLEAN:
        d = d.astype(jnp.uint8)
    return d.astype(jnp.int64).astype(jnp.uint64) if jnp.issubdtype(
        d.dtype, jnp.signedinteger
    ) else d.astype(jnp.uint64)


def convert_to_rows(batch: ColumnBatch, row_valid=None) -> StringColumn:
    """Table -> JCUDF rows as a binary column (reference
    ``convert_to_rows``, row_conversion.cu:1990)."""
    cols = batch.columns
    n = batch.num_rows
    offsets, validity_off, fixed_end, nv = row_layout(cols)

    string_cols = [c for c in cols if isinstance(c, StringColumn)]
    var_cap = sum(c.max_len for c in string_cols)
    width = _align(fixed_end + var_cap, 8)

    out = jnp.zeros((n, width), jnp.uint8)

    # --- per-row string placement (lengths of nulls count as 0) ----------
    str_lens = []
    for c in string_cols:
        str_lens.append(jnp.where(c.validity, c.lengths, 0))
    starts = []
    cur = jnp.full((n,), fixed_end, jnp.int32)
    for ln in str_lens:
        starts.append(cur)
        cur = cur + ln
    row_len = _align(cur, 8)

    # --- fixed-width slots ----------------------------------------------
    si = 0
    for c, off in zip(cols, offsets):
        if isinstance(c, StringColumn):
            pair = _le_bytes(
                starts[si].astype(jnp.uint64)
                | (str_lens[si].astype(jnp.uint64) << 32),
                8,
            )
            out = out.at[:, off : off + 8].set(pair)
            si += 1
        elif isinstance(c, Decimal128Column) and c.dtype.decimal_storage_bits == 128:
            lo = _le_bytes(c.limbs[:, 0], 8)
            hi = _le_bytes(c.limbs[:, 1], 8)
            out = out.at[:, off : off + 16].set(jnp.concatenate([lo, hi], axis=1))
        else:
            w = _col_width(c)
            out = out.at[:, off : off + w].set(_le_bytes(_fixed_as_u64(c), w))

    # --- validity bytes --------------------------------------------------
    for b in range(nv):
        byte = jnp.zeros((n,), jnp.uint8)
        for c_idx in range(8 * b, min(8 * b + 8, len(cols))):
            bit = cols[c_idx].validity.astype(jnp.uint8) << (c_idx % 8)
            byte = byte | bit
        out = out.at[:, validity_off + b].set(byte)

    # --- string bytes (gather formulation) ------------------------------
    if string_cols:
        j = jnp.arange(width, dtype=jnp.int32)[None, :]  # [1, W]
        acc = jnp.zeros((n, width), jnp.uint8)
        for c, st, ln in zip(string_cols, starts, str_lens):
            src = j - st[:, None]  # position within this column's string
            inside = (src >= 0) & (src < ln[:, None])
            gathered = jnp.take_along_axis(
                c.chars, jnp.clip(src, 0, max(c.max_len - 1, 0)), axis=1
            )
            acc = jnp.where(inside, gathered, acc)
        out = jnp.where(j < fixed_end, out, acc | out)

    return StringColumn(
        out,
        row_len if row_valid is None else jnp.where(row_valid, row_len, 0),
        jnp.ones((n,), jnp.bool_) if row_valid is None else row_valid,
    )


def _read_le(rows, off: int, width: int):
    """uint8[n, W] rows -> uint64[n] little-endian value at static offset."""
    out = jnp.zeros(rows.shape[:1], jnp.uint64)
    for i in range(width):
        out = out | (rows[:, off + i].astype(jnp.uint64) << (8 * i))
    return out


def _u64_to_kind(u, dtype: T.SparkType, width: int):
    kind = dtype.kind
    if kind is T.Kind.BOOLEAN:
        return (u & 1).astype(jnp.bool_)
    if kind is T.Kind.FLOAT32:
        return jax.lax.bitcast_convert_type(u.astype(jnp.uint32), jnp.float32)
    if kind is T.Kind.FLOAT64:
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        pair = jnp.stack([lo, hi], axis=-1)
        # bitcast uint32[n, 2] -> float64[n] (collapses the pair axis)
        return jax.lax.bitcast_convert_type(pair, jnp.float64)
    np_dtype = dtype.jnp_dtype
    # sign-extend: shift the value to the top of 64 bits, arithmetic-shift back
    from .hashing import _u64_to_i64

    signed = _u64_to_i64(u << jnp.uint64(64 - 8 * width)) >> (64 - 8 * width)
    return signed.astype(np_dtype)


def convert_from_rows(
    rows: StringColumn, schema: dict
) -> ColumnBatch:
    """JCUDF rows -> table (reference ``convert_from_rows``,
    row_conversion.cu:2145).  ``schema``: name -> SparkType (+ for strings,
    use ``(SparkType, max_len)`` to bound the padded width)."""
    n = rows.num_rows
    data = rows.chars

    # layout needs column shapes; build placeholder descriptors
    class _Desc:
        def __init__(self, dtype, max_len=0):
            self.dtype = dtype
            self.max_len = max_len

    descs = []
    for name, spec in schema.items():
        if isinstance(spec, tuple):
            dtype, ml = spec
        else:
            dtype, ml = spec, 0
        d = _Desc(dtype, ml)
        descs.append((name, d))

    def width_of(d):
        if d.dtype.kind is T.Kind.STRING:
            return 8
        if d.dtype.kind is T.Kind.DECIMAL:
            return (
                16 if d.dtype.decimal_storage_bits == 128
                else d.dtype.decimal_storage_bits // 8
            )
        return _WIDTH[d.dtype.kind]

    offsets, validity_off, _, _ = layout_from_widths(
        [width_of(d) for _, d in descs]
    )

    out = {}
    for i, ((name, d), coff) in enumerate(zip(descs, offsets)):
        vbyte = data[:, validity_off + i // 8]
        valid = ((vbyte >> (i % 8)) & 1).astype(jnp.bool_)
        if d.dtype.kind is T.Kind.STRING:
            pair = _read_le(data, coff, 8)
            s_off = (pair & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
            s_len = (pair >> jnp.uint64(32)).astype(jnp.int32)
            ml = max(d.max_len, 1)
            idx = s_off[:, None] + jnp.arange(ml, dtype=jnp.int32)[None, :]
            chars = jnp.take_along_axis(
                data, jnp.clip(idx, 0, data.shape[1] - 1), axis=1
            )
            mask = jnp.arange(ml)[None, :] < s_len[:, None]
            chars = jnp.where(mask, chars, jnp.uint8(0))
            out[name] = StringColumn(chars, s_len * valid, valid)
        elif d.dtype.kind is T.Kind.DECIMAL:
            if d.dtype.decimal_storage_bits == 128:
                lo = _read_le(data, coff, 8)
                hi = _read_le(data, coff + 8, 8)
            else:  # sign-extend the 4/8-byte slot into two limbs
                w = width_of(d)
                from .hashing import _u64_to_i64

                raw = _read_le(data, coff, w)
                i64 = _u64_to_i64(raw << jnp.uint64(64 - 8 * w)) >> (64 - 8 * w)
                lo = i64.astype(jnp.uint64)
                hi = jnp.where(i64 < 0, jnp.uint64(2**64 - 1), jnp.uint64(0))
            out[name] = Decimal128Column(
                jnp.stack([lo, hi], axis=1), valid, d.dtype
            )
        else:
            w = width_of(d)
            u = _read_le(data, coff, w)
            out[name] = Column(_u64_to_kind(u, d.dtype, w), valid, d.dtype)
    return ColumnBatch(out)


# ---------------------------------------------------------------------------
# batching + the fixed-width-optimized entry (reference RowConversion.java)
# ---------------------------------------------------------------------------

MAX_BATCH_BYTES = (1 << 31) - 8  # one output batch stays under 2GB
FIXED_OPT_MAX_COLS = 100         # RowConversion.java:32-33
FIXED_OPT_MAX_ROW_BYTES = 1024   # RowConversion.java:115-116


def _slice_col(col, lo: int, hi: int):
    import dataclasses

    if isinstance(col, StringColumn):
        return StringColumn(col.chars[lo:hi], col.lengths[lo:hi],
                            col.validity[lo:hi], col.dtype)
    if isinstance(col, Decimal128Column):
        return Decimal128Column(col.limbs[lo:hi], col.validity[lo:hi],
                                col.dtype)
    return dataclasses.replace(col, data=col.data[lo:hi],
                               validity=col.validity[lo:hi])


def convert_to_rows_fixed_width_optimized(batch: ColumnBatch,
                                          row_valid=None) -> StringColumn:
    """The <100-column, <=1KB-row fast-path entry.

    Mirrors the reference's separate optimized kernel contract
    (``convert_to_rows_fixed_width_optimized``, ``row_conversion.cu:2053``;
    limits from ``RowConversion.java:32-33,115-116``).  Under XLA the
    string-free layout already compiles to pure aligned byte slices, so
    this entry enforces the contract and dispatches to the same program.
    """
    cols = batch.columns
    if len(cols) >= FIXED_OPT_MAX_COLS:
        raise ValueError(
            f"fixed-width-optimized path requires <{FIXED_OPT_MAX_COLS} "
            f"columns, got {len(cols)}")
    for name, col in zip(batch.names, cols):
        if isinstance(col, StringColumn):
            raise ValueError(
                f"fixed-width-optimized path cannot handle string column "
                f"{name!r}")
    _, _, fixed_end, _ = row_layout(cols)
    row_bytes = _align(fixed_end, 8)
    if row_bytes > FIXED_OPT_MAX_ROW_BYTES:
        raise ValueError(
            f"fixed-width-optimized path caps rows at "
            f"{FIXED_OPT_MAX_ROW_BYTES}B, layout needs {row_bytes}B")
    return convert_to_rows(batch, row_valid=row_valid)


def convert_to_rows_batched(batch: ColumnBatch,
                            max_batch_bytes: int = MAX_BATCH_BYTES) -> list:
    """Split the input so each output row image stays under the byte cap.

    The TPU equivalent of the reference's ``build_batches``
    (``row_conversion.cu:1458``): one cudf LIST<INT8> column is capped at
    2GB of child data, so conversions of big tables must emit multiple
    batches.  Splitting happens on the input row axis with a worst-case
    per-row byte bound (fixed layout + each string column's max_len).
    """
    n = batch.num_rows
    cols = batch.columns
    _, _, fixed_end, _ = row_layout(cols)
    # the actual row image width: fixed area + worst-case string bytes,
    # padded to 8 as convert_to_rows does
    worst_row = _align(
        fixed_end + sum(c.max_len for c in cols
                        if isinstance(c, StringColumn)), 8)
    worst_row = max(worst_row, 1)
    rows_per_batch = max(1, int(max_batch_bytes // worst_row))
    out = []
    for lo in range(0, max(n, 1), rows_per_batch):
        hi = min(lo + rows_per_batch, n)
        piece = ColumnBatch({
            name: _slice_col(col, lo, hi)
            for name, col in zip(batch.names, cols)
        })
        out.append(convert_to_rows(piece))
    return out


def convert_from_rows_batched(row_batches: list, schema) -> ColumnBatch:
    """Inverse of :func:`convert_to_rows_batched`: concatenate batches."""
    import dataclasses

    parts = [convert_from_rows(rb, schema) for rb in row_batches]
    if len(parts) == 1:
        return parts[0]
    out = {}
    for name in parts[0].names:
        cols = [p[name] for p in parts]
        c0 = cols[0]
        if isinstance(c0, StringColumn):
            width = max(c.max_len for c in cols)
            chars = jnp.concatenate([
                jnp.pad(c.chars, ((0, 0), (0, width - c.max_len)))
                for c in cols
            ])
            out[name] = StringColumn(
                chars, jnp.concatenate([c.lengths for c in cols]),
                jnp.concatenate([c.validity for c in cols]), c0.dtype)
        elif isinstance(c0, Decimal128Column):
            out[name] = Decimal128Column(
                jnp.concatenate([c.limbs for c in cols]),
                jnp.concatenate([c.validity for c in cols]), c0.dtype)
        else:
            out[name] = dataclasses.replace(
                c0, data=jnp.concatenate([c.data for c in cols]),
                validity=jnp.concatenate([c.validity for c in cols]))
    return ColumnBatch(out)
