"""Data-clustering indexes: DeltaLake ``interleave_bits`` and Hilbert index.

Semantics from the reference ``zorder.cu``:

* ``interleave_bits`` (zorder.cu:137): C same-type fixed-width columns ->
  per-row binary of ``C * sizeof(T)`` bytes.  Output bit k (MSB-first across
  the whole row) comes from column ``k % C`` (column 0 most significant),
  bit ``k // C`` of the value read big-endian.  Null values read as 0.
* ``hilbert_index`` (zorder.cu:224): C int32 columns, ``num_bits_per_entry``
  bits each (``bits*C <= 64``) -> int64 Hilbert distance, Skilling's
  transpose algorithm (same lineage as the davidmoten/hilbert-curve library
  the reference tests compare against).  Null values read as 0.

Both vectorize naturally: every loop bound (bit counts, dimensions) is
static, so the "loops" unroll into pure elementwise uint32 ops on [n] lanes.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column, StringColumn


def _value_bits(col: Column):
    """(bits uint8[n, w*8] MSB-first, byte width) for a fixed-width column."""
    kind = col.dtype.kind
    d = col.data
    if kind is T.Kind.BOOLEAN:
        u = d.astype(jnp.uint8)
    elif kind in (T.Kind.INT8,):
        u = d.astype(jnp.uint8)
    elif kind is T.Kind.INT16:
        u = d.astype(jnp.uint16)
    elif kind in (T.Kind.INT32, T.Kind.DATE):
        u = d.astype(jnp.uint32)
    elif kind in (T.Kind.INT64, T.Kind.TIMESTAMP):
        u = d.astype(jnp.uint64)
    elif kind is T.Kind.FLOAT32:
        u = jax.lax.bitcast_convert_type(d, jnp.uint32)
    elif kind is T.Kind.FLOAT64:
        pair = jax.lax.bitcast_convert_type(d, jnp.uint32)
        lo = pair[..., 0].astype(jnp.uint64)
        hi = pair[..., 1].astype(jnp.uint64)
        u = lo | (hi << 32)
    else:
        raise NotImplementedError(f"interleave_bits over {col.dtype!r}")
    u = jnp.where(col.validity, u, jnp.zeros((), u.dtype))
    nbits = u.dtype.itemsize * 8
    shifts = jnp.arange(nbits - 1, -1, -1, dtype=u.dtype)
    bits = ((u[:, None] >> shifts[None, :]) & jnp.ones((), u.dtype)).astype(jnp.uint8)
    return bits, u.dtype.itemsize


def interleave_bits(columns: Sequence[Column]) -> StringColumn:
    """Byte-interleaved z-order key as a binary column (reference zorder.cu:137)."""
    if not columns:
        raise ValueError("interleave_bits requires at least one column")
    kinds = {c.dtype.kind for c in columns}
    if len(kinds) > 1:
        raise ValueError("all columns must share one type")
    per_col = [_value_bits(c) for c in columns]
    width = per_col[0][1]
    C = len(columns)
    n = columns[0].num_rows
    # [n, C, nbits] -> [n, nbits, C] -> flat bit stream, column 0 first
    stacked = jnp.stack([b for b, _ in per_col], axis=1)
    stream = jnp.transpose(stacked, (0, 2, 1)).reshape(n, width * 8 * C)
    weights = jnp.array([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    by = stream.reshape(n, width * C, 8) * weights[None, None, :]
    out_bytes = by.sum(axis=2, dtype=jnp.uint8)
    lengths = jnp.full((n,), width * C, jnp.int32)
    return StringColumn(out_bytes, lengths, jnp.ones((n,), jnp.bool_))


def hilbert_index(num_bits_per_entry: int, columns: Sequence[Column]) -> Column:
    """Hilbert distance of int32 points (reference zorder.cu:224).

    Skilling's algorithm on C uint32 lanes: inverse-undo from the top bit
    down, gray encode, then bit-interleave the transposed index.
    """
    if not (0 < num_bits_per_entry <= 32):
        raise ValueError("num_bits_per_entry must be in (0, 32]")
    C = len(columns)
    if C * num_bits_per_entry > 64:
        raise ValueError("only up to 64 output bits supported")
    if C == 0:
        raise ValueError("at least one column is required")
    for c in columns:
        if c.dtype.kind is not T.Kind.INT32:
            raise ValueError("all columns must be INT32")
    n = columns[0].num_rows
    mask_entry = jnp.uint32((1 << num_bits_per_entry) - 1)
    x = [
        jnp.where(c.validity, c.data.astype(jnp.uint32), jnp.uint32(0)) & mask_entry
        for c in columns
    ]

    M = 1 << (num_bits_per_entry - 1)
    q = M
    while q > 1:  # inverse undo (hilbert_transposed_index, zorder.cu:94)
        p = jnp.uint32(q - 1)
        for i in range(C):
            hi = (x[i] & jnp.uint32(q)) != 0
            t = (x[0] ^ x[i]) & p
            inv_x0 = x[0] ^ p
            x0_new = jnp.where(hi, inv_x0, x[0] ^ t)
            xi_new = jnp.where(hi, x[i], x[i] ^ t)
            # i == 0: the else-branch t is 0, both branches only touch x[0]
            x[0] = x0_new
            if i != 0:
                x[i] = xi_new
        q >>= 1

    for i in range(1, C):  # gray encode
        x[i] = x[i] ^ x[i - 1]
    t = jnp.zeros((n,), jnp.uint32)
    q = M
    while q > 1:
        t = jnp.where((x[C - 1] & jnp.uint32(q)) != 0, t ^ jnp.uint32(q - 1), t)
        q >>= 1
    x = [xi ^ t for xi in x]

    # to_hilbert_index (zorder.cu:75): interleave MSB-first, column 0 first
    b = jnp.zeros((n,), jnp.uint64)
    b_index = num_bits_per_entry * C - 1
    for i in range(num_bits_per_entry):
        mask = jnp.uint32(1 << (num_bits_per_entry - 1 - i))
        for j in range(C):
            bit = ((x[j] & mask) != 0).astype(jnp.uint64)
            b = b | (bit << jnp.uint64(b_index))
            b_index -= 1
    return Column(b.astype(jnp.int64), jnp.ones((n,), jnp.bool_), T.INT64)
