"""Bit-parallel fast path for ``get_json_object`` (clean-document subset).

The general engine (:mod:`get_json_object`) is a char-level ``lax.scan``:
``max_len`` *sequential* steps, each a vector over the batch.  That shape
is latency-bound on TPU — the carry round-trips HBM every step.  This
module re-expresses the common case as ~60 *data-parallel* passes over
the ``[n, L]`` char matrix (the simdjson stage-1 idea, mapped to XLA):
quote-parity prefix sums for the in-string mask, masked cumulative sums
for nesting depth, forward-fills for grammar anchors, and a static
unrolled walk over the (static) JSONPath — no sequential dependence on
``L`` anywhere.

Reference semantics: ``/root/reference/src/main/cpp/src/json_parser.cuh``
(tokenizer) and ``get_json_object.cu:360-788`` (path evaluator), as
modeled by ``tests/json_oracle.py``.

**Accept-list contract.**  The fast path only keeps rows it can prove it
handles exactly; everything else raises the per-row ``fallback`` flag and
the caller routes the batch through the general scan machine
(``lax.cond`` — the serial engine still defines the semantics).  A row
falls back when any of these hold:

* a backslash anywhere in the document (escapes, and the reference's
  ``\\uXXXX`` field-name-never-matches quirk, stay on the scan machine);
* a single-quote character anywhere (the two-quote-type automaton is not
  a parity sum);
* nesting depth > 16 (the owner-bracket forward-fill is per-depth);
* any local grammar check fails (the row may be malformed: the scan
  machine decides NULL properly — the fast path never declares NULL for
  a doc it cannot fully validate, except provably-structural cases);
* the matched value needs non-trivial rewriting: a float-containing or
  ``-0``-containing container copy, or control chars inside a container
  copy.  (Scalar float targets are handled in-engine via the scan
  machine's own ``_format_floats`` — same parser, same exponent
  canonicalization, any token length.)

Rows the fast path *keeps* are fully validated: every accepted document
parses under the reference grammar (numbers, literals, separator
placement by container kind), so emitting bytes for them is sound.

Wildcard paths never enter the fast path (static routing in
``get_json_object``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from . import float_to_string

MAX_FF_DEPTH = 16   # owner forward-fill depth budget; deeper rows fall back

_U8 = jnp.uint8
_I32 = jnp.int32


def _c(ch: str):
    return _U8(ord(ch))


def _ffill_max(x, axis=1):
    """Running maximum (forward fill of the latest index)."""
    return jax.lax.cummax(x, axis=axis)


def _first_true(mask, L):
    """Index of first True per row, L if none.  mask: bool [n, L]."""
    pos = jnp.arange(L, dtype=_I32)
    return jnp.min(jnp.where(mask, pos[None, :], _I32(L)), axis=1)


def _gather_cols(mat, idx):
    """mat [n, L], idx [n] -> mat[i, idx[i]] with idx clipped."""
    n, L = mat.shape
    safe = jnp.clip(idx, 0, L - 1)
    return jnp.take_along_axis(mat, safe[:, None], axis=1)[:, 0]


# anchor kinds (token-level grammar elements)
A_NONE = 0
A_OBRACE = 1    # {
A_CBRACE = 2    # }
A_OBRK = 3      # [
A_CBRK = 4      # ]
A_COMMA = 5
A_COLON = 6
A_OPENQ = 7     # opening quote of a string
A_CLOSEQ = 8    # closing quote of a value string
A_FCLOSEQ = 9   # closing quote of a field-name string
A_VEND = 10     # last char of a number/literal run
A_START = 11    # virtual "before document" anchor


@partial(jax.jit, static_argnames=("path_tuple", "max_out"))
def fast_path(chars, lengths, validity, path_tuple, max_out):
    """Evaluate a wildcard-free JSONPath over clean documents.

    Returns ``(out_chars u8[n, max_out], out_lens i32[n], ok bool[n],
    fallback bool[n])``.  ``ok`` is meaningful only where ``fallback`` is
    False; callers must route fallback rows through the scan machine.
    """
    n, L = chars.shape
    pos = jnp.arange(L, dtype=_I32)[None, :]
    lens = lengths.astype(_I32)
    inb = pos < lens[:, None]
    ch = jnp.where(inb, chars, _U8(0))

    fb = jnp.zeros((n,), jnp.bool_)      # fallback
    bad = jnp.zeros((n,), jnp.bool_)     # provably NULL (structural miss)

    # ---- trigger 1: characters the fast path does not model ----------
    fb |= jnp.any(inb & ((ch == _c("\\")) | (ch == _c("'"))), axis=1)

    # ---- in-string mask (double quotes only, no escapes) -------------
    isq = ch == _c('"')
    qpre = jnp.cumsum(isq.astype(_I32), axis=1)          # inclusive
    open_q = isq & (qpre % 2 == 1)
    close_q = isq & (qpre % 2 == 0)
    content = (~isq) & ((qpre % 2) == 1) & inb           # strictly inside
    outside = inb & ~content & ~isq

    isws = (ch == _c(" ")) | (ch == _c("\t")) | (ch == _c("\n")) | (
        ch == _c("\r"))
    ws = outside & isws
    punct_chars = ((ch == _c("{")) | (ch == _c("}")) | (ch == _c("[")) |
                   (ch == _c("]")) | (ch == _c(",")) | (ch == _c(":")))
    punct = outside & punct_chars
    valch = outside & ~ws & ~punct_chars                 # number/literal

    opens = outside & ((ch == _c("{")) | (ch == _c("[")))
    closes = outside & ((ch == _c("}")) | (ch == _c("]")))
    delta = opens.astype(_I32) - closes.astype(_I32)
    depth_after = jnp.cumsum(delta, axis=1)
    depth_before = depth_after - delta

    # ---- root span ---------------------------------------------------
    nonws = inb & ~isws
    root_start = _first_true(nonws, L)
    empty_doc = root_start >= lens                        # NULL, not fb
    c0 = _gather_cols(ch, root_start)
    root_is_container = (c0 == _c("{")) | (c0 == _c("["))
    # matching close of the root container: first close AFTER root_start
    # whose depth_after is 0
    close0 = closes & (depth_after == 0) & (pos > root_start[:, None])
    root_close = _first_true(close0, L)
    # scalar roots end at their token end (string close / run end)
    run_end = valch & ~jnp.concatenate(
        [valch[:, 1:], jnp.zeros((n, 1), jnp.bool_)], axis=1)
    str_close_after = lambda s: _first_true(  # noqa: E731
        close_q & (pos > s[:, None]), L)
    vend_at = lambda s: _first_true(  # noqa: E731
        run_end & (pos >= s[:, None]), L)
    root_end = jnp.where(
        root_is_container, root_close,
        jnp.where(c0 == _c('"'), str_close_after(root_start),
                  vend_at(root_start)))
    # a container root with no matching close, or a scalar root with no
    # token end, may still be junk the scan machine NULLs — fall back
    fb |= (~empty_doc) & (root_end >= L)
    span = (pos >= root_start[:, None]) & (pos <= root_end[:, None]) & inb

    # parity must close inside the root span (an unclosed string whose
    # quote count balances later in trailing junk would corrupt masks)
    qpre_end = _gather_cols(qpre, root_end)
    fb |= (~empty_doc) & (qpre_end % 2 != 0)
    # trailing junk is ignored by the reference; nothing after root_end
    # participates in any mask below
    depth_ok = depth_before >= 0
    fb |= jnp.any(span & ~depth_ok, axis=1)
    # a document of L chars cannot nest deeper than L // 2 (every level
    # costs an open AND a close bracket), so the per-depth forward-fill
    # budget shrinks with narrow columns (bucketed small widths) for free
    ff_depth = max(1, min(MAX_FF_DEPTH, L // 2))
    maxd = jnp.max(jnp.where(span, depth_after, 0), axis=1)
    fb |= maxd > ff_depth

    # ---- owner container type per position ---------------------------
    # owner_char_at_depth[d][j] = char of the latest open bracket with
    # depth_after == d at or before j (the bracket owning level d)
    neg1 = jnp.full((n, L), -1, _I32)
    own_idx = []
    for d in range(1, ff_depth + 1):
        cand = jnp.where(opens & span & (depth_after == d), pos, neg1)
        own_idx.append(_ffill_max(cand))
    # container type for a position with depth_before == d: the owner
    # bracket char at level d (0 -> ROOT sentinel)
    def owner_char(db, at):
        """db: [n, L] depth_before; at: [n, L] positions; -> u8 char,
        0 for ROOT."""
        out = jnp.zeros((n, L), _U8)
        for d in range(1, ff_depth + 1):
            oc = jnp.where(own_idx[d - 1] >= 0,
                           jnp.take_along_axis(
                               ch, jnp.clip(own_idx[d - 1], 0, L - 1),
                               axis=1),
                           _U8(0))
            out = jnp.where(db == d, oc, out)
        return out

    cont = owner_char(depth_before, pos)   # container char per position

    # ---- anchors and prev-anchor grammar -----------------------------
    run_start = valch & ~jnp.concatenate(
        [jnp.zeros((n, 1), jnp.bool_), valch[:, :-1]], axis=1)
    kind = jnp.zeros((n, L), _I32)
    kind = jnp.where(punct & (ch == _c("{")), A_OBRACE, kind)
    kind = jnp.where(punct & (ch == _c("}")), A_CBRACE, kind)
    kind = jnp.where(punct & (ch == _c("[")), A_OBRK, kind)
    kind = jnp.where(punct & (ch == _c("]")), A_CBRK, kind)
    kind = jnp.where(punct & (ch == _c(",")), A_COMMA, kind)
    kind = jnp.where(punct & (ch == _c(":")), A_COLON, kind)
    kind = jnp.where(open_q, A_OPENQ, kind)
    kind = jnp.where(close_q, A_CLOSEQ, kind)  # field/value split below
    kind = jnp.where(run_end, A_VEND, kind)
    anchor = (kind != 0) & span

    # prev anchor kind/char before each position (START if none)
    aidx = jnp.where(anchor, pos, neg1)
    prev_idx_incl = _ffill_max(aidx)                  # latest anchor <= j
    prev_idx = jnp.concatenate(
        [jnp.full((n, 1), -1, _I32), prev_idx_incl[:, :-1]], axis=1)
    prev_kind = jnp.where(
        prev_idx >= 0,
        jnp.take_along_axis(kind, jnp.clip(prev_idx, 0, L - 1), axis=1),
        _I32(A_START))

    # field-name strings: an opening quote in an object context whose
    # previous anchor is '{' or ',' (value strings follow ':')
    is_fq_open = open_q & span & (cont == _c("{")) & (
        (prev_kind == A_OBRACE) | (prev_kind == A_COMMA))
    # propagate the field flag from each open quote to its close quote:
    # encode (position, flag) as pos*2+flag so the running max carries the
    # LATEST open quote's flag (a bare 0/1 cummax would let an earlier
    # field's 1 shadow a later value string's 0)
    fq_ff = _ffill_max(jnp.where(
        open_q, pos * 2 + is_fq_open.astype(_I32), -1))
    close_is_field = close_q & (fq_ff >= 0) & (fq_ff % 2 == 1)
    kind = jnp.where(close_is_field, A_FCLOSEQ, kind)
    prev_kind = jnp.where(
        prev_idx >= 0,
        jnp.take_along_axis(kind, jnp.clip(prev_idx, 0, L - 1), axis=1),
        _I32(A_START))

    is_obj = cont == _c("{")
    is_arr = cont == _c("[")
    is_root_ctx = cont == _U8(0)

    pk = prev_kind
    value_end_kinds = ((pk == A_CLOSEQ) | (pk == A_CBRACE) | (pk == A_CBRK)
                       | (pk == A_VEND))
    value_start_ok = (
        (is_obj & (pk == A_COLON))
        | (is_arr & ((pk == A_OBRK) | (pk == A_COMMA)))
        | (is_root_ctx & (pk == A_START)))

    rule_ok = jnp.ones((n, L), jnp.bool_)

    def apply(mask, ok):
        """AND a rule into rule_ok at masked positions (a position may be
        subject to several rules — e.g. a digit is checked by the
        value-start rule, the leading-zero rule, and the digit budget)."""
        nonlocal rule_ok
        rule_ok = jnp.where(mask & span, rule_ok & ok, rule_ok)

    apply(kind == A_OBRACE, value_start_ok)
    apply(kind == A_OBRK, value_start_ok)
    apply(run_start, value_start_ok)
    apply(open_q & ~is_fq_open,
          value_start_ok | (is_obj & (pk == A_COLON)))
    apply(kind == A_CBRACE,
          is_obj & ((pk == A_OBRACE) | value_end_kinds))
    apply(kind == A_CBRK,
          is_arr & ((pk == A_OBRK) | value_end_kinds))
    apply(kind == A_COMMA, (is_obj | is_arr) & value_end_kinds)
    apply(kind == A_COLON, is_obj & (pk == A_FCLOSEQ))
    # a field close-quote must be followed by ':' — equivalently no other
    # anchor may have a field-close as its previous anchor
    apply((kind != 0) & (kind != A_COLON) & (pk == A_FCLOSEQ),
          jnp.zeros((n, L), jnp.bool_))

    # ---- number / literal token validation ---------------------------
    isdig = (ch >= _c("0")) & (ch <= _c("9"))
    num_allowed = (isdig | (ch == _c("-")) | (ch == _c("+"))
                   | (ch == _c(".")) | (ch == _c("e")) | (ch == _c("E")))
    lit_allowed = ((ch == _c("t")) | (ch == _c("r")) | (ch == _c("u"))
                   | (ch == _c("e")) | (ch == _c("f")) | (ch == _c("a"))
                   | (ch == _c("l")) | (ch == _c("s")) | (ch == _c("n")))

    # first char of each run, forward-filled across the run
    rs_idx = _ffill_max(jnp.where(run_start, pos, neg1))
    rs_char = jnp.where(rs_idx >= 0,
                        jnp.take_along_axis(ch, jnp.clip(rs_idx, 0, L - 1),
                                            axis=1), _U8(0))
    is_lit_run = ((rs_char == _c("t")) | (rs_char == _c("f"))
                  | (rs_char == _c("n")))
    is_num_run = valch & ~is_lit_run
    lit_run = valch & is_lit_run

    apply(is_num_run, num_allowed)
    apply(lit_run, lit_allowed)

    # literal runs must be exactly true/false/null
    def win_eq(s_idx, lit):
        m = jnp.ones((n,), jnp.bool_)
        for i, b in enumerate(lit):
            m &= _gather_cols(ch, s_idx + i) == _U8(b)
        return m

    lit_start = run_start & is_lit_run & span
    # validate every literal run via its start (vector over positions)
    lit_len_map = {b"true": 4, b"false": 5, b"null": 4}
    # run length at run START: find this run's end = first run_end >= start
    # (per-position: the run end forward-filled from the right); compute
    # via reversed ffill
    rev = lambda x: x[:, ::-1]  # noqa: E731
    next_end_rev = _ffill_max(rev(jnp.where(run_end, (L - 1) - pos, neg1)))
    next_end = (L - 1) - rev(next_end_rev)  # first run_end >= j (L-1-(-1) if none)
    run_len = jnp.where(valch, next_end - rs_idx + 1, 0)
    for lit, ll in lit_len_map.items():
        first = _U8(lit[0])
        sel = lit_start & (ch == first)
        okm = jnp.zeros((n, L), jnp.bool_)
        for i, b in enumerate(lit):
            at = jnp.clip(pos + i, 0, L - 1)
            okm_i = jnp.take_along_axis(ch, at, axis=1) == _U8(b)
            okm = okm_i if i == 0 else (okm & okm_i)
        apply(sel, okm & (run_len == ll))
    # literal starts with t/f/n but matching none of the three first chars
    # is impossible (is_lit_run keyed on first char), but 't' runs not
    # spelling "true" are caught by the window check above

    # number grammar: local char rules + per-run aggregates
    prev_ch = jnp.concatenate([jnp.zeros((n, 1), _U8), ch[:, :-1]], axis=1)
    next_ch = jnp.concatenate([ch[:, 1:], jnp.zeros((n, 1), _U8)], axis=1)
    prev_dig = (prev_ch >= _c("0")) & (prev_ch <= _c("9"))
    next_dig = (next_ch >= _c("0")) & (next_ch <= _c("9"))
    is_e = is_num_run & ((ch == _c("e")) | (ch == _c("E")))
    nn_ch = jnp.concatenate([ch[:, 2:], jnp.zeros((n, 2), _U8)], axis=1)
    nn_dig = (nn_ch >= _c("0")) & (nn_ch <= _c("9"))
    apply(is_num_run & (ch == _c("-")), run_start | (
        (prev_ch == _c("e")) | (prev_ch == _c("E"))))
    apply(is_num_run & (ch == _c("+")),
          (prev_ch == _c("e")) | (prev_ch == _c("E")))
    apply(is_num_run & (ch == _c(".")), prev_dig & next_dig)
    apply(is_e, prev_dig & (next_dig | (
        ((next_ch == _c("+")) | (next_ch == _c("-"))) & nn_dig)))
    # leading zero: '0' at int-part start directly followed by a digit
    int_start = run_start | (prev_ch == _c("-")) & (rs_idx == pos - 1)
    apply(is_num_run & (ch == _c("0")) & int_start, ~next_dig)
    # at most one e / one dot, dot before e — per-run aggregates via
    # cumsum differences anchored at the run start
    cum_e = jnp.cumsum(is_e.astype(_I32), axis=1)
    cum_d = jnp.cumsum((is_num_run & (ch == _c("."))).astype(_I32), axis=1)
    base_e = jnp.where(rs_idx >= 0,
                       jnp.take_along_axis(cum_e, jnp.clip(rs_idx, 0, L - 1),
                                           axis=1), 0)
    base_d = jnp.where(rs_idx >= 0,
                       jnp.take_along_axis(cum_d, jnp.clip(rs_idx, 0, L - 1),
                                           axis=1), 0)
    e_at_start = jnp.where(
        rs_idx >= 0, jnp.take_along_axis(
            is_e.astype(_I32), jnp.clip(rs_idx, 0, L - 1), axis=1), 0)
    run_e = cum_e - base_e + e_at_start
    run_d = cum_d - base_d  # '.' can never be at run start (rule above)
    apply(is_e, run_e <= 1)
    apply(is_num_run & (ch == _c(".")), (run_d <= 1) & (run_e == 0))
    # digit budget (reference: <=1000 digits).  run_len <= 1000 implies
    # digits <= 1000 (sound accept); valid numbers of 1001-1007 chars with
    # exactly <=1000 digits false-reject into the harmless fallback
    apply(run_start & is_num_run, run_len <= 1000)

    # any rule failure -> fall back (the scan machine decides NULL)
    fb |= jnp.any(span & ~rule_ok, axis=1)

    # ---- path navigation (static unrolled) ---------------------------
    cs = root_start
    alive = ~empty_doc
    for (ptype, parg) in path_tuple:
        ccur = _gather_cols(ch, cs)
        cd = _gather_cols(depth_after, cs)    # depth of contents
        # matching close of this container
        close_m = closes & (pos > cs[:, None]) & (
            depth_after == (cd - 1)[:, None]) & span
        cend = _first_true(close_m, L)
        if ptype == "named":
            name = parg
            k = len(name)
            bad |= alive & (ccur != _c("{"))
            alive &= ccur == _c("{")
            # candidate field quotes at this level inside (cs, cend)
            cand = (kind == A_OPENQ) & is_fq_open & (
                depth_before == cd[:, None]) & (pos > cs[:, None]) & (
                pos < cend[:, None])
            m = cand
            for i, b in enumerate(name):
                at = jnp.clip(pos + 1 + i, 0, L - 1)
                m &= jnp.take_along_axis(ch, at, axis=1) == _U8(b)
            at = jnp.clip(pos + 1 + k, 0, L - 1)
            m &= jnp.take_along_axis(ch, at, axis=1) == _c('"')
            q0 = _first_true(m, L)
            found = q0 < L
            bad |= alive & ~found
            alive &= found
            # value start: first non-ws after the colon after q0+k+1
            colon = _first_true(
                (~isws) & inb & (pos > (q0 + k + 1)[:, None]), L)
            vstart = _first_true((~isws) & inb & (pos > colon[:, None]), L)
            # matched null at a named step -> NULL overall
            vc = _gather_cols(ch, vstart)
            is_null = (vc == _c("n")) & win_eq(vstart, b"null")
            bad |= alive & is_null
            alive &= ~is_null
            cs = jnp.where(alive, vstart, cs)
        else:  # ("index", i)
            idx = int(parg)
            bad |= alive & (ccur != _c("["))
            alive &= ccur == _c("[")
            first_elem = _first_true(
                (~isws) & inb & (pos > cs[:, None]), L)
            empty_arr = _gather_cols(ch, first_elem) == _c("]")
            if idx == 0:
                bad |= alive & empty_arr
                alive &= ~empty_arr
                cs = jnp.where(alive, first_elem, cs)
            else:
                commas = (kind == A_COMMA) & (
                    depth_before == cd[:, None]) & (pos > cs[:, None]) & (
                    pos < cend[:, None])
                ccount = jnp.cumsum(commas.astype(_I32), axis=1)
                target_comma = _first_true(commas & (ccount == idx), L)
                have = target_comma < L
                bad |= alive & ~have
                alive &= have
                estart = _first_true(
                    (~isws) & inb & (pos > target_comma[:, None]), L)
                cs = jnp.where(alive, estart, cs)

    # ---- target classification & span --------------------------------
    tc = _gather_cols(ch, cs)
    t_is_str = tc == _c('"')
    t_is_cont = (tc == _c("{")) | (tc == _c("["))
    t_is_lit = (tc == _c("t")) | (tc == _c("f")) | (tc == _c("n"))
    t_is_num = alive & ~t_is_str & ~t_is_cont & ~t_is_lit

    td = _gather_cols(depth_after, cs)
    t_close = _first_true(closes & (pos > cs[:, None]) & (
        depth_after == (td - 1)[:, None]) & span, L)
    t_strclose = str_close_after(cs)
    t_vend = vend_at(cs)
    t_end = jnp.where(t_is_cont, t_close,
                      jnp.where(t_is_str, t_strclose, t_vend))

    in_tspan = (pos >= cs[:, None]) & (pos <= t_end[:, None])

    # container-copy fallback triggers: float numbers, "-0" ints,
    # control chars inside strings (all need rewriting)
    t_has_float = jnp.any(
        in_tspan & is_num_run & ((ch == _c(".")) | is_e), axis=1)
    neg0 = run_start & (ch == _c("-")) & (next_ch == _c("0")) & (run_len == 2)
    t_has_neg0 = jnp.any(in_tspan & neg0, axis=1)
    t_has_ctrl = jnp.any(in_tspan & content & (ch < _U8(0x20)), axis=1)
    fb |= alive & t_is_cont & (t_has_float | t_has_neg0 | t_has_ctrl)

    # scalar float target (no length bound: the shared formatter below
    # reads the same <=326-char window the scan machine does)
    t_num_end = t_vend
    t_tok_len = t_num_end - cs + 1
    t_is_float = t_is_num & jnp.any(
        in_tspan & is_num_run & ((ch == _c(".")) | is_e), axis=1)

    # ---- materialization ---------------------------------------------
    W = int(max_out)
    outp = jnp.arange(W, dtype=_I32)[None, :]

    # verbatim channel (string content / int / literal / container-compact)
    # string: span (cs+1, t_strclose); int/literal: [cs, t_vend]
    v_start = jnp.where(t_is_str, cs + 1, cs)
    v_len = jnp.where(t_is_str, t_strclose - cs - 1,
                      jnp.where(t_is_cont, jnp.zeros_like(cs),
                                t_vend - cs + 1))
    # "-0" -> "0"
    is_neg0_t = t_is_num & (_gather_cols(ch, cs) == _c("-")) & (
        _gather_cols(ch, cs + 1) == _c("0")) & (t_tok_len == 2)
    v_start = jnp.where(is_neg0_t, cs + 1, v_start)
    v_len = jnp.where(is_neg0_t, 1, v_len)
    src = jnp.clip(v_start[:, None] + outp, 0, L - 1)
    verb = jnp.where(outp < v_len[:, None],
                     jnp.take_along_axis(ch, src, axis=1), _U8(0))

    # container-compact channel: keep = non-ws within span (strings keep
    # everything incl. quotes); compacted by left_compact_rows (counting
    # scatter on CPU, stable argsort on accelerators).  The compaction
    # only runs when some live row actually has a container target
    # (lax.cond) — the common scalar extraction skips it entirely.
    any_cont = jnp.any(alive & t_is_cont)

    def compact_containers(_):
        # platform-aware row compaction (r5): counting scatter on CPU,
        # stable argsort on accelerators
        from .strings import left_compact_rows

        keep = in_tspan & (content | isq | (outside & ~ws))
        return left_compact_rows(ch, keep)

    packed, c_len = jax.lax.cond(
        any_cont, compact_containers,
        lambda _: (jnp.zeros((n, L), _U8), jnp.zeros((n,), _I32)), None)
    if W >= L:
        cont_out = jnp.pad(packed, ((0, 0), (0, W - L)))
    else:
        cont_out = packed[:, :W]
    cont_out = jnp.where(outp < c_len[:, None], cont_out, _U8(0))

    # float channel: gather the token into a static window, parse+format
    # (Ryu) — also gated on any live float target existing
    any_float = jnp.any(alive & t_is_float)

    def format_floats(_):
        # the SAME parser+formatter as the scan machine (exponent
        # canonicalization then string_to_float + Ryu): r5 caught a
        # >4-exponent-digit golden ('...e0005603...' -> "Infinity")
        # diverging when this path parsed through a private window —
        # the serial engine stays the float-semantics source
        from .get_json_object import _format_floats

        fbytes3, flens2 = _format_floats(
            ch, cs[:, None],
            jnp.where(t_is_float, t_tok_len, 0)[:, None], 1)
        return fbytes3[:, 0], flens2[:, 0].astype(_I32)

    fbytes, flens = jax.lax.cond(
        any_float, format_floats,
        lambda _: (jnp.zeros((n, float_to_string.DOUBLE_JSON_W), _U8),
                   jnp.zeros((n,), _I32)),
        None)
    FW = fbytes.shape[1]
    if W >= FW:
        float_out = jnp.pad(fbytes, ((0, 0), (0, W - FW)))
    else:
        float_out = fbytes[:, :W]
    float_out = jnp.where(outp < flens[:, None], float_out, _U8(0))

    use_float = t_is_float
    use_cont = t_is_cont
    out_chars = jnp.where(use_float[:, None], float_out,
                          jnp.where(use_cont[:, None], cont_out, verb))
    out_lens = jnp.where(use_float, flens,
                         jnp.where(use_cont, c_len, v_len))

    ok = alive & ~bad & validity
    ok &= out_lens <= W   # overlong -> null (matches the scan machine)
    out_lens = jnp.where(ok, out_lens, 0)
    out_chars = jnp.where(ok[:, None], out_chars, _U8(0))
    fb &= validity       # null rows never need the scan machine
    return out_chars, out_lens, ok, fb
