"""Spark ``format_number``-style float formatting (#,###,###.##).

Reference: ``format_float.cu`` + ``ftos_converter.cuh:1247-1476``.  The
value's *shortest* decimal digits (Ryu core, shared with
:mod:`float_to_string`) are rounded half-even to ``digits`` decimal places
and grouped with thousands separators.  Specials: NaN -> U+FFFD
(replacement char), ±Inf -> [-]U+221E, ±0 -> [-]0.000…

All three layout branches of the reference's ``to_formatted_chars`` are
computed for every row and selected by mask; the integer part is carried as
a digit *vector* (values up to 1e308 overflow any integer lane type) and
the comma grouping is a pure position-arithmetic gather.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column, StringColumn
from .float_to_string import _d2d, _f2d, _digit_count, _U64

_MAX_INT_DIGITS = 310  # 1.8e308


def _pow10_u64(e):
    """10**e for e int32[n] in [0, 19] as uint64 (gather from a table)."""
    table = jnp.asarray(np.array([10**k for k in range(20)], dtype=np.uint64))
    return jnp.take(table, jnp.clip(e, 0, 19))


def _round_half_even(mant, olength, keep):
    """Round the olength-digit integer to its leading ``keep`` digits
    (reference round_half_even, ftos_converter.cuh:1247)."""
    drop = olength - keep
    no_round = drop <= 0
    div = _pow10_u64(jnp.maximum(drop, 0))
    mod = mant % div
    num = mant // div
    half = div // _U64(2)
    inc = (mod > half) | ((mod == half) & (num % _U64(2) == 1) & (mod != 0))
    return jnp.where(no_round, mant, num + inc.astype(jnp.uint64))


def format_float(col: Column, digits: int) -> StringColumn:
    """Format with ``digits`` decimal places (reference format_float.cu:112)."""
    if digits < 0:
        raise ValueError("digits must be >= 0")
    kind = col.dtype.kind
    if kind is T.Kind.FLOAT64:
        pair = jax.lax.bitcast_convert_type(col.data, jnp.uint32)
        bits = pair[..., 0].astype(jnp.uint64) | (
            pair[..., 1].astype(jnp.uint64) << 32
        )
        negative = (bits >> _U64(63)) != 0
        exp_f = (bits >> _U64(52)) & _U64(0x7FF)
        mant_f = bits & _U64((1 << 52) - 1)
        is_nan = (exp_f == 0x7FF) & (mant_f != 0)
        is_inf = (exp_f == 0x7FF) & (mant_f == 0)
        is_zero = (exp_f == 0) & (mant_f == 0)
        mant, e10 = _d2d(bits & _U64((1 << 63) - 1))
    elif kind is T.Kind.FLOAT32:
        bits = jax.lax.bitcast_convert_type(col.data, jnp.uint32)
        negative = (bits >> 31) != 0
        exp_f = (bits >> 23) & jnp.uint32(0xFF)
        mant_f = bits & jnp.uint32((1 << 23) - 1)
        is_nan = (exp_f == 0xFF) & (mant_f != 0)
        is_inf = (exp_f == 0xFF) & (mant_f == 0)
        is_zero = (exp_f == 0) & (mant_f == 0)
        mant, e10 = _f2d(bits & jnp.uint32((1 << 31) - 1))
    else:
        raise TypeError(f"format_float expects FLOAT32/64, got {col.dtype!r}")

    n = col.num_rows
    olength = _digit_count(mant)
    exp = e10 + olength - 1

    # digit vector of the mantissa, MSB-first [n, 17]
    digs = []
    x = mant
    for _ in range(17):
        digs.append((x % _U64(10)).astype(jnp.int32))
        x = x // _U64(10)
    dig_rev = jnp.stack(digs, axis=1)  # LSB-first

    d = digits

    # ---------- branch A: exp < 0 ----------
    zeros_cnt = jnp.clip(-exp - 1, 0, d)  # leading fractional zeros
    actual_round = d - zeros_cnt
    a_olength = jnp.minimum(olength, actual_round)
    a_rounded = _round_half_even(mant, olength, actual_round)
    a_carry = a_rounded >= _pow10_u64(a_olength)
    a_rounded = jnp.where(a_carry, a_rounded - _pow10_u64(a_olength), a_rounded)
    # carry only propagates when the zeros run reaches the digits (i == exp+1)
    a_has_carry = a_carry & ((-exp - 1) <= d)

    # ---------- branch C: 0 <= exp < olength-1 ----------
    temp_d = jnp.minimum(jnp.int32(d), olength - exp - 1)
    tailing_zero = d - temp_d
    c_rounded = _round_half_even(mant, olength, exp + temp_d + 1)
    c_pow = _pow10_u64(temp_d)
    c_integer = c_rounded // c_pow
    c_decimal = c_rounded % c_pow

    branch_a = exp < 0
    branch_b = (~branch_a) & (exp + 1 >= olength)
    branch_c = ~branch_a & ~branch_b

    # ---------- integer part as digit vector [n, MAXI], MSB-first --------
    # A: "0" or "1" (carry with no leading zeros); B: mantissa digits +
    # zero padding; C: digits of c_integer
    int_len = jnp.where(
        branch_a,
        1,
        jnp.where(branch_b, exp + 1, _digit_count(c_integer)),
    )
    j_int = jnp.arange(_MAX_INT_DIGITS, dtype=jnp.int32)[None, :]
    # digit index from most-significant: B reads mantissa digit j (0 pad
    # beyond olength); C reads c_integer digit j; A constant
    b_dig = jnp.where(
        j_int < olength[:, None],
        jnp.take_along_axis(
            dig_rev, jnp.clip(olength[:, None] - 1 - j_int, 0, 16), axis=1
        ),
        0,
    )
    c_digs = []
    x = c_integer
    for _ in range(18):
        c_digs.append((x % _U64(10)).astype(jnp.int32))
        x = x // _U64(10)
    c_rev = jnp.stack(c_digs, axis=1)
    c_ilen = _digit_count(c_integer)
    c_dig = jnp.take_along_axis(
        c_rev, jnp.clip(c_ilen[:, None] - 1 - j_int, 0, 17), axis=1
    )
    a_int0 = jnp.where(a_has_carry & (zeros_cnt == 0), 1, 0)
    int_dig = jnp.where(
        branch_a[:, None],
        jnp.where(j_int == 0, a_int0[:, None], 0),
        jnp.where(branch_b[:, None], b_dig, c_dig),
    )

    # ---------- fractional part [n, d] -----------------------------------
    if d > 0:
        j_f = jnp.arange(d, dtype=jnp.int32)[None, :]
        # A: zeros_cnt zeros (last may carry to 1), then a_olength rounded
        # digits, then zeros
        a_digs = []
        x = a_rounded
        for _ in range(18):
            a_digs.append((x % _U64(10)).astype(jnp.int32))
            x = x // _U64(10)
        a_rev = jnp.stack(a_digs, axis=1)
        a_pos = j_f - zeros_cnt[:, None]
        a_frac = jnp.where(
            (a_pos >= 0) & (a_pos < a_olength[:, None]),
            jnp.take_along_axis(
                a_rev, jnp.clip(a_olength[:, None] - 1 - a_pos, 0, 17), axis=1
            ),
            0,
        )
        a_frac = jnp.where(
            (j_f == zeros_cnt[:, None] - 1) & a_has_carry[:, None], 1, a_frac
        )
        # C: c_decimal zero-padded to temp_d, then tailing zeros
        d_digs = []
        x = c_decimal
        for _ in range(18):
            d_digs.append((x % _U64(10)).astype(jnp.int32))
            x = x // _U64(10)
        d_rev = jnp.stack(d_digs, axis=1)
        c_frac = jnp.where(
            j_f < temp_d[:, None],
            jnp.take_along_axis(
                d_rev, jnp.clip(temp_d[:, None] - 1 - j_f, 0, 17), axis=1
            ),
            0,
        )
        frac = jnp.where(
            branch_a[:, None], a_frac, jnp.where(branch_b[:, None], 0, c_frac)
        )
    else:
        frac = jnp.zeros((n, 0), jnp.int32)

    # ---------- assemble: sign + grouped integer + '.' + frac ------------
    fmt_int_len = int_len + (int_len - 1) // 3
    sign_len = negative.astype(jnp.int32)
    width = 1 + _MAX_INT_DIGITS + (_MAX_INT_DIGITS - 1) // 3 + 1 + d
    j = jnp.arange(width, dtype=jnp.int32)[None, :]
    p = j - sign_len[:, None]

    # grouped integer: reverse position r from the right end of the group
    r = fmt_int_len[:, None] - 1 - p
    in_int = (p >= 0) & (r >= 0)
    is_comma = (r % 4 == 3)
    dr = r - r // 4  # digit index from the right
    int_char = jnp.where(
        is_comma,
        ord(","),
        ord("0")
        + jnp.take_along_axis(
            int_dig, jnp.clip(int_len[:, None] - 1 - dr, 0, _MAX_INT_DIGITS - 1),
            axis=1,
        ),
    )
    out = jnp.where(in_int, int_char, ord(" "))
    out = jnp.where((j == 0) & negative[:, None], ord("-"), out)

    if d > 0:
        dot_pos = fmt_int_len[:, None]
        out = jnp.where(p == dot_pos, ord("."), out)
        fpos = p - dot_pos - 1
        m_frac = (fpos >= 0) & (fpos < d)
        fchar = ord("0") + jnp.take_along_axis(
            jnp.pad(frac, ((0, 0), (0, 1))), jnp.clip(fpos, 0, d - 1), axis=1
        )
        out = jnp.where(m_frac, fchar, out)
        length = sign_len + fmt_int_len + 1 + d
    else:
        length = sign_len + fmt_int_len

    chars = out.astype(jnp.uint8)

    # ---------- specials --------------------------------------------------
    def literal(s: bytes):
        buf = np.zeros((width,), np.uint8)
        buf[: len(s)] = np.frombuffer(s, np.uint8)
        return jnp.asarray(buf)[None, :], len(s)

    zero_str = b"0." + b"0" * d if d > 0 else b"0"
    nzero_str = b"-" + zero_str
    nan_c, nan_l = literal(b"\xef\xbf\xbd")
    inf_c, inf_l = literal(b"\xe2\x88\x9e")
    ninf_c, ninf_l = literal(b"-\xe2\x88\x9e")
    z_c, z_l = literal(zero_str)
    nz_c, nz_l = literal(nzero_str)
    for mask, c, l in (
        (is_zero & ~negative, z_c, z_l),
        (is_zero & negative, nz_c, nz_l),
        (is_inf & ~negative, inf_c, inf_l),
        (is_inf & negative, ninf_c, ninf_l),
        (is_nan, nan_c, nan_l),
    ):
        chars = jnp.where(mask[:, None], c, chars)
        length = jnp.where(mask, l, length)

    chars = jnp.where(j < length[:, None], chars, jnp.uint8(0))
    return StringColumn(chars, length * col.validity, col.validity)
