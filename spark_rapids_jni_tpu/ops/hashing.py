"""Spark-exact row hashes: MurmurHash3_32 and XXHash64.

Semantics derived from the reference implementation (spark-rapids-jni
``murmur_hash.cuh``/``murmur_hash.cu``/``xxhash64.cu``/``hash.cuh``; the Java
surface is ``Hash.java``):

* Row hash = fold over columns, the previous column's hash is the seed for
  the next element ("serial seeding"); **null elements return the seed
  unchanged** (Spark ignores nulls in hashes).
* Murmur3: Spark's variant — tail bytes that don't fill a 4-byte block each
  go through a FULL mix round with the byte **sign-extended** to 32 bits
  (plain Murmur3 packs the tail into one k1).  bool/int8/int16 widen to a
  4-byte block; int32/float/date are 4 bytes; int64/double/timestamp are 8
  bytes (two little-endian blocks).  Floats normalize NaNs to the canonical
  quiet NaN but do NOT normalize -0.0 (Java ``doubleToLongBits`` semantics).
* XXHash64: standard XXH64 over the same widened little-endian
  representations, but floats normalize **both** NaNs and -0.0
  (``normalize_nans_and_zeros`` in the reference).
* decimal32/64 hash their unscaled value sign-extended to 8 bytes.
  decimal128 hashes the minimal big-endian two's-complement byte string of
  the unscaled value (``java.math.BigInteger.toByteArray`` semantics,
  reference ``hash.cuh:64-103``).
* A struct's hash equals hashing its leaves as separate columns (reference
  HashTest ``testSpark32BitMurmur3HashStruct``), so callers pass struct
  leaves in order; nested *columns* are rejected until the nested substrate
  lands.

Everything is vectorized over rows: byte-string hashing runs a
``lax.fori_loop`` over the static padded width with per-row masks, so one
XLA loop serves every row regardless of individual string lengths.
"""

from __future__ import annotations

from typing import Sequence, Union

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column, ColumnBatch, Decimal128Column, StringColumn

DEFAULT_XXHASH64_SEED = 42  # Hash.java:26

# ---------------------------------------------------------------------------
# Murmur3_32 primitives (vectorized over rows; everything uint32)
# ---------------------------------------------------------------------------

# numpy, not jnp: module scope must not mint device arrays (GL001) — this
# module is imported lazily from inside jitted bodies, and a jnp constant
# created under an active trace escapes as a tracer (the PR 2 decimal bug)
_MM3_C1 = np.uint32(0xCC9E2D51)
_MM3_C2 = np.uint32(0x1B873593)
_MM3_C3 = np.uint32(0xE6546B64)


def _rotl32(x, r: int):
    return (x << r) | (x >> (32 - r))


from ._util import char_at as _gather_byte  # noqa: E402


def _mm3_mix(h, k1):
    """One full Murmur3 round: mix k1 into h."""
    k1 = k1 * _MM3_C1
    k1 = _rotl32(k1, 15)
    k1 = k1 * _MM3_C2
    h = h ^ k1
    h = _rotl32(h, 13)
    return h * jnp.uint32(5) + _MM3_C3


def _fmix32(h):
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def murmur3_u32(vals_u32, seed_u32):
    """Hash each 4-byte value (uint32[n]) with per-row seeds."""
    h = _mm3_mix(seed_u32, vals_u32)
    h = h ^ jnp.uint32(4)
    return _fmix32(h)


def murmur3_u64(vals_u64, seed_u32):
    """Hash each 8-byte value as two little-endian 4-byte blocks."""
    lo = (vals_u64 & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (vals_u64 >> jnp.uint64(32)).astype(jnp.uint32)
    h = _mm3_mix(seed_u32, lo)
    h = _mm3_mix(h, hi)
    h = h ^ jnp.uint32(8)
    return _fmix32(h)


def murmur3_bytes(chars, lengths, seed_u32):
    """Hash per-row byte strings.

    chars: uint8[n, L] (padded), lengths: int32[n], seed: uint32[n].
    4-byte little-endian blocks, then Spark's per-byte sign-extended tail.
    """
    n, L = chars.shape
    nblocks = (lengths // 4).astype(jnp.int32)

    def block_body(j, h):
        blk = jax.lax.dynamic_slice(chars, (0, 4 * j), (n, 4)).astype(jnp.uint32)
        k1 = blk[:, 0] | (blk[:, 1] << 8) | (blk[:, 2] << 16) | (blk[:, 3] << 24)
        return jnp.where(j < nblocks, _mm3_mix(h, k1), h)

    h = seed_u32
    if L >= 4:  # fori_loop traces its body even for a zero trip count
        h = jax.lax.fori_loop(0, L // 4, block_body, h)

    tail_start = nblocks * 4
    for t in range(min(3, L)):
        pos = tail_start + t
        byte = _gather_byte(chars, pos)
        # Java byte->int sign-extends; reproduce via int8 view.
        k1 = byte.astype(jnp.int8).astype(jnp.int32).astype(jnp.uint32)
        h = jnp.where(pos < lengths, _mm3_mix(h, k1), h)

    h = h ^ lengths.astype(jnp.uint32)
    return _fmix32(h)


# ---------------------------------------------------------------------------
# XXHash64 primitives (vectorized over rows; everything uint64)
# ---------------------------------------------------------------------------

_XXH_P1 = np.uint64(0x9E3779B185EBCA87)
_XXH_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_XXH_P3 = np.uint64(0x165667B19E3779F9)
_XXH_P4 = np.uint64(0x85EBCA77C2B2AE63)
_XXH_P5 = np.uint64(0x27D4EB2F165667C5)


def _rotl64(x, r: int):
    return (x << r) | (x >> (64 - r))


def _xxh_finalize(h):
    h = h ^ (h >> 33)
    h = h * _XXH_P2
    h = h ^ (h >> 29)
    h = h * _XXH_P3
    h = h ^ (h >> 32)
    return h


def _xxh_merge_round(h, v):
    v = v * _XXH_P2
    v = _rotl64(v, 31)
    v = v * _XXH_P1
    h = h ^ v
    return h * _XXH_P1 + _XXH_P4


def _xxh_mix8(h, k):
    k = k * _XXH_P2
    k = _rotl64(k, 31)
    k = k * _XXH_P1
    h = h ^ k
    return _rotl64(h, 27) * _XXH_P1 + _XXH_P4


def _xxh_mix4(h, k_u32):
    h = h ^ (k_u32.astype(jnp.uint64) * _XXH_P1)
    return _rotl64(h, 23) * _XXH_P2 + _XXH_P3


def _xxh_mix1(h, byte_u8):
    h = h ^ (byte_u8.astype(jnp.uint64) * _XXH_P5)
    return _rotl64(h, 11) * _XXH_P1


def xxhash64_u32(vals_u32, seed_u64):
    """Hash each value widened to a 4-byte block."""
    h = seed_u64 + _XXH_P5 + jnp.uint64(4)
    h = _xxh_mix4(h, vals_u32)
    return _xxh_finalize(h)


def xxhash64_u64(vals_u64, seed_u64):
    h = seed_u64 + _XXH_P5 + jnp.uint64(8)
    h = _xxh_mix8(h, vals_u64)
    return _xxh_finalize(h)


def xxhash64_bytes(chars, lengths, seed_u64):
    """Hash per-row byte strings (uint8[n, L] padded + int32 lengths)."""
    n, L = chars.shape
    len64 = lengths.astype(jnp.uint64)

    def get_u64(j8):
        # 8 bytes starting at byte offset 8*j8 (little-endian)
        blk = jax.lax.dynamic_slice(chars, (0, 8 * j8), (n, 8)).astype(jnp.uint64)
        out = blk[:, 0]
        for b in range(1, 8):
            out = out | (blk[:, b] << (8 * b))
        return out

    # --- 32-byte stripe accumulation ------------------------------------
    nstripes = (lengths // 32).astype(jnp.int32)
    v1 = seed_u64 + _XXH_P1 + _XXH_P2
    v2 = seed_u64 + _XXH_P2
    v3 = seed_u64
    v4 = seed_u64 - _XXH_P1

    def stripe_body(s, vs):
        v1, v2, v3, v4 = vs
        m = s < nstripes

        def acc(v, k):
            return jnp.where(m, _rotl64((v + k * _XXH_P2), 31) * _XXH_P1, v)

        v1 = acc(v1, get_u64(4 * s + 0))
        v2 = acc(v2, get_u64(4 * s + 1))
        v3 = acc(v3, get_u64(4 * s + 2))
        v4 = acc(v4, get_u64(4 * s + 3))
        return v1, v2, v3, v4

    if L >= 32:
        v1, v2, v3, v4 = jax.lax.fori_loop(0, L // 32, stripe_body, (v1, v2, v3, v4))

    h_long = (
        _rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12) + _rotl64(v4, 18)
    )
    for v in (v1, v2, v3, v4):
        h_long = _xxh_merge_round(h_long, v)
    h = jnp.where(lengths >= 32, h_long, seed_u64 + _XXH_P5)
    h = h + len64

    # --- remaining 8-byte chunks ----------------------------------------
    rem_start = nstripes * 32
    n8 = ((lengths % 32) // 8).astype(jnp.int32)  # 0..3 eight-byte chunks

    if L >= 8:
        def chunk8_body(j, h):
            # j-th 8-byte chunk after the stripes; per-row offset varies, so
            # gather bytes via take_along_axis.
            off = rem_start + 8 * j
            out = jnp.zeros((n,), jnp.uint64)
            for b in range(8):
                out = out | (_gather_byte(chars, off + b).astype(jnp.uint64) << (8 * b))
            return jnp.where(j < n8, _xxh_mix8(h, out), h)

        h = jax.lax.fori_loop(0, min(3, L // 8), chunk8_body, h)

    # --- one optional 4-byte chunk --------------------------------------
    off4 = rem_start + 8 * n8
    if L >= 4:
        word = jnp.zeros((n,), jnp.uint32)
        for b in range(4):
            word = word | (_gather_byte(chars, off4 + b).astype(jnp.uint32) << (8 * b))
        has4 = (lengths % 8) >= 4
        h = jnp.where(has4, _xxh_mix4(h, word), h)

    # --- trailing 1-3 bytes ---------------------------------------------
    offb = off4 + jnp.where((lengths % 8) >= 4, 4, 0)
    for t in range(min(3, L)):
        pos = offb + t
        h = jnp.where(pos < lengths, _xxh_mix1(h, _gather_byte(chars, pos)), h)

    return _xxh_finalize(h)


# ---------------------------------------------------------------------------
# Value widening (shared by both hash families)
# ---------------------------------------------------------------------------

_F32_QNAN = np.uint32(0x7FC00000)
_F64_QNAN = np.uint64(0x7FF8000000000000)


def _f64_bits(d):
    """f64 -> uint64 bit pattern without a 64-bit bitcast.

    TPU's X64-rewrite pass can't handle bitcast-convert on 64-bit element
    types, so bitcast to a uint32 pair (minor dim, little-endian) and
    reassemble with uint64 arithmetic (which the rewrite does support).
    """
    pair = jax.lax.bitcast_convert_type(d, jnp.uint32)
    lo = pair[..., 0].astype(jnp.uint64)
    hi = pair[..., 1].astype(jnp.uint64)
    return lo | (hi << 32)


def _u64_to_i64(h):
    """uint64 -> int64 reinterpret without a 64-bit bitcast (see _f64_bits)."""
    lo = (h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (h >> jnp.uint64(32)).astype(jnp.uint32)
    hi_signed = jax.lax.bitcast_convert_type(hi, jnp.int32).astype(jnp.int64)
    return (hi_signed << 32) | lo.astype(jnp.int64)


def _widen_fixed(col: Column, normalize_zeros: bool):
    """Return ('u32'|'u64', widened lanes) per reference type rules."""
    kind = col.dtype.kind
    d = col.data
    if kind in (T.Kind.BOOLEAN, T.Kind.INT8, T.Kind.INT16, T.Kind.INT32, T.Kind.DATE):
        return "u32", d.astype(jnp.int32).astype(jnp.uint32)
    if kind in (T.Kind.INT64, T.Kind.TIMESTAMP):
        return "u64", d.astype(jnp.int64).astype(jnp.uint64)
    if kind is T.Kind.FLOAT32:
        if normalize_zeros:
            d = jnp.where(d == 0.0, jnp.float32(0.0), d)
        bits = jax.lax.bitcast_convert_type(d, jnp.uint32)
        bits = jnp.where(jnp.isnan(d), _F32_QNAN, bits)
        return "u32", bits
    if kind is T.Kind.FLOAT64:
        if normalize_zeros:
            d = jnp.where(d == 0.0, jnp.float64(0.0), d)
        bits = jnp.where(jnp.isnan(d), _F64_QNAN, _f64_bits(d))
        return "u64", bits
    if kind is T.Kind.DECIMAL:
        # decimal32/64 widen (sign-extended) to 8 bytes; only called for <=18
        return "u64", d.astype(jnp.int64).astype(jnp.uint64)
    raise NotImplementedError(f"hash of {col.dtype!r}")


def _decimal128_java_bytes(col: Decimal128Column):
    """Minimal big-endian two's-complement bytes (BigInteger.toByteArray).

    Returns (bytes uint8[n,16] big-endian left-justified, lengths int32[n]).
    Reference semantics: hash.cuh:64-103.
    """
    limbs = col.limbs  # uint64 [n, 2] little-endian
    n = limbs.shape[0]
    # little-endian byte matrix [n, 16]
    le = jnp.stack(
        [
            ((limbs[:, k // 8] >> jnp.uint64(8 * (k % 8))) & jnp.uint64(0xFF)).astype(
                jnp.uint8
            )
            for k in range(16)
        ],
        axis=1,
    )
    negative = (limbs[:, 1] >> jnp.uint64(63)) != 0
    sign_byte = jnp.where(negative, jnp.uint8(0xFF), jnp.uint8(0x00))
    # count leading (most-significant) bytes equal to the sign byte
    eq = le[:, ::-1] == sign_byte[:, None]
    lead = jnp.cumprod(eq.astype(jnp.int32), axis=1).sum(axis=1)
    length = jnp.maximum(1, 16 - lead).astype(jnp.int32)
    # keep one extra byte when the top retained bit doesn't match the sign
    top_byte = jnp.take_along_axis(le, (length - 1)[:, None], axis=1)[:, 0]
    top_bit = (top_byte & jnp.uint8(0x80)) != 0
    need_pad = (length < 16) & (negative ^ top_bit)
    length = length + need_pad.astype(jnp.int32)
    # big-endian, left-justified: out[:, j] = le[:, length-1-j] for j < length
    j = jnp.arange(16)[None, :]
    src = jnp.clip(length[:, None] - 1 - j, 0, 15)
    be = jnp.take_along_axis(le, src, axis=1)
    be = jnp.where(j < length[:, None], be, jnp.uint8(0))
    return be, length


def _element_murmur3(col, seed_u32):
    if isinstance(col, StringColumn):
        return murmur3_bytes(col.chars, col.lengths, seed_u32)
    if isinstance(col, Decimal128Column):
        if col.dtype.decimal_storage_bits < 128:
            # low limb is already the sign-extended two's-complement value
            return murmur3_u64(col.limbs[:, 0], seed_u32)
        be, length = _decimal128_java_bytes(col)
        return murmur3_bytes(be, length, seed_u32)
    width, vals = _widen_fixed(col, normalize_zeros=False)
    return murmur3_u32(vals, seed_u32) if width == "u32" else murmur3_u64(vals, seed_u32)


def _element_xxhash64(col, seed_u64):
    if isinstance(col, StringColumn):
        return xxhash64_bytes(col.chars, col.lengths, seed_u64)
    if isinstance(col, Decimal128Column):
        if col.dtype.decimal_storage_bits < 128:
            return xxhash64_u64(col.limbs[:, 0], seed_u64)
        be, length = _decimal128_java_bytes(col)
        return xxhash64_bytes(be, length, seed_u64)
    width, vals = _widen_fixed(col, normalize_zeros=True)
    return (
        xxhash64_u32(vals, seed_u64) if width == "u32" else xxhash64_u64(vals, seed_u64)
    )


Columns = Union[ColumnBatch, Sequence]


def _as_columns(columns: Columns):
    """Expand top-level structs into their children (the reference's JNI
    layer decomposes structs before the kernel — HashTest struct tests
    assert struct hash == hashing the leaves in order).  A null struct row
    nulls its children, so the fold skips them (seed passes through).
    Bucketed string members of a MULTI-column row hash are merged back to
    one flat column first: the fold threads a per-row running hash
    through every column, which per-bucket evaluation can't reproduce
    (the single-column fast paths stay bucketed — they dispatch before
    this)."""
    from ..columnar.bucketed import BucketedStringColumn
    from ..columnar.column import StructColumn
    from ..columnar.encoded import is_encoded, materialize_column

    cols = columns.columns if isinstance(columns, ColumnBatch) else list(columns)
    out = []

    def expand(c, parent_valid=None):
        if is_encoded(c):
            # hash VALUES, not codes: Spark-exact row hashes must agree
            # bit-for-bit with the decoded path, and the murmur/xxhash
            # fold threads per-row carry state, so the per-entry hash is
            # not separable — one gather materializes the column here (a
            # sanctioned late-materialization point)
            c = materialize_column(c)
        if isinstance(c, BucketedStringColumn):
            c = c.merge()
        if isinstance(c, StructColumn):
            v = c.validity if parent_valid is None else (c.validity & parent_valid)
            for child in c.children:
                expand(child, v)
        else:
            if parent_valid is not None:
                c = dataclasses.replace(c, validity=c.validity & parent_valid)
            out.append(c)

    for c in cols:
        expand(c)
    return out


def _drill_list(col):
    """Leaf column + per-row [start, end) leaf-element ranges.

    Mirrors the reference adapter's drill loop (murmur_hash.cu:122-131):
    LIST composes offsets; STRUCT inside a list must be decomposed (single
    child) — multi-field structs inside lists are unsupported there too.
    """
    from ..columnar.column import ListColumn, StructColumn

    start = col.offsets[:-1]
    end = col.offsets[1:]
    cur = col.child
    while isinstance(cur, (ListColumn, StructColumn)):
        if isinstance(cur, StructColumn):
            if len(cur.children) != 1:
                raise NotImplementedError(
                    "hash of a multi-field STRUCT inside a LIST (the "
                    "reference kernel assumes decomposed single-child "
                    "structs, murmur_hash.cu:128)"
                )
            cur = cur.children[0]
        else:
            start = jnp.take(cur.offsets, jnp.clip(start, 0, cur.num_rows))
            end = jnp.take(cur.offsets, jnp.clip(end, 0, cur.num_rows))
            cur = cur.child
    return cur, start.astype(jnp.int32), end.astype(jnp.int32)


def _list_fold(col, h, element_fn):
    """Chained element fold: h = hash(elem, seed=h), nulls pass through.

    The loop trip count is the batch's longest list (a device scalar via
    ``while_loop``); cost is O(max-row-length * n) gathers — fine for the
    short lists these row hashes see (partition keys).
    """
    from ..relational.gather import gather_column

    leaf, start, end = _drill_list(col)
    if leaf.num_rows == 0:  # all rows null/empty: every fold is a no-op
        return h
    max_len = jnp.maximum((end - start).max(), 0)

    def cond(st):
        k, _ = st
        return k < max_len

    def body(st):
        k, h = st
        idx = start + k
        active = idx < end
        g = gather_column(leaf, jnp.clip(idx, 0, max(leaf.num_rows - 1, 0)))
        eh = element_fn(g, h)
        return k + 1, jnp.where(active & g.validity, eh, h)

    _, h = jax.lax.while_loop(cond, body, (jnp.int32(0), h))
    return h


def _validate(cols):
    if not cols:
        raise ValueError("hashing requires at least 1 column of input")
    n = cols[0].num_rows
    for c in cols:
        if c.num_rows != n:
            raise ValueError(
                f"row count mismatch: {c.num_rows} vs {n}; all columns must be the same size"
            )
    return n


def murmur_hash3_32(columns: Columns, seed: int = 42) -> Column:
    """Spark Murmur3_32 row hash across columns (reference murmur_hash.cu:187)."""
    from ..columnar.bucketed import BucketedStringColumn

    cols = columns if isinstance(columns, (list, tuple)) else [columns]
    if len(cols) == 1 and isinstance(cols[0], BucketedStringColumn):
        # per-bucket hashing at each bucket's width, scatter-merged
        return cols[0].apply_column(
            lambda b: murmur_hash3_32([b], seed=seed))
    cols = _as_columns(columns)
    n = _validate(cols)
    from ..columnar.column import ListColumn

    # r5: the Pallas hash kernels were deleted (v5e-measured 10-130x
    # slower than this jnp formulation — PALLAS_MEMO.md); XLA's fusion
    # of the chain below IS the TPU fast path.
    h = jnp.full((n,), jnp.uint32(seed & 0xFFFFFFFF))
    for c in cols:
        if isinstance(c, ListColumn):
            h = jnp.where(c.validity, _list_fold(c, h, _element_murmur3), h)
        else:
            h = jnp.where(c.validity, _element_murmur3(c, h), h)
    out = jax.lax.bitcast_convert_type(h, jnp.int32)
    return Column(out, jnp.ones((n,), jnp.bool_), T.INT32)


def xxhash64(columns: Columns, seed: int = DEFAULT_XXHASH64_SEED) -> Column:
    """Spark XXHash64 row hash across columns (reference xxhash64.cu:330)."""
    from ..columnar.column import ListColumn

    from ..columnar.bucketed import BucketedStringColumn

    pre = columns if isinstance(columns, (list, tuple)) else [columns]
    if len(pre) == 1 and isinstance(pre[0], BucketedStringColumn):
        return pre[0].apply_column(lambda b: xxhash64([b], seed=seed))
    cols = _as_columns(columns)
    n = _validate(cols)
    h = jnp.full((n,), jnp.uint64(seed & 0xFFFFFFFFFFFFFFFF))
    for c in cols:
        if isinstance(c, ListColumn):
            # the reference's xxhash64 has no nested support (Hash.java:78)
            raise NotImplementedError(
                "xxhash64 over LIST columns (unsupported in the reference)")
        h = jnp.where(c.validity, _element_xxhash64(c, h), h)
    out = _u64_to_i64(h)
    return Column(out, jnp.ones((n,), jnp.bool_), T.INT64)
