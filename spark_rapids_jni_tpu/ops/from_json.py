"""Spark ``from_json`` -> MAP<STRING,STRING> extraction.

Reference: ``/root/reference/src/main/cpp/src/map_utils.cu`` (FST token
stream over concatenated rows -> node tree -> LIST<STRUCT<STRING,STRING>>
of the top-level key/value pairs, values as RAW substrings).  Here the
char-level tokenizer scan from :mod:`get_json_object` is reused with a
tiny pair recorder instead of the JSONPath evaluator:

* at each top-level FIELD token, remember the key span (quotes stripped);
* at the completion of its value (terminal token or the END event of a
  depth-1 container), emit a (key span, raw value span) pair event;
* post-scan, pair events flatten row-major and front-compact via a
  2-operand flag sort (no scatter), the spans gather into padded key /
  value char matrices, and per-row counts prefix-sum into list offsets.

Output matches MapUtilsTest.java: string values keep their raw content
(no unescaping), container values are verbatim substrings including inner
whitespace, ``{}`` -> empty list, null/non-object/invalid rows -> null.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..columnar.column import ListColumn, StringColumn, StructColumn
from .get_json_object import (
    EV_FIELD,
    EV_NULL,
    EV_SARR,
    EV_SOBJ,
    EV_STR,
    M_DONE,
    M_VALUE,
    _pack_path,
    _step,
)


def _recorder_step(P, ptypes, pindexes, pnames, pnamelens, carry, xs):
    """Tokenizer step + top-level key/value pair recorder.

    Runs the full _step (its evaluator runs with an empty path; its
    emissions are ignored) and layers the map recorder on the raw token
    events it now exports (ev_a/ev_b + spans).
    """
    (j, c) = xs
    rec = {k: carry[k] for k in ("key_s", "key_e", "val_s", "root_obj")}
    tok_carry = {k: v for k, v in carry.items() if k not in rec}
    out, ys = _step(P, ptypes, pindexes, pnames, pnamelens, tok_carry, xs)
    ev_a, ev_b = ys["ev_a"], ys["ev_b"]
    span_s, span_len = ys["span_s"], ys["span_len"]
    depth_before = tok_carry["depth"]

    root_obj = rec["root_obj"] | ((ev_a == EV_SOBJ) & (depth_before == 0))

    # top-level field: remember the key content span (quotes stripped)
    fieldev = (ev_a == EV_FIELD) & (depth_before == 1)
    key_s = jnp.where(fieldev, span_s + 1, rec["key_s"])
    key_e = jnp.where(fieldev, span_s + span_len - 1, rec["key_e"])

    # the value: terminals complete in one event; containers open at
    # depth 1 and close via the END event returning to depth 1
    is_term = (ev_a >= EV_STR) & (ev_a <= EV_NULL)
    t_done = is_term & (depth_before == 1) & root_obj
    c_open = ((ev_a == EV_SOBJ) | (ev_a == EV_SARR)) & (depth_before == 1)
    val_s = jnp.where(c_open, j, rec["val_s"])
    c_done = (ev_b != 0) & (out["depth"] == 1) & (depth_before == 2) \
        & (rec["val_s"] >= 0) & root_obj

    pair_done = t_done | c_done
    # terminal values: strip quotes from strings to match the raw-map
    # contract (MapUtilsTest: value of "STANDARD" is STANDARD)
    is_str = ev_a == EV_STR
    t_s = jnp.where(is_str, span_s + 1, span_s)
    t_len = jnp.where(is_str, span_len - 2, span_len)
    pv_s = jnp.where(t_done, t_s, rec["val_s"])
    pv_e = jnp.where(t_done, t_s + t_len, j + 1)

    ys_rec = {
        "pair": pair_done,
        "pk_s": jnp.where(pair_done, rec["key_s"], 0),
        "pk_e": jnp.where(pair_done, rec["key_e"], 0),
        "pv_s": jnp.where(pair_done, pv_s, 0),
        "pv_e": jnp.where(pair_done, pv_e, 0),
    }
    out.update(
        key_s=key_s,
        key_e=key_e,
        val_s=jnp.where(pair_done, jnp.int32(-1), val_s),
        root_obj=root_obj,
    )
    return out, ys_rec


@partial(jax.jit, static_argnames=("max_pairs_per_row",))
def _extract(chars, lengths, validity, max_pairs_per_row):
    n, L = chars.shape
    i32 = jnp.int32
    ptypes, pindexes, pnames, pnamelens, P = _pack_path([])

    from .get_json_object import EVM_NORM, MAX_PATH

    D = MAX_PATH + 1
    zeros = jnp.zeros((n,), i32)
    carry = {
        "mode": jnp.full((n,), M_VALUE, i32),
        "depth": zeros,
        "cstack_lo": jnp.zeros((n,), jnp.uint32),
        "cstack_hi": jnp.zeros((n,), jnp.uint32),
        "allow_close": jnp.zeros((n,), jnp.bool_),
        "quote": jnp.zeros((n,), jnp.uint8),
        "sfield": jnp.zeros((n,), jnp.bool_),
        "tok_start": zeros,
        "ndig": zeros,
        "numf": jnp.zeros((n,), jnp.bool_),
        "ucnt": zeros,
        "lit_id": zeros,
        "lit_pos": zeros,
        "length": lengths.astype(i32),
        "fm_ok": jnp.zeros((n,), jnp.bool_),
        "fm_pos": zeros,
        "term_emit": jnp.zeros((n,), jnp.bool_),
        "term_esc": jnp.zeros((n,), jnp.bool_),
        "nfloat": zeros,
        "neg0": jnp.zeros((n,), jnp.bool_),
        "evm": jnp.full((n,), EVM_NORM, i32),
        "base_depth": zeros,
        "sp": zeros,
        "root_wait": jnp.ones((n,), jnp.bool_),
        "root_dirty": zeros,
        "ev_done": jnp.zeros((n,), jnp.bool_),
        "ev_fail": jnp.zeros((n,), jnp.bool_),
        "g_adep": zeros,
        "g_empty": jnp.ones((n,), jnp.bool_),
        "k_kind": jnp.zeros((n, D), i32),
        "k_wait": jnp.zeros((n, D), i32),
        "k_cpi": jnp.zeros((n, D), i32),
        "k_cnt": jnp.zeros((n, D), i32),
        "k_depth": jnp.zeros((n, D), i32),
        "k_dirty": jnp.zeros((n, D), i32),
        "k_chstyle": jnp.zeros((n, D), i32),
        "k_sadep": jnp.zeros((n, D), i32),
        "k_sempty": jnp.zeros((n, D), jnp.bool_),
        "k_gap": jnp.zeros((n, D), i32),
        # recorder fields
        "key_s": zeros,
        "key_e": zeros,
        "val_s": jnp.full((n,), -1, i32),
        "root_obj": jnp.zeros((n,), jnp.bool_),
    }
    cpad = jnp.pad(chars, ((0, 0), (0, 1)))
    xs = (jnp.arange(L + 1, dtype=i32), cpad.T)
    step = partial(_recorder_step, P, ptypes, pindexes, pnames, pnamelens)
    final, ys = jax.lax.scan(step, carry, xs)
    ys = {k: jnp.moveaxis(v, 0, 1) for k, v in ys.items()}  # [n, L+1]

    row_ok = validity & final["root_obj"] & (final["mode"] == M_DONE) \
        & ~final["ev_fail"]
    pair = ys["pair"] & row_ok[:, None]
    counts = pair.sum(axis=1).astype(i32)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), i32), jnp.cumsum(counts).astype(i32)])

    # flatten pair events row-major and front-compact (platform-aware
    # stable regroup, r5: counting scatter on CPU, lax.sort elsewhere)
    from ..parallel.partition import regroup_order

    L1 = L + 1
    flat_pair = pair.reshape(n * L1)
    order = regroup_order(
        jnp.where(flat_pair, 0, 1).astype(i32), 2)
    C = n * max_pairs_per_row
    picks = order[:C]
    total = counts.sum()
    live = jnp.arange(C, dtype=i32) < total

    def span(arr_s, arr_e, W):
        s = arr_s.reshape(n * L1)[picks]
        e = arr_e.reshape(n * L1)[picks]
        row = picks // L1
        ln = jnp.clip(e - s, 0, W)
        idx = jnp.clip(s[:, None], 0, L) + jnp.arange(W, dtype=i32)[None, :]
        rows = jnp.take(jnp.pad(chars, ((0, 0), (0, W))), row, axis=0)
        win = jnp.take_along_axis(rows, jnp.clip(idx, 0, L + W - 1), axis=1)
        win = jnp.where(jnp.arange(W, dtype=i32)[None, :] < ln[:, None],
                        win, jnp.uint8(0))
        return win, jnp.where(live, ln, 0)

    kc, kl = span(ys["pk_s"], ys["pk_e"], L)
    vc, vl = span(ys["pv_s"], ys["pv_e"], L)
    return (offsets, row_ok, kc, kl, vc, vl, live, total)


def from_json_to_raw_map(col: StringColumn,
                         max_pairs_per_row: int = 0) -> ListColumn:
    """LIST<STRUCT<key STRING, value STRING>> of top-level object fields."""
    n, L = col.chars.shape
    if max_pairs_per_row <= 0:
        # the smallest possible pair is 5 chars ('"":0,'); +1 slack covers
        # the missing trailing comma of the last pair
        max_pairs_per_row = max(1, L // 5 + 1)
    offsets, row_ok, kc, kl, vc, vl, live, total = _extract(
        col.chars, col.lengths, col.validity, max_pairs_per_row)
    keys = StringColumn(kc, kl, live)
    values = StringColumn(vc, vl, live)
    structs = StructColumn({"key": keys, "value": values}, live)
    return ListColumn(offsets, structs, row_ok)
