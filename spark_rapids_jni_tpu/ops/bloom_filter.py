"""Spark ``BloomFilterImpl``-bit-compatible bloom filter.

Reference: ``bloom_filter.cu``.  The serialized form is Spark's: a
big-endian header {version=1, num_hashes, num_longs} followed by the bit
array as big-endian longs — interchangeable with Spark CPU
(``bloom_filter.cu:46-60`` derives a word/byte swizzle so its
little-endian device words dump to that exact byte stream).

TPU design: the filter lives as ``bool[num_longs * 64]`` — one lane per
bit, indexed in the reference's swizzled order, so "set" is a plain
scatter of True (idempotent — no atomics needed) and "probe" is a gather.
Packing to the serialized bytes happens only at host boundaries.

Hashing (``gpu_bloom_filter_put``, bloom_filter.cu:63-87): h1 =
murmur3(long, seed=0), h2 = murmur3(long, seed=h1); bit k of probe i uses
``combined = h1 + i*h2`` (int32 wrap), flipped if negative, mod num_bits.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column
from .hashing import murmur3_u64

SPARK_BLOOM_FILTER_VERSION = 1


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BloomFilter:
    """num_longs*64 bits in serialized-buffer bit order (see module doc)."""

    bits: jax.Array  # bool[num_longs * 64]
    num_hashes: int
    num_longs: int

    def tree_flatten(self):
        return (self.bits,), (self.num_hashes, self.num_longs)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)


def bloom_filter_create(num_hashes: int, num_longs: int) -> BloomFilter:
    """Empty filter (reference bloom_filter_create, bloom_filter.cu:225)."""
    if num_hashes <= 0 or num_longs <= 0:
        raise ValueError("num_hashes and num_longs must be positive")
    return BloomFilter(
        jnp.zeros((num_longs * 64,), jnp.bool_), num_hashes, num_longs
    )


def _probe_positions(col: Column, num_hashes: int, num_longs: int):
    """Swizzled bit positions [n, num_hashes]; invalid rows out-of-range."""
    if col.dtype.kind is not T.Kind.INT64:
        raise TypeError("bloom filter input must be INT64")
    n = col.num_rows
    bits = jnp.uint32(num_longs * 64)
    el = col.data.astype(jnp.int64).astype(jnp.uint64)
    zero = jnp.zeros((n,), jnp.uint32)
    h1 = murmur3_u64(el, zero)
    h2 = murmur3_u64(el, h1)
    pos = []
    for i in range(1, num_hashes + 1):
        combined = h1 + jnp.uint32(i) * h2  # int32 wraparound semantics
        neg = (combined >> 31) != 0
        iv = jnp.where(neg, ~combined, combined)
        index = iv % bits
        word = (index >> 5) ^ jnp.uint32(1)  # 64-bit-long word swizzle
        bit = (index & jnp.uint32(31)) ^ jnp.uint32(0x18)  # byte swizzle
        pos.append((word << 5) | bit)
    out = jnp.stack(pos, axis=1).astype(jnp.int32)
    return jnp.where(col.validity[:, None], out, jnp.int32(num_longs * 64))


def bloom_filter_put(bf: BloomFilter, col: Column) -> BloomFilter:
    """Insert non-null longs (reference gpu_bloom_filter_put); functional —
    returns the updated filter."""
    pos = _probe_positions(col, bf.num_hashes, bf.num_longs).reshape(-1)
    bits = bf.bits.at[pos].set(True, mode="drop")
    return BloomFilter(bits, bf.num_hashes, bf.num_longs)


def bloom_filter_build(
    num_hashes: int, num_longs: int, col: Column
) -> BloomFilter:
    return bloom_filter_put(bloom_filter_create(num_hashes, num_longs), col)


def bloom_filter_merge(filters: Sequence[BloomFilter]) -> BloomFilter:
    """Bitwise OR (reference bloom_filter_merge, bloom_filter.cu:277)."""
    filters = list(filters)
    if not filters:
        raise ValueError("bloom_filter_merge requires at least one filter")
    first = filters[0]
    for f in filters[1:]:
        if (f.num_hashes, f.num_longs) != (first.num_hashes, first.num_longs):
            raise ValueError("mismatched bloom filter parameters")
    bits = first.bits
    for f in filters[1:]:
        bits = bits | f.bits
    return BloomFilter(bits, first.num_hashes, first.num_longs)


def bloom_filter_probe(bf: BloomFilter, col: Column) -> Column:
    """Membership test per row (reference bloom_filter_probe,
    bloom_filter.cu:339); null rows stay null."""
    pos = _probe_positions(col, bf.num_hashes, bf.num_longs)
    hit = jnp.take(bf.bits, jnp.clip(pos, 0, bf.num_longs * 64 - 1), axis=0)
    found = hit.all(axis=1)
    return Column(found, col.validity, T.BOOLEAN)


# ---------------------------------------------------------------------------
# host (de)serialization — Spark interchange format
# ---------------------------------------------------------------------------


def bloom_filter_serialize(bf: BloomFilter) -> bytes:
    """Header + bit array, byte-compatible with Spark's BloomFilterImpl."""
    header = struct.pack(
        ">iii", SPARK_BLOOM_FILTER_VERSION, bf.num_hashes, bf.num_longs
    )
    bits = np.asarray(jax.device_get(bf.bits)).astype(np.uint8)
    # position p = word*32 + bit; device words are little-endian uint32s
    # dumped in order, so byte b of the payload holds bits 8*(b%4)..+7 of
    # word b//4, LSB-first
    by = bits.reshape(bf.num_longs * 8, 8)
    weights = (1 << np.arange(8)).astype(np.uint8)
    payload = (by * weights[None, :]).sum(axis=1).astype(np.uint8)
    return header + payload.tobytes()


def bloom_filter_deserialize(buf: bytes) -> BloomFilter:
    if len(buf) < 12:
        raise ValueError("bloom filter buffer too short for header")
    version, num_hashes, num_longs = struct.unpack(">iii", buf[:12])
    if version != SPARK_BLOOM_FILTER_VERSION:
        raise ValueError(f"unsupported bloom filter version {version}")
    if num_hashes <= 0 or num_longs <= 0:
        raise ValueError(
            f"corrupt bloom filter header: num_hashes={num_hashes} "
            f"num_longs={num_longs}"
        )
    if len(buf) < 12 + num_longs * 8:
        raise ValueError(
            f"bloom filter buffer truncated: header claims {num_longs} longs"
        )
    payload = np.frombuffer(buf[12 : 12 + num_longs * 8], dtype=np.uint8)
    bits = (payload[:, None] >> np.arange(8)[None, :]) & 1
    return BloomFilter(
        jnp.asarray(bits.reshape(-1).astype(np.bool_)), num_hashes, num_longs
    )
