"""Spark ``percentile`` over (value, frequency) histograms.

Reference: ``histogram.cu`` — ``create_histogram_if_valid`` (:283) validates
frequencies (negative -> error) and nulls out entries with freq <= 0;
``percentile_from_histogram`` (:429) segment-sorts each histogram's
elements, computes inclusive cumulative frequencies, and linearly
interpolates ``position = (total_freq - 1) * percentage`` between the two
straddling elements (``fill_percentile_fn``, :50).

Here a batch of H histograms is (values Column, freqs int64 Column,
offsets int32[H+1]) — the flattened LIST layout.  The sort is one
``lax.sort`` keyed (segment, validity, value); cumulative counts are a
segmented cumsum (global cumsum minus per-segment base — scan + gather, no
scatter); the per-(histogram, percentage) rank search is a vectorized
binary search over the cumulative array restricted to each segment.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column
from ..relational import keys as K


def create_histogram_if_valid(
    values: Column, frequencies: Column
) -> Tuple[Column, Column]:
    """Validate and pack (value, freq) pairs (reference histogram.cu:283).

    Negative frequencies raise; entries with freq <= 0 or null value become
    null elements.  Returns the masked (values, frequencies).
    """
    if frequencies.dtype.kind is not T.Kind.INT64:
        raise TypeError("frequencies must be INT64")
    if values.num_rows != frequencies.num_rows:
        raise ValueError("values and frequencies must have the same size")
    # mask null-frequency rows: their buffer lanes may hold residual values
    freq = jnp.where(frequencies.validity, frequencies.data, jnp.int64(0))
    if bool(jnp.any(freq < 0)):  # host sync, same as the reference's check
        raise ValueError("The input frequencies must not contain negative values.")
    valid = values.validity & (freq > 0)
    return (
        Column(values.data, valid, values.dtype),
        Column(freq, frequencies.validity, frequencies.dtype),
    )


def percentile_from_histogram(
    values: Column,
    frequencies: Column,
    offsets,
    percentages: Sequence[float],
) -> Tuple[jax.Array, jax.Array]:
    """Exact percentiles per histogram (reference histogram.cu:429).

    ``offsets``: int32[H+1] flattened-list boundaries.  Returns
    ``(out float64[H, P], histogram_valid bool[H])``; all-null histograms
    yield invalid rows.
    """
    if any(not (0.0 <= p <= 1.0) for p in percentages):
        raise ValueError("percentages must be in [0, 1]")
    offsets = jnp.asarray(offsets, jnp.int32)
    H = offsets.shape[0] - 1
    P = len(percentages)
    n = values.num_rows
    pct = jnp.asarray(np.asarray(percentages, np.float64))

    seg = (jnp.searchsorted(offsets, jnp.arange(n, dtype=jnp.int32), side="right") - 1
           ).astype(jnp.int32)
    invalid = ~values.validity

    ops = (
        [seg.astype(jnp.uint32), invalid.astype(jnp.uint32)]
        + [
            jnp.where(values.validity, k, jnp.zeros((), k.dtype))
            for k in K.column_radix_keys(values, equality=False)
        ]
        + [jnp.arange(n, dtype=jnp.int32)]
    )
    res = jax.lax.sort(tuple(ops), num_keys=len(ops) - 1, is_stable=True)
    perm = res[-1]

    s_vals = jnp.take(values.data, perm).astype(jnp.float64)
    s_valid = jnp.take(values.validity, perm)
    s_freq = jnp.take(frequencies.data, perm) * s_valid.astype(jnp.int64)

    total = jnp.cumsum(s_freq)
    starts = offsets[:H]
    base = jnp.where(starts > 0, jnp.take(total, jnp.maximum(starts - 1, 0)), 0)
    acc = total - jnp.take(base, seg)  # per-segment inclusive cumulative

    valid_counts = jax.ops.segment_sum(
        s_valid.astype(jnp.int32), seg, num_segments=H
    )
    ends = starts + valid_counts  # nulls sorted to each segment's tail
    hist_valid = valid_counts > 0

    total_freq = jnp.where(
        hist_valid, jnp.take(acc, jnp.maximum(ends - 1, 0)), jnp.int64(1)
    )
    max_positions = (total_freq - 1).astype(jnp.float64)

    # per (h, p) rank positions
    position = max_positions[:, None] * pct[None, :]  # [H, P]
    lower = jnp.floor(position).astype(jnp.int64)
    higher = jnp.ceil(position).astype(jnp.int64)

    def search(rank):  # first idx in [start, end) with acc[idx] >= rank
        lo = jnp.broadcast_to(starts[:, None], rank.shape)
        hi = jnp.broadcast_to(ends[:, None], rank.shape)
        steps = max(1, int(n).bit_length() + 1)

        def body(_, lohi):
            lo, hi = lohi
            active = lo < hi
            mid = (lo + hi) >> 1
            v = jnp.take(acc, jnp.clip(mid, 0, max(n - 1, 0)))
            adv = v < rank
            lo = jnp.where(active & adv, mid + 1, lo)
            hi = jnp.where(active & ~adv, mid, hi)
            return lo, hi

        lo, _ = jax.lax.fori_loop(0, steps, body, (lo, hi))
        return lo

    idx_lo = search(lower + 1)
    idx_hi = search(higher + 1)
    el_lo = jnp.take(s_vals, jnp.clip(idx_lo, 0, max(n - 1, 0)))
    el_hi = jnp.take(s_vals, jnp.clip(idx_hi, 0, max(n - 1, 0)))

    same = (higher == lower) | (el_hi == el_lo)
    lower_part = (higher.astype(jnp.float64) - position) * el_lo
    higher_part = (position - lower.astype(jnp.float64)) * el_hi
    out = jnp.where(same, el_lo, lower_part + higher_part)
    return out, hist_valid
