"""Spark-semantics-exact kernels over column batches."""
