"""Spark-semantics-exact kernels over column batches.

Import kernels from their modules (``ops.cast_string``, ``ops.hashing``,
``ops.get_json_object``, ``ops.parse_uri``, ``ops.from_json``, ...); the
high-traffic entry points are also re-exported here.
"""

from .from_json import from_json_to_raw_map  # noqa: F401
from .get_json_object import get_json_object, parse_path  # noqa: F401
from .parse_uri import parse_uri  # noqa: F401
