"""UTC ⇄ local timestamp conversion via a device transitions table.

The reference splits this across two pieces: the Java ``GpuTimeZoneDB``
builds a ``LIST<STRUCT<utcInstant, tzInstant, utcOffset>>`` table from the
JVM tz database (GpuTimeZoneDB.java:261-330) and ``timezones.cu`` binary-
searches it per row.  Here the loader parses the IANA TZif binaries
directly (same data the JVM reads) and the kernel is a vectorized
``searchsorted`` over the zone's transition slice.

Semantics replicated exactly:

* Only fixed-offset zones and zones with no *recurring* DST rules are
  supported (``isSupportedTimeZone``, GpuTimeZoneDB.java:237-247): a TZif
  footer naming a DST rule marks the zone unsupported.
* Sentinel first row at ``INT64_MIN`` carries the pre-transition offset.
* Gap transitions key the local-time breakpoint at ``instant +
  offsetAfter``; overlaps at ``instant + offsetBefore`` (Spark's choice of
  which side of an ambiguous/skipped local time wins); the applied offset
  is always ``offsetAfter`` (GpuTimeZoneDB.java:300-320).
* The row timestamp is reduced to seconds with C++ ``duration_cast``
  truncation-toward-zero before the search (timezones.cu:74-75), then the
  full-resolution value is shifted by the found offset.
"""

from __future__ import annotations

import dataclasses
import os
import re
import struct
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column

_INT64_MIN = -(2**63)


# ---------------------------------------------------------------------------
# TZif parsing (RFC 8536)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _ZoneData:
    utc_instants: np.ndarray  # int64 seconds, first row INT64_MIN
    tz_instants: np.ndarray   # int64 seconds (local breakpoints)
    offsets: np.ndarray       # int32 seconds (offset AFTER each transition)


def _parse_tzif(path: str) -> Optional[_ZoneData]:
    """Parse a TZif file into the Spark transition-table form.

    Returns None for zones with recurring DST rules (unsupported, matching
    the reference's isSupportedTimeZone filter).
    """
    with open(path, "rb") as f:
        data = f.read()

    def read_header(off):
        magic, ver, isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = (
            struct.unpack(">4s c 15x 6I", data[off : off + 44])
        )
        if magic != b"TZif":
            raise ValueError(f"{path}: not a TZif file")
        return ver, isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt

    ver, isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = read_header(0)
    v1_size = 44 + timecnt * 5 + typecnt * 6 + charcnt + leapcnt * 8 + isstdcnt + isutcnt
    if ver in (b"2", b"3", b"4"):
        off = v1_size
        _, isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = read_header(off)
        off += 44
        tsize = 8
    else:
        # v1 files carry no footer TZ string, so recurring-DST rules can't
        # be ruled out — treat as unsupported (modern tzdata is all v2+)
        return None

    times = np.frombuffer(
        data, dtype=f">i{tsize}", count=timecnt, offset=off
    ).astype(np.int64)
    off += timecnt * tsize
    type_idx = np.frombuffer(data, dtype=np.uint8, count=timecnt, offset=off)
    off += timecnt
    ttinfo = [
        struct.unpack(">i?B", data[off + 6 * i : off + 6 * i + 6])
        for i in range(typecnt)
    ]
    off += typecnt * 6 + charcnt + leapcnt * (tsize + 4) + isstdcnt + isutcnt

    if tsize == 8:  # footer: "\nTZ-string\n"
        footer = data[off:].decode("ascii", "replace").strip("\n")
        # Recurring DST -> unsupported, like the reference's
        # isSupportedTimeZone.  A fixed-offset TZ string is exactly one
        # abbreviation plus an optional offset ("CST-8", "<+07>-7");
        # anything more (dst abbreviation "EST5EDT", comma rule section)
        # names a recurring rule.
        if footer and not re.match(
            r"^(<[^>]+>|[A-Za-z]+)([+-]?\d+(:\d+(:\d+)?)?)?$", footer
        ):
            return None

    utoffs = np.array([t[0] for t in ttinfo], dtype=np.int64)

    # offset before any transition: first non-DST type, else type 0
    first_type = 0
    for i, (_, isdst, _) in enumerate(ttinfo):
        if not isdst:
            first_type = i
            break
    base_off = int(utoffs[first_type]) if typecnt else 0

    utc_instants = [_INT64_MIN]
    tz_instants = [_INT64_MIN]
    offsets = [base_off]
    prev_off = base_off
    for t, idx in zip(times.tolist(), type_idx.tolist()):
        off_after = int(utoffs[idx])
        if off_after > prev_off:  # gap: local breakpoint uses offsetAfter
            tz_instants.append(t + off_after)
        else:  # overlap (or no-op): uses offsetBefore
            tz_instants.append(t + prev_off)
        utc_instants.append(t)
        offsets.append(off_after)
        prev_off = off_after

    return _ZoneData(
        np.array(utc_instants, np.int64),
        np.array(tz_instants, np.int64),
        np.array(offsets, np.int32),
    )


_FIXED_RE = re.compile(r"^([+-])(\d{2}):(\d{2})(?::(\d{2}))?$")


def _normalize_zone_id(zone_id: str) -> str:
    """Spark's pre-3.0 (+|-)h:mm and (+|-)hh:m forms (getZoneId)."""
    zone_id = re.sub(r"^([+-])(\d):", r"\g<1>0\g<2>:", zone_id)
    zone_id = re.sub(r"^([+-])(\d\d):(\d)$", r"\g<1>\g<2>:0\g<3>", zone_id)
    return zone_id


def _fixed_offset_zone(zone_id: str) -> Optional[_ZoneData]:
    if zone_id in ("UTC", "Z", "GMT"):
        secs = 0
    else:
        m = _FIXED_RE.match(_normalize_zone_id(zone_id))
        if not m:
            return None
        sign = 1 if m.group(1) == "+" else -1
        secs = sign * (
            int(m.group(2)) * 3600
            + int(m.group(3)) * 60
            + int(m.group(4) or 0)
        )
    return _ZoneData(
        np.array([_INT64_MIN], np.int64),
        np.array([_INT64_MIN], np.int64),
        np.array([secs], np.int32),
    )


class TimeZoneDB:
    """Lazily-loaded transitions table (GpuTimeZoneDB equivalent).

    Zones load on first use and are concatenated into flat device arrays
    (the LIST layout: per-zone slices of shared child buffers).
    """

    def __init__(self, tzpath: str = "/usr/share/zoneinfo"):
        self._tzpath = tzpath
        self._zones: Dict[str, Optional[_ZoneData]] = {}

    _ZONE_ID_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_+\-]*(/[A-Za-z0-9_+\-]+)*$")

    def zone(self, zone_id: str) -> _ZoneData:
        z = self._zones.get(zone_id)
        if z is None and zone_id not in self._zones:
            z = _fixed_offset_zone(zone_id)
            if z is None and self._ZONE_ID_RE.match(zone_id):
                # the id grammar forbids '.' components, so the join below
                # cannot escape tzpath
                path = os.path.join(self._tzpath, *zone_id.split("/"))
                if os.path.isfile(path):
                    try:
                        z = _parse_tzif(path)
                    except (struct.error, ValueError, OSError):
                        z = None
            self._zones[zone_id] = z
        if z is None:
            raise ValueError(f"unsupported time zone: {zone_id!r}")
        return z

    def is_supported(self, zone_id: str) -> bool:
        try:
            self.zone(zone_id)
            return True
        except ValueError:
            return False


_default_db: Optional[TimeZoneDB] = None


def default_db() -> TimeZoneDB:
    global _default_db
    if _default_db is None:
        _default_db = TimeZoneDB()
    return _default_db


def _convert(col: Column, zone_id: str, to_utc: bool, db: Optional[TimeZoneDB]):
    if col.dtype.kind is not T.Kind.TIMESTAMP:
        raise TypeError(f"expected TIMESTAMP, got {col.dtype!r}")
    z = (db or default_db()).zone(zone_id)
    micros = col.data
    # duration_cast truncation toward zero (timezones.cu:74)
    neg = micros < 0
    seconds = jnp.where(neg, -((-micros) // 1000000), micros // 1000000)
    keys = jnp.asarray(z.tz_instants if to_utc else z.utc_instants)
    idx = jnp.searchsorted(keys, seconds, side="right") - 1
    offset = jnp.take(jnp.asarray(z.offsets), idx).astype(jnp.int64) * 1000000
    out = jnp.where(to_utc, micros - offset, micros + offset)
    return Column(out, col.validity, col.dtype)


def convert_timestamp_to_utc(
    col: Column, zone_id: str, db: Optional[TimeZoneDB] = None
) -> Column:
    """Local wall-clock micros -> UTC micros (reference timezones.hpp:42)."""
    return _convert(col, zone_id, to_utc=True, db=db)


def convert_utc_to_timezone(
    col: Column, zone_id: str, db: Optional[TimeZoneDB] = None
) -> Column:
    """UTC micros -> local wall-clock micros (reference timezones.hpp:55)."""
    return _convert(col, zone_id, to_utc=False, db=db)
