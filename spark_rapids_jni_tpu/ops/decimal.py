"""Spark DECIMAL128 arithmetic with 256-bit intermediates, vectorized.

Semantics derived from the reference's ``decimal_utils.cu`` (spark-rapids-jni):
every operation computes in a 256-bit integer domain ("chunked256",
``decimal_utils.cu:32-119``), rescales with HALF_UP rounding, and reports
per-row overflow = |result| >= 10^38 (``is_greater_than_decimal_38``).
Scales here are **Spark scales** (digits right of the point, >= 0); the
reference uses cudf scales which are their negation.

Replicated quirks (each is a compatibility contract, SURVEY.md §7):

* ``multiply`` with ``cast_interim_result=True`` (the default, matching
  Spark < 3.4.2/3.5.1/4.0.0) first rounds the raw product to 38 digits of
  precision, then rounds to the target scale — a known Spark bug
  (``DecimalUtils.java:33-37``) that changes the last digit for some inputs.
* ``integer_divide`` overflow is judged on the 128-bit quotient *before* the
  int64 narrowing (``DecimalUtils.java integerDivide128`` doc).
* ``remainder`` follows Java's sign rule (result sign = dividend sign) and
  computes via ``a - (a // b) * b`` in the divisor's scale domain
  (``dec128_remainder``).
* divide-by-zero rows report overflow=True, result 0 (``dec128_divider``).

TPU mapping: a 256-bit value is ``uint32[n, 8]`` little-endian limbs (native
32-bit VPU lanes; 64-bit ops on TPU are emulated pairs).  Multiplication is
8x8 schoolbook with uint64 partial products; division is the reference's
bit-serial long division (``divide_unsigned``, decimal_utils.cu:149) turned
inside-out: instead of indexing bit i of the numerator (dynamic limb index),
the numerator shifts left one bit per step so the loop body is
shift/compare/subtract on whole vectors — 256 ``lax.fori_loop`` steps with
no data-dependent control flow.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column, Decimal128Column

# numpy, not jnp: this module is imported lazily from inside jitted
# aggregation bodies, and a jnp scalar created under an active trace is a
# tracer that outlives it (UnexpectedTracerError on the next trace)
_MASK32 = np.uint64(0xFFFFFFFF)

# pow10 limb table: 10^0 .. 10^76 as uint32[77, 8] little-endian
_POW10_NP = np.zeros((77, 8), dtype=np.uint32)
for _e in range(77):
    _v = 10**_e
    for _i in range(8):
        _POW10_NP[_e, _i] = (_v >> (32 * _i)) & 0xFFFFFFFF


def _pow10(e: int):
    """Static-exponent 10^e as a [1, 8] broadcastable constant."""
    return jnp.asarray(_POW10_NP[e : e + 1])


def _pow10_rows(e_rows):
    """Per-row 10^e gather (e int32[n] in [0, 76]) -> uint32[n, 8]."""
    return jnp.take(jnp.asarray(_POW10_NP), jnp.clip(e_rows, 0, 76), axis=0)


# ---------------------------------------------------------------------------
# uint32[n, 8] limb primitives
# ---------------------------------------------------------------------------


def _from_i128(limbs64) -> jax.Array:
    """Decimal128Column limbs (uint64[n,2] LE) -> sign-extended uint32[n,8]."""
    lo, hi = limbs64[:, 0], limbs64[:, 1]
    neg = (hi >> jnp.uint64(63)) != 0
    ext = jnp.where(neg, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    lanes = [
        (lo & _MASK32).astype(jnp.uint32),
        (lo >> jnp.uint64(32)).astype(jnp.uint32),
        (hi & _MASK32).astype(jnp.uint32),
        (hi >> jnp.uint64(32)).astype(jnp.uint32),
        ext, ext, ext, ext,
    ]
    return jnp.stack(lanes, axis=1)


def _to_i128(u) -> jax.Array:
    """Truncate uint32[n,8] -> uint64[n,2] (chunked256::as_128_bits)."""
    lo = u[:, 0].astype(jnp.uint64) | (u[:, 1].astype(jnp.uint64) << 32)
    hi = u[:, 2].astype(jnp.uint64) | (u[:, 3].astype(jnp.uint64) << 32)
    return jnp.stack([lo, hi], axis=1)


def _sign_neg(u) -> jax.Array:
    """bool[n]: 256-bit two's-complement value is negative."""
    return (u[:, 7] >> 31) != 0


def _add(a, b) -> jax.Array:
    lanes = []
    carry = jnp.zeros(a.shape[:1], jnp.uint64)
    for i in range(8):
        s = a[:, i].astype(jnp.uint64) + b[:, i].astype(jnp.uint64) + carry
        lanes.append((s & _MASK32).astype(jnp.uint32))
        carry = s >> jnp.uint64(32)
    return jnp.stack(lanes, axis=1)


def _add_small(a, inc) -> jax.Array:
    """a + inc where inc is int32[n] in {-1, 0, 1} (sign-extended)."""
    ext = jnp.where(inc < 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    b = jnp.stack(
        [inc.astype(jnp.uint32)] + [ext] * 7, axis=1
    )
    return _add(a, b)


def _neg(a) -> jax.Array:
    ones = jnp.ones(a.shape[:1], jnp.int32)
    return _add_small(~a, ones)


def _abs(a) -> Tuple[jax.Array, jax.Array]:
    neg = _sign_neg(a)
    return jnp.where(neg[:, None], _neg(a), a), neg


def _lt_u(a, b) -> jax.Array:
    """unsigned a < b; LSB-first fold so the highest differing limb wins."""
    res = jnp.zeros(a.shape[:1], jnp.bool_)
    for i in range(8):
        res = jnp.where(a[:, i] == b[:, i], res, a[:, i] < b[:, i])
    return res


def _shl1(a) -> jax.Array:
    lanes = [(a[:, 0] << 1)]
    for i in range(1, 8):
        lanes.append((a[:, i] << 1) | (a[:, i - 1] >> 31))
    return jnp.stack(lanes, axis=1)


def _mul(a, b) -> jax.Array:
    """Low 256 bits of a*b (reference ``multiply``, decimal_utils.cu:127)."""
    n = a.shape[0]
    res = [jnp.zeros((n,), jnp.uint32) for _ in range(8)]
    a64 = [a[:, i].astype(jnp.uint64) for i in range(8)]
    b64 = [b[:, j].astype(jnp.uint64) for j in range(8)]
    for j in range(8):
        carry = jnp.zeros((n,), jnp.uint64)
        for i in range(8 - j):
            t = a64[i] * b64[j] + res[i + j].astype(jnp.uint64) + carry
            res[i + j] = (t & _MASK32).astype(jnp.uint32)
            carry = t >> jnp.uint64(32)
    return jnp.stack(res, axis=1)


def _divmod_u(num, den) -> Tuple[jax.Array, jax.Array]:
    """Unsigned 256-bit / 256-bit long division -> (quotient, remainder).

    Bit-serial (256 steps), all rows in lockstep; den must be nonzero
    (callers mask div-by-zero rows to 1 and overwrite the result).
    """

    def body(_, st):
        nn, q, r = st
        top = nn[:, 7] >> 31  # numerator MSB enters the remainder
        nn = _shl1(nn)
        r = _shl1(r)
        r = r.at[:, 0].set(r[:, 0] | top)
        ge = ~_lt_u(r, den)
        r = jnp.where(ge[:, None], _add(r, _neg(den)), r)
        q = _shl1(q)
        q = q.at[:, 0].set(q[:, 0] | ge.astype(jnp.uint32))
        return nn, q, r

    n = num.shape[0]
    zeros = jnp.zeros((n, 8), jnp.uint32)
    _, q, r = jax.lax.fori_loop(0, 256, body, (num, zeros, zeros))
    return q, r


def _divmod_u_small(u, den) -> Tuple[jax.Array, jax.Array]:
    """Unsigned 256-bit / u32 long division -> (quotient, remainder).

    ``den``: uint64[n], 0 < den < 2^32.  Schoolbook base-2^32 from the top
    limb — 8 u64 divmods instead of :func:`_divmod_u`'s 256 shift-subtract
    steps (group-average divides by a row count, always a small divisor).
    """
    rem = jnp.zeros(u.shape[:1], jnp.uint64)
    qs = []
    for i in range(7, -1, -1):
        cur = (rem << jnp.uint64(32)) | u[:, i].astype(jnp.uint64)
        qs.append((cur // den).astype(jnp.uint32))
        rem = cur % den
    return jnp.stack(qs[::-1], axis=1), rem


def _precision10(u_abs) -> jax.Array:
    """Smallest i with 10^i >= |value| (reference precision10)."""
    table = jnp.asarray(_POW10_NP)  # [77, 8]
    # ge[n, e] = table[e] >= u_abs[n]; LSB-first fold, highest limb wins
    res = jnp.ones(u_abs.shape[:1] + (77,), jnp.bool_)
    for i in range(8):
        t = table[None, :, i]
        v = u_abs[:, i, None]
        res = jnp.where(t == v, res, t > v)
    return jnp.argmax(res, axis=1).astype(jnp.int32)


def _overflow_38(u) -> jax.Array:
    a, _ = _abs(u)
    return ~_lt_u(a, _pow10(38))


# ---------------------------------------------------------------------------
# signed helpers mirroring the reference's divide / rounding machinery
# ---------------------------------------------------------------------------


def _divide_signed(n_limbs, d_limbs):
    """(quotient signed, |remainder|, n_neg, d_neg); divisor 0 handled by
    callers (rows masked)."""
    abs_n, n_neg = _abs(n_limbs)
    abs_d, d_neg = _abs(d_limbs)
    safe_d = jnp.where(
        _is_zero(abs_d)[:, None], _one_like(abs_d), abs_d
    )
    q, r = _divmod_u(abs_n, safe_d)
    q = jnp.where((n_neg ^ d_neg)[:, None], _neg(q), q)
    return q, r, n_neg, d_neg


def _is_zero(u) -> jax.Array:
    return (u == 0).all(axis=1)


def _one_like(u) -> jax.Array:
    one = jnp.zeros_like(u)
    return one.at[:, 0].set(1)


def _round_half_up(q_signed, r_abs, d_abs, round_down) -> jax.Array:
    """HALF_UP: bump |q| by 1 when 2|r| >= |d| (reference
    round_from_remainder; the 256-bit domain makes its double-remainder
    overflow check unnecessary)."""
    need_inc = ~_lt_u(_shl1(r_abs), d_abs)
    inc = jnp.where(
        need_inc, jnp.where(round_down, jnp.int32(-1), jnp.int32(1)), jnp.int32(0)
    )
    return _add_small(q_signed, inc)


def _divide_and_round(n_limbs, d_limbs) -> jax.Array:
    """Signed divide with HALF_UP rounding (reference divide_and_round)."""
    q, r, n_neg, d_neg = _divide_signed(n_limbs, d_limbs)
    abs_d, _ = _abs(d_limbs)
    return _round_half_up(q, r, abs_d, n_neg ^ d_neg)


def _integer_divide(n_limbs, d_limbs) -> jax.Array:
    q, _, _, _ = _divide_signed(n_limbs, d_limbs)
    return q


def _set_scale_and_round(u, from_scale: int, to_scale: int) -> jax.Array:
    """Rescale between static Spark scales with HALF_UP on scale decrease."""
    if to_scale == from_scale:
        return u
    if to_scale > from_scale:
        return _mul(u, jnp.broadcast_to(_pow10(to_scale - from_scale), u.shape))
    d = jnp.broadcast_to(_pow10(from_scale - to_scale), u.shape)
    return _divide_and_round(u, d)


# ---------------------------------------------------------------------------
# public ops — each returns (overflow Column<bool>, result)
# ---------------------------------------------------------------------------


def _both_valid(a: Decimal128Column, b: Decimal128Column) -> jax.Array:
    return a.validity & b.validity


def _result(limbs_u8, valid, scale: int) -> Decimal128Column:
    return Decimal128Column(
        _to_i128(limbs_u8), valid, T.SparkType.decimal(38, scale)
    )


def _add_sub(a, b, result_scale: int, is_sub: bool):
    sa, sb = a.scale, b.scale
    inter = max(sa, sb)
    ua = _set_scale_and_round(_from_i128(a.limbs), sa, inter)
    ub = _set_scale_and_round(_from_i128(b.limbs), sb, inter)
    if is_sub:
        ub = _neg(ub)
    s = _add(ua, ub)
    s = _set_scale_and_round(s, inter, result_scale)
    valid = _both_valid(a, b)
    overflow = _overflow_38(s)
    return Column(overflow, valid, T.BOOLEAN), _result(s, valid, result_scale)


def add_decimal128(a: Decimal128Column, b: Decimal128Column, result_scale: int):
    """a + b at result_scale (reference add_decimal128, decimal_utils.cu:1110)."""
    return _add_sub(a, b, result_scale, is_sub=False)


def sub_decimal128(a: Decimal128Column, b: Decimal128Column, result_scale: int):
    """a - b at result_scale (reference sub_decimal128, decimal_utils.cu:1143)."""
    return _add_sub(a, b, result_scale, is_sub=True)


def multiply_decimal128(
    a: Decimal128Column,
    b: Decimal128Column,
    product_scale: int,
    cast_interim_result: bool = True,
):
    """a * b at product_scale (reference dec128_multiplier, decimal_utils.cu:657).

    ``cast_interim_result`` replicates the Spark < 3.4.2 double-rounding bug
    (round to precision 38 first, then to the target scale).
    """
    ua = _from_i128(a.limbs)
    ub = _from_i128(b.limbs)
    product = _mul(ua, ub)
    n = product.shape[0]
    mult_scale = jnp.full((n,), a.scale + b.scale, jnp.int32)

    if cast_interim_result:
        abs_p, _ = _abs(product)
        fdp = _precision10(abs_p) - 38
        do = fdp > 0
        divisor = _pow10_rows(jnp.where(do, fdp, 0))
        rounded = _divide_and_round(product, divisor)
        product = jnp.where(do[:, None], rounded, product)
        mult_scale = mult_scale - jnp.where(do, fdp, 0)

    # exponent > 0: divide down to the target scale; < 0: scale up
    exponent = mult_scale - product_scale
    abs_p, _ = _abs(product)
    new_precision = _precision10(abs_p)
    up_overflow = (exponent < 0) & (new_precision - exponent > 38)

    scale_div = _pow10_rows(jnp.where(exponent > 0, exponent, 0))
    scaled_down = _divide_and_round(product, scale_div)
    scale_mul = _pow10_rows(jnp.where(exponent < 0, -exponent, 0))
    scaled_up = _mul(product, scale_mul)
    product = jnp.where(
        (exponent > 0)[:, None],
        scaled_down,
        jnp.where((exponent < 0)[:, None], scaled_up, product),
    )

    valid = _both_valid(a, b)
    overflow = up_overflow | _overflow_38(product)
    return Column(overflow, valid, T.BOOLEAN), _result(product, valid, product_scale)


def _div_prepare(a: Decimal128Column, b: Decimal128Column, quotient_scale: int):
    """Shared scaling logic of dec128_divider (reference decimal_utils.cu:744).

    Returns (n, d, n_shift_exp, div_by_zero) with Spark scales:
    n_shift_exp = quotient_scale - (a.scale - b.scale), the power of ten the
    numerator must gain (positive) or the quotient must lose (negative).
    """
    n_limbs = _from_i128(a.limbs)
    d_limbs = _from_i128(b.limbs)
    div0 = _is_zero(_abs(d_limbs)[0])
    shift = quotient_scale - (a.scale - b.scale)
    return n_limbs, d_limbs, shift, div0


def divide_decimal128(
    a: Decimal128Column, b: Decimal128Column, quotient_scale: int
):
    """a / b at quotient_scale, HALF_UP (reference dec128_divider<__int128_t>)."""
    n_limbs, d_limbs, shift, div0 = _div_prepare(a, b, quotient_scale)

    if shift < 0:
        # quotient has too many digits: divide, then shed 10^-shift with rounding
        q1 = _integer_divide(n_limbs, d_limbs)
        res = _divide_and_round(q1, jnp.broadcast_to(_pow10(-shift), q1.shape))
    elif shift > 38:
        # two-stage scale-up (reference n_shift_exp < -38 branch): multiply by
        # 10^38, divide, then scale quotient+remainder by the rest and divide
        # the remainder again so no intermediate exceeds 256 bits
        n1 = _mul(n_limbs, jnp.broadcast_to(_pow10(38), n_limbs.shape))
        q1, r1, n_neg, d_neg = _divide_signed(n1, d_limbs)
        r1_signed = jnp.where(n_neg[:, None], _neg(r1), r1)
        rest = shift - 38
        pow_rest = jnp.broadcast_to(_pow10(rest), q1.shape)
        res = _mul(q1, pow_rest)
        scaled_r = _mul(r1_signed, pow_rest)
        q2, r2, _, _ = _divide_signed(scaled_r, d_limbs)
        res = _add(res, q2)
        abs_d, _ = _abs(d_limbs)
        res = _round_half_up(res, r2, abs_d, n_neg ^ d_neg)
    else:
        n1 = _mul(n_limbs, jnp.broadcast_to(_pow10(shift), n_limbs.shape))
        res = _divide_and_round(n1, d_limbs)

    res = jnp.where(div0[:, None], jnp.zeros_like(res), res)
    valid = _both_valid(a, b)
    overflow = div0 | _overflow_38(res)
    return Column(overflow, valid, T.BOOLEAN), _result(res, valid, quotient_scale)


def integer_divide_decimal128(a: Decimal128Column, b: Decimal128Column):
    """a div b -> int64 (reference dec128_divider<uint64_t, true>; overflow is
    judged on the wide quotient, not the int64 narrowing)."""
    n_limbs, d_limbs, shift, div0 = _div_prepare(a, b, 0)

    if shift < 0:
        q1 = _integer_divide(n_limbs, d_limbs)
        res = _integer_divide(q1, jnp.broadcast_to(_pow10(-shift), q1.shape))
    elif shift > 38:
        n1 = _mul(n_limbs, jnp.broadcast_to(_pow10(38), n_limbs.shape))
        q1, r1, n_neg, _ = _divide_signed(n1, d_limbs)
        r1_signed = jnp.where(n_neg[:, None], _neg(r1), r1)
        rest = shift - 38
        pow_rest = jnp.broadcast_to(_pow10(rest), q1.shape)
        res = _mul(q1, pow_rest)
        scaled_r = _mul(r1_signed, pow_rest)
        q2, _, _, _ = _divide_signed(scaled_r, d_limbs)
        res = _add(res, q2)
    else:
        n1 = _mul(n_limbs, jnp.broadcast_to(_pow10(shift), n_limbs.shape))
        res = _integer_divide(n1, d_limbs)

    res = jnp.where(div0[:, None], jnp.zeros_like(res), res)
    valid = _both_valid(a, b)
    overflow = div0 | _overflow_38(res)
    limbs = _to_i128(res)
    # as_64_bits: low limb reinterpreted as int64
    lo = limbs[:, 0]
    hi32 = (lo >> jnp.uint64(32)).astype(jnp.uint32)
    lo32 = (lo & _MASK32).astype(jnp.uint32)
    i64 = (
        jax.lax.bitcast_convert_type(hi32, jnp.int32).astype(jnp.int64) << 32
    ) | lo32.astype(jnp.int64)
    return Column(overflow, valid, T.BOOLEAN), Column(i64, valid, T.INT64)


def remainder_decimal128(
    a: Decimal128Column, b: Decimal128Column, remainder_scale: int
):
    """a % b at remainder_scale, Java sign rule (reference dec128_remainder)."""
    n_limbs = _from_i128(a.limbs)
    d_limbs = _from_i128(b.limbs)
    div0 = _is_zero(_abs(d_limbs)[0])

    abs_n, n_neg = _abs(n_limbs)
    abs_d, _ = _abs(d_limbs)

    # shift the divisor into the remainder's scale domain
    d_shift = remainder_scale - b.scale  # >0: scale divisor up exactly
    n_shift = remainder_scale - a.scale
    if d_shift < 0:
        # rounding drop on the divisor (set_scale_and_round path)
        abs_d = _divide_and_round(
            abs_d, jnp.broadcast_to(_pow10(-d_shift), abs_d.shape)
        )
    else:
        n_shift -= d_shift

    safe_d = jnp.where(_is_zero(abs_d)[:, None], _one_like(abs_d), abs_d)

    if n_shift < 0:
        q1, _ = _divmod_u(abs_n, safe_d)
        int_div = _integer_divide(
            q1, jnp.broadcast_to(_pow10(-n_shift), q1.shape)
        )
    else:
        abs_n2 = (
            _mul(abs_n, jnp.broadcast_to(_pow10(n_shift), abs_n.shape))
            if n_shift > 0
            else abs_n
        )
        abs_n = abs_n2
        int_div, _ = _divmod_u(abs_n, safe_d)

    less_n = _mul(int_div, abs_d)
    if d_shift > 0:
        # the divisor was left unscaled (we shifted n instead), so the
        # subtrahend must gain the divisor's scale shift
        less_n = _mul(less_n, jnp.broadcast_to(_pow10(d_shift), less_n.shape))
    res = _add(abs_n, _neg(less_n))
    res = jnp.where(n_neg[:, None], _neg(res), res)
    res = jnp.where(div0[:, None], jnp.zeros_like(res), res)

    valid = _both_valid(a, b)
    overflow = div0 | _overflow_38(res)
    return Column(overflow, valid, T.BOOLEAN), _result(res, valid, remainder_scale)
