"""Shared per-row char helpers for string kernels."""

from __future__ import annotations

import jax.numpy as jnp


def char_at(chars, pos):
    """chars[i, pos[i]] with clamped gather; 0 where pos is out of range."""
    L = chars.shape[1]
    c = jnp.take_along_axis(chars, jnp.clip(pos, 0, L - 1)[:, None], axis=1)[:, 0]
    return jnp.where((pos >= 0) & (pos < L), c, jnp.uint8(0))


def is_ws(c):
    """Whitespace or C0 control code (reference cast_string.cu:46-56)."""
    return c <= jnp.uint8(0x20)


def is_digit(c):
    return (c >= jnp.uint8(ord("0"))) & (c <= jnp.uint8(ord("9")))


def strip_and_sign(chars, lengths, strip: bool):
    """Locate the value start: optional stripped whitespace then one sign.

    Returns (start, has_sign, negative) where ``start`` indexes the first
    content char after whitespace and sign.  All three casts share this
    preamble (reference cast_string.cu:184-198, cast_string_to_float.cu:99-102).
    """
    n, L = chars.shape
    idx = jnp.arange(L)[None, :]
    in_range = idx < lengths[:, None]
    if strip:
        nonws = in_range & ~is_ws(chars)
        any_nonws = nonws.any(axis=1)
        s0 = jnp.where(any_nonws, jnp.argmax(nonws, axis=1), lengths).astype(jnp.int32)
    else:
        s0 = jnp.zeros((n,), jnp.int32)
    sc = char_at(chars, s0)
    has_sign = (sc == ord("+")) | (sc == ord("-"))
    negative = sc == ord("-")
    return s0 + has_sign.astype(jnp.int32), has_sign, negative
