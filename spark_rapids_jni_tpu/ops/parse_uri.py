"""Spark ``parse_url`` (PROTOCOL/HOST/QUERY/PATH[, key]) on TPU.

Reference: the RFC-3986-ish device validator/extractor
``/root/reference/src/main/cpp/src/parse_uri.cu:94-1005`` (semantics also
modeled by ``tests/uri_oracle.py``, which mirrors java.net.URI).  The
reference runs a thread-per-row two-pass kernel; here everything is
whole-column vectorized over the padded char matrix:

* component boundaries (first ``:/#?``, authority internals, last colon /
  bracket) are masked min/max reductions and pure position arithmetic;
* per-chunk character-class validation is one vectorized pass with
  neighbor-window logic for ``%XX`` escapes and UTF-8 multi-byte
  whitespace (the reference's ``skip_and_validate_special``);
* the three stateful validators (IPv4 / IPv6 / domain-name) run as a
  single fused ``lax.scan`` over the extracted host window — the only
  sequential axis in the kernel, with a ~12-int vector state.

Outputs match Spark's null semantics: a fatally invalid URI nulls every
part; an invalid-but-tolerated host nulls only HOST (parse_uri.cu:74-79).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..columnar.column import StringColumn

PROTOCOL, HOST, AUTHORITY, PATH, FRAGMENT, QUERY, USERINFO, PORT, OPAQUE = \
    range(9)
_PARTS = {"PROTOCOL": PROTOCOL, "HOST": HOST, "QUERY": QUERY, "PATH": PATH,
          "AUTHORITY": AUTHORITY, "FRAGMENT": FRAGMENT, "USERINFO": USERINFO,
          "PORT": PORT, "OPAQUE": OPAQUE}


def _first_pos(mask, pos, L):
    """First position where mask holds, else L (int32[n])."""
    return jnp.min(jnp.where(mask, pos, L), axis=1).astype(jnp.int32)


def _last_pos(mask, pos):
    """Last position where mask holds, else -1."""
    return jnp.max(jnp.where(mask, pos, -1), axis=1).astype(jnp.int32)


def _is_alpha(c):
    return ((c >= ord("a")) & (c <= ord("z"))) | ((c >= ord("A")) & (c <= ord("Z")))


def _is_num(c):
    return (c >= ord("0")) & (c <= ord("9"))


def _is_hexd(c):
    return _is_num(c) | ((c >= ord("a")) & (c <= ord("f"))) \
        | ((c >= ord("A")) & (c <= ord("F")))


# ---------------------------------------------------------------------------
# chunk validation: char classes + escape/UTF-8 "special" handling
# ---------------------------------------------------------------------------

def _special_masks(chars, nxt1, nxt2, allow_invalid_escapes):
    """Per-position exemption + validity for the reference's
    skip_and_validate_special.

    Returns (exempt, bad): ``exempt`` marks positions the per-chunk char
    predicate must NOT see (escape hex pairs, UTF-8 sequences); ``bad``
    marks positions that invalidate the whole chunk when inside it.
    """
    c = chars.astype(jnp.int32)
    n1 = nxt1.astype(jnp.int32)
    n2 = nxt2.astype(jnp.int32)
    is_pct = c == ord("%")
    pct_ok = _is_hexd(n1) & _is_hexd(n2)
    prev_pct = jnp.pad(is_pct, ((0, 0), (1, 0)))[:, :-1]
    prev2_pct = jnp.pad(is_pct, ((0, 0), (2, 0)))[:, :-2]
    in_escape = (is_pct | prev_pct | prev2_pct) & ~allow_invalid_escapes

    lead2 = (c >> 5) == 0b110
    lead3 = (c >> 4) == 0b1110
    lead4 = (c >> 3) == 0b11110
    contb = (c >> 6) == 0b10
    is_lead = lead2 | lead3 | lead4
    prev_lead2p = jnp.pad(is_lead, ((0, 0), (1, 0)))[:, :-1]
    prev_lead34 = jnp.pad(lead3 | lead4, ((0, 0), (2, 0)))[:, :-2]
    prev_lead4 = jnp.pad(lead4, ((0, 0), (3, 0)))[:, :-3]
    in_mb = is_lead | ((prev_lead2p | prev_lead34 | prev_lead4) & contb)

    # packed code checks (the reference packs the char bytes big-endian)
    code2 = (c << 8) | n1
    code3 = (c << 16) | (n1 << 8) | n2
    cont_bad = (lead2 & ((n1 >> 6) != 0b10)) \
        | (lead3 & (((n1 >> 6) != 0b10) | ((n2 >> 6) != 0b10))) \
        | (lead4 & (((n1 >> 6) != 0b10) | ((n2 >> 6) != 0b10)))
    ws_bad = (lead2 & (code2 >= 0xC280) & (code2 <= 0xC2A0)) \
        | (lead3 & ((code3 == 0xE19A80)
                    | ((code3 >= 0xE28080) & (code3 <= 0xE2808A))
                    | (code3 == 0xE280AF) | (code3 == 0xE280A8)
                    | (code3 == 0xE2819F) | (code3 == 0xE38080)))
    esc_bad = is_pct & ~pct_ok & ~allow_invalid_escapes
    bad = esc_bad | (is_lead & (cont_bad | ws_bad))
    exempt = in_escape | in_mb
    return exempt, bad


def _chunk_valid(ok_char, chars, nxt1, nxt2, pos, start, end,
                 allow_invalid_escapes=False):
    """Vectorized validate_chunk over the [start, end) span of each row."""
    if isinstance(allow_invalid_escapes, bool):
        allow = jnp.full((chars.shape[0], 1), allow_invalid_escapes)
    else:
        allow = allow_invalid_escapes[:, None]
    exempt, bad = _special_masks(chars, nxt1, nxt2, allow)
    inside = (pos >= start[:, None]) & (pos < end[:, None])
    fn_bad = inside & ~exempt & ~ok_char(chars.astype(jnp.int32))
    return ~jnp.any(inside & bad, axis=1) & ~jnp.any(fn_bad, axis=1)


def _scheme_ok(chars, pos, start, end):
    c = chars.astype(jnp.int32)
    inside = (pos >= start[:, None]) & (pos < end[:, None])
    first = pos == start[:, None]
    ok = jnp.where(
        first, _is_alpha(c),
        _is_alpha(c) | _is_num(c) | (c == ord("+")) | (c == ord("-"))
        | (c == ord(".")))
    nonempty = end > start
    return nonempty & ~jnp.any(inside & ~ok, axis=1)


def _q_ok(c):
    return ((c == ord("!")) | (c == ord('"')) | (c == ord("$"))
            | ((c >= ord("&")) & (c <= ord(";"))) | (c == ord("="))
            | ((c >= ord("?")) & (c <= ord("]")) & (c != ord("\\")))
            | ((c >= ord("a")) & (c <= ord("z"))) | (c == ord("_"))
            | (c == ord("~")))


def _auth_ok(c):
    # '%' is appended conditionally by the caller via allow_invalid_escapes
    return ((c == ord("!")) | (c == ord("$"))
            | ((c >= ord("&")) & (c <= ord(";")) & (c != ord("/")))
            | (c == ord("="))
            | ((c >= ord("@")) & (c <= ord("_")) & (c != ord("^"))
               & (c != ord("\\")))
            | ((c >= ord("a")) & (c <= ord("z"))) | (c == ord("~")))


def _path_ok(c):
    return ((c == ord("!")) | (c == ord("$"))
            | ((c >= ord("&")) & (c <= ord(";"))) | (c == ord("="))
            | ((c >= ord("@")) & (c <= ord("Z"))) | (c == ord("_"))
            | ((c >= ord("a")) & (c <= ord("z"))) | (c == ord("~")))


def _opaque_ok(c):
    return ((c == ord("!")) | (c == ord("$"))
            | ((c >= ord("&")) & (c <= ord(";"))) | (c == ord("="))
            | ((c >= ord("?")) & (c <= ord("]")) & (c != ord("\\")))
            | (c == ord("_")) | (c == ord("~"))
            | ((c >= ord("a")) & (c <= ord("z"))))


def _userinfo_ok(c):
    return (c != ord("[")) & (c != ord("]"))


# ---------------------------------------------------------------------------
# host validation (the one sequential piece: fused ipv4/ipv6/domain scan)
# ---------------------------------------------------------------------------

def _validate_host(chars, lengths):
    """(valid, fatal) over extracted host windows [n, H].

    Port of validate_host + validate_ipv4/ipv6/domain (parse_uri.cu:
    165-398) as one scan with all three machines running in parallel.
    """
    n, H = chars.shape
    pos = jnp.arange(H, dtype=jnp.int32)[None, :]
    inside = pos < lengths[:, None]
    c0 = chars[:, 0].astype(jnp.int32)
    last = jnp.take_along_axis(
        chars, jnp.clip(lengths - 1, 0, H - 1)[:, None], axis=1)[:, 0]
    empty = lengths <= 0
    is_br = (c0 == ord("[")) & ~empty
    br_closed = last == ord("]")

    has_brackets = jnp.any(
        inside & ((chars == ord("[")) | (chars == ord("]"))), axis=1)
    last_period = _last_pos(inside & (chars == ord(".")),
                            jnp.broadcast_to(pos, chars.shape))
    after_lp = jnp.take_along_axis(
        chars, jnp.clip(last_period + 1, 0, H - 1)[:, None], axis=1)[:, 0]
    # domain-name route iff no period / trailing period / non-digit after
    domain_route = (last_period < 0) | (last_period == lengths - 1) \
        | ~_is_num(after_lp.astype(jnp.int32))

    def step(st, x):
        (j, c) = x
        c = c.astype(jnp.int32)
        act = (j < st["len"])
        isd = _is_num(c)
        # ---- ipv6 ----
        v6 = st["v6ok"]
        colon = c == ord(":")
        period = c == ord(".")
        pct = c == ord("%")
        openb = c == ord("[")
        closeb = c == ord("]")
        dc_now = colon & (st["prev"] == ord(":"))
        v6 = v6 & ~(act & openb & (st["nopen"] >= 1))
        v6 = v6 & ~(act & closeb & (st["nclose"] >= 1))
        v6 = v6 & ~(act & closeb & (st["nper"] > 0)
                    & (st["ahex"] | (st["addr"] > 255)))
        ncolon = st["ncol"] + (act & colon)
        v6 = v6 & ~(act & dc_now & st["dc"])
        dc = st["dc"] | (act & dc_now)
        v6 = v6 & ~(act & colon & ((ncolon > 8) | ((ncolon == 8) & ~dc)))
        v6 = v6 & ~(act & colon & ((st["nper"] > 0) | (st["npct"] > 0)))
        nper = st["nper"] + (act & period)
        v6 = v6 & ~(act & period & (
            (st["npct"] > 0) | (nper > 3) | st["ahex"] | (st["addr"] > 255)
            | ((st["ncol"] != 6) & ~st["dc"]) | (st["ncol"] >= 8)))
        npct = st["npct"] + (act & pct)
        v6 = v6 & ~(act & pct & (npct > 1))
        v6 = v6 & ~(act & pct & (st["nper"] > 0)
                    & (st["ahex"] | (st["addr"] > 255)))
        is_af = ((c >= ord("a")) & (c <= ord("f")))
        is_AZ = ((c >= ord("A")) & (c <= ord("Z")))
        other6 = act & ~(colon | period | pct | openb | closeb)
        digit_like = other6 & (st["npct"] == 0)
        v6 = v6 & ~(digit_like & (st["achars"] > 3))
        v6 = v6 & ~(digit_like & ~(is_af | is_AZ | isd))
        reset = act & (colon | period | pct)
        addr = jnp.where(reset, 0, st["addr"])
        ahex = jnp.where(reset, False, st["ahex"])
        achars = jnp.where(reset, 0, st["achars"])
        addr = jnp.where(digit_like,
                         addr * 10 + jnp.where(is_af, 10 + c - ord("a"),
                                 jnp.where(is_AZ, 10 + c - ord("A"),
                                           c - ord("0"))),
                         addr)
        ahex = ahex | (digit_like & (is_af | is_AZ))
        achars = jnp.where(digit_like, achars + 1, achars)
        # ---- ipv4 ----
        v4 = st["v4ok"]
        v4 = v4 & ~(act & ~isd & ((j == 0) | ~period))
        v4 = v4 & ~(act & period & (st["a4chars"] == 0))
        a4 = jnp.where(act & period, 0,
                       jnp.where(act & isd, st["a4"] * 10 + c - ord("0"),
                                 st["a4"]))
        a4chars = jnp.where(act & period, 0,
                            jnp.where(act & isd, st["a4chars"] + 1,
                                      st["a4chars"]))
        v4 = v4 & ~(act & isd & (a4 > 255))
        ndots = st["ndots"] + (act & period)
        # ---- domain ----
        dm = st["dmok"]
        alnum = _is_alpha(c) | isd
        dash = c == ord("-")
        dm = dm & ~(act & ~(alnum | dash | period))
        numeric_start = act & st["lastper"] & isd
        dm = dm & ~(act & dash & (st["lastper"] | (j == 0)
                                  | (j == st["len"] - 1)))
        dm = dm & ~(act & period & (st["lastdash"] | st["lastper"]
                                    | (st["nbefore"] == 0)))
        lastper = jnp.where(act, period, st["lastper"])
        lastdash = jnp.where(act, dash, st["lastdash"])
        nbefore = jnp.where(act & period, 0,
                            jnp.where(act & alnum, st["nbefore"] + 1,
                                      st["nbefore"]))
        numstart = jnp.where(act, numeric_start, st["numstart"])
        prev = jnp.where(act, c, st["prev"])
        return {
            "len": st["len"], "prev": prev,
            "v6ok": v6, "dc": dc, "ncol": ncolon, "nper": nper,
            "npct": npct, "nopen": st["nopen"] + (act & openb),
            "nclose": st["nclose"] + (act & closeb),
            "addr": addr, "ahex": ahex, "achars": achars,
            "v4ok": v4, "a4": a4, "a4chars": a4chars, "ndots": ndots,
            "dmok": dm, "lastper": lastper, "lastdash": lastdash,
            "nbefore": nbefore, "numstart": numstart,
        }, None

    z = jnp.zeros((n,), jnp.int32)
    f = jnp.zeros((n,), jnp.bool_)
    t = jnp.ones((n,), jnp.bool_)
    init = {
        "len": lengths.astype(jnp.int32), "prev": z,
        "v6ok": t, "dc": f, "ncol": z, "nper": z, "npct": z,
        "nopen": z, "nclose": z, "addr": z, "ahex": f, "achars": z,
        "v4ok": t, "a4": z, "a4chars": z, "ndots": z,
        "dmok": t, "lastper": f, "lastdash": f, "nbefore": z, "numstart": f,
    }
    st, _ = jax.lax.scan(step, init,
                         (jnp.arange(H, dtype=jnp.int32), chars.T))
    v6 = st["v6ok"] & (lengths >= 2)
    v4 = st["v4ok"] & (st["a4chars"] > 0) & (st["ndots"] == 3)
    dm = st["dmok"] & ~st["numstart"]

    fatal = is_br & (~br_closed | ~v6)
    valid_br = is_br & br_closed & v6
    fatal = fatal | (~is_br & has_brackets & ~empty)
    valid_nb = ~is_br & ~has_brackets & jnp.where(domain_route, dm, v4)
    valid = ~empty & (valid_br | (~is_br & ~has_brackets & valid_nb))
    fatal = fatal & ~empty
    return valid, fatal


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("part", "key"))
def _parse(chars, lengths, validity, part, key):
    n, L = chars.shape
    i32 = jnp.int32
    pos = jnp.arange(L, dtype=i32)[None, :]
    inside = pos < lengths[:, None]
    cpad = jnp.pad(chars, ((0, 0), (0, 2)))
    nxt1 = cpad[:, 1: L + 1]
    nxt2 = cpad[:, 2: L + 2]
    c = jnp.where(inside, chars, jnp.uint8(0))

    length = lengths.astype(i32)
    col = _first_pos(inside & (c == ord(":")), pos, L)
    slash = _first_pos(inside & (c == ord("/")), pos, L)
    hash_ = _first_pos(inside & (c == ord("#")), pos, L)
    question = _first_pos(inside & (c == ord("?")), pos, L)
    NOPE = i32(L)

    valid = jnp.ones((n,), jnp.bool_)  # not-yet-fatally-invalid
    has = {k: jnp.zeros((n,), jnp.bool_) for k in range(9)}
    spans = {k: (jnp.zeros((n,), i32), jnp.zeros((n,), i32)) for k in range(9)}

    # ---- fragment ------------------------------------------------------
    has_hash = hash_ < length
    frag_s, frag_e = hash_ + 1, length
    frag_ok = _chunk_valid(_opaque_ok, chars, nxt1, nxt2, pos, frag_s, frag_e)
    valid = valid & (~has_hash | frag_ok)
    has[FRAGMENT] = has_hash
    spans[FRAGMENT] = (frag_s, frag_e)
    length = jnp.where(has_hash, hash_, length)
    col = jnp.where(col > length, NOPE, col)
    slash = jnp.where(slash > length, NOPE, slash)
    question = jnp.where(question > length, NOPE, question)

    # ---- scheme --------------------------------------------------------
    has_scheme = (col < L) & (col < slash) & (col < hash_)
    scheme_ok = _scheme_ok(chars, pos, jnp.zeros((n,), i32), col)
    valid = valid & (~has_scheme | scheme_ok)
    has[PROTOCOL] = has_scheme
    spans[PROTOCOL] = (jnp.zeros((n,), i32), col)
    start = jnp.where(has_scheme, col + 1, 0)

    # ---- empty remainder: only an (empty) path survives, scheme dies ---
    empty_rest = length - start <= 0
    valid = valid & (~empty_rest | ~has_scheme)
    only_path = empty_rest & ~has_scheme
    # the reference OVERWRITES valid here (:608-614): an empty remainder
    # keeps only the empty path — the fragment bit is lost too
    has[FRAGMENT] = has[FRAGMENT] & ~empty_rest

    # ---- hierarchical vs opaque ----------------------------------------
    first_c = jnp.take_along_axis(cpad, jnp.clip(start, 0, L)[:, None],
                                  axis=1)[:, 0].astype(i32)
    hier = ~empty_rest & ((first_c == ord("/")) | (start == 0))
    opaque = ~empty_rest & ~hier
    op_ok = _chunk_valid(_opaque_ok, chars, nxt1, nxt2, pos, start, length)
    valid = valid & (~opaque | op_ok)
    has[OPAQUE] = opaque
    spans[OPAQUE] = (start, length)

    # ---- query ----------------------------------------------------------
    has_q = hier & (question < length) & (question >= start)
    q_s, q_e = question + 1, length
    q_ok = _chunk_valid(_q_ok, chars, nxt1, nxt2, pos, q_s, q_e)
    valid = valid & (~has_q | q_ok)
    has[QUERY] = has_q
    spans[QUERY] = (q_s, q_e)
    path_end = jnp.where(has_q, question, length)

    # ---- authority // --------------------------------------------------
    second_c = jnp.take_along_axis(cpad, jnp.clip(start + 1, 0, L)[:, None],
                                   axis=1)[:, 0].astype(i32)
    has_auth = hier & (first_c == ord("/")) & (second_c == ord("/")) \
        & (start + 1 < length)
    auth_s = start + 2
    next_slash = _first_pos(inside & (c == ord("/")) & (pos >= auth_s[:, None])
                            & (pos < path_end[:, None]), pos, L)
    have_ns = has_auth & (next_slash < path_end)
    auth_e = jnp.where(have_ns, next_slash, jnp.minimum(path_end, length))
    auth_nonempty = has_auth & (auth_e > auth_s)
    # ipv6-style authorities tolerate bare % (device routing suffix)
    a_first = jnp.take_along_axis(cpad, jnp.clip(auth_s, 0, L)[:, None],
                                  axis=1)[:, 0].astype(i32)
    ipv6_auth = auth_nonempty & (auth_e - auth_s > 2) & (a_first == ord("["))
    auth_ok = _chunk_valid(
        lambda ch: _auth_ok(ch) | (ipv6_auth[:, None] & (ch == ord("%"))),
        chars, nxt1, nxt2, pos, auth_s, auth_e,
        allow_invalid_escapes=ipv6_auth)
    valid = valid & (~auth_nonempty | auth_ok)
    has[AUTHORITY] = auth_nonempty
    spans[AUTHORITY] = (auth_s, auth_e)

    # path: from next_slash (if any) else empty
    path_s = jnp.where(has_auth, jnp.where(have_ns, next_slash, length),
                       start)
    path_e = jnp.where(has_auth, jnp.where(have_ns, path_end, length),
                       path_end)
    path_s = jnp.where(only_path, 0, path_s)
    path_e = jnp.where(only_path, 0, path_e)
    has_path = hier | only_path
    p_ok = _chunk_valid(_path_ok, chars, nxt1, nxt2, pos, path_s, path_e)
    valid = valid & (~has_path | p_ok)
    has[PATH] = has_path
    spans[PATH] = (path_s, path_e)

    # ---- userinfo / host / port inside the authority --------------------
    in_auth = inside & (pos >= auth_s[:, None]) & (pos < auth_e[:, None])
    amp = _first_pos(in_auth & (c == ord("@")), pos, L)
    has_amp = auth_nonempty & (amp < auth_e) & (amp > auth_s)  # amp>0 rel.
    ui_s, ui_e = auth_s, amp
    ui_ok = _chunk_valid(_userinfo_ok, chars, nxt1, nxt2, pos, ui_s, ui_e)
    valid = valid & (~has_amp | ui_ok)
    has[USERINFO] = has_amp
    spans[USERINFO] = (ui_s, ui_e)
    host_s = jnp.where(has_amp, amp + 1, auth_s)
    # last ':' and ']' at positions after userinfo
    in_host_zone = inside & (pos >= host_s[:, None]) & (pos < auth_e[:, None])
    last_colon = _last_pos(in_host_zone & (c == ord(":")),
                           jnp.broadcast_to(pos, chars.shape))
    last_brk = _last_pos(in_host_zone & (c == ord("]")),
                         jnp.broadcast_to(pos, chars.shape))
    # the reference computes last_colon relative (i or i-amp-1) and tests
    # last_colon > 0: a colon at relative 0 does NOT make a port
    rel0 = last_colon == host_s
    has_port = auth_nonempty & (last_colon >= 0) & ~rel0 \
        & ((last_brk < 0) | (last_colon > last_brk))
    port_s, port_e = last_colon + 1, auth_e
    # (reference validate_port accepts any char — a preserved quirk)
    has[PORT] = has_port
    spans[PORT] = (port_s, port_e)
    host_e = jnp.where(has_port, last_colon, auth_e)
    # extract host window and validate
    H = min(L, 256)
    hidx = jnp.clip(host_s[:, None], 0, L) + jnp.arange(H, dtype=i32)[None, :]
    hwin = jnp.take_along_axis(jnp.pad(chars, ((0, 0), (0, H))),
                               jnp.clip(hidx, 0, L + H - 1), axis=1)
    hlen = jnp.clip(host_e - host_s, 0, H)
    hwin = jnp.where(jnp.arange(H, dtype=i32)[None, :] < hlen[:, None],
                     hwin, jnp.uint8(0))
    host_valid, host_fatal = _validate_host(hwin, hlen)
    valid = valid & (~auth_nonempty | ~host_fatal)
    has[HOST] = auth_nonempty & host_valid
    spans[HOST] = (host_s, host_e)

    # ---- select the requested part --------------------------------------
    part_id = _PARTS[part]
    out_has = has[part_id] & valid & validity
    s, e = spans[part_id]

    if part_id == QUERY and key is not None:
        kb = key.encode()
        klen = len(kb)
        karr = jnp.asarray(list(kb), jnp.uint8) if klen else None
        q_s_, q_e_ = spans[QUERY]
        in_q = inside & (pos >= q_s_[:, None]) & (pos < q_e_[:, None])
        # match at param starts: q_s or after '&'; needle then '='
        prev_chars = jnp.pad(chars, ((0, 0), (1, 0)))[:, :L]
        at_start = (pos == q_s_[:, None]) | (
            in_q & (prev_chars == ord("&")))
        match = jnp.ones((n, L), jnp.bool_)
        cp2 = jnp.pad(chars, ((0, 0), (0, klen + 1)))
        for k in range(klen):
            match = match & (cp2[:, k: L + k] == karr[k])
        match = match & (cp2[:, klen: L + klen] == ord("="))
        # reference stops the search once p + klen >= q_e
        match = match & at_start & ((pos + klen) < q_e_[:, None])
        mpos = _first_pos(match, pos, L)
        found = out_has & (mpos < L)
        v_s = mpos + klen + 1
        after_amp = _first_pos(
            inside & (c == ord("&")) & (pos >= v_s[:, None])
            & (pos < q_e_[:, None]), pos, L)
        v_e = jnp.minimum(after_amp, q_e_)
        out_has = found
        s, e = v_s, v_e

    out_len = jnp.clip(e - s, 0, L)
    W = L
    oidx = jnp.clip(s[:, None], 0, L) + jnp.arange(W, dtype=i32)[None, :]
    out = jnp.take_along_axis(jnp.pad(chars, ((0, 0), (0, W))),
                              jnp.clip(oidx, 0, L + W - 1), axis=1)
    out = jnp.where(jnp.arange(W, dtype=i32)[None, :] < out_len[:, None],
                    out, jnp.uint8(0))
    return out, jnp.where(out_has, out_len, 0), out_has


def parse_uri(col: StringColumn, part: str,
              key: Optional[str] = None) -> StringColumn:
    """Extract one URI component per row; invalid rows -> null.

    ``part`` is one of PROTOCOL/HOST/QUERY/PATH (plus the internal
    AUTHORITY/FRAGMENT/USERINFO/PORT/OPAQUE chunks); ``key`` filters the
    query to one parameter's value (Spark ``parse_url(url, 'QUERY', k)``).
    """
    part = part.upper()
    if part not in _PARTS:
        raise ValueError(f"unknown URI part {part!r}")
    if key is not None and part != "QUERY":
        raise ValueError("key filter is only valid with QUERY")
    from ..columnar.bucketed import BucketedStringColumn

    if isinstance(col, BucketedStringColumn):
        # per-bucket: each bucket's validator scan runs at ITS width
        return col.apply(lambda b: parse_uri(b, part, key))
    out, lens, has = _parse(col.chars, col.lengths, col.validity, part, key)
    return StringColumn(out, lens, has)


def parse_uri_query_with_column(col: StringColumn,
                                keys: StringColumn) -> StringColumn:
    """Per-row query-parameter extraction (reference ParseURI.java:82
    parseURIQueryWithColumn over parse_uri.cu's column-key kernel).

    Two stages: the shared validator/extractor pulls each row's QUERY
    span, then a vectorized matcher finds ``key=`` at parameter starts
    (query start or after ``&``) with the key length varying per row.
    Null keys or invalid URIs produce null rows.
    """
    if keys.num_rows != col.num_rows:
        raise ValueError("key column must match the URI column's row count")
    q = parse_uri(col, "QUERY")
    qc, ql, qv = q.chars, q.lengths, q.validity
    kc, kl, kv = keys.chars, keys.lengths, keys.validity
    n, L = qc.shape
    KL = kc.shape[1]
    i32 = jnp.int32
    pos = jnp.arange(L, dtype=i32)[None, :]
    in_q = pos < ql[:, None]

    prev = jnp.pad(qc, ((0, 0), (1, 0)))[:, :L]
    at_start = in_q & ((pos == 0) | (prev == ord("&")))

    qp = jnp.pad(qc, ((0, 0), (0, KL + 1)))
    match = jnp.ones((n, L), jnp.bool_)
    for j in range(KL):
        active = (jnp.int32(j) < kl)[:, None]
        match = match & (~active | (qp[:, j: L + j] == kc[:, j][:, None]))
    # '=' must follow the (per-row-length) key
    eq_idx = jnp.clip(pos + kl[:, None], 0, L + KL)
    eq_char = jnp.take_along_axis(qp, eq_idx, axis=1)
    match = match & (eq_char == ord("="))
    match = match & at_start & ((pos + kl[:, None]) < ql[:, None])

    mpos = _first_pos(match, jnp.broadcast_to(pos, (n, L)), L)
    found = qv & kv & (mpos < L)
    v_s = mpos + kl + 1
    amp = _first_pos(
        (qc == ord("&")) & (pos >= v_s[:, None]) & in_q,
        jnp.broadcast_to(pos, (n, L)), L)
    v_e = jnp.minimum(amp, ql)

    out_len = jnp.clip(v_e - v_s, 0, L)
    oidx = jnp.clip(v_s[:, None], 0, L) + jnp.arange(L, dtype=i32)[None, :]
    out = jnp.take_along_axis(jnp.pad(qc, ((0, 0), (0, L))),
                              jnp.clip(oidx, 0, 2 * L - 1), axis=1)
    out = jnp.where(pos < out_len[:, None], out, jnp.uint8(0))
    return StringColumn(out, jnp.where(found, out_len, 0), found)
