"""String expression kernels for the string-heavy benchmark config.

The reference repo delegates plain string functions to libcudf (out of
tree); the driver's string/regex-heavy config (BASELINE.md #4) names
``substring`` alongside the in-tree ``regexp`` fast path and
``get_json_object``, so the Spark-exact substring lives here.

Semantics follow Spark's ``UTF8String.substringSQL`` (character-based,
1-based positions, negative position counts from the end, window clamped
to the string):

    substring('abc',  -5, 3) -> 'a'    (window [-2, 1) clamps to [0, 1))
    substring('abcd', -2, 3) -> 'cd'
    substring('abc',   0, 2) -> 'ab'   (pos 0 behaves like 1)
"""

from __future__ import annotations

import jax.numpy as jnp

from ..columnar.column import StringColumn
from .regex_rewrite import _decode_utf8


def left_compact_rows(mat, keep, engine: str = "auto"):
    """Stable left-compaction of kept cells per row; returns
    ``(compacted, counts)`` with the tail beyond each row's count
    zeroed.

    The engine is a hardware fact (same pattern as
    ``parallel.regroup_order``, r5): on CPU (``'scatter'``) a per-row
    counting compaction — rank kept cells with one masked cumsum,
    invert the destination map with ONE scatter — because a ``[n, L]``
    stable sort is XLA-CPU's worst primitive (the argsort formulation
    measured ~630 ms for 16K x 788 bytes in the qstr pipeline; the
    counting path is linear).  On accelerators (``'sort'``) the stable
    argsort stays: sorts lower natively on TPU while per-element
    scatters serialize (BASELINE.md r2 primitive costs).  ``'auto'``
    picks by backend; the explicit names exist for tests and A/Bs.
    """
    import jax

    if engine == "auto":
        engine = "scatter" if jax.default_backend() == "cpu" else "sort"
    if engine not in ("scatter", "sort"):
        raise ValueError(f"unknown compaction engine {engine!r}")
    n, L = mat.shape
    counts = jnp.sum(keep, axis=1).astype(jnp.int32)
    if engine == "scatter":
        ki = keep.astype(jnp.int32)
        within = jnp.cumsum(ki, axis=1) - ki       # rank among kept
        dest = jnp.where(keep, within, L)          # L = discard column
        rows = jnp.arange(n, dtype=jnp.int32)[:, None]
        cols = jnp.broadcast_to(
            jnp.arange(L, dtype=jnp.int32)[None, :], (n, L))
        src = jnp.full((n, L + 1), L, jnp.int32).at[rows, dest].set(
            cols)[:, :L]
        padded = jnp.pad(mat, ((0, 0), (0, 1)))    # col L reads as 0
        out = jnp.take_along_axis(padded, src, axis=1)
    else:
        order = jnp.argsort(~keep, axis=1, stable=True)
        out = jnp.take_along_axis(mat, order, axis=1)
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    out = jnp.where(pos < counts[:, None], out,
                    jnp.zeros((), mat.dtype))
    return out, counts


def substring(col: StringColumn, pos: int, length: int = -1) -> StringColumn:
    """Character-based Spark substring; ``length < 0`` means "to the end".

    Works on the padded byte matrix: UTF-8 start bytes give each byte a
    character index (continuation bytes inherit their start byte's index),
    the [start, end) character window selects bytes, and
    :func:`left_compact_rows` left-compacts the survivors with the
    platform-appropriate engine.
    """
    from ..columnar.bucketed import BucketedStringColumn

    if isinstance(col, BucketedStringColumn):
        return col.apply(lambda b: substring(b, pos, length))
    chars, lengths, validity = col.chars, col.lengths, col.validity
    n, L = chars.shape
    posax = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_str = posax < lengths[:, None]

    _, _, is_start = _decode_utf8(chars)
    is_start = is_start & in_str
    # 0-based character index per byte (continuation bytes inherit)
    char_idx = jnp.cumsum(is_start.astype(jnp.int32), axis=1) - 1
    nchars = jnp.sum(is_start, axis=1).astype(jnp.int32)

    if pos > 0:
        s0 = jnp.full((n,), pos - 1, jnp.int32)
    elif pos < 0:
        s0 = nchars + pos
    else:
        s0 = jnp.zeros((n,), jnp.int32)
    if length < 0:
        e0 = jnp.full((n,), 2**31 - 1, jnp.int32)
    else:
        # window end BEFORE clamping the start (Spark: the negative-start
        # window loses the part hanging off the front of the string)
        e0 = s0 + length
    lo = jnp.maximum(s0, 0)

    keep = in_str & (char_idx >= lo[:, None]) & (char_idx < e0[:, None])
    out, out_len = left_compact_rows(chars, keep)
    return StringColumn(out, jnp.where(validity, out_len, 0), validity)
