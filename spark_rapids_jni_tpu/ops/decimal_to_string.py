"""decimal -> string, Java ``BigDecimal.toString`` rules (non-ANSI).

Reference: ``cast_decimal_to_string.cu:211`` (``decimal_to_non_ansi_string``).
With Spark scale s and digit count D, adjusted exponent a = D - 1 - s:

* s == 0: plain integer.
* s > 0 and a >= -6: ``[-]integer.fraction`` (fraction zero-padded to s).
* otherwise (negative scale or a < -6): scientific ``d[.frac]E±a``.

128-bit digit extraction: base-2^32 schoolbook division by 10^9 (each step
is u64 lane math), five passes -> base-1e9 groups -> per-group digit
unpack.  No 256-bit loops needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column, Decimal128Column, StringColumn

# numpy, not jnp: lazily imported modules must not mint jnp scalars at
# import time — under an active trace they become escaping tracers
_M32 = np.uint64(0xFFFFFFFF)
_BILLION = np.uint64(10**9)
_MAX_DIGITS = 45  # 5 groups of 9 (2^128 has 39 decimal digits)
_WIDTH = 88


def _u128_digits(lo, hi):
    """|value| digit matrix [n, 45] MSB-first + digit count (>= 1)."""
    limbs = [lo & _M32, lo >> 32, hi & _M32, hi >> 32]
    groups = []
    for _ in range(5):
        rem = jnp.zeros_like(lo)
        new = [None] * 4
        for i in range(3, -1, -1):
            cur = (rem << jnp.uint64(32)) | limbs[i]
            new[i] = cur // _BILLION
            rem = cur % _BILLION
        groups.append(rem)  # least-significant group first
        limbs = new
    digs = []
    for g in groups:
        x = g
        for _ in range(9):
            digs.append((x % jnp.uint64(10)).astype(jnp.int32))
            x = x // jnp.uint64(10)
    dig_lsb = jnp.stack(digs, axis=1)  # [n, 45] least-significant first
    nonzero = dig_lsb != 0
    k = jnp.arange(_MAX_DIGITS)[None, :]
    ndigits = jnp.maximum(
        jnp.max(jnp.where(nonzero, k + 1, 0), axis=1), 1
    ).astype(jnp.int32)
    # MSB-first view
    idx = ndigits[:, None] - 1 - k
    dig = jnp.where(
        k < ndigits[:, None],
        jnp.take_along_axis(dig_lsb, jnp.clip(idx, 0, _MAX_DIGITS - 1), axis=1),
        0,
    )
    return dig, ndigits


def decimal_to_string(col: Decimal128Column) -> StringColumn:
    """Spark CAST(decimal AS STRING), non-ANSI (reference
    cast_decimal_to_string.cu:211)."""
    s = col.scale
    limbs = col.limbs
    neg = (limbs[:, 1] >> jnp.uint64(63)) != 0
    # two's-complement abs: ~x + 1, carry into hi exactly when lo was 0
    lo0, hi0 = limbs[:, 0], limbs[:, 1]
    lo = jnp.where(neg, ~lo0 + jnp.uint64(1), lo0)
    hi = jnp.where(neg, ~hi0 + (lo0 == 0).astype(jnp.uint64), hi0)

    dig, nd = _u128_digits(lo, hi)
    n = limbs.shape[0]
    adjusted = nd - 1 - s

    j = jnp.arange(_WIDTH, dtype=jnp.int32)[None, :]
    sign_len = neg.astype(jnp.int32)[:, None]
    p = j - sign_len
    out = jnp.full((n, _WIDTH), ord(" "), jnp.int32)
    out = jnp.where((j == 0) & neg[:, None], ord("-"), out)

    def dig_at(q):
        return jnp.take_along_axis(dig, jnp.clip(q, 0, _MAX_DIGITS - 1), axis=1)

    plain = (s >= 0) & (adjusted >= -6)
    if s == 0:
        m = (p >= 0) & (p < nd[:, None])
        out = jnp.where(m, ord("0") + dig_at(p), out)
        length = sign_len[:, 0] + nd
        chars = out.astype(jnp.uint8)
        chars = jnp.where(j < length[:, None], chars, jnp.uint8(0))
        return StringColumn(chars, length * col.validity, col.validity)

    plain_m = plain[:, None]
    if s > 0:
        # ---- plain layout: int part (nd - s digits, or "0") . frac ------
        ip_digits = jnp.maximum(nd - s, 0)
        ip_len = jnp.maximum(ip_digits, 1)  # "0" when value < 1
        m_int = plain_m & (p >= 0) & (p < ip_len[:, None])
        int_char = jnp.where(
            ip_digits[:, None] == 0, ord("0"), ord("0") + dig_at(p)
        )
        out = jnp.where(m_int, int_char, out)
        out = jnp.where(plain_m & (p == ip_len[:, None]), ord("."), out)
        # fraction: s chars = zero padding (when nd < s) then trailing digits
        fpos = p - ip_len[:, None] - 1
        pad = (s - jnp.minimum(nd, s))[:, None]
        fchar = jnp.where(
            fpos < pad,
            ord("0"),
            ord("0") + dig_at(ip_digits[:, None] + fpos - pad),
        )
        m_frac = plain_m & (fpos >= 0) & (fpos < s)
        out = jnp.where(m_frac, fchar, out)
        len_plain = sign_len[:, 0] + ip_len + 1 + s
    else:
        len_plain = jnp.zeros((n,), jnp.int32)

    # ---- scientific: d[.frac]E±adj --------------------------------------
    msci = ~plain_m
    has_frac = nd > 1
    out = jnp.where(msci & (p == 0), ord("0") + dig[:, 0:1], out)
    out = jnp.where(msci & has_frac[:, None] & (p == 1), ord("."), out)
    spos = p - 2
    m_sf = msci & has_frac[:, None] & (spos >= 0) & (spos < (nd - 1)[:, None])
    out = jnp.where(m_sf, ord("0") + dig_at(1 + spos), out)
    e_at = jnp.where(has_frac, nd + 1, 1)[:, None]
    out = jnp.where(msci & (p == e_at), ord("E"), out)
    out = jnp.where(
        msci & (p == e_at + 1),
        jnp.where((adjusted < 0)[:, None], ord("-"), ord("+")),
        out,
    )
    absA = jnp.abs(adjusted)[:, None]
    a_len = 1 + (absA >= 10)  # |adjusted| < 45 + 38 < 100
    a_digs = jnp.concatenate([absA // 10 % 10, absA % 10], axis=1)
    ap = p - e_at - 2
    m_a = msci & (ap >= 0) & (ap < a_len)
    out = jnp.where(
        m_a,
        ord("0") + jnp.take_along_axis(a_digs, jnp.clip(2 - a_len + ap, 0, 1), axis=1),
        out,
    )
    len_sci = (
        sign_len[:, 0]
        + jnp.where(has_frac, nd + 1, 1)
        + 2
        + a_len[:, 0]
    )

    length = jnp.where(plain, len_plain, len_sci)
    chars = out.astype(jnp.uint8)
    chars = jnp.where(j < length[:, None], chars, jnp.uint8(0))
    return StringColumn(chars, length * col.validity, col.validity)
