"""Spark ``get_json_object`` as a TPU-native char-scan state machine.

Reference: the CUDA thread-per-row pull parser + JSONPath context-stack
evaluator (``/root/reference/src/main/cpp/src/json_parser.cuh``,
``get_json_object.cu:360-788``, semantics also modeled by
``tests/json_oracle.py``).  A thread-per-row branchy parser is the wrong
shape for the VPU, so this is a different machine with the same semantics:

* **One pass, char-level ``lax.scan``** over the padded char matrix: every
  row advances through char column ``j`` in lockstep; the carry holds a
  vectorized tokenizer state (modes, nesting bitstack) fused with the
  JSONPath evaluator state (a [n, 17] context stack of named/index
  containers being evaluated).  All branching is masked vector selects.
* **No byte is written during the scan.**  Each step only records compact
  *emission directives* (which channel emits at this step: a source span,
  a string-content expansion, a float re-format, or the char itself).
  Output bytes materialize afterwards in a fully vectorized gather pass:
  for each output position, binary-search the emitting step, then compute
  the byte as a pure function of the source chars around that step.  This
  is the reference's two-pass size-then-write pattern re-expressed as
  gather-not-scatter (SURVEY.md §7).
* **Float normalization** rides the existing Ryu kernels: float tokens are
  collected into a side buffer, parsed with ``cast_string.string_to_float``
  and re-formatted with Java ``Double.toString`` layout (quoted
  Infinity per ``ftos_converter.cuh:1154-1200``).

Supported paths: the full JSONPath subset of the reference — named
members, array indexes, and wildcards (all 12 evaluator case paths,
including the buffered-child single-wildcard semantics of case 6: a
two-byte ``[',', '[']`` gap is reserved when the wildcard array opens and
its keep flags are patched in at the array's end, once the element count
decides between Hive's bracketed and unwrapped forms).

Spark quirks replicated (all golden-tested against GetJsonObjectTest.java):
single-quoted strings, unescaped control chars, no leading zeros,
"-0" -> "0", number digit cap 1000, nesting cap 64, path cap 16, a
``\\uXXXX`` escape in a field name never matches (json_parser.cuh:983).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import StringColumn
from . import cast_string, float_to_string

MAX_NESTING = 64
MAX_PATH = 16
MAX_NUM_DIGITS = 1000
FLOAT_W = 26  # max formatted double width ("-2.2250738585072014E-308")

# ---------------------------------------------------------------------------
# tokenizer modes (carry `mode`)
# ---------------------------------------------------------------------------
M_VALUE = 0      # expecting start of a value (ws allowed)
M_STR = 1        # inside string content
M_ESC = 2        # after backslash
M_UHEX = 3       # inside \uXXXX hex run (ucnt counts)
M_NUM_SIGN = 4   # after leading '-'
M_NUM_LZ = 5     # after leading '0'
M_NUM_INT = 6    # in integer digits
M_NUM_DOT = 7    # just after '.'
M_NUM_FRAC = 8   # in fraction digits
M_NUM_E = 9      # just after e/E
M_NUM_ESIGN = 10  # after exponent sign
M_NUM_EXP = 11   # in exponent digits
M_LIT = 12       # inside true/false/null
M_AFTER = 13     # after a complete value (expect , ] } or eof)
M_FIELD = 14     # expecting field-name quote (ws allowed)
M_COLON = 15     # expecting ':' (ws allowed)
M_DONE = 16      # top-level value complete (trailing bytes ignored)
M_ERR = 17

# value/field events (phase A)
EV_NONE = 0
EV_STR = 1
EV_NUM = 2
EV_TRUE = 3
EV_FALSE = 4
EV_NULL = 5
EV_SOBJ = 6
EV_SARR = 7
EV_FIELD = 8

# end events (phase B)
EB_NONE = 0
EB_EOBJ = 1
EB_EARR = 2

# evaluator row modes
EVM_NORM = 0
EVM_COPY = 1
EVM_SKIP = 2

# context kinds (the reference's case-path numbers) / wait states
K2 = 2      # case 2: matched FLATTEN array — iterate, no brackets
K_OBJ = 4   # case 4: object, named instruction
K5 = 5      # case 5: double wildcard — '[' + flatten children
K6 = 6      # case 6: single wildcard, raw/flatten — buffered child + gap
K7 = 7      # case 7: single wildcard, quoted — '[' + quoted children
K_ARR = 9   # cases 8/9: array, index instruction (8 = quoted child style)
W_FIELDSCAN = 0   # scanning fields for the named match
W_SKIPVAL = 1     # consuming the value of a non-matching field
W_VALUE = 2       # next value event is the matched target
W_SKIPREST = 3    # skipping to this container's end
W_IDX = 4         # skipping cnt more elements; cnt==0 -> next value is target
W_ELEMS = 5       # array iteration: every element is evaluated

# write styles (reference write_style RAW/QUOTED/FLATTEN)
S_RAW = 0
S_QUOTED = 1
S_FLATTEN = 2

# string-content emission flags (per step)
SF_NONE = 0
SF_CONTENT = 1   # plain string content char
SF_ESCCHAR = 2   # the char after a backslash
SF_UHEXLAST = 3  # 4th hex digit of \uXXXX: emits the decoded UTF-8
SF_QUOTE = 4     # open/close quote emitting '"' (escaped style only)

# path instruction types
P_NAMED = 0
P_INDEX = 1
P_WILD = 2

# numpy, not jnp: module scope must not mint device arrays (GL001) — this
# module is imported lazily, and a jnp constant created under an active
# trace escapes as a tracer (the PR 2 decimal bug)
_LIT_TABLE = np.asarray(
    [list(b"true\x00"), list(b"false"), list(b"null\x00")], dtype=np.uint8
)
_LIT_LEN = np.asarray([4, 5, 4], dtype=np.int32)


def parse_path(path: str):
    """'$.a[3].b' -> instruction tuples (same surface as JSONUtils.java)."""
    out = []
    i = 0
    if path.startswith("$"):
        i = 1
    while i < len(path):
        c = path[i]
        if c == ".":
            i += 1
            j = i
            while j < len(path) and path[j] not in ".[":
                j += 1
            name = path[i:j]
            out.append(("wildcard",) if name == "*" else ("named", name.encode()))
            i = j
        elif c == "[":
            j = path.index("]", i)
            inner = path[i + 1: j].strip()
            if inner == "*":
                out.append(("wildcard",))
            elif inner.startswith("'"):
                out.append(("named", inner.strip("'").encode()))
            else:
                out.append(("index", int(inner)))
            i = j + 1
        else:
            raise ValueError(f"bad JSONPath {path!r} at offset {i}")
    return out


def _pack_path(instructions):
    """Host: instruction tuples -> (types[P], indexes[P], names[P,W], nlen[P])."""
    if len(instructions) > MAX_PATH:
        raise ValueError(f"path deeper than {MAX_PATH}")
    types, indexes, names = [], [], []
    for ins in instructions:
        if ins[0] == "named":
            types.append(P_NAMED)
            indexes.append(0)
            names.append(ins[1])
        elif ins[0] == "index":
            types.append(P_INDEX)
            indexes.append(int(ins[1]))
            names.append(b"")
        elif ins[0] == "wildcard":
            types.append(P_WILD)
            indexes.append(0)
            names.append(b"")
        else:
            raise ValueError(f"unknown path instruction {ins!r}")
    P = max(1, len(instructions))
    W = max(1, max((len(nm) for nm in names), default=1))
    import numpy as np

    t = np.zeros((P,), np.int32)
    ix = np.zeros((P,), np.int32)
    nc = np.zeros((P, W), np.uint8)
    nl = np.zeros((P,), np.int32)
    for k, (ty, iv, nm) in enumerate(zip(types, indexes, names)):
        t[k] = ty
        ix[k] = iv
        nc[k, : len(nm)] = np.frombuffer(nm, np.uint8)
        nl[k] = len(nm)
    return (jnp.asarray(t), jnp.asarray(ix), jnp.asarray(nc), jnp.asarray(nl),
            len(instructions))


# ---------------------------------------------------------------------------
# the scan step
# ---------------------------------------------------------------------------

def _step(P, ptypes, pindexes, pnames, pnamelens, carry, xs):
    """One char column for all rows.  Pure masked-vector logic."""
    (j, c) = xs
    st = dict(carry)
    n = c.shape[0]
    i32 = jnp.int32

    alive = (j <= st["length"]) & (st["mode"] != M_ERR) & (st["mode"] != M_DONE)
    at_eof = j == st["length"]
    mode = st["mode"]

    is_ws = (c == 32) | (c == 9) | (c == 10) | (c == 13)
    is_digit = (c >= ord("0")) & (c <= ord("9"))
    is_hex = is_digit | ((c >= 65) & (c <= 70)) | ((c >= 97) & (c <= 102))
    in_obj_bit = _stack_top(st["cstack_lo"], st["cstack_hi"], st["depth"])

    # ---- 1. number completion (shares its step with the delimiter char) --
    num_modes = (mode >= M_NUM_SIGN) & (mode <= M_NUM_EXP)
    num_cont = jnp.where(
        mode == M_NUM_SIGN, is_digit,
        jnp.where(mode == M_NUM_LZ, (c == ord(".")) | (c == ord("e")) | (c == ord("E")),
        jnp.where(mode == M_NUM_INT,
                  is_digit | (c == ord(".")) | (c == ord("e")) | (c == ord("E")),
        jnp.where(mode == M_NUM_DOT, is_digit,
        jnp.where(mode == M_NUM_FRAC,
                  is_digit | (c == ord("e")) | (c == ord("E")),
        jnp.where(mode == M_NUM_E, is_digit | (c == ord("+")) | (c == ord("-")),
        jnp.where(mode == M_NUM_ESIGN, is_digit,
                  is_digit)))))))  # M_NUM_EXP
    num_cont = num_cont & ~at_eof
    # a digit directly after a leading zero is a tokenize error ("01"),
    # not a completed "0" token (try_unsigned_number, json_parser.cuh:1076)
    lz_digit_err = alive & (mode == M_NUM_LZ) & is_digit & ~at_eof
    num_completes = alive & num_modes & ~num_cont & ~lz_digit_err
    num_ok_state = (
        (mode == M_NUM_LZ) | (mode == M_NUM_INT) | (mode == M_NUM_FRAC)
        | (mode == M_NUM_EXP)
    )
    num_valid = num_completes & num_ok_state & (st["ndig"] <= MAX_NUM_DIGITS)
    num_err = (num_completes & ~(num_ok_state & (st["ndig"] <= MAX_NUM_DIGITS))
               | lz_digit_err)
    # after a valid number the delimiter char is processed in M_AFTER below
    eff_mode = jnp.where(num_valid, i32(M_AFTER), mode)

    ev_a = jnp.where(num_valid, i32(EV_NUM), i32(EV_NONE))
    ev_num_float = st["numf"]
    ev_span_start = st["tok_start"]
    ev_span_len = j - st["tok_start"]
    err = num_err

    # ---- 2. per-mode tokenizer transitions ------------------------------
    new_mode = eff_mode
    new_depth = st["depth"]
    clo, chi = st["cstack_lo"], st["cstack_hi"]
    new_allow_close = st["allow_close"]
    new_quote = st["quote"]
    new_sfield = st["sfield"]
    new_tok = st["tok_start"]
    new_ndig = st["ndig"]
    new_numf = st["numf"]
    new_ucnt = st["ucnt"]
    new_lid = st["lit_id"]
    new_lpos = st["lit_pos"]
    ev_b = jnp.zeros((n,), i32)

    # -- M_VALUE: value start ------------------------------------------
    mv = alive & (eff_mode == M_VALUE) & ~at_eof
    open_obj = mv & (c == ord("{"))
    open_arr = mv & (c == ord("["))
    depth_ok = st["depth"] < MAX_NESTING
    ev_a = jnp.where(open_obj & depth_ok, i32(EV_SOBJ), ev_a)
    ev_a = jnp.where(open_arr & depth_ok, i32(EV_SARR), ev_a)
    err = err | ((open_obj | open_arr) & ~depth_ok)
    push = (open_obj | open_arr) & depth_ok
    clo, chi = _stack_push(clo, chi, st["depth"], open_obj, push)
    new_depth = jnp.where(push, st["depth"] + 1, new_depth)
    # after '{' expect field-or-'}'; after '[' expect value-or-']'
    new_mode = jnp.where(open_obj & depth_ok, i32(M_FIELD), new_mode)
    new_mode = jnp.where(open_arr & depth_ok, i32(M_VALUE), new_mode)
    new_allow_close = jnp.where(push, True, new_allow_close)

    sq = mv & ((c == ord('"')) | (c == ord("'")))
    new_mode = jnp.where(sq, i32(M_STR), new_mode)
    new_quote = jnp.where(sq, c, new_quote)
    new_sfield = jnp.where(sq, False, new_sfield)
    new_tok = jnp.where(sq, j, new_tok)

    lit = mv & ((c == ord("t")) | (c == ord("f")) | (c == ord("n")))
    new_mode = jnp.where(lit, i32(M_LIT), new_mode)
    new_lid = jnp.where(
        lit, jnp.where(c == ord("t"), 0, jnp.where(c == ord("f"), 1, 2)), new_lid
    )
    new_lpos = jnp.where(lit, 1, new_lpos)
    new_tok = jnp.where(lit, j, new_tok)

    num0 = mv & ((c == ord("-")) | is_digit)
    new_mode = jnp.where(
        num0,
        jnp.where(c == ord("-"), i32(M_NUM_SIGN),
                  jnp.where(c == ord("0"), i32(M_NUM_LZ), i32(M_NUM_INT))),
        new_mode,
    )
    new_tok = jnp.where(num0, j, new_tok)
    new_ndig = jnp.where(num0, jnp.where(is_digit, 1, 0), new_ndig)
    new_numf = jnp.where(num0, False, new_numf)

    arr_close = mv & (c == ord("]")) & st["allow_close"] & (st["depth"] > 0) & ~in_obj_bit
    ev_b = jnp.where(arr_close, i32(EB_EARR), ev_b)
    new_depth = jnp.where(arr_close, st["depth"] - 1, new_depth)
    new_mode = jnp.where(arr_close, i32(M_AFTER), new_mode)

    bad_v = mv & ~(is_ws | open_obj | open_arr | sq | lit | num0 | arr_close)
    err = err | bad_v

    # -- M_FIELD: field-name start (or immediate '}') ------------------
    mf = alive & (eff_mode == M_FIELD) & ~at_eof
    fq = mf & ((c == ord('"')) | (c == ord("'")))
    new_mode = jnp.where(fq, i32(M_STR), new_mode)
    new_quote = jnp.where(fq, c, new_quote)
    new_sfield = jnp.where(fq, True, new_sfield)
    new_tok = jnp.where(fq, j, new_tok)
    obj_close = mf & (c == ord("}")) & st["allow_close"] & (st["depth"] > 0) & in_obj_bit
    ev_b = jnp.where(obj_close, i32(EB_EOBJ), ev_b)
    new_depth = jnp.where(obj_close, st["depth"] - 1, new_depth)
    new_mode = jnp.where(obj_close, i32(M_AFTER), new_mode)
    err = err | (mf & ~(is_ws | fq | obj_close))
    # field-match trackers reset at field start
    new_fmok = jnp.where(fq, True, st["fm_ok"])
    new_fmpos = jnp.where(fq, 0, st["fm_pos"])

    # -- M_COLON --------------------------------------------------------
    mc = alive & (eff_mode == M_COLON) & ~at_eof
    col = mc & (c == ord(":"))
    new_mode = jnp.where(col, i32(M_VALUE), new_mode)
    new_allow_close = jnp.where(col, False, new_allow_close)
    err = err | (mc & ~(is_ws | col))

    # -- M_AFTER: between values ---------------------------------------
    ma = alive & (eff_mode == M_AFTER) & ~at_eof
    top = ma & (st["depth"] == 0)
    # trailing content after the root value is ignored (reference SUCCESS)
    new_mode = jnp.where(top & ~is_ws, i32(M_DONE), new_mode)
    comma = ma & ~top & (c == ord(","))
    new_mode = jnp.where(comma, jnp.where(in_obj_bit, i32(M_FIELD), i32(M_VALUE)),
                         new_mode)
    new_allow_close = jnp.where(comma, False, new_allow_close)
    close_o = ma & ~top & (c == ord("}")) & in_obj_bit
    close_a = ma & ~top & (c == ord("]")) & ~in_obj_bit
    ev_b = jnp.where(close_o, i32(EB_EOBJ), jnp.where(close_a, i32(EB_EARR), ev_b))
    new_depth = jnp.where(close_o | close_a, st["depth"] - 1, new_depth)
    new_mode = jnp.where(close_o | close_a, i32(M_AFTER), new_mode)
    err = err | (ma & ~top & ~(is_ws | comma | close_o | close_a))

    # -- M_STR / M_ESC / M_UHEX ----------------------------------------
    ms = alive & (eff_mode == M_STR) & ~at_eof
    quote_close = ms & (c == st["quote"])
    backslash = ms & (c == 0x5C)
    content = ms & ~quote_close & ~backslash
    new_mode = jnp.where(backslash, i32(M_ESC), new_mode)
    new_mode = jnp.where(quote_close & st["sfield"], i32(M_COLON), new_mode)
    new_mode = jnp.where(quote_close & ~st["sfield"], i32(M_AFTER), new_mode)
    ev_a = jnp.where(quote_close,
                     jnp.where(st["sfield"], i32(EV_FIELD), i32(EV_STR)), ev_a)
    ev_span_start = jnp.where(quote_close, st["tok_start"], ev_span_start)
    ev_span_len = jnp.where(quote_close, j + 1 - st["tok_start"], ev_span_len)

    me = alive & (eff_mode == M_ESC) & ~at_eof
    esc_short = me & (
        (c == ord('"')) | (c == ord("'")) | (c == 0x5C) | (c == ord("/"))
        | (c == ord("b")) | (c == ord("f")) | (c == ord("n")) | (c == ord("r"))
        | (c == ord("t"))
    )
    esc_u = me & (c == ord("u"))
    new_mode = jnp.where(esc_short, i32(M_STR), new_mode)
    new_mode = jnp.where(esc_u, i32(M_UHEX), new_mode)
    new_ucnt = jnp.where(esc_u, 0, new_ucnt)
    err = err | (me & ~(esc_short | esc_u))

    mu = alive & (eff_mode == M_UHEX) & ~at_eof
    uhex_ok = mu & is_hex
    new_ucnt = jnp.where(uhex_ok, st["ucnt"] + 1, new_ucnt)
    uhex_done = uhex_ok & (st["ucnt"] == 3)
    new_mode = jnp.where(uhex_done, i32(M_STR), new_mode)
    err = err | (mu & ~is_hex)

    # -- M_LIT ----------------------------------------------------------
    ml = alive & (eff_mode == M_LIT) & ~at_eof
    expected = jnp.asarray(_LIT_TABLE)[st["lit_id"], jnp.minimum(st["lit_pos"], 4)]
    lit_ok = ml & (c == expected)
    new_lpos = jnp.where(lit_ok, st["lit_pos"] + 1, new_lpos)
    lit_done = lit_ok & (st["lit_pos"] + 1 == jnp.asarray(_LIT_LEN)[st["lit_id"]])
    new_mode = jnp.where(lit_done, i32(M_AFTER), new_mode)
    ev_a = jnp.where(
        lit_done,
        jnp.where(st["lit_id"] == 0, i32(EV_TRUE),
                  jnp.where(st["lit_id"] == 1, i32(EV_FALSE), i32(EV_NULL))),
        ev_a,
    )
    ev_span_start = jnp.where(lit_done, st["tok_start"], ev_span_start)
    ev_span_len = jnp.where(lit_done, j + 1 - st["tok_start"], ev_span_len)
    err = err | (ml & ~lit_ok)

    # -- number digit / float tracking ---------------------------------
    mnum = alive & num_modes & num_cont
    new_ndig = jnp.where(mnum & is_digit, st["ndig"] + 1, new_ndig)
    new_numf = jnp.where(
        mnum & ((c == ord(".")) | (c == ord("e")) | (c == ord("E"))),
        True, new_numf)
    new_mode = jnp.where(
        mnum,
        jnp.where(
            (eff_mode == M_NUM_SIGN),
            jnp.where(c == ord("0"), i32(M_NUM_LZ), i32(M_NUM_INT)),
        jnp.where(
            (eff_mode == M_NUM_LZ) | (eff_mode == M_NUM_INT),
            jnp.where(c == ord("."), i32(M_NUM_DOT),
            jnp.where((c == ord("e")) | (c == ord("E")), i32(M_NUM_E),
                      i32(M_NUM_INT))),
        jnp.where(
            (eff_mode == M_NUM_DOT) | (eff_mode == M_NUM_FRAC),
            jnp.where(is_digit, i32(M_NUM_FRAC), i32(M_NUM_E)),
        jnp.where(
            eff_mode == M_NUM_E,
            jnp.where(is_digit, i32(M_NUM_EXP), i32(M_NUM_ESIGN)),
            i32(M_NUM_EXP))))),
        new_mode,
    )

    # -- EOF ------------------------------------------------------------
    eof_live = alive & at_eof
    eof_ok = eof_live & (
        ((eff_mode == M_AFTER) | (eff_mode == M_DONE)) & (new_depth == 0)
    )
    new_mode = jnp.where(eof_ok, i32(M_DONE), new_mode)
    err = err | (eof_live & ~eof_ok)

    err = err & alive
    new_mode = jnp.where(err, i32(M_ERR), new_mode)

    # ======================================================================
    # evaluator (the reference's 12 case paths, re-expressed as wait-state
    # transitions on a per-row context stack — see module docstring)
    # ======================================================================
    ev_alive = ~st["ev_done"] & ~st["ev_fail"]
    tok_err = err & ev_alive  # tokenizer error while still evaluating
    evnorm = ev_alive & (st["evm"] == EVM_NORM)
    lvl = st["depth"]  # container level for start events (level it occupies)

    sp = st["sp"]
    D = st["k_kind"].shape[1]
    slot = jnp.arange(D, dtype=i32)[None, :]
    top_sel = slot == (sp - 1)[:, None]

    def top_get(a):
        return jnp.where(top_sel, a, 0).sum(axis=1).astype(a.dtype)

    top_kind = top_get(st["k_kind"])
    top_wait = top_get(st["k_wait"])
    top_cpi = top_get(st["k_cpi"])
    top_cnt = top_get(st["k_cnt"])
    top_depth = top_get(st["k_depth"])
    top_chstyle = top_get(st["k_chstyle"])
    top_sadep = top_get(st["k_sadep"])
    top_sempty = top_get(st["k_sempty"])
    top_gap = top_get(st["k_gap"])

    has_ctx = sp > 0
    # who expects the next value event, at what path offset, in what style?
    expect_skip = has_ctx & (
        (top_wait == W_SKIPVAL)
        | ((top_wait == W_IDX) & (top_cnt > 0))
        | (top_wait == W_SKIPREST)
    )
    child_pi = jnp.where(has_ctx, top_cpi, 0)
    child_style = jnp.where(has_ctx, top_chstyle, i32(S_RAW))
    matched = child_pi >= P  # path fully consumed at this value
    expect_target = ~expect_skip & (
        ~has_ctx & st["root_wait"]
        | (has_ctx & ((top_wait == W_VALUE) | (top_wait == W_ELEMS)
                      | ((top_wait == W_IDX) & (top_cnt == 0))))
    )

    is_valev = (ev_a >= EV_STR) & (ev_a <= EV_SARR)
    is_term = (ev_a >= EV_STR) & (ev_a <= EV_NULL)
    is_cont = (ev_a == EV_SOBJ) | (ev_a == EV_SARR)
    valev = evnorm & is_valev

    upd = {
        "ev_done": st["ev_done"], "ev_fail": st["ev_fail"],
        "root_dirty": st["root_dirty"], "root_wait": st["root_wait"],
        "k_kind": st["k_kind"], "k_wait": st["k_wait"], "k_cpi": st["k_cpi"],
        "k_cnt": st["k_cnt"], "k_depth": st["k_depth"],
        "k_dirty": st["k_dirty"], "k_chstyle": st["k_chstyle"],
        "k_sadep": st["k_sadep"], "k_sempty": st["k_sempty"],
        "k_gap": st["k_gap"], "sp": sp, "evm": st["evm"],
        "base_depth": st["base_depth"],
        "g_adep": st["g_adep"], "g_empty": st["g_empty"],
    }
    upd["root_wait"] = jnp.where(valev, False, upd["root_wait"])

    # generator comma state at step entry (json_generator.need_comma)
    gnc = (st["g_adep"] > 0) & ~st["g_empty"]

    # ---- value_done bookkeeping (shared by several paths) -------------
    # routing of a completed child value's dirty onto the expecting slot:
    #  root         -> root_dirty=d, ev_done
    #  W_VALUE      -> ctx.dirty+=d; d>0 ? wait=W_SKIPREST : row fail (case 4)
    #  W_IDX cnt==0 -> ctx.dirty+=d; wait=W_SKIPREST              (case 8/9)
    #  W_ELEMS      -> ctx.dirty+=d                           (cases 2/5/6/7)
    def value_done(cond, d, sel, waits, hasc):
        root_done = cond & ~hasc
        upd["ev_done"] = upd["ev_done"] | root_done
        upd["root_dirty"] = jnp.where(root_done, d, upd["root_dirty"])
        on_value = cond & hasc & (waits == W_VALUE)
        upd["ev_fail"] = upd["ev_fail"] | (on_value & (d == 0))
        on_idx = cond & hasc & (waits == W_IDX)
        on_elems = cond & hasc & (waits == W_ELEMS)
        dm = (on_value | on_idx | on_elems)[:, None] & sel
        upd["k_dirty"] = jnp.where(dm, upd["k_dirty"] + d[:, None],
                                   upd["k_dirty"])
        wm = (on_value | on_idx)[:, None] & sel
        upd["k_wait"] = jnp.where(wm, i32(W_SKIPREST), upd["k_wait"])

    # ---- terminal values under NORM -----------------------------------
    term = valev & is_term
    # a null target under a matched *field* fails the whole row (case 4's
    # "meets null token" check); elsewhere null is a copyable value
    null_fail = term & (ev_a == EV_NULL) & has_ctx & (top_wait == W_VALUE) \
        & ~expect_skip
    upd["ev_fail"] = upd["ev_fail"] | null_fail
    # skip-expectant: consume silently
    t_skip = term & expect_skip
    sv = t_skip & (top_wait == W_SKIPVAL)
    si = t_skip & (top_wait == W_IDX)
    upd["k_wait"] = jnp.where(sv[:, None] & top_sel, i32(W_FIELDSCAN),
                              upd["k_wait"])
    upd["k_cnt"] = jnp.where(si[:, None] & top_sel, upd["k_cnt"] - 1,
                             upd["k_cnt"])
    # target terminal: dirty = matched (unmatched leftover path over a
    # terminal is reference case 12 -> dirty 0)
    t_tgt = term & expect_target & ~null_fail
    value_done(t_tgt, (t_tgt & matched).astype(i32), top_sel, top_wait,
               has_ctx)

    # ---- container values under NORM ----------------------------------
    cont = valev & is_cont
    c_skip = cont & expect_skip
    upd["evm"] = jnp.where(c_skip, i32(EVM_SKIP), upd["evm"])
    upd["base_depth"] = jnp.where(c_skip, lvl, upd["base_depth"])
    c_tgt = cont & expect_target
    # matched FLATTEN array -> case 2 (iterate without brackets);
    # any other matched container -> escaped verbatim copy (case 3)
    c_flat = c_tgt & matched & (ev_a == EV_SARR) & (child_style == S_FLATTEN)
    c_copy = c_tgt & matched & ~c_flat
    upd["evm"] = jnp.where(c_copy, i32(EVM_COPY), upd["evm"])
    upd["base_depth"] = jnp.where(c_copy, lvl, upd["base_depth"])
    # descend: dispatch the next path instruction (cases 4,5,6,7,8,9,12)
    c_desc = c_tgt & ~matched
    pmax = ptypes.shape[0] - 1
    ins_t = ptypes[jnp.clip(child_pi, 0, pmax)]
    ins_ix = pindexes[jnp.clip(child_pi, 0, pmax)]
    has2 = child_pi + 1 < P
    ins2_w = has2 & (ptypes[jnp.clip(child_pi + 1, 0, pmax)] == P_WILD)
    p4 = c_desc & (ev_a == EV_SOBJ) & (ins_t == P_NAMED)
    p5 = c_desc & (ev_a == EV_SARR) & (ins_t == P_WILD) & ins2_w
    p6 = (c_desc & (ev_a == EV_SARR) & (ins_t == P_WILD) & ~ins2_w
          & (child_style != S_QUOTED))
    p7 = (c_desc & (ev_a == EV_SARR) & (ins_t == P_WILD) & ~ins2_w
          & (child_style == S_QUOTED))
    p8 = c_desc & (ev_a == EV_SARR) & (ins_t == P_INDEX) & ins2_w
    p9 = c_desc & (ev_a == EV_SARR) & (ins_t == P_INDEX) & ~ins2_w
    mismatch = c_desc & ~(p4 | p5 | p6 | p7 | p8 | p9)
    upd["evm"] = jnp.where(mismatch, i32(EVM_SKIP), upd["evm"])
    upd["base_depth"] = jnp.where(mismatch, lvl, upd["base_depth"])
    # (a mismatched target skip routes as value_done(0) at skip exit)

    do_push = p4 | p5 | p6 | p7 | p8 | p9 | c_flat
    new_sel = slot == sp[:, None]
    pushm = do_push[:, None] & new_sel
    kind = jnp.where(p4, K_OBJ, jnp.where(p5, K5, jnp.where(p6, K6,
           jnp.where(p7, K7, jnp.where(c_flat, K2, K_ARR)))))
    wait0 = jnp.where(p4, W_FIELDSCAN,
            jnp.where(p8 | p9, W_IDX, W_ELEMS))
    cpi0 = jnp.where(p5, child_pi + 2,
           jnp.where(c_flat, child_pi, child_pi + 1))
    chst0 = jnp.where(p4 | p9, child_style,
            jnp.where(p6, jnp.where(child_style == S_RAW, S_QUOTED, S_FLATTEN),
            jnp.where(p7 | p8, i32(S_QUOTED), i32(S_FLATTEN))))  # 2/5: FLATTEN
    upd["k_kind"] = jnp.where(pushm, kind[:, None], upd["k_kind"])
    upd["k_wait"] = jnp.where(pushm, wait0[:, None], upd["k_wait"])
    upd["k_cpi"] = jnp.where(pushm, cpi0[:, None], upd["k_cpi"])
    upd["k_cnt"] = jnp.where(pushm, ins_ix[:, None], upd["k_cnt"])
    upd["k_depth"] = jnp.where(pushm, lvl[:, None], upd["k_depth"])
    upd["k_dirty"] = jnp.where(pushm, 0, upd["k_dirty"])
    upd["k_chstyle"] = jnp.where(pushm, chst0[:, None], upd["k_chstyle"])
    upd["sp"] = jnp.where(do_push, sp + 1, upd["sp"])
    # case 5/7 write their '[' at first enter (with parent comma)
    open_arr57 = p5 | p7
    upd["g_adep"] = jnp.where(open_arr57, st["g_adep"] + 1, upd["g_adep"])
    upd["g_empty"] = jnp.where(open_arr57, True, upd["g_empty"])
    # case 6: buffer child output behind a 2-byte gap [',', '['] whose keep
    # flags resolve at END (write_child_raw_value's insert logic)
    upd["k_sadep"] = jnp.where(pushm & p6[:, None], st["g_adep"][:, None],
                               upd["k_sadep"])
    upd["k_sempty"] = jnp.where(pushm & p6[:, None], st["g_empty"][:, None],
                                upd["k_sempty"])
    upd["k_gap"] = jnp.where(pushm & p6[:, None], j, upd["k_gap"])
    upd["g_adep"] = jnp.where(p6, 1, upd["g_adep"])
    upd["g_empty"] = jnp.where(p6, True, upd["g_empty"])

    # ---- FIELD events ---------------------------------------------------
    fieldev = evnorm & (ev_a == EV_FIELD) & has_ctx & (top_wait == W_FIELDSCAN)
    name_ins = jnp.clip(top_cpi - 1, 0, pmax)  # case 4's own instruction
    name_match = st["fm_ok"] & (st["fm_pos"] == pnamelens[name_ins])
    upd["k_wait"] = jnp.where(
        (fieldev & name_match)[:, None] & top_sel, i32(W_VALUE), upd["k_wait"])
    upd["k_wait"] = jnp.where(
        (fieldev & ~name_match)[:, None] & top_sel, i32(W_SKIPVAL),
        upd["k_wait"])

    # ---- field-name matching accumulators (during string scan) ---------
    scanning_field = ev_alive & (st["evm"] == EVM_NORM) & st["sfield"] \
        & has_ctx & (top_wait == W_FIELDSCAN)
    nm_w = pnames.shape[1]
    want = pnames[name_ins, jnp.clip(st["fm_pos"], 0, nm_w - 1)]
    unit_raw = scanning_field & content
    dec = jnp.where(c == ord("b"), 8,
          jnp.where(c == ord("f"), 12,
          jnp.where(c == ord("n"), 10,
          jnp.where(c == ord("r"), 13,
          jnp.where(c == ord("t"), 9, c))))).astype(jnp.uint8)
    unit_esc = scanning_field & me & esc_short
    unit = jnp.where(unit_esc, dec, c)
    has_unit = unit_raw | unit_esc
    ok_unit = has_unit & (st["fm_pos"] < pnamelens[name_ins]) & (unit == want)
    new_fmok2 = jnp.where(has_unit & ~ok_unit, False, new_fmok)
    # the reference never matches a field containing a \uXXXX escape
    new_fmok2 = jnp.where(scanning_field & esc_u, False, new_fmok2)
    new_fmpos2 = jnp.where(has_unit, new_fmpos + 1, new_fmpos)

    # ---- phase B: END events under NORM --------------------------------
    # A number can complete on the same char as its container's close
    # (phase A then phase B in one step), so wait/dirty must be read AFTER
    # phase A's updates.
    top_wait_b = jnp.where(top_sel, upd["k_wait"], 0).sum(axis=1).astype(i32)
    top_dirty_b = jnp.where(top_sel, upd["k_dirty"], 0).sum(axis=1).astype(i32)
    endev = evnorm & (ev_b != EB_NONE)
    lvl_closed = new_depth  # after decrement == level of the closed container
    on_top = endev & has_ctx & (top_depth == lvl_closed)
    # case 8/9 W_IDX: array ended before the target index -> row fails
    upd["ev_fail"] = upd["ev_fail"] | (on_top & (top_kind == K_ARR)
                                       & (top_wait_b == W_IDX))
    iter_kind = (top_kind == K2) | (top_kind == K5) | (top_kind == K6) \
        | (top_kind == K7)
    # case 6 finishing with nothing written: reference leaves the context
    # unfinished and errors out on the next dispatch -> row is null
    end6 = on_top & (top_kind == K6)
    upd["ev_fail"] = upd["ev_fail"] | (end6 & (top_dirty_b == 0))
    pop = on_top & (
        ((top_kind == K_OBJ) & ((top_wait_b == W_FIELDSCAN)
                                | (top_wait_b == W_SKIPREST)))
        | ((top_kind == K_ARR) & (top_wait_b == W_SKIPREST))
        | iter_kind
    )
    # case 5/7 close their bracket; case 6 commits its buffered child
    end57 = on_top & ((top_kind == K5) | (top_kind == K7))
    upd["g_adep"] = jnp.where(end57, upd["g_adep"] - 1, upd["g_adep"])
    upd["g_empty"] = jnp.where(end57, False, upd["g_empty"])
    par_nc = (top_sadep > 0) & ~(top_sempty != 0)
    commit6 = end6 & (top_dirty_b > 0)
    upd["g_adep"] = jnp.where(commit6, top_sadep, upd["g_adep"])
    upd["g_empty"] = jnp.where(commit6, False, upd["g_empty"])
    patch_valid = commit6
    patch_tgt = jnp.where(commit6, top_gap, -1)
    patch_k0 = commit6 & par_nc
    patch_k1 = commit6 & (top_dirty_b > 1)

    pop_dirty = jnp.where(pop, top_dirty_b, 0)
    upd["sp"] = jnp.where(pop, upd["sp"] - 1, upd["sp"])
    # route the popped dirty to the NEW top (the expecting slot below)
    sp2 = upd["sp"]
    top_sel2 = slot == (sp2 - 1)[:, None]
    has_ctx2 = sp2 > 0
    top_wait2 = jnp.where(top_sel2, upd["k_wait"], 0).sum(axis=1).astype(i32)

    value_done(pop, pop_dirty, top_sel2, top_wait2, has_ctx2)

    # ---- COPY / SKIP mode exits ----------------------------------------
    inmode = ev_alive & (st["evm"] != EVM_NORM)
    mode_exit = inmode & (ev_b != EB_NONE) & (new_depth == st["base_depth"])
    exit_copy = mode_exit & (st["evm"] == EVM_COPY)
    exit_skip = mode_exit & (st["evm"] == EVM_SKIP)
    upd["evm"] = jnp.where(mode_exit, i32(EVM_NORM), upd["evm"])
    # copy completion = value_done(1) on the expecting slot
    value_done(exit_copy, exit_copy.astype(i32), top_sel2, top_wait2,
               has_ctx2)
    # skip completion: route by the expecting slot's wait state
    sk_v = exit_skip & has_ctx2 & (top_wait2 == W_SKIPVAL)
    upd["k_wait"] = jnp.where(sk_v[:, None] & top_sel2, i32(W_FIELDSCAN),
                              upd["k_wait"])
    sk_i = exit_skip & has_ctx2 & (top_wait2 == W_IDX)
    sk_i_consume = sk_i & (jnp.where(top_sel2, upd["k_cnt"], 0).sum(axis=1) > 0)
    upd["k_cnt"] = jnp.where(sk_i_consume[:, None] & top_sel2,
                             upd["k_cnt"] - 1, upd["k_cnt"])
    # skip of a mismatched target (case 12) -> value_done(0)
    sk_tgt = exit_skip & (sk_i & ~sk_i_consume
                          | (has_ctx2 & ((top_wait2 == W_VALUE)
                                         | (top_wait2 == W_ELEMS)))
                          | ~has_ctx2)
    value_done(sk_tgt, jnp.zeros((n,), i32), top_sel2, top_wait2, has_ctx2)

    upd["ev_fail"] = upd["ev_fail"] | tok_err

    # ======================================================================
    # emissions
    # ======================================================================
    copying = ev_alive & (st["evm"] == EVM_COPY)
    # matched terminal starting now? set per-char emit flags for str/lit
    t_str_start = evnorm & sq & expect_target & matched & ~expect_skip
    t_lit_start = evnorm & lit & expect_target & matched & ~expect_skip
    new_term_emit = st["term_emit"]
    new_term_emit = jnp.where(t_str_start | t_lit_start, True, new_term_emit)
    new_term_emit = jnp.where(quote_close | lit_done, False, new_term_emit)
    term_emitting = st["term_emit"] | t_str_start | t_lit_start
    # terminal style: RAW -> bare/unescaped (case 1); QUOTED/FLATTEN ->
    # escaped with quotes (case 3 on a terminal)
    t_esc_now = child_style != S_RAW
    new_term_esc = jnp.where(t_str_start | t_lit_start, t_esc_now,
                             st["term_esc"])
    term_esc = jnp.where(t_str_start | t_lit_start, t_esc_now,
                         st["term_esc"])

    in_str_emit = (copying | term_emitting) & (ms | me | mu | sq | fq)
    esc_style = copying | (term_emitting & term_esc)

    sf = jnp.zeros((n,), i32)
    sf = jnp.where(in_str_emit & content, i32(SF_CONTENT), sf)
    sf = jnp.where(in_str_emit & me & esc_short, i32(SF_ESCCHAR), sf)
    sf = jnp.where(in_str_emit & uhex_done, i32(SF_UHEXLAST), sf)
    sf = jnp.where(esc_style & in_str_emit & (sq | fq | quote_close),
                   i32(SF_QUOTE), sf)

    # self-emission: copy-mode structural chars + literal chars.  The
    # copied container's own '{'/'[' arrives on the step that ENTERS copy
    # mode (evm still NORM in the carry), hence copying | c_copy.
    copying_now = copying | c_copy
    self_emit = copying_now & (
        open_obj | open_arr | close_o | close_a | obj_close | arr_close
        | comma | col | (ml & lit_ok)
    )
    # a literal's first char ('t'/'f'/'n') arrives while still in M_VALUE
    self_emit = self_emit | (copying & lit) | t_lit_start
    self_emit = self_emit | (term_emitting & ml & lit_ok)

    # number emission: at EV_NUM when copying or matched target
    num_emit = (ev_a == EV_NUM) & (copying | (evnorm & expect_target & matched
                                              & ~expect_skip))
    int_emit = num_emit & ~ev_num_float
    # "-0" normalizes to "0" (write_unescaped_text, json_parser.cuh:1420)
    is_neg0 = int_emit & (ev_span_len == 2) & st["neg0"]
    src_start = jnp.where(is_neg0, ev_span_start + 1, ev_span_start)
    src_len = jnp.where(int_emit, jnp.where(is_neg0, 1, ev_span_len), 0)
    flt_emit = num_emit & ev_num_float
    fidx = jnp.where(flt_emit, st["nfloat"], -1)
    new_nfloat = jnp.where(flt_emit, st["nfloat"] + 1, st["nfloat"])
    new_neg0 = jnp.where(num0, c == ord("-"), st["neg0"])
    new_neg0 = new_neg0 & ~(mnum & is_digit & (eff_mode != M_NUM_SIGN))
    new_neg0 = jnp.where(mnum & (eff_mode == M_NUM_SIGN) & (c != ord("0")),
                         False, new_neg0)

    # generator writes in NORM mode: a leading comma where needed, and the
    # '[' of case 5/7.  Writes happen at: terminal string/literal starts,
    # number completions, copy entries, case 5/7/6 pushes, case 6 commits.
    write_evt = (t_str_start | t_lit_start
                 | (num_emit & ~copying) | c_copy | open_arr57)
    # case 6's committing comma lives in its gap slot, not here
    pre_comma = write_evt & gnc & ~open_arr57
    upd["g_empty"] = jnp.where(write_evt & ~open_arr57 & ~p6, False,
                               upd["g_empty"])
    pre_b0 = jnp.where(pre_comma, jnp.uint8(ord(",")),
             jnp.where(open_arr57 | p6, jnp.uint8(ord(",")), jnp.uint8(0)))
    pre_b1 = jnp.where(open_arr57 | p6, jnp.uint8(ord("[")), jnp.uint8(0))
    pre_k0 = pre_comma | (open_arr57 & gnc)   # gap steps resolve via patch
    pre_k1 = open_arr57
    pre_gap = p6
    # case 5/7/6-commit closing bracket emits after this step's content
    post_br = end57 | (commit6 & (top_dirty_b > 1))

    ys = {
        "sf": sf.astype(jnp.uint8),
        "esc": esc_style,
        "self": self_emit,
        "src_start": src_start.astype(i32),
        "src_len": src_len.astype(i32),
        "fidx": fidx.astype(i32),
        "fstart": jnp.where(flt_emit, ev_span_start, -1).astype(i32),
        "flen": jnp.where(flt_emit, ev_span_len, 0).astype(i32),
        "pre_b0": pre_b0,
        "pre_b1": pre_b1,
        "pre_k0": pre_k0,
        "pre_k1": pre_k1,
        "pre_gap": pre_gap,
        "post_br": post_br,
        "patch_tgt": patch_tgt.astype(i32),
        "patch_k0": patch_k0,
        "patch_k1": patch_k1,
        # raw token events (consumed by from_json's recorder)
        "ev_a": ev_a,
        "ev_b": ev_b,
        "span_s": ev_span_start.astype(i32),
        "span_len": ev_span_len.astype(i32),
    }

    out = {
        "mode": new_mode, "depth": new_depth,
        "cstack_lo": clo, "cstack_hi": chi,
        "allow_close": new_allow_close, "quote": new_quote,
        "sfield": new_sfield, "tok_start": new_tok,
        "ndig": new_ndig, "numf": new_numf, "ucnt": new_ucnt,
        "lit_id": new_lid, "lit_pos": new_lpos,
        "length": st["length"],
        "fm_ok": new_fmok2, "fm_pos": new_fmpos2,
        "term_emit": new_term_emit, "term_esc": new_term_esc,
        "nfloat": new_nfloat, "neg0": new_neg0,
        "evm": upd["evm"], "base_depth": upd["base_depth"],
        "sp": upd["sp"], "root_wait": upd["root_wait"],
        "root_dirty": upd["root_dirty"],
        "ev_done": upd["ev_done"], "ev_fail": upd["ev_fail"],
        "g_adep": upd["g_adep"], "g_empty": upd["g_empty"],
        "k_kind": upd["k_kind"], "k_wait": upd["k_wait"],
        "k_cpi": upd["k_cpi"], "k_cnt": upd["k_cnt"],
        "k_depth": upd["k_depth"], "k_dirty": upd["k_dirty"],
        "k_chstyle": upd["k_chstyle"], "k_sadep": upd["k_sadep"],
        "k_sempty": upd["k_sempty"], "k_gap": upd["k_gap"],
    }
    return out, ys


def _stack_push(lo, hi, depth, is_obj, do):
    """Set bit `depth` of the 64-bit (lo, hi) stack to is_obj where do."""
    in_lo = depth < 32
    bit_lo = jnp.where(do & in_lo, jnp.uint32(1) << depth.astype(jnp.uint32), 0)
    bit_hi = jnp.where(do & ~in_lo,
                       jnp.uint32(1) << (depth - 32).astype(jnp.uint32), 0)
    lo = jnp.where(do & in_lo & is_obj, lo | bit_lo, lo & ~bit_lo)
    hi = jnp.where(do & ~in_lo & is_obj, hi | bit_hi, hi & ~bit_hi)
    return lo, hi


def _stack_top(lo, hi, depth):
    """Bit at level depth-1: True = object context."""
    d = jnp.maximum(depth - 1, 0)
    in_lo = d < 32
    b_lo = (lo >> d.astype(jnp.uint32)) & 1
    b_hi = (hi >> jnp.maximum(d - 32, 0).astype(jnp.uint32)) & 1
    return jnp.where(in_lo, b_lo, b_hi) == 1


# ---------------------------------------------------------------------------
# output materialization
# ---------------------------------------------------------------------------

def _str_emit_len(chars_at, prev3, flag, esc):
    """Per-position emission length for the string channel.

    chars_at: the source char at the position; prev3: chars at p-3..p-1
    (for \\uXXXX decode, p is the 4th hex digit).
    """
    c = chars_at.astype(jnp.int32)
    # SF_CONTENT
    ctrl = c < 32
    content_esc = jnp.where(c == ord('"'), 2,
                  jnp.where(ctrl & _is_short_esc(c), 2,
                  jnp.where(ctrl, 6, 1)))
    content_len = jnp.where(esc, content_esc, 1)
    # SF_ESCCHAR
    two = ((c == ord('"')) | (c == 0x5C) | (c == ord("b")) | (c == ord("f"))
           | (c == ord("n")) | (c == ord("r")) | (c == ord("t")))
    escchar_len = jnp.where(esc & two, 2, 1)
    # SF_UHEXLAST: UTF-8 width of the decoded code point
    cp = _hex4(prev3, c)
    uhex_len = jnp.where(cp < 0x80, 1, jnp.where(cp < 0x800, 2, 3))
    out = jnp.where(flag == SF_CONTENT, content_len,
          jnp.where(flag == SF_ESCCHAR, escchar_len,
          jnp.where(flag == SF_UHEXLAST, uhex_len,
          jnp.where(flag == SF_QUOTE, 1, 0))))
    return out.astype(jnp.int32)


def _is_short_esc(c):
    return (c == 8) | (c == 9) | (c == 10) | (c == 12) | (c == 13)


def _hex_val(c):
    c = c.astype(jnp.int32)
    return jnp.where(c >= ord("a"), c - ord("a") + 10,
                     jnp.where(c >= ord("A"), c - ord("A") + 10, c - ord("0")))


def _hex4(prev3, c4):
    """Decode 4 hex chars: prev3 = [p-3, p-2, p-1] stacked last axis."""
    return ((_hex_val(prev3[..., 0]) << 12) | (_hex_val(prev3[..., 1]) << 8)
            | (_hex_val(prev3[..., 2]) << 4) | _hex_val(c4))


# numpy, not jnp (GL001): the escape tables are built mutably on host and
# trace as constants at their use sites
_SHORT_ESC_CODE = np.zeros((32,), np.uint8)
for _ctrl, _esc in ((8, "b"), (9, "t"), (10, "n"), (12, "f"), (13, "r")):
    _SHORT_ESC_CODE[_ctrl] = ord(_esc)
_ESC_DECODE = np.arange(256, dtype=np.uint8)
for _ctrl, _esc in ((8, "b"), (12, "f"), (10, "n"), (13, "r"), (9, "t")):
    _ESC_DECODE[ord(_esc)] = _ctrl


def _str_emit_byte(c, prev3, flag, esc, off):
    """Byte `off` of the string-channel emission at a position."""
    c32 = c.astype(jnp.int32)
    # SF_CONTENT bytes
    ctrl = c32 < 32
    short = _is_short_esc(c32)
    hexlo = jnp.where(c32 % 16 < 10, ord("0") + c32 % 16,
                      ord("A") + c32 % 16 - 10)
    u6 = jnp.select(
        [off == 0, off == 1, off == 2, off == 3, off == 4],
        [ord("\\"), ord("u"), ord("0"), ord("0"),
         jnp.where(c32 >= 16, ord("1"), ord("0"))],
        hexlo,
    )
    content_esc = jnp.where(
        c32 == ord('"'), jnp.where(off == 0, ord("\\"), ord('"')),
        jnp.where(ctrl & short,
                  jnp.where(off == 0, ord("\\"),
                            jnp.asarray(_SHORT_ESC_CODE)[c32 % 32]),
                  jnp.where(ctrl, u6, c32)))
    content_b = jnp.where(esc, content_esc, c32)
    # SF_ESCCHAR bytes
    dec = jnp.asarray(_ESC_DECODE)[c]
    esc2 = jnp.where(off == 0, ord("\\"),
                     jnp.where(c32 == ord('"'), ord('"'),
                     jnp.where(c32 == 0x5C, ord("\\"), c32)))
    two = ((c32 == ord('"')) | (c32 == 0x5C) | (c32 == ord("b"))
           | (c32 == ord("f")) | (c32 == ord("n")) | (c32 == ord("r"))
           | (c32 == ord("t")))
    escchar_b = jnp.where(esc & two, esc2, dec.astype(jnp.int32))
    # SF_UHEXLAST: UTF-8 bytes of code point
    cp = _hex4(prev3, c)
    w = jnp.where(cp < 0x80, 1, jnp.where(cp < 0x800, 2, 3))
    b0 = jnp.where(w == 1, cp, jnp.where(w == 2, 0xC0 | (cp >> 6),
                                         0xE0 | (cp >> 12)))
    b1 = jnp.where(w == 2, 0x80 | (cp & 0x3F), 0x80 | ((cp >> 6) & 0x3F))
    b2 = 0x80 | (cp & 0x3F)
    uhex_b = jnp.select([off == 0, off == 1], [b0, b1], b2)
    out = jnp.where(flag == SF_CONTENT, content_b,
          jnp.where(flag == SF_ESCCHAR, escchar_b,
          jnp.where(flag == SF_UHEXLAST, uhex_b, ord('"'))))
    return out.astype(jnp.uint8)


def _materialize(chars, ys, fail, float_bytes, float_lens, max_out):
    """ys [n, L+1] directive arrays -> (out_chars [n, max_out], out_lens)."""
    n, L1 = ys["sf"].shape
    # chars padded with one EOF column to align with L+1 steps
    cpad = jnp.pad(chars, ((0, 0), (0, 1)))
    prev3 = jnp.stack(
        [jnp.pad(cpad, ((0, 0), (k, 0)))[:, :L1] for k in (3, 2, 1)], axis=-1
    )
    # resolve case-6 gap keeps: patch events scatter onto their gap steps
    rowix = jnp.arange(n, dtype=jnp.int32)[:, None].repeat(L1, axis=1)
    pvalid = ys["patch_tgt"] >= 0
    ptgt = jnp.where(pvalid, jnp.clip(ys["patch_tgt"], 0, L1 - 1), L1)
    gk0 = jnp.zeros((n, L1 + 1), jnp.bool_).at[rowix, ptgt].set(
        ys["patch_k0"])[:, :L1]
    gk1 = jnp.zeros((n, L1 + 1), jnp.bool_).at[rowix, ptgt].set(
        ys["patch_k1"])[:, :L1]
    pre_k0 = jnp.where(ys["pre_gap"], gk0, ys["pre_k0"])
    pre_k1 = jnp.where(ys["pre_gap"], gk1, ys["pre_k1"])
    pre_len = pre_k0.astype(jnp.int32) + pre_k1.astype(jnp.int32)
    post_len = ys["post_br"].astype(jnp.int32)
    slen = jnp.where(ys["sf"] > 0,
                     _str_emit_len(cpad, prev3, ys["sf"].astype(jnp.int32),
                                   ys["esc"]), 0)
    flen = jnp.where(ys["fidx"] >= 0,
                     jnp.take_along_axis(
                         float_lens, jnp.clip(ys["fidx"], 0, None), axis=1),
                     0)
    step_len = (pre_len + slen + ys["src_len"] + flen
                + ys["self"].astype(jnp.int32) + post_len)
    step_len = jnp.where(fail[:, None], 0, step_len)
    cum = jnp.cumsum(step_len, axis=1)
    total = cum[:, -1]

    pos = jnp.arange(max_out, dtype=jnp.int32)[None, :]
    # emitting step for each output byte: first step with cum > pos
    step = jax.vmap(lambda c, p: jnp.searchsorted(c, p, side="right"))(
        cum, jnp.broadcast_to(pos, (n, max_out))
    ).astype(jnp.int32)
    step = jnp.clip(step, 0, L1 - 1)
    base = jnp.take_along_axis(
        jnp.pad(cum, ((0, 0), (1, 0))), step, axis=1)
    off = pos - base

    def g(a):
        return jnp.take_along_axis(a, step, axis=1)

    sf_s = g(ys["sf"].astype(jnp.int32))
    esc_s = g(ys["esc"])
    slen_s = g(slen)
    srcs_s = g(ys["src_start"])
    srcl_s = g(ys["src_len"])
    fidx_s = g(ys["fidx"])
    flen_s = g(flen)
    c_s = g(cpad)
    prek0_s = g(pre_k0)
    preb0_s = g(ys["pre_b0"])
    preb1_s = g(ys["pre_b1"])
    prel_s = g(pre_len)
    self_s = g(ys["self"].astype(jnp.int32))
    prev3_s = jnp.stack([jnp.take_along_axis(prev3[..., k], step, axis=1)
                         for k in range(3)], axis=-1)

    off2 = off - prel_s
    in_pre = off < prel_s
    in_str = ~in_pre & (off2 < slen_s)
    in_src = ~in_pre & ~in_str & (off2 < slen_s + srcl_s)
    in_flt = ~in_pre & ~in_str & ~in_src & (off2 < slen_s + srcl_s + flen_s)
    in_self = (~in_pre & ~in_str & ~in_src & ~in_flt
               & (off2 < slen_s + srcl_s + flen_s + self_s))

    b_pre = jnp.where((off == 0) & prek0_s, preb0_s, preb1_s)
    b_str = _str_emit_byte(c_s, prev3_s, sf_s, esc_s, off2)
    src_pos = jnp.clip(srcs_s + (off2 - slen_s), 0, chars.shape[1] - 1)
    b_src = jnp.take_along_axis(cpad, src_pos, axis=1)
    fb = jnp.take_along_axis(
        float_bytes, jnp.clip(fidx_s, 0, None)[..., None].repeat(
            float_bytes.shape[2], axis=2),
        axis=1)
    b_flt = jnp.take_along_axis(
        fb, jnp.clip(off2 - slen_s - srcl_s, 0, FLOAT_W - 1)[..., None],
        axis=2)[..., 0]
    out = jnp.where(in_pre, b_pre,
          jnp.where(in_str, b_str,
          jnp.where(in_src, b_src,
          jnp.where(in_flt, b_flt,
          jnp.where(in_self, c_s, jnp.uint8(ord("]"))))))).astype(jnp.uint8)
    out = jnp.where(pos < total[:, None], out, jnp.uint8(0))
    # a row overflowing the buffer cannot be represented: null it rather
    # than return a silently truncated string
    total = jnp.where(total > max_out, -1, total)
    return out, total


def _format_floats(chars, fstarts, flens, F):
    """Parse + Java-format the float tokens: returns (bytes [n,F,28], lens).

    The Spark cast kernel this reuses reads at most 4 exponent digits
    (matching ``cast_string_to_float.cu:523``), but JSON normalization
    follows stod: any exponent length is legal, saturating to ±Inf / 0.
    So the exponent is canonicalized first — leading zeros stripped and
    values beyond 4 digits clamped to ±9999 (anything past ±9999 is far
    beyond double range, so the clamp is value-preserving).
    """
    n, L = chars.shape
    W = min(L, 326)
    cpad = jnp.pad(chars, ((0, 0), (0, W)))
    # substring extraction: gather a [n, F, W] window per float token
    idx = jnp.clip(fstarts[..., None], 0, L) + jnp.arange(W, dtype=jnp.int32)
    win = jnp.take_along_axis(cpad[:, None, :].repeat(F, axis=1),
                              jnp.clip(idx, 0, L + W - 1), axis=2)
    inlen = jnp.clip(flens, 0, W)
    pos = jnp.arange(W, dtype=jnp.int32)[None, None, :]
    mask = pos < inlen[..., None]
    win = jnp.where(mask, win, jnp.uint8(0))

    # canonicalize the exponent: [mantissa] 'e' sign DDDD (4 digits)
    is_e = ((win == ord("e")) | (win == ord("E"))) & mask
    e_pos = jnp.min(jnp.where(is_e, pos, W), axis=2)
    has_e = e_pos < inlen

    def at(p):
        return jnp.take_along_axis(win, jnp.clip(p, 0, W - 1)[..., None],
                                   axis=2)[..., 0]

    sgn_c = at(e_pos + 1)
    has_sign = (sgn_c == ord("+")) | (sgn_c == ord("-"))
    neg = sgn_c == ord("-")
    d_start = e_pos + 1 + has_sign.astype(jnp.int32)
    # first non-'0' digit of the run
    in_run = (pos >= d_start[..., None]) & mask
    nz = in_run & (win != ord("0"))
    nz_start = jnp.min(jnp.where(nz, pos, W), axis=2)
    sig = jnp.where(nz_start >= inlen, 0, inlen - nz_start)
    d0, d1, d2, d3 = (at(nz_start), at(nz_start + 1), at(nz_start + 2),
                      at(nz_start + 3))

    def dv(c, k):
        return jnp.where(sig > k, (c - ord("0")).astype(jnp.int32), 0)

    val4 = (dv(d0, 0) * jnp.where(sig > 3, 1000, jnp.where(sig > 2, 100,
            jnp.where(sig > 1, 10, 1)))
            + dv(d1, 1) * jnp.where(sig > 3, 100, jnp.where(sig > 2, 10, 1))
            + dv(d2, 2) * jnp.where(sig > 3, 10, 1) + dv(d3, 3))
    eval_ = jnp.where(sig > 4, 9999, val4)
    # rebuild: chars past e_pos replaced by canonical exponent
    W2 = W + 6
    winp = jnp.pad(win, ((0, 0), (0, 0), (0, 6)))
    pos2 = jnp.arange(W2, dtype=jnp.int32)[None, None, :]
    rel = pos2 - e_pos[..., None]
    edig = jnp.stack([eval_ // 1000 % 10, eval_ // 100 % 10,
                      eval_ // 10 % 10, eval_ % 10], axis=-1) + ord("0")
    canon = jnp.select(
        [rel == 0, rel == 1, rel == 2, rel == 3, rel == 4, rel == 5],
        [jnp.broadcast_to(jnp.uint8(ord("e")), winp.shape),
         jnp.where(neg, jnp.uint8(ord("-")), jnp.uint8(ord("+")))[..., None]
         .repeat(W2, axis=-1),
         edig[..., 0:1].astype(jnp.uint8).repeat(W2, axis=-1),
         edig[..., 1:2].astype(jnp.uint8).repeat(W2, axis=-1),
         edig[..., 2:3].astype(jnp.uint8).repeat(W2, axis=-1),
         edig[..., 3:4].astype(jnp.uint8).repeat(W2, axis=-1)],
        jnp.uint8(0),
    )
    use_canon = has_e[..., None] & (rel >= 0) & (rel < 6)
    win2 = jnp.where(use_canon, canon, winp)
    len2 = jnp.where(has_e, e_pos + 6, inlen)
    win2 = jnp.where(pos2 < len2[..., None], win2, jnp.uint8(0))

    sc = StringColumn(win2.reshape(n * F, W2), len2.reshape(n * F),
                      jnp.ones((n * F,), jnp.bool_))
    vals = cast_string.string_to_float(sc, T.FLOAT64)
    fb, fl = float_to_string.double_to_json_string(vals.data)
    return fb.reshape(n, F, -1), fl.reshape(n, F).astype(jnp.int32)


@partial(jax.jit, static_argnames=("path_tuple", "max_out", "unroll"))
def _run(col_chars, col_lengths, col_validity, path_tuple, max_out,
         unroll=1):
    instructions = list(path_tuple)
    ptypes, pindexes, pnames, pnamelens, P = _pack_path(instructions)
    n, L = col_chars.shape
    i32 = jnp.int32

    D = MAX_PATH + 1
    zeros = jnp.zeros((n,), i32)
    carry = {
        "mode": jnp.full((n,), M_VALUE, i32),
        "depth": zeros,
        "cstack_lo": jnp.zeros((n,), jnp.uint32),
        "cstack_hi": jnp.zeros((n,), jnp.uint32),
        "allow_close": jnp.zeros((n,), jnp.bool_),
        "quote": jnp.zeros((n,), jnp.uint8),
        "sfield": jnp.zeros((n,), jnp.bool_),
        "tok_start": zeros,
        "ndig": zeros,
        "numf": jnp.zeros((n,), jnp.bool_),
        "ucnt": zeros,
        "lit_id": zeros,
        "lit_pos": zeros,
        "length": col_lengths.astype(i32),
        "fm_ok": jnp.zeros((n,), jnp.bool_),
        "fm_pos": zeros,
        "term_emit": jnp.zeros((n,), jnp.bool_),
        "term_esc": jnp.zeros((n,), jnp.bool_),
        "nfloat": zeros,
        "neg0": jnp.zeros((n,), jnp.bool_),
        "evm": jnp.full((n,), EVM_NORM, i32),
        "base_depth": zeros,
        "sp": zeros,
        "root_wait": jnp.ones((n,), jnp.bool_),
        "root_dirty": zeros,
        "ev_done": jnp.zeros((n,), jnp.bool_),
        "ev_fail": jnp.zeros((n,), jnp.bool_),
        "g_adep": zeros,
        "g_empty": jnp.ones((n,), jnp.bool_),
        "k_kind": jnp.zeros((n, D), i32),
        "k_wait": jnp.zeros((n, D), i32),
        "k_cpi": jnp.zeros((n, D), i32),
        "k_cnt": jnp.zeros((n, D), i32),
        "k_depth": jnp.zeros((n, D), i32),
        "k_dirty": jnp.zeros((n, D), i32),
        "k_chstyle": jnp.zeros((n, D), i32),
        "k_sadep": jnp.zeros((n, D), i32),
        "k_sempty": jnp.zeros((n, D), jnp.bool_),
        "k_gap": jnp.zeros((n, D), i32),
    }
    cpad = jnp.pad(col_chars, ((0, 0), (0, 1)))
    xs = (jnp.arange(L + 1, dtype=i32), cpad.T)
    step = partial(_step, P, ptypes, pindexes, pnames, pnamelens)
    # unroll: several chars per while-loop iteration — the big carry
    # round-trips HBM once per ITERATION, so unrolling divides the
    # scan's memory-latency cost by the unroll factor (VERDICT r2 §4:
    # "process chunks per step"); the carry threads through the unrolled
    # body in registers/VMEM.  Static jit arg: it must key the cache.
    final, ys = jax.lax.scan(step, carry, xs,
                             unroll=min(max(1, unroll), L + 1))
    ys = {k: jnp.moveaxis(v, 0, 1) for k, v in ys.items()}  # [n, L+1]

    ok = final["ev_done"] & ~final["ev_fail"] & (final["root_dirty"] > 0)
    fail = ~ok

    F = max(1, min(L, 1 + L // 4))
    import numpy as _np  # static shapes only

    # float span table: scatter the (rare) float events into [n, F]
    rowix = jnp.arange(n, dtype=i32)[:, None].repeat(L + 1, axis=1)
    fvalid = ys["fidx"] >= 0
    fslot = jnp.where(fvalid, jnp.clip(ys["fidx"], 0, F - 1), F)
    fstarts = jnp.zeros((n, F + 1), i32).at[rowix, fslot].set(
        jnp.where(fvalid, ys["fstart"], 0))[:, :F]
    flens_src = jnp.zeros((n, F + 1), i32).at[rowix, fslot].set(
        jnp.where(fvalid, ys["flen"], 0))[:, :F]
    float_bytes, float_lens = _format_floats(col_chars, fstarts, flens_src, F)

    out_chars, out_lens = _materialize(
        col_chars, ys, fail, float_bytes, float_lens, max_out)
    valid = col_validity & ok & (out_lens >= 0)
    return out_chars, jnp.where(valid, out_lens, 0), valid


@partial(jax.jit, static_argnames=("path_tuple", "max_out", "unroll"))
def _run_hybrid(col_chars, col_lengths, col_validity, path_tuple, max_out,
                unroll=1):
    """Bit-parallel fast path with whole-batch scan-machine fallback.

    :func:`json_fast.fast_path` evaluates wildcard-free paths over clean
    documents in O(path + log L) data-parallel passes and flags every row
    it cannot prove it handles; if ANY row flags, the whole batch runs
    the general char-scan machine (one ``lax.cond`` — the scan engine
    stays the single source of semantics).  Kept as the
    ``json_fallback_div=0`` engine; the default routing is
    :func:`_run_hybrid_compact`, which scans only the flagged rows.
    """
    from . import json_fast

    fast_c, fast_l, fast_ok, fb = json_fast.fast_path(
        col_chars, col_lengths, col_validity, path_tuple, max_out)

    def serial(_):
        return _run(col_chars, col_lengths, col_validity, path_tuple,
                    max_out, unroll=unroll)

    def fast(_):
        return fast_c, fast_l.astype(jnp.int32), fast_ok

    return jax.lax.cond(jnp.any(fb), serial, fast, None)


@partial(jax.jit,
         static_argnames=("path_tuple", "max_out", "unroll", "cap"))
def _run_hybrid_compact(col_chars, col_lengths, col_validity, path_tuple,
                        max_out, unroll=1, cap=0):
    """Fast path + fixed-capacity per-row fallback compaction.

    The pre-r5 hybrid routed the ENTIRE batch through the serial scan if
    even one row flagged — at realistic dirty-row rates (any backslash,
    single quote, or depth>16; 1-10% of real-world JSON) the fast engine
    almost never fired (VERDICT r4 weak #2).  Here flagged rows are
    *compacted*: a ``lax.while_loop`` gathers up to ``cap`` flagged rows
    per iteration into a ``[cap, L]`` sub-batch, runs the scan machine on
    that sub-batch only, and scatters the results back over the fast
    engine's output.  The loop runs ``ceil(n_flagged/cap)`` iterations —
    ZERO for clean batches, one for the common low-dirty case, and
    ``ceil(n/cap)`` (~= the old whole-batch cost) in the worst all-dirty
    case, so there is no cliff.  The scan machine is traced exactly once
    (inside the loop body) at the sub-batch shape, so compile cost does
    not grow vs the whole-batch hybrid.

    Semantics anchor: the scan machine remains the single source of truth
    for every flagged row (reference behavior:
    ``src/main/cpp/src/get_json_object.cu:360-420``'s per-row parser is
    the oracle for both engines).
    """
    from . import json_fast

    n, L = col_chars.shape
    C = int(cap) if cap and cap > 0 else n
    C = max(1, min(C, n))

    fast_c, fast_l, fast_ok, fb = json_fast.fast_path(
        col_chars, col_lengths, col_validity, path_tuple, max_out)

    fbi = fb.astype(jnp.int32)
    nfb = jnp.sum(fbi)
    ranks = jnp.cumsum(fbi) - fbi          # flagged rows: 0..nfb-1

    # Row n is a discard slot: unused capacity gathers row n-1 (harmless
    # duplicate work) and scatters to row n (sliced off at the end).
    out_c = jnp.concatenate(
        [fast_c, jnp.zeros((1, fast_c.shape[1]), fast_c.dtype)], axis=0)
    out_l = jnp.concatenate(
        [fast_l.astype(jnp.int32), jnp.zeros((1,), jnp.int32)])
    out_v = jnp.concatenate([fast_ok, jnp.zeros((1,), jnp.bool_)])

    def cond_fn(st):
        return st[0] * C < nfb

    def body_fn(st):
        r, oc, ol, ov = st
        lo = r * C
        window = fb & (ranks >= lo) & (ranks < lo + C)
        (pos,) = jnp.nonzero(window, size=C, fill_value=n)
        gpos = jnp.minimum(pos, n - 1)
        live = pos < n
        sc, sl, sv = _run(col_chars[gpos], col_lengths[gpos],
                          col_validity[gpos] & live, path_tuple, max_out,
                          unroll=unroll)
        return (r + 1,
                oc.at[pos].set(sc),
                ol.at[pos].set(sl),
                ov.at[pos].set(sv & live))

    _, oc, ol, ov = jax.lax.while_loop(
        cond_fn, body_fn, (jnp.int32(0), out_c, out_l, out_v))
    return oc[:n], ol[:n], ov[:n]


def get_json_object(
    col,
    path: Union[str, Sequence],
    max_out: int = 0,
):
    """Evaluate a JSONPath against every row; invalid/no-match rows -> null.

    ``max_out`` pins the output char-matrix width (default 6*L+20 covers
    the worst-case escape expansion; lower it to trade memory when inputs
    are known tame — overlong results then clamp to null).

    A :class:`~spark_rapids_jni_tpu.columnar.bucketed.BucketedStringColumn`
    input evaluates per bucket — each bucket's scan runs only that
    bucket's width — and returns a bucketed result (``.merge()`` for a
    flat column).
    """
    from ..columnar.bucketed import BucketedStringColumn

    if isinstance(col, BucketedStringColumn):
        return col.apply(lambda b: get_json_object(b, path, max_out))
    instructions = parse_path(path) if isinstance(path, str) else list(path)
    if len(instructions) > MAX_PATH:
        raise ValueError(f"path deeper than {MAX_PATH}")
    L = col.max_len
    if max_out <= 0:
        from .. import config

        max_out = config.get("json_max_out")
    if max_out <= 0:
        # provable worst case: every source byte expands to at most 6
        # output bytes (control char -> \u00XX in escaped style); floats
        # emit <= srclen+9; case-6 brackets add <=3 per '[' char
        max_out = 6 * L + 20
    from .. import config

    use_fast = bool(config.get("json_fast_path")) and not any(
        i[0] == "wildcard" for i in instructions)
    unroll = max(1, int(config.get("json_scan_unroll")))
    if use_fast:
        div = int(config.get("json_fallback_div"))
        if div > 0:
            n = col.chars.shape[0]
            cap = max(1, -(-n // div))  # ceil(n/div), static per n
            out_chars, out_lens, valid = _run_hybrid_compact(
                col.chars, col.lengths, col.validity, tuple(instructions),
                max_out, unroll=unroll, cap=cap)
        else:
            out_chars, out_lens, valid = _run_hybrid(
                col.chars, col.lengths, col.validity, tuple(instructions),
                max_out, unroll=unroll)
    else:
        out_chars, out_lens, valid = _run(
            col.chars, col.lengths, col.validity, tuple(instructions),
            max_out, unroll=unroll)
    return StringColumn(out_chars, out_lens, valid)
