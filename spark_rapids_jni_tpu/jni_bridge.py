"""Host-boundary dispatcher behind the Java/JNI API surface.

The reference exposes 26 Java classes (``com.nvidia.spark.rapids.jni.*``,
reference ``src/main/java/.../jni/*.java``) whose static native methods land
in per-class JNI glue (``src/main/cpp/src/*Jni.cpp``).  Here the native side
is one C-ABI bridge library (``jni/src/bridge.cpp``) that embeds CPython and
funnels every op through :func:`invoke` — argument marshaling happens once,
in Python, where the kernels live, instead of 15 hand-written marshaling
files.  The Java classes (``jni/java/...``) keep the reference's public
signatures (e.g. ``CastStrings.toInteger`` ``CastStrings.java:49``,
``Hash.murmurHash32`` ``Hash.java:40``) and call the bridge through thin
JNI glue (``jni/src/jni_glue.cpp``).

Handles are live Python objects (columns, bloom filters, footers) whose
references are owned by the C++ side; there is no serialization on the hot
path — host buffers cross the boundary exactly once at column construction.

Columns cross as Arrow-style host buffers:

* fixed width:  ``data`` little-endian packed values, ``validity`` one byte
  per row (empty = all valid)
* strings:      ``data`` concatenated UTF-8 chars + ``offsets`` int32[n+1]
* decimal128:   ``data`` 16 bytes per row, little-endian two's complement
"""

from __future__ import annotations

import base64
import json

import numpy as np

# SRJ_FORCE_CPU (embedded hosts) is honored by the package __init__,
# which runs before any op-table submodule can initialize a backend.


def _types():
    from .columnar import types as T

    return T


def _valid_arr(validity: bytes, n: int):
    if not validity:
        return np.ones(n, dtype=np.bool_)
    return np.frombuffer(validity, dtype=np.uint8, count=n).astype(np.bool_)


def column_from_host(kind_name: str, n: int, data: bytes, validity: bytes,
                     precision: int = 0, scale: int = 0):
    """Build a device column from host buffers (one copy, then HBM)."""
    import jax.numpy as jnp

    T = _types()
    kind = T.Kind(kind_name)
    valid = _valid_arr(validity, n)
    if kind is T.Kind.DECIMAL:
        from .columnar.column import Decimal128Column

        raw = np.frombuffer(data, dtype=np.uint64, count=2 * n).reshape(n, 2)
        return Decimal128Column(
            jnp.asarray(raw), jnp.asarray(valid),
            T.SparkType.decimal(precision or 38, scale))
    from .columnar.column import Column

    st = T.SparkType(kind)
    np_dtype = np.dtype(st.jnp_dtype)
    arr = np.frombuffer(data, dtype=np_dtype, count=n)
    return Column(jnp.asarray(arr), jnp.asarray(valid), st)


def string_column_from_host(chars: bytes, offsets: bytes, validity: bytes,
                            n: int):
    """Ragged (chars, offsets) -> padded matrix, one vectorized scatter
    (same shape as columnar/arrow.py _string_array_to_column)."""
    import jax.numpy as jnp

    from .columnar.column import StringColumn

    from .columnar.arrow import segment_positions

    offs = np.frombuffer(offsets, dtype=np.int32, count=n + 1)
    valid = _valid_arr(validity, n)
    # null rows must have zero extent (ListColumn/hash-fold invariant)
    lengths = np.where(valid, offs[1:] - offs[:-1], 0).astype(np.int32)
    max_len = max(int(lengths.max()) if n else 0, 1)
    mat = np.zeros((n, max_len), dtype=np.uint8)
    buf = np.frombuffer(chars, dtype=np.uint8)
    if buf.size and lengths.sum():
        row_idx, within = segment_positions(lengths)
        src = np.repeat(offs[:-1], lengths) + within
        mat[row_idx, within] = buf[src]
    return StringColumn(jnp.asarray(mat), jnp.asarray(lengths),
                        jnp.asarray(valid))


def column_to_host(col):
    """-> (kind_name, n, data, validity, offsets|None, precision, scale)."""
    import jax

    from .columnar.column import Column, Decimal128Column, StringColumn

    T = _types()
    if isinstance(col, StringColumn):
        chars = np.asarray(jax.device_get(col.chars))
        lengths = np.asarray(jax.device_get(col.lengths))
        valid = np.asarray(jax.device_get(col.validity))
        n = len(lengths)
        lens = np.where(valid, lengths, 0).astype(np.int64)
        offs = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=offs[1:])
        # padded matrix -> ragged bytes with one boolean-mask gather
        keep = np.arange(chars.shape[1])[None, :] < lens[:, None]
        out = chars[keep]
        return ("string", col.num_rows, out.tobytes(),
                valid.astype(np.uint8).tobytes(), offs.tobytes(), 0, 0)
    if isinstance(col, Decimal128Column):
        limbs = np.asarray(jax.device_get(col.limbs)).astype(np.uint64)
        valid = np.asarray(jax.device_get(col.validity))
        return ("decimal", col.num_rows, limbs.tobytes(),
                valid.astype(np.uint8).tobytes(), None,
                col.dtype.precision, col.dtype.scale)
    if isinstance(col, Column):
        data = np.asarray(jax.device_get(col.data))
        valid = np.asarray(jax.device_get(col.validity))
        return (col.dtype.kind.value, col.num_rows, data.tobytes(),
                valid.astype(np.uint8).tobytes(), None, 0, 0)
    raise TypeError(f"not a host-exportable column: {type(col).__name__}")


# ---------------------------------------------------------------------------
# op dispatch — names mirror the reference's native methods
# ---------------------------------------------------------------------------

def _kind_of(args):
    T = _types()
    kind = args["kind"]
    # the JNI surface's UINT64 (conv() casts) stores the same 64 bits in
    # our signed INT64 columns (types.py has no unsigned kinds)
    if kind in ("uint64", "uint32", "uint16", "uint8"):
        kind = "int" + kind[4:]
    return T.SparkType(T.Kind(kind))


def _op_cast_to_integer(args, objs):
    from .ops import cast_string

    return [cast_string.string_to_integer(
        objs[0], _kind_of(args), ansi_mode=args["ansi"],
        strip=args.get("strip", True))], {}


def _op_cast_to_float(args, objs):
    from .ops import cast_string

    return [cast_string.string_to_float(
        objs[0], _kind_of(args), ansi_mode=args["ansi"])], {}


def _op_cast_to_decimal(args, objs):
    from .ops import cast_string

    return [cast_string.string_to_decimal(
        objs[0], args["precision"], args["scale"], ansi_mode=args["ansi"],
        strip=args.get("strip", True))], {}


def _op_cast_from_float(args, objs):
    from .ops.float_to_string import float_to_string

    return [float_to_string(objs[0])], {}


def _op_cast_from_float_fmt(args, objs):
    from .ops.format_float import format_float

    return [format_float(objs[0], args["digits"])], {}


def _op_cast_from_decimal(args, objs):
    from .ops.decimal_to_string import decimal_to_string

    return [decimal_to_string(objs[0])], {}


def _op_cast_to_int_base(args, objs):
    from .ops import cast_string

    return [cast_string.string_to_integer_with_base(
        objs[0], _kind_of(args), base=args["base"],
        ansi_mode=args["ansi"])], {}


def _op_cast_from_int_base(args, objs):
    from .ops import cast_string

    return [cast_string.integer_to_string_with_base(
        objs[0], base=args["base"])], {}


def _op_murmur(args, objs):
    from .ops.hashing import murmur_hash3_32

    return [murmur_hash3_32(objs, seed=args.get("seed", 42))], {}


def _op_xxhash(args, objs):
    from .ops import hashing

    return [hashing.xxhash64(
        objs, seed=args.get("seed", hashing.DEFAULT_XXHASH64_SEED))], {}


def _op_bloom_create(args, objs):
    from .ops import bloom_filter as bf

    nlongs = (args["bits"] + 63) // 64
    return [bf.bloom_filter_create(args["num_hashes"], nlongs)], {}


def _op_bloom_put(args, objs):
    from .ops import bloom_filter as bf

    return [bf.bloom_filter_put(objs[0], objs[1])], {}


def _op_bloom_merge(args, objs):
    from .ops import bloom_filter as bf

    return [bf.bloom_filter_merge(objs)], {}


def _op_bloom_probe(args, objs):
    from .ops import bloom_filter as bf

    return [bf.bloom_filter_probe(objs[0], objs[1])], {}


def _op_bloom_serialize(args, objs):
    from .ops import bloom_filter as bf

    raw = bf.bloom_filter_serialize(objs[0])
    return [], {"data": base64.b64encode(raw).decode("ascii")}


def _op_bloom_deserialize(args, objs):
    from .ops import bloom_filter as bf

    return [bf.bloom_filter_deserialize(base64.b64decode(args["data"]))], {}


def _op_rebase_g2j(args, objs):
    from .ops.datetime_rebase import rebase_gregorian_to_julian

    return [rebase_gregorian_to_julian(objs[0])], {}


def _op_rebase_j2g(args, objs):
    from .ops.datetime_rebase import rebase_julian_to_gregorian

    return [rebase_julian_to_gregorian(objs[0])], {}


def _op_dec128(fn_name, n_out=2):
    def run(args, objs):
        from .ops import decimal as D

        fn = getattr(D, fn_name)
        if fn_name in ("integer_divide_decimal128",):
            overflow, res = fn(objs[0], objs[1])
        elif fn_name == "multiply_decimal128":
            overflow, res = fn(
                objs[0], objs[1], args["scale"],
                cast_interim_result=args.get("interim_cast", True))
        else:
            overflow, res = fn(objs[0], objs[1], args["scale"])
        return [overflow, res], {}

    return run


def _op_histogram_create(args, objs):
    from .ops.histogram import create_histogram_if_valid

    vals, freqs = create_histogram_if_valid(objs[0], objs[1])
    return [vals, freqs], {}


def _op_histogram_percentile(args, objs):
    import jax.numpy as jnp

    from .columnar import types as T
    from .columnar.column import Column
    from .ops.histogram import percentile_from_histogram

    values, freqs = objs[0], objs[1]
    n = values.num_rows
    offsets = jnp.asarray([0, n], jnp.int32)
    out, valid = percentile_from_histogram(
        values, freqs, offsets, list(args["percentages"]))
    return [Column(out.reshape(-1), valid.reshape(-1), T.FLOAT64)], {}


def _op_get_json(args, objs):
    """Wire triples [type, name, index] (JSONUtils.java PathInstructionJni)
    -> the internal instruction tuples parse_path produces."""
    from .ops.get_json_object import get_json_object

    path = []
    for typ, name, idx in args["path"]:
        if typ == "wildcard":
            path.append(("wildcard",))
        elif typ == "index":
            path.append(("index", int(idx)))
        elif typ == "named":
            path.append(("named", name.encode("utf-8")))
        else:
            raise ValueError(f"unknown path instruction type {typ!r}")
    return [get_json_object(objs[0], path)], {}


def _op_from_json(args, objs):
    from .ops.from_json import from_json_to_raw_map

    lst = from_json_to_raw_map(objs[0])
    kv = lst.child
    return [kv.field("key"), kv.field("value")], {
        "offsets": np.asarray(lst.offsets).tolist()}


def _op_parse_uri(args, objs):
    from .ops.parse_uri import parse_uri, parse_uri_query_with_column

    if len(objs) > 1:  # per-row keys (ParseURI.parseURIQueryWithColumn)
        return [parse_uri_query_with_column(objs[0], objs[1])], {}
    return [parse_uri(objs[0], args["part"], key=args.get("key"))], {}


def _op_regex_literal_range(args, objs):
    from .ops.regex_rewrite import literal_range_pattern

    return [literal_range_pattern(
        objs[0], args["literal"], args["len"], args["start"],
        args["end"])], {}


def _batch(objs):
    from .columnar.column import ColumnBatch

    return ColumnBatch({f"c{i}": c for i, c in enumerate(objs)})


def _op_rows_to(args, objs):
    from .ops.row_conversion import convert_to_rows_batched

    return list(convert_to_rows_batched(_batch(objs))), {}


def _op_rows_to_fixed(args, objs):
    from .ops.row_conversion import convert_to_rows_fixed_width_optimized

    return [convert_to_rows_fixed_width_optimized(_batch(objs))], {}


def _schema_types(args):
    T = _types()
    out = {}
    for i, s in enumerate(args["schema"]):
        kind = T.Kind(s["kind"])
        if kind is T.Kind.DECIMAL:
            if "precision" not in s or "scale" not in s:
                raise ValueError(
                    "decimal schema entries need explicit precision/scale")
            st = T.SparkType.decimal(s["precision"], s["scale"])
        else:
            st = T.SparkType(kind)
        if kind is T.Kind.STRING:
            if "max_len" not in s:
                raise ValueError(
                    "string schema entries need an explicit max_len")
            st = (st, s["max_len"])
        out[f"c{i}"] = st
    return out


def _op_rows_from(args, objs):
    from .ops.row_conversion import convert_from_rows

    batch = convert_from_rows(objs[0], _schema_types(args))
    return list(batch.columns), {}


def _op_zorder_interleave(args, objs):
    from .ops.zorder import interleave_bits

    return [interleave_bits(objs)], {}


def _op_zorder_hilbert(args, objs):
    from .ops.zorder import hilbert_index

    return [hilbert_index(args["num_bits"], objs)], {}


def _op_tz_to_utc(args, objs):
    from .ops.timezones import convert_timestamp_to_utc

    return [convert_timestamp_to_utc(objs[0], args["zone"])], {}


def _op_tz_from_utc(args, objs):
    from .ops.timezones import convert_utc_to_timezone

    return [convert_utc_to_timezone(objs[0], args["zone"])], {}


def _op_tz_supported(args, objs):
    from .ops.timezones import default_db

    return [], {"supported": default_db().is_supported(args["zone"])}


def _wire_schema(node):
    """JSON-safe schema wire format -> the io.parquet_footer spec.

    leaf = null; struct = object; list = {"__list__": elem};
    map = {"__map__": [key, value]} (JSON cannot carry the internal
    tuple/None shapes directly — ParquetFooter.java SchemaElement.toJson
    emits this encoding).
    """
    if node is None:
        return None
    if isinstance(node, dict):
        if "__list__" in node and len(node) == 1:
            return [_wire_schema(node["__list__"])]
        if "__map__" in node and len(node) == 1:
            k, v = node["__map__"]
            return (_wire_schema(k), _wire_schema(v))
        return {k: _wire_schema(v) for k, v in node.items()}
    raise TypeError(f"bad wire schema node {node!r}")


def _op_parquet_read_filter(args, objs):
    from .io.parquet_footer import ParquetFooter

    schema = args.get("schema")
    footer = ParquetFooter.read_and_filter(
        base64.b64decode(args["data"]),
        part_offset=args.get("part_offset", 0),
        part_length=args.get("part_length", 1 << 62),
        schema=_wire_schema(schema) if schema is not None else None,
        ignore_case=args.get("ignore_case", False),
    )
    return [footer], {}


def _op_parquet_num_rows(args, objs):
    return [], {"value": objs[0].num_rows}


def _op_parquet_num_columns(args, objs):
    return [], {"value": objs[0].num_columns}


def _op_parquet_serialize(args, objs):
    raw = objs[0].serialize()
    return [], {"data": base64.b64encode(raw).decode("ascii")}


def _op_profiler(method):
    def run(args, objs):
        from .profiler import FileWriter, Profiler

        if method == "init":
            Profiler.init(FileWriter(args["path"]))
        else:
            getattr(Profiler, method)()
        return [], {}

    return run


_OPS = {
    "CastStrings.toInteger": _op_cast_to_integer,
    "CastStrings.toFloat": _op_cast_to_float,
    "CastStrings.toDecimal": _op_cast_to_decimal,
    "CastStrings.fromFloat": _op_cast_from_float,
    "CastStrings.fromFloatWithFormat": _op_cast_from_float_fmt,
    "CastStrings.fromDecimal": _op_cast_from_decimal,
    "CastStrings.toIntegersWithBase": _op_cast_to_int_base,
    "CastStrings.fromIntegersWithBase": _op_cast_from_int_base,
    "Hash.murmurHash32": _op_murmur,
    "Hash.xxhash64": _op_xxhash,
    "BloomFilter.create": _op_bloom_create,
    "BloomFilter.put": _op_bloom_put,
    "BloomFilter.merge": _op_bloom_merge,
    "BloomFilter.probe": _op_bloom_probe,
    "BloomFilter.serialize": _op_bloom_serialize,
    "BloomFilter.deserialize": _op_bloom_deserialize,
    "DateTimeRebase.rebaseGregorianToJulian": _op_rebase_g2j,
    "DateTimeRebase.rebaseJulianToGregorian": _op_rebase_j2g,
    "DecimalUtils.add128": _op_dec128("add_decimal128"),
    "DecimalUtils.subtract128": _op_dec128("sub_decimal128"),
    "DecimalUtils.multiply128": _op_dec128("multiply_decimal128"),
    "DecimalUtils.divide128": _op_dec128("divide_decimal128"),
    "DecimalUtils.integerDivide128": _op_dec128("integer_divide_decimal128"),
    "DecimalUtils.remainder128": _op_dec128("remainder_decimal128"),
    "Histogram.createHistogramIfValid": _op_histogram_create,
    "Histogram.percentileFromHistogram": _op_histogram_percentile,
    "JSONUtils.getJsonObject": _op_get_json,
    "MapUtils.extractRawMapFromJsonString": _op_from_json,
    "ParseURI.parseURI": _op_parse_uri,
    "RegexRewriteUtils.literalRangePattern": _op_regex_literal_range,
    "RowConversion.convertToRows": _op_rows_to,
    "RowConversion.convertToRowsFixedWidthOptimized": _op_rows_to_fixed,
    "RowConversion.convertFromRows": _op_rows_from,
    "RowConversion.convertFromRowsFixedWidthOptimized": _op_rows_from,
    "ZOrder.interleaveBits": _op_zorder_interleave,
    "ZOrder.hilbertIndex": _op_zorder_hilbert,
    "GpuTimeZoneDB.fromTimestampToUtcTimestamp": _op_tz_to_utc,
    "GpuTimeZoneDB.fromUtcTimestampToTimestamp": _op_tz_from_utc,
    "GpuTimeZoneDB.isSupportedTimeZone": _op_tz_supported,
    "ParquetFooter.readAndFilter": _op_parquet_read_filter,
    "ParquetFooter.getNumRows": _op_parquet_num_rows,
    "ParquetFooter.getNumColumns": _op_parquet_num_columns,
    "ParquetFooter.serializeThriftFile": _op_parquet_serialize,
    "Profiler.init": _op_profiler("init"),
    "Profiler.start": _op_profiler("start"),
    "Profiler.stop": _op_profiler("stop"),
    "Profiler.shutdown": _op_profiler("shutdown"),
}


# error codes shared with jni/src/bridge.h (SrjErrorCode)
(OK, ERR_GENERIC, ERR_CAST, ERR_RETRY_OOM, ERR_SPLIT_OOM, ERR_OOM,
 ERR_CPU_RETRY_OOM, ERR_CPU_SPLIT_OOM) = range(8)


def classify_exception(exc) -> int:
    """Map a Python exception to the bridge/Java exception family.

    The Cpu subclasses must win over their Gpu parents so the Java side
    can throw CpuRetryOOM/CpuSplitAndRetryOOM (host-memory recovery takes
    a different plugin path than device OOM).
    """
    from .mem import rmm_spark as M
    from .ops.cast_string import CastException

    if isinstance(exc, CastException):
        return ERR_CAST
    if isinstance(exc, M.CpuSplitAndRetryOOM):
        return ERR_CPU_SPLIT_OOM
    if isinstance(exc, M.CpuRetryOOM):
        return ERR_CPU_RETRY_OOM
    if isinstance(exc, M.SplitAndRetryOOM):
        return ERR_SPLIT_OOM
    if isinstance(exc, M.RetryOOM):
        return ERR_RETRY_OOM
    if isinstance(exc, M.OOMError):
        return ERR_OOM
    return ERR_GENERIC


def invoke(name: str, args_json: str, objs: list):
    """Run one op. Returns (result_objects, result_json_string)."""
    try:
        fn = _OPS[name]
    except KeyError:
        raise NotImplementedError(f"unknown bridge op {name!r}") from None
    args = json.loads(args_json) if args_json else {}
    out_objs, meta = fn(args, list(objs))
    return out_objs, json.dumps(meta)
