"""Window functions over sorted partitions (the TPC-DS q67 shape).

The reference repo itself carries no window kernels (they live in libcudf),
but q67 — sort + window + rollup — is one of the five driver benchmark
configs (BASELINE.md), so the relational layer needs them.  TPU-first
formulation: one multi-operand ``lax.sort`` by (partition keys, order
keys) carrying payload values, then every window primitive is either a
segmented ``associative_scan`` (running sum/min/max/count) or pure
boundary arithmetic (row_number / rank / dense_rank) — no scatters, same
design as :mod:`aggregate`.

Results come back in the SORTED row order together with the permutation
(``sorted_row``), matching Spark's window-operator output contract where
rows flow on in partition order.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column, ColumnBatch
from . import keys as K
from .gather import gather_batch

_WINDOW_OPS = ("row_number", "rank", "dense_rank", "sum", "min", "max",
               "count", "avg", "lag", "lead")


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    op: str                    # row_number | rank | dense_rank | sum | ...
    column: Optional[str]      # None for row_number/rank/dense_rank/count(*)
    out_name: str
    offset: int = 1            # lag/lead only

    def __post_init__(self):
        if self.op not in _WINDOW_OPS:
            raise ValueError(f"unknown window op {self.op!r}")
        if self.column is None and self.op in ("sum", "min", "max", "avg",
                                               "lag", "lead"):
            raise ValueError(f"{self.op} needs a value column")
        if self.op in ("lag", "lead") and self.offset < 0:
            raise ValueError("lag/lead offset must be >= 0")


def _seg_scan(vals, boundary, combine):
    """Inclusive segmented scan; segments restart where boundary is True."""
    def comb(a, b):
        av, ab = a
        bv, bb = b
        return jnp.where(bb, bv, combine(av, bv)), ab | bb

    out, _ = jax.lax.associative_scan(comb, (vals, boundary))
    return out


def window(
    batch: ColumnBatch,
    partition_by: Sequence[str],
    order_by: Sequence[str],
    specs: Sequence[WindowSpec],
    descending: Sequence[bool] = (),
) -> ColumnBatch:
    """Evaluate window functions; running frame = UNBOUNDED PRECEDING..CURRENT
    ROW for aggregates (Spark's default with ORDER BY).

    Returns the input columns in sorted order plus one column per spec.
    """
    n = batch.num_rows
    pkeys = [batch[k] for k in partition_by]
    okeys = [batch[k] for k in order_by]
    desc = list(descending) if descending else [False] * len(order_by)

    if len(desc) != len(order_by):
        raise ValueError(
            f"descending has {len(desc)} entries for {len(order_by)} "
            "order-by columns")
    karr = K.batch_radix_keys(pkeys, equality=True, nulls_first=True)
    np_part = len(karr)
    for col, d in zip(okeys, desc):
        # Spark default: ASC -> NULLS FIRST, DESC -> NULLS LAST.  Only the
        # DATA words invert for descending; the null flag already encodes
        # its placement and must not be flipped again.
        arrs = [K.null_flag(col, nulls_first=not d)] + [
            ~a if d else a
            for a in (
                jnp.where(col.validity, w, jnp.zeros((), w.dtype))
                for w in K.column_radix_keys(col, equality=False)
            )
        ]
        karr.extend(arrs)

    iota = jnp.arange(n, dtype=jnp.int32)
    res = jax.lax.sort(tuple(karr) + (iota,), num_keys=len(karr),
                       is_stable=True)
    skeys = res[:-1]
    perm = res[-1]
    sorted_batch = gather_batch(batch, perm)

    part_boundary = ~K.rows_equal_adjacent(skeys[:np_part])
    full_boundary = ~K.rows_equal_adjacent(skeys)  # partition + order change

    ones = jnp.ones((n,), jnp.int64)
    # row_number: 1-based position within partition
    rn = _seg_scan(ones, part_boundary, lambda a, b: a + b)
    # dense_rank: count of order-key changes within the partition
    order_change = full_boundary & ~part_boundary
    dr = _seg_scan(order_change.astype(jnp.int64), part_boundary,
                   lambda a, b: a + b) + 1
    # rank: row_number of the first peer — propagate rn at order changes
    first_of_peers = part_boundary | order_change
    rank = _seg_scan(jnp.where(first_of_peers, rn, 0), part_boundary,
                     lambda a, b: jnp.maximum(a, b))

    out = {name: col for name, col in
           zip(sorted_batch.names, sorted_batch.columns)}
    out["sorted_row"] = Column(perm, jnp.ones((n,), jnp.bool_), T.INT32)

    for spec in specs:
        if spec.op == "row_number":
            out[spec.out_name] = Column(rn, jnp.ones((n,), jnp.bool_), T.INT64)
            continue
        if spec.op == "rank":
            out[spec.out_name] = Column(rank, jnp.ones((n,), jnp.bool_),
                                        T.INT64)
            continue
        if spec.op == "dense_rank":
            out[spec.out_name] = Column(dr, jnp.ones((n,), jnp.bool_),
                                        T.INT64)
            continue

        if spec.op == "count" and spec.column is None:
            out[spec.out_name] = Column(rn, jnp.ones((n,), jnp.bool_),
                                        T.INT64)
            continue

        col = sorted_batch[spec.column]
        data, valid = col.data, col.validity

        if spec.op in ("lag", "lead"):
            # partition extents: first index (running min of iota) and
            # last index (running max over the reversed segments)
            ps = _seg_scan(iota, part_boundary, jnp.minimum)
            last_of_part = jnp.concatenate(
                [part_boundary[1:], jnp.ones((1,), jnp.bool_)])
            pe = jnp.flip(_seg_scan(jnp.flip(iota), jnp.flip(last_of_part),
                                    jnp.maximum))
            k = spec.offset
            if spec.op == "lag":
                src_i = iota - k
                ok = src_i >= ps
            else:
                src_i = iota + k
                ok = src_i <= pe
            src_i = jnp.clip(src_i, 0, n - 1)
            from .gather import gather_column

            shifted = gather_column(col, src_i, valid=ok)
            out[spec.out_name] = shifted
            continue

        if spec.op == "count":
            cnt = _seg_scan(valid.astype(jnp.int64), part_boundary,
                            lambda a, b: a + b)
            out[spec.out_name] = Column(cnt, jnp.ones((n,), jnp.bool_),
                                        T.INT64)
            continue

        nn = _seg_scan(valid.astype(jnp.int64), part_boundary,
                       lambda a, b: a + b)
        has_any = nn > 0
        if spec.op in ("sum", "avg"):
            from .aggregate import _sum_dtype

            out_t = T.FLOAT64 if spec.op == "avg" else _sum_dtype(col.dtype)
            acc = data.astype(out_t.jnp_dtype if spec.op == "sum"
                              else jnp.float64)
            acc = jnp.where(valid, acc, jnp.zeros((), acc.dtype))
            s = _seg_scan(acc, part_boundary, lambda a, b: a + b)
            if spec.op == "avg":
                s = s / jnp.maximum(nn, 1).astype(jnp.float64)
            out[spec.out_name] = Column(s, has_any, out_t)
        else:  # min / max running
            is_float = jnp.issubdtype(data.dtype, jnp.floating)
            if is_float:
                fill = jnp.array(jnp.inf if spec.op == "min" else -jnp.inf,
                                 data.dtype)
            else:
                info = jnp.iinfo(data.dtype)
                fill = jnp.array(info.max if spec.op == "min" else info.min,
                                 data.dtype)
            masked = jnp.where(valid, data, fill)
            f = jnp.minimum if spec.op == "min" else jnp.maximum
            r = _seg_scan(masked, part_boundary, f)
            out[spec.out_name] = Column(r, has_any, col.dtype)

    return ColumnBatch(out)
