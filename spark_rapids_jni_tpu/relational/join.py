"""Equality joins with static-shape outputs, engine-selectable probe.

libcudf joins use a GPU hash table; here two engines share one output
contract, picked by the ``join_engine`` knob (``auto | sort | hash``) or
the ``engine=`` argument:

* **sort** — sorted build side + fused lexicographic binary search
  (:func:`keys.equal_range`): log2(n) gather rounds, every probe row in
  flight at once, no scatter anywhere.  The accelerator engine — on TPU
  pointer-chasing scatters serialize on the VPU.
* **hash** — open-addressing slot table over the build side
  (:mod:`hashtable`) + a linear-probe walk per probe row: expected O(1)
  rounds against the sort engine's fixed ~log2(32n) bisection steps,
  and no build-side ``lax.sort``.  The CPU engine — XLA-CPU's sort is
  its slowest primitive.  Output is bit-identical to the sort engine
  (matches enumerate in original right-row order under both; the build
  groups rows by slot with ONE stable single-operand sort).

Both expand matches via the classic offsets/searchsorted expansion,
padded to a static ``capacity``.

Spark semantics: SQL equality join keys — ``null`` matches nothing (inner
drops null-keyed rows, left outer emits them with a null right side, left
anti *keeps* them); float keys normalize -0.0/NaN (equality domain of
:mod:`keys`).

Join types: inner / left / right / full / semi / anti.  ``right`` is the
swapped left join (output keeps the right side's columns first, probe-side
key columns dropped — document order, not semantics).  ``full`` keeps ALL
right columns (keys included) so unmatched right rows retain their key
values, and appends them after the left-join region; its output capacity
is ``capacity + right.num_rows``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar.column import Column, ColumnBatch, Decimal128Column, StringColumn
from ..columnar.encoded import (
    BitPackedColumn,
    DictionaryColumn,
    FrameOfReferenceColumn,
    RunLengthColumn,
    align_encoded_key_columns,
)
from . import keys as K
from .filter import compact
from .gather import gather_batch

_HOWS = ("inner", "left", "right", "full", "semi", "anti")


def _resolve_join_engine(engine):
    """``engine=None`` reads the ``join_engine`` knob; ``auto`` is the
    same platform call as ``groupby_engine`` (hash on CPU, sort on
    accelerators)."""
    from .. import config as _config

    if engine is None:
        engine = _config.get("join_engine")
    if engine == "auto":
        return "hash" if jax.default_backend() == "cpu" else "sort"
    if engine not in ("sort", "hash", "pallas"):
        raise ValueError(f"unknown join engine {engine!r} "
                         "(use 'auto', 'sort', 'hash', or 'pallas')")
    return engine


def _hash_build(rkeys, nr, table_engine: str = "lax"):
    """Hash-engine build product over the build side's radix words.

    Returns the flat tuple ``(owner, rslot, rperm, counts_slot,
    off_slot, *rkeys)`` — the same shape :func:`hash_join` accepts as a
    ``prebuilt`` when ``engine='hash'`` (the ``'pallas'`` engine builds
    a bit-identical tuple through the fused kernel, so the two tags are
    interchangeable on the probe side):

    * ``owner`` int32[S] — slot table (S = 2x the build rows rounded up
      to a power of two: load factor <= 1/2, so insertion always
      terminates and overflow is impossible);
    * ``rslot`` int32[nr] — each build row's slot (== its key group);
    * ``rperm`` int32[nr] — build rows grouped by slot, original order
      within a slot (ONE stable single-operand sort; within one key
      group this is exactly the order the sort engine's stable key sort
      yields, which is what makes the engines bit-identical);
    * ``counts_slot`` int32[S+1] / ``off_slot`` int32[S+1] — per-slot
      row counts and exclusive offsets into ``rperm``.
    """
    from . import hashtable as H

    S = H.next_pow2(2 * nr)
    iota_r = jnp.arange(nr, dtype=jnp.int32)
    owner, rslot, _ = H.build_slot_table(
        rkeys, jnp.ones((nr,), jnp.bool_), S, engine=table_engine)
    counts_slot = jax.ops.segment_sum(
        jnp.ones((nr,), jnp.int32), rslot, num_segments=S + 1)
    off_slot = jnp.cumsum(counts_slot) - counts_slot
    rperm = jax.lax.sort((rslot, iota_r), num_keys=1, is_stable=True)[-1]
    return (owner, rslot, rperm,
            counts_slot.astype(jnp.int32), off_slot.astype(jnp.int32)) \
        + tuple(rkeys)


def _one_null_row_like(batch: ColumnBatch) -> ColumnBatch:
    """A 1-row all-null batch with the same schema (empty-build-side pad).

    The padding row can never match: its null flag differs from every valid
    probe key, and ``counts`` is forced to zero anyway.
    """
    import dataclasses as _dc

    out = {}
    for name, col in zip(batch.names, batch.columns):
        invalid = jnp.zeros((1,), jnp.bool_)
        if isinstance(col, DictionaryColumn):
            # keep the dictionary (and token): downstream concat/keys see
            # a same-dictionary column whose one row is null
            out[name] = _dc.replace(col, codes=jnp.zeros((1,), jnp.uint32),
                                    validity=invalid)
            continue
        if isinstance(col, (RunLengthColumn, FrameOfReferenceColumn)):
            out[name] = Column(
                jnp.zeros((1,), col.dtype.jnp_dtype), invalid, col.dtype)
            continue
        if isinstance(col, BitPackedColumn):
            # keep the packed form (reference/width are program-family
            # aux): one null row = one zero residual lane
            out[name] = _dc.replace(col, lanes=jnp.zeros((1,), jnp.uint32),
                                    validity=invalid)
            continue
        if isinstance(col, StringColumn):
            out[name] = StringColumn(
                jnp.zeros((1, col.max_len), jnp.uint8),
                jnp.zeros((1,), jnp.int32),
                invalid,
                col.dtype,
            )
        elif isinstance(col, Decimal128Column):
            out[name] = Decimal128Column(
                jnp.zeros((1, 2), jnp.uint64), invalid, col.dtype
            )
        else:
            out[name] = Column(
                jnp.zeros((1,), col.data.dtype), invalid, col.dtype
            )
    return ColumnBatch(out)


def hash_join(
    left: ColumnBatch,
    right: ColumnBatch,
    left_on: Sequence[str],
    right_on: Sequence[str],
    how: str = "inner",
    capacity: Optional[int] = None,
    suffixes: tuple = ("", "_r"),
    left_valid=None,
    right_valid=None,
    prebuilt=None,
    engine=None,
) -> tuple:
    """Equality join; returns ``(result_batch, count)``.

    ``capacity`` is the static output row budget for the inner/left-join
    region; when omitted it defaults to ``left.num_rows``, which is
    exact whenever the build side is key-unique (fact-to-dimension) and
    a best-effort budget otherwise (full joins always append up to
    ``right.num_rows`` more rows on top of it).  ``count`` is the true
    match total; ``count > capacity`` signals truncation and callers
    re-run with a bigger budget — the TPU analogue of the reference's
    split-and-retry contract on output-size overflow.

    semi/anti return filtered left rows (padded + count, like ``compact``).

    ``left_valid``/``right_valid`` (bool[n], optional) mark live rows when
    the inputs carry shuffle slot padding: dead right rows never match,
    dead left rows produce no output (not even for left/anti joins, where
    Spark WOULD keep a live null-keyed row).

    ``engine``: ``'sort' | 'hash' | 'pallas' | 'auto'`` (default: the
    ``join_engine`` knob; ``'pallas'`` is the hash engine with the slot
    table built and probed by the fused VMEM kernels in
    :mod:`ops.pallas_kernels` — interpret mode off-accelerator, same
    bits).  All engines produce bit-identical live
    rows; see the module docstring for when each wins.

    ``prebuilt`` skips the build: either a raw build product tuple —
    ``(*sorted_rkeys, rperm)`` for the sort engine, :func:`_hash_build`'s
    tuple for the hash engine; it must match the engine this call
    resolves to — or a :class:`SpillableBuildTable` from
    :func:`spillable_build_table` (pinned for the duration, fetched
    through the retry ladder, probed under whichever engine it was
    (re)built with).  It MUST have been built from the same
    ``right``/``right_on``/``right_valid`` — nothing re-validates that.
    """
    if how not in _HOWS:
        raise ValueError(f"unknown join type {how!r}")
    if len(left_on) != len(right_on):
        raise ValueError("left_on/right_on length mismatch")
    if how == "right":
        if prebuilt is not None:
            # the swap makes the LEFT input the build side; a prebuilt
            # table for the original right would silently probe wrong
            raise ValueError("prebuilt build tables are not supported for "
                             "how='right' (the swap changes the build side)")
        # swapped left join (reference cudf right joins are the same
        # reversal); right side's columns come first in the output
        return hash_join(right, left, right_on, left_on, "left",
                         capacity=capacity, suffixes=(suffixes[1],
                                                      suffixes[0]),
                         left_valid=right_valid, right_valid=left_valid,
                         engine=engine)
    if prebuilt is not None and hasattr(prebuilt, "get"):
        from ..mem.executor import run_with_retry

        # hold the pin across the recursive call so an evictor cannot
        # drop the table (releasing its charge) while the probe is in
        # flight; get() re-runs the build if it was already dropped —
        # under whatever engine the join_engine knob selects at THAT
        # moment, which is why the probe takes the engine from the
        # handle rather than from this call's arguments
        with prebuilt.pinned():
            built = run_with_retry(prebuilt.get)
            return hash_join(left, right, left_on, right_on, how,
                             capacity=capacity, suffixes=suffixes,
                             left_valid=left_valid, right_valid=right_valid,
                             prebuilt=tuple(built),
                             engine=getattr(prebuilt, "engine", "sort"))

    nl, nr = left.num_rows, right.num_rows
    padded_right = nr == 0
    if nr == 0:
        if prebuilt is not None:
            raise ValueError("prebuilt build table for an empty build side")
        # pad the build side with one unmatchable null row: downstream
        # gathers stay in-bounds and every probe misses (count semantics of
        # an empty build: inner/semi -> 0 rows, left -> all-null right, anti
        # -> all left rows)
        right = _one_null_row_like(right)
        nr = 1
    if nl == 0:
        # empty probe side (e.g. how='right' over an empty right input):
        # one DEAD pad row keeps every downstream gather in-bounds while
        # producing no output — count semantics of an empty probe are 0
        # rows for every join type except full, which still appends the
        # unmatched right rows
        left = _one_null_row_like(left)
        nl = 1
        left_valid = jnp.zeros((1,), jnp.bool_)
    lkcols = [left[k] for k in left_on]
    rkcols = [right[k] for k in right_on]
    if prebuilt is None:
        # canon fast path: key pairs over the SAME dictionary (static
        # dict_token match) collapse to one u32 word per column; pairs
        # from different dictionaries keep the gathered-value-words
        # lowering, which is correct across dictionaries — the decoded
        # fallback inside the same program.  A prebuilt table's keys are
        # always value words, so substitution is skipped for it.
        lkcols, rkcols = align_encoded_key_columns(lkcols, rkcols)
    lcols, rcols = K.align_string_key_columns(lkcols, rkcols)
    if right_valid is not None:
        import dataclasses as _dc

        rcols = [_dc.replace(c, validity=c.validity & right_valid)
                 for c in rcols]

    engine = _resolve_join_engine(engine)
    lkeys = K.batch_radix_keys(lcols, equality=True, nulls_first=False)
    l_null = jnp.zeros((nl,), jnp.bool_)
    for c in lcols:
        l_null = l_null | ~c.validity
    l_live = (jnp.ones((nl,), jnp.bool_) if left_valid is None
              else left_valid.astype(jnp.bool_))

    # build + probe.  Null build keys can never match: under the sort
    # engine they sort last and their flag word mismatches every valid
    # probe; under the hash engine they sit in their own slot that no
    # valid probe's words equal.  Null/dead probe rows are masked either
    # way.  Both engines yield the same (counts, lo, rperm) semantics:
    # a probe row's matches are rperm[lo .. lo+counts), enumerated in
    # original right-row order.
    rkeys = None
    if engine in ("hash", "pallas"):
        from . import hashtable as H
        from ..plan import adaptive as _adaptive

        table_engine = "pallas" if engine == "pallas" else "lax"
        if prebuilt is not None:
            owner, rslot, rperm = prebuilt[0], prebuilt[1], prebuilt[2]
            counts_slot, off_slot = prebuilt[3], prebuilt[4]
            rkeys = tuple(prebuilt[5:])
        else:
            rkeys = K.batch_radix_keys(rcols, equality=True,
                                       nulls_first=False)
            built = _hash_build(rkeys, nr, table_engine)
            owner, rslot, rperm, counts_slot, off_slot = built[:5]
        probe_live = ~l_null & l_live
        found, lslot = H.probe_slot_table(
            owner, rkeys, lkeys, probe_live,
            max_rounds=_adaptive.bound_probe_rounds(owner, nr),
            engine=table_engine)
        counts = jnp.where(found, jnp.take(counts_slot, lslot),
                           jnp.int32(0))
        lo = jnp.take(off_slot, lslot)
    else:
        if prebuilt is not None:
            sorted_rkeys, rperm = tuple(prebuilt[:-1]), prebuilt[-1]
        else:
            rkeys = K.batch_radix_keys(rcols, equality=True,
                                       nulls_first=False)
            iota_r = jnp.arange(nr, dtype=jnp.int32)
            sorted_ops = jax.lax.sort(
                tuple(rkeys) + (iota_r,), num_keys=len(rkeys),
                is_stable=True
            )
            sorted_rkeys, rperm = sorted_ops[:-1], sorted_ops[-1]
        lo, hi = K.equal_range(sorted_rkeys, lkeys)
        counts = jnp.where(l_null, 0, hi - lo).astype(jnp.int32)
        counts = jnp.where(l_live, counts, 0)

    if how == "semi":
        return compact(left, (counts > 0) & l_live)
    if how == "anti":
        return compact(left, (counts == 0) & l_live)

    outer = how in ("left", "full")
    counts_out = jnp.where(l_live, jnp.maximum(counts, 1), 0) if outer \
        else counts
    cum = jnp.cumsum(counts_out)  # inclusive
    total = cum[-1] if nl else jnp.int32(0)
    offsets = cum - counts_out

    if capacity is None:
        capacity = nl
    j = jnp.arange(capacity, dtype=jnp.int32)
    # source left row for each output slot
    li = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    li = jnp.clip(li, 0, max(nl - 1, 0))
    k = j - offsets[li] if nl else jnp.zeros_like(j)
    pos = jnp.clip(lo[li] + k, 0, max(nr - 1, 0))
    ri = rperm[pos] if nr else jnp.zeros_like(j)

    out_valid = j < total
    matched = (counts[li] > 0) & out_valid if nl else jnp.zeros_like(out_valid)

    lpart = gather_batch(left, li, out_valid)
    # full joins keep the right key columns so unmatched right rows
    # retain their key values in the appended region
    right_names = (list(right.names) if how == "full"
                   else [n for n in right.names if n not in right_on])
    rpart = gather_batch(
        right.select(right_names) if right_names else ColumnBatch({}),
        ri,
        matched if outer else out_valid,
    )

    if how == "full":
        r_live = (jnp.ones((nr,), jnp.bool_) if right_valid is None
                  else right_valid.astype(jnp.bool_))
        if engine in ("hash", "pallas"):
            # a right row is matched iff some live non-null probe row
            # FOUND its slot: scatter-OR the probe hits over the slot
            # table, then read each build row's slot back.  (Misses and
            # dead probes carry lslot == S, the absorbing extra slot.)
            S = owner.shape[0]
            hit = jnp.zeros((S + 1,), jnp.bool_).at[lslot].max(found)
            unmatched = ~jnp.take(hit, rslot) & r_live
        else:
            # unmatched right rows: probe the LEFT keys with the right
            # keys.  Dead (shuffle-padding) left rows must not count as
            # matches: re-key them as nulls, which sort last and match
            # nothing.
            if left_valid is not None:
                import dataclasses as _dc

                lcols_live = [_dc.replace(c, validity=c.validity & l_live)
                              for c in lcols]
                lkeys = K.batch_radix_keys(lcols_live, equality=True,
                                           nulls_first=False)
            lkeys_sorted_ops = jax.lax.sort(
                tuple(lkeys) + (jnp.arange(nl, dtype=jnp.int32),),
                num_keys=len(lkeys), is_stable=True)
            sorted_lkeys = lkeys_sorted_ops[:-1]
            if rkeys is None:
                # prebuilt path carries only the SORTED keys; the reverse
                # probe needs them in right-row order
                rkeys = K.batch_radix_keys(rcols, equality=True,
                                           nulls_first=False)
            rlo, rhi = K.equal_range(sorted_lkeys, rkeys)
            r_null = jnp.zeros((nr,), jnp.bool_)
            for c in rcols:
                r_null = r_null | ~c.validity
            rcounts = jnp.where(r_null | ~r_live, 0, rhi - rlo)
            unmatched = (rcounts == 0) & r_live
        if padded_right:
            # the synthetic 1-row pad (empty build side) is not a real
            # right row; it must not be appended
            unmatched = jnp.zeros_like(unmatched)
        n_un = jnp.sum(unmatched.astype(jnp.int32))
        order = jnp.argsort(~unmatched, stable=True).astype(jnp.int32)
        app_valid = jnp.arange(nr, dtype=jnp.int32) < n_un
        rpart_app = gather_batch(right.select(right_names), order, app_valid)
        lpart_app = gather_batch(left, jnp.zeros((nr,), jnp.int32),
                                 jnp.zeros((nr,), jnp.bool_))
        lpart = _concat_batches(lpart, lpart_app)
        rpart = _concat_batches(rpart, rpart_app)
        # the append region sits at offset `capacity`; pull it up so live
        # rows are contiguous.  If the left-join region overflowed its
        # budget (emitted_main < true total_main), surface an
        # unambiguous overflow count — capacity+nr+1 always exceeds any
        # representable output, so the caller's count>capacity check
        # fires instead of garbage rows being presented as live.
        total_main = total
        emitted_main = jnp.minimum(total_main, capacity)
        total = jnp.where(total_main > capacity,
                          jnp.int32(capacity + nr + 1),
                          total_main + n_un)
        idx = jnp.arange(capacity + nr, dtype=jnp.int32)
        srcrow = jnp.where(idx < emitted_main, idx,
                           capacity + idx - emitted_main)
        srcrow = jnp.clip(srcrow, 0, capacity + nr - 1)
        live = idx < emitted_main + n_un
        lpart = gather_batch(lpart, srcrow, live)
        rpart = gather_batch(rpart, srcrow, live)

    return _merge_parts(lpart, rpart, suffixes), total


def join_dense_or_hash(
    left: ColumnBatch,
    right: ColumnBatch,
    left_on: str,
    right_on: str,
    domain: int,
    how: str = "inner",
    capacity: Optional[int] = None,
    suffixes: tuple = ("", "_r"),
    left_valid=None,
    right_valid=None,
) -> tuple:
    """Adaptive inner join for the dimension-table shape: when the build
    side's keys are UNIQUE ints in ``[0, domain)`` (dense surrogate keys
    — every TPC-DS dim), the sort+binary-search engine reduces to one
    scatter (build a ``[domain]`` rowid table) plus gathers; otherwise
    one ``lax.cond`` runs the general :func:`hash_join`.  Same adaptive
    pattern as ``group_by_domain_or_sort``: both branches trace, the
    data picks at runtime, and the output contract (row order = matches
    compacted in left-row order, ``(result, count)``, ``count >
    capacity`` = truncation) is bit-identical between branches.

    Only single-int-key inner joins take the dense path; anything else
    delegates to :func:`hash_join` outright.  Measured r5 on the q95
    shape (64K fact x 8K dim, 1-core XLA-CPU): the general engine's
    per-join cost is dominated by the build sort that this path skips.
    """
    lcol, rcol = left[left_on], right[right_on]
    eligible = (how == "inner" and domain > 0
                and not isinstance(lcol, (StringColumn, Decimal128Column,
                                          DictionaryColumn, RunLengthColumn,
                                          BitPackedColumn,
                                          FrameOfReferenceColumn))
                and not isinstance(rcol, (StringColumn, Decimal128Column,
                                          DictionaryColumn, RunLengthColumn,
                                          BitPackedColumn,
                                          FrameOfReferenceColumn))
                and jnp.issubdtype(lcol.data.dtype, jnp.integer)
                and jnp.issubdtype(rcol.data.dtype, jnp.integer)
                and right.num_rows > 0)
    if not eligible:
        return hash_join(left, right, [left_on], [right_on], how,
                         capacity=capacity, suffixes=suffixes,
                         left_valid=left_valid, right_valid=right_valid)

    nl, nr = left.num_rows, right.num_rows
    K1 = int(domain)
    cap = nl if capacity is None else int(capacity)

    rv = (jnp.ones((nr,), jnp.bool_) if right_valid is None
          else right_valid.astype(jnp.bool_))
    r_live = rcol.validity & rv
    rk = rcol.data.astype(jnp.int32)
    in_dom = r_live & (rk >= 0) & (rk < K1)
    slot = jnp.where(in_dom, rk, K1)          # K1 = discard slot
    cnt = jnp.zeros((K1 + 1,), jnp.int32).at[slot].add(1)
    # wider-than-32-bit keys must round-trip the int32 cast exactly on
    # BOTH sides, else a key >= 2^32 could wrap into [0, domain) and
    # fabricate matches the general engine would never produce
    lv_pre = (jnp.ones((nl,), jnp.bool_) if left_valid is None
              else left_valid.astype(jnp.bool_))
    lk32 = lcol.data.astype(jnp.int32)
    no_wrap = (
        jnp.all((rk.astype(rcol.data.dtype) == rcol.data) | ~r_live)
        & jnp.all((lk32.astype(lcol.data.dtype) == lcol.data)
                  | ~(lcol.validity & lv_pre)))
    dense_ok = (jnp.all(in_dom | ~r_live) & jnp.all(cnt[:K1] <= 1)
                & no_wrap)

    def dense(_):
        rowid = jnp.zeros((K1 + 1,), jnp.int32).at[slot].set(
            jnp.arange(nr, dtype=jnp.int32))
        present = cnt[:K1] > 0
        lv = (jnp.ones((nl,), jnp.bool_) if left_valid is None
              else left_valid.astype(jnp.bool_))
        lk = lcol.data.astype(jnp.int32)
        lk_ok = lcol.validity & lv & (lk >= 0) & (lk < K1)
        lk_safe = jnp.where(lk_ok, lk, 0)
        match = lk_ok & present[lk_safe]
        total = jnp.sum(match, dtype=jnp.int32)
        from ..parallel.partition import regroup_order

        order = regroup_order(jnp.where(match, 0, 1), 2)  # matches first
        li = order[:cap] if cap <= nl else jnp.pad(
            order, (0, cap - nl), constant_values=0)
        out_valid = jnp.arange(cap, dtype=jnp.int32) < total
        ri = rowid[jnp.clip(jnp.take(lk_safe, li), 0, K1)]
        lpart = gather_batch(left, li, out_valid)
        right_names = [n for n in right.names if n != right_on]
        rpart = gather_batch(
            right.select(right_names) if right_names else ColumnBatch({}),
            ri, out_valid)
        return _merge_parts(lpart, rpart, suffixes), total

    def general(_):
        return hash_join(left, right, [left_on], [right_on], "inner",
                         capacity=cap, suffixes=suffixes,
                         left_valid=left_valid, right_valid=right_valid)

    return jax.lax.cond(dense_ok, dense, general, None)


def _merge_parts(lpart: ColumnBatch, rpart: ColumnBatch,
                 suffixes: tuple) -> ColumnBatch:
    """Suffix-disambiguating column merge shared by the join engines."""
    collisions = set(lpart.names) & set(rpart.names)
    merged = {}
    for part, suffix in ((lpart, suffixes[0]), (rpart, suffixes[1])):
        for name, col in zip(part.names, part.columns):
            out = name + suffix if name in collisions else name
            if out in merged:
                raise ValueError(
                    f"join output name collision: {out!r} "
                    f"(suffixes={suffixes!r})")
            merged[out] = col
    return ColumnBatch(merged)


def _concat_col(a, b):
    if isinstance(a, (BitPackedColumn, FrameOfReferenceColumn)) or \
            isinstance(b, (BitPackedColumn, FrameOfReferenceColumn)):
        # packed lane streams are not concatenable unless the first ends
        # lane-aligned (n*width % 32 == 0) AND the static aux matches —
        # concat is an output boundary, so materialize like mixed dicts
        from ..columnar.encoded import materialize_column

        a, b = materialize_column(a), materialize_column(b)
    if isinstance(a, DictionaryColumn) or isinstance(b, DictionaryColumn):
        import dataclasses as _dc

        if (isinstance(a, DictionaryColumn) and isinstance(b, DictionaryColumn)
                and a.dict_token == b.dict_token and a.dict_token > 0):
            # same dictionary: codes concatenate directly, stays encoded
            return _dc.replace(a, codes=jnp.concatenate([a.codes, b.codes]),
                               validity=jnp.concatenate([a.validity,
                                                         b.validity]))
        from ..columnar.encoded import materialize_column

        a, b = materialize_column(a), materialize_column(b)
    if isinstance(a, StringColumn):
        W = max(a.max_len, b.max_len)

        def pad(c):
            return jnp.pad(c.chars, ((0, 0), (0, W - c.max_len)))

        return StringColumn(
            jnp.concatenate([pad(a), pad(b)]),
            jnp.concatenate([a.lengths, b.lengths]),
            jnp.concatenate([a.validity, b.validity]), a.dtype)
    if isinstance(a, Decimal128Column):
        return Decimal128Column(
            jnp.concatenate([a.limbs, b.limbs]),
            jnp.concatenate([a.validity, b.validity]), a.dtype)
    return Column(jnp.concatenate([a.data, b.data]),
                  jnp.concatenate([a.validity, b.validity]), a.dtype)


def _concat_batches(a: ColumnBatch, b: ColumnBatch) -> ColumnBatch:
    return ColumnBatch({n: _concat_col(a[n], b[n]) for n in a.names})


# ---------------------------------------------------------------------------
# spillable build tables: eviction drops, read-back rebuilds
# ---------------------------------------------------------------------------

def spillable_build_table(right: ColumnBatch, right_on: Sequence[str],
                          right_valid=None, ctx=None,
                          name: Optional[str] = None, engine=None):
    """Register a join build table (the build product over
    ``right[right_on]``) in the spill framework as a
    :class:`SpillableBuildTable`.

    The reference spills hash-join build-side GpuColumnarBatches like any
    other buffer; here the build product is *derived* state — the source
    columns stay with the caller — so eviction just DROPS it (releasing
    the device charge with no host copy) and ``get()`` re-runs the
    compiled build.  Recompute-over-copy is the right trade for a product
    the probe can deterministically regenerate.

    The build product's SHAPE follows ``engine`` (sorted keys +
    permutation for the sort engine, :func:`_hash_build`'s slot-table
    tuple for the hash engine).  With ``engine=None`` the
    ``join_engine`` knob is re-read at every rebuild: a table built
    under one engine and evicted rebuilds under whatever engine is
    active THEN, and the handle's ``engine`` attribute tells
    ``hash_join(prebuilt=...)`` how to probe what it got.  Pass an
    explicit engine to PIN it across rebuilds — what the plan
    compiler's adaptive broadcast decision does, so an eviction-driven
    rebuild can never disagree with the engine the compiled program was
    traced against.

    Pass the result as ``hash_join(..., prebuilt=table)`` to reuse one
    build across many probe batches.  Close it when done.

    Raises for string join keys (their radix width is aligned to the
    probe side's ``max_len``, so a probe-independent prebuild could
    disagree with what ``hash_join`` derives) and for an empty build side
    (which ``hash_join`` pads with a synthetic row).
    """
    if right.num_rows == 0:
        raise ValueError("cannot pre-build an empty build side")
    rcols = [right[k] for k in right_on]
    if any(isinstance(c, StringColumn)
           or (isinstance(c, DictionaryColumn)
               and isinstance(c.dictionary, StringColumn))
           for c in rcols):
        raise ValueError(
            "string join keys cannot be pre-built: their radix key width "
            "depends on the probe side (align_string_key_columns)")
    if right_valid is not None:
        import dataclasses as _dc

        rcols = [_dc.replace(c, validity=c.validity & right_valid)
                 for c in rcols]
    nr = right.num_rows

    def builder():
        # pinned engine, else the knob at (re)build time
        eng = _resolve_join_engine(engine)
        rkeys = K.batch_radix_keys(rcols, equality=True, nulls_first=False)
        if eng in ("hash", "pallas"):
            return eng, _hash_build(rkeys, nr,
                                    "pallas" if eng == "pallas" else "lax")
        iota_r = jnp.arange(nr, dtype=jnp.int32)
        return eng, tuple(jax.lax.sort(
            tuple(rkeys) + (iota_r,), num_keys=len(rkeys), is_stable=True))

    return SpillableBuildTable(builder, ctx=ctx, name=name)


from ..mem.spill import SpillableHandle as _SpillableHandle  # noqa: E402


class SpillableBuildTable(_SpillableHandle):
    """A :class:`~spark_rapids_jni_tpu.mem.spill.SpillableHandle` whose
    payload is recomputed rather than copied: ``spill()`` drops the device
    tree and releases the charge (no host/disk tiers); read-back goes
    through the base class's generalized ``recompute=`` lineage path,
    which re-charges and re-runs the stored builder.

    ``builder`` returns ``(engine, tree)``; the engine tag of the most
    recent (re)build is exposed as ``self.engine`` so the probe side
    interprets the tree correctly even when the ``join_engine`` knob
    changed between eviction and read-back."""

    def __init__(self, builder, ctx=None, name: Optional[str] = None):
        self._builder = builder
        super().__init__(self._build(), ctx=ctx,
                         name=name or f"build-table-{id(self):x}",
                         recompute=self._build)

    def _build(self):
        self.engine, tree = self._builder()
        return tree

    @property
    def rebuilds(self) -> int:
        return self.lineage_rebuilds

    def spill(self) -> int:
        if not self._lock.acquire(blocking=False):
            return 0  # busy in another thread's get(): treat as pinned
        try:
            if self._closed or self._tree is None or self._pins > 0:
                return 0
            self._tree = None
            freed = self._device_charged
            if self._ctx is not None and self._device_charged:
                self._ctx.release(self._device_charged)
                self._device_charged = 0
            if self._fw is not None:
                # dropping IS this handle's device->host transition for
                # accounting purposes: zero bytes moved, one eviction
                self._fw.metrics.record("device_to_host", 0, self.task_id)
            return freed
        finally:
            self._lock.release()

    spill_host = spill  # no host tier to demote; keep the interface
