"""Row gather for every column representation.

The workhorse behind sort / filter-compaction / join materialization: one
permutation (or index) vector applied to each buffer of each column.  On TPU
this lowers to XLA gathers, which vectorize on the VPU; the string char
matrix gathers whole padded rows (a 2-D gather with a broadcast index).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..columnar.column import Column, ColumnBatch, Decimal128Column, StringColumn
from ..columnar.encoded import (
    BitPackedColumn,
    DictionaryColumn,
    FrameOfReferenceColumn,
    RunLengthColumn,
    gather_bitpacked,
)


def gather_column(col, idx, valid=None):
    """Take rows ``idx`` (int32[m], clipped); rows where ``valid`` is False
    become nulls (used for padded filter/join outputs)."""
    if isinstance(col, (RunLengthColumn, FrameOfReferenceColumn)):
        # runs / FoR blocks do not survive an arbitrary permutation:
        # decode here (a sanctioned materialization point) so neither
        # flows deeper
        col = col.decode()
    n = col.num_rows
    idx = jnp.clip(idx, 0, max(n - 1, 0))
    if isinstance(col, BitPackedColumn):
        # the global reference DOES survive permutation: extract
        # residuals, take, repack — the output stays packed
        return gather_bitpacked(col, idx, valid)
    if isinstance(col, DictionaryColumn):
        # gather CODES; the dictionary (and its token) ride along, so the
        # output stays encoded through compaction and join materialization
        v = col.validity[idx]
        if valid is not None:
            v = v & valid
        return dataclasses.replace(col, codes=col.codes[idx], validity=v)
    if isinstance(col, StringColumn):
        v = col.validity[idx]
        if valid is not None:
            v = v & valid
        return StringColumn(col.chars[idx], col.lengths[idx] * v, v, col.dtype)
    if isinstance(col, Decimal128Column):
        v = col.validity[idx]
        if valid is not None:
            v = v & valid
        return Decimal128Column(col.limbs[idx], v, col.dtype)
    v = col.validity[idx]
    if valid is not None:
        v = v & valid
    return Column(col.data[idx], v, col.dtype)


def gather_batch(batch: ColumnBatch, idx, valid=None) -> ColumnBatch:
    return ColumnBatch(
        {
            name: gather_column(col, idx, valid)
            for name, col in zip(batch.names, batch.columns)
        }
    )
