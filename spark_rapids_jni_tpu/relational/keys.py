"""Order-preserving radix keys for sort / group-by / join.

Each key column is lowered to a list of ``uint32`` arrays such that comparing
rows by the concatenated arrays in unsigned lexicographic order reproduces
Spark's SQL ordering:

* signed ints: XOR the sign bit (``x ^ 0x80000000`` reinterpreted unsigned).
* floats: IEEE-754 total-order transform — negative values flip all bits,
  non-negative flip only the sign bit.  For *equality domains* (group/join)
  Spark first normalizes ``-0.0`` to ``0.0`` and every NaN to the canonical
  quiet NaN (NormalizeFloatingNumbers); for ordering, NaN sorts greater than
  +Inf, which the total-order transform already gives.
* 64-bit values emit (hi, lo) uint32 pairs — native 32-bit lanes on the VPU.
* strings: big-endian 4-byte words of the padded char matrix.  Trailing
  padding is zero, and a shorter string is a prefix of nothing else on equal
  words, so unsigned word order == byte order (cudf strings compare bytewise
  the same way).
* decimal128: sign-flipped high limb then lower limbs (values of one Spark
  decimal column share a scale, so unscaled-value order == value order).
* validity: one leading flag array placing nulls first or last.

The same lowering feeds ``lax.sort`` operands (sort), segment-boundary
detection (group-by) and lexicographic binary search (join probe).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import types as T
from ..columnar.column import Column, Decimal128Column, StringColumn
from ..columnar.encoded import (
    BitPackedColumn,
    DictionaryColumn,
    FrameOfReferenceColumn,
    RunLengthColumn,
)

# numpy, not jnp: module scope must not mint device arrays (GL001)
_SIGN32 = np.uint32(0x80000000)
_F64_QNAN = np.uint64(0x7FF8000000000000)


def _split64(u64):
    """uint64[n] -> (hi, lo) uint32 pair."""
    return (u64 >> jnp.uint64(32)).astype(jnp.uint32), (
        u64 & jnp.uint64(0xFFFFFFFF)
    ).astype(jnp.uint32)


_F32_QNAN = np.uint32(0x7FC00000)


def _f32_total_order(d, normalize_zero: bool):
    if normalize_zero:
        d = jnp.where(d == 0.0, jnp.float32(0.0), d)
    bits = jax.lax.bitcast_convert_type(d, jnp.uint32)
    # all NaNs canonicalize (Java Double.compare semantics: one NaN, greatest)
    bits = jnp.where(jnp.isnan(d), _F32_QNAN, bits)
    neg = (bits & _SIGN32) != 0
    return jnp.where(neg, ~bits, bits ^ _SIGN32)


def _f64_total_order(d, normalize_zero: bool):
    if normalize_zero:
        d = jnp.where(d == 0.0, jnp.float64(0.0), d)
    # bitcast via uint32 pair: TPU X64 rewrite can't bitcast 64-bit lanes
    pair = jax.lax.bitcast_convert_type(d, jnp.uint32)
    lo = pair[..., 0].astype(jnp.uint64)
    hi = pair[..., 1].astype(jnp.uint64)
    bits = lo | (hi << 32)
    bits = jnp.where(jnp.isnan(d), _F64_QNAN, bits)
    neg = (bits >> jnp.uint64(63)) != 0
    sign64 = jnp.uint64(1) << jnp.uint64(63)
    return jnp.where(neg, ~bits, bits ^ sign64)


def column_radix_keys(col, *, equality: bool = False) -> list:
    """Lower one column to its list of uint32 key arrays (nulls not encoded).

    ``equality=True`` applies Spark's equality-domain float normalization
    (NormalizeFloatingNumbers: -0.0 -> 0.0 for group-by / join / partition
    keys).  Ordering domains (sort) keep -0.0 < 0.0, matching Java
    ``Double.compare``.  NaNs canonicalize in both domains (Java has one NaN,
    greater than +Inf).
    """
    if isinstance(col, DictionaryColumn):
        # words computed once on the d-entry dictionary, then gathered by
        # code: cross-dictionary safe (both sides lower to VALUE words),
        # and the per-row cost is one gather instead of a padded compare.
        # The single-word canon fast path lives in encoded.py and is
        # substituted by callers only under a dict_token match.
        idx = col.codes.astype(jnp.int32)
        return [w[idx] for w in
                column_radix_keys(col.dictionary, equality=equality)]
    if isinstance(col, RunLengthColumn):
        run = col.row_to_run()
        values = Column(col.run_values,
                        jnp.ones((col.num_runs,), jnp.bool_), col.dtype)
        return [w[run] for w in column_radix_keys(values, equality=equality)]
    if isinstance(col, BitPackedColumn):
        # reference+residual arithmetic, not a decode: the packed column
        # lowers straight to VALUE words, so it groups/joins against
        # plain int columns (and differently-referenced packed ones)
        # bit-identically
        vals = col.residuals().astype(jnp.int64) + col.reference
        return _int_value_words(vals, col.dtype)
    if isinstance(col, FrameOfReferenceColumn):
        return _int_value_words(col.values64(), col.dtype)
    if isinstance(col, StringColumn):
        chars, L = col.chars, col.max_len
        nwords = max(1, -(-L // 4))
        pad = nwords * 4 - L
        if pad:
            chars = jnp.pad(chars, ((0, 0), (0, pad)))
        w = chars.astype(jnp.uint32).reshape(chars.shape[0], nwords, 4)
        words = (w[:, :, 0] << 24) | (w[:, :, 1] << 16) | (w[:, :, 2] << 8) | w[:, :, 3]
        # trailing length key: padding is zero bytes, so equal-word prefixes
        # fall through to the length — distinguishes 'a' from 'a\x00'
        return [words[:, i] for i in range(nwords)] + [
            col.lengths.astype(jnp.uint32)
        ]
    if isinstance(col, Decimal128Column):
        if col.dtype.decimal_storage_bits < 128:
            lo_limb = col.limbs[:, 0]
            hi, lo = _split64(lo_limb ^ (jnp.uint64(1) << jnp.uint64(63)))
            return [hi, lo]
        hi_limb = col.limbs[:, 1] ^ (jnp.uint64(1) << jnp.uint64(63))
        parts = _split64(hi_limb) + _split64(col.limbs[:, 0])
        return list(parts)

    kind = col.dtype.kind
    d = col.data
    if kind is T.Kind.BOOLEAN:
        return [d.astype(jnp.uint32)]
    if kind in (T.Kind.INT8, T.Kind.INT16, T.Kind.INT32, T.Kind.DATE):
        return [d.astype(jnp.int32).astype(jnp.uint32) ^ _SIGN32]
    if kind in (T.Kind.INT64, T.Kind.TIMESTAMP):
        u = d.astype(jnp.int64).astype(jnp.uint64) ^ (jnp.uint64(1) << jnp.uint64(63))
        return list(_split64(u))
    if kind is T.Kind.FLOAT32:
        return [_f32_total_order(d, normalize_zero=equality)]
    if kind is T.Kind.FLOAT64:
        return list(_split64(_f64_total_order(d, normalize_zero=equality)))
    raise NotImplementedError(f"radix keys for {col.dtype!r}")


def _int_value_words(vals64, dtype) -> list:
    """int64[n] decoded values -> the kind's order-preserving words
    (shared by the packed-column lowerings)."""
    kind = dtype.kind
    if kind in (T.Kind.INT8, T.Kind.INT16, T.Kind.INT32, T.Kind.DATE):
        return [vals64.astype(jnp.int32).astype(jnp.uint32) ^ _SIGN32]
    if kind in (T.Kind.INT64, T.Kind.TIMESTAMP):
        u = vals64.astype(jnp.uint64) ^ (jnp.uint64(1) << jnp.uint64(63))
        return list(_split64(u))
    raise NotImplementedError(f"packed radix keys for {dtype!r}")


def null_flag(col, nulls_first: bool) -> jax.Array:
    """Leading key array encoding null placement (0 sorts before 1)."""
    v = col.validity
    return jnp.where(v, jnp.uint32(1), jnp.uint32(0)) if nulls_first else jnp.where(
        v, jnp.uint32(0), jnp.uint32(1)
    )


def batch_radix_keys(
    cols: Sequence, *, equality: bool, nulls_first: bool = True
) -> list:
    """Key arrays for a composite key across columns, nulls flag included.

    Data keys of null rows are zeroed so every null row carries identical
    keys: padded/filtered batches keep residual payload data under a False
    validity bit, and Spark groups all nulls as ONE group.
    """
    out = []
    for c in cols:
        out.append(null_flag(c, nulls_first))
        v = c.validity
        out.extend(
            jnp.where(v, k, jnp.zeros((), k.dtype))
            for k in column_radix_keys(c, equality=equality)
        )
    return out


def rows_equal_adjacent(key_arrays: Sequence[jax.Array]) -> jax.Array:
    """bool[n]: row i has identical keys to row i-1 (row 0 -> False)."""
    n = key_arrays[0].shape[0]
    eq = jnp.ones((n,), jnp.bool_)
    for k in key_arrays:
        eq = eq & (k == jnp.roll(k, 1))
    return eq.at[0].set(False)


def _lex_less(a_keys, b_keys, or_equal: bool):
    """Vectorized lexicographic a < b (or a <= b) over parallel key lists."""
    res = jnp.full(a_keys[0].shape, or_equal)
    for a, b in zip(reversed(a_keys), reversed(b_keys)):
        res = jnp.where(a == b, res, a < b)
    return res


def _search(sorted_keys, query_keys, *, lower: bool):
    """Vectorized lexicographic binary search over sorted composite keys.

    Returns int32 positions in [0, n].  ``lower=True`` gives the first index
    whose key is >= query (lower bound); else first index > query.
    """
    if len(sorted_keys) != len(query_keys):
        raise ValueError(
            f"composite key arity mismatch: {len(sorted_keys)} sorted vs "
            f"{len(query_keys)} query arrays (string key columns must be "
            "width-aligned first — see align_string_key_columns)"
        )
    n = sorted_keys[0].shape[0]
    m = query_keys[0].shape[0]
    if n == 0:
        return jnp.zeros((m,), jnp.int32)
    lo = jnp.zeros((m,), jnp.int32)
    hi = jnp.full((m,), n, jnp.int32)
    steps = n.bit_length() + 1

    def body(_, lohi):
        lo, hi = lohi
        active = lo < hi
        mid = (lo + hi) >> 1
        mid_keys = [jnp.take(k, mid, mode="clip") for k in sorted_keys]
        # advance when sorted[mid] < q (lower) / sorted[mid] <= q (upper)
        adv = _lex_less(mid_keys, query_keys, or_equal=not lower)
        lo = jnp.where(active & adv, mid + 1, lo)
        hi = jnp.where(active & ~adv, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def lower_bound(sorted_keys, query_keys):
    return _search(sorted_keys, query_keys, lower=True)


def upper_bound(sorted_keys, query_keys):
    return _search(sorted_keys, query_keys, lower=False)


def equal_range(sorted_keys, query_keys):
    """(lower, upper) bounds in one fused loop — both carried as state, so
    the probe pays one round of composite-key gathers per bisection step
    instead of two (the join's dominant cost)."""
    if len(sorted_keys) != len(query_keys):
        raise ValueError(
            f"composite key arity mismatch: {len(sorted_keys)} sorted vs "
            f"{len(query_keys)} query arrays (string key columns must be "
            "width-aligned first — see align_string_key_columns)"
        )
    n = sorted_keys[0].shape[0]
    m = query_keys[0].shape[0]
    if n == 0:
        z = jnp.zeros((m,), jnp.int32)
        return z, z
    init = (
        jnp.zeros((m,), jnp.int32),
        jnp.full((m,), n, jnp.int32),
        jnp.zeros((m,), jnp.int32),
        jnp.full((m,), n, jnp.int32),
    )
    steps = n.bit_length() + 1

    def body(_, st):
        llo, lhi, ulo, uhi = st
        # two bisections share each round's gather when their mids coincide
        # (XLA CSEs the duplicate takes); state stays a flat 4-tuple
        lmid = (llo + lhi) >> 1
        umid = (ulo + uhi) >> 1
        lkeys = [jnp.take(k, lmid, mode="clip") for k in sorted_keys]
        ukeys = [jnp.take(k, umid, mode="clip") for k in sorted_keys]
        ladv = _lex_less(lkeys, query_keys, or_equal=False)
        uadv = _lex_less(ukeys, query_keys, or_equal=True)
        lact = llo < lhi
        uact = ulo < uhi
        llo = jnp.where(lact & ladv, lmid + 1, llo)
        lhi = jnp.where(lact & ~ladv, lmid, lhi)
        ulo = jnp.where(uact & uadv, umid + 1, ulo)
        uhi = jnp.where(uact & ~uadv, umid, uhi)
        return llo, lhi, ulo, uhi

    llo, _, ulo, _ = jax.lax.fori_loop(0, steps, body, init)
    return llo, ulo


def align_string_key_columns(lcols: Sequence, rcols: Sequence):
    """Pad paired string key columns to a common char-matrix width.

    Radix-key arity is derived from ``max_len``; comparing keys across two
    batches (join probe) requires both sides to lower to the same number of
    word arrays, else words would misalign against the trailing length key.
    """
    from ..columnar.column import StringColumn as _S

    def str_width(c):
        """Char-matrix width if the column lowers to string words."""
        if isinstance(c, _S):
            return c.max_len
        if isinstance(c, DictionaryColumn) and isinstance(c.dictionary, _S):
            return c.dictionary.max_len
        return None

    def pad_to(c, width):
        if isinstance(c, DictionaryColumn):
            d = c.dictionary
            if d.max_len == width:
                return c
            chars = jnp.pad(d.chars, ((0, 0), (0, width - d.max_len)))
            return dataclasses.replace(
                c, dictionary=_S(chars, d.lengths, d.validity, d.dtype))
        if c.max_len == width:
            return c
        chars = jnp.pad(c.chars, ((0, 0), (0, width - c.max_len)))
        return _S(chars, c.lengths, c.validity, c.dtype)

    lout, rout = [], []
    for lc, rc in zip(lcols, rcols):
        lw, rw = str_width(lc), str_width(rc)
        if (lw is None) != (rw is None):
            raise TypeError(f"join key type mismatch: {lc.dtype!r} vs {rc.dtype!r}")
        if lw is not None and lw != rw:
            width = max(lw, rw)
            lc, rc = pad_to(lc, width), pad_to(rc, width)
        lout.append(lc)
        rout.append(rc)
    return lout, rout
