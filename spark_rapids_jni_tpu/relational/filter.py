"""Filter: boolean-mask row selection with static-shape compaction.

XLA demands static shapes, so ``compact`` keeps the input length and returns
``(batch, count)``: selected rows are moved (stably) to the front, ``count``
is a device scalar, and trailing rows are nulled out.  Downstream kernels
either honor ``count`` or operate harmlessly on null padding — the same
discipline the reference applies to its ≤2GB batch splits (SURVEY.md §5
"long-context analogues").
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..columnar.column import ColumnBatch
from ..columnar.encoded import predicate_mask  # noqa: F401  (encoded filter
# path: evaluate the predicate over the d-entry dictionary once, map to
# rows with one gather — re-exported here as part of the filter API)
from ..columnar.encoded import packed_filter_mask  # noqa: F401  (packed
# filter path: compare u32 residual lanes against the once-transformed
# literal, no decode — the compressed-domain half of the filter API)
from .gather import gather_batch


def selection_indices(mask):
    """(idx int32[n], count int32): stable front-compaction of True rows.

    ``idx`` is a true permutation: ``idx[:count]`` are the positions of the
    True rows in order, ``idx[count:]`` the False rows' positions in order.
    """
    n = mask.shape[0]
    mask = mask.astype(jnp.bool_)
    count = mask.sum(dtype=jnp.int32)
    # destination of each row: selected rows pack to the front by prefix
    # count, unselected rows follow — one permutation scatter instead of an
    # argsort (TPU sorts are the pipeline bottleneck; cumsum+scatter is not)
    sel_pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    unsel_pos = count + jnp.cumsum((~mask).astype(jnp.int32)) - 1
    pos = jnp.where(mask, sel_pos, unsel_pos)
    iota = jnp.arange(n, dtype=jnp.int32)
    idx = jnp.zeros((n,), jnp.int32).at[pos].set(iota)
    return idx, count


def compact(batch: ColumnBatch, mask) -> tuple:
    """Move rows where ``mask`` is True to the front; null out the tail."""
    idx, count = selection_indices(mask)
    valid = jnp.arange(idx.shape[0], dtype=jnp.int32) < count
    return gather_batch(batch, idx, valid), count


def apply_mask(batch: ColumnBatch, mask) -> ColumnBatch:
    """Null out rows where ``mask`` is False (no movement).

    The cheap filter: keeps shapes and row positions, so it fuses into
    surrounding elementwise work; use ``compact`` only when downstream cost
    depends on live row count.
    """
    mask = mask.astype(jnp.bool_)
    return ColumnBatch(
        {
            name: dataclasses.replace(col, validity=col.validity & mask)
            for name, col in zip(batch.names, batch.columns)
        }
    )
