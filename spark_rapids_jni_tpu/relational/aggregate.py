"""Hash-based group-by aggregation (Spark hash-aggregate semantics).

Round 1 used radix-sort + segment boundaries; on real TPU hardware the sort
dominated the whole q6 pipeline (BENCH_r02 micro: group_by 3.2 Mrows/s vs
murmur3 160 Mrows/s).  This is now a true hash aggregate, formulated for the
VPU with no serial probe chains:

1. lower keys to uint32 radix words (:mod:`keys`, equality domain),
2. elect one *representative row* per distinct key by iterated bucket
   election: hash → ``scatter-min`` of row ids into a 2n-slot table →
   exact key compare against the winner → resolved rows drop out, colliding
   keys re-hash with a new seed (``lax.while_loop``; expected O(1) rounds —
   a round only repeats for distinct keys whose 32-bit mix collided),
3. group id = prefix-count of representatives (first-occurrence order),
4. ``jax.ops.segment_*`` scatter reductions per aggregate.

No sort anywhere.  Output is padded to the input row count with a device
``num_groups`` scalar (same discipline as :mod:`filter`); groups appear in
first-occurrence order of their representative row (deterministic).

Spark null/type semantics implemented here (mirrors what the plugin gets
from cudf groupby + Spark's type promotion):

* group keys: nulls form their own group; floats normalize -0.0/NaN first
  (equality domain, :mod:`keys`).
* sum/min/max ignore null inputs; all-null group -> null result.
* count(col) counts non-nulls, count(*) counts rows; never null.
* sum(int*) -> int64 (non-ANSI wraparound), sum(float*) -> float64,
  avg(*) -> float64.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column, ColumnBatch, Decimal128Column, StringColumn
from . import keys as K
from .gather import gather_column

_OPS = ("sum", "count", "min", "max", "mean")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    op: str           # sum | count | min | max | mean
    column: Optional[str]  # None only for count(*)
    out_name: str

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"unknown agg op {self.op!r}")
        if self.column is None and self.op != "count":
            raise ValueError("only count supports column=None (count(*))")


def _sum_dtype(dtype: T.SparkType) -> T.SparkType:
    if dtype.kind in (T.Kind.BOOLEAN, T.Kind.INT8, T.Kind.INT16, T.Kind.INT32,
                      T.Kind.INT64):
        return T.INT64
    if dtype.kind in (T.Kind.FLOAT32, T.Kind.FLOAT64):
        return T.FLOAT64
    raise NotImplementedError(f"sum of {dtype!r}")


def _segment_minmax(data, valid, gid, n, op: str):
    """Null-ignoring segmented min/max with Spark float/bool semantics.

    Spark orders NaN greater than every number (Java compare): max of a
    group containing NaN is NaN; min skips NaNs unless the group is all-NaN.
    """
    is_float = jnp.issubdtype(data.dtype, jnp.floating)
    was_bool = data.dtype == jnp.bool_
    if is_float:
        fill = jnp.array(jnp.inf if op == "min" else -jnp.inf, data.dtype)
        nan_in = valid & jnp.isnan(data)
        valid_num = valid & ~jnp.isnan(data)
    elif was_bool:
        data = data.astype(jnp.uint8)
        fill = jnp.uint8(1 if op == "min" else 0)
        valid_num = valid
    else:
        info = jnp.iinfo(data.dtype)
        fill = jnp.array(info.max if op == "min" else info.min, data.dtype)
        valid_num = valid
    masked = jnp.where(valid_num, data, fill)
    f = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    res = f(masked, gid, num_segments=n + 1)[:n]
    if is_float:
        seg_has_nan = (
            jax.ops.segment_sum(nan_in.astype(jnp.int32), gid,
                                num_segments=n + 1)[:n] > 0
        )
        seg_has_num = (
            jax.ops.segment_sum(valid_num.astype(jnp.int32), gid,
                                num_segments=n + 1)[:n] > 0
        )
        nan = jnp.array(jnp.nan, res.dtype)
        if op == "max":
            res = jnp.where(seg_has_nan, nan, res)
        else:
            res = jnp.where(seg_has_nan & ~seg_has_num, nan, res)
    if was_bool:
        res = res.astype(jnp.bool_)
    return res


def _mix32(h):
    """murmur3 finalizer: full-avalanche 32-bit mix."""
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _hash_words(karr, seed_u32):
    """Combine uint32 key word arrays into one well-mixed uint32[n]."""
    h = jnp.broadcast_to(_mix32(seed_u32 ^ jnp.uint32(0x9E3779B9)),
                         karr[0].shape).astype(jnp.uint32)
    for w in karr:
        h = _mix32((h * jnp.uint32(31)) ^ w.astype(jnp.uint32))
    return h


def _elect_representatives(karr, occ, n):
    """(rep_row int32[n], is_rep bool[n]): one representative per distinct key.

    Iterated bucket election (no sort): each round, unresolved rows
    scatter-min their row id into ``table[hash(keys, round) mod S]``; rows
    whose keys exactly equal the bucket winner's keys resolve to that winner.
    All rows of one key share every bucket, so the winner for a key is always
    its minimum (first-occurrence) row — representatives are round-invariant.
    A round only repeats for *distinct* keys that collided in a 2n-slot
    table, so expected rounds are O(1); the loop runs until empty.
    """
    S = 1 << max(3, (2 * n - 1).bit_length() if n > 1 else 3)
    S = min(S, 1 << 22)
    iota = jnp.arange(n, dtype=jnp.int32)
    BIG = jnp.int32(2**31 - 1)

    def cond(st):
        _, unres, _ = st
        return unres.any()

    def body(st):
        rep, unres, r = st
        h = _hash_words(karr, r.astype(jnp.uint32))
        b = jnp.where(unres, (h & jnp.uint32(S - 1)).astype(jnp.int32),
                      jnp.int32(S))
        table = jnp.full((S + 1,), BIG, jnp.int32).at[b].min(
            jnp.where(unres, iota, BIG)
        )
        cand = jnp.clip(jnp.take(table, b), 0, n - 1)
        eq = unres
        for k in karr:
            eq = eq & (k == jnp.take(k, cand))
        rep = jnp.where(eq, cand, rep)
        return rep, unres & ~eq, r + jnp.uint32(1)

    rep0 = jnp.full((n,), -1, jnp.int32)
    rep, _, _ = jax.lax.while_loop(cond, body, (rep0, occ, jnp.uint32(0)))
    is_rep = occ & (rep == iota)
    return rep, is_rep


def group_by(
    batch: ColumnBatch,
    key_names: Sequence[str],
    aggs: Sequence[AggSpec],
    row_valid=None,
) -> tuple:
    """Group ``batch`` by ``key_names``; returns (result_batch, num_groups).

    The result batch has the key columns (group order = first occurrence of
    each key, deterministic) followed by one column per AggSpec, padded to
    the input row count with null rows past ``num_groups``.

    ``row_valid`` (bool[n], optional) marks rows that exist: padding rows of
    an upstream compaction/shuffle are excluded from every group (without it
    they would merge into the null-key group).  Their aggregates route to a
    trash segment that is sliced off.
    """
    n = batch.num_rows
    key_cols = [batch[k] for k in key_names]
    karr = K.batch_radix_keys(key_cols, equality=True, nulls_first=True)
    occ = (jnp.ones((n,), jnp.bool_) if row_valid is None
           else row_valid.astype(jnp.bool_))
    iota = jnp.arange(n, dtype=jnp.int32)

    rep, is_rep = _elect_representatives(karr, occ, n)
    gid_of_row = jnp.cumsum(is_rep.astype(jnp.int32)) - 1  # valid at rep rows
    num_groups = is_rep.sum(dtype=jnp.int32)
    # every live row inherits its representative's group id; dead rows route
    # to trash segment n (sliced off below)
    gid = jnp.where(occ, jnp.take(gid_of_row, jnp.clip(rep, 0, n - 1)),
                    jnp.int32(n))
    # inverse permutation: row index of group g's representative
    pos = jnp.where(is_rep, gid_of_row, jnp.int32(n))
    rep_rows = jnp.zeros((n + 1,), jnp.int32).at[pos].set(iota)[:n]
    out_valid = iota < num_groups

    def seg_sum(vals):
        return jax.ops.segment_sum(vals, gid, num_segments=n + 1)[:n]

    out = {}
    for name in key_names:
        out[name] = gather_column(batch[name], rep_rows, out_valid)

    for spec in aggs:
        if spec.op == "count":
            if spec.column is None:
                ones = occ.astype(jnp.int64)
            else:
                ones = (batch[spec.column].validity & occ).astype(jnp.int64)
            out[spec.out_name] = Column(seg_sum(ones), out_valid, T.INT64)
            continue

        col = batch[spec.column]
        if isinstance(col, (StringColumn, Decimal128Column)):
            raise NotImplementedError(
                f"{spec.op} over {col.dtype!r} groups not implemented yet"
            )
        data, valid = col.data, col.validity & occ
        nn = seg_sum(valid.astype(jnp.int32))
        has_any = nn > 0

        if spec.op in ("sum", "mean"):
            out_t = T.FLOAT64 if spec.op == "mean" else _sum_dtype(col.dtype)
            acc = data.astype(out_t.jnp_dtype if spec.op == "sum" else jnp.float64)
            acc = jnp.where(valid, acc, jnp.zeros((), acc.dtype))
            s = seg_sum(acc)
            if spec.op == "mean":
                s = s / jnp.maximum(nn, 1).astype(jnp.float64)
            out[spec.out_name] = Column(s, out_valid & has_any, out_t)
        else:  # min / max
            r = _segment_minmax(data, valid, gid, n, spec.op)
            out[spec.out_name] = Column(r, out_valid & has_any, col.dtype)

    return ColumnBatch(out), num_groups
