"""Engine-selectable group-by aggregation (Spark hash-aggregate semantics).

Two general-key engines live here, selected by the ``groupby_engine``
config knob (``auto | sort | scatter``) or the ``engine=`` argument:

* **sort** — one multi-operand ``lax.sort``, then only scans and
  gathers.  Three designs were measured on the real chip in round 1:
  radix-sort + argsort + segment ops hit 3.2 Mrows/s (each sort/scatter
  95-630ms at 2M rows on this TPU); scatter-min bucket election was no
  better (XLA scatters are the slowest primitive on that chip, ~150ms
  per 2M-row scatter); the surviving design has **no scatter anywhere**
  and optionally lets agg values ride the sort as payload operands.
* **scatter** — no sort anywhere: rows map to key groups through the
  open-addressing slot table (:mod:`hashtable`), every aggregate is one
  ``segment_*`` pass, and only the small ``num_slots``-sized table is
  sorted to emit groups in the same key order as the sort engine.  The
  inversion is again a hardware fact: on XLA-CPU ``lax.sort`` is the
  worst primitive and scatters the best (round-4 A/B: segment_sum 80x
  faster than the one-hot matmul), so ``auto`` resolves to scatter on
  CPU and sort on accelerators.  If the slot table overflows (more
  distinct keys than slots) the jitted program falls back to the sort
  engine via ``lax.cond`` — both engines trace, the data picks one.

Sort-engine pipeline: lower keys to uint32 radix words (:mod:`keys`,
equality domain)
-> one ``lax.sort`` carrying [keys..., row-id] (agg values are gathered
along the permutation afterwards by default; config
``group_sort_payload='ride'`` makes them ride the sort as extra payload
operands instead — round 3 measured the wide emulated-64-bit sort at
~1s/iter @256K rows on v5e, so narrow-sort+gather is the default) ->
adjacent-compare boundaries on the sorted key words -> per-agg prefix
``cumsum`` (or segmented min/max ``associative_scan``) -> group result =
scan value at each group's last row minus the previous group's, fetched
with one small gather at the compacted group-end positions.

Output is padded to the input row count with a device ``num_groups``
scalar (same discipline as :mod:`filter`); groups appear in key-sorted
order, nulls first (Spark does not define a group order; this one is
deterministic).

Spark null/type semantics implemented here (mirrors what the plugin gets
from cudf groupby + Spark's type promotion):

* group keys: nulls form their own group; floats normalize -0.0/NaN first
  (equality domain, :mod:`keys`).
* sum/min/max ignore null inputs; all-null group -> null result.
* count(col) counts non-nulls, count(*) counts rows; never null.
* sum(int*) -> int64 (non-ANSI wraparound), sum(float*) -> float64,
  avg(*) -> float64.  Float sums are computed as prefix-sum differences;
  they are not bit-identical to a per-group left-fold (Spark itself is
  order-nondeterministic under shuffles).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column, ColumnBatch, Decimal128Column, StringColumn
from ..columnar.encoded import (
    DictionaryColumn,
    canon_key_column,
    is_encoded,
    materialize_batch,
    materialize_column,
)
from . import keys as K
from .gather import gather_column

_OPS = ("sum", "count", "min", "max", "mean")


def _canon_keys(key_cols):
    """Key-column substitution for the encoded fast path: within ONE
    batch every dictionary column's ``canon[codes]`` single word is both
    equality- and order-equivalent to its full gathered radix words, so
    both engines key on one u32 word and still emit bit-identical group
    order.  Output key columns gather from the ORIGINAL (still encoded)
    batch columns — only the key lowering is substituted."""
    return [canon_key_column(c) if isinstance(c, DictionaryColumn) else c
            for c in key_cols]


def _materialize_agg_values(batch, aggs):
    """Late-materialize encoded agg VALUE columns at the point of need
    (aggregation arithmetic runs on values, not codes); key columns stay
    encoded all the way to the output gather."""
    repl = {}
    for spec in aggs:
        c = spec.column
        if c is not None and c not in repl and is_encoded(batch[c]):
            repl[c] = materialize_column(batch[c])
    if not repl:
        return batch
    return ColumnBatch({n: repl.get(n, col)
                        for n, col in zip(batch.names, batch.columns)})


@dataclasses.dataclass(frozen=True)
class AggSpec:
    op: str           # sum | count | min | max | mean
    column: Optional[str]  # None only for count(*)
    out_name: str

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"unknown agg op {self.op!r}")
        if self.column is None and self.op != "count":
            raise ValueError("only count supports column=None (count(*))")


def _sum_dtype(dtype: T.SparkType) -> T.SparkType:
    if dtype.kind in (T.Kind.BOOLEAN, T.Kind.INT8, T.Kind.INT16, T.Kind.INT32,
                      T.Kind.INT64):
        return T.INT64
    if dtype.kind in (T.Kind.FLOAT32, T.Kind.FLOAT64):
        return T.FLOAT64
    raise NotImplementedError(f"sum of {dtype!r}")


def _seg_scan_minmax(vals, boundary, op):
    """Segmented running min/max: resets at rows where boundary is True."""
    def comb(a, b):
        av, ab = a
        bv, bb = b
        m = jnp.minimum(av, bv) if op == "min" else jnp.maximum(av, bv)
        return jnp.where(bb, bv, m), ab | bb

    out, _ = jax.lax.associative_scan(comb, (vals, boundary))
    return out


def _seg_scan_sum(vals, boundary):
    """Segmented running sum (resets at boundaries).

    Used for FLOAT sums: a global prefix-sum difference cancels
    catastrophically when a small group sorts after a large one (1e18
    prefixes have ~128 ulp); the segmented scan keeps each group's sum a
    tree-reduction of only its own elements.
    """
    def comb(a, b):
        av, ab = a
        bv, bb = b
        return jnp.where(bb, bv, av + bv), ab | bb

    out, _ = jax.lax.associative_scan(comb, (vals, boundary))
    return out


def _seg_scan_sum256(vals, boundary):
    """Segmented running 256-bit sum over uint32[n, 8] limb rows
    (decimal128 group sums; limb add from :mod:`ops.decimal`)."""
    from ..ops import decimal as D

    def comb(a, b):
        av, ab = a
        bv, bb = b
        return jnp.where(bb[:, None], bv, D._add(av, bv)), ab | bb

    out, _ = jax.lax.associative_scan(comb, (vals, boundary))
    return out


def _dec128_lt(alo, ahi, blo, bhi):
    """Signed 128-bit a < b over (lo u64, hi u64) pairs."""
    ah = jax.lax.bitcast_convert_type(ahi, jnp.int64)
    bh = jax.lax.bitcast_convert_type(bhi, jnp.int64)
    return (ah < bh) | ((ah == bh) & (alo < blo))


def _seg_scan_minmax128(lo, hi, boundary, op):
    """Segmented running signed-128 min/max over (lo, hi) u64 limb pairs."""
    def comb(a, b):
        alo, ahi, ab = a
        blo, bhi, bb = b
        if op == "min":
            pick_b = _dec128_lt(blo, bhi, alo, ahi)
        else:
            pick_b = _dec128_lt(alo, ahi, blo, bhi)
        pick_b = pick_b | bb
        return (jnp.where(pick_b, blo, alo), jnp.where(pick_b, bhi, ahi),
                ab | bb)

    olo, ohi, _ = jax.lax.associative_scan(comb, (lo, hi, boundary))
    return olo, ohi


def _average_decimal_type(p: int, s: int):
    """Spark ``Average`` over DecimalType(p, s): ``DecimalType.bounded(
    p+4, s+4)`` — a plain clamp of precision AND scale to 38 (bounded
    does NOT apply adjustPrecisionScale's integral-digit trade; avg of
    decimal(38, 10) is decimal(38, 14) in Spark)."""
    return min(p + 4, 38), min(s + 4, 38)


def _decimal_avg(s256, cnt, in_dtype):
    """Group average from exact 256-bit sums: rescale to the result scale,
    divide by the count with HALF_UP, overflow -> invalid.

    Returns (limbs128, ok_mask, result_dtype); rows with cnt == 0 divide
    by a masked 1 — callers AND ``ok`` with their has-any mask.
    """
    from ..ops import decimal as D

    p_res, s_res = _average_decimal_type(in_dtype.precision, in_dtype.scale)
    d = s_res - in_dtype.scale  # >= 0 by the bounded rules
    scaled = D._mul(s256, jnp.broadcast_to(D._pow10(d), s256.shape)) \
        if d else s256
    mag, neg = D._abs(scaled)
    den = jnp.maximum(cnt, 1).astype(jnp.uint64)
    q, rem = D._divmod_u_small(mag, den)
    q = D._add_small(q, ((rem * 2) >= den).astype(jnp.int32))  # HALF_UP
    ok = D._lt_u(q, jnp.broadcast_to(D._pow10(p_res), q.shape))
    signed = jnp.where(neg[:, None], D._neg(q), q)
    return (D._to_i128(signed), ok,
            T.SparkType.decimal(p_res, s_res))


def _resolve_groupby_engine(engine):
    """``engine=None`` reads the ``groupby_engine`` knob; ``auto`` is a
    platform call (scatter on CPU, sort on accelerators — see the module
    docstring for the measurements behind it)."""
    from .. import config as _config

    if engine is None:
        engine = _config.get("groupby_engine")
    if engine == "auto":
        return "scatter" if jax.default_backend() == "cpu" else "sort"
    if engine not in ("sort", "scatter", "pallas"):
        raise ValueError(f"unknown groupby engine {engine!r} "
                         "(use 'auto', 'sort', 'scatter', or 'pallas')")
    return engine


def group_by(
    batch: ColumnBatch,
    key_names: Sequence[str],
    aggs: Sequence[AggSpec],
    row_valid=None,
    *,
    engine=None,
    num_slots=None,
    assume_grouped: bool = False,
) -> tuple:
    """Group ``batch`` by ``key_names``; returns (result_batch, num_groups).

    The result batch has the key columns (group order = key sort order,
    nulls first, deterministic — both engines emit the same order)
    followed by one column per AggSpec, padded to the input row count
    with null rows past ``num_groups``.

    ``row_valid`` (bool[n], optional) marks rows that exist: padding rows
    of an upstream filter/shuffle are excluded from every group.  They
    sort to the back as one trailing pseudo-run that the group count and
    end positions simply never reach.

    ``engine``: ``'sort' | 'scatter' | 'pallas' | 'auto'`` (default: the
    ``groupby_engine`` knob; ``'pallas'`` is the scatter engine with the
    slot table built by the fused VMEM kernel, bit-identical and
    interpret-mode-safe off-accelerator).  The scatter engine's slot
    table holds
    ``num_slots`` distinct keys (power of two, default 4096, clamped to
    2n); data with more distinct keys falls back to the sort engine at
    runtime inside the same jitted program, so the hint only costs
    speed, never correctness.  Size it at ~2x the expected key
    cardinality to keep probe chains short.

    ``assume_grouped``: the caller guarantees rows with equal keys are
    already adjacent and (when ``row_valid`` is given) dead rows form
    one trailing run — e.g. the batch came out of an exchange whose sort
    carried the group key as a secondary operand.  The main sort is
    skipped entirely (the boundary scan runs on input order) and groups
    are emitted in first-appearance instead of key order — Spark defines
    no group order.  Implies the sort engine: with no sort left to skip,
    the scatter engine has nothing to offer.
    """
    eng = _resolve_groupby_engine(engine)
    if not assume_grouped and eng in ("scatter", "pallas"):
        # 'pallas' is the scatter engine with the slot table built by the
        # fused VMEM kernel (ops.pallas_kernels) — bit-identical product,
        # so everything downstream of the table is shared
        return _group_by_hash(batch, key_names, aggs, row_valid, num_slots,
                              "pallas" if eng == "pallas" else "lax")
    return _group_by_sortscan(batch, key_names, aggs, row_valid,
                              assume_grouped)


def _group_by_sortscan(batch, key_names, aggs, row_valid, assume_grouped):
    """The sort engine: one stable multi-operand sort, then scans."""
    n = batch.num_rows
    batch = _materialize_agg_values(batch, aggs)
    key_cols = _canon_keys([batch[k] for k in key_names])
    karr = K.batch_radix_keys(key_cols, equality=True, nulls_first=True)
    have_rv = row_valid is not None
    if have_rv:
        occ = row_valid.astype(jnp.bool_)
        karr = [jnp.where(occ, jnp.uint32(0), jnp.uint32(1))] + [
            jnp.where(occ, k, jnp.zeros((), k.dtype)) for k in karr
        ]
    iota = jnp.arange(n, dtype=jnp.int32)

    agg_cols = []
    for spec in aggs:
        if spec.column is not None:
            col = batch[spec.column]
            if isinstance(col, StringColumn):
                raise NotImplementedError(
                    f"{spec.op} over {col.dtype!r} groups not implemented yet"
                )
            if spec.column not in agg_cols:
                agg_cols.append(spec.column)
    # Two ways to move agg values into sorted order (config
    # ``group_sort_payload``).  'ride': values ride the sort as payload
    # operands — no post-sort gathers, but every 64-bit operand is an
    # emulated u32 pair inside the TPU sort network, and the multi-operand
    # sort measured ~1s/iter at 256K rows on v5e (round 3).  'gather':
    # sort carries only [keys..., row-id]; each agg column is fetched
    # afterwards with one take() along the permutation (linear passes,
    # ~24ms per 2M-row gather measured round 2).
    from .. import config as _config

    ride = _config.get("group_sort_payload") == "ride"
    payload = [iota]
    spans = {}
    if ride:
        # agg data rides the sort in its native dtype (the TPU X64-rewrite
        # pass legalizes 64-bit sort payloads but not u32-pair bitcasts).
        # Decimal128 limbs are [n, 2] and cannot be sort operands — those
        # columns always gather along the permutation instead.
        for name in agg_cols:
            col = batch[name]
            if isinstance(col, Decimal128Column):
                continue
            spans[name] = len(payload)
            payload.extend([col.data, col.validity])

    nk = len(karr)
    if assume_grouped:
        # sort-order reuse: an upstream stage already laid equal keys out
        # adjacently (dead rows in one trailing run), so the boundary
        # scan below works on input order directly and the whole sort —
        # the engine's dominant cost — disappears.
        skeys = tuple(karr)
        sperm = iota
        spay = tuple(payload[1:])
    else:
        res = jax.lax.sort(tuple(karr) + tuple(payload), num_keys=nk,
                           is_stable=True)
        skeys = res[:nk]
        sperm = res[nk]
        spay = res[nk + 1:]

    boundary = ~K.rows_equal_adjacent(skeys)
    sorted_occ = (skeys[0] == 0) if have_rv else jnp.ones((n,), jnp.bool_)
    num_groups = (boundary & sorted_occ).sum(dtype=jnp.int32)

    # last row of each live group: next row starts a new group / is dead /
    # doesn't exist
    nxt_boundary = jnp.concatenate(
        [boundary[1:], jnp.ones((1,), jnp.bool_)])
    nxt_occ = jnp.concatenate([sorted_occ[1:], jnp.zeros((1,), jnp.bool_)])
    is_end = sorted_occ & (nxt_boundary | ~nxt_occ)
    # compact end positions to the front (2-operand flag sort, no scatter)
    ends = jax.lax.sort(
        ((~is_end).astype(jnp.uint32), iota), num_keys=1, is_stable=True
    )[1]
    prev_ends = jnp.roll(ends, 1)
    out_valid = iota < num_groups

    def at_ends_diff(cs):
        """Per-group total from a prefix scan: cs[end_g] - cs[end_{g-1}]."""
        ce = jnp.take(cs, ends)
        cp = jnp.where(iota == 0, jnp.zeros((), cs.dtype),
                       jnp.take(cs, prev_ends))
        return ce - cp

    out = {}
    starts = jnp.where(iota == 0, 0, prev_ends + 1)
    rows0 = jnp.take(sperm, jnp.clip(starts, 0, n - 1))
    for name in key_names:
        out[name] = gather_column(batch[name], rows0, out_valid)

    def sorted_valid(name):
        return jnp.take(batch[name].validity, sperm) & sorted_occ

    def sorted_col(name):
        if ride and name in spans:
            off = spans[name]
            data = spay[off - 1]  # payload[0] is iota (== sperm)
            valid = spay[off] & sorted_occ
            return data, valid
        col = batch[name]
        return jnp.take(col.data, sperm), sorted_valid(name)

    for spec in aggs:
        if spec.op == "count":
            if spec.column is None:
                ones = sorted_occ.astype(jnp.int64)
            else:
                ones = sorted_valid(spec.column).astype(jnp.int64)
            out[spec.out_name] = Column(at_ends_diff(jnp.cumsum(ones)),
                                        out_valid, T.INT64)
            continue

        if isinstance(batch[spec.column], Decimal128Column):
            # Decimal128 aggregation over sorted runs.  sum/mean: exact
            # 256-bit segmented sums (values sign-extend to uint32[n,8]; a
            # 2^31-row group of |v|<2^127 stays < 2^158, never wraps) —
            # sum gets Spark's decimal(min(38, p+10), s) with overflow ->
            # null, mean divides by the count per Average's bounded(p+4,
            # s+4) HALF_UP.  min/max: signed-128 segmented scans on the
            # raw limb pairs.  (Non-ANSI nullOnOverflow; reference
            # DecimalUtils ops are per-element — group aggregation lives
            # above cudf in the plugin, so semantics follow Spark's
            # aggregate expressions.)
            from ..ops import decimal as D

            dcol = batch[spec.column]
            svalid = sorted_valid(spec.column)
            slimbs = jnp.take(dcol.limbs, sperm, axis=0)
            nn_d = at_ends_diff(jnp.cumsum(svalid.astype(jnp.int32)))
            has_any_d = out_valid & (nn_d > 0)
            if spec.op in ("min", "max"):
                if spec.op == "min":  # fill nulls with +max signed 128
                    flo = jnp.uint64(0xFFFFFFFFFFFFFFFF)
                    fhi = jnp.uint64(0x7FFFFFFFFFFFFFFF)
                else:                 # fill with -min signed 128
                    flo = jnp.uint64(0)
                    fhi = jnp.uint64(0x8000000000000000)
                lo = jnp.where(svalid, slimbs[:, 0], flo)
                hi = jnp.where(svalid, slimbs[:, 1], fhi)
                rlo, rhi = _seg_scan_minmax128(lo, hi, boundary, spec.op)
                out[spec.out_name] = Decimal128Column(
                    jnp.stack([jnp.take(rlo, ends),
                               jnp.take(rhi, ends)], axis=1),
                    has_any_d, dcol.dtype)
                continue
            u = D._from_i128(slimbs)
            u = jnp.where(svalid[:, None], u, jnp.zeros((), jnp.uint32))
            run = _seg_scan_sum256(u, boundary)
            s256 = jnp.take(run, ends, axis=0)
            if spec.op == "mean":
                limbs128, ok, out_t = _decimal_avg(s256, nn_d, dcol.dtype)
                out[spec.out_name] = Decimal128Column(
                    limbs128, has_any_d & ok, out_t)
                continue
            out_p = min(38, dcol.dtype.precision + 10)
            mag, _ = D._abs(s256)
            overflow = ~D._lt_u(mag, jnp.broadcast_to(D._pow10(out_p),
                                                      mag.shape))
            out[spec.out_name] = Decimal128Column(
                D._to_i128(s256), has_any_d & ~overflow,
                T.SparkType.decimal(out_p, dcol.dtype.scale))
            continue

        data, valid = sorted_col(spec.column)
        col_dtype = batch[spec.column].dtype
        nn = at_ends_diff(jnp.cumsum(valid.astype(jnp.int32)))
        has_any = nn > 0

        if spec.op in ("sum", "mean"):
            out_t = T.FLOAT64 if spec.op == "mean" else _sum_dtype(col_dtype)
            acc = data.astype(out_t.jnp_dtype if spec.op == "sum"
                              else jnp.float64)
            acc = jnp.where(valid, acc, jnp.zeros((), acc.dtype))
            if jnp.issubdtype(acc.dtype, jnp.floating):
                s = jnp.take(_seg_scan_sum(acc, boundary), ends)
            else:
                s = at_ends_diff(jnp.cumsum(acc))  # exact mod-2^64
            if spec.op == "mean":
                s = s / jnp.maximum(nn, 1).astype(jnp.float64)
            out[spec.out_name] = Column(s, out_valid & has_any, out_t)
        else:  # min / max — Spark float semantics: NaN greatest, one NaN
            is_float = jnp.issubdtype(data.dtype, jnp.floating)
            was_bool = data.dtype == jnp.bool_
            if is_float:
                fill = jnp.array(jnp.inf if spec.op == "min" else -jnp.inf,
                                 data.dtype)
                nan_in = valid & jnp.isnan(data)
                valid_num = valid & ~jnp.isnan(data)
            elif was_bool:
                data = data.astype(jnp.uint8)
                fill = jnp.uint8(1 if spec.op == "min" else 0)
                valid_num = valid
            else:
                info = jnp.iinfo(data.dtype)
                fill = jnp.array(info.max if spec.op == "min" else info.min,
                                 data.dtype)
                valid_num = valid
            masked = jnp.where(valid_num, data, fill)
            run = _seg_scan_minmax(masked, boundary, spec.op)
            r = jnp.take(run, ends)
            if is_float:
                seg_nan = at_ends_diff(jnp.cumsum(nan_in.astype(jnp.int32))) > 0
                seg_num = at_ends_diff(
                    jnp.cumsum(valid_num.astype(jnp.int32))) > 0
                nan = jnp.array(jnp.nan, r.dtype)
                if spec.op == "max":
                    r = jnp.where(seg_nan, nan, r)
                else:
                    r = jnp.where(seg_nan & ~seg_num, nan, r)
            if was_bool:
                r = r.astype(jnp.bool_)
            out[spec.out_name] = Column(r, out_valid & has_any, col_dtype)

    return ColumnBatch(out), num_groups


_DEFAULT_GROUP_SLOTS = 4096


def _group_by_hash(batch, key_names, aggs, row_valid, num_slots,
                   table_engine: str = "lax"):
    """The scatter engine: slot-table key mapping + segment reductions.

    Same contract, semantics, and group order as the sort engine — the
    only rounding difference is float sums/means (scatter-add order vs
    segmented-scan order; Spark itself is order-nondeterministic there).
    Slot-table overflow falls back to the sort engine via ``lax.cond``.
    ``table_engine`` picks the slot-table implementation (``'lax'`` or
    the fused ``'pallas'`` kernel — bit-identical either way).
    """
    from . import hashtable as H
    from ..plan import adaptive as _adaptive

    n = batch.num_rows
    batch = _materialize_agg_values(batch, aggs)
    key_cols = _canon_keys([batch[k] for k in key_names])
    karr = K.batch_radix_keys(key_cols, equality=True, nulls_first=True)
    row_live = jnp.ones((n,), jnp.bool_) if row_valid is None else \
        row_valid.astype(jnp.bool_)
    S = H.next_pow2(_DEFAULT_GROUP_SLOTS if num_slots is None
                    else int(num_slots))
    S = min(S, H.next_pow2(2 * n))
    # a spuriously long probe chain only costs a fallback to the sort
    # engine, so the round bound stays far below the table size — the
    # adaptive layer tightens it further from the observed load factor
    owner, slot, overflow = H.build_slot_table(
        karr, row_live, S, max_rounds=_adaptive.bound_build_rounds(n, S),
        engine=table_engine)

    def scat(_):
        return _scatter_groups(batch, key_names, aggs, karr, row_live,
                               owner, slot, S)

    def srt(_):
        return _group_by_sortscan(batch, key_names, aggs, row_valid, False)

    return jax.lax.cond(overflow, srt, scat, None)


def _scatter_groups(batch, key_names, aggs, karr, row_live, owner, slot, S):
    """Segment-reduction group-by over a resolved slot table.

    ``slot`` (int32[n], dead rows -> S) is the segment id; every
    aggregate is one ``segment_*`` over ``S + 1`` segments (segment S
    discards dead rows).  The S slots then sort by their owner's key
    words — a table-sized sort, not a row-sized one — so groups come out
    in exactly the sort engine's order (key order, nulls first), with
    the same representative row per group (the slot owner is the
    minimum row id of its key, which is also what the stable sort
    exposes as the group's first row).
    """
    from jax.ops import segment_max, segment_min, segment_sum

    n = batch.num_rows
    iota = jnp.arange(n, dtype=jnp.int32)
    dead_slot = owner == n
    oc = jnp.clip(owner, 0, n - 1)

    ops = [dead_slot.astype(jnp.uint32)] + [
        jnp.where(dead_slot, jnp.zeros((), k.dtype), jnp.take(k, oc))
        for k in karr] + [jnp.arange(S, dtype=jnp.int32)]
    rank2slot = jax.lax.sort(tuple(ops), num_keys=len(ops) - 1,
                             is_stable=True)[-1]
    num_groups = (~dead_slot).sum(dtype=jnp.int32)
    out_valid = iota < num_groups

    def per_group(per_slot):
        """[S+1] (or [S+1, ...]) segment result -> [n] in group-rank
        order (pad with zeros when the table is smaller than the batch;
        live groups always fit — there are at most n of them)."""
        a = jnp.take(per_slot[:S], rank2slot, axis=0)
        if a.shape[0] >= n:
            return a[:n]
        pad = jnp.zeros((n - a.shape[0],) + a.shape[1:], a.dtype)
        return jnp.concatenate([a, pad], axis=0)

    def seg_sum(vals):
        return per_group(segment_sum(vals, slot, num_segments=S + 1))

    rows0 = per_group(oc)
    out = {}
    for name in key_names:
        out[name] = gather_column(batch[name], rows0, out_valid)

    for spec in aggs:
        if spec.column is not None and \
                isinstance(batch[spec.column], StringColumn):
            raise NotImplementedError(
                f"{spec.op} over {batch[spec.column].dtype!r} groups "
                "not implemented yet")
        if spec.op == "count":
            if spec.column is None:
                ones = row_live.astype(jnp.int64)
            else:
                ones = (batch[spec.column].validity
                        & row_live).astype(jnp.int64)
            out[spec.out_name] = Column(seg_sum(ones), out_valid, T.INT64)
            continue

        col = batch[spec.column]
        valid = col.validity & row_live
        nn = seg_sum(valid.astype(jnp.int32))
        has_any = nn > 0

        if isinstance(col, Decimal128Column):
            from ..ops import decimal as D

            has_any_d = out_valid & has_any
            if spec.op in ("min", "max"):
                # signed-128 min/max in two passes: elect the extreme hi
                # limb (signed), then the extreme unsigned lo limb among
                # rows holding it.  Fills match the sort engine's and the
                # segment identities (so empty/all-null groups agree).
                if spec.op == "min":
                    flo = jnp.uint64(0xFFFFFFFFFFFFFFFF)
                    fhi = jnp.uint64(0x7FFFFFFFFFFFFFFF)
                    seg_mm = segment_min
                else:
                    flo = jnp.uint64(0)
                    fhi = jnp.uint64(0x8000000000000000)
                    seg_mm = segment_max
                lo = jnp.where(valid, col.limbs[:, 0], flo)
                hi_i = jax.lax.bitcast_convert_type(
                    jnp.where(valid, col.limbs[:, 1], fhi), jnp.int64)
                m_hi = seg_mm(hi_i, slot, num_segments=S + 1)
                at_best = valid & (hi_i == jnp.take(m_hi, slot))
                m_lo = seg_mm(jnp.where(at_best, lo, flo), slot,
                              num_segments=S + 1)
                out[spec.out_name] = Decimal128Column(
                    jnp.stack([per_group(m_lo),
                               jax.lax.bitcast_convert_type(
                                   per_group(m_hi), jnp.uint64)], axis=1),
                    has_any_d, col.dtype)
                continue
            # sum / mean: exact 256-bit sums, u32 lanes summed in u64
            # (n <= 2^31 rows of < 2^32 stays under 2^63), carry-folded
            # once — the same argument as _domain_partials_scatter
            u = D._from_i128(jnp.where(valid[:, None], col.limbs,
                                       jnp.zeros((), jnp.uint64)))
            lanes = segment_sum(u.astype(jnp.uint64), slot,
                                num_segments=S + 1)
            s256 = per_group(_carry_fold_u64_lanes(lanes[:S]))
            if spec.op == "mean":
                limbs128, ok, out_t = _decimal_avg(s256, nn, col.dtype)
                out[spec.out_name] = Decimal128Column(
                    limbs128, has_any_d & ok, out_t)
                continue
            out_p = min(38, col.dtype.precision + 10)
            mag, _ = D._abs(s256)
            dovf = ~D._lt_u(mag, jnp.broadcast_to(D._pow10(out_p),
                                                  mag.shape))
            out[spec.out_name] = Decimal128Column(
                D._to_i128(s256), has_any_d & ~dovf,
                T.SparkType.decimal(out_p, col.dtype.scale))
            continue

        data = col.data
        if spec.op in ("sum", "mean"):
            out_t = T.FLOAT64 if spec.op == "mean" else _sum_dtype(col.dtype)
            acc = data.astype(out_t.jnp_dtype if spec.op == "sum"
                              else jnp.float64)
            acc = jnp.where(valid, acc, jnp.zeros((), acc.dtype))
            s = seg_sum(acc)
            if spec.op == "mean":
                s = s / jnp.maximum(nn, 1).astype(jnp.float64)
            out[spec.out_name] = Column(s, out_valid & has_any, out_t)
        else:  # min / max — same fills and NaN rules as the sort engine
            is_float = jnp.issubdtype(data.dtype, jnp.floating)
            was_bool = data.dtype == jnp.bool_
            if is_float:
                fill = jnp.array(jnp.inf if spec.op == "min" else -jnp.inf,
                                 data.dtype)
                nan_in = valid & jnp.isnan(data)
                valid_num = valid & ~jnp.isnan(data)
            elif was_bool:
                data = data.astype(jnp.uint8)
                fill = jnp.uint8(1 if spec.op == "min" else 0)
                valid_num = valid
            else:
                info = jnp.iinfo(data.dtype)
                fill = jnp.array(info.max if spec.op == "min" else info.min,
                                 data.dtype)
                valid_num = valid
            masked = jnp.where(valid_num, data, fill)
            seg_mm = segment_min if spec.op == "min" else segment_max
            r = per_group(seg_mm(masked, slot, num_segments=S + 1))
            if is_float:
                seg_nan = seg_sum(nan_in.astype(jnp.int32)) > 0
                seg_num = seg_sum(valid_num.astype(jnp.int32)) > 0
                nan = jnp.array(jnp.nan, r.dtype)
                if spec.op == "max":
                    r = jnp.where(seg_nan, nan, r)
                else:
                    r = jnp.where(seg_nan & ~seg_num, nan, r)
            if was_bool:
                r = r.astype(jnp.bool_)
            out[spec.out_name] = Column(r, out_valid & has_any, col.dtype)

    return ColumnBatch(out), num_groups


# ---------------------------------------------------------------------------
# MXU path: one-hot int8 matmul aggregation for small static key domains
# ---------------------------------------------------------------------------

def group_by_onehot(
    batch: ColumnBatch,
    key_name: str,
    aggs: Sequence[AggSpec],
    domain: int,
    row_valid=None,
    float_mode: str = "f64",
    engine: str = "xla",
):
    """Hash-aggregate as matmuls: the TPU-first alternative to the
    sort-scan path when one integer key column has a small static domain
    ``[0, domain)`` (dimension ids, date ordinals, bucketed keys — the q6
    shape).  The per-key FLOPs land on the MXU instead of the VPU sort
    network:

    * one-hot ``[n, K+1]`` int8 (bucket K holds null keys);
    * ALL integer payloads ride ONE chunked int8 x int8 -> int32
      contraction: column 0 is the count(*) ones, then per referenced
      column a validity flag, then for each integer sum the eight byte
      limbs ``b_l - 128`` (exact: true limb sums are rebuilt with
      ``+128*count`` and recombined in uint64 with Spark's non-ANSI
      wraparound).  One HBM pass over the one-hot instead of one per agg;
    * float sums ride ONE f32 contraction in ``f32x3`` mode (exact 3-way
      Dekker split of the f64 mantissa — MXU-native, accumulation
      rounding inside Spark's order-nondeterminism) or one emulated-f64
      contraction in ``f64`` mode (slow on TPU but rounding-compatible
      with the sort-scan path);
    * mean: sum / count in f64.

    min/max and multi-column keys stay on the sort-scan path.  Returns
    ``(result, num_groups, overflow)`` — ``overflow`` is a device bool
    that is True if any non-null key fell outside ``[0, domain)`` (result
    is then invalid; callers assert or fall back).

    ``engine="pallas"`` routes the contraction through the fused
    :func:`ops.pallas_kernels.onehot_groupby_parts` kernel, which never
    materializes the one-hot in HBM (the XLA engine does, twice at the
    widest dtype); the pallas engine always uses the f32x3 float split.
    ``engine="scatter"`` delegates to :func:`group_by_scatter` (linear
    segment sums — the CPU-fast engine); ``engine="auto"`` resolves per
    platform: scatter on CPU, xla one-hot on accelerators (measured both
    ways round 4: segment_sum 80x faster on XLA-CPU, scatters 2 orders
    slow on v5e).

    Internally this is :func:`_domain_partials` (additive per-bucket
    partials — the map-side-combine unit that
    :func:`parallel.distributed.distributed_group_by_domain` psum-merges
    across a mesh) followed by :func:`_finalize_domain`.
    """
    parts, overflow = _domain_partials(batch, key_name, aggs, domain,
                                       row_valid, engine, float_mode)
    res, ng = _finalize_domain(batch, key_name, int(domain), aggs, parts)
    return res, ng, overflow


def _domain_partials(batch, key_name, aggs, domain, row_valid=None,
                     engine="auto", float_mode="f64"):
    """Additive per-bucket partial aggregates over a static key domain.

    Returns ``(parts, overflow)`` where ``parts`` is a pytree of
    psum-mergeable arrays over buckets ``[0, K]`` (bucket K = null keys):

    * ``star``  int64[K+1] — count(*) rows
    * ``cnt``   {col: int64[K+1]} — non-null counts
    * ``isum``  {col: int64[K+1]} — integer sums (wrap mod 2^64 under
      merging, exactly Spark's non-ANSI overflow)
    * ``fsum``  {col: float64[K+1]} — float sums (merge-order rounding
      sits inside Spark's shuffle nondeterminism)
    * ``d64``   {col: uint64[K+1, 8]} — decimal128 sums as 256-bit
      two's-complement u32 limbs widened to u64, so a psum over P
      devices cannot carry out of a lane (P·2^32 < 2^64); the merged
      lanes re-fold in :func:`_finalize_domain`

    Every leaf is additive: element-wise sum of two devices' parts is
    the parts of their concatenated rows.  min/max are not expressible
    this way under psum and stay on the sort-scan path.
    """
    # the domain engines run arithmetic on raw key/value buffers: encoded
    # columns materialize here (their late point of need)
    batch = materialize_batch(batch)
    if engine == "auto":
        engine = "scatter" if jax.default_backend() == "cpu" else "xla"
    if engine == "scatter":
        return _domain_partials_scatter(batch, key_name, aggs, domain,
                                        row_valid)
    return _domain_partials_onehot(batch, key_name, aggs, domain,
                                   row_valid, float_mode, engine)


def _domain_partials_onehot(batch, key_name, aggs, domain, row_valid,
                            float_mode, engine):
    K = int(domain)
    col = batch[key_name]
    if col.dtype.kind not in (T.Kind.INT8, T.Kind.INT16, T.Kind.INT32,
                              T.Kind.INT64):
        raise TypeError("group_by_onehot needs an integer key column")
    n = col.num_rows
    row_live = jnp.ones((n,), jnp.bool_) if row_valid is None else row_valid
    live = col.validity & row_live

    # null keys form their own group (bucket K), like the sort-scan path;
    # dead padding rows are dropped from the onehot entirely (callers
    # rely on the overflow flag to fall back to sort-scan)
    bucket, overflow = _domain_bucket_overflow(col, live, K)

    # ---- plan the stacked payload ------------------------------------
    # int8 slots: [0]=ones(count*), then per referenced column one valid
    # flag, then 8 byte limbs per integer sum column
    is_float = {}
    int_cols, float_cols, dec_cols = [], [], []
    valid_slot = {}
    for spec in aggs:
        if spec.op not in ("sum", "mean", "count"):
            raise NotImplementedError(
                f"group_by_onehot: {spec.op} stays on the sort-scan path")
        if spec.column is None:
            continue
        c = spec.column
        if isinstance(batch[c], Decimal128Column):
            if spec.op not in ("sum", "count", "mean"):
                raise NotImplementedError(
                    f"group_by_onehot: {spec.op} over decimal groups "
                    "stays on the sort-scan path")
            valid_slot.setdefault(c, 0)
            is_float[c] = False
            if spec.op in ("sum", "mean") and c not in dec_cols:
                dec_cols.append(c)
            continue
        valid_slot.setdefault(c, 0)  # slot index assigned below
        if spec.op in ("sum", "mean"):
            fl = batch[c].dtype.kind in (T.Kind.FLOAT32, T.Kind.FLOAT64)
            is_float[c] = fl
            target = float_cols if fl else int_cols
            if c not in target:
                target.append(c)

    cols8 = [jnp.ones((n,), jnp.int8)]  # slot 0: count(*)
    for c in valid_slot:
        valid_slot[c] = len(cols8)
        cols8.append((batch[c].validity & row_live).astype(jnp.int8))
    limb_slot = {}
    for c in int_cols:
        vcol = batch[c]
        vvalid = vcol.validity & row_live
        u = jax.lax.bitcast_convert_type(
            jnp.where(vvalid, vcol.data.astype(jnp.int64), jnp.int64(0)),
            jnp.uint64)
        bytes8 = jax.lax.bitcast_convert_type(u, jnp.uint8)  # [n, 8]
        x = jnp.where(vvalid[:, None],
                      bytes8.astype(jnp.int16) - jnp.int16(128),
                      jnp.int16(0)).astype(jnp.int8)
        limb_slot[c] = len(cols8)
        cols8.extend(x[:, j] for j in range(8))
    # decimal128 sum columns: 16 byte limbs of the two's-complement
    # unscaled value + one negative-flag slot (the signed sum is the
    # unsigned-representation sum minus 2^128 x #negatives — unlike the
    # int64 path that correction does NOT wrap away, since decimal
    # overflow is judged exactly against 10^precision)
    dec_slot = {}
    for c in dec_cols:
        vcol = batch[c]
        vvalid = vcol.validity & row_live
        limbs = jnp.where(vvalid[:, None], vcol.limbs,
                          jnp.zeros((), jnp.uint64))
        bytes16 = jax.lax.bitcast_convert_type(
            limbs, jnp.uint8).reshape(n, 16)
        x = jnp.where(vvalid[:, None],
                      bytes16.astype(jnp.int16) - jnp.int16(128),
                      jnp.int16(0)).astype(jnp.int8)
        neg = (vvalid
               & ((limbs[:, 1] >> jnp.uint64(63)) != 0)).astype(jnp.int8)
        dec_slot[c] = len(cols8)
        cols8.extend(x[:, j] for j in range(16))
        cols8.append(neg)
    X8 = jnp.stack(cols8, axis=1)  # [n, m8]

    def dekker_limbs(c):
        """Exact 3-way split of a masked f64 column into f32 (hi, mid, lo)."""
        vcol = batch[c]
        vvalid = vcol.validity & row_live
        v = jnp.where(vvalid, vcol.data.astype(jnp.float64), 0.0)
        hi = v.astype(jnp.float32)
        r1 = v - hi.astype(jnp.float64)
        mid = r1.astype(jnp.float32)
        lo_ = (r1 - mid.astype(jnp.float64)).astype(jnp.float32)
        return [hi, mid, lo_]

    if engine not in ("xla", "pallas"):
        raise ValueError(f"unknown engine {engine!r} "
                         "(use 'auto', 'xla', 'pallas', or 'scatter')")
    if engine == "pallas" and float_cols and float_mode != "f32x3":
        raise ValueError(
            "engine='pallas' computes float sums with the f32x3 Dekker "
            "split only (no f64 contraction in the kernel); pass "
            "float_mode='f32x3' to acknowledge the non-bit-stable rounding")
    use_f32x3 = float_mode == "f32x3" or engine == "pallas"

    F = None
    if float_cols:
        if use_f32x3:
            F = jnp.stack(
                sum((dekker_limbs(c) for c in float_cols), []), axis=1)
        else:
            F = jnp.stack(
                [jnp.where(batch[c].validity & row_live,
                           batch[c].data.astype(jnp.float64), 0.0)
                 for c in float_cols], axis=1)

    if engine == "pallas":
        from ..ops.pallas_kernels import onehot_groupby_parts

        bucket_pl = jnp.where(row_live, bucket, jnp.int32(-1))
        Fp = F if F is not None else jnp.zeros((n, 0), jnp.float32)
        part, fpart = onehot_groupby_parts(bucket_pl, X8, Fp, K + 1)
    else:
        # Chunked contractions with the one-hot built PER CHUNK: int32
        # partials hold |x| <= 128 summed over a block, so blocks stay
        # under 2^31/128 = 2^24 rows — and only one [B, K+1] one-hot is
        # ever live (a full-width [n, K+1] float one-hot is multi-GB at
        # bench row counts; the f64-emulated contraction of one OOM'd
        # real v5e HBM at 16M rows in round 3).  Static n means static
        # slices, combined in int64/float64 across chunks.
        B = 1 << 23
        kids = jnp.arange(K + 1, dtype=jnp.int32)[None, :]
        fdt = jnp.float32 if use_f32x3 else jnp.float64
        part = jnp.zeros((K + 1, X8.shape[1]), jnp.int64)
        fpart = (jnp.zeros((K + 1, F.shape[1]), jnp.float64)
                 if float_cols else None)
        for lo in range(0, n, B):
            ohc = ((bucket[lo:lo + B, None] == kids)
                   & row_live[lo:lo + B, None])
            part = part + jax.lax.dot_general(
                ohc.astype(jnp.int8).T, X8[lo:lo + B],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            ).astype(jnp.int64)
            if float_cols:
                fpart = fpart + jax.lax.dot_general(
                    ohc.astype(fdt).T, F[lo:lo + B],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=fdt,
                ).astype(jnp.float64)

    fsum_of = {}
    for i, c in enumerate(float_cols):
        if use_f32x3:
            fsum_of[c] = (fpart[:, 3 * i] + fpart[:, 3 * i + 1]
                          + fpart[:, 3 * i + 2])
        else:
            fsum_of[c] = fpart[:, i]

    counts_star = part[:, 0]
    cnt_of = {c: part[:, s] for c, s in valid_slot.items()}

    # ---- exact integer sums: rebuild from offset byte limbs ----------
    isum_of = {}
    shifts = (jnp.uint64(8) * jnp.arange(8, dtype=jnp.uint64))[None, :]
    for c in int_cols:
        s = limb_slot[c]
        true_limb = part[:, s:s + 8] + jnp.int64(128) * cnt_of[c][:, None]
        total_u = jnp.sum(
            jax.lax.bitcast_convert_type(true_limb, jnp.uint64)
            << shifts, axis=1)
        isum_of[c] = jax.lax.bitcast_convert_type(total_u, jnp.int64)

    # ---- exact decimal128 sums: 256-bit rebuild with sign correction --
    # sum = (Σ_j true_limb_j · 256^j) − 2^128 · #negatives, carried out in
    # uint32[K+1, 8] limbs (≤ 2^158 for 2^31 rows — never wraps); overflow
    # vs 10^min(38, p+10) nulls the group (Spark non-ANSI Sum)
    d64_of = {}
    if dec_cols:
        from ..ops import decimal as D

        m32 = jnp.uint64(0xFFFFFFFF)
        KP1 = K + 1
        for c in dec_cols:
            s = dec_slot[c]
            true_limb = jax.lax.bitcast_convert_type(
                part[:, s:s + 16]
                + jnp.int64(128) * cnt_of[c][:, None], jnp.uint64)
            # lane accumulators stay uint64 (each < 2^41 + carries);
            # every byte sum j lands at bit 8j = 32·(j//4) + 8·(j%4)
            lanes = [jnp.zeros((KP1,), jnp.uint64) for _ in range(9)]
            for j in range(16):
                q, r = divmod(8 * j, 32)
                slo = true_limb[:, j] & m32  # < 2^33; slo<<r fits u64
                shi = true_limb[:, j] >> jnp.uint64(32)
                a = slo << jnp.uint64(r)
                b = shi << jnp.uint64(r)
                lanes[q] = lanes[q] + (a & m32)
                lanes[q + 1] = lanes[q + 1] + (a >> jnp.uint64(32)) \
                    + (b & m32)
                lanes[q + 2] = lanes[q + 2] + (b >> jnp.uint64(32))
            usum = _carry_fold_u64_lanes(jnp.stack(lanes[:8], axis=1))
            negcnt = part[:, s + 16]  # >= 0, < 2^31: one u32 limb at 2^128
            sub = jnp.zeros((KP1, 8), jnp.uint32).at[:, 4].set(
                negcnt.astype(jnp.uint32))
            d64_of[c] = D._add(usum, D._neg(sub)).astype(jnp.uint64)

    parts = {"star": counts_star, "cnt": cnt_of, "isum": isum_of,
             "fsum": fsum_of, "d64": d64_of}
    return parts, overflow


def _domain_bucket_overflow(col, live, K):
    """Shared key lowering for the domain engines: bucket id per row
    (null/dead keys -> K) and the full-width out-of-domain flag.

    The bounds check runs at int64 width: an INT64 key like 2**32 wraps
    to 0 under an int32 cast and would silently pass, and a domain beyond
    a narrow key dtype's range (INT8 key, domain=200) must compare
    instead of raising at trace time.
    """
    k_orig = col.data.astype(jnp.int64)
    overflow = jnp.any(live & ((k_orig < 0) | (k_orig >= K)))
    k = k_orig.astype(jnp.int32)
    bucket = jnp.where(live, jnp.clip(k, 0, K - 1), K)
    return bucket, overflow


def _carry_fold_u64_lanes(lanes):
    """[G, 8] uint64 per-lane sums -> uint32[G, 8] limbs mod 2^256
    (carry-propagate once; bits beyond limb 7 drop = mod-2^256 add)."""
    m32 = jnp.uint64(0xFFFFFFFF)
    carry = jnp.zeros(lanes.shape[:1], jnp.uint64)
    out32 = []
    for i in range(8):
        t = lanes[:, i] + carry
        out32.append((t & m32).astype(jnp.uint32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out32, axis=1)


def _finalize_domain(batch, key_name, K, aggs, parts):
    """Turn (possibly psum-merged) :func:`_domain_partials` into the
    group-by result.  Decimal lanes re-fold their carries here — after
    merging — and the overflow-vs-10^p check runs on the GLOBAL sum, so
    a per-device overflow that cancels across devices does not null the
    group (matching what a single-chip aggregation of the union would
    produce)."""
    from ..ops import decimal as D

    dsum_of, dover_of, draw_of = {}, {}, {}
    for c, d64 in parts["d64"].items():
        s256 = _carry_fold_u64_lanes(d64)
        out_p = min(38, batch[c].dtype.precision + 10)
        mag, _ = D._abs(s256)
        dover_of[c] = ~D._lt_u(mag, jnp.broadcast_to(D._pow10(out_p),
                                                     mag.shape))
        dsum_of[c] = (D._to_i128(s256),
                      T.SparkType.decimal(out_p, batch[c].dtype.scale))
        draw_of[c] = s256
    return _assemble_domain_result(
        batch, key_name, K, aggs, parts["star"], parts["cnt"],
        parts["isum"], parts["fsum"], dsum_of, dover_of, draw_of)


def _assemble_domain_result(batch, key_name, K, aggs, counts_star, cnt_of,
                            isum_of, fsum_of, dsum_of, dover_of, draw_of):
    """Shared tail of the domain-key engines (onehot / scatter): turn the
    per-bucket reductions into a result batch with live groups compacted
    to the front in key order (null-key bucket K last among live)."""
    col = batch[key_name]
    out_cols = {}
    key_valid = jnp.arange(K + 1) < K
    out_cols[key_name] = Column(
        jnp.arange(K + 1, dtype=col.dtype.jnp_dtype),
        key_valid & (counts_star > 0), col.dtype)

    for spec in aggs:
        if spec.op == "count" and spec.column is None:
            out_cols[spec.out_name] = Column(
                counts_star.astype(jnp.int64), counts_star >= 0, T.INT64)
            continue
        cnt_v = cnt_of[spec.column]
        if spec.op == "count":
            out_cols[spec.out_name] = Column(
                cnt_v.astype(jnp.int64), cnt_v >= 0, T.INT64)
            continue
        if spec.column in dsum_of:
            if spec.op == "mean":
                limbs128, ok, out_t = _decimal_avg(
                    draw_of[spec.column], cnt_v, batch[spec.column].dtype)
                out_cols[spec.out_name] = Decimal128Column(
                    limbs128, (cnt_v > 0) & ok, out_t)
            else:
                limbs128, out_t = dsum_of[spec.column]
                out_cols[spec.out_name] = Decimal128Column(
                    limbs128, (cnt_v > 0) & ~dover_of[spec.column], out_t)
            continue
        if spec.column in fsum_of:
            fsum = fsum_of[spec.column]
            if spec.op == "mean":
                res = fsum / jnp.maximum(cnt_v, 1).astype(jnp.float64)
            else:
                res = fsum
            out_cols[spec.out_name] = Column(res, cnt_v > 0, T.FLOAT64)
        elif spec.op == "mean":
            out_cols[spec.out_name] = Column(
                isum_of[spec.column].astype(jnp.float64)
                / jnp.maximum(cnt_v, 1).astype(jnp.float64),
                cnt_v > 0, T.FLOAT64)
        else:
            out_cols[spec.out_name] = Column(
                isum_of[spec.column], cnt_v > 0, T.INT64)

    # compact live groups to the front (stable) like the sort-scan path
    live_group = counts_star > 0
    order = jnp.argsort(~live_group, stable=True).astype(jnp.int32)
    from .gather import gather_column

    compacted = ColumnBatch({
        name: gather_column(c, order) for name, c in out_cols.items()})
    ng = jnp.sum(live_group.astype(jnp.int32))
    return compacted, ng


def group_by_scatter(
    batch: ColumnBatch,
    key_name: str,
    aggs: Sequence[AggSpec],
    domain: int,
    row_valid=None,
):
    """Hash-aggregate as segment sums — the linear-pass engine for
    platforms where scatter-add is cheap.

    Same contract and Spark semantics as :func:`group_by_onehot`
    (small static integer key domain, null keys in bucket K, returns
    ``(result, num_groups, overflow)``), but each aggregate is ONE
    ``segment_sum`` pass over the rows instead of a one-hot contraction.

    Distinct from the general ``engine="scatter"`` of :func:`group_by`
    (r6 delete-or-measure verdict: NOT redundant, both stay): here the
    keys ARE the segment ids — dense ints in a static domain — so there
    is no key normalization, no slot-table build, no probe walk, and no
    overflow fallback.  The general scatter engine pays all four to
    handle arbitrary multi-column keys; at q6's shape the domain engine
    stays measurably ahead (micro rows ``group_by_100keys_scatter`` vs
    ``group_by_100keys_domain``).

    Engine choice is a hardware fact, not a preference: XLA scatters
    measured 16-150ms per 2M rows on TPU v5e (BASELINE.md) — two orders
    off the MXU one-hot — while on XLA-CPU the relationship inverts
    (segment_sum 5ms vs one-hot matmul 416ms at 256K rows, round 4).
    ``group_by_onehot(engine="auto")`` picks per platform.

    Float sums are plain f64 adds (the sort-scan path's rounding class);
    int64 sums keep Spark's non-ANSI mod-2^64 wraparound; decimal128
    sums are exact 256-bit with overflow -> null.
    """
    parts, overflow = _domain_partials_scatter(batch, key_name, aggs,
                                               domain, row_valid)
    res, ng = _finalize_domain(batch, key_name, int(domain), aggs, parts)
    return res, ng, overflow


def _domain_partials_scatter(batch, key_name, aggs, domain, row_valid=None):
    """Scatter/segment-sum engine for :func:`_domain_partials`."""
    from jax.ops import segment_sum

    batch = materialize_batch(batch)  # direct group_by_scatter entry
    K = int(domain)
    col = batch[key_name]
    if col.dtype.kind not in (T.Kind.INT8, T.Kind.INT16, T.Kind.INT32,
                              T.Kind.INT64):
        raise TypeError("group_by_scatter needs an integer key column")
    n = col.num_rows
    row_live = jnp.ones((n,), jnp.bool_) if row_valid is None else \
        row_valid.astype(jnp.bool_)
    live = col.validity & row_live

    bucket, overflow = _domain_bucket_overflow(col, live, K)
    # dead rows land in bucket K with all-zero contributions (their
    # count/valid/value weights below are masked by row_live)

    counts_star = segment_sum(
        row_live.astype(jnp.int64), bucket, num_segments=K + 1)

    cnt_of, isum_of, fsum_of, d64_of = {}, {}, {}, {}
    for spec in aggs:
        if spec.column is None:
            continue
        if spec.op not in ("sum", "mean", "count"):
            raise NotImplementedError(
                f"group_by_scatter: {spec.op} stays on the sort-scan path")
        c = spec.column
        vcol = batch[c]
        vvalid = vcol.validity & row_live
        if c not in cnt_of:
            cnt_of[c] = segment_sum(
                vvalid.astype(jnp.int64), bucket, num_segments=K + 1)
        if spec.op not in ("sum", "mean"):
            continue
        if isinstance(vcol, Decimal128Column):
            if c in d64_of:
                continue
            from ..ops import decimal as D

            # _from_i128 sign-extends to 256-bit two's complement, so the
            # per-lane sums are already correct mod 2^256 (same argument
            # as the sort path's _seg_scan_sum256: <= 2^31 rows of
            # |v| < 2^127 never reach the wrap)
            u = D._from_i128(jnp.where(vvalid[:, None], vcol.limbs,
                                       jnp.zeros((), jnp.uint64)))
            # each u32 lane sums in uint64: n <= 2^31 rows of < 2^32
            # stays under 2^63; carry-propagate once at the end
            lanes = segment_sum(u.astype(jnp.uint64), bucket,
                                num_segments=K + 1)  # [K+1, 8]
            d64_of[c] = _carry_fold_u64_lanes(lanes).astype(jnp.uint64)
        elif vcol.dtype.kind in (T.Kind.FLOAT32, T.Kind.FLOAT64):
            if c not in fsum_of:
                fsum_of[c] = segment_sum(
                    jnp.where(vvalid, vcol.data.astype(jnp.float64), 0.0),
                    bucket, num_segments=K + 1)
        else:
            if c not in isum_of:
                isum_of[c] = segment_sum(
                    jnp.where(vvalid, vcol.data.astype(jnp.int64),
                              jnp.int64(0)),
                    bucket, num_segments=K + 1)

    return {"star": counts_star, "cnt": cnt_of, "isum": isum_of,
            "fsum": fsum_of, "d64": d64_of}, overflow


def _pad_rows(col, pad_to: int):
    """Pad a result column with null rows up to ``pad_to`` rows."""
    n = col.num_rows
    if n == pad_to:
        return col
    extra = pad_to - n
    pv = jnp.concatenate([col.validity, jnp.zeros((extra,), jnp.bool_)])
    if isinstance(col, Decimal128Column):
        pl = jnp.concatenate(
            [col.limbs, jnp.zeros((extra, 2), jnp.uint64)], axis=0)
        return Decimal128Column(pl, pv, col.dtype)
    pd = jnp.concatenate(
        [col.data, jnp.zeros((extra,), col.data.dtype)])
    return Column(pd, pv, col.dtype)


def group_by_domain_or_sort(
    batch: ColumnBatch,
    key_name: str,
    aggs: Sequence[AggSpec],
    domain: int,
    row_valid=None,
    engine: str = "auto",
    float_mode: str = "f64",
):
    """Adaptive aggregation: the domain engine when every live key fits
    ``[0, domain)``, the general sort-scan otherwise — in ONE jitted
    program.  Both paths trace; the overflow flag picks which executes
    at runtime (``lax.cond``), so callers no longer hand-roll the
    "assert or fall back" dance the raw :func:`group_by_onehot` contract
    requires.  Only the O(n) bounds check runs outside the cond; the
    domain partials (the O(n*K) contraction / segment sums) trace inside
    the domain branch, so an overflowing batch pays the sort-scan alone.

    Output rows are padded to ``max(num_rows, domain + 1)`` so the two
    branches agree in shape; group ORDER differs by branch (domain: key
    order with the null group last; sort-scan: key order, nulls first) —
    Spark defines no group order.  sum/count/mean only (the domain
    engines' op set).  Returns ``(result, num_groups)``.
    """
    n = batch.num_rows
    K = int(domain)
    pad_to = max(n, K + 1)
    col = materialize_column(batch[key_name])
    row_live = jnp.ones((n,), jnp.bool_) if row_valid is None else \
        row_valid.astype(jnp.bool_)
    _, overflow = _domain_bucket_overflow(col, col.validity & row_live, K)

    def pad(res_ng):
        res, ng = res_ng
        return (ColumnBatch({name: _pad_rows(c, pad_to)
                             for name, c in zip(res.names, res.columns)}),
                ng.astype(jnp.int32))

    def dom(_):
        parts, _ovf = _domain_partials(batch, key_name, aggs, domain,
                                       row_valid, engine, float_mode)
        return pad(_finalize_domain(batch, key_name, K, list(aggs), parts))

    def srt(_):
        return pad(group_by(batch, [key_name], list(aggs),
                            row_valid=row_valid))

    return jax.lax.cond(overflow, srt, dom, None)
