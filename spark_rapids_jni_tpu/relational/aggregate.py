"""Sort-based group-by aggregation (Spark hash-aggregate semantics).

A hash aggregate on TPU would fight the hardware (serial probing, scatter
chains); instead: radix-key sort → adjacent-difference segment boundaries →
``jax.ops.segment_*`` reductions, all static-shape.  Output is padded to the
input row count with a device ``num_groups`` scalar (same discipline as
:mod:`filter`).

Spark null/type semantics implemented here (mirrors what the plugin gets
from cudf groupby + Spark's type promotion):

* group keys: nulls form their own group; floats normalize -0.0/NaN first
  (equality domain, :mod:`keys`).
* sum/min/max ignore null inputs; all-null group -> null result.
* count(col) counts non-nulls, count(*) counts rows; never null.
* sum(int*) -> int64 (non-ANSI wraparound), sum(float*) -> float64,
  avg(*) -> float64.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column, ColumnBatch, Decimal128Column, StringColumn
from . import keys as K
from .gather import gather_batch, gather_column

_OPS = ("sum", "count", "min", "max", "mean")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    op: str           # sum | count | min | max | mean
    column: Optional[str]  # None only for count(*)
    out_name: str

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"unknown agg op {self.op!r}")
        if self.column is None and self.op != "count":
            raise ValueError("only count supports column=None (count(*))")


def _sum_dtype(dtype: T.SparkType) -> T.SparkType:
    if dtype.kind in (T.Kind.BOOLEAN, T.Kind.INT8, T.Kind.INT16, T.Kind.INT32,
                      T.Kind.INT64):
        return T.INT64
    if dtype.kind in (T.Kind.FLOAT32, T.Kind.FLOAT64):
        return T.FLOAT64
    raise NotImplementedError(f"sum of {dtype!r}")


def _segment_minmax(data, valid, gid, n, op: str):
    """Null-ignoring segmented min/max with Spark float/bool semantics.

    Spark orders NaN greater than every number (Java compare): max of a
    group containing NaN is NaN; min skips NaNs unless the group is all-NaN.
    """
    is_float = jnp.issubdtype(data.dtype, jnp.floating)
    was_bool = data.dtype == jnp.bool_
    if is_float:
        fill = jnp.array(jnp.inf if op == "min" else -jnp.inf, data.dtype)
        nan_in = valid & jnp.isnan(data)
        valid_num = valid & ~jnp.isnan(data)
    elif was_bool:
        data = data.astype(jnp.uint8)
        fill = jnp.uint8(1 if op == "min" else 0)
        valid_num = valid
    else:
        info = jnp.iinfo(data.dtype)
        fill = jnp.array(info.max if op == "min" else info.min, data.dtype)
        valid_num = valid
    masked = jnp.where(valid_num, data, fill)
    f = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    res = f(masked, gid, num_segments=n, indices_are_sorted=True)
    if is_float:
        seg_has_nan = (
            jax.ops.segment_sum(nan_in.astype(jnp.int32), gid, num_segments=n,
                                indices_are_sorted=True) > 0
        )
        seg_has_num = (
            jax.ops.segment_sum(valid_num.astype(jnp.int32), gid, num_segments=n,
                                indices_are_sorted=True) > 0
        )
        nan = jnp.array(jnp.nan, res.dtype)
        if op == "max":
            res = jnp.where(seg_has_nan, nan, res)
        else:
            res = jnp.where(seg_has_nan & ~seg_has_num, nan, res)
    if was_bool:
        res = res.astype(jnp.bool_)
    return res


def group_by(
    batch: ColumnBatch,
    key_names: Sequence[str],
    aggs: Sequence[AggSpec],
    row_valid=None,
) -> tuple:
    """Group ``batch`` by ``key_names``; returns (result_batch, num_groups).

    The result batch has the key columns (group order = key sort order,
    deterministic) followed by one column per AggSpec, padded to the input
    row count with null rows past ``num_groups``.

    ``row_valid`` (bool[n], optional) marks rows that exist: padding rows of
    an upstream compaction/shuffle are excluded from every group (without it
    they would merge into the null-key group).  They sort as one trailing
    pseudo-group masked out of the result.
    """
    n = batch.num_rows
    key_cols = [batch[k] for k in key_names]
    karr = K.batch_radix_keys(key_cols, equality=True, nulls_first=True)
    if row_valid is not None:
        occ = row_valid.astype(jnp.bool_)
        karr = [jnp.where(occ, jnp.uint32(0), jnp.uint32(1))] + [
            jnp.where(occ, k, jnp.zeros((), k.dtype)) for k in karr
        ]
    iota = jnp.arange(n, dtype=jnp.int32)
    res = jax.lax.sort(tuple(karr) + (iota,), num_keys=len(karr), is_stable=True)
    sorted_keys, perm = res[:-1], res[-1]

    boundary = ~K.rows_equal_adjacent(sorted_keys)
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    if row_valid is not None:
        sorted_occ = jnp.take(row_valid.astype(jnp.bool_), perm)
        num_groups = (boundary & sorted_occ).sum(dtype=jnp.int32)
    else:
        num_groups = boundary.sum(dtype=jnp.int32)

    needed = list(dict.fromkeys(
        list(key_names) + [a.column for a in aggs if a.column is not None]
    ))
    sorted_batch = gather_batch(batch.select(needed), perm)

    # group-start row positions in group order (stable front-compaction)
    start_pos = jnp.argsort(~boundary, stable=True).astype(jnp.int32)
    out_valid = iota < num_groups

    out = {}
    for name in key_names:
        out[name] = gather_column(sorted_batch[name], start_pos, out_valid)

    for spec in aggs:
        if spec.op == "count":
            if spec.column is None:
                ones = jnp.ones((n,), jnp.int64)
            else:
                ones = sorted_batch[spec.column].validity.astype(jnp.int64)
            cnt = jax.ops.segment_sum(ones, gid, num_segments=n,
                                      indices_are_sorted=True)
            out[spec.out_name] = Column(cnt, out_valid, T.INT64)
            continue

        col = sorted_batch[spec.column]
        if isinstance(col, (StringColumn, Decimal128Column)):
            raise NotImplementedError(
                f"{spec.op} over {col.dtype!r} groups not implemented yet"
            )
        data, valid = col.data, col.validity
        nn = jax.ops.segment_sum(valid.astype(jnp.int32), gid, num_segments=n,
                                 indices_are_sorted=True)
        has_any = nn > 0

        if spec.op in ("sum", "mean"):
            out_t = T.FLOAT64 if spec.op == "mean" else _sum_dtype(col.dtype)
            acc = data.astype(out_t.jnp_dtype if spec.op == "sum" else jnp.float64)
            acc = jnp.where(valid, acc, jnp.zeros((), acc.dtype))
            s = jax.ops.segment_sum(acc, gid, num_segments=n,
                                    indices_are_sorted=True)
            if spec.op == "mean":
                s = s / jnp.maximum(nn, 1).astype(jnp.float64)
            out[spec.out_name] = Column(s, out_valid & has_any, out_t)
        else:  # min / max
            r = _segment_minmax(data, valid, gid, n, spec.op)
            out[spec.out_name] = Column(r, out_valid & has_any, col.dtype)

    return ColumnBatch(out), num_groups
