"""Sort-scan group-by aggregation (Spark hash-aggregate semantics).

Three designs were measured on the real chip this round:

* radix-sort + argsort + segment ops (round 1): 3.2 Mrows/s — the two
  sorts and the scatter-backed ``segment_*`` ops each cost 95-630ms at 2M
  rows on this TPU;
* scatter-min bucket election + segment ops: no better — XLA scatters are
  the single slowest primitive on this chip (~150ms per 2M-row scatter);
* THIS design: **one multi-operand sort, then only scans and gathers** —
  no scatter anywhere, and agg values ride the sort as extra payload
  operands so no full-width random gather is needed afterwards either.

Pipeline: lower keys to uint32 radix words (:mod:`keys`, equality domain)
-> one ``lax.sort`` carrying [keys..., row-id, agg-value words...] ->
adjacent-compare boundaries on the sorted key words -> per-agg prefix
``cumsum`` (or segmented min/max ``associative_scan``) -> group result =
scan value at each group's last row minus the previous group's, fetched
with one small gather at the compacted group-end positions.

Output is padded to the input row count with a device ``num_groups``
scalar (same discipline as :mod:`filter`); groups appear in key-sorted
order, nulls first (Spark does not define a group order; this one is
deterministic).

Spark null/type semantics implemented here (mirrors what the plugin gets
from cudf groupby + Spark's type promotion):

* group keys: nulls form their own group; floats normalize -0.0/NaN first
  (equality domain, :mod:`keys`).
* sum/min/max ignore null inputs; all-null group -> null result.
* count(col) counts non-nulls, count(*) counts rows; never null.
* sum(int*) -> int64 (non-ANSI wraparound), sum(float*) -> float64,
  avg(*) -> float64.  Float sums are computed as prefix-sum differences;
  they are not bit-identical to a per-group left-fold (Spark itself is
  order-nondeterministic under shuffles).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import types as T
from ..columnar.column import Column, ColumnBatch, Decimal128Column, StringColumn
from . import keys as K
from .gather import gather_column

_OPS = ("sum", "count", "min", "max", "mean")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    op: str           # sum | count | min | max | mean
    column: Optional[str]  # None only for count(*)
    out_name: str

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"unknown agg op {self.op!r}")
        if self.column is None and self.op != "count":
            raise ValueError("only count supports column=None (count(*))")


def _sum_dtype(dtype: T.SparkType) -> T.SparkType:
    if dtype.kind in (T.Kind.BOOLEAN, T.Kind.INT8, T.Kind.INT16, T.Kind.INT32,
                      T.Kind.INT64):
        return T.INT64
    if dtype.kind in (T.Kind.FLOAT32, T.Kind.FLOAT64):
        return T.FLOAT64
    raise NotImplementedError(f"sum of {dtype!r}")


def _seg_scan_minmax(vals, boundary, op):
    """Segmented running min/max: resets at rows where boundary is True."""
    def comb(a, b):
        av, ab = a
        bv, bb = b
        m = jnp.minimum(av, bv) if op == "min" else jnp.maximum(av, bv)
        return jnp.where(bb, bv, m), ab | bb

    out, _ = jax.lax.associative_scan(comb, (vals, boundary))
    return out


def _seg_scan_sum(vals, boundary):
    """Segmented running sum (resets at boundaries).

    Used for FLOAT sums: a global prefix-sum difference cancels
    catastrophically when a small group sorts after a large one (1e18
    prefixes have ~128 ulp); the segmented scan keeps each group's sum a
    tree-reduction of only its own elements.
    """
    def comb(a, b):
        av, ab = a
        bv, bb = b
        return jnp.where(bb, bv, av + bv), ab | bb

    out, _ = jax.lax.associative_scan(comb, (vals, boundary))
    return out


def group_by(
    batch: ColumnBatch,
    key_names: Sequence[str],
    aggs: Sequence[AggSpec],
    row_valid=None,
) -> tuple:
    """Group ``batch`` by ``key_names``; returns (result_batch, num_groups).

    The result batch has the key columns (group order = key sort order,
    nulls first, deterministic) followed by one column per AggSpec, padded
    to the input row count with null rows past ``num_groups``.

    ``row_valid`` (bool[n], optional) marks rows that exist: padding rows
    of an upstream filter/shuffle are excluded from every group.  They
    sort to the back as one trailing pseudo-run that the group count and
    end positions simply never reach.
    """
    n = batch.num_rows
    key_cols = [batch[k] for k in key_names]
    karr = K.batch_radix_keys(key_cols, equality=True, nulls_first=True)
    have_rv = row_valid is not None
    if have_rv:
        occ = row_valid.astype(jnp.bool_)
        karr = [jnp.where(occ, jnp.uint32(0), jnp.uint32(1))] + [
            jnp.where(occ, k, jnp.zeros((), k.dtype)) for k in karr
        ]
    iota = jnp.arange(n, dtype=jnp.int32)

    # agg columns ride the sort as payload words (no post-sort gathers)
    agg_cols = []
    for spec in aggs:
        if spec.column is not None and spec.column not in agg_cols:
            col = batch[spec.column]
            if isinstance(col, (StringColumn, Decimal128Column)):
                raise NotImplementedError(
                    f"{spec.op} over {col.dtype!r} groups not implemented yet"
                )
            agg_cols.append(spec.column)
    # agg data rides the sort in its native dtype (the TPU X64-rewrite
    # pass legalizes 64-bit sort payloads but not u32-pair bitcasts)
    payload = [iota]
    spans = {}
    for name in agg_cols:
        col = batch[name]
        spans[name] = len(payload)
        payload.extend([col.data, col.validity])

    nk = len(karr)
    res = jax.lax.sort(tuple(karr) + tuple(payload), num_keys=nk,
                       is_stable=True)
    skeys = res[:nk]
    sperm = res[nk]
    spay = res[nk + 1:]

    boundary = ~K.rows_equal_adjacent(skeys)
    sorted_occ = (skeys[0] == 0) if have_rv else jnp.ones((n,), jnp.bool_)
    num_groups = (boundary & sorted_occ).sum(dtype=jnp.int32)

    # last row of each live group: next row starts a new group / is dead /
    # doesn't exist
    nxt_boundary = jnp.concatenate(
        [boundary[1:], jnp.ones((1,), jnp.bool_)])
    nxt_occ = jnp.concatenate([sorted_occ[1:], jnp.zeros((1,), jnp.bool_)])
    is_end = sorted_occ & (nxt_boundary | ~nxt_occ)
    # compact end positions to the front (2-operand flag sort, no scatter)
    ends = jax.lax.sort(
        ((~is_end).astype(jnp.uint32), iota), num_keys=1, is_stable=True
    )[1]
    prev_ends = jnp.roll(ends, 1)
    out_valid = iota < num_groups

    def at_ends_diff(cs):
        """Per-group total from a prefix scan: cs[end_g] - cs[end_{g-1}]."""
        ce = jnp.take(cs, ends)
        cp = jnp.where(iota == 0, jnp.zeros((), cs.dtype),
                       jnp.take(cs, prev_ends))
        return ce - cp

    out = {}
    starts = jnp.where(iota == 0, 0, prev_ends + 1)
    rows0 = jnp.take(sperm, jnp.clip(starts, 0, n - 1))
    for name in key_names:
        out[name] = gather_column(batch[name], rows0, out_valid)

    def sorted_col(name):
        off = spans[name]
        data = spay[off - 1]  # payload[0] is iota (== sperm)
        valid = spay[off] & sorted_occ
        return data, valid

    for spec in aggs:
        if spec.op == "count":
            if spec.column is None:
                ones = sorted_occ.astype(jnp.int64)
            else:
                _, valid = sorted_col(spec.column)
                ones = valid.astype(jnp.int64)
            out[spec.out_name] = Column(at_ends_diff(jnp.cumsum(ones)),
                                        out_valid, T.INT64)
            continue

        data, valid = sorted_col(spec.column)
        col_dtype = batch[spec.column].dtype
        nn = at_ends_diff(jnp.cumsum(valid.astype(jnp.int32)))
        has_any = nn > 0

        if spec.op in ("sum", "mean"):
            out_t = T.FLOAT64 if spec.op == "mean" else _sum_dtype(col_dtype)
            acc = data.astype(out_t.jnp_dtype if spec.op == "sum"
                              else jnp.float64)
            acc = jnp.where(valid, acc, jnp.zeros((), acc.dtype))
            if jnp.issubdtype(acc.dtype, jnp.floating):
                s = jnp.take(_seg_scan_sum(acc, boundary), ends)
            else:
                s = at_ends_diff(jnp.cumsum(acc))  # exact mod-2^64
            if spec.op == "mean":
                s = s / jnp.maximum(nn, 1).astype(jnp.float64)
            out[spec.out_name] = Column(s, out_valid & has_any, out_t)
        else:  # min / max — Spark float semantics: NaN greatest, one NaN
            is_float = jnp.issubdtype(data.dtype, jnp.floating)
            was_bool = data.dtype == jnp.bool_
            if is_float:
                fill = jnp.array(jnp.inf if spec.op == "min" else -jnp.inf,
                                 data.dtype)
                nan_in = valid & jnp.isnan(data)
                valid_num = valid & ~jnp.isnan(data)
            elif was_bool:
                data = data.astype(jnp.uint8)
                fill = jnp.uint8(1 if spec.op == "min" else 0)
                valid_num = valid
            else:
                info = jnp.iinfo(data.dtype)
                fill = jnp.array(info.max if spec.op == "min" else info.min,
                                 data.dtype)
                valid_num = valid
            masked = jnp.where(valid_num, data, fill)
            run = _seg_scan_minmax(masked, boundary, spec.op)
            r = jnp.take(run, ends)
            if is_float:
                seg_nan = at_ends_diff(jnp.cumsum(nan_in.astype(jnp.int32))) > 0
                seg_num = at_ends_diff(
                    jnp.cumsum(valid_num.astype(jnp.int32))) > 0
                nan = jnp.array(jnp.nan, r.dtype)
                if spec.op == "max":
                    r = jnp.where(seg_nan, nan, r)
                else:
                    r = jnp.where(seg_nan & ~seg_num, nan, r)
            if was_bool:
                r = r.astype(jnp.bool_)
            out[spec.out_name] = Column(r, out_valid & has_any, col_dtype)

    return ColumnBatch(out), num_groups


# ---------------------------------------------------------------------------
# MXU path: one-hot int8 matmul aggregation for small static key domains
# ---------------------------------------------------------------------------

def group_by_onehot(
    batch: ColumnBatch,
    key_name: str,
    aggs: Sequence[AggSpec],
    domain: int,
    row_valid=None,
    float_mode: str = "f64",
):
    """Hash-aggregate as matmuls: the TPU-first alternative to the
    sort-scan path when one integer key column has a small static domain
    ``[0, domain)`` (dimension ids, date ordinals, bucketed keys — the q6
    shape).  The per-key FLOPs land on the MXU instead of the VPU sort
    network:

    * one-hot ``[n, K+1]`` int8 (bucket K holds null keys), fused by XLA
      into the dot operand;
    * count(*) / count(col): ``onehot^T @ 1`` with int32 accumulation;
    * sum(int*): exact via byte limbs — each int64 value becomes eight
      int8 lanes ``b_l - 128``; ``onehot^T @ limbs`` accumulates in int32
      (|x|<=128, n<=2^23 keeps partials under 2^31), then the true limb
      sums are rebuilt with ``+128*count`` and recombined in uint64 with
      Spark's non-ANSI wraparound;
    * sum(float*): f32 limb split (hi/mid/lo, exact 3-way Dekker split of
      the f64 mantissa) so the dot runs on MXU-native f32; accumulation
      rounding is within Spark's order-nondeterministic tolerance;
    * mean: sum / count in f64.

    min/max and multi-column keys stay on the sort-scan path.  Returns
    ``(result, num_groups, overflow)`` — ``overflow`` is a device bool
    that is True if any non-null key fell outside ``[0, domain)`` (result
    is then invalid; callers assert or fall back).
    """
    K = int(domain)
    col = batch[key_name]
    if col.dtype.kind not in (T.Kind.INT8, T.Kind.INT16, T.Kind.INT32,
                              T.Kind.INT64):
        raise TypeError("group_by_onehot needs an integer key column")
    n = col.num_rows
    row_live = jnp.ones((n,), jnp.bool_) if row_valid is None else row_valid
    live = col.validity & row_live

    k = col.data.astype(jnp.int32)
    overflow = jnp.any(live & ((k < 0) | (k >= K)))
    # null keys form their own group (bucket K), like the sort-scan path;
    # dead padding rows are dropped from the onehot entirely
    bucket = jnp.where(live, jnp.clip(k, 0, K - 1), K)
    oh = ((bucket[:, None] == jnp.arange(K + 1, dtype=jnp.int32)[None, :])
          & row_live[:, None]).astype(jnp.int8)

    counts_star = jax.lax.dot_general(
        oh.T, jnp.ones((n, 1), jnp.int8),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32,
    )[:, 0]

    out_cols = {}
    key_valid = jnp.arange(K + 1) < K
    out_cols[key_name] = Column(
        jnp.arange(K + 1, dtype=col.dtype.jnp_dtype),
        key_valid & (counts_star > 0), col.dtype)

    for spec in aggs:
        if spec.op == "count" and spec.column is None:
            out_cols[spec.out_name] = Column(
                counts_star.astype(jnp.int64), counts_star >= 0, T.INT64)
            continue
        vcol = batch[spec.column]
        vvalid = vcol.validity & row_live
        if spec.op == "count":
            cnt = jax.lax.dot_general(
                oh.T, vvalid.astype(jnp.int8)[:, None],
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32,
            )[:, 0]
            out_cols[spec.out_name] = Column(
                cnt.astype(jnp.int64), cnt >= 0, T.INT64)
            continue
        if spec.op not in ("sum", "mean"):
            raise NotImplementedError(
                f"group_by_onehot: {spec.op} stays on the sort-scan path")

        cnt_v = jax.lax.dot_general(
            oh.T, vvalid.astype(jnp.int8)[:, None],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32,
        )[:, 0]

        if vcol.dtype.kind in (T.Kind.FLOAT32, T.Kind.FLOAT64):
            v = jnp.where(vvalid, vcol.data.astype(jnp.float64), 0.0)
            if float_mode == "f32x3":
                # MXU-native: exact 3-way Dekker split, f32 accumulation.
                # Rounding ~1e-6 relative at millions of rows — inside
                # Spark's shuffle-order nondeterminism for many queries,
                # but NOT bit-stable; opt-in.
                hi = v.astype(jnp.float32)
                r1 = v - hi.astype(jnp.float64)
                mid = r1.astype(jnp.float32)
                lo = (r1 - mid.astype(jnp.float64)).astype(jnp.float32)
                limbs = jnp.stack([hi, mid, lo], axis=1)  # [n, 3] f32
                part = jax.lax.dot_general(
                    oh.astype(jnp.float32).T, limbs,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ).astype(jnp.float64)
                fsum = part[:, 0] + part[:, 1] + part[:, 2]
            else:
                # exact mode: f64 contraction (XLA emulates f64 off the
                # MXU; accumulation error matches the sort-scan path's)
                fsum = jax.lax.dot_general(
                    oh.astype(jnp.float64).T, v[:, None],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float64,
                )[:, 0]
            if spec.op == "mean":
                res = fsum / jnp.maximum(cnt_v, 1).astype(jnp.float64)
            else:
                res = fsum
            out_cols[spec.out_name] = Column(res, cnt_v > 0, T.FLOAT64)
            continue

        # exact integer sums via byte limbs
        u = jax.lax.bitcast_convert_type(
            jnp.where(vvalid, vcol.data.astype(jnp.int64), jnp.int64(0)),
            jnp.uint64)
        bytes8 = jax.lax.bitcast_convert_type(u, jnp.uint8)  # [n, 8]
        x = jnp.where(vvalid[:, None],
                      bytes8.astype(jnp.int16) - jnp.int16(128),
                      jnp.int16(0)).astype(jnp.int8)
        part = jax.lax.dot_general(
            oh.T, x, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [K+1, 8]
        true_limb = part.astype(jnp.int64) + jnp.int64(128) * cnt_v[:, None]
        shifts = (jnp.uint64(8) * jnp.arange(8, dtype=jnp.uint64))[None, :]
        total_u = jnp.sum(
            jax.lax.bitcast_convert_type(true_limb, jnp.uint64)
            << shifts, axis=1)
        isum = jax.lax.bitcast_convert_type(total_u, jnp.int64)
        if spec.op == "mean":
            out_cols[spec.out_name] = Column(
                isum.astype(jnp.float64)
                / jnp.maximum(cnt_v, 1).astype(jnp.float64),
                cnt_v > 0, T.FLOAT64)
        else:
            out_cols[spec.out_name] = Column(isum, cnt_v > 0, T.INT64)

    # compact live groups to the front (stable) like the sort-scan path
    live_group = counts_star > 0
    order = jnp.argsort(~live_group, stable=True).astype(jnp.int32)
    from .gather import gather_column

    compacted = ColumnBatch({
        name: gather_column(c, order) for name, c in out_cols.items()})
    ng = jnp.sum(live_group.astype(jnp.int32))
    return compacted, ng, overflow
