"""Vectorized open-addressing slot table over radix key words.

The scatter/hash engines in :mod:`aggregate` and :mod:`join` need a
"which distinct key is this row" primitive that does NOT sort.  This
module provides it as two data-parallel loops over a static power-of-two
slot table:

* :func:`build_slot_table` — every row hashes its uint32 key words
  (:func:`fold_hash`) and linear-probes for a slot.  Each round, still
  unplaced rows propose themselves for their candidate slot and EMPTY
  slots elect the minimum proposing row id (a plain scatter-min over the
  whole table would let a later round's smaller row id steal a slot
  another key already owns, silently merging two key groups — the
  claim is therefore masked to empty slots only).  Rows whose candidate
  slot's owner has equal key words retire; everyone else steps to the
  next slot.  Equal keys share a hash, hence a probe sequence, hence a
  slot: the table is a perfect row -> key-group map when the loop
  drains.
* :func:`probe_slot_table` — the read-only walk: a probe row follows
  its chain until the owner's words match (hit) or an empty slot proves
  the key absent (the linear-probing invariant: a key's chain never
  crosses a slot that was empty at insert time).

Everything is fixed-shape and jit-safe: the while loops are bounded by
``max_rounds`` (insert reports ``overflow`` when rows remain unplaced,
callers fall back to the sort engine under ``lax.cond``), and one round
costs a handful of n-sized gathers/compares — with a table at most half
full the expected round count is the expected probe-chain length, low
single digits.

Because the slot election picks the MINIMUM row id, a slot's owner is
the first occurrence of its key in row order — the same representative
row the stable sort-scan engine exposes, which is what lets the scatter
group-by emit bit-identical key columns.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# FNV-1a over uint32 words, then a lowbias32-style finalizer so every
# key word influences the low bits that pick the slot.
_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)
_MIX1 = np.uint32(0x7FEB352D)
_MIX2 = np.uint32(0x846CA68B)


def next_pow2(x: int) -> int:
    """Smallest power of two >= max(x, 1)."""
    return 1 << max(int(x) - 1, 0).bit_length() if x > 1 else 1


def fold_hash(words):
    """uint32[n] hash per row from a sequence of uint32[n] key words."""
    h = jnp.full(words[0].shape, jnp.asarray(_FNV_OFFSET))
    for w in words:
        h = (h ^ w) * jnp.asarray(_FNV_PRIME)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.asarray(_MIX1)
    h = h ^ (h >> jnp.uint32(15))
    h = h * jnp.asarray(_MIX2)
    return h ^ (h >> jnp.uint32(16))


def build_slot_table(words, live, num_slots: int, max_rounds=None,
                     engine: str = "lax"):
    """Insert rows keyed by ``words`` into an open-addressed slot table.

    ``words``: uint32[n] arrays (radix key words, :mod:`keys`);
    ``live``: bool[n], rows to place (dead rows never probe and never
    own a slot); ``num_slots``: static power of two.

    Returns ``(owner, slot, overflow)``:

    * ``owner`` int32[num_slots] — row id owning each slot (the minimum
      live row id of that slot's key group), ``n`` where empty;
    * ``slot`` int32[n] — each live row's slot, ``num_slots`` for dead
      or unplaced rows (usable directly as a segment id with
      ``num_segments=num_slots + 1``);
    * ``overflow`` bool[] — True when some live row failed to place
      within ``max_rounds`` (more distinct keys than slots, or a probe
      chain past the round bound); the table is then NOT a complete
      key map and callers must fall back.

    ``engine='pallas'`` runs the fused VMEM-resident kernel
    (:func:`ops.pallas_kernels.slot_table_build`) — bit-identical
    product, interpret mode off-accelerator.
    """
    if engine == "pallas":
        from ..ops.pallas_kernels import slot_table_build

        return slot_table_build(words, live, num_slots, max_rounds)
    n = words[0].shape[0]
    S = int(num_slots)
    if S & (S - 1):
        raise ValueError(f"num_slots must be a power of two, got {S}")
    if max_rounds is None:
        max_rounds = S
    imask = jnp.int32(S - 1)
    sentinel = jnp.int32(n)
    rowid = jnp.arange(n, dtype=jnp.int32)
    cand0 = (fold_hash(words) & jnp.uint32(S - 1)).astype(jnp.int32)

    def cond(state):
        rnd, _cand, _slot, active, _owner = state
        return (rnd < max_rounds) & jnp.any(active)

    def body(state):
        rnd, cand, slot, active, owner = state
        claim = jnp.where(active, rowid, sentinel)
        prop = jnp.full((S,), sentinel, jnp.int32).at[cand].min(claim)
        owner = jnp.where(owner == sentinel, prop, owner)
        o = jnp.clip(jnp.take(owner, cand), 0, max(n - 1, 0))
        match = active
        for w in words:
            match = match & (jnp.take(w, o) == w)
        slot = jnp.where(match, cand, slot)
        active = active & ~match
        cand = (cand + 1) & imask
        return rnd + 1, cand, slot, active, owner

    state = (jnp.int32(0), cand0, jnp.full((n,), S, jnp.int32),
             live.astype(jnp.bool_), jnp.full((S,), sentinel, jnp.int32))
    _, _, slot, active, owner = jax.lax.while_loop(cond, body, state)
    return owner, slot, jnp.any(active)


def probe_slot_table(owner, build_words, probe_words, live, max_rounds=None,
                     engine: str = "lax"):
    """Look probe rows' keys up in a built slot table.

    ``owner``: int32[S] from :func:`build_slot_table` (sentinel = number
    of build rows); ``build_words``/``probe_words``: matching uint32
    word sequences for the build and probe sides; ``live``: bool[m]
    probe rows to look up.

    ``max_rounds`` bounds the chain walk; ``None`` keeps the historical
    full-table bound ``S``.  Any bound that covers the table's longest
    occupied run (:func:`chain_bound` computes the exact one) yields
    identical results — the bound only gates termination, so callers can
    stop a pathological chain from walking the whole table.

    Returns ``(found, slot)``: bool[m] and int32[m] (slot is ``S`` for
    misses and dead rows).

    ``engine='pallas'`` runs the fused VMEM-resident chain walk
    (:func:`ops.pallas_kernels.slot_table_probe`) — bit-identical.
    """
    if engine == "pallas":
        from ..ops.pallas_kernels import slot_table_probe

        return slot_table_probe(owner, build_words, probe_words, live,
                                max_rounds)
    S = owner.shape[0]
    n = build_words[0].shape[0]
    sentinel = jnp.int32(n)
    imask = jnp.int32(S - 1)
    cand0 = (fold_hash(probe_words) & jnp.uint32(S - 1)).astype(jnp.int32)
    m = probe_words[0].shape[0]
    if max_rounds is None:
        max_rounds = S

    def cond(state):
        rnd, _cand, _slot, _found, active = state
        return (rnd < max_rounds) & jnp.any(active)

    def body(state):
        rnd, cand, slot, found, active = state
        o = jnp.take(owner, cand)
        empty = o == sentinel
        oc = jnp.clip(o, 0, max(n - 1, 0))
        match = ~empty
        for bw, pw in zip(build_words, probe_words):
            match = match & (jnp.take(bw, oc) == pw)
        hit = active & match
        slot = jnp.where(hit, cand, slot)
        found = found | hit
        # an empty slot ends the chain: the key cannot live past it
        active = active & ~match & ~empty
        cand = (cand + 1) & imask
        return rnd + 1, cand, slot, found, active

    state = (jnp.int32(0), cand0, jnp.full((m,), S, jnp.int32),
             jnp.zeros((m,), jnp.bool_), live.astype(jnp.bool_))
    _, _, slot, found, _ = jax.lax.while_loop(cond, body, state)
    return found, slot


def chain_bound(owner, n_build: int):
    """Exact probe-round bound for a built table: longest circular run
    of occupied slots, plus the empty slot that ends the walk.

    A probe walks occupied slots until a match or the first empty slot,
    so no chain — hit or miss — can be longer than the longest occupied
    run + 1.  Using this as ``probe_slot_table(max_rounds=...)`` is
    therefore result-identical to the full-table bound while keeping a
    pathological (clustered) table from costing ``S`` rounds per probe.
    Returns a traced int32 in ``[1, S]`` (``S`` when the table has no
    empty slot).
    """
    S = owner.shape[0]
    occ = owner != jnp.int32(n_build)
    # unroll the circle once so a run wrapping the table boundary is
    # seen contiguously; cap at S (a full table has no terminating slot)
    occ2 = jnp.concatenate([occ, occ])
    idx = jnp.arange(2 * S, dtype=jnp.int32)
    last_empty = jax.lax.cummax(jnp.where(occ2, jnp.int32(-1), idx))
    run = jnp.where(occ2, idx - last_empty, 0)
    longest = jnp.minimum(jnp.max(run), jnp.int32(S))
    return jnp.clip(longest + 1, 1, S)
