"""Multi-key sort via ``lax.sort`` over order-preserving radix keys.

Spark semantics: per-key ascending/descending and nulls-first/last.  The key
lowering (:mod:`keys`) yields uint32 arrays whose unsigned lexicographic
order is Spark's; descending keys are bitwise-complemented.  ``lax.sort``
with ``num_keys=len(keys)+1`` co-sorts an iota operand that becomes the row
permutation — XLA lowers this to its vectorized bitonic sorter on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from ..columnar.column import ColumnBatch
from . import keys as K
from .gather import gather_batch


@dataclasses.dataclass(frozen=True)
class SortKey:
    name: str
    ascending: bool = True
    nulls_first: bool = True


def sort_permutation(batch: ColumnBatch, sort_keys: Sequence[SortKey]):
    """int32[n] permutation ordering the batch by the given keys (stable)."""
    ops = []
    for sk in sort_keys:
        col = batch[sk.name]
        # Spark default: nulls first when ascending, last when descending;
        # callers pass the explicit flag.  Descending complements key bits,
        # including the null flag, so compute the flag for ascending order.
        flag_first = sk.nulls_first if sk.ascending else not sk.nulls_first
        arrays = [K.null_flag(col, flag_first)]
        # zero null rows' data keys: deterministic (stable) order among nulls
        arrays += [
            jnp.where(col.validity, k, jnp.zeros((), k.dtype))
            for k in K.column_radix_keys(col, equality=False)
        ]
        if not sk.ascending:
            arrays = [~a for a in arrays]
        ops.extend(arrays)
    n = batch.num_rows
    iota = jnp.arange(n, dtype=jnp.int32)
    res = jax.lax.sort(tuple(ops) + (iota,), num_keys=len(ops), is_stable=True)
    return res[-1]


def sort_by(batch: ColumnBatch, sort_keys: Sequence[SortKey]) -> ColumnBatch:
    return gather_batch(batch, sort_permutation(batch, sort_keys))
