"""Relational operators (filter / sort / aggregate / join), TPU-first.

The reference repo delegates these to libcudf (SURVEY.md §2 preamble); for the
TPU framework they are in-tree, built on three primitives chosen for the XLA
compilation model:

* **Order-preserving radix keys** (:mod:`keys`): every Spark key column maps
  to one or more ``uint32`` arrays whose lexicographic unsigned order equals
  Spark's SQL ordering (nulls placement included).  32-bit lanes are native
  to the TPU VPU; 64-bit compares would be emulated.
* **Static shapes everywhere**: filters/joins return padded outputs plus a
  device row count instead of dynamically-shaped arrays, so everything stays
  inside one ``jit`` region.
* **Engine-selectable grouping/joining**: each hot path ships a SORT
  engine (``lax.sort`` + segmented scans / binary-search probes — bitonic
  sort and vectorized gathers pipeline well on the MXU/VPU, which have no
  efficient scatter-chase) and a SCATTER/HASH engine (vectorized
  open-addressing slot table + ``segment_*`` reductions, :mod:`hashtable`
  — XLA-CPU's sort is its slowest primitive and its scatters the
  fastest).  The ``groupby_engine``/``join_engine`` knobs (default
  ``auto``: scatter/hash on CPU, sort on accelerators) pick per platform;
  outputs are bit-identical either way.
"""

from .filter import apply_mask, compact
from .gather import gather_batch, gather_column
from .sort import SortKey, sort_by
from .aggregate import AggSpec, group_by, group_by_domain_or_sort
from .join import (hash_join, join_dense_or_hash, spillable_build_table,
                   SpillableBuildTable)
from .window import WindowSpec, window

__all__ = [
    "apply_mask",
    "compact",
    "gather_batch",
    "gather_column",
    "SortKey",
    "sort_by",
    "AggSpec",
    "group_by",
    "group_by_domain_or_sort",
    "hash_join",
    "join_dense_or_hash",
    "spillable_build_table",
    "SpillableBuildTable",
    "WindowSpec",
    "window",
]
