"""Out-of-core ShuffleService: lossless multi-round exchange.

The reference stack splits the shuffle story across three layers, and
each module here is the TPU analogue of one of them:

* **Partition + pack** — the reference computes Spark-exact partition
  ids (``murmur_hash.cu:187``) and packs rows into fixed-size contiguous
  batches with size-then-write two-pass kernels (``row_conversion.cu``):
  here the map step of :mod:`.service` routes by the same
  ``pmod(murmur3(keys, 42), P)`` id, regroups rows destination-major,
  and emits the exact ``[P, P]`` count matrix — one cheap counts-only
  pass before any data moves.
* **Spillable shuffle buffers** — spark-rapids registers every shuffle
  buffer with the spill catalog so memory pressure demotes them
  device→host→disk instead of OOMing: :mod:`.buffers` wraps the map
  output and every received round chunk in a
  :class:`~spark_rapids_jni_tpu.mem.spill.SpillableHandle` registered
  with the PR-1 :class:`~spark_rapids_jni_tpu.mem.spill.SpillableStore`,
  with creation charges and read-backs running under the
  ``run_with_retry`` rollback ladder (a shuffle round is a retryable
  unit; ``RetryOOM`` between rounds triggers cross-task eviction, not
  job failure).
* **Fixed-batch transport discipline** — the reference never sizes a
  buffer for the worst case; it streams fixed 2GB batches:
  :mod:`.planner` turns the count matrix into a static
  ``(rounds, capacity)`` plan (``rounds * capacity >= max bucket``, so
  lossless by construction, with the skew ratio recorded) and
  :mod:`.service` drains the buckets through the existing static
  ``lax.all_to_all`` one capacity-slice per round — skewed keys cost
  rounds, never rows and never quadratic slot memory.
* **Shuffle manager bookkeeping** — RapidsShuffleManager keys exchanges
  by shuffle id and meters them: :mod:`.registry` assigns ids, records a
  :class:`ShuffleInfo` per exchange, and aggregates
  :class:`ShuffleMetrics` (rounds, rows/bytes moved, spilled bytes, skew
  peak, out-of-range ids, the ``dropped == 0`` invariant), surfaced via
  ``profiler.shuffle_summary()`` and ``RmmSpark.shuffle_metrics()``.

* **Persistent shuffle plane** — the external-shuffle-service role:
  :mod:`.store` persists committed map outputs and drained round chunks
  (crash-safe tmp→fsync→rename commits, CRC-per-chunk manifests, epoch
  fencing against zombie writers) to a fleet-shared dir that survives
  the worker, so a replacement ADOPTS a dead worker's finished shards
  instead of lineage re-running them — ``adopted_shards`` vs
  ``lineage_rebuilds`` in :class:`ShuffleMetrics` decompose the
  recovery cost.

Out-of-range partition ids raise under the ``shuffle_strict_pids`` config
knob and are routed to the null partition (and counted) otherwise;
``shuffle_round_rows`` bounds per-round slot memory and
``shuffle_max_rounds`` caps the round count by raising capacity.
"""

from .buffers import MorselBuffer, PartitionBuffer, RoundChunk, \
    store_recompute
from .morsel import MorselSource
from .planner import (
    HierarchicalPlan,
    RoundPlan,
    plan_hierarchical,
    plan_rounds,
    plan_stream_capacity,
)
from .registry import (
    ShuffleInfo,
    ShuffleMetrics,
    ShuffleRegistry,
    get_registry,
)
from .service import ShuffleError, ShuffleResult, ShuffleService
from .store import ShuffleStore, get_store, install, shutdown_store

__all__ = [
    "MorselBuffer",
    "MorselSource",
    "PartitionBuffer",
    "RoundChunk",
    "ShuffleStore",
    "get_store",
    "install",
    "shutdown_store",
    "store_recompute",
    "HierarchicalPlan",
    "RoundPlan",
    "plan_hierarchical",
    "plan_rounds",
    "plan_stream_capacity",
    "ShuffleInfo",
    "ShuffleMetrics",
    "ShuffleRegistry",
    "get_registry",
    "ShuffleError",
    "ShuffleResult",
    "ShuffleService",
]
