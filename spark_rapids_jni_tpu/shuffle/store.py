"""Persistent shuffle plane: a durable map-output store with crash
adoption and attempt fencing.

PR 10's front door recovers from a dead worker by reaping its spill dir
and lineage re-running every map shard it held — correct, but at fleet
scale the dominant recovery cost is recomputing work that had already
finished.  This module is the missing tier below disk: committed map
outputs and drained round chunks written to a location that *survives
the worker* (a fleet-shared ``shuffle_store_dir``), so a replacement
worker ADOPTS finished shards instead of re-running them.

Layout (separated metadata/payload, the Thallus shape)::

    <root>/FENCE                                  fence state (floor + revoked)
    <root>/<key>/shard-<name>/attempt-<epoch>/    one committed entry
        manifest.json      skeleton + per-chunk (crc32, nbytes) + epoch
        chunk-0000.npy     one npy payload per pytree leaf
    <root>/<key>/shard-<name>/.tmp-e<E>-<pid>-<n>/  in-flight write
    <root>/<key>/shard-<name>/.quarantine-*        corrupt entry, moved aside

Commit protocol (crash-safe at every byte):

1. write every chunk + the manifest into a dot-prefixed tmp dir, fsync
   each file and the dir — nothing under a dot prefix is ever adoptable;
2. check the FENCE: a superseded (zombie) worker's epoch is below the
   stamped floor or in the revoked set, and its commit is REJECTED
   here, pre-rename — a late commit from a worker the supervisor
   already declared dead can never become visible;
3. ``os.rename`` tmp → ``attempt-<epoch>`` — the single atomic commit
   point.  A kill anywhere before it leaves only a tmp dir (reaped by
   :meth:`reap_uncommitted`); a kill after it leaves a complete entry.

Adoption reads the highest *committed* attempt, re-verifying every
chunk against the manifest's CRC32/nbytes (the same ``_leaf_meta``
checksum path the spill tiers use).  A torn or damaged entry — missing
manifest, short chunk, CRC mismatch — is quarantined loudly, counted,
and the next-best attempt (or the caller's lineage re-run) takes over:
graceful degradation, never a wrong answer.

Fault kinds (``tools/chaos.py`` proves both end-to-end):

* ``store_commit`` fires at the pre-rename probe; the store tears the
  write (drops the manifest, keeps the tmp) and reports failure.  A
  ``worker_crash`` rule at the same probe is the SIGKILL-mid-commit
  variant.
* ``store_corrupt`` fires at the post-commit probe; the store flips
  bytes in a chunk it just committed so adoption-time verification is
  exercised against genuine on-disk damage.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config, faultinj
from ..columnar import types as T
from ..columnar.column import (
    Column,
    ColumnBatch,
    Decimal128Column,
    ListColumn,
    StringColumn,
    StructColumn,
)
from ..mem import codec as _codec
from ..mem.spill import _flip_file_bytes, _flip_file_head_bytes, _leaf_meta

# probe names: "store_commit" fires immediately before the atomic
# rename; "store_corrupt_file" immediately after a successful commit
_commit_probe = faultinj.instrument(lambda: None, "store_commit")
_corrupt_probe = faultinj.instrument(lambda: None, "store_corrupt_file")

_FENCE = "FENCE"
_MANIFEST = "manifest.json"


# ---------------------------------------------------------------------------
# pytree <-> (JSON skeleton, npy chunk list) codec
# ---------------------------------------------------------------------------
# The durable format is backend-neutral by construction (the RDataFrame
# migration-study argument): a JSON skeleton describing the container
# nesting plus flat npy payloads, no pickle anywhere — a corrupt file can
# fail verification but can never execute.

def _enc_type(t: T.SparkType) -> dict:
    return {
        "kind": t.kind.value,
        "precision": t.precision,
        "scale": t.scale,
        "tz": t.tz,
        "children": [_enc_type(c) for c in t.children],
        "field_names": list(t.field_names),
    }


def _dec_type(d: dict) -> T.SparkType:
    return T.SparkType(
        T.Kind(d["kind"]),
        precision=int(d.get("precision", 0)),
        scale=int(d.get("scale", 0)),
        children=tuple(_dec_type(c) for c in d.get("children", [])),
        field_names=tuple(d.get("field_names", [])),
        tz=d.get("tz", ""),
    )


def _encode(obj, leaves: List[np.ndarray]):
    """Recursively encode ``obj`` into a JSON skeleton, appending array
    payloads to ``leaves``.  Raises ``TypeError`` on anything outside
    the supported closed set — ``put`` converts that into a failed
    (skipped) persist, never a wrong entry."""
    if isinstance(obj, (np.ndarray, jax.Array)):
        leaves.append(np.asarray(jax.device_get(obj)))
        return {"t": "leaf", "i": len(leaves) - 1}
    if obj is None:
        return {"t": "none"}
    if isinstance(obj, (bool, int, float, str)):
        return {"t": "scalar", "v": obj}
    if isinstance(obj, np.generic):
        return {"t": "scalar", "v": obj.item()}
    if isinstance(obj, tuple):
        return {"t": "tuple", "c": [_encode(x, leaves) for x in obj]}
    if isinstance(obj, list):
        return {"t": "list", "c": [_encode(x, leaves) for x in obj]}
    if isinstance(obj, dict):
        keys = list(obj.keys())
        if not all(isinstance(k, str) for k in keys):
            raise TypeError("store skeleton requires str dict keys")
        return {"t": "dict", "k": keys,
                "c": [_encode(obj[k], leaves) for k in keys]}
    if isinstance(obj, ColumnBatch):
        return {"t": "batch", "k": list(obj.names),
                "c": [_encode(c, leaves) for c in obj.columns]}
    if isinstance(obj, Column):
        return {"t": "col", "dtype": _enc_type(obj.dtype),
                "c": [_encode(obj.data, leaves),
                      _encode(obj.validity, leaves)]}
    if isinstance(obj, StringColumn):
        return {"t": "strcol",
                "c": [_encode(obj.chars, leaves),
                      _encode(obj.lengths, leaves),
                      _encode(obj.validity, leaves)]}
    if isinstance(obj, Decimal128Column):
        return {"t": "deccol", "dtype": _enc_type(obj.dtype),
                "c": [_encode(obj.limbs, leaves),
                      _encode(obj.validity, leaves)]}
    if isinstance(obj, ListColumn):
        return {"t": "listcol", "dtype": _enc_type(obj.dtype),
                "c": [_encode(obj.offsets, leaves),
                      _encode(obj.child, leaves),
                      _encode(obj.validity, leaves)]}
    if isinstance(obj, StructColumn):
        return {"t": "structcol", "k": list(obj.field_names),
                "dtype": _enc_type(obj.dtype),
                "c": [_encode(c, leaves) for c in obj.children]
                + [_encode(obj.validity, leaves)]}
    raise TypeError(f"unsupported store tree node: {type(obj).__name__}")


def _leaf_value(node: dict, leaves: List[np.ndarray]):
    return jnp.asarray(leaves[node["i"]])


def _decode(node: dict, leaves: List[np.ndarray]):
    t = node["t"]
    if t == "leaf":
        return _leaf_value(node, leaves)
    if t == "none":
        return None
    if t == "scalar":
        return node["v"]
    if t == "tuple":
        return tuple(_decode(c, leaves) for c in node["c"])
    if t == "list":
        return [_decode(c, leaves) for c in node["c"]]
    if t == "dict":
        return {k: _decode(c, leaves)
                for k, c in zip(node["k"], node["c"])}
    if t == "batch":
        return ColumnBatch({k: _decode(c, leaves)
                            for k, c in zip(node["k"], node["c"])})
    if t == "col":
        data, valid = (_decode(c, leaves) for c in node["c"])
        return Column(data, valid, _dec_type(node["dtype"]))
    if t == "strcol":
        chars, lengths, valid = (_decode(c, leaves) for c in node["c"])
        return StringColumn(chars, lengths, valid)
    if t == "deccol":
        limbs, valid = (_decode(c, leaves) for c in node["c"])
        return Decimal128Column(limbs, valid, _dec_type(node["dtype"]))
    if t == "listcol":
        offsets, child, valid = (_decode(c, leaves) for c in node["c"])
        return ListColumn(offsets, child, valid, _dec_type(node["dtype"]))
    if t == "structcol":
        *kids, valid = (_decode(c, leaves) for c in node["c"])
        return StructColumn(dict(zip(node["k"], kids)), valid,
                            _dec_type(node["dtype"]))
    raise faultinj.StoreCorruptionError(f"unknown skeleton node {t!r}")


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _safe(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-._" else "_" for c in name)


class ShuffleStore:
    """One process's handle onto the fleet-shared durable store.

    ``epoch`` is this process's stamped attempt number (the front door
    uses the worker generation); commits are keyed by it and fenced
    against it.  All methods are safe under concurrent writers in other
    processes — the commit point is a single ``os.rename``."""

    COUNTERS = ("commits", "commit_failures", "fenced_commits",
                "adoptions", "adoption_misses", "corrupt_quarantined",
                "reaped_uncommitted", "pruned_attempts")

    def __init__(self, root: str, epoch: int = 0,
                 max_attempts: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.epoch = int(epoch)
        self._max_attempts = max_attempts
        self._lock = threading.Lock()
        self._tmp_seq = 0
        self._counts = {k: 0 for k in self.COUNTERS}
        os.makedirs(self.root, exist_ok=True)

    # -- fencing ---------------------------------------------------------
    # Two fence shapes, both checked pre-rename: a monotonic FLOOR
    # (``stamp`` — fences every generation below it at once; a fleet
    # restart stamps past its predecessor's gens) and a REVOKED set
    # (``revoke`` — the supervisor's surgical fence at worker-loss time;
    # a threshold alone can't fence gen 2's zombie while gen 1 is still
    # alive and committing).  Only the supervisor writes fence state, so
    # its read-modify-write needs no cross-process lock; workers only
    # ever read it.

    def _fence_state(self) -> dict:
        try:
            with open(os.path.join(self.root, _FENCE)) as f:
                raw = f.read().strip()
        except OSError:
            return {"floor": 0, "revoked": []}
        try:
            st = json.loads(raw or "0")
        except ValueError:
            return {"floor": 0, "revoked": []}
        if isinstance(st, int):  # legacy bare-int floor
            return {"floor": st, "revoked": []}
        if not isinstance(st, dict):
            return {"floor": 0, "revoked": []}
        return {"floor": int(st.get("floor", 0)),
                "revoked": sorted(int(e) for e in st.get("revoked", []))}

    def _write_fence(self, state: dict) -> None:
        tmp = os.path.join(self.root, f".{_FENCE}-{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, _FENCE))
        _fsync_dir(self.root)

    def fence(self) -> int:
        """The stamped floor epoch (0 = none)."""
        return self._fence_state()["floor"]

    def fenced(self, epoch: int) -> bool:
        """Would a commit at ``epoch`` be rejected right now?"""
        st = self._fence_state()
        return int(epoch) < st["floor"] or int(epoch) in st["revoked"]

    def revoked(self) -> List[int]:
        """Surgically fenced generations, ascending — the supervisor's
        worker-loss verdicts (chaos asserts none of them can commit)."""
        return self._fence_state()["revoked"]

    def stamp(self, epoch: int) -> int:
        """Raise the fence floor to ``epoch`` (monotonic; atomic
        replace): every generation strictly below it is fenced."""
        st = self._fence_state()
        if int(epoch) <= st["floor"]:
            return st["floor"]
        st["floor"] = int(epoch)
        self._write_fence(st)
        return st["floor"]

    def revoke(self, epoch: int) -> None:
        """Fence exactly one generation.  Two callers, same contract:
        the supervisor revokes a worker's epoch the moment it declares
        the worker lost, so a zombie process that outlives its SIGKILL
        verdict can finish writing tmp entries but can never commit
        them; and a partitioned worker revokes its OWN epoch when the
        supervisor has been unreachable past ``serve_partition_grace_ms``
        (serve/worker.py self-fence) — whichever side of a network
        partition acts first, commits from the cut-off generation are
        rejected at the rename, so split-brain can never zombie-commit."""
        st = self._fence_state()
        if int(epoch) in st["revoked"]:
            return
        st["revoked"] = sorted(st["revoked"] + [int(epoch)])
        self._write_fence(st)

    def fence_handoff(self, dead_epochs, floor: int) -> dict:
        """Supervisor-restart generation handoff (serve/journal.py
        adoption): revoke every dead generation surgically, raise the
        floor to the oldest SURVIVING generation — never past it, or
        the survivors the new supervisor is about to re-adopt would be
        fenced out of their own commits — and reap each dead
        generation's uncommitted tmp entries.  One fence-state write:
        the dead supervisor's generations can never zombie-commit from
        the instant the adopting one takes over, while every committed
        shard stays adoptable."""
        st = self._fence_state()
        dead = sorted({int(e) for e in dead_epochs}
                      - set(st["revoked"]))
        if dead:
            st["revoked"] = sorted(st["revoked"] + dead)
        st["floor"] = max(st["floor"], int(floor))
        self._write_fence(st)
        reaped = 0
        for e in dead:
            reaped += self.reap_uncommitted(epoch=e)
        return {"revoked": dead, "floor": st["floor"],
                "reaped_uncommitted": reaped}

    # -- paths -----------------------------------------------------------
    def _shard_dir(self, key: str, shard: str) -> str:
        return os.path.join(self.root, _safe(key), f"shard-{_safe(shard)}")

    def _committed(self, shard_dir: str) -> List[Tuple[int, str]]:
        """Committed attempts, highest epoch first."""
        try:
            entries = os.listdir(shard_dir)
        except OSError:
            return []
        out = []
        for e in entries:
            if not e.startswith("attempt-"):
                continue
            try:
                out.append((int(e.split("-", 1)[1]),
                            os.path.join(shard_dir, e)))
            except ValueError:
                continue
        out.sort(reverse=True)
        return out

    # -- write path ------------------------------------------------------
    def put(self, key: str, shard: str, tree) -> bool:
        """Durably commit ``tree`` as this epoch's attempt for
        ``(key, shard)``.  Returns False (never raises) when the write
        is torn, fenced, or the tree is not storable — callers always
        still hold the in-memory copy."""
        shard_dir = self._shard_dir(key, shard)
        final = os.path.join(shard_dir, f"attempt-{self.epoch:08d}")
        if os.path.isdir(final):
            return True
        try:
            leaves: List[np.ndarray] = []
            skeleton = _encode(tree, leaves)
        except TypeError:
            with self._lock:
                self._counts["commit_failures"] += 1
            return False
        os.makedirs(shard_dir, exist_ok=True)
        with self._lock:
            self._tmp_seq += 1
            seq = self._tmp_seq
        tmp = os.path.join(
            shard_dir, f".tmp-e{self.epoch}-{os.getpid()}-{seq}")
        manifest_path = os.path.join(tmp, _MANIFEST)
        try:
            os.makedirs(tmp)
            codec = str(config.get("spill_codec") or "off").lower()
            metas = []
            for i, arr in enumerate(leaves):
                cpath = os.path.join(tmp, f"chunk-{i:04d}.npy")
                if codec == "off":
                    payload = arr
                    meta = list(_leaf_meta(arr))
                else:
                    # codec'd chunk: the manifest meta grows to
                    # [orig_crc, orig_nbytes, codec, stored_crc,
                    # stored_nbytes] so any later fleet can adopt the
                    # entry without knowing this run's knob setting
                    payload = _codec.encode_block(arr, codec)
                    meta = (list(_leaf_meta(arr))
                            + [_codec.codec_name(payload)]
                            + list(_leaf_meta(payload)))
                with open(cpath, "wb") as f:
                    np.save(f, payload, allow_pickle=False)
                    f.flush()
                    os.fsync(f.fileno())
                metas.append(meta)
            with open(manifest_path, "w") as f:
                json.dump({"skeleton": skeleton, "leaves": metas,
                           "epoch": self.epoch, "key": key,
                           "shard": shard}, f)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            with self._lock:
                self._counts["commit_failures"] += 1
            return False
        try:
            # pre-rename boundary: a worker_crash rule here SIGKILLs with
            # the tmp entry fully written but never committed
            _commit_probe()
        except faultinj.StoreCommitError:
            # torn write: the manifest is dropped so the tmp remnant can
            # never be mistaken for a complete entry; leave the chunks
            # for reap_uncommitted to prove the reaper path
            try:
                os.unlink(manifest_path)
            except OSError:
                pass
            with self._lock:
                self._counts["commit_failures"] += 1
            return False
        if self.fenced(self.epoch):
            # a zombie generation's late commit: rejected at the rename
            shutil.rmtree(tmp, ignore_errors=True)
            with self._lock:
                self._counts["fenced_commits"] += 1
            return False
        try:
            os.rename(tmp, final)
        except OSError:
            # lost a same-attempt race: the other writer's entry stands
            shutil.rmtree(tmp, ignore_errors=True)
            return os.path.isdir(final)
        _fsync_dir(shard_dir)
        with self._lock:
            self._counts["commits"] += 1
        try:
            _corrupt_probe()
        except faultinj.StoreCorruptionError:
            # convert the injected fault into real on-disk damage in the
            # entry we just committed — adoption's CRC pass must catch it
            chunks = sorted(f for f in os.listdir(final)
                            if f.startswith("chunk-"))
            if chunks:
                _flip_file_bytes(os.path.join(final, chunks[0]))
                if str(config.get("spill_codec") or "off").lower() != "off":
                    # also damage the codec frame header so the loud
                    # decode-failure defense is exercised, not just CRC
                    _flip_file_head_bytes(os.path.join(final, chunks[0]))
        self._prune(shard_dir)
        return True

    def _prune(self, shard_dir: str) -> None:
        keep = self._max_attempts
        if keep is None:
            keep = int(config.get("shuffle_store_max_attempts"))
        if keep <= 0:
            return
        for _epoch, path in self._committed(shard_dir)[keep:]:
            shutil.rmtree(path, ignore_errors=True)
            with self._lock:
                self._counts["pruned_attempts"] += 1

    # -- read path -------------------------------------------------------
    def has_committed(self, key: str, shard: str) -> bool:
        return bool(self._committed(self._shard_dir(key, shard)))

    def attempts(self, key: str, shard: str) -> List[int]:
        return [e for e, _ in self._committed(self._shard_dir(key, shard))]

    def adopt(self, key: str, shard: str):
        """The highest committed, CRC-verified attempt for
        ``(key, shard)`` as a live tree, or None.  Entries failing
        verification are quarantined (renamed out of the committed
        namespace) and the next-best attempt is tried."""
        shard_dir = self._shard_dir(key, shard)
        for _epoch, path in self._committed(shard_dir):
            try:
                tree = self._load_verified(path)
            except (OSError, ValueError, KeyError, json.JSONDecodeError):
                self._quarantine(path)
                continue
            with self._lock:
                self._counts["adoptions"] += 1
            return tree
        with self._lock:
            self._counts["adoption_misses"] += 1
        return None

    def _load_verified(self, path: str):
        with open(os.path.join(path, _MANIFEST)) as f:
            manifest = json.load(f)
        metas = manifest["leaves"]
        leaves = []
        for i, meta in enumerate(metas):
            arr = np.load(os.path.join(path, f"chunk-{i:04d}.npy"),
                          allow_pickle=False)
            got_crc, got_nbytes = _leaf_meta(arr)
            if len(meta) == 5:
                # codec'd chunk (self-describing meta — works across
                # runs/knob settings): verify the stored frame bytes,
                # decode loudly, then verify the decoded leaf
                crc, nbytes, cname, stored_crc, stored_nbytes = meta
                if got_crc != stored_crc or got_nbytes != stored_nbytes:
                    raise faultinj.StoreCorruptionError(
                        f"store chunk {i} of {path} ({cname}) failed "
                        f"stored-payload verification: crc "
                        f"{got_crc:#x}!={stored_crc:#x} or nbytes "
                        f"{got_nbytes}!={stored_nbytes}")
                try:
                    arr = _codec.decode_block(arr)
                except _codec.CodecError as e:
                    raise faultinj.StoreCorruptionError(
                        f"store chunk {i} of {path}: corrupt {cname} "
                        f"frame: {e}") from e
                got_crc, got_nbytes = _leaf_meta(arr)
                if got_nbytes != nbytes or (crc and got_crc != crc):
                    raise faultinj.StoreCorruptionError(
                        f"store chunk {i} of {path} failed decoded-leaf "
                        f"verification: crc {got_crc:#x}!={crc:#x} or "
                        f"nbytes {got_nbytes}!={nbytes}")
            else:
                crc, nbytes = meta
                if got_crc != crc or got_nbytes != nbytes:
                    raise faultinj.StoreCorruptionError(
                        f"store chunk {i} of {path} failed verification: "
                        f"crc {got_crc:#x}!={crc:#x} or "
                        f"nbytes {got_nbytes}!={nbytes}")
            leaves.append(arr)
        return _decode(manifest["skeleton"], leaves)

    def _quarantine(self, path: str) -> None:
        with self._lock:
            self._counts["corrupt_quarantined"] += 1
            self._tmp_seq += 1
            seq = self._tmp_seq
        dst = os.path.join(
            os.path.dirname(path),
            f".quarantine-{os.path.basename(path)}-{os.getpid()}-{seq}")
        try:
            os.rename(path, dst)
        except OSError:
            shutil.rmtree(path, ignore_errors=True)

    # -- janitorial ------------------------------------------------------
    def reap_uncommitted(self, epoch: Optional[int] = None) -> int:
        """Remove in-flight tmp entries (a dead worker's mid-commit
        remnants).  ``epoch`` limits the reap to one generation's tmp
        dirs; None reaps every uncommitted entry.  Committed attempts
        and quarantined entries are never touched."""
        prefix = ".tmp-" if epoch is None else f".tmp-e{int(epoch)}-"
        reaped = 0
        try:
            keys = os.listdir(self.root)
        except OSError:
            return 0
        for key in keys:
            kdir = os.path.join(self.root, key)
            if not os.path.isdir(kdir):
                continue
            for shard in os.listdir(kdir):
                sdir = os.path.join(kdir, shard)
                if not os.path.isdir(sdir):
                    continue
                for e in os.listdir(sdir):
                    if e.startswith(prefix):
                        shutil.rmtree(os.path.join(sdir, e),
                                      ignore_errors=True)
                        reaped += 1
        with self._lock:
            self._counts["reaped_uncommitted"] += reaped
        return reaped

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)


# ---------------------------------------------------------------------------
# process-level store handle
# ---------------------------------------------------------------------------
# One store per process, installed explicitly (workers: from the
# supervisor's --store-dir/--epoch) or lazily from the
# ``shuffle_store_dir`` knob; the ShuffleService adopts through
# whichever is live.

_installed: Optional[ShuffleStore] = None
_installed_lock = threading.Lock()


def install(root: Optional[str] = None, epoch: int = 0) -> ShuffleStore:
    """Install the process's store handle (replacing any previous one)."""
    global _installed
    root = root or str(config.get("shuffle_store_dir"))
    if not root:
        raise ValueError("no store root: pass root= or set the "
                         "shuffle_store_dir knob")
    with _installed_lock:
        _installed = ShuffleStore(root, epoch=epoch)
        return _installed


def get_store() -> Optional[ShuffleStore]:
    """The installed store, lazily created from ``shuffle_store_dir``
    when the knob is set; None when no store is configured."""
    global _installed
    with _installed_lock:
        if _installed is None:
            root = str(config.get("shuffle_store_dir"))
            if root:
                _installed = ShuffleStore(root, epoch=0)
        return _installed


def shutdown_store() -> None:
    """Drop the process's store handle (files are left for the owner —
    the front door's shutdown decides retention via
    ``shuffle_store_retain``)."""
    global _installed
    with _installed_lock:
        _installed = None
