"""Shuffle id assignment + per-shuffle bookkeeping (the ShuffleManager
registry role).

The reference's shuffle manager (RapidsShuffleManager plugin-side) keys
every exchange by a shuffle id and keeps per-shuffle state — buffers in
flight, bytes moved, spill activity — next to the catalog.  Here the
:class:`ShuffleRegistry` does the same for the TPU service: it hands out
monotonically increasing shuffle ids, records one :class:`ShuffleInfo`
per completed exchange, and aggregates :class:`ShuffleMetrics` for the
process (surfaced via ``profiler.shuffle_summary()`` and
``RmmSpark.shuffle_metrics()``).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass(frozen=True)
class ShuffleInfo:
    """One completed exchange, exactly accounted."""

    shuffle_id: int
    rounds: int
    capacity: int          # per-(sender,destination) slot rows per round
    rows_moved: int        # rows delivered (== rows sent; the invariant)
    bytes_moved: int       # grid bytes the all_to_all rounds transported
    spilled_bytes: int     # device->host + host->disk bytes during it
    skew_ratio: float      # max bucket / mean bucket from the plan
    oob_rows: int          # out-of-range pids routed to the null partition
    recovered_partitions: int = 0  # buffers rebuilt via map lineage
    streamed: bool = False         # went through exchange_stream
    morsels: int = 0               # morsels mapped (streamed only)
    rounds_overlapped: int = 0     # rounds drained before end-of-stream
    decode_ms: float = 0.0         # cumulative morsel decode+map time
    drain_ms: float = 0.0          # cumulative round drain time
    compressed_bytes_saved: int = 0  # wire bytes the pack plan saved
    #   (bytes_moved already reflects the packed size; this is the delta
    #   vs the raw grid the same rounds would have shipped)
    blocks_skipped: int = 0        # zone blocks the morsel check excluded
    blocks_scanned: int = 0        # zone blocks consulted and kept


class ShuffleMetrics:
    """Process-wide shuffle counters (int fields + the float skew peak).

    ``dropped_rows`` exists to make the lossless invariant observable:
    the service RAISES when accounting finds a deficit, recording the
    deficit here first — a nonzero value means a shuffle failed loudly,
    never that rows vanished silently.
    """

    FIELDS = (
        "shuffles", "rounds", "rows_moved", "bytes_moved",
        "spilled_bytes", "oob_rows", "dropped_rows", "io_failures",
        "recovered_partitions", "adopted_shards", "lineage_rebuilds",
        "compressed_bytes_saved", "blocks_skipped", "blocks_scanned",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._c = dict.fromkeys(self.FIELDS, 0)
        self._max_skew = 0.0

    def record_shuffle(self, info: ShuffleInfo):
        with self._lock:
            self._c["shuffles"] += 1
            self._c["rounds"] += info.rounds
            self._c["rows_moved"] += info.rows_moved
            self._c["bytes_moved"] += info.bytes_moved
            self._c["spilled_bytes"] += info.spilled_bytes
            self._c["oob_rows"] += info.oob_rows
            self._c["compressed_bytes_saved"] += info.compressed_bytes_saved
            self._c["blocks_skipped"] += info.blocks_skipped
            self._c["blocks_scanned"] += info.blocks_scanned
            self._max_skew = max(self._max_skew, info.skew_ratio)

    def record_dropped(self, n: int):
        with self._lock:
            self._c["dropped_rows"] += int(n)

    def record_io_failure(self):
        with self._lock:
            self._c["io_failures"] += 1

    def record_recovered(self):
        """One lost/corrupt partition buffer rebuilt from map lineage.

        Recorded LIVE at recovery time (not summed from ShuffleInfo at
        exchange completion) so a recovery is visible even when the
        exchange later fails for an unrelated reason."""
        with self._lock:
            self._c["recovered_partitions"] += 1

    def record_adopted(self):
        """One shard ADOPTED from the persistent store instead of
        computed — either pre-map (a prior attempt's committed output
        found at exchange start) or during lineage recovery (the store
        answered before the rebuild closure ran)."""
        with self._lock:
            self._c["adopted_shards"] += 1

    def record_lineage_rebuild(self):
        """One shard actually RE-RUN through its lineage closure after
        the store could not answer (no committed attempt, or every
        attempt quarantined as corrupt) — the complement of
        ``adopted_shards``; together they decompose recovery cost."""
        with self._lock:
            self._c["lineage_rebuilds"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["max_skew_ratio"] = self._max_skew
            return out

    def reset(self):
        with self._lock:
            self._c = dict.fromkeys(self.FIELDS, 0)
            self._max_skew = 0.0


class ShuffleRegistry:
    """Thread-safe shuffle id counter + completed-shuffle records."""

    def __init__(self):
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._info: Dict[int, ShuffleInfo] = {}
        self.metrics = ShuffleMetrics()

    def begin_shuffle(self) -> int:
        return next(self._ids)

    def record(self, info: ShuffleInfo):
        with self._lock:
            self._info[info.shuffle_id] = info
        self.metrics.record_shuffle(info)

    def info(self, shuffle_id: int) -> Optional[ShuffleInfo]:
        with self._lock:
            return self._info.get(shuffle_id)

    def shuffles(self) -> Dict[int, ShuffleInfo]:
        with self._lock:
            return dict(self._info)

    def reset(self):
        with self._lock:
            self._info.clear()
        self.metrics.reset()


_registry = ShuffleRegistry()


def get_registry() -> ShuffleRegistry:
    """The process-wide registry every :class:`ShuffleService` shares."""
    return _registry
